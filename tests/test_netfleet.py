"""Cross-host fleet (serve/net.py, serve/wire.py v2, utils/hostmap.py):
stream-frame hardening, the heartbeat lease and its two fencing edges
(router forfeits the flush, worker discards the finished result), the
host-map grammar, partition fault injection, and a live 2-worker TCP
fleet — partitions mid-flight lose nothing and heal, predictions stay
bit-identical to the threaded path.

Ordering note: the local-path pins run FIRST (before the module-scoped
net fleet exists) because a live fleet's heartbeats call the
``serve.net.*`` fault sites continuously — the inertness pin measures a
process with no remote peer configured.
"""

import socket
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.obs import metrics
from keystone_tpu.serve import net, wire
from keystone_tpu.serve.procfleet import WorkerCrashed, WorkerSpawnError
from keystone_tpu.utils import hostmap

pytestmark = pytest.mark.serve

DIM = 6


def _spair():
    """An in-process byte pipe for pure framing tests (no TCP stack)."""
    return socket.socketpair()


def _tcp_pair():
    """A real loopback TCP pair — NetWorkerHandle sets TCP options, so
    its tests need an AF_INET socket, not a socketpair."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    cli = socket.create_connection(srv.getsockname())
    peer, _ = srv.accept()
    srv.close()
    cli.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    peer.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return cli, peer


def _close_all(*socks):
    for s in socks:
        try:
            s.close()
        except OSError:
            pass


# ------------------------------------------------- wire v2 stream frames
def test_stream_frame_roundtrip_with_array_payload():
    a, b = _spair()
    try:
        arr = np.arange(24, dtype=np.float32).reshape(4, 6) * 0.25
        meta, payload = wire.array_payload(arr)
        msg = {"op": "apply", "fid": "f1", "n": 4, "meta": meta}
        wire.send_stream_frame(a, msg, payload)
        got, gpayload = wire.recv_stream_frame(b, timeout=5.0)
        assert got == msg
        out = wire.payload_array(got["meta"], gpayload)
        assert out.tobytes() == arr.tobytes()
        assert out.dtype == arr.dtype
    finally:
        _close_all(a, b)


def test_stream_frame_roundtrip_empty_payload():
    a, b = _spair()
    try:
        wire.send_stream_frame(a, {"op": "beat"})
        got, payload = wire.recv_stream_frame(b, timeout=5.0)
        assert got == {"op": "beat"} and payload == b""
    finally:
        _close_all(a, b)


def test_stream_frame_rejects_truncation():
    # close mid-body: a torn frame, not a clean goodbye
    a, b = _spair()
    try:
        frame = wire.pack_stream_frame({"op": "apply"}, b"payload-bytes")
        a.sendall(frame[:-3])
        a.close()
        with pytest.raises(wire.WireError, match="truncated"):
            wire.recv_stream_frame(b, timeout=5.0)
    finally:
        _close_all(a, b)

    # close mid-PREFIX: same verdict
    a, b = _spair()
    try:
        a.sendall(frame[:5])
        a.close()
        with pytest.raises(wire.WireError, match="truncated"):
            wire.recv_stream_frame(b, timeout=5.0)
    finally:
        _close_all(a, b)


def test_stream_frame_rejects_garbage_magic():
    a, b = _spair()
    try:
        frame = wire.pack_stream_frame({"op": "beat"})
        a.sendall(b"XXXX" + frame[4:])
        with pytest.raises(wire.WireError, match="magic"):
            wire.recv_stream_frame(b, timeout=5.0)
    finally:
        _close_all(a, b)


def test_stream_frame_rejects_version_skew():
    a, b = _spair()
    try:
        frame = bytearray(wire.pack_stream_frame({"op": "beat"}))
        frame[len(wire.MAGIC)] = wire.VERSION  # the SLAB protocol version
        a.sendall(bytes(frame))
        with pytest.raises(wire.WireError, match="version"):
            wire.recv_stream_frame(b, timeout=5.0)
    finally:
        _close_all(a, b)


def test_stream_frame_rejects_crc_mismatch():
    a, b = _spair()
    try:
        frame = wire.pack_stream_frame({"op": "result"}, b"damaged-in-flight")
        a.sendall(net._corrupt_frame(frame))
        with pytest.raises(wire.WireError, match="CRC"):
            wire.recv_stream_frame(b, timeout=5.0)
    finally:
        _close_all(a, b)


def test_stream_frame_clean_close_is_eof_not_error():
    a, b = _spair()
    try:
        a.close()
        with pytest.raises(EOFError):
            wire.recv_stream_frame(b, timeout=5.0)
    finally:
        _close_all(b)


def test_stream_frame_refuses_oversize_before_allocating():
    a, b = _spair()
    try:
        wire.send_stream_frame(a, {"op": "apply"}, b"x" * 256)
        with pytest.raises(wire.WireError, match="cap"):
            wire.recv_stream_frame(b, timeout=5.0, max_frame_bytes=64)
    finally:
        _close_all(a, b)


def test_stream_frame_idle_timeout_raises_timeout():
    a, b = _spair()
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            wire.recv_stream_frame(b, timeout=0.2)
        assert time.monotonic() - t0 < 5.0  # bounded, never a hang
    finally:
        _close_all(a, b)


def test_stream_frame_mid_frame_stall_is_torn(monkeypatch):
    # a peer that starts a frame and stalls holds a TORN channel, not an
    # idle one — the receiver gives up on the frame, bounded
    monkeypatch.setattr(wire, "MID_FRAME_TIMEOUT_S", 0.3)
    a, b = _spair()
    try:
        frame = wire.pack_stream_frame({"op": "apply"}, b"abcdef")
        a.sendall(frame[:10])
        with pytest.raises(wire.WireError, match="stalled"):
            wire.recv_stream_frame(b, timeout=5.0)
    finally:
        _close_all(a, b)


def test_reader_idle_poll_never_caps_concurrent_sendall():
    """THE shared-socket timeout pin: a reader thread polling
    ``recv_stream_frame(timeout=0.25)`` — exactly the router/worker
    read loops — shares the socket with ``sendall`` callers, and the
    socket-object timeout caps sendall's TOTAL duration.  A send too
    large to flush before the peer starts reading must still complete:
    the reader waits via select and never narrows the send budget."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    cli = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        # tiny buffers: the frame cannot flush until the peer reads,
        # so sendall provably outlives many reader poll intervals
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
        cli.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16384)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        cli.connect(srv.getsockname())
        peer, _ = srv.accept()
    except OSError:
        srv.close()
        cli.close()
        raise
    srv.close()
    cli.settimeout(wire.SEND_TIMEOUT_S)  # the net.py setup discipline
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            try:
                wire.recv_stream_frame(cli, timeout=0.25)
            except TimeoutError:
                continue
            except (EOFError, OSError, wire.WireError):
                return

    payload = b"x" * (4 << 20)
    errs = []

    def send():
        try:
            wire.send_stream_frame(cli, {"op": "apply", "fid": "big"}, payload)
        except Exception as e:  # noqa: BLE001 — the pin IS "no exception"
            errs.append(e)

    reader = threading.Thread(target=poll, daemon=True)
    sender = threading.Thread(target=send, daemon=True)
    reader.start()
    sender.start()
    try:
        # hold the peer silent across several poll intervals: the send
        # is wedged on full buffers the whole time
        time.sleep(0.8)
        msg, got = wire.recv_stream_frame(peer, timeout=30.0)
        sender.join(10.0)
        assert not sender.is_alive()
        assert errs == []
        assert msg == {"op": "apply", "fid": "big"} and got == payload
    finally:
        stop.set()
        _close_all(cli, peer)
        reader.join(2.0)


def test_payload_array_rejects_meta_length_mismatch():
    meta, payload = wire.array_payload(np.zeros(8, np.float32))
    with pytest.raises(wire.WireError):
        wire.payload_array(meta, payload[:-4])


def test_parse_address_grammar():
    assert net.parse_address("10.0.0.5:9000") == ("10.0.0.5", 9000)
    with pytest.raises(ValueError):
        net.parse_address("no-port")
    with pytest.raises(ValueError):
        net.parse_address(":9000")


def test_payload_digest_is_content_addressed():
    assert net.payload_digest(b"gen-1") == net.payload_digest(b"gen-1")
    assert net.payload_digest(b"gen-1") != net.payload_digest(b"gen-2")


# --------------------------------------------------- network fault sites
def test_partition_alias_parses_to_drop():
    plan = faults.parse_plan("serve.net.send:partition:ctx.link=w0")
    assert plan.specs[0].action == "drop"
    assert plan.specs[0].match == {"link": "w0"}


def test_drop_rejected_outside_wire_sites():
    with pytest.raises(faults.FaultPlanError):
        faults.parse_plan("serve.enqueue:drop")
    with pytest.raises(faults.FaultPlanError):
        faults.parse_plan("ckpt.save:partition")


def test_fault_point_returns_wire_advisories():
    with faults.inject("serve.net.send:drop:ctx.link=w0"):
        assert faults.fault_point("serve.net.send", link="w0") == "drop"
        # context match: another link sails through
        assert faults.fault_point("serve.net.send", link="w1") is None
    with faults.inject("serve.net.recv:corrupt"):
        assert faults.fault_point("serve.net.recv", link="w0") == "corrupt"
    # no active plan: the site is inert
    assert faults.fault_point("serve.net.send", link="w0") is None


def test_raise_wins_over_drop_at_the_same_site():
    with faults.inject("serve.net.send:drop;serve.net.send:raise"):
        with pytest.raises(faults.FaultInjected):
            faults.fault_point("serve.net.send", link="w0")


def test_net_sites_registered():
    assert {
        "serve.net.connect",
        "serve.net.send",
        "serve.net.recv",
    } <= faults.SITES


def test_connect_drop_verdict_is_a_failed_dial():
    """A drop/partition plan at ``serve.net.connect`` must not parse
    and then silently do nothing: the verdict is a refused dial,
    absorbed (and retried) by the backoff ladder like any dead router."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(2)
    try:
        host, port = srv.getsockname()[:2]
        with faults.inject("serve.net.connect:drop:times=1"):
            sock = net._connect(
                host, port, "dial-w", attempts=3, base_delay=0.01
            )
            sock.close()
        # a persistent partition at the dial exhausts the ladder
        with faults.inject("serve.net.connect:partition"):
            with pytest.raises(net.ConnectRetriesExhausted):
                net._connect(
                    host, port, "dial-w", attempts=2, base_delay=0.01
                )
    finally:
        srv.close()


# ------------------------------------------------------------- host map
def test_parse_hosts_grammar():
    entries = hostmap.parse_hosts("local:2, 10.0.0.5:4")
    assert [(e.host, e.slots) for e in entries] == [
        ("local", 2),
        ("10.0.0.5", 4),
    ]
    assert entries[0].local and not entries[1].local
    # a bare host is unbounded; list and pair forms are accepted
    assert hostmap.parse_hosts(["bighost"])[0].slots is None
    assert hostmap.parse_hosts([("h", 3)])[0].slots == 3
    with pytest.raises(ValueError):
        hostmap.parse_hosts("")
    with pytest.raises(ValueError):
        hostmap.parse_hosts("h:xx")


def test_hostmap_capacity_and_exhaustion():
    hm = hostmap.HostMap("local:1,local:1")
    assert hm.capacity() == 2

    class _LiveProc:
        def poll(self):
            return None

    for e in hm.entries:
        e.spawned.append(_LiveProc())
    assert hm.in_flight() == 2
    with pytest.raises(hostmap.HostCapacityError):
        hm._pick()
    # any unbounded host makes total capacity unbounded
    assert hostmap.HostMap("local").capacity() is None


def test_hostmap_swap_overflow_exempts_slot_budget():
    """A staged swap generation coexists with the one it replaces
    until commit, so with a budget sized to the steady-state fleet the
    swap's spawns carry a transient overflow allowance — the hard
    budget stays hard for everyone else (autoscaler, heals)."""
    hm = hostmap.HostMap("local:1")

    class _LiveProc:
        def poll(self):
            return None

    hm.entries[0].spawned.append(_LiveProc())
    with pytest.raises(hostmap.HostCapacityError):
        hm._pick()
    assert hm._pick(allow_overflow=True) is hm.entries[0]


def test_hostmap_command_shapes():
    hm = hostmap.HostMap("local,gpu-02:4")
    local_cmd = hm._command(hm.entries[0], ["--connect", "127.0.0.1:1"])
    assert local_cmd[1:4] == ["-m", "keystone_tpu.cli", "worker"]
    remote_cmd = hm._command(hm.entries[1], ["--connect", "127.0.0.1:1"])
    assert remote_cmd[0] == "ssh" and "gpu-02" in remote_cmd


# ------------------------------------------------ local paths stay local
def _pipeline(scale: float = 2.0):
    import jax.numpy as jnp

    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.ops.stats import NormalizeRows
    from keystone_tpu.workflow import Pipeline

    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * scale)
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


def _rows(k: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).normal(size=(k, DIM)).astype(np.float32)


def test_local_service_never_touches_net_sites():
    """With no remote peer configured the ``serve.net.*`` sites are
    structurally inert: a threaded service serves a request without a
    single call into them (this runs before the module fleet exists —
    a live fleet's heartbeats call these sites continuously)."""
    from keystone_tpu.serve import serve

    faults.reset_stats()
    svc = serve(
        _pipeline(),
        max_batch=8,
        max_wait_ms=1.0,
        example=np.zeros(DIM, np.float32),
        name="netfleet_local",
        supervise=False,
    )
    try:
        assert svc._pool.backend == "thread"
        assert svc._pool._listener is None and svc._pool._hostmap is None
        svc.submit(np.ones(DIM, np.float32)).result(timeout=60)
    finally:
        svc.close()
    st = faults.stats()
    for site in ("serve.net.connect", "serve.net.send", "serve.net.recv"):
        assert st.get(site, {}).get("calls", 0) == 0


def test_hosts_requires_worker_processes():
    from keystone_tpu.serve import serve

    with pytest.raises(ValueError, match="workers"):
        serve(
            _pipeline(),
            hosts=["local"],
            example=np.zeros(DIM, np.float32),
            name="netfleet_bad",
        )


# ------------------------------------- router side vs a scripted worker
class _FakeWorker:
    """The far side of a NetWorkerHandle, scripted: answers the deploy
    with ``ready`` (or whatever ``ready`` says), then drains frames and
    consults ``on_apply`` — return ``(reply, payload)`` to answer or
    ``None`` to withhold.  ``beat_interval`` keeps the router's lease
    fresh; omit it to simulate a silent (partitioned/dead) worker."""

    def __init__(self, sock, on_apply=None, ready=None, beat_interval=None):
        self.sock = sock
        self.on_apply = on_apply
        self.ready = ready or {
            "op": "ready",
            "pid": 4242,
            "primed": 0,
            "reused": False,
            "artifact_buckets": 0,
            "artifact_keys": [],
            "startup_seconds": 0.0,
        }
        self.deploy = None
        self.frames = []
        self._send_lock = threading.Lock()
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()
        if beat_interval is not None:
            threading.Thread(
                target=self._beat, args=(beat_interval,), daemon=True
            ).start()

    def send(self, msg, payload=b""):
        with self._send_lock:
            wire.send_stream_frame(self.sock, msg, payload)

    def _beat(self, interval):
        while not self._stop.wait(interval):
            try:
                self.send({"op": "beat"})
            except OSError:
                return

    def _run(self):
        try:
            msg, payload = wire.recv_stream_frame(self.sock, timeout=10.0)
            self.deploy = (msg, payload)
            self.send(self.ready)
            if self.ready.get("op") != "ready":
                return
            while True:
                msg, payload = wire.recv_stream_frame(self.sock, timeout=10.0)
                self.frames.append(msg)
                if msg.get("op") == "apply" and self.on_apply is not None:
                    out = self.on_apply(msg, payload)
                    if out is not None:
                        self.send(out[0], out[1])
                if msg.get("op") == "bye":
                    self.send({"op": "bye_ack"})
                    return
        except (TimeoutError, EOFError, OSError, wire.WireError):
            return
        finally:
            self._stop.set()

    def close(self):
        self._stop.set()
        _close_all(self.sock)


def test_handle_deploy_ships_digest_and_payload_inline():
    router, worker = _tcp_pair()
    fw = _FakeWorker(worker, beat_interval=0.1)
    try:
        h = net.NetWorkerHandle(
            "t", 0, router, {"name": "fw", "pid": 4242, "host": "fakehost"},
            b"generation-payload", lease_s=2.0, ready_timeout=5.0,
        )
        try:
            msg, payload = fw.deploy
            assert msg["op"] == "deploy"
            assert payload == b"generation-payload"
            assert msg["spec"]["digest"] == net.payload_digest(payload)
            assert msg["spec"]["lease_s"] == 2.0
            assert h.alive() and h.pid == 4242 and h.peer_host == "fakehost"
            assert h.stats()["lease_s"] == 2.0
        finally:
            h.kill()
    finally:
        fw.close()
        _close_all(router)


def test_handle_apply_roundtrip_survives_compute_longer_than_lease():
    """A computing worker KEEPS BEATING, and a beating worker holds its
    lease — only silence fences, never slowness."""
    router, worker = _tcp_pair()

    def on_apply(msg, payload):
        arr = wire.payload_array(msg["meta"], payload)
        time.sleep(1.2)  # > lease_s: beats must carry the lease
        rmeta, rp = wire.array_payload(arr * 2.0)
        return {"op": "result", "fid": msg["fid"], "meta": rmeta}, rp

    fw = _FakeWorker(worker, on_apply=on_apply, beat_interval=0.1)
    try:
        h = net.NetWorkerHandle(
            "t", 0, router, {"name": "fw", "pid": 1},
            b"gen", lease_s=0.5, ready_timeout=5.0,
        )
        try:
            arr = _rows(3, seed=1)
            out = h.apply(arr, 3)
            assert out.tobytes() == (arr * 2.0).tobytes()
        finally:
            h.shutdown(timeout=1.0)
    finally:
        fw.close()
        _close_all(router)


def test_handle_retransmits_lost_apply_on_a_beating_link():
    """The lost-frame hole: a partition can eat exactly one apply frame
    and heal within the lease window — the worker beats on, so the
    lease never expires, and without retransmission the router would
    wait forever.  The handle must resend every ``lease_s / 2``; the
    duplicate is answered normally (or from the reply cache), and the
    flush completes instead of wedging."""
    router, worker = _tcp_pair()
    applies = {"n": 0}

    def on_apply(msg, payload):
        applies["n"] += 1
        if applies["n"] == 1:
            return None  # the first copy "never arrived"
        arr = wire.payload_array(msg["meta"], payload)
        rmeta, rp = wire.array_payload(arr + 1.0)
        return {"op": "result", "fid": msg["fid"], "meta": rmeta}, rp

    fw = _FakeWorker(worker, on_apply=on_apply, beat_interval=0.1)
    try:
        h = net.NetWorkerHandle(
            "t", 0, router, {"name": "fw", "pid": 1},
            b"gen", lease_s=0.8, ready_timeout=5.0,
        )
        try:
            before = metrics.REGISTRY.counter_total("serve.net.retransmits")
            arr = _rows(2, seed=9)
            out = h.apply(arr, 2)
            assert out.tobytes() == (arr + 1.0).tobytes()
            assert applies["n"] >= 2
            assert (
                metrics.REGISTRY.counter_total("serve.net.retransmits")
                > before
            )
        finally:
            h.shutdown(timeout=1.0)
    finally:
        fw.close()
        _close_all(router)


def test_handle_fatal_ready_raises_spawn_error():
    router, worker = _tcp_pair()
    fw = _FakeWorker(
        worker,
        ready={"op": "fatal", "etype": "RuntimeError", "emsg": "boom"},
    )
    try:
        with pytest.raises(WorkerSpawnError, match="failed to start"):
            net.NetWorkerHandle(
                "t", 0, router, {"name": "fw"}, b"gen",
                lease_s=1.0, ready_timeout=5.0,
            )
    finally:
        fw.close()
        _close_all(router)


def test_lease_expiry_forfeits_flush_and_discards_late_result():
    """THE fencing pin: a worker that goes silent mid-request costs the
    router exactly one WorkerCrashed (un-claim → front-requeue → heal),
    and when its result limps in after the lease was forfeited, the
    reader observes it and DISCARDS it — a no-op, never a double
    delivery (``serve.net.late_discards``)."""
    router, worker = _tcp_pair()
    held = {}

    def on_apply(msg, payload):
        held["msg"] = msg
        return None  # withhold: the worker "partitioned" mid-compute

    # no beat_interval: the fake goes silent after ready
    fw = _FakeWorker(worker, on_apply=on_apply)
    try:
        h = net.NetWorkerHandle(
            "t", 0, router, {"name": "fw", "pid": 1},
            b"gen", lease_s=0.6, ready_timeout=5.0,
        )
        try:
            before = metrics.REGISTRY.counter_total("serve.net.late_discards")
            t0 = time.monotonic()
            with pytest.raises(WorkerCrashed, match="lease expired"):
                h.apply(_rows(2, seed=2), 2)
            # forfeited at the lease bound, not some unrelated timeout
            assert 0.4 < time.monotonic() - t0 < 10.0
            assert not h.alive()
            # the fenced loser's result arrives late: discarded, counted
            assert "msg" in held
            rmeta, rp = wire.array_payload(np.zeros((2, DIM), np.float32))
            fw.send(
                {"op": "result", "fid": held["msg"]["fid"], "meta": rmeta},
                rp,
            )
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if (
                    metrics.REGISTRY.counter_total("serve.net.late_discards")
                    > before
                ):
                    break
                time.sleep(0.02)
            else:
                pytest.fail("late result was not observed and discarded")
        finally:
            h.kill()
    finally:
        fw.close()
        _close_all(router)


def test_handle_injected_partition_is_silence_then_crash():
    """A ``drop`` plan on this link suppresses outbound frames and
    discards inbound ones — the handle sees a partition (silence), and
    an apply forfeits at the lease bound."""
    router, worker = _tcp_pair()

    def on_apply(msg, payload):
        arr = wire.payload_array(msg["meta"], payload)
        rmeta, rp = wire.array_payload(arr)
        return {"op": "result", "fid": msg["fid"], "meta": rmeta}, rp

    fw = _FakeWorker(worker, on_apply=on_apply, beat_interval=0.05)
    try:
        h = net.NetWorkerHandle(
            "t", 7, router, {"name": "fw", "pid": 1},
            b"gen", lease_s=0.5, ready_timeout=5.0,
        )
        try:
            assert h.name == "t-net7"
            plan = (
                f"serve.net.send:ctx.link={h.name}:drop;"
                f"serve.net.recv:ctx.link={h.name}:partition"
            )
            with faults.inject(plan):
                with pytest.raises(WorkerCrashed):
                    h.apply(_rows(2, seed=4), 2)
        finally:
            h.kill()
    finally:
        fw.close()
        _close_all(router)


# ------------------------------------- worker side: session state machine
def _recv_skipping_beats(sock, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            msg, payload = wire.recv_stream_frame(sock, timeout=0.5)
        except TimeoutError:
            continue
        if msg.get("op") != "beat":
            return msg, payload
    raise TimeoutError("no non-beat frame")


def test_worker_session_reuses_cached_applier_and_dedups_retransmits():
    """Rejoin economics + idempotency: a cached digest skips the
    rebuild (``reused: true``), and a retransmitted flush id answers
    from the reply cache without recomputing — at-least-once dispatch,
    exactly-once effect."""
    router, worker = _tcp_pair()
    calls = {"n": 0}

    def applier(ds, deadline=None):
        calls["n"] += 1
        return SimpleNamespace(
            array=np.full((2, DIM), float(calls["n"]), np.float32)
        )

    payload = b"generation-A"
    digest = net.payload_digest(payload)
    cache = {digest: (applier, 0)}
    out = {}
    t = threading.Thread(
        target=lambda: out.setdefault(
            "reason", net._worker_session(worker, "sess-w", cache)
        ),
        daemon=True,
    )
    t.start()
    try:
        spec = {"name": "sess-w", "digest": digest, "lease_s": 5.0}
        wire.send_stream_frame(router, {"op": "deploy", "spec": spec}, payload)
        ready, _ = wire.recv_stream_frame(router, timeout=10.0)
        assert ready["op"] == "ready" and ready["reused"] is True

        meta, p = wire.array_payload(_rows(2, seed=5))
        req = {"op": "apply", "fid": "fX", "n": 2, "meta": meta}
        wire.send_stream_frame(router, req, p)
        r1, p1 = _recv_skipping_beats(router)
        assert r1["op"] == "result" and r1["fid"] == "fX"
        # the same fid again: same bytes back, applier NOT re-invoked
        wire.send_stream_frame(router, req, p)
        r2, p2 = _recv_skipping_beats(router)
        assert r2["fid"] == "fX" and p2 == p1
        assert calls["n"] == 1

        wire.send_stream_frame(router, {"op": "bye"})
        msg, _ = _recv_skipping_beats(router)
        assert msg["op"] == "bye_ack"
        t.join(5.0)
        assert out.get("reason") == "bye"
    finally:
        _close_all(router, worker)


def test_worker_session_self_fences_and_never_sends_the_result():
    """The split-brain pin from the worker's seat: silence outlasting
    the lease while a flush computes means the router has re-dispatched
    it — the finished result is DISCARDED (never sent) and the session
    exits ``fenced`` to rejoin for a fresh lease."""
    router, worker = _tcp_pair()

    def applier(ds, deadline=None):
        time.sleep(1.2)  # compute outlasts the lease, with NO beats
        return SimpleNamespace(array=np.zeros((2, DIM), np.float32))

    payload = b"generation-B"
    digest = net.payload_digest(payload)
    cache = {digest: (applier, 0)}
    out = {}
    t = threading.Thread(
        target=lambda: out.setdefault(
            "reason", net._worker_session(worker, "fence-w", cache)
        ),
        daemon=True,
    )
    t.start()
    try:
        spec = {"name": "fence-w", "digest": digest, "lease_s": 0.4}
        wire.send_stream_frame(router, {"op": "deploy", "spec": spec}, payload)
        ready, _ = wire.recv_stream_frame(router, timeout=10.0)
        assert ready["op"] == "ready"
        meta, p = wire.array_payload(_rows(2, seed=6))
        wire.send_stream_frame(
            router, {"op": "apply", "fid": "f1", "n": 2, "meta": meta}, p
        )
        # go SILENT and collect everything the worker sends until it
        # closes: beats only — the computed result must never appear
        seen = []
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                msg, _ = wire.recv_stream_frame(router, timeout=0.5)
            except TimeoutError:
                continue
            except (EOFError, OSError, wire.WireError):
                break
            seen.append(msg.get("op"))
        t.join(5.0)
        assert out.get("reason") == "fenced"
        assert "result" not in seen and "error" not in seen
    finally:
        _close_all(router, worker)


def test_drain_ready_preserves_stashed_payload_bytes():
    """Frames stashed by the mid-compute drain keep their payload:
    replaying an apply with ``b""`` would turn a recomputable request
    into a meta/byte-count ``WireError`` the moment the stashed fid
    misses the last-reply cache."""
    a, b = _spair()
    try:
        meta, p = wire.array_payload(_rows(2, seed=7))
        wire.send_stream_frame(a, {"op": "beat"})
        wire.send_stream_frame(
            a, {"op": "apply", "fid": "fZ", "n": 2, "meta": meta}, p
        )
        time.sleep(0.1)  # let both frames land in b's kernel buffer
        stashed, got_any, dead = net._drain_ready(
            b, wire.DEFAULT_MAX_FRAME_BYTES, "drain-w"
        )
        assert got_any and not dead
        assert len(stashed) == 1
        msg, payload = stashed[0]
        assert msg["fid"] == "fZ" and payload == p
        arr = wire.payload_array(msg["meta"], payload)
        assert arr.shape == (2, DIM)
    finally:
        _close_all(a, b)


# ------------------------------------------- fleet telemetry over the wire


def _doubling_worker(sock, telemetry=None):
    """A _FakeWorker that doubles its input; ``telemetry`` (a callable
    returning the reply's telemetry body) makes it a NEW-protocol
    worker, None keeps it an OLD one (no telemetry keys anywhere)."""

    def on_apply(msg, payload):
        t_rx = time.monotonic()
        arr = wire.payload_array(msg["meta"], payload)
        rmeta, rp = wire.array_payload(arr * 2.0)
        reply = {"op": "result", "fid": msg["fid"], "meta": rmeta}
        if telemetry is not None:
            reply["telemetry"] = telemetry(t_rx)
        return reply, rp

    return _FakeWorker(sock, on_apply=on_apply, beat_interval=0.1)


def test_apply_frame_carries_trace_only_when_given():
    """The recorder-off wire pin at frame granularity: without trace
    context the apply frame has EXACTLY the pre-tracing keys (an old
    worker sees the old protocol, byte-for-byte); with context the
    ``trace`` body rides along verbatim."""
    router, worker = _tcp_pair()
    fw = _doubling_worker(worker)
    try:
        h = net.NetWorkerHandle(
            "t", 0, router, {"name": "fw", "pid": 1},
            b"gen", lease_s=2.0, ready_timeout=5.0,
        )
        try:
            h.apply(_rows(2, seed=0), 2)
            ctx = {"batch": "b1", "request_ids": ["r1", "r2"]}
            h.apply(_rows(2, seed=1), 2, trace=ctx)
            applies = [f for f in fw.frames if f.get("op") == "apply"]
            assert len(applies) == 2
            assert "trace" not in applies[0]
            assert set(applies[0]) == {"op", "fid", "n", "meta", "deadline_s"}
            assert applies[1]["trace"] == ctx
        finally:
            h.shutdown(timeout=1.0)
    finally:
        fw.close()
        _close_all(router)


def test_old_worker_without_telemetry_is_tolerated():
    """Version skew, worker-side: a worker that never ships telemetry
    (no keys in ready/replies/beats) serves normally and the attached
    sink simply records nothing — absent field means old peer."""
    from keystone_tpu.serve.telemetry import FleetTelemetry

    router, worker = _tcp_pair()
    fw = _doubling_worker(worker)
    try:
        h = net.NetWorkerHandle(
            "t", 0, router, {"name": "fw", "pid": 1},
            b"gen", lease_s=2.0, ready_timeout=5.0,
        )
        try:
            sink = FleetTelemetry(registry=metrics.MetricsRegistry())
            h.attach_telemetry(sink)
            arr = _rows(3, seed=2)
            out = h.apply(arr, 3, trace={"batch": "bX"})
            assert out.tobytes() == (arr * 2.0).tobytes()
            assert sink.known_workers() == []
        finally:
            h.shutdown(timeout=1.0)
    finally:
        fw.close()
        _close_all(router)


def test_worker_shipped_telemetry_stitches_and_aggregates():
    """The full return path over a real socket: ready-frame metrics
    flush on attach, reply spans stitch into the traced flush's batch
    record, and a beat-piggybacked delta lands in the registry under
    worker=/host= labels."""
    from keystone_tpu.obs.recorder import FlightRecorder
    from keystone_tpu.serve.telemetry import FleetTelemetry

    router, worker = _tcp_pair()

    def reply_telemetry(t_rx):
        now = time.monotonic()
        return {
            "t_rx": t_rx,
            "t_tx": now,
            "spans": [{"name": "worker.apply", "t0": t_rx, "t1": now}],
        }

    fw = _doubling_worker(worker, telemetry=reply_telemetry)
    try:
        h = net.NetWorkerHandle(
            "t", 0, router, {"name": "fw", "pid": 1, "host": "fakehost"},
            b"gen", lease_s=2.0, ready_timeout=5.0,
        )
        try:
            reg = metrics.MetricsRegistry()
            rec = FlightRecorder()
            sink = FleetTelemetry(registry=reg, recorder=rec)
            h.attach_telemetry(sink)
            rec.annotate("r1", "serve.replica", batch="b1", replica=0)
            rec.batch("b1", ["r1"], replica=0, rows=2)
            arr = _rows(2, seed=4)
            out = h.apply(arr, 2, trace={"batch": "b1", "request_ids": ["r1"]})
            assert out.tobytes() == (arr * 2.0).tobytes()
            # the reply's spans were aligned + stitched into the record
            assert sink.known_workers() == ["t-net0"]
            rec.finish("r1", "completed", batch="b1")
            (b,) = rec.request("r1")["batch_records"]
            assert b["worker"] == "t-net0" and b["host"] == "fakehost"
            assert b["wire"]["rtt_s"] is not None and b["wire"]["rtt_s"] >= 0.0
            names = [s["name"] for s in b["worker_spans"]]
            assert "worker.apply" in names
            for s in b["worker_spans"]:
                assert s["seconds"] >= 0.0 and s["t_off"] >= 0.0
            assert (
                reg.histogram_summary(
                    "serve.fleet.apply_seconds", worker="t-net0", host="fakehost"
                )["count"]
                == 1
            )
            # a beat-piggybacked metrics delta merges under the labels
            fw.send(
                {
                    "op": "beat",
                    "telemetry": {
                        "metrics": [["c", "serve.fake_beat_total", [], 3.0]]
                    },
                }
            )
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if reg.counter_value(
                    "serve.fake_beat_total", worker="t-net0", host="fakehost"
                ):
                    break
                time.sleep(0.02)
            assert (
                reg.counter_value(
                    "serve.fake_beat_total", worker="t-net0", host="fakehost"
                )
                == 3.0
            )
        finally:
            h.shutdown(timeout=1.0)
    finally:
        fw.close()
        _close_all(router)


# --------------------------------------------------- live TCP fleet e2e
@pytest.fixture(scope="module")
def net_service():
    """One workers=2 cross-host fleet on loopback, shared by the e2e
    tests (each worker spawn pays a fresh interpreter + jax import;
    lease healing keeps the fixture valid across tests)."""
    from keystone_tpu.serve import serve

    svc = serve(
        _pipeline(),
        workers=2,
        hosts=["local", "local"],
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=512,
        example=np.zeros(DIM, np.float32),
        name="netfleet_t",
        supervise_interval_s=0.1,
        heartbeat_s=10.0,
        restart_limit=1000,
        worker_opts={"lease_s": 1.0, "spawn_grace_s": 3.0},
    )
    yield svc
    svc.close()


def _threaded_ref(x: np.ndarray) -> np.ndarray:
    from keystone_tpu.serve import serve

    ref = serve(
        _pipeline(),
        max_batch=8,
        max_wait_ms=2.0,
        example=np.zeros(DIM, np.float32),
        name="netfleet_ref",
        supervise=False,
    )
    try:
        return np.stack(
            [f.result(timeout=60) for f in [ref.submit(r) for r in x]]
        )
    finally:
        ref.close()


def test_net_fleet_serves_and_matches_threaded(net_service):
    """Predictions over TCP are BIT-identical to the threaded
    single-replica service — the transport is a transport, never a
    numerics change."""
    x = _rows(12, seed=3)
    got = np.stack(
        [f.result(timeout=60) for f in [net_service.submit(r) for r in x]]
    )
    assert got.tobytes() == _threaded_ref(x).tobytes()


def test_net_fleet_status_exposes_leased_links(net_service):
    st = net_service.status()
    assert st["backend"] == "net"
    reps = st["replicas"]
    assert reps and all(r["backend"] == "net" for r in reps)
    assert all(r["lease_s"] == 1.0 for r in reps)
    alive = [r for r in reps if r["worker_alive"]]
    assert alive, "no live leased worker in status"
    assert all(isinstance(r["link"], str) and r["link"] for r in reps)
    ages = [
        r["worker_heartbeat_age_s"]
        for r in alive
        if r["worker_heartbeat_age_s"] is not None
    ]
    assert ages and min(ages) < 1.0  # beats at lease/4 = 0.25s


def test_partition_mid_flight_loses_nothing_and_heals(net_service):
    """THE acceptance pin: sever one worker's link both directions
    while requests stream — zero lost futures (the forfeited flush
    re-serves on the survivor), results bit-identical to the
    unpartitioned reference, and after the partition lifts the fleet
    heals back to two live leased workers (the fenced worker rejoins
    through the front door)."""
    x = _rows(48, seed=7)
    want = _threaded_ref(x)
    links = [r["link"] for r in net_service.replica_statuses() if "link" in r]
    assert links, "no leased links to partition"
    victim = links[0]
    plan = (
        f"serve.net.send:ctx.link={victim}:partition;"
        f"serve.net.recv:ctx.link={victim}:partition"
    )
    futs = []
    with faults.inject(plan):
        for r in x[:24]:
            futs.append(net_service.submit(r))
        # hold the partition past the lease (1.0s): the victim's
        # in-flight flush forfeits and re-dispatches on the survivor,
        # the victim self-fences
        time.sleep(2.5)
    for r in x[24:]:
        futs.append(net_service.submit(r))
    got = np.stack([f.result(timeout=120) for f in futs])
    assert got.tobytes() == want.tobytes()

    # heal gate: both slots hold live leases again
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        alive = [
            r
            for r in net_service.replica_statuses()
            if r.get("worker_alive")
        ]
        if len(alive) >= 2:
            break
        time.sleep(0.25)
    else:
        pytest.fail("fleet did not heal back to 2 live workers within 60s")


def test_net_fleet_aggregates_metrics_and_stitches_trace(net_service):
    """E2E acceptance, TCP edition: with two leased workers, the
    router's ops surface covers the whole fleet — worker-shipped
    series land in the registry under worker=/host= labels, /statusz
    grows a fleet block with clock-sync state for BOTH workers, and a
    traced request's /requestz chain crosses the wire (stitched
    worker@host, wire accounting, aligned worker.apply span)."""
    rid = "net-trace-e2e"
    x = _rows(16, seed=13)
    futs = [net_service.submit(x[0], request_id=rid)]
    futs += [net_service.submit(r) for r in x[1:]]
    for f in futs:
        f.result(timeout=120)
    # the deploy→ready exchange gave every worker a clock sample, so
    # the fleet block lists both slots even before both serve a flush
    fleet = net_service.status().get("fleet")
    assert fleet is not None
    assert set(fleet["workers"]) == {"netfleet_t-net0", "netfleet_t-net1"}
    for entry in fleet["workers"].values():
        assert entry["host"]
        assert entry["clock_samples"] >= 1
    series = metrics.REGISTRY.histogram_series("serve.fleet.apply_seconds")
    assert series, "no worker-shipped apply series reached the registry"
    assert all(lb.get("worker") and lb.get("host") for lb, _ in series)
    net_workers = [
        lb["worker"] for lb, _ in series if lb["worker"].startswith("netfleet_t-")
    ]
    assert net_workers, f"no net-fleet series in {series}"
    tr = net_service.recorder.request(rid)
    assert tr is not None
    stitched = [b for b in tr["batch_records"] if b.get("worker")]
    assert stitched, f"unstitched batch records: {tr['batch_records']}"
    b = stitched[0]
    assert b["worker"].startswith("netfleet_t-net") and b.get("host")
    assert "wire" in b
    assert "worker.apply" in [s["name"] for s in b.get("worker_spans", [])]
