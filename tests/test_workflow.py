"""Workflow core tests.

Mirrors the reference's workflow/PipelineSuite.scala, OptimizerSuite.scala,
GraphSuite.scala pattern: toy graphs, side-effect counters in fake nodes to
assert CSE merges and memoized execution counts (SURVEY.md §4).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.workflow import (
    Dataset,
    Estimator,
    FusedTransformer,
    LabelEstimator,
    Pipeline,
    Transformer,
    default_optimizer,
    transformer,
)


class CountingDouble(Transformer):
    """x * 2 with an invocation counter; CSE-mergeable."""

    calls = 0

    def params(self):
        return ("double",)

    def apply_one(self, x):
        return x * 2.0

    def apply_batch(self, xs, mask=None):
        CountingDouble.calls += 1
        return xs * 2.0


class AddConst(Transformer):
    def __init__(self, c):
        self.c = float(c)

    def params(self):
        return (self.c,)

    def apply_one(self, x):
        return x + self.c

    def apply_batch(self, xs, mask=None):
        return xs + self.c


class MeanShift(Estimator):
    """Fits the mean; transformer subtracts it."""

    fit_calls = 0

    def params(self):
        return ("meanshift",)

    def fit_arrays(self, x):
        MeanShift.fit_calls += 1
        mu = jnp.mean(x, axis=0)
        return AddConst(0.0) if mu.ndim == 0 else _Sub(mu)


class _Sub(Transformer):
    def __init__(self, mu):
        self.mu = mu

    def apply_batch(self, xs, mask=None):
        return xs - self.mu


class ScaleToLabelMean(LabelEstimator):
    def fit_arrays(self, x, y=None):
        s = jnp.mean(y) / jnp.maximum(jnp.mean(x), 1e-9)
        return AddConst(0.0) if s.ndim != 0 else _Scale(s)


class _Scale(Transformer):
    def __init__(self, s):
        self.s = s

    def apply_batch(self, xs, mask=None):
        return xs * self.s


def test_transformer_eager_apply():
    t = AddConst(1.0)
    ds = Dataset(np.zeros((5, 3), np.float32))
    out = t(ds)
    assert np.allclose(out.numpy(), 1.0)
    assert out.n == 5
    assert float(t(jnp.array(2.0))) == 3.0


def test_lambda_transformer():
    t = transformer(lambda x: x * 3.0, name="Triple")
    ds = Dataset(np.ones((4, 2), np.float32))
    assert np.allclose(t(ds).numpy(), 3.0)
    assert t.label == "Triple"


def test_pipeline_chain_and_apply():
    p = AddConst(1.0) | AddConst(2.0)
    ds = Dataset(np.zeros((6, 2), np.float32))
    out = p(ds).get()
    assert np.allclose(out.numpy(), 3.0)


def test_padding_preserved_through_pipeline():
    # 5 rows on a 4-wide data axis: padded to 8, but numpy() returns 5.
    ds = Dataset(np.arange(10, dtype=np.float32).reshape(5, 2))
    out = (AddConst(1.0) | AddConst(1.0))(ds).get()
    assert out.numpy().shape == (5, 2)


def test_estimator_with_data_and_fit():
    data = np.random.default_rng(0).normal(2.0, 1.0, (32, 4)).astype(np.float32)
    pipe = AddConst(0.0) | MeanShift().with_data(Dataset(data))
    out = pipe(Dataset(data)).get().numpy()
    assert abs(out.mean()) < 1e-5


def test_label_estimator():
    x = np.ones((16, 3), np.float32)
    y = np.full((16, 3), 5.0, np.float32)
    pipe = Pipeline.of(AddConst(0.0)).and_then(
        ScaleToLabelMean(), Dataset(x), Dataset(y)
    )
    out = pipe(Dataset(x)).get().numpy()
    assert np.allclose(out, 5.0, atol=1e-5)


def test_fit_resolves_estimators_and_is_reusable():
    MeanShift.fit_calls = 0
    data = np.random.default_rng(1).normal(3.0, 1.0, (32, 4)).astype(np.float32)
    pipe = AddConst(1.0).and_then(MeanShift(), Dataset(data))
    fitted = pipe.fit()
    out1 = fitted(Dataset(data)).get().numpy()
    out2 = fitted(Dataset(data + 1.0)).get().numpy()
    assert MeanShift.fit_calls == 1
    assert abs(out1.mean()) < 1e-4
    assert abs(out2.mean() - 1.0) < 1e-4


def test_gather_concatenates_features():
    branches = [Pipeline.of(AddConst(float(i))) for i in range(3)]
    p = Pipeline.gather(branches)
    ds = Dataset(np.zeros((4, 2), np.float32))
    out = p(ds).get().numpy()
    assert out.shape == (4, 6)
    assert np.allclose(out[:, 0:2], 0.0)
    assert np.allclose(out[:, 4:6], 2.0)


def test_cse_merges_identical_branches():
    """Two gather branches share an identical CountingDouble prefix; after
    CSE it must execute exactly once (EquivalentNodeMergeRule semantics).

    The merge + single-execution property is asserted on the CSE rule
    directly (the default path's materialization pass ALSO samples the
    graph during optimization — the reference's AutoCacheRule ran the
    same kind of sampling jobs — which would obscure the count)."""
    from keystone_tpu.workflow import GraphExecutor
    from keystone_tpu.workflow.optimizer import EquivalentNodeMergeRule

    CountingDouble.calls = 0
    b1 = CountingDouble() | AddConst(1.0)
    b2 = CountingDouble() | AddConst(2.0)
    p = Pipeline.gather([b1, b2])
    ds = Dataset(np.ones((4, 2), np.float32))
    g = EquivalentNodeMergeRule().apply(p(ds).graph)
    out_expr = GraphExecutor(g).execute(g.sinks[0])
    out = np.asarray(out_expr.dataset.array)
    assert out.shape == (4, 4)
    assert np.allclose(out[:, :2], 3.0)
    assert np.allclose(out[:, 2:], 4.0)
    assert CountingDouble.calls == 1

    # and the full default path still produces the same result
    out2 = p(Dataset(np.ones((4, 2), np.float32))).get().numpy()
    assert np.allclose(out2, out)


def test_fusion_rule_fuses_linear_chains():
    from keystone_tpu.workflow import Graph, StageFusionRule, TransformerOperator

    g = Graph()
    g, src = g.add_source()
    g, n1 = g.add_node(TransformerOperator(AddConst(1.0)), (src,))
    g, n2 = g.add_node(TransformerOperator(AddConst(2.0)), (n1,))
    g, n3 = g.add_node(TransformerOperator(AddConst(3.0)), (n2,))
    g, sink = g.add_sink(n3)
    fused = StageFusionRule().apply(g)
    ops = [op for op in fused.operators.values()]
    assert len(ops) == 1
    assert isinstance(ops[0].transformer, FusedTransformer)
    assert len(ops[0].transformer.stages) == 3


def test_fusion_preserves_no_memoize_flag():
    """Fusing INTO an over-HBM-budget node (no_memoize — recompute per
    consumer) must carry the flag to the fused replacement, or the
    executor pins the very output the cache rule decided the device
    cannot afford."""
    from keystone_tpu.workflow import Graph, StageFusionRule, TransformerOperator

    g = Graph()
    g, src = g.add_source()
    g, n1 = g.add_node(TransformerOperator(AddConst(1.0)), (src,))
    flagged = TransformerOperator(AddConst(2.0))
    flagged.no_memoize = True
    g, n2 = g.add_node(flagged, (n1,))
    g, sink = g.add_sink(n2)
    fused = StageFusionRule().apply(g)
    ops = [op for op in fused.operators.values()]
    assert len(ops) == 1
    assert isinstance(ops[0].transformer, FusedTransformer)
    assert getattr(ops[0], "no_memoize", False) is True


def test_fused_transformer_matches_unfused():
    chain = [AddConst(1.0), CountingDouble(), AddConst(-0.5)]
    fused = FusedTransformer(chain)
    x = jnp.arange(12, dtype=jnp.float32).reshape(4, 3)
    expect = (x + 1.0) * 2.0 - 0.5
    assert np.allclose(np.asarray(fused.apply_batch(x)), np.asarray(expect))


def test_fit_re_fuses_chains_through_substituted_estimators():
    """fit() must re-run stage fusion AFTER estimator substitution: the
    fitted model's apply node was a DelegatingOperator (unfusable) during
    optimization, so the scoring path would otherwise dispatch one jit
    program per post-model stage (each costing a per-process trace +
    cache load — BASELINE.md r4 fit-overhead split)."""
    data = np.random.default_rng(3).normal(1.0, 1.0, (16, 4)).astype(np.float32)
    fitted = (
        AddConst(0.5)
        .and_then(MeanShift(), Dataset(data))
        .and_then(AddConst(1.0))
        .and_then(AddConst(2.0))
    ).fit()
    from keystone_tpu.workflow import TransformerOperator

    fused = [
        op.transformer
        for op in fitted.graph.operators.values()
        if isinstance(op, TransformerOperator)
        and isinstance(op.transformer, FusedTransformer)
    ]
    # the fitted MeanShift + trailing AddConsts collapse into one stage
    assert any(len(f.stages) >= 3 for f in fused)
    out = fitted(Dataset(data)).get().numpy()
    expect = (data + 0.5) - (data + 0.5).mean(axis=0) + 3.0
    assert np.allclose(out, expect, atol=1e-5)


def test_chunked_apply_matches_unchunked(monkeypatch):
    """Row-chunked device applies (shape-stable programs — fit setup
    cost stops scaling with n) must be bit-identical to the whole-batch
    program: plain ops, ragged tails padded to the canonical chunk, and
    ragged (values, mask) producers."""
    monkeypatch.setenv("KEYSTONE_APPLY_CHUNK", "64")
    rng = np.random.default_rng(11)
    x = rng.normal(size=(205, 6)).astype(np.float32)  # 3 full + ragged tail
    op = AddConst(1.5)
    chunked = op.apply_dataset(Dataset(x))
    monkeypatch.setenv("KEYSTONE_APPLY_CHUNK", "0")
    whole = op.apply_dataset(Dataset(x))
    np.testing.assert_array_equal(
        np.asarray(chunked.array), np.asarray(whole.array)
    )
    assert chunked.n == whole.n


def test_chunked_apply_ragged_producer_and_sampler(monkeypatch):
    """SIFT (a (values, mask) producer) and ColumnSampler (global-index
    keys) through the chunked path == unchunked, including the sampler's
    offset-keyed chunks."""
    from keystone_tpu.ops import ColumnSampler, SIFTExtractor

    rng = np.random.default_rng(4)
    imgs = rng.uniform(0, 1, (70, 40, 40)).astype(np.float32)
    sift = SIFTExtractor(step=6, bin_sizes=(4,))
    sampler = ColumnSampler(8, seed=3)

    monkeypatch.setenv("KEYSTONE_APPLY_CHUNK", "32")
    d1 = sift.apply_dataset(Dataset(imgs))
    s1 = sampler.apply_dataset(d1)
    monkeypatch.setenv("KEYSTONE_APPLY_CHUNK", "0")
    d0 = sift.apply_dataset(Dataset(imgs))
    s0 = sampler.apply_dataset(d0)
    np.testing.assert_allclose(
        np.asarray(d1.array), np.asarray(d0.array), atol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(d1.mask), np.asarray(d0.mask)
    )
    np.testing.assert_allclose(
        np.asarray(s1.array), np.asarray(s0.array), atol=1e-6
    )


def test_host_transformer_path():
    up = transformer(lambda s: s.upper(), name="Upper", host=True)
    ds = Dataset(["ab", "cd"])
    out = up(ds)
    assert out.items == ["AB", "CD"]


def test_save_load_fitted(tmp_path):
    data = np.random.default_rng(2).normal(1.0, 1.0, (16, 4)).astype(np.float32)
    fitted = AddConst(0.5).and_then(MeanShift(), Dataset(data)).fit()
    path = str(tmp_path / "pipe.pkl")
    fitted.save(path)
    from keystone_tpu.workflow import FittedPipeline

    loaded = FittedPipeline.load(path)
    a = fitted(Dataset(data)).get().numpy()
    b = loaded(Dataset(data)).get().numpy()
    assert np.allclose(a, b)


def test_fitted_read_back_reads_every_fitted_array():
    """read_back() must return one finite scalar per fitted device array
    (the bench fit leg's run-end sync — a REAL device→host transfer,
    robust to fusion wrapping because it walks nested state generically)."""
    data = np.random.default_rng(5).normal(2.0, 1.0, (16, 4)).astype(np.float32)
    fitted = AddConst(0.5).and_then(MeanShift(), Dataset(data)).fit()
    scalars = fitted.read_back()
    assert scalars.size >= 1  # at least the fitted mean
    assert np.all(np.isfinite(scalars))


def test_pipeline_datum_apply():
    p = AddConst(1.0) | AddConst(1.0)
    out = p.apply_datum(jnp.array([1.0, 2.0])).get()
    assert np.allclose(np.asarray(out), [3.0, 4.0])


def test_save_load_fitted_after_apply(tmp_path):
    """Applying a fitted pipeline populates the per-transformer jit cache;
    save/load must still work (the cache is weak+module-level, never
    pickled) and the loaded pipeline must predict identically."""
    from keystone_tpu.models import LinearMapEstimator
    from keystone_tpu.ops import ClassLabelIndicators, LinearRectifier
    from keystone_tpu.workflow.pipeline import FittedPipeline

    rng = np.random.default_rng(0)
    x = Dataset(rng.normal(size=(64, 8)).astype(np.float32))
    y = ClassLabelIndicators(3)(
        Dataset(rng.integers(0, 3, size=(64,)).astype(np.int32))
    )
    fitted = (
        Pipeline.of(LinearRectifier(0.0)).and_then(LinearMapEstimator(lam=0.1), x, y)
    ).fit()
    before = fitted(x).get().numpy()  # populates _JIT_APPLY_CACHE
    path = str(tmp_path / "fp.pkl")
    fitted.save(path)
    loaded = FittedPipeline.load(path)
    np.testing.assert_allclose(loaded(x).get().numpy(), before, atol=1e-6)


# ------------------------------------------------- traced-parameter applies
def test_traced_params_share_one_program_across_instances():
    """Two PCATransformers (different fitted values, same shapes) must
    share ONE compiled program: parameters ride as traced arguments
    (Transformer.traced_attrs), so lowering never embeds fitted device
    arrays as constants — the measured ~0.4 s/array tunnel read and the
    refit-recompiles-everything cache-key hazard (BASELINE.md r5)."""
    import importlib

    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.workflow.dataset import Dataset

    # the workflow package re-exports the `transformer` DECORATOR under
    # the module's name, so attribute-style imports get the function
    T = importlib.import_module("keystone_tpu.workflow.transformer")

    # hermetic: earlier tests may have populated PCA entries for other
    # input signatures (bf16 mode, masked applies)
    for k in [k for k in T._SHARED_APPLY_CACHE if k[0] is PCATransformer]:
        del T._SHARED_APPLY_CACHE[k]

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(8, 12)).astype(np.float32)
    c1 = rng.normal(size=(12, 3)).astype(np.float32)
    c2 = rng.normal(size=(12, 3)).astype(np.float32)
    m1 = rng.normal(size=(12,)).astype(np.float32)
    p1 = PCATransformer(jnp.asarray(c1), jnp.asarray(m1))
    p2 = PCATransformer(jnp.asarray(c2), None)

    y1 = np.asarray(p1.apply_dataset(Dataset(xs, shard=False)).array)
    y2 = np.asarray(p2.apply_dataset(Dataset(xs, shard=False)).array)
    np.testing.assert_allclose(y1, (xs - m1) @ c1, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(y2, xs @ c2, rtol=1e-5, atol=1e-5)

    # one shared wrapper per parameter STRUCTURE (mean present vs absent
    # key separately so a bad instance poisons only its own signature);
    # instances with equal structure share one wrapper and one program
    keys = [k for k in T._SHARED_APPLY_CACHE if k[0] is PCATransformer]
    assert len(keys) == 2
    key2 = [k for k in keys if k[3] == T.traced_param_sig(p2)]
    assert len(key2) == 1
    fn = T._SHARED_APPLY_CACHE[key2[0]]
    # a third instance with the SAME structure as p2 must hit the cache,
    # not grow it
    sizes = fn._cache_size()
    p3 = PCATransformer(jnp.asarray(c1), None)
    y3 = np.asarray(p3.apply_dataset(Dataset(xs, shard=False)).array)
    np.testing.assert_allclose(y3, xs @ c1, rtol=1e-5, atol=1e-5)
    assert fn._cache_size() == sizes
    # the process-lifetime template must not pin fitted arrays (review:
    # fingerprint caches ride shallow copies)
    tpl = T.stripped_template(p1)
    assert tpl.components is None and tpl.mean is None
    assert "_fp" not in vars(tpl)


def test_traced_params_refit_uses_new_values():
    """The shared program must read the INSTANCE's current parameters —
    a stale closure constant would silently score with the old fit."""
    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.workflow.dataset import Dataset

    xs = np.eye(4, dtype=np.float32)
    w1 = np.full((4, 2), 2.0, np.float32)
    w2 = np.full((4, 2), 5.0, np.float32)
    out1 = np.asarray(LinearMapper(jnp.asarray(w1)).apply_dataset(
        Dataset(xs, shard=False)).array)
    out2 = np.asarray(LinearMapper(jnp.asarray(w2)).apply_dataset(
        Dataset(xs, shard=False)).array)
    np.testing.assert_allclose(out1, xs @ w1)
    np.testing.assert_allclose(out2, xs @ w2)


def test_fused_chain_shares_program_across_instances():
    """Two FusedTransformer instances with identical stage identities
    (class+params) share one compiled chain (optimizer._FUSED_SHARED_CACHE)."""
    from keystone_tpu.ops.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.workflow import optimizer as O

    O._FUSED_SHARED_CACHE.clear()  # hermetic: identify OUR chain's entry
    f1 = O.FusedTransformer([SignedHellingerMapper(), NormalizeRows()])
    f2 = O.FusedTransformer([SignedHellingerMapper(), NormalizeRows()])
    xs = jnp.asarray(np.random.default_rng(1).normal(size=(4, 6)), jnp.float32)
    y1 = f1.apply_batch(xs)
    target = [
        k
        for k, v in O._FUSED_SHARED_CACHE.items()
        if callable(v) and k[0][0][1] is SignedHellingerMapper
    ]
    assert target, "fused chain did not take the shared path"
    fn = O._FUSED_SHARED_CACHE[target[0]]
    size = fn._cache_size()
    y2 = f2.apply_batch(xs)
    assert fn._cache_size() == size  # second instance reused the program
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)
    # and the executor path must ride the same shared program — the
    # per-instance outer jit would otherwise inline it with the stage
    # parameters embedded as constants (self_jitted bypass)
    from keystone_tpu.workflow.dataset import Dataset

    y3 = f2.apply_dataset(Dataset(np.asarray(xs), shard=False)).array
    assert fn._cache_size() == size
    np.testing.assert_allclose(np.asarray(y3), np.asarray(y1), rtol=1e-6)


def test_fused_chain_with_traced_stage_params():
    """A fused chain containing a traced_attrs stage (PCA) passes the
    stage's arrays as arguments and still matches the eager compose."""
    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.ops.stats import NormalizeRows
    from keystone_tpu.workflow import optimizer as O

    rng = np.random.default_rng(2)
    xs = jnp.asarray(rng.normal(size=(5, 8)), jnp.float32)
    comp = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
    pca = PCATransformer(comp, None)
    fused = O.FusedTransformer([pca, NormalizeRows()])
    got = np.asarray(fused.apply_batch(xs))
    want = np.asarray(NormalizeRows().apply_batch(pca.apply_batch(xs)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
