"""Pallas kernel tests (interpret mode on CPU — the TPU lowering is
exercised by bench/verify runs on hardware)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.fisher import _fisher_encode
from keystone_tpu.ops.fisher_pallas import fisher_encode_pallas


def _setup(n=3, t=200, d=16, k=8, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, t, d)).astype(np.float32)
    mask = (rng.random((n, t)) < 0.8).astype(np.float32)
    w = np.abs(rng.random(k)).astype(np.float32)
    w /= w.sum()
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = (0.5 + rng.random((k, d))).astype(np.float32)
    return map(jnp.asarray, (xs, mask, w, mu, var))


def test_pallas_fv_matches_xla_path():
    xs, mask, w, mu, var = _setup()
    ref = np.asarray(_fisher_encode(xs, mask, w, mu, var))
    got = np.asarray(fisher_encode_pallas(xs, mask, w, mu, var, interpret=True))
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_pallas_fv_nondivisible_t_padding():
    xs, mask, w, mu, var = _setup(t=137)  # one tile of 144 (pad 137→144)
    ref = np.asarray(_fisher_encode(xs, mask, w, mu, var))
    got = np.asarray(fisher_encode_pallas(xs, mask, w, mu, var, interpret=True))
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_tile_t_budget_covers_multiscale_in_one_tile():
    """The VMEM-budgeted cap (r4): the reference multi-scale shape
    (T=2520, K=256, d=64) fits ONE tile — no descriptor pad copy, no
    per-tile overhead (measured 620→524 µs/batch) — while a K large
    enough to blow the budget still tiles with a 128-multiple."""
    from keystone_tpu.ops import fisher_pallas as fp

    assert fp._tile_t(2520, 256, 64) == 2520  # exact, padless
    assert fp._tile_t(784, 256, 64) == 784  # headline unchanged
    big_k = fp._tile_t(8192, 2048, 128)
    assert big_k % 128 == 0 and big_k < 8192  # budget forces tiling
    # the 128-up-rounding must not breach the budget cap (the tile
    # search adds tiles until the rounded tile fits)
    rows = fp._VMEM_TILE_BUDGET // (4 * (3 * 2048 + 2 * 128))
    assert big_k <= max(rows // 8 * 8, 128)


def test_pallas_fv_multi_tile_accumulation(monkeypatch):
    """tiles>1 exercises the revolving-accumulator t-loop, the
    128-multiple _tile_t branch, and the (1, 1, tile_t) mask index map
    (none of which the single-tile tests touch).  The VMEM-budgeted cap
    would cover these small test shapes in one tile, so the budget is
    pinched to force tiling."""
    from keystone_tpu.ops import fisher_pallas as fp

    monkeypatch.setattr(fp, "_VMEM_TILE_BUDGET", 1 << 17)
    for t in (1500, 2049):
        tile = fp._tile_t(t, 8, 16)
        assert tile % 128 == 0
        assert -(-t // tile) >= 2
        xs, mask, w, mu, var = _setup(t=t)
        ref = np.asarray(_fisher_encode(xs, mask, w, mu, var))
        got = np.asarray(
            fisher_encode_pallas(xs, mask, w, mu, var, interpret=True)
        )
        np.testing.assert_allclose(got, ref, atol=2e-5)


def test_fisher_vector_auto_mode_selects_by_gamma_size(monkeypatch):
    """use_pallas=None: fused kernel only on TPU and only when the
    per-image responsibility tensor is large enough to be bandwidth-bound."""
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.ops import fisher as fisher_mod
    from keystone_tpu.ops import fisher_pallas as fp_mod

    calls = []

    def fake_pallas(xs, mask, w, mu, var, interpret=False, mxu="f32"):
        calls.append("pallas")
        return fisher_mod._fisher_encode(xs, mask, w, mu, var)

    monkeypatch.setattr(fp_mod, "pallas_supported", lambda x=None: True)
    monkeypatch.setattr(fp_mod, "fisher_encode_pallas", fake_pallas)

    xs, mask, w, mu, var = _setup(n=2, t=64, k=8)  # γ = 512 elems: einsum
    gmm = GaussianMixtureModel(w, mu, var)
    FisherVector = fisher_mod.FisherVector
    FisherVector(gmm).apply_batch(xs, mask=mask)
    assert calls == []

    big_t = FisherVector._PALLAS_GAMMA_THRESHOLD // 8  # γ = threshold: pallas
    xs2, mask2, *_ = _setup(n=2, t=big_t, k=8)
    FisherVector(gmm).apply_batch(xs2, mask=mask2)
    assert calls == ["pallas"]

    # explicit False always wins over a capable backend
    calls.clear()
    FisherVector(gmm, use_pallas=False).apply_batch(xs2, mask=mask2)
    assert calls == []


# ------------------------------------------------ fused forward megakernel


def _fused_setup(n=3, t=150, d_in=32, d=16, k=8, seed=1):
    rng = np.random.default_rng(seed)
    raw = rng.normal(size=(n, t, d_in)).astype(np.float32)
    mask = (rng.random((n, t)) < 0.8).astype(np.float32)
    comp = np.linalg.qr(rng.normal(size=(d_in, d)))[0].astype(np.float32)
    mean = (0.1 * rng.normal(size=(d_in,))).astype(np.float32)
    w = np.abs(rng.random(k)).astype(np.float32)
    w /= w.sum()
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = (0.5 + rng.random((k, d))).astype(np.float32)
    return raw, mask, comp, mean, w, mu, var


def _chain_reference(raw, mask, comp, mean, w, mu, var, normalize):
    """The unfused per-stage path the megakernel must match."""
    from keystone_tpu.ops.sift import _sift_normalize

    z = jnp.asarray(raw)
    if normalize:
        z = _sift_normalize(z)
    if mean is not None:
        z = z - mean
    z = z @ jnp.asarray(comp)
    return np.asarray(_fisher_encode(z, jnp.asarray(mask), w, mu, var))


@pytest.mark.parametrize("normalize", [True, False])
@pytest.mark.parametrize("with_mean", [True, False])
def test_fused_forward_matches_unfused_chain(normalize, with_mean):
    from keystone_tpu.ops.fisher_pallas import fused_forward_pallas

    raw, mask, comp, mean, w, mu, var = _fused_setup()
    mean_arg = mean if with_mean else None
    ref = _chain_reference(raw, mask, comp, mean_arg, w, mu, var, normalize)
    got = np.asarray(
        fused_forward_pallas(
            raw, mask, comp, mean_arg, w, mu, var,
            interpret=True, normalize=normalize,
        )
    )
    np.testing.assert_allclose(got, ref, atol=3e-5)


def test_fused_forward_multi_tile_accumulation(monkeypatch):
    """Multiple descriptor tiles exercise the revolving accumulators AND
    the in-kernel normalize/projection of tile PADDING rows (masked to
    zero contribution)."""
    from keystone_tpu.ops import fisher_pallas as fp

    monkeypatch.setattr(fp, "_VMEM_TILE_BUDGET", 1 << 17)
    raw, mask, comp, mean, w, mu, var = _fused_setup(t=1500)
    assert -(-1500 // fp._tile_t(1500, 8, 32 + 16)) >= 2
    ref = _chain_reference(raw, mask, comp, mean, w, mu, var, True)
    got = np.asarray(
        fp.fused_forward_pallas(
            raw, mask, comp, mean, w, mu, var, interpret=True, normalize=True
        )
    )
    np.testing.assert_allclose(got, ref, atol=3e-5)


def test_fused_forward_bf16_stream_tolerance():
    """Under the bf16 policies the descriptor stream crosses HBM at half
    width; the encode must stay within bf16-quantization tolerance of
    the f32 kernel (compute is f32 in VMEM either way)."""
    from keystone_tpu.ops.fisher_pallas import fused_forward_pallas

    raw, mask, comp, mean, w, mu, var = _fused_setup(seed=3)
    f32 = np.asarray(
        fused_forward_pallas(
            raw, mask, comp, mean, w, mu, var, interpret=True, normalize=True
        )
    )
    for mode in ("bf16", "bf16_apply"):
        half = np.asarray(
            fused_forward_pallas(
                raw, mask, comp, mean, w, mu, var,
                interpret=True, mxu=mode, normalize=True,
            )
        )
        # raw descriptors are O(1); bf16 has an 8-bit mantissa
        np.testing.assert_allclose(half, f32, atol=5e-2)
        assert np.abs(half - f32).max() > 0  # the cast actually happened


def test_fused_transformer_fallback_matches_chain():
    """Off-TPU the FusedPcaFisherVector transformer applies the
    IDENTICAL math as the PCATransformer → FisherVector chain."""
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.ops.fisher import FisherVector, FusedPcaFisherVector

    raw, mask, comp, mean, w, mu, var = _fused_setup(seed=5)
    pca = PCATransformer(jnp.asarray(comp), mean=jnp.asarray(mean))
    gmm = GaussianMixtureModel(
        jnp.asarray(w), jnp.asarray(mu), jnp.asarray(var)
    )
    z, m2 = pca.apply_batch(jnp.asarray(raw), mask=jnp.asarray(mask))
    want = np.asarray(FisherVector(gmm).apply_batch(z, mask=m2))
    fused = FusedPcaFisherVector(pca, gmm, use_pallas=False)
    got = np.asarray(
        fused.apply_batch(jnp.asarray(raw), mask=jnp.asarray(mask))
    )
    np.testing.assert_array_equal(got, want)  # same ops, same bits
    # the sift_normalize variant folds the extractor's tail in front
    from keystone_tpu.ops.sift import _sift_normalize

    fused_n = FusedPcaFisherVector(
        pca, gmm, sift_normalize=True, use_pallas=False
    )
    z2, _ = pca.apply_batch(_sift_normalize(jnp.asarray(raw)), mask=jnp.asarray(mask))
    want_n = np.asarray(FisherVector(gmm).apply_batch(z2, mask=m2))
    got_n = np.asarray(
        fused_n.apply_batch(jnp.asarray(raw), mask=jnp.asarray(mask))
    )
    np.testing.assert_array_equal(got_n, want_n)


def test_fused_transformer_routes_to_pallas(monkeypatch):
    """When the backend is Pallas-capable and γ crosses the threshold,
    the transformer dispatches the fused kernel (one program)."""
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.ops import fisher as fisher_mod
    from keystone_tpu.ops import fisher_pallas as fp_mod
    from keystone_tpu.ops.fisher import FusedPcaFisherVector

    raw, mask, comp, mean, w, mu, var = _fused_setup(
        t=fisher_mod.FisherVector._PALLAS_GAMMA_THRESHOLD // 8
    )
    calls = []

    def fake_fused(xs, mask_, comp_, mean_, w_, mu_, var_, **kw):
        calls.append(kw.get("normalize"))
        return jnp.zeros(
            (xs.shape[0], 2 * mu_.shape[0] * mu_.shape[1]), jnp.float32
        )

    monkeypatch.setattr(fp_mod, "pallas_supported", lambda x=None: True)
    monkeypatch.setattr(fp_mod, "fused_forward_pallas", fake_fused)
    pca = PCATransformer(jnp.asarray(comp), mean=jnp.asarray(mean))
    gmm = GaussianMixtureModel(
        jnp.asarray(w), jnp.asarray(mu), jnp.asarray(var)
    )
    FusedPcaFisherVector(pca, gmm, sift_normalize=True).apply_batch(
        jnp.asarray(raw), mask=jnp.asarray(mask)
    )
    assert calls == [True]


def test_pallas_fv_fusion_rule_rewrites_and_matches(monkeypatch):
    """End to end: on a Pallas-capable mesh the optimizer rule collapses
    each branch's PCA → FV pair into one fused node (absorbing the
    exclusive SIFT feed's normalize), and the rewritten pipeline scores
    identically (the CPU fallback is the bit-identical chain)."""
    import keystone_tpu.ops.fisher_pallas as fp
    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        Config,
        ImageNetSiftLcsFV,
    )
    from keystone_tpu.workflow.optimizer import PallasFvFusionRule

    cfg = Config(
        num_classes=4, synthetic_n=16, image_size=32, gmm_k=4, pca_dims=8,
        gmm_iters=2, num_epochs=1,
    )
    train = ImageNetLoader.synthetic(16, 4, size=(32, 32), seed=1)
    fitted = ImageNetSiftLcsFV.build(cfg, train.data, train.labels).fit()
    test = ImageNetLoader.synthetic(8, 4, size=(32, 32), seed=2)
    base = fitted(test.data).get().numpy()

    g = fitted.graph
    # inert off-TPU: the CPU graph is untouched (compile-count pins ride
    # the pre-rule path)
    assert PallasFvFusionRule().apply(g) is g
    with monkeypatch.context() as mp:
        mp.setattr(fp, "pallas_supported", lambda x=None: True)
        g2 = PallasFvFusionRule().apply(g)
        # the kill switch wins even on capable devices
        mp.setenv("KEYSTONE_FUSED_FV", "0")
        assert PallasFvFusionRule().apply(g) is g
    labels = {
        getattr(g2.operators.get(n), "transformer", None)
        and g2.operators[n].transformer.label
        for n in g2.topological_nodes()
    }
    assert "FusedFV[SiftNorm > PCA > FV]" in labels  # SIFT branch, absorbed
    assert "FusedFV[PCA > FV]" in labels  # LCS branch
    assert not any(lbl == "PCATransformer" for lbl in labels if lbl)
    # SIFT now emits raw descriptors for the fused consumer
    sift = next(
        g2.operators[n].transformer
        for n in g2.topological_nodes()
        if getattr(
            getattr(g2.operators.get(n), "transformer", None), "label", ""
        )
        == "SIFTExtractor"
    )
    assert sift.normalize is False
    fitted.graph = g2
    fused_out = fitted(test.data).get().numpy()
    np.testing.assert_array_equal(fused_out, base)


def test_fisher_vector_transformer_pallas_flag():
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.ops.fisher import FisherVector

    xs, mask, w, mu, var = _setup(n=2, t=64)
    gmm = GaussianMixtureModel(w, mu, var)
    a = np.asarray(FisherVector(gmm).apply_batch(xs, mask=mask))
    # interpret path via monkey wiring: call kernel directly (the flag
    # itself routes to the TPU lowering, which CPU can't run un-interpreted)
    b = np.asarray(fisher_encode_pallas(xs, mask, w, mu, var, interpret=True))
    np.testing.assert_allclose(a, b, atol=2e-5)
