"""Pallas kernel tests (interpret mode on CPU — the TPU lowering is
exercised by bench/verify runs on hardware)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.fisher import _fisher_encode
from keystone_tpu.ops.fisher_pallas import fisher_encode_pallas


def _setup(n=3, t=200, d=16, k=8, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, t, d)).astype(np.float32)
    mask = (rng.random((n, t)) < 0.8).astype(np.float32)
    w = np.abs(rng.random(k)).astype(np.float32)
    w /= w.sum()
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = (0.5 + rng.random((k, d))).astype(np.float32)
    return map(jnp.asarray, (xs, mask, w, mu, var))


def test_pallas_fv_matches_xla_path():
    xs, mask, w, mu, var = _setup()
    ref = np.asarray(_fisher_encode(xs, mask, w, mu, var))
    got = np.asarray(fisher_encode_pallas(xs, mask, w, mu, var, interpret=True))
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_pallas_fv_nondivisible_t_padding():
    xs, mask, w, mu, var = _setup(t=137)  # one tile of 144 (pad 137→144)
    ref = np.asarray(_fisher_encode(xs, mask, w, mu, var))
    got = np.asarray(fisher_encode_pallas(xs, mask, w, mu, var, interpret=True))
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_tile_t_budget_covers_multiscale_in_one_tile():
    """The VMEM-budgeted cap (r4): the reference multi-scale shape
    (T=2520, K=256, d=64) fits ONE tile — no descriptor pad copy, no
    per-tile overhead (measured 620→524 µs/batch) — while a K large
    enough to blow the budget still tiles with a 128-multiple."""
    from keystone_tpu.ops import fisher_pallas as fp

    assert fp._tile_t(2520, 256, 64) == 2520  # exact, padless
    assert fp._tile_t(784, 256, 64) == 784  # headline unchanged
    big_k = fp._tile_t(8192, 2048, 128)
    assert big_k % 128 == 0 and big_k < 8192  # budget forces tiling
    # the 128-up-rounding must not breach the budget cap (the tile
    # search adds tiles until the rounded tile fits)
    rows = fp._VMEM_TILE_BUDGET // (4 * (3 * 2048 + 2 * 128))
    assert big_k <= max(rows // 8 * 8, 128)


def test_pallas_fv_multi_tile_accumulation(monkeypatch):
    """tiles>1 exercises the revolving-accumulator t-loop, the
    128-multiple _tile_t branch, and the (1, 1, tile_t) mask index map
    (none of which the single-tile tests touch).  The VMEM-budgeted cap
    would cover these small test shapes in one tile, so the budget is
    pinched to force tiling."""
    from keystone_tpu.ops import fisher_pallas as fp

    monkeypatch.setattr(fp, "_VMEM_TILE_BUDGET", 1 << 17)
    for t in (1500, 2049):
        tile = fp._tile_t(t, 8, 16)
        assert tile % 128 == 0
        assert -(-t // tile) >= 2
        xs, mask, w, mu, var = _setup(t=t)
        ref = np.asarray(_fisher_encode(xs, mask, w, mu, var))
        got = np.asarray(
            fisher_encode_pallas(xs, mask, w, mu, var, interpret=True)
        )
        np.testing.assert_allclose(got, ref, atol=2e-5)


def test_fisher_vector_auto_mode_selects_by_gamma_size(monkeypatch):
    """use_pallas=None: fused kernel only on TPU and only when the
    per-image responsibility tensor is large enough to be bandwidth-bound."""
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.ops import fisher as fisher_mod
    from keystone_tpu.ops import fisher_pallas as fp_mod

    calls = []

    def fake_pallas(xs, mask, w, mu, var, interpret=False, mxu="f32"):
        calls.append("pallas")
        return fisher_mod._fisher_encode(xs, mask, w, mu, var)

    monkeypatch.setattr(fp_mod, "pallas_supported", lambda x=None: True)
    monkeypatch.setattr(fp_mod, "fisher_encode_pallas", fake_pallas)

    xs, mask, w, mu, var = _setup(n=2, t=64, k=8)  # γ = 512 elems: einsum
    gmm = GaussianMixtureModel(w, mu, var)
    FisherVector = fisher_mod.FisherVector
    FisherVector(gmm).apply_batch(xs, mask=mask)
    assert calls == []

    big_t = FisherVector._PALLAS_GAMMA_THRESHOLD // 8  # γ = threshold: pallas
    xs2, mask2, *_ = _setup(n=2, t=big_t, k=8)
    FisherVector(gmm).apply_batch(xs2, mask=mask2)
    assert calls == ["pallas"]

    # explicit False always wins over a capable backend
    calls.clear()
    FisherVector(gmm, use_pallas=False).apply_batch(xs2, mask=mask2)
    assert calls == []


def test_fisher_vector_transformer_pallas_flag():
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.ops.fisher import FisherVector

    xs, mask, w, mu, var = _setup(n=2, t=64)
    gmm = GaussianMixtureModel(w, mu, var)
    a = np.asarray(FisherVector(gmm).apply_batch(xs, mask=mask))
    # interpret path via monkey wiring: call kernel directly (the flag
    # itself routes to the TPU lowering, which CPU can't run un-interpreted)
    b = np.asarray(fisher_encode_pallas(xs, mask, w, mu, var, interpret=True))
    np.testing.assert_allclose(a, b, atol=2e-5)
