"""Hardened durable-I/O layer (keystone_tpu/utils/durable.py):
checksummed atomic writes, retry/backoff, rolling last-good fallback."""

import os

import numpy as np
import pytest

from keystone_tpu.utils import durable
from keystone_tpu.utils.durable import CorruptStateError


def _flip_middle_byte(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


def test_save_load_round_trip_with_checksum(tmp_path):
    path = str(tmp_path / "state.npz")
    arrays = {"w": np.arange(12, dtype=np.float32).reshape(3, 4), "it": np.int32(7)}
    durable.save_npz(path, arrays)
    assert os.path.exists(durable.checksum_path(path))
    loaded = durable.load_npz(path)
    assert loaded is not None
    z, used = loaded
    assert used == path
    np.testing.assert_array_equal(z["w"], arrays["w"])
    assert int(z["it"]) == 7


def test_checksum_verification_catches_corruption(tmp_path):
    path = str(tmp_path / "state.npz")
    durable.save_npz(path, {"w": np.ones(64, np.float32)})
    _flip_middle_byte(path)
    with pytest.raises(CorruptStateError, match="checksum mismatch"):
        durable.verify_checksum(path)


def test_missing_sidecar_is_legacy_pass(tmp_path):
    path = str(tmp_path / "old.npz")
    with open(path, "wb") as f:
        np.savez(f, w=np.zeros(3))
    assert durable.verify_checksum(path) is False  # unverified, not fatal
    with pytest.raises(CorruptStateError, match="missing checksum"):
        durable.verify_checksum(path, required=True)
    loaded = durable.load_npz(path)  # legacy files still load
    assert loaded is not None


def test_corrupt_newest_falls_back_to_last_good(tmp_path, caplog):
    path = str(tmp_path / "ckpt.npz")
    durable.save_npz(path, {"epoch": np.asarray(0)}, keep=2)
    durable.save_npz(path, {"epoch": np.asarray(1)}, keep=2)
    assert os.path.exists(path + ".1")  # previous epoch rotated aside
    _flip_middle_byte(path)
    z, used = durable.load_npz(path)
    assert used == path + ".1"
    assert int(z["epoch"]) == 0  # degraded to the last good epoch


def test_all_candidates_corrupt_returns_none(tmp_path):
    path = str(tmp_path / "ckpt.npz")
    durable.save_npz(path, {"epoch": np.asarray(0)}, keep=2)
    durable.save_npz(path, {"epoch": np.asarray(1)}, keep=2)
    _flip_middle_byte(path)
    _flip_middle_byte(path + ".1")
    assert durable.load_npz(path) is None


def test_validator_rejection_scans_deeper(tmp_path):
    path = str(tmp_path / "ckpt.npz")
    durable.save_npz(path, {"tag": np.asarray("good")}, keep=2)
    durable.save_npz(path, {"tag": np.asarray("stale")}, keep=2)
    z, used = durable.load_npz(
        path, validate=lambda z: str(z["tag"]) == "good"
    )
    assert used == path + ".1"


def test_retention_keeps_exactly_n(tmp_path):
    path = str(tmp_path / "ckpt.npz")
    for e in range(6):
        durable.save_npz(path, {"epoch": np.asarray(e)}, keep=3)
    assert sorted(
        f for f in os.listdir(tmp_path) if not f.endswith(durable.CHECKSUM_SUFFIX)
    ) == ["ckpt.npz", "ckpt.npz.1", "ckpt.npz.2"]
    assert int(durable.load_npz(path)[0]["epoch"]) == 5
    assert int(durable.load_npz(path + ".2")[0]["epoch"]) == 3


def test_atomic_write_never_publishes_partial(tmp_path):
    path = str(tmp_path / "state.npz")
    durable.save_npz(path, {"w": np.zeros(8)})
    before = durable.compute_checksum(path)

    def exploding(tmp):
        with open(tmp, "wb") as f:
            f.write(b"partial garbage")
        raise RuntimeError("crash mid-write")

    with pytest.raises(RuntimeError, match="crash mid-write"):
        durable.atomic_write(path, exploding)
    # the published file is byte-identical to before the failed save
    assert durable.compute_checksum(path) == before
    durable.verify_checksum(path)


def test_with_retries_backoff_and_budget():
    calls = {"n": 0}
    naps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert (
        durable.with_retries(flaky, retries=3, sleep=naps.append) == "ok"
    )
    assert calls["n"] == 3
    assert len(naps) == 2
    assert naps[1] > naps[0] * 1.2  # backoff actually grows

    calls["n"] = -10  # needs 13 calls; budget allows 3
    with pytest.raises(OSError):
        durable.with_retries(flaky, retries=2, sleep=lambda _: None)


def test_with_retries_never_retries_corruption():
    calls = {"n": 0}

    def corrupt():
        calls["n"] += 1
        raise CorruptStateError("deterministic damage")

    with pytest.raises(CorruptStateError):
        durable.with_retries(corrupt, retries=5, sleep=lambda _: None)
    assert calls["n"] == 1  # no futile retries


def test_backoff_delays_deterministic_with_seed():
    a = list(durable.backoff_delays(5, seed=3))
    b = list(durable.backoff_delays(5, seed=3))
    c = list(durable.backoff_delays(5, seed=4))
    assert a == b
    assert a != c
    assert all(x <= 2.0 * 1.5 for x in a)  # max_delay * (1 + jitter)


def test_quarantine_moves_file_and_sidecar(tmp_path):
    path = str(tmp_path / "bad.npz")
    durable.save_npz(path, {"w": np.zeros(4)})
    dest = durable.quarantine(path)
    assert dest == path + ".corrupt"
    assert not os.path.exists(path)
    assert os.path.exists(dest)
    assert os.path.exists(durable.checksum_path(dest))
