"""Regression locks for the ADVICE r5 fixes that ride with the
fault-injection PR: native-chain duplicate orders, GMM 1-D row masks,
heterogeneous host doc lists."""

import numpy as np
import pytest


def test_chain_config_rejects_duplicate_ngram_orders():
    """ADVICE r5 (medium): a chain like NGramsFeaturizer((1, 1)) counts
    every unigram twice on the Python path, but the native orders_mask
    collapses duplicates — silently halving tf values.  chain_config must
    return None so the chain falls back to the Python path."""
    from keystone_tpu.ops.nlp import NGramsFeaturizer, TermFrequency, Tokenizer
    from keystone_tpu.ops.nlp_native import chain_config

    supported = [Tokenizer(), NGramsFeaturizer((1, 2)), TermFrequency()]
    assert chain_config(supported) is not None  # sanity: pattern matches

    dup = [Tokenizer(), NGramsFeaturizer((1, 1)), TermFrequency()]
    assert chain_config(dup) is None

    dup_mixed = [Tokenizer(), NGramsFeaturizer((2, 1, 2)), TermFrequency()]
    assert chain_config(dup_mixed) is None


def test_duplicate_order_python_path_counts_duplicates():
    """The behavior the native path cannot reproduce (and so must not
    claim): duplicate orders double every count."""
    from keystone_tpu.ops.nlp import NGramsFeaturizer, TermFrequency

    tokens = ["a", "b", "a"]
    single = TermFrequency().apply_one(NGramsFeaturizer((1,)).apply_one(tokens))
    doubled = TermFrequency().apply_one(NGramsFeaturizer((1, 1)).apply_one(tokens))
    assert doubled == {k: 2 * v for k, v in single.items()}


def test_gmm_fit_dataset_handles_1d_row_mask():
    """ADVICE r5 (low): a 1-D row mask reached _gmm_fit with n=None and
    crashed (DynamicJaxprTracer + NoneType).  It must fit, deriving the
    true count from the mask and zeroing masked rows."""
    import jax.numpy as jnp

    from keystone_tpu.models.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.workflow.dataset import Dataset

    rng = np.random.default_rng(0)
    n_valid, n_rows, d = 48, 64, 5
    x = np.zeros((n_rows, d), np.float32)
    x[:n_valid] = rng.normal(size=(n_valid, d)).astype(np.float32)
    # garbage beyond the valid range: the mask must keep it out
    x[n_valid:] = 1e6

    est = GaussianMixtureModelEstimator(k=3, max_iterations=8, seed=0)
    masked = Dataset(
        x, n=n_rows, mask=jnp.asarray(np.arange(n_rows) < n_valid)
    )
    gm = est.fit_dataset(masked)  # crashed before the fix

    assert np.isfinite(np.asarray(gm.means)).all()
    assert np.isfinite(np.asarray(gm.weights)).all()
    np.testing.assert_allclose(np.asarray(gm.weights).sum(), 1.0, atol=1e-4)
    # the 1e6 garbage rows must not have pulled any component's mean
    assert np.abs(np.asarray(gm.means)).max() < 100.0

    # and the mask-derived count matches the n-based fit (identical
    # math: same rows zeroed, same true count)
    x_clean = np.zeros_like(x)
    x_clean[:n_valid] = x[:n_valid]
    gm_ref = est.fit_dataset(Dataset(x_clean, n=n_valid))
    np.testing.assert_allclose(
        np.sort(np.asarray(gm.means), axis=0),
        np.sort(np.asarray(gm_ref.means), axis=0),
        rtol=1e-4,
        atol=1e-4,
    )


def test_base_docs_rejects_heterogeneous_host_lists():
    """ADVICE r5 (low): _base_docs gated the native path on docs[0]
    alone; a stray non-str doc later in the list died in native packing
    with AttributeError on .encode.  It must return None (Python-path
    fallback) like the stream variants."""
    from keystone_tpu.ops.nlp import _base_docs
    from keystone_tpu.workflow.dataset import Dataset

    clean = Dataset(["one doc", "two docs"])
    assert _base_docs(clean) == ["one doc", "two docs"]

    hetero = Dataset(["one doc", {"not": "a str"}, "three"])
    assert _base_docs(hetero) is None

    first_bad = Dataset([None, "str later"])
    assert _base_docs(first_bad) is None


def test_heterogeneous_docs_fall_back_to_python_path():
    """End-to-end: a featurize over a heterogeneous doc list must not
    crash even when the native library is available — the dataset-level
    gate routes it to the Python path, which raises the ordinary
    per-item type error only if the items are truly unusable."""
    from keystone_tpu.ops import nlp_native
    from keystone_tpu.ops.nlp import CommonSparseFeatures
    from keystone_tpu.workflow.dataset import Dataset

    if not nlp_native.available():
        pytest.skip("native text library not built")
    # term-dict items (the Python path's contract) with full provenance
    # absent: fit_dataset must take the Python branch without touching
    # native packing
    docs = Dataset([{"a": 1.0}, {"b": 2.0}])
    model = CommonSparseFeatures(4).fit_dataset(docs)
    assert set(model.vocab) == {"a", "b"}


def test_multihost_init_deterministic_error_fails_fast(monkeypatch):
    """The init retry loop used to retry on bare RuntimeError, so a
    deterministic config error (e.g. mismatched num_processes) burned
    the full backoff budget before surfacing.  It must fail on the
    FIRST attempt; connection-shaped RuntimeErrors keep their retries."""
    import jax

    from keystone_tpu.parallel import multihost

    calls = {"n": 0}

    def die(**kwargs):
        calls["n"] += 1
        raise RuntimeError(
            "Number of processes 4 does not match num_processes 2"
        )

    monkeypatch.setattr(jax.distributed, "initialize", die)
    monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
    with pytest.raises(RuntimeError, match="does not match num_processes"):
        multihost.initialize(
            coordinator_address="localhost:1",
            num_processes=2,
            process_id=0,
            retries=3,
        )
    assert calls["n"] == 1  # fail-fast: no backoff budget burned


def test_multihost_init_connection_error_still_retried(monkeypatch):
    """The other direction: a coordinator race (connection-shaped
    RuntimeError) must still consume the retry budget."""
    import jax

    from keystone_tpu.parallel import multihost
    from keystone_tpu.utils import durable

    calls = {"n": 0}

    def flaky(**kwargs):
        calls["n"] += 1
        raise RuntimeError("failed to connect to coordinator: UNAVAILABLE")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
    # no real sleeping inside the regression suite: zero-length backoff
    monkeypatch.setattr(
        durable, "backoff_delays", lambda *a, **k: iter([0.0] * 8)
    )
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        multihost.initialize(
            coordinator_address="localhost:1",
            num_processes=2,
            process_id=0,
            retries=2,
        )
    assert calls["n"] == 3  # initial attempt + both retries
