"""Tier-1 tests for the deadline/watchdog/breaker layer
(keystone_tpu/utils/guard.py) and its wiring: executor per-stage
deadlines, graceful degradation (optional / with_fallback), stream fetch
timeouts, latency fault actions (delay / hang), and the multihost init
retry filter.  The acceptance scenario — a chaos plan injecting ``hang``
at ``executor.stage`` and ``delay`` at ``stream.batch`` completing under
a configured deadline with ``deadline_exceeded`` / ``breaker.transition``
/ ``degraded`` ledger events — lives at the bottom.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.obs import ledger, metrics
from keystone_tpu.utils import guard
from keystone_tpu.workflow import Dataset, GraphExecutor, Pipeline, Transformer


@pytest.fixture(autouse=True)
def _fresh_guard_state():
    guard.reset_breakers()
    yield
    guard.reset_breakers()


def _ledger_events(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


# ------------------------------------------------------------- Deadline


def test_deadline_remaining_and_expiry():
    dl = guard.Deadline.after(10.0)
    assert 9.0 < dl.remaining() <= 10.0
    assert not dl.expired()
    assert guard.Deadline.after(-1.0).expired()


def test_deadline_child_never_outlives_parent():
    parent = guard.Deadline.after(0.5)
    child = parent.child(100.0)
    assert child.remaining() <= parent.remaining() + 1e-6
    tight = parent.child(0.1)
    assert tight.remaining() <= 0.1 + 1e-6
    inherit = parent.child(None)
    assert abs(inherit.at - parent.at) < 1e-9


def test_as_deadline_coercions():
    assert guard.as_deadline(None) is None
    dl = guard.Deadline.after(5)
    assert guard.as_deadline(dl) is dl
    assert isinstance(guard.as_deadline(2.5), guard.Deadline)


# ----------------------------------------------------- run_with_deadline


def test_run_with_deadline_none_is_same_thread_passthrough():
    """The inert guarantee: deadline=None runs fn on the CALLING thread
    (no watchdog thread, no queue — one None check)."""
    seen = []
    out = guard.run_with_deadline(
        lambda: seen.append(threading.current_thread()) or "v", None
    )
    assert out == "v"
    assert seen == [threading.current_thread()]


def test_run_with_deadline_returns_result_and_propagates_errors():
    assert guard.run_with_deadline(lambda: 41 + 1, guard.Deadline.after(5)) == 42
    with pytest.raises(ValueError, match="boom"):
        guard.run_with_deadline(
            lambda: (_ for _ in ()).throw(ValueError("boom")),
            guard.Deadline.after(5),
        )


def test_watchdog_fires_on_sleeping_fn():
    """A fn that sleeps past the budget raises DeadlineExceeded — an
    OSError, so every transient-I/O retry path absorbs overruns — and
    the abandoned worker is unparked via the cooperative cancel flag."""
    released = threading.Event()

    def sleepy():
        guard.interruptible_sleep(30.0)
        released.set()

    t0 = time.perf_counter()
    with pytest.raises(guard.DeadlineExceeded) as ei:
        guard.run_with_deadline(sleepy, guard.Deadline.after(0.2), site="t")
    took = time.perf_counter() - t0
    assert took < 5.0  # the watchdog, not the sleep, set the pace
    assert isinstance(ei.value, OSError)
    assert released.wait(timeout=5.0)  # cancel flag unparked the worker
    assert metrics.REGISTRY.counter_value("guard.deadline_exceeded", site="t") >= 1


def test_expired_deadline_fails_fast_without_running():
    ran = []
    with pytest.raises(guard.DeadlineExceeded):
        guard.run_with_deadline(
            lambda: ran.append(1), guard.Deadline.after(-1.0), site="t2"
        )
    assert not ran


def test_deadline_exceeded_event_lands_in_ledger(tmp_path):
    led = ledger.start_run(str(tmp_path))
    with pytest.raises(guard.DeadlineExceeded):
        guard.run_with_deadline(
            lambda: time.sleep(5), guard.Deadline.after(0.1), site="ev"
        )
    ledger.stop_run()
    evs = _ledger_events(led.path)
    hits = [e for e in evs if e.get("name") == "deadline_exceeded"]
    assert hits and hits[0]["attrs"]["site"] == "ev"


# ------------------------------------------------------- CircuitBreaker


def test_breaker_open_halfopen_close_cycle():
    clk = [0.0]
    b = guard.CircuitBreaker("cyc", threshold=2, reset_timeout=10.0, clock=lambda: clk[0])
    assert b.allow() and b.state() == guard.CLOSED
    b.record_failure()
    assert b.state() == guard.CLOSED  # one failure < threshold
    b.record_failure()
    assert b.state() == guard.OPEN
    assert not b.allow()
    clk[0] = 10.0  # reset timeout elapses -> half-open, ONE probe
    assert b.allow()
    assert b.state() == guard.HALF_OPEN
    assert not b.allow()  # second caller is not the probe
    b.record_success()
    assert b.state() == guard.CLOSED and b.allow()


def test_breaker_halfopen_failure_reopens():
    clk = [0.0]
    b = guard.CircuitBreaker("re", threshold=1, reset_timeout=5.0, clock=lambda: clk[0])
    b.record_failure()
    assert b.state() == guard.OPEN
    clk[0] = 5.0
    assert b.allow()  # the probe
    b.record_failure()  # probe failed
    assert b.state() == guard.OPEN
    assert not b.allow()  # clock has not advanced again
    clk[0] = 9.0  # reset clock restarted at reopen (t=5), not the first open
    assert not b.allow()
    clk[0] = 10.0
    assert b.allow()


def test_breaker_unrecorded_probe_does_not_wedge_halfopen():
    """A half-open probe whose outcome is never recorded (its caller
    died, or its failure was deliberately not charged) must not wedge
    the breaker: after another reset_timeout a fresh probe is admitted."""
    clk = [0.0]
    b = guard.CircuitBreaker("wedge", threshold=1, reset_timeout=5.0, clock=lambda: clk[0])
    b.record_failure()  # open
    clk[0] = 5.0
    assert b.allow()  # probe admitted … and its outcome never recorded
    assert not b.allow()
    clk[0] = 9.9
    assert not b.allow()  # stale-probe window not yet elapsed
    clk[0] = 10.0
    assert b.allow()  # presumed lost -> fresh probe
    b.record_success()
    assert b.state() == guard.CLOSED


def test_breaker_success_resets_consecutive_count():
    b = guard.CircuitBreaker("cnt", threshold=2, reset_timeout=5.0)
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state() == guard.CLOSED  # failures were not consecutive


def test_breaker_transitions_mirror_into_metrics_and_ledger(tmp_path):
    led = ledger.start_run(str(tmp_path))
    b = guard.CircuitBreaker("obs-key", threshold=1, reset_timeout=60.0)
    b.record_failure()
    ledger.stop_run()
    assert metrics.REGISTRY.gauge_value("breaker.state", key="obs-key") == 2.0
    assert metrics.REGISTRY.counter_value("breaker.opens", key="obs-key") == 1.0
    evs = _ledger_events(led.path)
    tr = [e for e in evs if e.get("name") == "breaker.transition"]
    assert tr and tr[-1]["attrs"] == {
        "key": "obs-key",
        "from_state": "closed",
        "to_state": "open",
    }


def test_breaker_registry_is_per_key_and_stable():
    a = guard.breaker("a", threshold=5)
    assert guard.breaker("a", threshold=9) is a  # settings fixed at creation
    assert a.threshold == 5
    assert guard.breaker("b") is not a
    guard.reset_breakers()
    assert guard.breaker("a") is not a


# ------------------------------------------- executor wiring: degradation


class _AddOne(Transformer):
    def params(self):
        return ()

    def apply_dataset(self, ds):
        return ds.with_array(ds.array + 1.0)


class _Broken(Transformer):
    """Deterministically-failing stage; counts apply attempts."""

    def __init__(self):
        self.calls = 0

    def params(self):
        return None

    def apply_dataset(self, ds):
        self.calls += 1
        raise OSError("broken stage")


class _Const(Transformer):
    def params(self):
        return None

    def apply_dataset(self, ds):
        import jax.numpy as jnp

        return ds.with_array(jnp.full_like(ds.array, 9.0))


def test_optional_node_degrades_to_identity(tmp_path):
    led = ledger.start_run(str(tmp_path))
    t = _Broken()
    t.optional = True
    lazy = Pipeline.of(t)(Dataset(np.full((4, 2), 7.0, np.float32)))
    out = GraphExecutor(lazy.graph, node_retries=1).execute(lazy.graph.sinks[0])
    ledger.stop_run()
    np.testing.assert_allclose(np.asarray(out.dataset.array), 7.0)
    assert t.calls == 2  # the retry budget really was spent first
    evs = _ledger_events(led.path)
    deg = [e for e in evs if e.get("name") == "degraded"]
    assert deg and deg[0]["attrs"]["substitute"] == "Identity"
    assert deg[0]["attrs"]["reason"] == "budget_exhausted"


def test_with_fallback_substitutes_and_original_untouched():
    t = _Broken()
    fb = t.with_fallback(_Const())
    assert t.fallback is None  # with_fallback returns a copy
    lazy = Pipeline.of(fb)(Dataset(np.ones((4, 2), np.float32)))
    out = GraphExecutor(lazy.graph, node_retries=0).execute(lazy.graph.sinks[0])
    np.testing.assert_allclose(np.asarray(out.dataset.array), 9.0)
    assert metrics.REGISTRY.counter_value("executor.degraded", node="_Broken") >= 1


def test_mandatory_node_failure_still_propagates():
    t = _Broken()
    lazy = Pipeline.of(t)(Dataset(np.ones((4, 2), np.float32)))
    with pytest.raises(OSError, match="broken stage"):
        GraphExecutor(lazy.graph, node_retries=1).execute(lazy.graph.sinks[0])
    assert t.calls == 2


def test_degradation_declarations_block_stage_fusion():
    """An optional/fallback stage fused into a chain would lose its
    per-stage degradation contract — the fusion rule must skip it."""
    from keystone_tpu.workflow.optimizer import _fusable
    from keystone_tpu.workflow.graph import TransformerOperator

    assert _fusable(TransformerOperator(_AddOne()))
    opt = _AddOne()
    opt.optional = True
    assert not _fusable(TransformerOperator(opt))
    assert not _fusable(TransformerOperator(_AddOne().with_fallback(_Const())))


def test_degradation_declarations_split_cse_signature():
    plain = _AddOne()
    optional = _AddOne()
    optional.optional = True
    assert plain.signature() != optional.signature()
    assert plain.signature() != _AddOne().with_fallback(_Const()).signature()


# --------------------------------------------- executor wiring: breakers


def test_breaker_open_short_circuits_next_run(monkeypatch):
    monkeypatch.setenv(guard.ENV_BREAKER_THRESHOLD, "1")
    t = _Broken()
    lazy = Pipeline.of(t)(Dataset(np.ones((4, 2), np.float32)))
    with pytest.raises(OSError):
        GraphExecutor(lazy.graph, node_retries=0).execute(lazy.graph.sinks[0])
    assert t.calls == 1
    # breaker is now open for this node label: the next run is REFUSED
    # without calling the transformer again
    with pytest.raises(guard.CircuitOpenError):
        GraphExecutor(lazy.graph, node_retries=0).execute(lazy.graph.sinks[0])
    assert t.calls == 1


def test_breaker_open_degrades_optional_node(monkeypatch):
    monkeypatch.setenv(guard.ENV_BREAKER_THRESHOLD, "1")
    t = _Broken()
    t.optional = True
    lazy = Pipeline.of(t)(Dataset(np.full((4, 2), 3.0, np.float32)))
    out1 = GraphExecutor(lazy.graph, node_retries=0).execute(lazy.graph.sinks[0])
    np.testing.assert_allclose(np.asarray(out1.dataset.array), 3.0)
    assert t.calls == 1
    out2 = GraphExecutor(lazy.graph, node_retries=0).execute(lazy.graph.sinks[0])
    np.testing.assert_allclose(np.asarray(out2.dataset.array), 3.0)
    assert t.calls == 1  # second run never attempted the broken stage
    assert metrics.REGISTRY.counter_total("breaker.opens") >= 1


def test_breaker_keys_are_per_node_not_per_label(monkeypatch):
    """One flaky node must not open the breaker of a healthy twin with
    the same label: signatureless same-class nodes get per-node keys."""
    monkeypatch.setenv(guard.ENV_BREAKER_THRESHOLD, "1")
    bad, good = _Broken(), _Broken()
    lazy_bad = Pipeline.of(bad)(Dataset(np.ones((4, 2), np.float32)))
    lazy_good = Pipeline.of(good)(Dataset(np.ones((4, 2), np.float32)))
    with pytest.raises(OSError):
        GraphExecutor(lazy_bad.graph, node_retries=0).execute(
            lazy_bad.graph.sinks[0]
        )
    # the OTHER node (same class, same label) is still attempted — its
    # own breaker is untouched.  It fails on its own merits, but with
    # OSError (a real attempt), not CircuitOpenError (a refusal).
    with pytest.raises(OSError):
        GraphExecutor(lazy_good.graph, node_retries=0).execute(
            lazy_good.graph.sinks[0]
        )
    assert good.calls == 1


def test_breaker_opening_mid_retry_loop_stops_remaining_retries(monkeypatch):
    """Once a failure opens the node's breaker, the remaining retry
    budget must not be burned against it — that repeated cost is what
    the breaker exists to stop paying."""
    monkeypatch.setenv(guard.ENV_BREAKER_THRESHOLD, "1")
    t = _Broken()
    lazy = Pipeline.of(t)(Dataset(np.ones((4, 2), np.float32)))
    with pytest.raises(OSError, match="broken stage"):
        GraphExecutor(lazy.graph, node_retries=5).execute(lazy.graph.sinks[0])
    assert t.calls == 1  # threshold=1: first failure opened it, no retries


def test_breakers_disabled_by_default_no_registry_entries():
    guard.reset_breakers()
    t = _AddOne()
    lazy = Pipeline.of(t)(Dataset(np.ones((4, 2), np.float32)))
    GraphExecutor(lazy.graph).execute(lazy.graph.sinks[0])
    assert not guard._BREAKERS  # no KEYSTONE_BREAKER_THRESHOLD -> no lookups


# ------------------------------------------------- fit/apply deadline API


def test_fit_deadline_bitmatches_undeadlined_fit(monkeypatch):
    """A generous budget changes nothing: same bits as a plain fit, and
    the per-stage env knob alone leaves the solver output untouched —
    the deadline layer is host-side only (no traced-program effect)."""
    from keystone_tpu.models import BlockLeastSquaresEstimator

    rng = np.random.default_rng(7)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    y = rng.normal(size=(64, 2)).astype(np.float32)
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=1e-3)
    ref = est.with_data(Dataset(x), Dataset(y)).fit()(Dataset(x)).get().numpy()

    got = (
        est.with_data(Dataset(x), Dataset(y))
        .fit(deadline=300.0)(Dataset(x))
        .get(deadline=300.0)
        .numpy()
    )
    np.testing.assert_array_equal(ref, got)

    monkeypatch.setenv(guard.ENV_STAGE_DEADLINE, "300")
    env_got = est.with_data(Dataset(x), Dataset(y)).fit()(Dataset(x)).get().numpy()
    np.testing.assert_array_equal(ref, env_got)


def test_solver_program_hlo_identical_under_stage_deadline(monkeypatch):
    """The acceptance pin: with or without a configured deadline the
    traced solver program is byte-identical — the watchdog lives
    entirely outside jit."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.models.block_ls import _bcd_epoch_body

    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 16, 8)), jnp.float32)
    y = jnp.ones((16, 2), jnp.float32)
    w = jnp.zeros((2, 8, 2), jnp.float32)
    p = jnp.zeros((16, 2), jnp.float32)

    def step(xb, y, w, p):
        return _bcd_epoch_body(xb, y, jnp.float32(16.0), 1e-3, (w, p))

    monkeypatch.delenv(guard.ENV_STAGE_DEADLINE, raising=False)
    plain = jax.jit(step).lower(x, y, w, p).as_text()
    monkeypatch.setenv(guard.ENV_STAGE_DEADLINE, "0.001")
    monkeypatch.setenv(guard.ENV_BREAKER_THRESHOLD, "1")
    guarded = jax.jit(step).lower(x, y, w, p).as_text()
    assert plain == guarded


def test_blown_pipeline_budget_fails_in_bounded_time():
    """An expired executor-wide budget must fail fast even with a retry
    budget configured: further attempts are born expired, so the loop
    must not burn node_retries × backoff sleeps per remaining node."""
    t = _AddOne()
    lazy = Pipeline.of(t)(Dataset(np.ones((4, 2), np.float32)))
    ex = GraphExecutor(lazy.graph, node_retries=3, deadline=guard.Deadline.after(-1.0))
    before = metrics.REGISTRY.counter_value("executor.stage_retries")
    t0 = time.perf_counter()
    with pytest.raises(guard.DeadlineExceeded):
        ex.execute(lazy.graph.sinks[0])
    assert time.perf_counter() - t0 < 1.0  # no backoff sleeps
    assert metrics.REGISTRY.counter_value("executor.stage_retries") == before


def test_stage_span_parenting_survives_watchdog_thread(monkeypatch, tmp_path):
    """With a deadline configured the stage body runs on the watchdog
    worker thread; ledger events it emits must still nest under the
    executor.stage span (the span stack is thread-local and is carried
    into the worker by run_with_deadline)."""
    monkeypatch.setenv(guard.ENV_STAGE_DEADLINE, "60")

    class Emitting(Transformer):
        def params(self):
            return None

        def apply_dataset(self, ds):
            ledger.event("inner.probe")
            return ds

    led = ledger.start_run(str(tmp_path))
    lazy = Pipeline.of(Emitting())(Dataset(np.ones((4, 2), np.float32)))
    GraphExecutor(lazy.graph, node_retries=0).execute(lazy.graph.sinks[0])
    ledger.stop_run()
    evs = _ledger_events(led.path)
    probe = [e for e in evs if e.get("name") == "inner.probe"]
    stage_spans = {
        e["span"]: (e.get("attrs") or {}).get("node")
        for e in evs
        if e.get("kind") == "span_start" and e.get("name") == "executor.stage"
    }
    assert probe and probe[0].get("parent") in stage_spans
    assert stage_spans[probe[0]["parent"]] == "Emitting"


# ------------------------------------------------ stream fetch timeouts


class _HangSource:
    """Batch-resumable source whose ``bad`` batch hangs (cancel-aware)."""

    def __init__(self, n, bad, hang_for=30.0):
        self.n, self.bad, self.hang_for = n, bad, hang_for
        self.hangs = 0

    def __call__(self):
        outer = self

        class It:
            def __init__(self):
                self.i = 0

            def __iter__(self):
                return self

            def __next__(self):
                if self.i >= outer.n:
                    raise StopIteration
                i = self.i
                self.i += 1
                if i == outer.bad:
                    outer.hangs += 1
                    guard.interruptible_sleep(outer.hang_for)
                return np.full((4, 2), i, np.float32)

        return It()


def test_resilient_timeout_retries_then_drops_hung_batch():
    from keystone_tpu.loaders.stream import resilient

    src = _HangSource(5, bad=2)
    out = list(
        resilient(
            src, retries=1, max_bad_batches=1, base_delay=0.0, timeout=0.2
        )()
    )
    assert [int(b[0, 0]) for b in out] == [0, 1, 3, 4]
    assert src.hangs == 2  # first attempt + one retry, both timed out


def test_resilient_timeout_zero_quota_propagates():
    from keystone_tpu.loaders.stream import resilient

    with pytest.raises(guard.DeadlineExceeded):
        list(
            resilient(
                _HangSource(5, bad=1), retries=1, base_delay=0.0, timeout=0.2
            )()
        )


def test_stream_dataset_timeout_plumbs_through():
    from keystone_tpu.workflow.dataset import StreamDataset

    src = _HangSource(4, bad=1)
    ds = StreamDataset(src, n=16, retries=1, max_bad_batches=1, timeout=0.2)
    rows = sum(np.asarray(b).shape[0] for b, _m in ds.device_batches())
    assert rows == 12  # one 4-row batch dropped against the quota


def test_resilient_timeout_generator_source_transient_hang():
    """A timed-out fetch abandons a GENERATOR iterator mid-next(); the
    replay must use a fresh iterator — pulling more from the occupied
    one would raise 'generator already executing' against the next
    healthy batch."""
    from keystone_tpu.loaders.stream import resilient

    hangs = {"n": 0}

    def source():
        def it():
            for i in range(5):
                if i == 2 and hangs["n"] < 1:
                    hangs["n"] += 1
                    guard.interruptible_sleep(30.0)
                yield np.full((4, 2), i, np.float32)

        return it()

    out = list(resilient(source, retries=2, base_delay=0.0, timeout=0.2)())
    assert [int(b[0, 0]) for b in out] == [0, 1, 2, 3, 4]
    assert hangs["n"] == 1


def test_resilient_timeout_permanent_hang_fails_bounded():
    """A NON-cooperative batch (plain time.sleep — the worker never
    vacates the iterator) that hangs on every replay cannot be skipped
    on a generator source; the stall bound converts what would be an
    infinite timeout-per-cycle spin into a loud, bounded failure."""
    from keystone_tpu.loaders.stream import resilient

    def source():
        def it():
            for i in range(5):
                if i == 2:
                    time.sleep(30.0)
                yield np.full((4, 2), i, np.float32)

        return it()

    t0 = time.perf_counter()
    with pytest.raises(guard.DeadlineExceeded):
        list(
            resilient(
                source,
                retries=1,
                max_bad_batches=1,
                base_delay=0.0,
                timeout=0.2,
            )()
        )
    assert time.perf_counter() - t0 < 10.0  # bounded, not a spin


def test_stall_guard_exempts_transient_raises_across_batches():
    """The stall bound targets un-skippable HANGS only: alternating
    raise-y transient failures across different replay batches stay on
    the documented per-batch budget and must complete."""
    from collections import defaultdict

    from keystone_tpu.loaders.stream import resilient

    counts = defaultdict(int)

    class It:
        def __init__(self):
            self.i = 0

        def __iter__(self):
            return self

        def __next__(self):
            if self.i >= 6:
                raise StopIteration
            i = self.i
            self.i += 1
            counts[i] += 1
            # batches 1 and 3 alternate transient failures over several
            # replay cycles — zero progress between restarts, but each
            # batch stays within its own retry budget
            if i == 1 and counts[1] in (2, 4):
                raise OSError(f"transient at 1 (visit {counts[1]})")
            if i == 3 and counts[3] in (1, 3):
                raise OSError(f"transient at 3 (visit {counts[3]})")
            return np.full((2, 2), i, np.float32)

    out = list(resilient(It, retries=2, base_delay=0.0, timeout=30.0)())
    assert [int(b[0, 0]) for b in out] == [0, 1, 2, 3, 4, 5]


def test_resilient_no_timeout_stays_same_thread():
    """timeout=None keeps fetches on the calling thread (inert path)."""
    from keystone_tpu.loaders.stream import resilient

    threads = []

    def source():
        def it():
            threads.append(threading.current_thread())
            yield np.zeros((1, 1), np.float32)

        return it()

    list(resilient(source, retries=0)())
    assert threads == [threading.current_thread()]


# --------------------------------------------------- latency fault plans


def test_delay_action_stalls_then_proceeds():
    t0 = time.perf_counter()
    with faults.inject("stream.batch:times=1:delay=0.15"):
        faults.fault_point("stream.batch")
        faults.fault_point("stream.batch")  # spec exhausted: no stall
    assert 0.15 <= time.perf_counter() - t0 < 2.0


def test_latency_actions_valid_at_every_site():
    for site in sorted(faults.SITES):
        plan = faults.parse_plan(f"{site}:delay=0.01;{site}:hang")
        assert {s.action for s in plan.specs} == {"delay", "hang"}


def test_bare_delay_token_rejected():
    with pytest.raises(faults.FaultPlanError, match="delay needs seconds"):
        faults.parse_plan("stream.batch:delay")


@pytest.mark.chaos
def test_chaos_hang_at_executor_stage_survives_deadline_plus_retry(
    monkeypatch, tmp_path
):
    """A hung stage under a per-stage deadline is retried like a raised
    fault and the run completes."""
    monkeypatch.setenv(guard.ENV_STAGE_DEADLINE, "0.3")
    led = ledger.start_run(str(tmp_path))
    lazy = Pipeline.of(_AddOne())(Dataset(np.ones((4, 2), np.float32)))
    with faults.inject("executor.stage:times=1:hang"):
        ex = GraphExecutor(lazy.graph, node_retries=1)
        out = ex.execute(lazy.graph.sinks[0])
    ledger.stop_run()
    np.testing.assert_allclose(np.asarray(out.dataset.array), 2.0)
    evs = _ledger_events(led.path)
    assert any(e.get("name") == "deadline_exceeded" for e in evs)
    assert any(e.get("name") == "executor.retry" for e in evs)


@pytest.mark.chaos
def test_chaos_delay_at_stream_batch_survives_timeout(monkeypatch):
    """An injected per-batch delay longer than the fetch timeout is
    converted to DeadlineExceeded and absorbed by the stream retry
    budget — the consumer sees every row."""
    from keystone_tpu.loaders.stream import batched
    from keystone_tpu.workflow.dataset import StreamDataset

    x = np.arange(64, dtype=np.float32).reshape(16, 4)
    monkeypatch.setenv(faults.ENV_VAR, "stream.batch:after=1:times=1:delay=5")
    ds = StreamDataset(batched(x, 8), n=16, retries=2, timeout=0.3)
    rows = np.concatenate([np.asarray(b) for b, _m in ds.device_batches()])
    np.testing.assert_array_equal(rows, x)


@pytest.mark.chaos
@pytest.mark.hangs
def test_acceptance_hang_and_delay_complete_under_deadline(
    monkeypatch, tmp_path
):
    """The PR's acceptance scenario: one plan injects ``hang`` at
    executor.stage (repeatedly — enough to open the stage's breaker)
    and ``delay`` at stream.batch; with a stage deadline, stage retries,
    a stream fetch timeout, and an optional featurizer stage, the
    pipeline completes and the ledger holds all three event kinds:
    ``deadline_exceeded``, ``breaker.transition``, and ``degraded``."""
    from keystone_tpu.loaders.stream import batched
    from keystone_tpu.workflow.dataset import StreamDataset

    monkeypatch.setenv(guard.ENV_STAGE_DEADLINE, "0.3")
    monkeypatch.setenv(guard.ENV_BREAKER_THRESHOLD, "2")
    x = np.ones((16, 4), np.float32)

    led = ledger.start_run(str(tmp_path))
    # after=1 skips the (non-degradable) Dataset source node: both hangs
    # land on the optional _AddOne stage — attempt + retry — which opens
    # its breaker (threshold 2) and then degrades
    plan = "executor.stage:after=1:times=2:hang;stream.batch:times=1:delay=0.05"
    with faults.inject(plan):
        # the delayed (but sub-timeout) stream still yields every row
        ds = StreamDataset(batched(x, 8), n=16, retries=2, timeout=2.0)
        rows = np.concatenate([np.asarray(b) for b, _m in ds.device_batches()])

        # the hung stage: retries spend the injected hangs, the breaker
        # opens after 2 consecutive deadline overruns, and the optional
        # declaration degrades the stage instead of failing the run
        t = _AddOne()
        t.optional = True
        lazy = Pipeline.of(t)(Dataset(np.full((4, 2), 5.0, np.float32)))
        ex = GraphExecutor(lazy.graph, node_retries=1)
        out = ex.execute(lazy.graph.sinks[0])
    ledger.stop_run()

    np.testing.assert_array_equal(rows, x)
    # degraded to identity: the input passes through unchanged
    np.testing.assert_allclose(np.asarray(out.dataset.array), 5.0)

    names = {e.get("name") for e in _ledger_events(led.path)}
    assert "deadline_exceeded" in names
    assert "breaker.transition" in names
    assert "degraded" in names


# ------------------------------------------------- multihost health/init


def test_health_barrier_single_process_inert():
    from keystone_tpu.parallel import multihost

    t0 = time.perf_counter()
    assert multihost.health_barrier(timeout=0.1) is True
    assert multihost.maybe_health_barrier("t") is True
    assert time.perf_counter() - t0 < 1.0


def test_transient_init_error_classifier():
    from keystone_tpu.parallel.multihost import _transient_init_error

    assert _transient_init_error(OSError("disk"))
    assert _transient_init_error(ConnectionError("nope"))
    assert _transient_init_error(
        RuntimeError("failed to connect to coordinator: UNAVAILABLE")
    )
    assert _transient_init_error(RuntimeError("Barrier timed out"))
    assert not _transient_init_error(
        RuntimeError("Number of processes 4 does not match num_processes 2")
    )
    assert not _transient_init_error(RuntimeError("process_id out of range"))
