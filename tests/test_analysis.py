"""Pre-flight pipeline analyzer (keystone_tpu/analysis).

Three suites:

- **false-positive gate**: every bundled pipeline (all 8 apps, built
  over tiny synthetic data) analyzes to ZERO findings, and the solver
  precision lint is clean under every KEYSTONE_MATMUL mode — the
  analyzer is only trustworthy if a clean pipeline stays clean;
- **seeded-defect corpus**: at least one planted bug per pass (a–d) is
  caught — mis-shaped stage, host-stream mis-wiring, f64 downcast,
  bf16 leaking into a 'solver', unknown fault site, infeasible
  deadline, breaker-without-fallback, signature collision, dataset
  name collision, unfitted-estimator apply;
- **wiring**: Pipeline.fit(validate=)/KEYSTONE_VALIDATE, freeze
  validation, the cli `check` subcommand, the DOT findings overlay,
  and the inertness of the default path.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.analysis import (
    AnalysisReport,
    Finding,
    PipelineValidationError,
    analyze,
    check_fn,
)
from keystone_tpu.analysis import precision as precision_pass
from keystone_tpu.analysis.bundled import BUNDLED, build_bundled
from keystone_tpu.workflow import Dataset, Pipeline
from keystone_tpu.workflow import graph as G
from keystone_tpu.workflow.transformer import Transformer


class Scale(Transformer):
    """Minimal well-behaved device transformer for fixtures."""

    def __init__(self, k: float):
        self.k = float(k)

    def params(self):
        return (self.k,)

    def apply_batch(self, xs, mask=None):
        return xs * self.k


class FixedDot(Transformer):
    """Multiplies by a fixed (d, d) matrix — mis-shaped inputs fail."""

    def __init__(self, d: int):
        self.d = d
        self.w = jnp.eye(d, dtype=jnp.float32)

    def params(self):
        return (self.d,)

    def apply_batch(self, xs, mask=None):
        return xs @ self.w


# ------------------------------------------------------ false-positive gate
@pytest.mark.parametrize("name", BUNDLED)
def test_bundled_pipeline_zero_findings(name):
    pipe, example = build_bundled(name)
    report = analyze(pipe, example=example)
    assert not report.findings, report.render()


def test_solver_precision_lint_clean_all_modes():
    """Pass (b) over every registered solver entry under every
    KEYSTONE_MATMUL mode (bf16_apply force-resolved): the PR-2
    byte-identity pins, generalized to a checker, hold for every
    solver."""
    findings = precision_pass.run()
    assert not findings, "\n".join(f.render() for f in findings)


def test_solver_registry_covers_every_family():
    names = {n for n, _ in precision_pass.SOLVER_ENTRIES}
    assert {
        "lbfgs.dense",
        "lbfgs.sparse",
        "block_ls",
        "block_weighted_ls",
        "kernel_ridge",
    } <= names


# ------------------------------------------------- pass (a): shapes/dtypes
def test_shape_mismatch_detected():
    pipe = Pipeline.of(Scale(2.0)).and_then(FixedDot(8))
    report = analyze(pipe, example=np.zeros((4, 12), np.float32))
    assert [f.code for f in report.errors] == ["shape-mismatch"]
    f = report.errors[0]
    assert f.pass_id == "shapes" and f.node is not None
    assert f.label == "FixedDot"


def test_clean_pipeline_no_findings():
    pipe = Pipeline.of(Scale(2.0)).and_then(FixedDot(8))
    report = analyze(pipe, example=np.zeros((4, 8), np.float32))
    assert not report.findings, report.render()


def test_untraceable_stage_is_not_a_false_positive():
    """Tracer/concretization errors mention 'shape' too — they must
    classify as untraceable (UNKNOWN), not shape-mismatch: the runtime
    executes these stages on the unjitted fallback, so refusing them
    would break the zero-false-positive contract (review finding)."""

    class DataDependent(Transformer):
        def params(self):
            return ()

        def apply_batch(self, xs, mask=None):
            if float(np.asarray(jnp.sum(xs))) > 0:  # concretizes a tracer
                return xs
            return -xs

    class HostNumpy(Transformer):
        def params(self):
            return ()

        def apply_batch(self, xs, mask=None):
            return jnp.asarray(np.asarray(xs) * 2.0)

    for t in (DataDependent(), HostNumpy()):
        pipe = Pipeline.of(t).and_then(Scale(1.0))
        report = analyze(pipe, example=np.zeros((4, 8), np.float32))
        assert not report.findings, report.render()
    # ...and the stages really do run on the eager fallback
    out = DataDependent()(
        Dataset(np.ones((4, 8), np.float32), shard=False)
    )
    assert out.numpy().shape == (4, 8)


def test_f64_input_downcast_warning():
    pipe = Pipeline.of(Scale(2.0))
    report = analyze(pipe, example=np.zeros((4, 8), np.float64))
    codes = {f.code for f in report.warnings}
    assert "dtype-downcast" in codes
    assert not report.errors  # a downcast warns, it does not refuse


def test_f64_datum_literal_downcast_warning():
    # a raw f64 datum bound into the graph (Dataset literals convert at
    # construction, so the datum path is where the analyzer can still
    # see the original dtype)
    lazy = Pipeline.of(Scale(1.0)).apply_datum(np.zeros(4, np.float64))
    report = analyze(lazy)
    assert any(f.code == "dtype-downcast" for f in report.warnings)


def test_host_stream_into_device_stage_is_error():
    from keystone_tpu.workflow.dataset import StreamDataset

    stream = StreamDataset(lambda: iter([["a", "b"]]), n=2, host=True)
    g = G.Graph()
    g, src = g.add_source()
    g, dsn = g.add_node(G.DatasetOperator(stream), ())
    g, t = g.add_node(G.TransformerOperator(Scale(1.0)), (dsn,))
    g, sink = g.add_sink(t)
    report = analyze(Pipeline(g, src, sink))
    assert [f.code for f in report.errors] == ["host-stream-device-stage"]


def test_unfitted_estimator_reference_detected():
    """A DelegatingOperator whose dep 0 is not an estimator output —
    the executor would raise TypeError at run time, possibly hours in."""
    data = Dataset(np.zeros((4, 3), np.float32), shard=False)
    g = G.Graph()
    g, src = g.add_source()
    g, dsn = g.add_node(G.DatasetOperator(data), ())
    g, dlg = g.add_node(G.DelegatingOperator(), (dsn, src))
    g, sink = g.add_sink(dlg)
    report = analyze(Pipeline(g, src, sink))
    assert "bad-delegate" in {f.code for f in report.errors}


def test_gather_mismatch_detected():
    class Widen(Transformer):
        def __init__(self, extra):
            self.extra = extra

        def params(self):
            return (self.extra,)

        def apply_batch(self, xs, mask=None):
            # reshapes the batch axis — branches disagree beyond features
            return jnp.repeat(xs, self.extra, axis=0)

    pipe = Pipeline.gather([Scale(1.0), Widen(2)])
    report = analyze(pipe, example=np.zeros((4, 8), np.float32))
    assert "gather-mismatch" in {f.code for f in report.errors}


def test_unfitted_estimator_is_error_in_apply_mode():
    from keystone_tpu.models import LinearMapEstimator

    data = Dataset(np.zeros((8, 4), np.float32), shard=False)
    labels = Dataset(np.ones((8, 2), np.float32), shard=False)
    pipe = Pipeline.of(Scale(1.0)).and_then(
        LinearMapEstimator(lam=0.1), data, labels
    )
    assert analyze(pipe, mode="fit").ok
    report = analyze(pipe, mode="apply")
    assert "unfitted-estimator" in {f.code for f in report.errors}


def test_kernel_mapper_shape_mismatch_detected():
    """The kernel-tier shapes case (ISSUE 13): a fitted kernel mapper
    whose input feature dim disagrees with its train rows fails
    pre-flight with a kernel-specific finding, not mid-sweep."""
    from keystone_tpu.models.kernel_ridge import (
        GaussianKernelGenerator,
        KernelBlockLinearMapper,
    )

    kern = GaussianKernelGenerator(0.1)
    tx = jnp.zeros((64, 8), jnp.float32)
    m = KernelBlockLinearMapper(kern, tx, jnp.zeros((64, 3)), 16, 64)
    rep = analyze(Pipeline.of(m), example=np.zeros((4, 8), np.float32))
    assert not rep.findings, rep.render()
    rep = analyze(Pipeline.of(m), example=np.zeros((4, 9), np.float32))
    assert [f.code for f in rep.errors] == ["kernel-shape-mismatch"]


def test_kernel_mapper_bad_state_detected():
    """Misshaped fitted kernel state (α rows vs train rows) is the
    explode-mid-sweep class the explicit case exists for."""
    from keystone_tpu.models.kernel_ridge import (
        GaussianKernelGenerator,
        KernelBlockLinearMapper,
    )

    m = KernelBlockLinearMapper(
        GaussianKernelGenerator(0.1),
        jnp.zeros((64, 8), jnp.float32),
        jnp.zeros((48, 3)),  # 48 α rows against 64 train rows
        16,
        64,
    )
    rep = analyze(Pipeline.of(m), example=np.zeros((4, 8), np.float32))
    assert [f.code for f in rep.errors] == ["kernel-bad-state"]


def test_oc_kernel_mapper_checked_without_reading_blocks(tmp_path):
    """The out-of-core mapper is validated from its store's METADATA
    alone (analysis must never stream train blocks off disk), and a
    missing backing store is a pre-flight error."""
    from keystone_tpu.models.kernel_ridge import (
        GaussianKernelGenerator,
        OutOfCoreKernelBlockLinearMapper,
    )
    from keystone_tpu.workflow.blockstore import RowBlockStore

    store = RowBlockStore.from_array(
        str(tmp_path / "s"), np.zeros((64, 8), np.float32), 16
    )
    m = OutOfCoreKernelBlockLinearMapper(
        GaussianKernelGenerator(0.1), store.directory,
        jnp.zeros((64, 3)), 64,
    )
    from keystone_tpu.obs import metrics

    reads0 = metrics.REGISTRY.counter_value("blockstore.reads") or 0
    rep = analyze(Pipeline.of(m), example=np.zeros((4, 8), np.float32))
    assert not rep.findings, rep.render()
    assert (metrics.REGISTRY.counter_value("blockstore.reads") or 0) == reads0
    rep = analyze(Pipeline.of(m), example=np.zeros((4, 9), np.float32))
    assert [f.code for f in rep.errors] == ["kernel-shape-mismatch"]

    gone = OutOfCoreKernelBlockLinearMapper(
        GaussianKernelGenerator(0.1), str(tmp_path / "missing"),
        jnp.zeros((64, 3)), 64,
    )
    rep = analyze(Pipeline.of(gone), example=np.zeros((4, 8), np.float32))
    assert [f.code for f in rep.errors] == ["kernel-bad-state"]


def test_degenerate_kernel_generator_detected():
    """γ ≤ 0 / NaN on an UNFITTED kernel estimator fails pre-flight —
    exp(0)=1 everywhere converges to garbage silently otherwise."""
    from keystone_tpu.models.kernel_ridge import (
        GaussianKernelGenerator,
        KernelRidgeRegressionEstimator,
    )
    from keystone_tpu.models.nystrom import NystromFeatures

    for bad_gamma in (0.0, float("nan")):
        est = KernelRidgeRegressionEstimator(
            GaussianKernelGenerator(bad_gamma)
        )
        pipe = Pipeline.from_estimator(
            est,
            Dataset(np.zeros((8, 4), np.float32)),
            Dataset(np.zeros((8, 2), np.float32)),
        )
        rep = analyze(pipe, example=np.zeros((4, 4), np.float32))
        assert "bad-kernel-generator" in [f.code for f in rep.errors]

    nys = NystromFeatures(GaussianKernelGenerator(-1.0), 8)
    pipe = Pipeline.from_estimator(
        nys, Dataset(np.zeros((8, 4), np.float32))
    )
    rep = analyze(pipe, example=np.zeros((4, 4), np.float32))
    assert "bad-kernel-generator" in [f.code for f in rep.errors]


# --------------------------------------------------- pass (b): precision
def test_planted_bf16_solver_is_flagged():
    def bad(a, b):
        return jnp.dot(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16))

    avals = (jax.ShapeDtypeStruct((4, 4), np.float32),) * 2
    codes = [f.code for f in check_fn(bad, *avals, name="planted")]
    assert "bf16-solver-input" in codes
    assert "non-f32-accumulation" in codes  # bf16 output too


def test_apply_policy_leak_into_solver_is_flagged():
    """The exact defect class the pass exists for: someone routes the
    apply-side bf16 helpers into solver math; under bf16_apply (forced
    on CPU) the leak is visible in the jaxpr."""
    from keystone_tpu.utils import precision as prec

    def leaky_solver(a, b):
        return prec.apply_dot(a, b)

    avals = (jax.ShapeDtypeStruct((4, 4), np.float32),) * 2
    with prec.matmul("bf16_apply"), prec.force_bf16_apply():
        findings = check_fn(leaky_solver, *avals, name="leaky")
    assert [f.code for f in findings] == ["bf16-solver-input"]
    # ...and the same function is clean when the policy is inert,
    # which is why the sweep must force-resolve bf16_apply
    with prec.matmul("f32"):
        assert not check_fn(leaky_solver, *avals, name="leaky")


def test_checker_recurses_into_scan():
    def scanned(a, b):
        def step(c, _):
            return c @ b.astype(jnp.bfloat16).astype(jnp.float32) @ jnp.eye(
                4, dtype=jnp.bfloat16
            ), None

        out, _ = jax.lax.scan(step, a, None, length=2)
        return out

    avals = (jax.ShapeDtypeStruct((4, 4), np.float32),) * 2
    assert any(
        f.code == "bf16-solver-input"
        for f in check_fn(scanned, *avals, name="scan")
    )


# -------------------------------------------------- pass (c): robustness
def test_unknown_fault_site_in_env_plan(monkeypatch):
    monkeypatch.setenv("KEYSTONE_FAULTS", "bogus.site:raise")
    report = analyze(Pipeline.of(Scale(1.0)))
    assert [f.code for f in report.errors] == ["bad-fault-plan"]
    assert "bogus.site" in report.errors[0].message


def test_valid_fault_plan_is_clean(monkeypatch):
    monkeypatch.setenv("KEYSTONE_FAULTS", "executor.stage:times=0")
    assert analyze(Pipeline.of(Scale(1.0))).ok


def test_mandatory_stage_under_breaker_warns(monkeypatch):
    monkeypatch.setenv("KEYSTONE_BREAKER_THRESHOLD", "2")
    report = analyze(Pipeline.of(Scale(1.0)))
    assert [f.code for f in report.warnings] == ["mandatory-under-breaker"]
    # a pipeline whose stages all degrade is clean under breakers
    report = analyze(Pipeline.of(Scale(1.0).with_fallback(Scale(0.0))))
    assert not report.findings, report.render()


def test_infeasible_deadline_warns():
    pipe, example = build_bundled("MnistRandomFFT")
    report = analyze(pipe, example=example, deadline=1e-6)
    assert "deadline-infeasible" in {f.code for f in report.warnings}
    # errors stay empty: an infeasible budget is a configuration smell,
    # not a refusal
    assert not report.errors


# -------------------------------------------------- pass (d): signatures
class UnderSpecified(Transformer):
    """params() omits ``k`` — the planted collision."""

    def __init__(self, k: float):
        self.k = float(k)

    def params(self):
        return ("underspecified",)

    def apply_batch(self, xs, mask=None):
        return xs * self.k


def test_signature_collision_detected():
    pipe = Pipeline.gather([UnderSpecified(1.0), UnderSpecified(2.0)])
    report = analyze(pipe, example=np.zeros((4, 8), np.float32))
    errs = [f for f in report.errors if f.code == "signature-collision"]
    assert errs and "'k'" in errs[0].message


def test_equal_state_instances_do_not_collide():
    pipe = Pipeline.gather([UnderSpecified(1.0), UnderSpecified(1.0)])
    report = analyze(pipe, example=np.zeros((4, 8), np.float32))
    assert not report.findings, report.render()


def test_array_valued_collision_detected():
    class ArrayParam(Transformer):
        def __init__(self, seed):
            self.w = jnp.asarray(
                np.random.RandomState(seed).randn(4).astype(np.float32)
            )

        def params(self):
            return ("arrayparam",)  # omits w

        def apply_batch(self, xs, mask=None):
            return xs * self.w

    pipe = Pipeline.gather([ArrayParam(0), ArrayParam(1)])
    report = analyze(pipe, example=np.zeros((4, 4), np.float32))
    assert "signature-collision" in {f.code for f in report.errors}


def test_dataset_name_collision_detected():
    from keystone_tpu.models import LinearMapEstimator

    d1 = Dataset(np.zeros((8, 4), np.float32), shard=False, name="train")
    d2 = Dataset(np.zeros((6, 4), np.float32), shard=False, name="train")
    labels = Dataset(np.ones((8, 2), np.float32), shard=False)
    l2 = Dataset(np.ones((6, 2), np.float32), shard=False)
    pipe = Pipeline.gather(
        [
            Pipeline.of(Scale(1.0)).and_then(
                LinearMapEstimator(lam=0.1), d1, labels
            ),
            Pipeline.of(Scale(2.0)).and_then(
                LinearMapEstimator(lam=0.2), d2, l2
            ),
        ]
    )
    report = analyze(pipe)
    assert "dataset-name-collision" in {f.code for f in report.errors}


def test_unstable_signature_detected():
    import itertools

    counter = itertools.count()

    class Unstable(Transformer):
        def params(self):
            return (next(counter),)

        def apply_batch(self, xs, mask=None):
            return xs

    report = analyze(Pipeline.of(Unstable()))
    assert "unstable-signature" in {f.code for f in report.errors}


# ----------------------------------------------------------- report schema
def test_report_render_and_dict():
    rep = AnalysisReport(
        [
            Finding("warning", "shapes", "dtype-downcast", "w", node=3, label="X"),
            Finding("error", "shapes", "shape-mismatch", "boom", node=5, label="Y"),
        ]
    )
    text = rep.render()
    # errors render first, with graph locations
    assert text.splitlines()[0].startswith("ERROR")
    assert "n5[Y]" in text and "n3[X]" in text
    d = rep.to_dict()
    assert d["errors"] == 1 and d["warnings"] == 1
    with pytest.raises(PipelineValidationError) as ei:
        rep.raise_for_errors()
    assert ei.value.report is rep


# ----------------------------------------------------------------- wiring
def _broken_fit_pipeline():
    """Estimator branch whose featurizer cannot accept the bound data."""
    from keystone_tpu.models import LinearMapEstimator

    data = Dataset(np.zeros((8, 12), np.float32), shard=False)
    labels = Dataset(np.ones((8, 2), np.float32), shard=False)
    return Pipeline.of(FixedDot(8)).and_then(
        LinearMapEstimator(lam=0.1), data, labels
    )


def test_fit_validate_refuses_broken_pipeline():
    with pytest.raises(PipelineValidationError) as ei:
        _broken_fit_pipeline().fit(validate=True)
    assert "shape-mismatch" in str(ei.value)


def test_fit_validate_env_gate(monkeypatch):
    monkeypatch.setenv("KEYSTONE_VALIDATE", "1")
    with pytest.raises(PipelineValidationError):
        _broken_fit_pipeline().fit()
    # explicit validate=False overrides the env (and the fit then fails
    # at device time instead — not exercised here)
    monkeypatch.setenv("KEYSTONE_VALIDATE", "0")
    with pytest.raises(PipelineValidationError):
        _broken_fit_pipeline().fit(validate=True)


def test_fit_validate_passes_clean_pipeline():
    from keystone_tpu.models import LinearMapEstimator

    data = Dataset(np.random.RandomState(0).randn(16, 4).astype(np.float32))
    labels = Dataset(np.ones((16, 2), np.float32))
    pipe = Pipeline.of(Scale(1.0)).and_then(
        LinearMapEstimator(lam=0.1), data, labels
    )
    fitted = pipe.fit(validate=True)
    out = fitted(np.zeros((4, 4), np.float32)).get()
    assert out.numpy().shape == (4, 2)
    # freeze validation accepts the fitted pipeline too
    applier = fitted.freeze(validate=True, example=(4,))
    assert applier(np.zeros((4, 4), np.float32)).numpy().shape == (4, 2)


def test_freeze_validate_flags_mis_shaped_example():
    fitted = Pipeline.of(FixedDot(8)).fit(validate=True)
    with pytest.raises(PipelineValidationError):
        fitted.freeze(validate=True, example=(12,))
    assert fitted.freeze(validate=True, example=(8,)) is not None


def test_cli_check_bundled(tmp_path, capsys):
    from keystone_tpu import cli

    dot = tmp_path / "graph.dot"
    rc = cli.main(
        ["check", "MnistRandomFFT", "--no-solver-lint", "--dot", str(dot)]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "no findings" in out
    assert dot.exists() and "digraph" in dot.read_text()


def test_cli_check_saved_model_roundtrip(tmp_path, capsys):
    from keystone_tpu import cli

    fitted = Pipeline.of(FixedDot(8)).fit()
    path = tmp_path / "model.pkl"
    fitted.save(str(path))
    assert cli.main(["check", "--model", str(path), "--no-solver-lint",
                     "--example-shape", "8"]) == 0
    capsys.readouterr()
    # a mis-shaped example spec makes the same model fail the check
    rc = cli.main(["check", "--model", str(path), "--no-solver-lint",
                   "--example-shape", "12"])
    out = capsys.readouterr().out
    assert rc == 1 and "shape-mismatch" in out


def test_cli_check_unknown_name():
    from keystone_tpu import cli

    assert cli.main(["check", "NoSuchPipeline", "--no-solver-lint"]) == 2


def test_to_dot_findings_overlay():
    pipe = Pipeline.of(Scale(2.0)).and_then(FixedDot(8))
    report = analyze(pipe, example=np.zeros((4, 12), np.float32))
    dot = pipe.to_dot(findings=report.findings)
    assert "#ff9999" in dot and "shape-mismatch" in dot
    # graph-level findings render as a note node
    dot2 = pipe.to_dot(
        findings=[Finding("warning", "robustness", "bad-fault-plan", "m")]
    )
    assert "analysis_findings" in dot2 and "#ffe680" in dot2


def test_default_fit_path_stays_inert(monkeypatch):
    """validate off (the default): fit never imports the analysis
    package — the solver byte-identity pins ride on this."""
    import sys

    from keystone_tpu.models import LinearMapEstimator

    for mod in [m for m in sys.modules if m.startswith("keystone_tpu.analysis")]:
        monkeypatch.delitem(sys.modules, mod, raising=False)
    monkeypatch.delenv("KEYSTONE_VALIDATE", raising=False)
    data = Dataset(np.random.RandomState(0).randn(16, 4).astype(np.float32))
    labels = Dataset(np.ones((16, 2), np.float32))
    Pipeline.of(Scale(1.0)).and_then(
        LinearMapEstimator(lam=0.1), data, labels
    ).fit().freeze()
    assert not any(
        m.startswith("keystone_tpu.analysis") for m in sys.modules
    )


# -------------------------------------------------------------- satellites
def test_inject_rejects_unknown_site_plan_object():
    from keystone_tpu import faults

    plan = faults.FaultPlan([faults.SiteSpec("typo.site")])
    with pytest.raises(faults.UnknownFaultSiteError) as ei:
        with faults.inject(plan):
            pass
    assert "typo.site" in str(ei.value)
    assert "executor.stage" in str(ei.value)  # lists the registered sites
    assert isinstance(ei.value, faults.FaultPlanError)  # typed subclass


def test_parse_plan_unknown_site_typed_error():
    from keystone_tpu import faults

    with pytest.raises(faults.UnknownFaultSiteError):
        faults.parse_plan("bogus.site:raise")


def test_metric_kind_conflict_rejected():
    from keystone_tpu.obs.metrics import MetricKindError, MetricsRegistry

    r = MetricsRegistry()
    r.inc("a.b", site="x")
    with pytest.raises(MetricKindError) as ei:
        r.set_gauge("a.b", 1.0)
    assert "counter" in str(ei.value) and "gauge" in str(ei.value)
    with pytest.raises(MetricKindError):
        r.observe("a.b", 0.5)
    # same kind, any labels: fine; reset clears the kind registry
    r.inc("a.b", site="y")
    r.reset()
    r.set_gauge("a.b", 1.0)
    assert r.gauge_value("a.b") == 1.0


def test_metric_kind_gauge_family_is_one_kind():
    from keystone_tpu.obs.metrics import MetricsRegistry

    r = MetricsRegistry()
    r.set_gauge("g.x", 1.0, key="a")
    r.gauge_max("g.x", 5.0, key="a")  # watermark and set share the kind
    r.remove_gauge("g.x", key="a")
    assert r.gauge_value("g.x", key="a") is None
