"""Online serving subsystem (keystone_tpu/serve): micro-batcher state
machine, admission control, deadline shedding, chaos over serve.* sites,
compiled-program reuse, HTTP front end, and the byte-identity pins.

All tier-1 (seconds-scale, CPU): the service is host-side threading over
tiny device programs.
"""

import json
import subprocess
import sys
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.obs import metrics
from keystone_tpu.ops.stats import NormalizeRows
from keystone_tpu.serve import (
    Overloaded,
    PipelineService,
    ServiceClosed,
    default_buckets,
    serve,
)
from keystone_tpu.utils import guard
from keystone_tpu.workflow import Dataset, Pipeline

pytestmark = pytest.mark.serve

DIM = 6


def _pipeline(scale: float = 2.0) -> Pipeline:
    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * scale)
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


def _service(**kw) -> PipelineService:
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 30.0)
    kw.setdefault("queue_bound", 64)
    kw.setdefault("example", np.zeros(DIM, np.float32))
    return serve(_pipeline(), **kw)


def _counter(name: str) -> float:
    return metrics.REGISTRY.counter_value(name)


# ------------------------------------------------------------- correctness


def test_serve_matches_offline_apply():
    """The padded-bucket serve path returns exactly what the offline
    batch apply returns (pad rows are sliced off, per-row semantics)."""
    x = np.random.default_rng(0).normal(size=(5, DIM)).astype(np.float32)
    pipe = _pipeline()
    ref = np.asarray(pipe(Dataset(x)).get().array)[:5]
    with _service() as svc:
        futs = svc.submit_many(x)
        got = np.stack([f.result(timeout=30) for f in futs])
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)


def test_freeze_rejects_unfitted_pipeline():
    from keystone_tpu.models.linear import LinearMapEstimator
    from keystone_tpu.workflow.pipeline import FrozenApplier

    x = np.random.default_rng(0).normal(size=(8, DIM)).astype(np.float32)
    y = np.eye(DIM, dtype=np.float32)[np.arange(8) % DIM]
    pipe = Pipeline.of(NormalizeRows()).and_then(
        LinearMapEstimator(lam=1e-3), x, y
    )
    with pytest.raises(TypeError, match="call fit"):
        FrozenApplier(pipe)
    # fitted, the same pipeline freezes and serves
    with serve(
        pipe.fit(), max_batch=4, max_wait_ms=5.0, example=x[0]
    ) as svc:
        out = svc.submit(x[0]).result(timeout=30)
    assert np.asarray(out).shape == (DIM,)


# --------------------------------------------------- batcher state machine


def test_flush_on_max_batch():
    """max_batch requests flush immediately — well before the (long)
    timer — and ride ONE batch."""
    before = _counter("serve.batches")
    with _service(max_batch=4, max_wait_ms=10_000.0) as svc:
        x = np.ones((4, DIM), np.float32)
        t0 = time.monotonic()
        futs = svc.submit_many(x)
        [f.result(timeout=30) for f in futs]
        elapsed = time.monotonic() - t0
    assert elapsed < 5.0  # nowhere near the 10 s timer
    assert _counter("serve.batches") == before + 1


def test_flush_on_timer():
    """A lone request flushes when the oldest-request timer expires,
    not when max_batch fills."""
    with _service(max_batch=8, max_wait_ms=50.0) as svc:
        fut = svc.submit(np.ones(DIM, np.float32))
        out = fut.result(timeout=30)
    assert np.asarray(out).shape == (DIM,)


def test_fifo_order_preserved():
    """Requests resolve with their OWN results in submission order —
    index-encoded payloads round-trip one-to-one (FIFO fairness)."""
    with _service(max_batch=4, max_wait_ms=5.0) as svc:
        xs = [np.full(DIM, float(i + 1), np.float32) for i in range(20)]
        futs = [svc.submit(x) for x in xs]
        outs = [np.asarray(f.result(timeout=30)) for f in futs]
    pipe = _pipeline()
    ref = np.asarray(pipe(Dataset(np.stack(xs))).get().array)[:20]
    for i, out in enumerate(outs):
        np.testing.assert_allclose(out, ref[i], rtol=1e-6, atol=1e-7)


def test_deadline_expired_request_is_shed():
    """A request whose deadline already passed is shed (typed
    DeadlineExceeded), while a live request in the same flush completes."""
    shed0 = _counter("serve.shed")
    with _service(max_batch=8, max_wait_ms=30.0) as svc:
        doomed = svc.submit(np.ones(DIM, np.float32), deadline=-0.01)
        live = svc.submit(np.ones(DIM, np.float32), deadline=30.0)
        with pytest.raises(guard.DeadlineExceeded):
            doomed.result(timeout=30)
        assert np.asarray(live.result(timeout=30)).shape == (DIM,)
    assert _counter("serve.shed") == shed0 + 1


def test_queue_bound_rejects_with_overloaded():
    """Admission control: submits past queue_bound raise Overloaded
    (and count) while queued requests still drain at shutdown."""
    rej0 = _counter("serve.rejected")
    svc = _service(max_batch=64, max_wait_ms=10_000.0, queue_bound=2)
    try:
        f1 = svc.submit(np.ones(DIM, np.float32))
        f2 = svc.submit(np.ones(DIM, np.float32))
        with pytest.raises(Overloaded):
            svc.submit(np.ones(DIM, np.float32))
        assert _counter("serve.rejected") == rej0 + 1
    finally:
        svc.close()  # drain flushes the two queued requests
    assert np.asarray(f1.result(timeout=5)).shape == (DIM,)
    assert np.asarray(f2.result(timeout=5)).shape == (DIM,)


def test_clean_shutdown_drains_in_flight():
    """close(drain=True) resolves every queued request before the
    worker exits; post-close submits raise ServiceClosed."""
    svc = _service(max_batch=4, max_wait_ms=10_000.0, queue_bound=64)
    futs = [svc.submit(np.ones(DIM, np.float32)) for _ in range(10)]
    svc.close()
    for f in futs:
        assert np.asarray(f.result(timeout=5)).shape == (DIM,)
    with pytest.raises(ServiceClosed):
        svc.submit(np.ones(DIM, np.float32))


def test_close_without_drain_fails_queued():
    svc = _service(max_batch=64, max_wait_ms=10_000.0)
    futs = [svc.submit(np.ones(DIM, np.float32)) for _ in range(3)]
    svc.close(drain=False)
    for f in futs:
        with pytest.raises(ServiceClosed):
            f.result(timeout=5)


def test_cancelled_future_does_not_kill_batcher():
    """A caller cancelling its queued future must not brick the worker:
    the cancelled request is skipped and later requests still serve."""
    with _service(max_batch=4, max_wait_ms=50.0) as svc:
        doomed = svc.submit(np.ones(DIM, np.float32))
        assert doomed.cancel()  # still queued: cancel succeeds
        later = svc.submit(np.ones(DIM, np.float32))
        assert np.asarray(later.result(timeout=30)).shape == (DIM,)
        again = svc.submit(np.ones(DIM, np.float32))
        assert np.asarray(again.result(timeout=30)).shape == (DIM,)


def test_rejected_first_call_does_not_fix_item_shape():
    """An oversize first submit_many is rejected whole — and must not
    lock in an item-shape contract no served request ever set."""
    with serve(
        _pipeline(), max_batch=4, max_wait_ms=5.0, queue_bound=2
    ) as svc:
        with pytest.raises(Overloaded):
            svc.submit_many(np.ones((3, DIM + 1), np.float32))
        assert svc.queue_depth == 0  # atomic: nothing orphaned
        # the real workload's shape is learned fresh
        out = svc.submit(np.ones(DIM, np.float32)).result(timeout=30)
        assert np.asarray(out).shape == (DIM,)


def test_shed_predictor_recovers_from_outlier_batch():
    """A poisoned EWMA (e.g. a cold compile measured into the first
    sample) must decay across fully-shed flushes instead of shedding
    100% of deadline traffic forever."""
    with _service(max_batch=8, max_wait_ms=2.0) as svc:
        svc._ewma_batch_s = 5.0  # simulate one 5 s outlier sample
        deadline = 1.0
        out = None
        for _ in range(30):  # decay: 5.0 * 0.7^n < 1.0 within ~5 flushes
            try:
                out = svc.submit(
                    np.ones(DIM, np.float32), deadline=deadline
                ).result(timeout=30)
                break
            except guard.DeadlineExceeded:
                continue
        assert out is not None, "predictor never recovered"
        assert svc._ewma_batch_s < 1.0


def test_http_frontend_stop_without_start_does_not_hang():
    from keystone_tpu.serve import HttpFrontend

    with _service(max_batch=4, max_wait_ms=5.0) as svc:
        front = HttpFrontend(svc, port=0)
        front.stop()  # never started: must close, not deadlock
        # and the context manager auto-starts
        with HttpFrontend(svc, port=0) as started:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{started.port}/healthz", timeout=10
            ) as resp:
                assert resp.status == 200


def test_shape_mismatch_rejected_at_submit():
    """A bad request fails ITS OWN submit — never the batch it would
    have ridden in."""
    with _service() as svc:
        good = svc.submit(np.ones(DIM, np.float32))
        with pytest.raises(TypeError, match="item shape"):
            svc.submit(np.ones(DIM + 1, np.float32))
        assert np.asarray(good.result(timeout=30)).shape == (DIM,)


def test_default_buckets():
    assert default_buckets(32) == (8, 16, 32)
    assert default_buckets(24) == (8, 16, 24)
    assert default_buckets(4) == (4,)
    assert default_buckets(1) == (1,)


# ----------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_chaos_enqueue_fault_backpressures_caller():
    """An injected fault at serve.enqueue surfaces to the submitting
    caller (admission chaos); the next submit succeeds."""
    with _service(max_batch=2, max_wait_ms=5.0) as svc:
        with faults.inject("serve.enqueue:times=1:raise"):
            with pytest.raises(faults.FaultInjected):
                svc.submit(np.ones(DIM, np.float32))
            fut = svc.submit(np.ones(DIM, np.float32))
            assert np.asarray(fut.result(timeout=30)).shape == (DIM,)


@pytest.mark.chaos
def test_chaos_batch_fault_fails_batch_not_service():
    """An injected fault at serve.batch fails that flush's futures and
    ONLY them — the worker survives and serves the next flush."""
    err0 = _counter("serve.batch_errors")
    with _service(max_batch=2, max_wait_ms=5.0) as svc:
        with faults.inject("serve.batch:times=1:raise"):
            bad = svc.submit_many(np.ones((2, DIM), np.float32))
            for f in bad:
                with pytest.raises(faults.FaultInjected):
                    f.result(timeout=30)
            good = svc.submit(np.ones(DIM, np.float32))
            assert np.asarray(good.result(timeout=30)).shape == (DIM,)
    assert _counter("serve.batch_errors") == err0 + 1


@pytest.mark.chaos
@pytest.mark.hangs
def test_chaos_batch_stall_sheds_waiting_deadlines():
    """The hang scenario: a stalled flush (serve.batch:delay) makes the
    request queued behind it miss its deadline — it is shed, while the
    stalled request itself completes."""
    with _service(max_batch=1, max_wait_ms=2.0, queue_bound=8) as svc:
        with faults.inject("serve.batch:times=1:delay=0.4"):
            slow = svc.submit(np.ones(DIM, np.float32), deadline=10.0)
            time.sleep(0.05)  # the worker is now inside the stalled flush
            doomed = svc.submit(np.ones(DIM, np.float32), deadline=0.05)
            assert np.asarray(slow.result(timeout=30)).shape == (DIM,)
            with pytest.raises(guard.DeadlineExceeded):
                doomed.result(timeout=30)


def test_optional_stage_degrades_on_serve_path():
    """Executor degradation applies to served batches: a failing
    ``optional=True`` stage is replaced by Identity instead of failing
    the flush."""
    from keystone_tpu.workflow import Transformer

    class _Flaky(Transformer):
        optional = True

        def apply_one(self, x):
            raise RuntimeError("boom")

        def apply_batch(self, xs, mask=None):
            raise RuntimeError("boom")

    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * 3.0)
    pipe = Pipeline.of(_Flaky()) | LinearMapper(w)
    deg0 = metrics.REGISTRY.counter_total("executor.degraded")
    x = np.random.default_rng(2).normal(size=(DIM,)).astype(np.float32)
    with serve(
        pipe, max_batch=4, max_wait_ms=5.0, example=np.zeros(DIM, np.float32)
    ) as svc:
        out = np.asarray(svc.submit(x).result(timeout=30))
    np.testing.assert_allclose(out, x * 3.0, rtol=1e-6)
    assert metrics.REGISTRY.counter_total("executor.degraded") > deg0


# -------------------------------------------------- compiled-program reuse


def _total_apply_programs() -> int:
    """Compiled apply-program count across every jit cache an apply can
    ride: the fused-chain shared cache, the traced-params shared cache,
    and the per-instance wrappers.

    Collect first: the per-instance cache is a WeakKeyDictionary over
    transformer objects, and earlier tests' dead pipelines linger as
    cyclic garbage until a generational GC pass — one landing BETWEEN
    two counts silently shrinks the second and fails an equality pin
    that no new compile violated.  Forcing collection before every
    count makes both sides see post-GC state; a genuinely new program
    still raises the count."""
    import gc
    import importlib

    gc.collect()
    T = importlib.import_module("keystone_tpu.workflow.transformer")
    O = importlib.import_module("keystone_tpu.workflow.optimizer")
    n = 0
    for v in O._FUSED_SHARED_CACHE.values():
        if callable(v):
            n += v._cache_size()
    for v in T._SHARED_APPLY_CACHE.values():
        if callable(v):
            n += v._cache_size()
    for entry in T._JIT_APPLY_CACHE.values():
        for f in entry.values():
            if callable(f):
                n += f._cache_size()
    return n


def test_single_datum_rides_bucket_program():
    """The compile-count pin (ISSUE 5 satellite): after priming, a
    single-datum request is padded to the smallest bucket and reuses
    its BATCH program — no per-datum program is ever traced."""
    with _service(buckets=(8,), max_batch=8, max_wait_ms=5.0) as svc:
        n0 = _total_apply_programs()
        assert n0 > 0  # priming compiled the bucket programs
        out = svc.submit(np.zeros(DIM, np.float32)).result(timeout=30)
        assert np.asarray(out).shape == (DIM,)
        assert _total_apply_programs() == n0


def test_priming_compiles_each_bucket_once():
    """Every bucket shape is primed at construction, so a first request
    at ANY admissible size pays zero traces."""
    with _service(buckets=(4, 8), max_batch=8, max_wait_ms=5.0) as svc:
        n0 = _total_apply_programs()
        for k in (1, 3, 4, 6, 8):  # both buckets, never a new shape
            futs = svc.submit_many(np.ones((k, DIM), np.float32))
            [f.result(timeout=30) for f in futs]
        assert _total_apply_programs() == n0


# ------------------------------------------------------- byte-identity pins


def test_solver_hlo_identical_with_service_running():
    """Running a service must not perturb traced solver programs: the
    serving layer lives entirely outside jit."""
    import jax

    from keystone_tpu.models.block_ls import _bcd_epoch_body

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 16, 8)), jnp.float32
    )
    y = jnp.ones((16, 2), jnp.float32)
    w = jnp.zeros((2, 8, 2), jnp.float32)
    p = jnp.zeros((16, 2), jnp.float32)

    def step(xb, yb, wb, pb):
        return _bcd_epoch_body(xb, yb, jnp.float32(16.0), 1e-3, (wb, pb))

    plain = jax.jit(step).lower(x, y, w, p).as_text()
    with _service() as svc:
        svc.submit(np.ones(DIM, np.float32)).result(timeout=30)
        serving = jax.jit(step).lower(x, y, w, p).as_text()
    assert plain == serving


def test_library_import_path_excludes_serve():
    """With no service running, importing the library must not import
    (or pay for) the serving subsystem — the offline import path is
    exactly what it was before this subsystem existed."""
    out = subprocess.run(
        [
            sys.executable,
            "-c",
            "import keystone_tpu, sys; "
            "print('keystone_tpu.serve' in sys.modules)",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-500:]
    assert out.stdout.strip().splitlines()[-1] == "False"


# ------------------------------------------------------------------- HTTP


def test_http_predict_healthz_metrics():
    from keystone_tpu.serve import serve_http

    x = np.random.default_rng(1).normal(size=(3, DIM)).astype(np.float32)
    pipe = _pipeline()
    ref = np.asarray(pipe(Dataset(x)).get().array)[:3]
    with _service(max_batch=4, max_wait_ms=5.0) as svc:
        with serve_http(svc, port=0) as front:
            base = f"http://127.0.0.1:{front.port}"
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"instances": x.tolist()}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
                preds = json.loads(resp.read())["predictions"]
            np.testing.assert_allclose(
                np.asarray(preds, np.float32), ref, rtol=1e-5, atol=1e-6
            )

            with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
                health = json.loads(resp.read())
            assert health["status"] == "ok"
            assert health["max_batch"] == 4

            with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
                text = resp.read().decode()
            assert "serve_completed_total" in text
            assert "serve_batch_rows_count" in text

            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(base + "/nope", timeout=10)
            assert err.value.code == 404


def test_http_bad_request_and_single_instance():
    from keystone_tpu.serve import serve_http

    with _service(max_batch=4, max_wait_ms=5.0) as svc:
        with serve_http(svc, port=0) as front:
            base = f"http://127.0.0.1:{front.port}"
            req = urllib.request.Request(
                base + "/predict", data=b"not json at all"
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 400

            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps(
                    {"instance": [1.0] * DIM, "deadline_ms": 5000}
                ).encode(),
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                preds = json.loads(resp.read())["predictions"]
            assert len(preds) == 1 and len(preds[0]) == DIM


# --------------------------------------------------------------- overload


@pytest.mark.hangs
def test_overload_keeps_accepting_with_bounded_queue():
    """The acceptance scenario (seconds-scale): offered QPS > capacity
    (a serve.batch delay plan emulates a heavier model).  The service
    keeps completing work at occupancy > 1, sheds/rejects the excess
    (counted), and every completed request beats its deadline."""
    sys.path.insert(
        0,
        __import__("os").path.dirname(
            __import__("os").path.dirname(__import__("os").path.abspath(__file__))
        ),
    )
    from tools import serve_bench

    svc, item_shape = serve_bench.build_service(
        dim=16,
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=32,
        deadline_ms=500.0,
    )
    try:
        rep = serve_bench.run_bench(
            svc,
            item_shape,
            qps=600.0,
            duration=1.5,
            deadline_ms=500.0,
            batch_delay_ms=15.0,
        )
    finally:
        svc.close()
    # offered 600 qps vs capacity ~ 8 rows / 15ms ≈ 530: overload
    assert rep["completed"] > 0
    assert rep["mean_batch_occupancy"] > 1.0
    assert rep["shed"] + rep["rejected"] > 0  # excess counted, not queued
    assert rep["errors"] == 0
    assert rep["deadline_miss"] == 0  # completed requests beat deadlines
    assert rep["p99_ms"] is not None and rep["p99_ms"] < 500.0
