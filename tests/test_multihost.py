"""Multi-host (multi-process) integration test.

SURVEY.md §2.9: the reference's distribution backend is Spark executors
over ethernet; ours is multi-process JAX — ICI within a slice, DCN (here:
Gloo over localhost TCP) across processes.  This launches TWO OS
processes, each owning 4 virtual CPU devices and feeding only its own
slice of the global batch, and asserts the sharded normal-equations
solve matches the exact full-data solve on both — the reference's
"distributed == exact local" golden pattern, across real process
boundaries.
"""

import os
import socket
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_solver_matches_exact():
    coordinator = f"127.0.0.1:{_free_port()}"
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        # the worker runs by path (script dir = tests/), so the repo root
        # must come from PYTHONPATH
        PYTHONPATH=cwd + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=cwd,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{err[-2000:]}"
        assert "MULTIHOST_OK" in out, f"missing OK marker:\n{out}\n{err[-1000:]}"


def test_two_process_sharded_store_fit_matches_exact(tmp_path):
    """Per-process-sharded FeatureBlockStore (pod out-of-core): each of
    two processes spills only its row slice; the swept fit must match
    the full-data in-memory fit."""
    coordinator = f"127.0.0.1:{_free_port()}"
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(os.path.dirname(__file__), "multihost_oc_worker.py")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=cwd + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, "2", str(pid), str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=cwd,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{err[-2000:]}"
        assert "MULTIHOST_OC_OK" in out
