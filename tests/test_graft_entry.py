"""Driver entry-point regression tests (8-device CPU mesh)."""

import os
import sys

import jax
import numpy as np
import pytest


def _load():
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import __graft_entry__ as g

    return g


def test_entry_compiles_and_runs():
    g = _load()
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[0].shape[0]
    assert np.isfinite(np.asarray(out)).all()


def test_dryrun_multichip_8():
    g = _load()
    g.dryrun_multichip(8)


def test_dryrun_multichip_odd():
    g = _load()
    g.dryrun_multichip(3)  # model_parallelism falls back to 1
