"""Out-of-core kernel solver tier (ISSUE 13): streamed gram-block BCD
parity with the in-core sweep, donation + tick flow-control pins,
prefetch plumbing, durable epoch checkpoints (corrupt-newest fallback,
kernel.sweep chaos), per-epoch telemetry, the Nyström tier's accuracy
gate, and the row-block store the whole tier rides."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.models.kernel_ridge import (
    GaussianKernelGenerator,
    KernelRidgeRegressionEstimator,
    OutOfCoreKernelBlockLinearMapper,
    _oc_krr_diag_step,
    _oc_krr_fit,
    _oc_krr_offdiag_step,
)
from keystone_tpu.workflow.blockstore import RowBlockStore
from keystone_tpu.workflow.dataset import Dataset, StreamDataset


def _problem(n=150, d=12, k=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    y = (x @ w + 0.01 * rng.normal(size=(n, k))).astype(np.float32)
    return x, y


def _est(bs=32, epochs=4, gamma=0.05, lam=1e-4):
    return KernelRidgeRegressionEstimator(
        GaussianKernelGenerator(gamma), lam=lam, block_size=bs,
        num_epochs=epochs,
    )


def _r2(a, b):
    return 1.0 - ((a - b) ** 2).sum() / ((b - b.mean(axis=0)) ** 2).sum()


# ------------------------------------------------ row-block store basics


def test_row_block_store_roundtrip(tmp_path):
    """Streaming batches of uneven sizes across block boundaries land
    row-exact; the final block zero-pads; reloads read through the same
    hardened path (sidecars written at finalize)."""
    x, _ = _problem(n=70, d=5)

    def batches():
        i = 0
        for m in (7, 20, 16, 3, 24):
            yield x[i : i + m]
            i += m

    st = RowBlockStore.from_batches(str(tmp_path / "s"), batches(), 70, 16)
    assert (st.num_blocks, st.n, st.d) == (5, 70, 5)
    rec = np.concatenate([st.read_block(b) for b in range(5)])[:70]
    np.testing.assert_array_equal(rec, x)
    assert not st.read_block(4)[6:].any()  # padding rows stay zero
    assert sorted(f for f in os.listdir(tmp_path / "s") if f.endswith(".b2"))
    # a torn block file is detected, not trusted
    from keystone_tpu.utils import durable

    path = st._block_path(st.directory, 2)
    with open(path, "r+b") as f:
        f.seek(200)
        f.write(b"\x11\x22\x33\x44")
    with pytest.raises(durable.CorruptStateError):
        RowBlockStore(str(tmp_path / "s")).read_block(2)


def test_row_store_rides_shared_device_feed(tmp_path):
    """RowBlockStore inherits the SAME iter_device_blocks machinery the
    feature store uses (one implementation, one flow-control contract)."""
    from keystone_tpu.workflow.blockstore import FeatureBlockStore

    assert (
        RowBlockStore.iter_device_blocks
        is FeatureBlockStore.iter_device_blocks
    )
    x, _ = _problem(n=64, d=6)
    st = RowBlockStore.from_array(str(tmp_path / "s"), x, 16)
    got = dict(st.iter_device_blocks([2, 0]))
    np.testing.assert_allclose(np.asarray(got[2]), x[32:48])


# ------------------------------------------------ in-core vs OC parity


def test_oc_kernel_fit_matches_incore(tmp_path):
    """The streamed gram-block sweep reproduces the in-core jitted
    sweep: same α (the per-tile gemm expansion is row-exact) and
    prediction r² ≥ 0.999 — the acceptance gate."""
    x, y = _problem()
    est = _est()
    ref = est.fit_arrays(x, y)
    store = RowBlockStore.from_array(str(tmp_path / "s"), x, 32)
    oc = est.fit_store(store, Dataset(jnp.asarray(y), n=x.shape[0]))
    np.testing.assert_allclose(
        np.asarray(oc.alpha), np.asarray(ref.alpha), atol=1e-5
    )
    xt = np.random.default_rng(9).normal(size=(40, x.shape[1])).astype(
        np.float32
    )
    p_ref = np.asarray(ref.apply_batch(jnp.asarray(xt)))
    p_oc = np.asarray(oc.apply_batch(jnp.asarray(xt)))
    assert _r2(p_oc, p_ref) >= 0.999


def test_oc_kernel_stream_dataset_path(tmp_path):
    """A StreamDataset routed through fit_dataset spills a row-block
    store that BACKS the fitted model (not deleted), and the mapper
    survives a pickle round trip (the store handle re-opens lazily)."""
    import pickle

    from keystone_tpu.loaders.stream import batched

    x, y = _problem(seed=4)
    est = _est(epochs=3)
    sd = StreamDataset(batched(x, 64), n=x.shape[0])
    oc = est.fit_dataset(sd, Dataset(y))
    assert isinstance(oc, OutOfCoreKernelBlockLinearMapper)
    assert os.path.isdir(oc.store_directory)  # the model's backing store
    ref = est.fit_arrays(x, y)
    xt = x[:16]
    p_ref = np.asarray(ref.apply_batch(jnp.asarray(xt)))
    p_oc = np.asarray(oc.apply_batch(jnp.asarray(xt)))
    assert _r2(p_oc, p_ref) >= 0.999
    clone = pickle.loads(pickle.dumps(oc))
    np.testing.assert_array_equal(
        np.asarray(clone.apply_batch(jnp.asarray(xt))), p_oc
    )


def test_host_stream_refused():
    est = _est()
    sd = StreamDataset([["a", "b"]], n=2, host=True)
    with pytest.raises(TypeError, match="host-payload"):
        est.fit_dataset(sd, Dataset(np.zeros((2, 1), np.float32)))


# ------------------------------------------------ donation + flow control


def test_oc_krr_steps_donate_carries():
    """The donation pins: the (F, α) carries are CONSUMED by the diag
    step and the F slice by the off-diag step; the staged row blocks
    are NOT (the diag block is reread by the whole F pass, streamed
    blocks free by refcount); the flow-control tick is NOT donated —
    it must stay waitable after the donated outputs feed later steps."""
    rng = np.random.default_rng(0)
    bs, d, k = 16, 8, 2
    xb = jnp.asarray(rng.normal(size=(bs, d)).astype(np.float32))
    yb = jnp.asarray(rng.normal(size=(bs, k)).astype(np.float32))
    fb = jnp.zeros((bs, k), jnp.float32)
    ab = jnp.zeros((bs, k), jnp.float32)
    ok = jnp.ones((bs,), jnp.float32)
    ab2, fb2, dab, tick = _oc_krr_diag_step(
        xb, fb, ab, yb, ok, jnp.float32(0.1), gamma=0.2
    )
    assert fb.is_deleted() and ab.is_deleted()
    assert not xb.is_deleted() and not yb.is_deleted()
    assert not tick.is_deleted()
    jax.block_until_ready(tick)

    xi = jnp.asarray(rng.normal(size=(bs, d)).astype(np.float32))
    fi = jnp.zeros((bs, k), jnp.float32)
    fi2, tick2 = _oc_krr_offdiag_step(fi, xi, xb, dab, ok, ok, gamma=0.2)
    assert fi.is_deleted()
    assert not xi.is_deleted() and not dab.is_deleted()
    assert not xb.is_deleted()
    assert not tick2.is_deleted()
    jax.block_until_ready(tick2)


# ------------------------------------------------ prefetch plumbing


def _row_prefetch_spy(monkeypatch):
    from keystone_tpu.workflow import blockstore as bs_mod

    seen = []
    orig = bs_mod.RowBlockStore.iter_blocks

    def spy(self, order, prefetch=2):
        seen.append(prefetch)
        return orig(self, order, prefetch=prefetch)

    monkeypatch.setattr(bs_mod.RowBlockStore, "iter_blocks", spy)
    return seen


def test_kernel_prefetch_plumbed_explicit(tmp_path, monkeypatch):
    """fit_store(prefetch=) reaches the sweep's iter_blocks."""
    seen = _row_prefetch_spy(monkeypatch)
    x, y = _problem(seed=5)
    est = _est(epochs=1)
    store = RowBlockStore.from_array(str(tmp_path / "s"), x, 32)
    est.fit_store(store, Dataset(y, n=x.shape[0]), prefetch=3)
    assert seen and all(p == 3 for p in seen), seen


def test_kernel_prefetch_env_and_bounds(tmp_path, monkeypatch):
    """The kernel paths ride the SAME [1, 64]-bounded resolution as
    _oc_bcd_fit: env override honored, garbage and out-of-range depths
    rejected with the variable named."""
    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", "4")
    seen = _row_prefetch_spy(monkeypatch)
    x, y = _problem(seed=6)
    store = RowBlockStore.from_array(str(tmp_path / "s"), x, 32)
    _est(epochs=1).fit_store(store, Dataset(y, n=x.shape[0]))
    assert seen and all(p == 4 for p in seen), seen

    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", "eight")
    with pytest.raises(ValueError, match="KEYSTONE_OC_PREFETCH"):
        _est(epochs=1).fit_store(store, Dataset(y, n=x.shape[0]))
    monkeypatch.delenv("KEYSTONE_OC_PREFETCH")
    with pytest.raises(ValueError, match="prefetch"):
        _est(epochs=1).fit_store(
            store, Dataset(y, n=x.shape[0]), prefetch=100
        )


# ------------------------------------- checkpoints + kernel.sweep chaos


def test_kernel_checkpoint_resume_bit_identical(tmp_path):
    """An injected crash at the kernel.sweep site mid-fit resumes from
    the last completed epoch and the final α bit-matches the
    uninterrupted fit; a corrupted NEWEST checkpoint falls back to the
    rotated last-good one, still bit-identically (the shared durable
    helper's contract)."""
    x, y = _problem(seed=7, n=96, d=8, k=2)
    est = _est(epochs=4)
    store = RowBlockStore.from_array(str(tmp_path / "s"), x, 32)
    labels = Dataset(jnp.asarray(y), n=x.shape[0])
    ref = est.fit_store(store, labels, checkpoint_dir=str(tmp_path / "c0"))

    ck = str(tmp_path / "ck")
    plan = faults.parse_plan("kernel.sweep:raise:after=7:times=1")
    with pytest.raises(faults.FaultInjected):
        with faults.inject(plan):
            est.fit_store(store, labels, checkpoint_dir=ck)
    # at least two epochs completed before the crash → rotation exists
    assert os.path.exists(os.path.join(ck, "krr_epoch.npz.1"))
    res = est.fit_store(store, labels, checkpoint_dir=ck)
    np.testing.assert_array_equal(np.asarray(res.alpha), np.asarray(ref.alpha))

    # corrupt the newest checkpoint: the resume scan must fall back
    with open(os.path.join(ck, "krr_epoch.npz"), "r+b") as f:
        f.seek(100)
        f.write(b"\xff\xff\xff\xff")
    res2 = est.fit_store(store, labels, checkpoint_dir=ck)
    np.testing.assert_array_equal(
        np.asarray(res2.alpha), np.asarray(ref.alpha)
    )


def test_kernel_checkpoint_rejects_different_problem(tmp_path):
    """The content-based fingerprint: a checkpoint from different data
    (or λ) must be ignored, not resumed into the wrong problem."""
    x, y = _problem(seed=8, n=96, d=8, k=2)
    est = _est(epochs=2)
    ck = str(tmp_path / "ck")
    store = RowBlockStore.from_array(str(tmp_path / "s1"), x, 32)
    est.fit_store(store, Dataset(y, n=x.shape[0]), checkpoint_dir=ck)

    x2 = x + 1.0
    store2 = RowBlockStore.from_array(str(tmp_path / "s2"), x2, 32)
    ref2 = est.fit_store(store2, Dataset(y, n=x.shape[0]))
    got2 = est.fit_store(
        store2, Dataset(y, n=x.shape[0]), checkpoint_dir=ck
    )
    np.testing.assert_array_equal(
        np.asarray(got2.alpha), np.asarray(ref2.alpha)
    )


def test_oc_sweep_survives_flaky_block_reads(tmp_path):
    """Chaos over the shared blockstore.read site: one transient read
    failure inside the gram-block stream is retried by the store's
    hardened read path — the sweep completes and matches."""
    x, y = _problem(seed=9)
    est = _est(epochs=2)
    store = RowBlockStore.from_array(str(tmp_path / "s"), x, 32)
    ref = est.fit_store(store, Dataset(y, n=x.shape[0]))
    def _injected():
        # faults.stats() is process-cumulative — delta, not absolute
        return faults.stats().get("blockstore.read", {}).get("injected", 0)

    before = _injected()
    plan = faults.parse_plan("blockstore.read:raise:after=5:times=2")
    with faults.inject(plan):
        got = est.fit_store(store, Dataset(y, n=x.shape[0]))
        injected = _injected() - before
    assert injected == 2
    np.testing.assert_array_equal(np.asarray(got.alpha), np.asarray(ref.alpha))


# ------------------------------------------------ telemetry


def _read_ledger_events(dirpath):
    runs = [f for f in os.listdir(dirpath) if f.startswith("run_")]
    events = []
    for r in runs:
        with open(os.path.join(dirpath, r), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    return events


@pytest.mark.obs
def test_kernel_solver_telemetry(tmp_path):
    """Per-epoch solver.epoch events for all three kernel sweeps —
    in-core (static obs flag + debug.callback), out-of-core (host
    loop), and the cached-block sweep (with cache_hits) — and the
    obs-off numerics stay bit-identical to the observed run."""
    from keystone_tpu.obs import ledger

    x, y = _problem(seed=10, n=96, d=8, k=2)
    est = _est(epochs=3)
    m0 = est.fit_arrays(x, y)  # inert
    store = RowBlockStore.from_array(str(tmp_path / "s"), x, 32)
    cached = KernelRidgeRegressionEstimator(
        GaussianKernelGenerator(0.05), lam=1e-4, block_size=32,
        num_epochs=3, cache_kernel_blocks=True,
    )

    obs_dir = str(tmp_path / "obs")
    ledger.start_run(obs_dir)
    try:
        m1 = est.fit_arrays(x, y)
        est.fit_store(store, Dataset(y, n=x.shape[0]))
        cached.fit_arrays(x, y)
        jax.effects_barrier()
    finally:
        ledger.stop_run()

    # observed vs inert: same bits (the flag only adds callbacks)
    np.testing.assert_array_equal(np.asarray(m0.alpha), np.asarray(m1.alpha))

    events = [
        e
        for e in _read_ledger_events(obs_dir)
        if e.get("kind") == "event" and e.get("name") == "solver.epoch"
    ]
    by_solver = {}
    for e in events:
        by_solver.setdefault(e["attrs"]["solver"], []).append(e["attrs"])
    assert len(by_solver.get("krr", [])) == 3  # in-core scan callbacks
    oc = by_solver.get("krr.out_of_core", [])
    assert len(oc) == 3
    assert all(
        a.get("epoch_seconds", 0) > 0 and "objective" in a for a in oc
    )
    ch = by_solver.get("krr.cached", [])
    assert len(ch) == 3
    # epoch 0 computes every column; epochs ≥ 2 reread from the cache
    assert ch[0]["cache_hits"] == 0 and ch[-1]["cache_hits"] > 0
    # the objective really converges epoch over epoch
    assert oc[-1]["objective"] <= oc[0]["objective"]


# ------------------------------------------------ Nyström tier


def test_nystrom_accuracy_gate_vs_exact_krr():
    """Nyström features + the existing linear block solver approximate
    the exact blockwise KRR predictions on a small problem (the
    accuracy gate), and the landmark draw is identical between the
    in-core and streamed fit paths on one seed."""
    from keystone_tpu.loaders.stream import batched
    from keystone_tpu.models.block_ls import BlockLeastSquaresEstimator
    from keystone_tpu.models.nystrom import NystromFeatures

    rng = np.random.default_rng(0)
    n, d, k = 400, 10, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    y = np.tanh(x @ w).astype(np.float32)
    kern = GaussianKernelGenerator(0.08)
    xt = rng.normal(size=(80, d)).astype(np.float32)

    exact = KernelRidgeRegressionEstimator(
        kern, lam=1e-4, block_size=100, num_epochs=20
    ).fit_arrays(x, y)
    p_exact = np.asarray(exact.apply_batch(jnp.asarray(xt)))

    nys = NystromFeatures(kern, num_landmarks=300, reg=1e-7, seed=0)
    fmap = nys.fit_arrays(x)
    lin = BlockLeastSquaresEstimator(
        block_size=128, num_iter=10, lam=1e-5, fit_intercept=False
    ).fit_arrays(fmap.apply_batch(jnp.asarray(x)), y)
    p_nys = np.asarray(
        lin.apply_batch(fmap.apply_batch(jnp.asarray(xt)))
    )
    # the gate: Nyström tracks the exact predictions closely AND its
    # held-out error stays within 1.5× the exact solver's
    assert _r2(p_nys, p_exact) >= 0.9
    yt = np.tanh(xt @ w).astype(np.float32)
    mse_exact = float(((p_exact - yt) ** 2).mean())
    mse_nys = float(((p_nys - yt) ** 2).mean())
    assert mse_nys <= 1.5 * mse_exact, (mse_nys, mse_exact)

    sd = StreamDataset(batched(x, 64), n=n)
    fmap2 = nys.fit_dataset(sd)
    np.testing.assert_array_equal(
        np.asarray(fmap2.landmarks), np.asarray(fmap.landmarks)
    )


def test_nystrom_whitening_reconstructs_kernel():
    """φ(L)·φ(L)ᵀ ≈ K_LL on the landmarks themselves — the defining
    Nyström identity the whitening solve must satisfy."""
    from keystone_tpu.models.nystrom import NystromFeatures

    rng = np.random.default_rng(2)
    x = rng.normal(size=(64, 6)).astype(np.float32)
    kern = GaussianKernelGenerator(0.1)
    fmap = NystromFeatures(kern, num_landmarks=64, reg=1e-7).fit_arrays(x)
    phi = np.asarray(fmap.apply_batch(fmap.landmarks))
    kmm = np.asarray(kern(fmap.landmarks, fmap.landmarks))
    np.testing.assert_allclose(phi @ phi.T, kmm, atol=5e-3)


def test_nystrom_stream_short_delivery_raises():
    from keystone_tpu.models.nystrom import NystromFeatures

    x = np.zeros((10, 4), np.float32)
    sd = StreamDataset([x[:5]], n=64)
    with pytest.raises(ValueError, match="landmarks"):
        NystromFeatures(GaussianKernelGenerator(0.1), 32).fit_dataset(sd)
