"""Replicated serving fleet (keystone_tpu/serve/fleet.py) + versioned
model registry with live hot-swap (serve/registry.py): router placement
and balance, breaker failover, blue/green swap under load, the registry
durability contract, the poll-watcher, and the fleet acceptance scenario
(N replicas out-serve one; a live swap drops nothing).

All tier-1 (seconds-scale, CPU): conftest forces 8 host-platform
devices, so multi-replica pools run in-process.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.obs import metrics
from keystone_tpu.ops.stats import NormalizeRows
from keystone_tpu.serve import (
    ModelRegistry,
    Overloaded,
    RegistryError,
    RegistryWatcher,
    serve,
)
from keystone_tpu.utils import durable
from keystone_tpu.workflow import Dataset, Pipeline

pytestmark = pytest.mark.serve

DIM = 6


def _pipeline(scale: float = 2.0) -> Pipeline:
    """NormalizeRows → eye*scale: every output row has norm ``scale``,
    so which model version served a row is readable off the result."""
    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * scale)
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


def _service(replicas: int, name: str, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("queue_bound", 256)
    kw.setdefault("example", np.zeros(DIM, np.float32))
    return serve(_pipeline(), replicas=replicas, name=name, **kw)


def _rows(k: int, seed: int = 0) -> np.ndarray:
    return (
        np.random.default_rng(seed).normal(size=(k, DIM)).astype(np.float32)
    )


def _row_scales(rows: np.ndarray) -> np.ndarray:
    """The model-version fingerprint: per-row output norms."""
    return np.linalg.norm(np.asarray(rows), axis=-1)


# ------------------------------------------------------------- registry
def test_registry_publish_load_roundtrip(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    assert v1 == "v0001"
    v2 = reg.publish(_pipeline(3.0))
    assert reg.versions() == ["v0001", "v0002"]
    assert reg.current() == v2
    fitted, ver = reg.load()
    assert ver == v2
    x = _rows(4)
    out = np.asarray(fitted(Dataset(x)).get().array)[:4]
    np.testing.assert_allclose(_row_scales(out), 3.0, rtol=1e-5)
    # strict path loads exactly the named version
    fitted1, ver1 = reg.load("v0001")
    assert ver1 == "v0001"
    out1 = np.asarray(fitted1(Dataset(x)).get().array)[:4]
    np.testing.assert_allclose(_row_scales(out1), 2.0, rtol=1e-5)


def test_registry_corrupt_newest_falls_back(tmp_path):
    """The deploy path (load(None)) degrades past a damaged newest
    version instead of taking the fleet down; the forensic path
    (explicit version) stays strict."""
    reg = ModelRegistry(str(tmp_path))
    reg.publish(_pipeline(2.0))
    reg.publish(_pipeline(3.0))
    with open(reg.model_path("v0002"), "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff\xff")
    before = metrics.REGISTRY.counter_value("serve.registry_fallback")
    fitted, ver = reg.load()
    assert ver == "v0001"
    assert metrics.REGISTRY.counter_value("serve.registry_fallback") > before
    with pytest.raises(durable.CorruptStateError):
        reg.load("v0002")


def test_registry_pointer_discipline(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    # blob-before-pointer: an un-current publish must not move CURRENT
    v2 = reg.publish(_pipeline(3.0), set_current=False)
    assert reg.current() == v1
    assert reg.versions() == [v1, v2]
    reg.set_current(v2)
    assert reg.current() == v2
    with pytest.raises(RegistryError, match="unpublished"):
        reg.set_current("v0099")
    with pytest.raises(RegistryError, match="v0001"):
        reg.publish(_pipeline(), version="not-a-version")


def test_registry_empty_raises(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    assert reg.current() is None
    assert reg.versions() == []
    with pytest.raises(RegistryError, match="no versions"):
        reg.load()


# ------------------------------------------------------------- routing
def test_pool_routes_across_all_replicas():
    """Under sustained load every replica serves, placement is one
    device per replica, and results are exactly the single-device ones."""
    x = _rows(64, seed=1)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)
    with _service(4, "fleet_route", max_wait_ms=1.0, queue_bound=1024) as svc:
        assert svc.replicas == 4
        futs = []
        for _ in range(8):  # 8 waves -> plenty of flushes to spread
            futs.extend(svc.submit_many(x))
        got = np.stack([f.result(timeout=60) for f in futs])
        np.testing.assert_allclose(got, np.tile(ref, (8, 1)), rtol=1e-5, atol=1e-6)
        statuses = svc.replica_statuses()
    devices = [s["device"] for s in statuses]
    assert len(set(devices)) == 4, devices
    assert all(s["flushes"] > 0 for s in statuses), statuses


def test_single_replica_is_direct_wrap():
    """replicas=1 with no devices is the PR-5 path bit-for-bit: the
    pool wraps the caller's applier directly — no clone, no placement."""
    from keystone_tpu.workflow.pipeline import FrozenApplier

    applier = FrozenApplier(_pipeline())
    svc = serve(
        applier,
        max_batch=8,
        example=np.zeros(DIM, np.float32),
        name="fleet_single",
    )
    try:
        rep = svc._pool.replicas[0]
        assert rep.device is None
        assert rep.applier is applier  # the very object, not a clone
    finally:
        svc.close()


def test_router_failover_when_breaker_opens():
    """An open replica breaker routes traffic AROUND that replica; the
    rest of the fleet absorbs it and every request still resolves."""
    x = _rows(8, seed=2)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)
    with _service(3, "fleet_failover", max_wait_ms=1.0) as svc:
        sick = svc._pool.replicas[0]
        while sick.breaker.state() != "open":
            sick.breaker.record_failure()
        for _ in range(6):  # sequential: router sees an idle fleet each time
            futs = svc.submit_many(x)
            got = np.stack([f.result(timeout=30) for f in futs])
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        statuses = svc.replica_statuses()
    assert statuses[0]["flushes"] == 0, statuses
    assert sum(s["flushes"] for s in statuses[1:]) >= 6


def test_all_breakers_open_fails_fast_then_probe_readmits():
    """Every breaker refusing = the fleet FAILS FAST (ISSUE 10): the
    batch's riders resolve with a typed ``FleetUnavailable`` (503 at
    HTTP) instead of being force-routed into the dead pool — and once a
    breaker's half-open window elapses, the probe re-admits traffic and
    the fleet recovers without an operator."""
    from keystone_tpu.serve import FleetUnavailable
    from keystone_tpu.utils import guard as _guard

    x = _rows(4, seed=3)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)
    with _service(2, "fleet_failfast", max_wait_ms=1.0) as svc:
        # short reset so the half-open probe is test-speed
        for rep in svc._pool.replicas:
            rep.breaker = _guard.CircuitBreaker(
                f"fleet_failfast.replica.{rep.index}", reset_timeout=0.3
            )
            while rep.breaker.state() != "open":
                rep.breaker.record_failure()
        futs = svc.submit_many(x)
        errs = [f.exception(timeout=30) for f in futs]
        assert all(isinstance(e, FleetUnavailable) for e in errs), errs
        # the health surface agrees while the fleet is down
        assert svc.available is False
        assert svc.status()["available"] is False
        # admission now refuses up front (the primed fast path)
        with pytest.raises(FleetUnavailable):
            svc.submit_many(x)
        # ... until the half-open window elapses: the probe is admitted
        # and a healthy apply closes the breaker — traffic flows again
        time.sleep(0.4)
        deadline = time.monotonic() + 30.0
        got = None
        while got is None and time.monotonic() < deadline:
            try:
                futs = svc.submit_many(x)
                got = np.stack([f.result(timeout=30) for f in futs])
            except FleetUnavailable:
                time.sleep(0.1)
        assert got is not None, "probe never re-admitted traffic"
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        assert svc.available is True


def test_replica_chaos_one_flush_fails_service_survives():
    """The ``serve.replica`` fault site: one injected flush failure
    fails only its own futures (typed, with the replica charged), and
    the fleet keeps serving."""
    x = _rows(4, seed=4)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)
    with _service(2, "fleet_chaos", max_wait_ms=1.0) as svc:
        with faults.inject("serve.replica:raise:times=1"):
            first = svc.submit_many(x)
            errs = [f.exception(timeout=30) for f in first]
        assert all(isinstance(e, faults.FaultInjected) for e in errs)
        futs = svc.submit_many(x)
        got = np.stack([f.result(timeout=30) for f in futs])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        statuses = svc.replica_statuses()
    assert sum(s["errors"] for s in statuses) == 1, statuses


# ------------------------------------------------------------ hot-swap
class _LoadGen:
    """Background open-ish-loop generator: submits rows continuously,
    collects every future, never drops one on the floor."""

    def __init__(self, svc, item: np.ndarray):
        self.svc = svc
        self.item = item
        self.futs: list = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            try:
                self.futs.append(self.svc.submit(self.item))
            except Overloaded:
                time.sleep(0.002)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def stop(self):
        self._stop.set()
        self._thread.join(10.0)

    def outcomes(self, timeout=60.0):
        """(ok_scales, exceptions) over every submitted future."""
        scales, excs = [], []
        for f in self.futs:
            e = f.exception(timeout=timeout)
            if e is not None:
                excs.append(e)
            else:
                scales.append(float(_row_scales(f.result())))
        return np.asarray(scales), excs


def test_swap_under_load_drops_nothing():
    """Blue/green swap while the load generator runs: zero failed or
    dropped futures, every result is consistently blue OR green (norm 2
    or 3 — never a torn mix), green serves after the commit, and the
    pause is bounded."""
    item = _rows(1, seed=5)[0]
    with _service(3, "fleet_swap", max_wait_ms=2.0) as svc:
        with _LoadGen(svc, item) as gen:
            time.sleep(0.25)
            info = svc.swap(_pipeline(3.0), version="green")
            time.sleep(0.25)
            gen.stop()
            scales, excs = gen.outcomes()
        assert not excs, excs[:3]
        assert len(scales) > 50  # the generator really ran
        blue = np.isclose(scales, 2.0, rtol=1e-4)
        green = np.isclose(scales, 3.0, rtol=1e-4)
        assert np.all(blue | green)
        assert green.any(), "no request ever saw the new version"
        # the LAST submitted request must be green: the swap committed
        tail = svc.submit(item).result(timeout=30)
        np.testing.assert_allclose(_row_scales(tail), 3.0, rtol=1e-5)
        assert svc.version == "green"
        assert info["replicas"] == 3
        # commit is a pointer swap under the router lock — far under
        # one flush interval even on a loaded CI box
        assert info["pause_seconds"] < svc.max_wait_s + 0.05
        statuses = svc.replica_statuses()
        assert all(s["version"] == "green" for s in statuses)


def test_swap_fault_leaves_old_generation_serving():
    """A failed stage (the ``serve.swap`` site) must be a no-op for the
    fleet: the old version keeps serving untouched."""
    item = _rows(1, seed=6)[0]
    with _service(2, "fleet_swapfault", max_wait_ms=1.0) as svc:
        with faults.inject("serve.swap:raise"):
            with pytest.raises(faults.FaultInjected):
                svc.swap(_pipeline(3.0), version="doomed")
        assert svc.version == "v0"
        out = svc.submit(item).result(timeout=30)
        np.testing.assert_allclose(_row_scales(out), 2.0, rtol=1e-5)


def test_watcher_hot_swaps_on_publish(tmp_path):
    """The CLI's --watch loop: a registry publish becomes a live swap;
    requests riding through it never fail."""
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    item = _rows(1, seed=7)[0]
    svc = _service(2, "fleet_watch", version=v1, max_wait_ms=2.0)
    watcher = RegistryWatcher(svc, reg, poll_seconds=0.05).start()
    try:
        with _LoadGen(svc, item) as gen:
            time.sleep(0.15)
            reg.publish(_pipeline(3.0))
            deadline = time.monotonic() + 30
            while svc.version != "v0002" and time.monotonic() < deadline:
                time.sleep(0.05)
            assert svc.version == "v0002"
            gen.stop()
            scales, excs = gen.outcomes()
        assert not excs, excs[:3]
        assert np.isclose(scales, 3.0, rtol=1e-4).any()
    finally:
        watcher.stop()
        svc.close()


def test_watcher_survives_bad_publish(tmp_path):
    """A corrupt publish is logged-and-counted, never fatal: the fleet
    keeps serving its good version, and a later good publish swaps in."""
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    svc = _service(1, "fleet_watchbad", version=v1)
    watcher = RegistryWatcher(svc, reg, poll_seconds=0.05)
    try:
        v2 = reg.publish(_pipeline(3.0))
        with open(reg.model_path(v2), "r+b") as f:
            f.seek(10)
            f.write(b"\xff\xff\xff\xff")
        before = metrics.REGISTRY.counter_value("serve.watch_errors")
        watcher.start()
        deadline = time.monotonic() + 30
        while (
            metrics.REGISTRY.counter_value("serve.watch_errors") == before
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert metrics.REGISTRY.counter_value("serve.watch_errors") > before
        assert svc.version == v1  # still serving the good version
        # repair: a good publish (v0003) swaps in
        reg.publish(_pipeline(4.0))
        deadline = time.monotonic() + 30
        while svc.version != "v0003" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert svc.version == "v0003"
    finally:
        watcher.stop()
        svc.close()


# ----------------------------------------------------------- retry hint
def test_retry_after_hint_tracks_ewma_and_fleet_size():
    with _service(2, "fleet_hint") as svc:
        svc._ewma_batch_s = 0.0
        assert svc.retry_after_hint() == 1.0  # no samples yet: fallback
        svc._ewma_batch_s = 2.0
        # empty queue: one flush, spread over 2 replicas
        assert svc.retry_after_hint() == pytest.approx(1.0)


# ------------------------------------------------------------ http admin
def test_http_fleet_endpoints(tmp_path):
    """/healthz grows the fleet view (version + per-replica status),
    /replicas exposes it alone, and POST /swap drives a registry-backed
    blue/green swap (404 unknown version, 409 with no registry)."""
    import json
    import urllib.error
    import urllib.request

    from keystone_tpu.serve import serve_http

    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    v2 = reg.publish(_pipeline(3.0), set_current=False)
    with _service(2, "fleet_http", version=v1) as svc:
        with serve_http(svc, port=0, registry=reg) as front:
            base = f"http://127.0.0.1:{front.port}"
            health = json.load(urllib.request.urlopen(base + "/healthz", timeout=10))
            assert health["version"] == v1
            assert len(health["replicas"]) == 2
            for rs in health["replicas"]:
                assert {"replica", "version", "breaker", "outstanding"} <= set(rs)
                assert rs["breaker"] == "closed"
            reps = json.load(urllib.request.urlopen(base + "/replicas", timeout=10))
            assert [r["replica"] for r in reps["replicas"]] == [0, 1]

            req = urllib.request.Request(
                base + "/swap", data=json.dumps({"version": v2}).encode()
            )
            info = json.load(urllib.request.urlopen(req, timeout=60))
            assert info["version"] == v2 and info["replicas"] == 2
            assert svc.version == v2
            out = svc.submit(_rows(1, seed=9)[0]).result(timeout=30)
            np.testing.assert_allclose(_row_scales(out), 3.0, rtol=1e-5)

            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    urllib.request.Request(
                        base + "/swap",
                        data=json.dumps({"version": "v9999"}).encode(),
                    ),
                    timeout=10,
                )
            assert err.value.code == 404
    # no registry attached: the admin endpoint refuses, typed
    with _service(1, "fleet_http_noreg") as svc:
        with serve_http(svc, port=0) as front:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{front.port}/swap", data=b"{}"
                    ),
                    timeout=10,
                )
            assert err.value.code == 409


def test_http_429_retry_after_is_derived():
    """The 429 Retry-After header comes from the EWMA flush-completion
    estimate (ceiled delta-seconds; the exact float rides the body) —
    not the old hard-coded 1."""
    import json
    import urllib.error
    import urllib.request

    from keystone_tpu.serve import serve_http

    svc = serve(
        _pipeline(),
        max_batch=1,
        max_wait_ms=5.0,
        queue_bound=2,
        example=np.zeros(DIM, np.float32),
        name="fleet_429",
    )
    try:
        svc._ewma_batch_s = 5.0  # as if flushes were observed slow
        with serve_http(svc, port=0) as front:
            base = f"http://127.0.0.1:{front.port}"
            item = _rows(1, seed=10)[0]
            with faults.inject("serve.batch:delay=0.5"):
                # fill admission to the bound AND let the batcher pull
                # its dispatch window first (the sleep), so the queue
                # stays at bound for the ~0.5 s flush the HTTP request
                # lands inside
                filled = False
                for _ in range(50):
                    try:
                        svc.submit(item)
                    except Overloaded:
                        filled = True
                        break
                    time.sleep(0.01)
                assert filled
                req = urllib.request.Request(
                    base + "/predict",
                    data=json.dumps({"instance": item.tolist()}).encode(),
                )
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 429
            retry_after = int(err.value.headers["Retry-After"])
            body = json.loads(err.value.read())
            assert retry_after >= 2  # ceil(EWMA-derived), not the old "1"
            assert body["retry_after_seconds"] > 1.0
    finally:
        svc.close()


# ---------------------------------------------------------- acceptance
def test_fleet_acceptance_scaling_and_live_swap():
    """The ISSUE-8 acceptance scenario on the forced-multi-device host:
    with an emulated-heavy model (flush time dominated by an injected
    stall, as in the bench fleet leg), a 4-replica fleet completes more
    requests than 1 replica over the same offered window, and a live
    blue/green swap during the fleet run drops zero requests with a
    bounded pause."""
    assert len(jax.local_devices()) >= 4
    item = _rows(1, seed=8)[0]

    def run(replicas: int, do_swap: bool):
        svc = _service(
            replicas,
            f"fleet_acc{replicas}",
            max_batch=16,
            max_wait_ms=2.0,
            queue_bound=128,
        )
        info = {}
        try:
            with faults.inject("serve.batch:delay=0.02"):
                with _LoadGen(svc, item) as gen:
                    time.sleep(0.6)
                    if do_swap:
                        info = svc.swap(_pipeline(3.0), version="green")
                    time.sleep(0.6)
                    gen.stop()
                    scales, excs = gen.outcomes()
        finally:
            svc.close()
        return scales, excs, info

    single_scales, single_excs, _ = run(1, do_swap=False)
    fleet_scales, fleet_excs, info = run(4, do_swap=True)
    assert not single_excs and not fleet_excs
    # scaling: the stall-dominated flushes overlap across replicas, so
    # the fleet must complete materially more in the same window (the
    # margin is conservative: CI boxes are 2-core and GIL-bound)
    assert len(fleet_scales) > 1.5 * len(single_scales), (
        len(fleet_scales),
        len(single_scales),
    )
    # the live swap: nothing dropped (asserted above), both versions
    # served, pause far under one flush interval (2 ms wait + 20 ms stall)
    assert np.isclose(fleet_scales, 2.0, rtol=1e-4).any()
    assert np.isclose(fleet_scales, 3.0, rtol=1e-4).any()
    assert np.all(
        np.isclose(fleet_scales, 2.0, rtol=1e-4)
        | np.isclose(fleet_scales, 3.0, rtol=1e-4)
    )
    assert info["pause_seconds"] < 0.022
