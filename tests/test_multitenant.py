"""Multi-tenant serving + the cross-pipeline shared stage pool (ISSUE 14).

Pins: pool eviction under budget pressure, per-entry refcounts across
tenants, the signature-collision admission gate (two same-signature
different-state stages are NEVER cross-shared), single-tenant-with-pool
byte identity vs the pre-pool path, shared-vs-unshared bit identity,
DRR fair-share flush forming, per-tenant quota/fault blast-radius
isolation, and the tenant surfaces (HTTP routing, /statusz)."""

import json
import urllib.request

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from keystone_tpu import faults
from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.ops.stats import (
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
)
from keystone_tpu.serve import (
    Overloaded,
    PipelineService,
    UnknownTenant,
    serve,
    serve_multi,
)
from keystone_tpu.workflow import Pipeline
from keystone_tpu.workflow.cross import plan_sharing
from keystone_tpu.workflow.stage_pool import SharedStagePool
from keystone_tpu.workflow.transformer import Transformer

DIM = 16


def _head_weights(classes, seed):
    rng = np.random.default_rng(seed)
    padded = 1 << (DIM - 1).bit_length()
    feat_dim = 2 * (padded // 2 + 1) * 2
    return jnp.asarray(rng.normal(size=(feat_dim, classes)).astype(np.float32))


def _tenant_pipeline(seed, classes=4):
    """A pipeline with a DETERMINISTIC shared featurization prefix
    (same branch seeds for every tenant) and a per-tenant head."""
    feat = Pipeline.gather(
        [
            RandomSignNode.init(DIM, 1000 + i)
            | PaddedFFT()
            | LinearRectifier(0.0, alpha=0.01 * (i + 1))
            for i in range(2)
        ]
    )
    return feat | NormalizeRows() | LinearMapper(_head_weights(classes, seed))


def _mk(models, pool=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 2.0)
    kw.setdefault("queue_bound", 64)
    kw.setdefault("example", np.zeros((DIM,), np.float32))
    return serve_multi(models, pool=pool, **kw)


# ------------------------------------------------------------------- pool
def test_pool_eviction_under_budget_pressure():
    pool = SharedStagePool(budget_bytes=100)
    tok = "t1"
    pool.begin_flush(tok, {"A": 2, "B": 2})
    assert pool.put(("A", tok), "va", nbytes=60)
    assert pool.put(("B", tok), "vb", nbytes=60)  # evicts A (LRU)
    hit, _ = pool.get(("A", tok))
    assert not hit, "evicted entry must miss (recompute, never wrong)"
    hit, v = pool.get(("B", tok))
    assert hit and v == "vb"
    st = pool.stats()
    assert st["evictions"] >= 1
    pool.end_flush(tok)
    assert pool.stats()["entries"] == 0


def test_pool_oversized_entry_never_resident():
    pool = SharedStagePool(budget_bytes=100)
    pool.begin_flush("t", {"A": 2})
    assert not pool.put(("A", "t"), "v", nbytes=1000)
    assert pool.stats()["resident_bytes"] == 0


def test_pool_refcount_frees_at_zero():
    """Per-entry refcounts across tenants: the entry is freed the
    moment its LAST declared consumer reads it — HBM returns early,
    not at flush end."""
    pool = SharedStagePool(budget_bytes=1 << 20)
    tok = 9
    pool.begin_flush(tok, {"S": 3})  # producer + 2 readers
    assert pool.put(("S", tok), "val", nbytes=10)
    assert pool.stats()["entries"] == 1
    hit, _ = pool.get(("S", tok))
    assert hit and pool.stats()["entries"] == 1  # one reader left
    hit, _ = pool.get(("S", tok))
    assert hit and pool.stats()["entries"] == 0  # last reader freed it
    hit, _ = pool.get(("S", tok))
    assert not hit


def test_pool_single_consumer_sig_not_stored():
    pool = SharedStagePool(budget_bytes=1 << 20)
    pool.begin_flush("t", {"S": 1})
    assert not pool.put(("S", "t"), "v", nbytes=10)
    assert pool.stats()["entries"] == 0


def test_pool_tokens_isolate_flushes():
    """Entries can never leak across flush tokens (different request
    batches)."""
    pool = SharedStagePool(budget_bytes=1 << 20)
    pool.begin_flush("t1", {"S": 2})
    pool.put(("S", "t1"), "flush1", nbytes=8)
    pool.begin_flush("t2", {"S": 2})
    hit, _ = pool.get(("S", "t2"))
    assert not hit
    pool.end_flush("t1")
    pool.end_flush("t2")


def test_pool_registered_tenant_entries_evict_last():
    pool = SharedStagePool(budget_bytes=100)
    pool.register_tenant("a", ["KEEP"])
    tok = "t"
    pool.begin_flush(tok, {"KEEP": 2, "DROP": 2})
    assert pool.put(("KEEP", tok), "k", nbytes=50)
    assert pool.put(("DROP", tok), "d", nbytes=50)
    # a third entry forces eviction: the unregistered sig goes first
    pool.begin_flush("t2", {"X": 2})
    assert pool.put(("X", "t2"), "x", nbytes=50)
    hit, _ = pool.get(("KEEP", tok))
    assert hit, "registered-tenant entry should outlive unregistered one"
    pool.unregister_tenant("a")
    assert pool.sig_refcount("KEEP") == 0


# ---------------------------------------------------------- sharing plan
def test_plan_sharing_detects_shared_prefix():
    a = _tenant_pipeline(1).freeze()
    b = _tenant_pipeline(2).freeze()
    plan = plan_sharing({"a": a.graph, "b": b.graph})
    assert plan.shared, "equal featurization prefixes must be planned shared"
    assert plan.refused == 0
    for sig in plan.shared:
        assert plan.consumers[sig] == 2
    # per-flush consumer counts restrict to the flush's tenants
    assert plan.sigs_for(["a", "b"])
    assert plan.sigs_for(["a"]) == {}


def test_plan_sharing_single_tenant_empty():
    a = _tenant_pipeline(1).freeze()
    plan = plan_sharing({"a": a.graph})
    assert not plan.shared and plan.node_sigs["a"] == {}


class _LeakyStage(Transformer):
    """Deliberately under-specified identity: params() omits ``scale``,
    so two observably different instances report EQUAL signatures —
    the exact bug class the collision gate exists to refuse."""

    def __init__(self, scale):
        self.scale = float(scale)

    def params(self):
        return ("leaky",)

    def apply_one(self, x):
        return x * self.scale

    def apply_batch(self, xs, mask=None):
        return xs * self.scale


def test_collision_gate_refuses_unsafe_share():
    a = Pipeline.of(_LeakyStage(2.0)).freeze()
    b = Pipeline.of(_LeakyStage(3.0)).freeze()
    plan = plan_sharing({"a": a.graph, "b": b.graph})
    assert plan.refused >= 1, "colliding signatures must be refused"
    assert not plan.shared
    # end to end: served co-tenant predictions stay tenant-correct
    svc = _mk(
        {"a": Pipeline.of(_LeakyStage(2.0)), "b": Pipeline.of(_LeakyStage(3.0))},
        pool=SharedStagePool(budget_bytes=1 << 20),
    )
    try:
        x = np.full((DIM,), 1.0, np.float32)
        ya = svc.submit(x, tenant="a").result(10)
        yb = svc.submit(x, tenant="b").result(10)
        np.testing.assert_array_equal(ya, x * 2.0)
        np.testing.assert_array_equal(yb, x * 3.0)
        assert svc.status()["stage_pool"]["collision_refusals"] >= 1
    finally:
        svc.close()


# ---------------------------------------------------------- byte identity
def test_single_tenant_with_pool_byte_identical_to_pre_pool():
    """The acceptance pin: single-tenant serving WITH the pool equals
    the pre-pool PipelineService path bit for bit."""
    pipe = _tenant_pipeline(7)
    pool = SharedStagePool(budget_bytes=1 << 24)
    multi = _mk({"only": pipe}, pool=pool)
    plain = serve(
        _tenant_pipeline(7),
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=64,
        example=np.zeros((DIM,), np.float32),
    )
    try:
        rng = np.random.default_rng(3)
        for _ in range(3):
            x = rng.normal(size=(DIM,)).astype(np.float32)
            ym = multi.submit(x).result(10)  # single tenant: no label needed
            yp = plain.submit(x).result(10)
            assert np.array_equal(ym, yp)
        st = multi.status()["stage_pool"]
        assert st["shared_stages"] == 0
        assert st["hits"] == 0 and st["misses"] == 0
    finally:
        multi.close()
        plain.close()


def test_shared_vs_unshared_bit_identical_and_pool_hits():
    models = lambda: {"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)}  # noqa: E731
    pool = SharedStagePool(budget_bytes=1 << 24)
    shared = _mk(models(), pool=pool)
    unshared = _mk(models(), share=False)
    try:
        rng = np.random.default_rng(5)
        x = rng.normal(size=(DIM,)).astype(np.float32)
        for t in ("a", "b"):
            ys = shared.submit(x, tenant=t).result(10)
            yu = unshared.submit(x, tenant=t).result(10)
            assert np.array_equal(ys, yu), f"tenant {t} diverged shared-vs-unshared"
        # the prefix actually pooled: priming + the live flushes hit
        assert pool.stats()["hits"] >= 1
        assert unshared.status()["stage_pool"]["sharing"] is False
    finally:
        shared.close()
        unshared.close()


def test_shared_prefix_computed_once_per_combined_flush():
    """Submit one co-tenant pair in a single flush window; the second
    tenant's walk must HIT the pool (shared prefix computed once)."""
    pool = SharedStagePool(budget_bytes=1 << 24)
    svc = _mk(
        {"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)},
        pool=pool,
        max_wait_ms=50.0,
    )
    try:
        h0 = pool.stats()["hits"]
        x = np.random.default_rng(0).normal(size=(DIM,)).astype(np.float32)
        fa = svc.submit(x, tenant="a")
        fb = svc.submit(x, tenant="b")
        fa.result(10)
        fb.result(10)
        assert pool.stats()["hits"] > h0
    finally:
        svc.close()


# ------------------------------------------------------------- scheduling
def test_drr_pop_forms_fair_mixed_flushes():
    svc = _mk({"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)})
    try:
        from keystone_tpu.serve.service import _Request

        with svc._cond:
            for i in range(20):
                svc._tq["a"].append(_Request(np.zeros((DIM,)), None, tenant="a"))
            for i in range(20):
                svc._tq["b"].append(_Request(np.zeros((DIM,)), None, tenant="b"))
            batch = svc._drr_pop_locked()
        counts = {"a": 0, "b": 0}
        for r in batch:
            counts[r.tenant] += 1
        assert len(batch) == svc.max_batch
        assert counts["a"] == counts["b"] == svc.max_batch // 2
        # tenant-contiguous ordering (the segment contract)
        tenants_seq = [r.tenant for r in batch]
        assert tenants_seq == sorted(tenants_seq) or tenants_seq == sorted(
            tenants_seq, reverse=True
        )
        # repeated pops stay fair — no banked-credit monopoly
        with svc._cond:
            batch2 = svc._drr_pop_locked()
        c2 = {"a": 0, "b": 0}
        for r in batch2:
            c2[r.tenant] += 1
        assert abs(c2["a"] - c2["b"]) <= 1
        for b in (batch, batch2):
            for r in b:
                r.future.cancel()
    finally:
        svc.close(drain=False)


def test_tenant_quota_overload_is_isolated():
    svc = _mk(
        {"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)},
        tenant_queue_bound={"a": 2, "b": 32},
        max_wait_ms=200.0,  # keep requests queued while we overfill
        max_batch=64,
    )
    try:
        x = np.zeros((DIM,), np.float32)
        futs = [svc.submit(x, tenant="a") for _ in range(2)]
        with pytest.raises(Overloaded):
            svc.submit(x, tenant="a")
        # tenant b is untouched by a's full quota
        fb = svc.submit(x, tenant="b")
        assert fb.result(10) is not None
        for f in futs:
            f.result(10)
    finally:
        svc.close()


def test_unknown_and_missing_tenant_rejected():
    svc = _mk({"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)})
    try:
        x = np.zeros((DIM,), np.float32)
        with pytest.raises(UnknownTenant):
            svc.submit(x, tenant="nope")
        with pytest.raises(UnknownTenant):
            svc.submit(x)  # ambiguous with 2 tenants
    finally:
        svc.close()


def test_single_tenant_service_refuses_tenant_kwarg():
    plain = serve(
        _tenant_pipeline(1),
        max_batch=4,
        example=np.zeros((DIM,), np.float32),
    )
    try:
        with pytest.raises(TypeError):
            plain.submit(np.zeros((DIM,), np.float32), tenant="a")
    finally:
        plain.close()


# ---------------------------------------------------------- blast radius
def test_tenant_targeted_enqueue_fault_isolated():
    svc = _mk({"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)})
    try:
        x = np.zeros((DIM,), np.float32)
        with faults.inject("serve.enqueue:ctx.tenant=a:raise"):
            with pytest.raises(faults.FaultInjected):
                svc.submit(x, tenant="a")
            yb = svc.submit(x, tenant="b").result(10)
            assert np.all(np.isfinite(yb))
    finally:
        svc.close()


def test_tenant_targeted_batch_fault_contained_to_tenant():
    """A serve.batch fault matched to ctx.tenant=a fails a's riders in
    the combined flush; b's riders in the SAME flush deliver."""
    svc = _mk(
        {"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)},
        max_wait_ms=50.0,
    )
    try:
        x = np.random.default_rng(1).normal(size=(DIM,)).astype(np.float32)
        with faults.inject("serve.batch:ctx.tenant=a:raise:times=1"):
            fa = svc.submit(x, tenant="a")
            fb = svc.submit(x, tenant="b")
            yb = fb.result(15)
            assert np.all(np.isfinite(yb))
            with pytest.raises(Exception):
                fa.result(15)
    finally:
        svc.close()


def test_tenant_breaker_opens_for_failing_tenant_only():
    svc = _mk(
        {"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)},
        tenant_breaker_threshold=2,
        max_wait_ms=5.0,
    )
    try:
        from keystone_tpu.utils import guard

        x = np.random.default_rng(1).normal(size=(DIM,)).astype(np.float32)
        with faults.inject("serve.batch:ctx.tenant=a:raise"):
            failures = 0
            for _ in range(6):
                try:
                    svc.submit(x, tenant="a").result(15)
                except guard.CircuitOpenError:
                    break
                except Exception:
                    failures += 1
            else:
                pytest.fail("tenant a's breaker never opened")
            assert failures >= 2
            # tenant b admits and serves throughout
            yb = svc.submit(x, tenant="b").result(15)
            assert np.all(np.isfinite(yb))
    finally:
        svc.close()


# --------------------------------------------------------------- surfaces
def test_statusz_tenants_and_pool_sections():
    svc = _mk({"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)})
    try:
        x = np.zeros((DIM,), np.float32)
        svc.submit(x, tenant="a").result(10)
        st = svc.status()
        assert set(st["tenants"]) == {"a", "b"}
        ta = st["tenants"]["a"]
        assert ta["counters"]["submitted"] >= 1
        assert ta["counters"]["completed"] >= 1
        assert "latency_ms" in ta and "quota" in ta
        sp = st["stage_pool"]
        assert {"hits", "misses", "shared_stages", "collision_refusals"} <= set(sp)
    finally:
        svc.close()


def test_http_tenant_routing():
    from keystone_tpu.serve import serve_http

    svc = _mk({"a": _tenant_pipeline(1), "b": _tenant_pipeline(2, classes=6)})
    front = serve_http(svc, port=0)
    base = f"http://127.0.0.1:{front.port}"
    try:
        def post(body):
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        x = [0.5] * DIM
        code, body = post({"instance": x, "tenant": "b"})
        assert code == 200 and len(body["predictions"][0]) == 6
        code, body = post({"instance": x, "tenant": "nope"})
        assert code == 400
        code, body = post({"instance": x})  # ambiguous
        assert code == 400
        # /statusz carries the tenant + pool sections
        with urllib.request.urlopen(base + "/statusz", timeout=30) as r:
            st = json.loads(r.read())
        assert set(st["tenants"]) == {"a", "b"}
        assert "stage_pool" in st
    finally:
        front.stop()
        svc.close()


def test_replicated_multi_tenant_serving():
    """The applier clones per replica (graphs() placement path), the
    pool keys stay content+token addressed across clones, AND a
    PRIVATE pool survives the clone's pickle round-trip (re-resolved
    by token — a clone falling back to the default pool would leave
    the configured budget/stats blind to live traffic)."""
    pool = SharedStagePool(budget_bytes=1 << 24)
    svc = _mk(
        {"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)},
        pool=pool,
        replicas=2,
    )
    try:
        x = np.random.default_rng(2).normal(size=(DIM,)).astype(np.float32)
        outs = [
            (
                svc.submit(x, tenant="a").result(15),
                svc.submit(x, tenant="b").result(15),
            )
            for _ in range(4)
        ]
        for ya, yb in outs[1:]:
            assert np.array_equal(ya, outs[0][0])
            assert np.array_equal(yb, outs[0][1])
        # the replica clones' flush walks hit THIS pool, not the
        # process default (the token re-resolution contract)
        assert pool.stats()["hits"] >= 1
    finally:
        svc.close()


def test_tenant_breaker_refusal_counts_as_rejected():
    """A tenant-breaker refusal is backpressure (HTTP 429): traced and
    counted as rejected, never as a tenant error."""
    from keystone_tpu.obs import metrics as _metrics
    from keystone_tpu.utils import guard

    svc = _mk(
        {"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)},
        tenant_breaker_threshold=1,
    )
    try:
        x = np.random.default_rng(1).normal(size=(DIM,)).astype(np.float32)
        with faults.inject("serve.batch:ctx.tenant=a:raise"):
            with pytest.raises(Exception):
                svc.submit(x, tenant="a").result(15)  # opens the breaker
            errs0 = _metrics.REGISTRY.counter_value(
                "serve.tenant_errors", tenant="a"
            )
            rej0 = _metrics.REGISTRY.counter_value(
                "serve.tenant_rejected", tenant="a"
            )
            with pytest.raises(guard.CircuitOpenError):
                svc.submit(x, tenant="a")
        assert (
            _metrics.REGISTRY.counter_value(
                "serve.tenant_rejected", tenant="a"
            )
            == rej0 + 1
        )
        assert (
            _metrics.REGISTRY.counter_value("serve.tenant_errors", tenant="a")
            == errs0
        )
    finally:
        svc.close()


# ------------------------------------------------------------- dedup
def test_dedup_identical_inflight_payloads_computed_once():
    from keystone_tpu.obs import metrics as _metrics

    """With dedup=True, identical concurrent payloads for the SAME
    tenant ride one computation: followers occupy no queue slot, count
    as serve.dedup_hits, and resolve bit-identically."""
    svc = _mk(
        {"a": _tenant_pipeline(1)},
        dedup=True,
        max_wait_ms=25.0,  # hold the flush open so followers pile up
    )
    try:
        x = np.random.default_rng(2).normal(size=(DIM,)).astype(np.float32)
        h0 = _metrics.REGISTRY.counter_total("serve.dedup_hits")
        futs = [svc.submit(x, tenant="a") for _ in range(6)]
        outs = [np.asarray(f.result(30)) for f in futs]
        for o in outs[1:]:
            assert o.tobytes() == outs[0].tobytes()
        hits = _metrics.REGISTRY.counter_total("serve.dedup_hits") - h0
        assert hits >= 4, hits
        # followers get an OWNING copy: mutating one response cannot
        # corrupt a co-rider's
        outs[1][:] = 0
        assert outs[2].tobytes() == outs[0].tobytes()
    finally:
        svc.close()


def test_dedup_never_crosses_tenants():
    """The same payload for two tenants runs two different models —
    dedup keys are (tenant, content), so results differ and no
    cross-tenant hit is counted."""
    svc = _mk(
        {"a": _tenant_pipeline(1), "b": _tenant_pipeline(2)},
        dedup=True,
        max_wait_ms=25.0,
    )
    try:
        x = np.random.default_rng(3).normal(size=(DIM,)).astype(np.float32)
        fa = svc.submit(x, tenant="a")
        fb = svc.submit(x, tenant="b")
        ya, yb = np.asarray(fa.result(30)), np.asarray(fb.result(30))
        assert ya.tobytes() != yb.tobytes()
    finally:
        svc.close()


def test_dedup_off_by_default():
    from keystone_tpu.obs import metrics as _metrics

    svc = _mk({"a": _tenant_pipeline(1)}, max_wait_ms=10.0)
    try:
        x = np.random.default_rng(4).normal(size=(DIM,)).astype(np.float32)
        h0 = _metrics.REGISTRY.counter_total("serve.dedup_hits")
        futs = [svc.submit(x, tenant="a") for _ in range(4)]
        outs = [np.asarray(f.result(30)) for f in futs]
        for o in outs[1:]:
            assert o.tobytes() == outs[0].tobytes()  # same math regardless
        assert (
            _metrics.REGISTRY.counter_total("serve.dedup_hits") - h0 == 0
        )
    finally:
        svc.close()


def test_dedup_map_drains_after_resolution():
    """The in-flight map is bounded by construction: entries leave when
    their leader resolves, so a long-running service cannot leak."""
    import time

    svc = _mk({"a": _tenant_pipeline(1)}, dedup=True)
    try:
        xs = np.random.default_rng(5).normal(size=(8, DIM)).astype(np.float32)
        futs = [svc.submit(xs[i], tenant="a") for i in range(8)]
        for f in futs:
            f.result(30)
        deadline = time.monotonic() + 5.0
        while svc._dedup_inflight and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not svc._dedup_inflight
    finally:
        svc.close()
