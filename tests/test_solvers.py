"""Solver equivalence tests.

The reference's key correctness pattern (SURVEY.md §4): the distributed
block solver must match an exact local solve on the same synthetic data
(BlockLinearMapperSuite.scala, BlockWeightedLeastSquaresSuite.scala,
LBFGSSuite.scala, KernelModelSuite.scala).  Here "distributed" means
sharded over the virtual 8-device CPU mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.models import (
    BlockLeastSquaresEstimator,
    BlockWeightedLeastSquaresEstimator,
    DenseLBFGSwithL2,
    DistributedPCAEstimator,
    GaussianKernelGenerator,
    GaussianMixtureModelEstimator,
    KernelRidgeRegressionEstimator,
    KMeansPlusPlusEstimator,
    LinearMapEstimator,
    LocalLeastSquaresEstimator,
    LogisticRegressionEstimator,
    NaiveBayesEstimator,
    PCAEstimator,
    ZCAWhitenerEstimator,
)
from keystone_tpu.workflow import Dataset


def _ridge_exact(x, y, lam_n, center=True):
    """Closed-form (centered) ridge in float64."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    if center:
        xm, ym = x.mean(0), y.mean(0)
        xc, yc = x - xm, y - ym
    else:
        xm = ym = None
        xc, yc = x, y
    w = np.linalg.solve(xc.T @ xc + lam_n * np.eye(x.shape[1]), xc.T @ yc)
    b = ym - xm @ w if center else np.zeros(y.shape[1])
    return w, b


@pytest.fixture
def regression_data():
    rng = np.random.default_rng(42)
    n, d, k = 96, 12, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(n, k)).astype(np.float32)
    return x, y


def test_linear_map_matches_exact(regression_data):
    x, y = regression_data
    lam = 0.1
    model = LinearMapEstimator(lam=lam).fit_dataset(Dataset(x), Dataset(y))
    w_ref, b_ref = _ridge_exact(x, y, lam * x.shape[0])
    np.testing.assert_allclose(np.asarray(model.weights), w_ref, atol=2e-3)
    np.testing.assert_allclose(np.asarray(model.intercept), b_ref, atol=2e-3)


def test_linear_map_with_padding_matches_unpadded(regression_data):
    """91 rows pad to 96 on the 4-wide data axis; result must be identical."""
    x, y = regression_data
    m1 = LinearMapEstimator(lam=0.1).fit_dataset(Dataset(x[:91]), Dataset(y[:91]))
    m2 = LinearMapEstimator(lam=0.1).fit_arrays(x[:91], y[:91])
    np.testing.assert_allclose(
        np.asarray(m1.weights), np.asarray(m2.weights), atol=1e-4
    )


def test_local_least_squares(regression_data):
    x, y = regression_data
    model = LocalLeastSquaresEstimator(lam=0.05).fit_dataset(Dataset(x), Dataset(y))
    w_ref, b_ref = _ridge_exact(x, y, 0.05 * x.shape[0])
    np.testing.assert_allclose(np.asarray(model.weights), w_ref, atol=2e-3)


def test_block_ls_converges_to_exact(regression_data):
    x, y = regression_data
    lam = 0.1
    est = BlockLeastSquaresEstimator(block_size=5, num_iter=40, lam=lam)
    model = est.fit_dataset(Dataset(x), Dataset(y))
    w_ref, b_ref = _ridge_exact(x, y, lam * x.shape[0])
    np.testing.assert_allclose(np.asarray(model.flat_weights)[: x.shape[1]], w_ref, atol=5e-3)
    np.testing.assert_allclose(np.asarray(model.intercept), b_ref, atol=5e-3)
    # predictions too
    pred = np.asarray(model.apply_batch(jnp.asarray(x)))
    np.testing.assert_allclose(pred, x @ w_ref + b_ref, atol=1e-2)


def test_block_ls_single_block_equals_linear_map(regression_data):
    x, y = regression_data
    lam = 0.2
    bm = BlockLeastSquaresEstimator(block_size=12, num_iter=1, lam=lam).fit_arrays(x, y)
    lm = LinearMapEstimator(lam=lam).fit_arrays(x, y)
    np.testing.assert_allclose(
        np.asarray(bm.flat_weights)[:12], np.asarray(lm.weights), atol=1e-3
    )


def test_block_weighted_ls_matches_direct_weighted_solve():
    rng = np.random.default_rng(7)
    n, d, k = 64, 8, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    labels = rng.integers(0, k, size=n)
    labels[: n // 2] = 0  # skew classes
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), labels] = 1.0
    lam, mw = 0.05, 0.5

    est = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iter=30, lam=lam, mixture_weight=mw
    )
    model = est.fit_arrays(x, y)

    # direct float64 weighted solve with the same weights
    counts = np.bincount(labels, minlength=k)
    alpha = mw * n / (k * counts[labels]) + (1 - mw)
    wsum = alpha.sum()
    xm = (alpha @ x) / wsum
    ym = (alpha @ y) / wsum
    xc, yc = x - xm, y - ym
    D = np.diag(alpha)
    w_ref = np.linalg.solve(
        xc.T @ D @ xc + lam * n * np.eye(d), xc.T @ D @ yc
    )
    b_ref = ym - xm @ w_ref
    np.testing.assert_allclose(np.asarray(model.flat_weights)[:d], w_ref, atol=5e-3)
    np.testing.assert_allclose(np.asarray(model.intercept), b_ref, atol=5e-3)


def test_block_weighted_mw_zero_equals_unweighted(regression_data):
    x, y = regression_data
    yy = (y == y.max(axis=1, keepdims=True)).astype(np.float32) * 2 - 1
    a = BlockWeightedLeastSquaresEstimator(
        block_size=6, num_iter=25, lam=0.1, mixture_weight=0.0
    ).fit_arrays(x, yy)
    b = BlockLeastSquaresEstimator(block_size=6, num_iter=25, lam=0.1).fit_arrays(x, yy)
    np.testing.assert_allclose(
        np.asarray(a.flat_weights), np.asarray(b.flat_weights), atol=2e-3
    )


def test_lbfgs_matches_closed_form(regression_data):
    x, y = regression_data
    lam = 0.1
    model = DenseLBFGSwithL2(lam=lam, num_iterations=80).fit_dataset(
        Dataset(x), Dataset(y)
    )
    n = x.shape[0]
    w_ref = np.linalg.solve(
        x.T @ x / n + lam * np.eye(x.shape[1]), x.T @ y / n
    )
    np.testing.assert_allclose(np.asarray(model.weights), w_ref, atol=5e-3)


def test_pca_projects_to_principal_subspace():
    rng = np.random.default_rng(3)
    # anisotropic data: top-2 dirs dominate
    base = rng.normal(size=(200, 6)).astype(np.float32)
    base[:, 2:] *= 0.05
    rot, _ = np.linalg.qr(rng.normal(size=(6, 6)))
    x = (base @ rot.T).astype(np.float32)
    for est in (PCAEstimator(2), DistributedPCAEstimator(2)):
        model = est.fit_dataset(Dataset(x))
        c = np.asarray(model.components)  # (6, 2)
        # projector onto learned subspace must match float64 PCA projector
        xm = x - x.mean(0)
        _, _, vt = np.linalg.svd(xm.astype(np.float64), full_matrices=False)
        p_ref = vt[:2].T @ vt[:2]
        p_got = c @ c.T
        np.testing.assert_allclose(p_got, p_ref, atol=1e-2)


def test_zca_whitens_covariance():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(400, 5)).astype(np.float32)
    x = x @ np.diag([3.0, 2.0, 1.0, 0.5, 0.25]).astype(np.float32)
    model = ZCAWhitenerEstimator(eps=1e-5).fit_dataset(Dataset(x))
    w = np.asarray(model.apply_batch(jnp.asarray(x)))
    cov = np.cov(w.T)
    np.testing.assert_allclose(cov, np.eye(5), atol=0.15)


def test_kmeans_recovers_separated_clusters():
    rng = np.random.default_rng(5)
    centers = np.array([[5, 5], [-5, 5], [0, -5]], np.float32)
    x = np.concatenate(
        [c + 0.2 * rng.normal(size=(50, 2)).astype(np.float32) for c in centers]
    )
    model = KMeansPlusPlusEstimator(3, max_iterations=20, seed=1).fit_dataset(
        Dataset(x)
    )
    got = np.sort(np.asarray(model.centers), axis=0)
    np.testing.assert_allclose(got, np.sort(centers, axis=0), atol=0.3)
    onehot = np.asarray(model.apply_batch(jnp.asarray(x)))
    assert onehot.shape == (150, 3)
    assert np.allclose(onehot.sum(axis=1), 1.0)


def test_gmm_recovers_components():
    rng = np.random.default_rng(6)
    x = np.concatenate(
        [
            np.array([4.0, 0.0], np.float32) + 0.5 * rng.normal(size=(150, 2)),
            np.array([-4.0, 0.0], np.float32) + 0.5 * rng.normal(size=(150, 2)),
        ]
    ).astype(np.float32)
    gmm = GaussianMixtureModelEstimator(k=2, max_iterations=30, seed=2).fit_dataset(
        Dataset(x)
    )
    means = np.sort(np.asarray(gmm.means)[:, 0])
    np.testing.assert_allclose(means, [-4.0, 4.0], atol=0.3)
    np.testing.assert_allclose(np.asarray(gmm.weights), [0.5, 0.5], atol=0.1)
    r = np.asarray(gmm.apply_batch(jnp.asarray(x)))
    assert np.allclose(r.sum(axis=1), 1.0, atol=1e-4)


def test_naive_bayes_counts():
    x = np.array(
        [[3, 0, 1], [2, 0, 0], [0, 4, 1], [0, 3, 2]], np.float32
    )
    y = np.array([0, 0, 1, 1])
    model = NaiveBayesEstimator(num_classes=2, lam=1.0).fit_arrays(x, y)
    lp = np.asarray(model.log_prior)
    np.testing.assert_allclose(np.exp(lp), [0.5, 0.5], atol=1e-5)
    lc = np.asarray(model.log_cond)
    # class 0: feature counts [5,0,1]+1 → [6,1,2]/9
    np.testing.assert_allclose(np.exp(lc[0]), [6 / 9, 1 / 9, 2 / 9], atol=1e-5)
    scores = np.asarray(model.apply_batch(jnp.asarray(x)))
    assert (scores.argmax(axis=1) == y).all()


def test_logistic_regression_separable():
    rng = np.random.default_rng(8)
    n = 100
    x = rng.normal(size=(n, 2)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.int32)
    model = LogisticRegressionEstimator(num_classes=2, lam=1e-3, num_iters=60).fit_arrays(
        x, y
    )
    pred = np.asarray(model.apply_batch(jnp.asarray(x))).argmax(axis=1)
    assert (pred == y).mean() > 0.97


def test_krr_matches_direct_dual_solve():
    rng = np.random.default_rng(9)
    n, d, k = 48, 4, 2
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    gamma, lam = 0.5, 1e-2
    kern = GaussianKernelGenerator(gamma)
    est = KernelRidgeRegressionEstimator(kern, lam=lam, block_size=16, num_epochs=25)
    model = est.fit_arrays(x, y)

    K = np.asarray(kern(jnp.asarray(x), jnp.asarray(x)), np.float64)
    alpha_ref = np.linalg.solve(K + lam * n * np.eye(n), y)
    np.testing.assert_allclose(np.asarray(model.alpha)[:n], alpha_ref, atol=5e-3)

    xt = rng.normal(size=(10, d)).astype(np.float32)
    pred = np.asarray(model.apply_batch(jnp.asarray(xt)))
    Kt = np.asarray(kern(jnp.asarray(xt), jnp.asarray(x)), np.float64)
    np.testing.assert_allclose(pred, Kt @ alpha_ref, atol=1e-2)


def test_krr_cached_blocks_matches_recompute():
    """cache_kernel_blocks=True (BlockKernelMatrix LRU sweep, the
    reference's cached-RDD strategy) must produce the same dual
    coefficients as the inlined recompute sweep — including with padding
    (n not a block multiple)."""
    rng = np.random.default_rng(11)
    n, d, k = 53, 5, 2
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    kern = GaussianKernelGenerator(0.4)
    kwargs = dict(lam=1e-2, block_size=16, num_epochs=8)
    plain = KernelRidgeRegressionEstimator(kern, **kwargs).fit_arrays(x, y)
    cached = KernelRidgeRegressionEstimator(
        kern, cache_kernel_blocks=True, **kwargs
    ).fit_arrays(x, y)
    np.testing.assert_allclose(
        np.asarray(cached.alpha)[:n], np.asarray(plain.alpha)[:n], atol=2e-4
    )


def test_solvers_in_pipeline_with_sharded_padding():
    """End-to-end through the DSL with a non-divisible row count."""
    rng = np.random.default_rng(10)
    x = rng.normal(size=(61, 6)).astype(np.float32)
    w = rng.normal(size=(6, 2)).astype(np.float32)
    y = x @ w
    from keystone_tpu.workflow import Identity, Pipeline

    pipe = Pipeline.of(Identity()).and_then(
        LinearMapEstimator(lam=1e-4), Dataset(x), Dataset(y)
    )
    pred = pipe(Dataset(x)).get().numpy()
    np.testing.assert_allclose(pred, y, atol=2e-2)


def test_linear_map_fit_stream_matches_in_memory(regression_data):
    """Out-of-core normal equations: streaming odd-sized host batches
    (forcing shard padding per batch) must reproduce the in-memory fit."""
    x, y = regression_data
    lam = 0.1
    full = LinearMapEstimator(lam=lam).fit_arrays(x, y)

    def batches():
        for i in range(0, x.shape[0], 37):  # 37 ∤ 4: every batch pads
            yield x[i : i + 37], y[i : i + 37]

    streamed = LinearMapEstimator(lam=lam).fit_stream(batches)
    np.testing.assert_allclose(
        np.asarray(streamed.weights), np.asarray(full.weights), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(streamed.intercept), np.asarray(full.intercept), atol=2e-4
    )
    # no-intercept variant, re-iterable list source
    full0 = LinearMapEstimator(lam=lam, fit_intercept=False).fit_arrays(x, y)
    lst = [(x[:50], y[:50]), (x[50:], y[50:])]
    s0 = LinearMapEstimator(lam=lam, fit_intercept=False).fit_stream(lst)
    np.testing.assert_allclose(
        np.asarray(s0.weights), np.asarray(full0.weights), atol=2e-4
    )


def test_linear_map_fit_stream_rejects_one_shot_generator(regression_data):
    x, y = regression_data
    gen = ((x[i : i + 32], y[i : i + 32]) for i in range(0, x.shape[0], 32))
    with pytest.raises(ValueError, match="not re-iterable"):
        LinearMapEstimator(lam=0.1).fit_stream(gen)


def test_standard_scaler_fit_stream_matches_in_memory():
    from keystone_tpu.ops import StandardScaler

    rng = np.random.default_rng(5)
    x = (100.0 + 3.0 * rng.normal(size=(301, 7))).astype(np.float32)
    full = StandardScaler().fit_arrays(x)
    streamed = StandardScaler().fit_stream(
        [x[i : i + 53] for i in range(0, 301, 53)]  # odd sizes force padding
    )
    np.testing.assert_allclose(
        np.asarray(streamed.mean), np.asarray(full.mean), rtol=1e-5
    )
    # the streaming path centers explicitly (more accurate than the
    # in-memory Σx²−n·mean² shortcut), so they agree only to f32 level
    np.testing.assert_allclose(
        np.asarray(streamed.std), np.asarray(full.std), rtol=5e-4
    )


def test_standard_scaler_fit_stream_survives_large_mean_small_spread():
    """The two-pass centered variance must not cancel: mean ~1e3 with
    std ~0.01 collapses to 0 under the one-pass f32 shortcut."""
    from keystone_tpu.ops import StandardScaler

    rng = np.random.default_rng(6)
    x64 = 1000.0 + 0.01 * rng.standard_normal((512, 5))
    x = x64.astype(np.float32)
    streamed = StandardScaler().fit_stream(
        lambda: (x[i : i + 128] for i in range(0, 512, 128))
    )
    ref_std = x64.std(axis=0, ddof=1)
    np.testing.assert_allclose(np.asarray(streamed.std), ref_std, rtol=0.05)


def test_out_of_core_featurize_then_fit_stream():
    """Full out-of-core training path: stream raw batches through a
    FITTED featurizer, feed featurized batches to the streaming solver,
    and match the in-memory fit of the same featurized data."""
    from keystone_tpu.ops import LinearRectifier, RandomSignNode

    from keystone_tpu.workflow import Pipeline

    rng = np.random.default_rng(9)
    n, d, k = 192, 16, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    y = (x @ w_true).astype(np.float32)
    featurizer = Pipeline.of(RandomSignNode.init(d, seed=1)).and_then(
        LinearRectifier(0.0)
    )

    def feat_batches():
        for i in range(0, n, 50):  # odd size: padding + pow2 bucketing
            bx, by = x[i : i + 50], y[i : i + 50]
            yield featurizer(Dataset(bx)).get().numpy(), by

    streamed = LinearMapEstimator(lam=1e-3).fit_stream(feat_batches)
    full_feats = featurizer(Dataset(x)).get().numpy()
    full = LinearMapEstimator(lam=1e-3).fit_arrays(full_feats, y)
    np.testing.assert_allclose(
        np.asarray(streamed.weights), np.asarray(full.weights), atol=2e-4
    )


def test_krr_cached_disk_tier_matches_recompute(monkeypatch, tmp_path):
    """K beyond the HBM budget: the cached mode goes TIERED (partial HBM
    LRU + disk-persisted column blocks) instead of silently assuming K
    fits HBM (VERDICT r2 weak-7).  Parity with the recompute fit, and
    epochs >= 2 must reread from cache/disk, not regenerate gemms."""
    from keystone_tpu.models.kernel_ridge import (
        GaussianKernelGenerator,
        KernelRidgeRegressionEstimator,
    )

    rng = np.random.default_rng(0)
    n, d, k = 256, 16, 2
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    kern = GaussianKernelGenerator(gamma=0.05)

    ref = KernelRidgeRegressionEstimator(
        kern, lam=1e-2, block_size=64, num_epochs=2
    ).fit_arrays(x, y)

    # force the disk tier: pretend HBM fits ~one column block
    import keystone_tpu.workflow.profiling as prof

    monkeypatch.setattr(
        prof, "device_hbm_budget", lambda frac=0.5: 256 * 64 * 4 + 1
    )

    # count kernel gemms: epoch 2 must REREAD (HBM/disk), not regenerate
    calls = []
    orig_call = type(kern).__call__

    def counting_call(self, a, b):
        calls.append(np.shape(a)[0])
        return orig_call(self, a, b)

    monkeypatch.setattr(type(kern), "__call__", counting_call)
    cached = KernelRidgeRegressionEstimator(
        kern,
        lam=1e-2,
        block_size=64,
        num_epochs=2,
        cache_kernel_blocks=True,
        kernel_cache_dir=str(tmp_path / "kcache"),
    ).fit_arrays(x, y)
    # exactly 4 full-column gemms (n rows each) across BOTH epochs —
    # later sweeps reload from the HBM LRU or disk
    assert [c for c in calls if c == n] == [n] * 4, calls
    # 4 column blocks + the fingerprint meta persisted on disk
    import os

    files = sorted(os.listdir(tmp_path / "kcache"))
    assert sum(f.endswith(".npy") for f in files) == 4, files
    # the durable spill path publishes a BLAKE2b sidecar per column —
    # read-time verification is what catches a torn spill block
    assert sum(f.endswith(".npy.b2") for f in files) == 4, files
    assert "kcache_meta.json" in files
    np.testing.assert_allclose(
        np.asarray(cached.alpha), np.asarray(ref.alpha), atol=2e-4
    )

    # a DIFFERENT problem reusing the same cache dir must invalidate it,
    # never serve the previous fit's kernel columns
    x2 = rng.normal(size=(n, d)).astype(np.float32)
    ref2 = KernelRidgeRegressionEstimator(
        kern, lam=1e-2, block_size=64, num_epochs=2
    ).fit_arrays(x2, y)
    cached2 = KernelRidgeRegressionEstimator(
        kern,
        lam=1e-2,
        block_size=64,
        num_epochs=2,
        cache_kernel_blocks=True,
        kernel_cache_dir=str(tmp_path / "kcache"),
    ).fit_arrays(x2, y)
    np.testing.assert_allclose(
        np.asarray(cached2.alpha), np.asarray(ref2.alpha), atol=2e-4
    )


def test_kernel_spill_dir_refuses_foreign_files(tmp_path):
    """A stale cache dir is cleared file-by-file (only kcol_*.npy +
    kcache_meta.json); a dir holding ANYTHING else is refused, never
    rmtree'd (ADVICE r3 medium: data-loss hazard on a reused user
    directory)."""
    import os

    from keystone_tpu.models.kernel_matrix import BlockKernelMatrix
    from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator

    rng = np.random.default_rng(1)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    kern = GaussianKernelGenerator(gamma=0.1)

    d = tmp_path / "user_dir"
    d.mkdir()
    (d / "precious.txt").write_text("do not delete")
    with pytest.raises(ValueError, match="does not own"):
        BlockKernelMatrix(kern, x, block_size=16, spill_dir=str(d))
    assert (d / "precious.txt").read_text() == "do not delete"

    # a dir holding ONLY cache-owned files from a stale fit is cleared
    # per-file and reused — including the durable path's derivatives: a
    # BLAKE2b sidecar and an atomic-write tmp abandoned by a crashed
    # writer (neither may render a reusable cache dir "foreign")
    d2 = tmp_path / "stale"
    d2.mkdir()
    (d2 / "kcol_00000.npy").write_bytes(b"stale")
    (d2 / "kcol_00000.npy.b2").write_bytes(b"stale-sidecar")
    (d2 / "kcol_00001.npy.tmp.1234.5678").write_bytes(b"crashed-writer")
    (d2 / "kcache_meta.json").write_text("{}")
    BlockKernelMatrix(kern, x, block_size=16, spill_dir=str(d2))
    assert not (d2 / "kcol_00000.npy").exists()
    assert not (d2 / "kcol_00000.npy.b2").exists()
    assert not (d2 / "kcol_00001.npy.tmp.1234.5678").exists()
    assert (d2 / "kcache_meta.json").exists()

    # the fingerprint keys the FULL kernel identity: same gamma attr on
    # a different generator type must invalidate, not pass validation
    class OtherKernel:
        gamma = 0.1

        def __call__(self, a, b):  # pragma: no cover - never sampled
            return np.zeros((a.shape[0], b.shape[0]), np.float32)

    BlockKernelMatrix(OtherKernel(), x, block_size=16, spill_dir=str(d2))
    import json

    # the fingerprint must be STABLE across instances (no id-based
    # default repr leaking in) — a fresh instance of the same plain
    # class must validate, not clear, the dir
    meta1 = json.load(open(d2 / "kcache_meta.json"))
    BlockKernelMatrix(OtherKernel(), x, block_size=16, spill_dir=str(d2))
    assert json.load(open(d2 / "kcache_meta.json")) == meta1

    # OS dotfile artifacts (.nfsXXXX, .DS_Store) are tolerated, not
    # treated as foreign user data
    (d2 / ".nfs0000deadbeef").write_bytes(b"")
    BlockKernelMatrix(kern, x, block_size=16, spill_dir=str(d2))
    assert (d2 / ".nfs0000deadbeef").exists()

    # and re-instantiating with the original generator re-fingerprints
    # (round-trip sanity: validation is on content, not mtime)
    assert meta1["fingerprint"] != json.load(
        open(d2 / "kcache_meta.json")
    )["fingerprint"]
