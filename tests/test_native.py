"""Native IO library tests: native fast paths must agree with the Python
fallbacks (the reference's JNI smoke-test pattern, gated on availability)."""

import io
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


def test_read_csv_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    mat = rng.normal(size=(20, 7)).astype(np.float32)
    path = str(tmp_path / "data.csv")
    np.savetxt(path, mat, delimiter=",", fmt="%.6f")
    got = native.read_csv(path)
    ref = np.loadtxt(path, delimiter=",", dtype=np.float32)
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_read_csv_negative_and_ints(tmp_path):
    path = str(tmp_path / "t.csv")
    with open(path, "w") as f:
        f.write("1,-2.5,3e2\n-0.125,4,5\n")
    got = native.read_csv(path)
    np.testing.assert_allclose(got, [[1, -2.5, 300], [-0.125, 4, 5]], atol=1e-6)


def test_read_cifar_matches_python(tmp_path):
    rng = np.random.default_rng(1)
    n = 5
    recs = np.zeros((n, 3073), np.uint8)
    recs[:, 0] = rng.integers(0, 10, size=n)
    recs[:, 1:] = rng.integers(0, 256, size=(n, 3072))
    path = str(tmp_path / "batch.bin")
    recs.tofile(path)
    pixels, labels = native.read_cifar(path)
    assert pixels.shape == (n, 32, 32, 3)
    np.testing.assert_array_equal(labels, recs[:, 0])
    ref = recs[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1) / 255.0
    np.testing.assert_allclose(pixels, ref.astype(np.float32), atol=1e-6)

    # and through the loader (which prefers the native path)
    from keystone_tpu.loaders.cifar import CifarLoader

    data = CifarLoader.load(path)
    np.testing.assert_allclose(data.data.numpy(), ref, atol=1e-6)


def test_tar_index_and_jpeg_decode(tmp_path):
    from PIL import Image as PILImage

    rng = np.random.default_rng(2)
    tar_path = str(tmp_path / "imgs.tar")
    raw_imgs = []
    with tarfile.open(tar_path, "w") as tf:
        for i in range(3):
            arr = rng.integers(0, 256, size=(40, 30, 3)).astype(np.uint8)
            raw_imgs.append(arr)
            buf = io.BytesIO()
            PILImage.fromarray(arr).save(buf, format="JPEG", quality=95)
            data = buf.getvalue()
            info = tarfile.TarInfo(name=f"img{i}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))

    index = native.tar_index(tar_path)
    assert [name for name, _, _ in index] == ["img0.jpg", "img1.jpg", "img2.jpg"]

    blobs = []
    with open(tar_path, "rb") as f:
        for _, off, sz in index:
            f.seek(off)
            blobs.append(f.read(sz))
    images, ok = native.decode_jpegs(blobs, (32, 32))
    assert ok.all()
    assert images.shape == (3, 32, 32, 3)
    assert images.dtype == np.uint8  # 1 byte/pixel on the wire
    # compare against PIL decode+resize of the same bytes (both bilinear-ish;
    # JPEG is lossy so tolerances are loose)
    for i, blob in enumerate(blobs):
        ref = PILImage.open(io.BytesIO(blob)).convert("RGB").resize((32, 32))
        ref = np.asarray(ref, np.float32)
        assert np.abs(images[i].astype(np.float32) - ref).mean() < 0.08 * 255


def test_decode_jpegs_bad_blob_flagged():
    images, ok = native.decode_jpegs([b"not a jpeg"], (16, 16))
    assert images.shape == (1, 16, 16, 3)
    assert not ok[0]


def test_read_csv_comments_and_ragged_rows(tmp_path):
    path = str(tmp_path / "c.csv")
    with open(path, "w") as f:
        f.write("# a header comment\n1,2,3\n4,5\n6,7,8\n")
    got = native.read_csv(path)
    # short row zero-fills its missing cells; later rows stay aligned
    np.testing.assert_allclose(got[0], [1, 2, 3])
    np.testing.assert_allclose(got[2], [6, 7, 8])
    assert got[1][0] == 4.0 and got[1][1] == 5.0


def test_tar_index_rejects_gzip(tmp_path):
    import gzip

    path = str(tmp_path / "fake.tar")
    rng = np.random.default_rng(0)
    with gzip.open(path, "wb") as f:
        f.write(rng.bytes(4096))  # incompressible -> > 512 bytes on disk
    # no ustar magic -> error (None) or empty; either way the loader falls
    # back to tarfile's auto-detection
    assert not native.tar_index(path)


def test_fisher_encode_ffi_matches_xla():
    # the C++ double-accumulation custom call (the EncEval-tier equivalent)
    # must agree with the f32 XLA einsum path
    import jax.numpy as jnp

    from keystone_tpu.ops.fisher import _fisher_encode
    from keystone_tpu.ops.fisher_ffi import ffi_available, fisher_encode_ffi

    if not ffi_available():
        import pytest

        pytest.skip("FFI library unavailable")
    rng = np.random.default_rng(0)
    n, t, d, k = 3, 17, 8, 5
    xs = rng.normal(size=(n, t, d)).astype(np.float32)
    mask = (rng.uniform(size=(n, t)) > 0.3).astype(np.float32)
    w = rng.dirichlet(np.ones(k)).astype(np.float32)
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = rng.uniform(0.5, 2.0, size=(k, d)).astype(np.float32)
    ref = np.asarray(_fisher_encode(xs, mask, w, mu, var))
    out = np.asarray(fisher_encode_ffi(xs, mask, w, mu, var))
    np.testing.assert_allclose(ref, out, atol=1e-4, rtol=1e-4)


def test_fisher_encode_ffi_f64_precision_reference():
    # in float64 the custom call serves as the precision reference
    # (SURVEY §7 hard part (a): f64-on-host parity for FV numerics)
    import jax

    from keystone_tpu.ops.fisher_ffi import ffi_available, fisher_encode_ffi

    if not ffi_available():
        import pytest

        pytest.skip("FFI library unavailable")
    rng = np.random.default_rng(1)
    n, t, d, k = 2, 9, 4, 3
    xs = rng.normal(size=(n, t, d))
    mask = np.ones((n, t))
    w = rng.dirichlet(np.ones(k))
    mu = rng.normal(size=(k, d))
    var = rng.uniform(0.5, 2.0, size=(k, d))
    with jax.enable_x64(True):
        out64 = np.asarray(
            fisher_encode_ffi(
                xs.astype(np.float64), mask, w, mu, var
            )
        )
    assert out64.dtype == np.float64
    out32 = np.asarray(
        fisher_encode_ffi(
            xs.astype(np.float32),
            mask.astype(np.float32),
            w.astype(np.float32),
            mu.astype(np.float32),
            var.astype(np.float32),
        )
    )
    # f32 I/O with f64 accumulation stays within f32 rounding of the f64 run
    np.testing.assert_allclose(out32, out64, atol=5e-5, rtol=5e-4)


def test_fisher_encode_ffi_f64_input_without_x64_falls_back():
    # with jax_enable_x64 off (the default), f64 inputs canonicalize to
    # f32 on device; the call must route to the f32 target, not crash
    from keystone_tpu.ops.fisher_ffi import ffi_available, fisher_encode_ffi

    if not ffi_available():
        import pytest

        pytest.skip("FFI library unavailable")
    rng = np.random.default_rng(2)
    n, t, d, k = 2, 5, 3, 2
    xs = rng.normal(size=(n, t, d))          # float64 by default
    mask = np.ones((n, t))
    w = rng.dirichlet(np.ones(k))
    mu = rng.normal(size=(k, d))
    var = rng.uniform(0.5, 2.0, size=(k, d))
    out = np.asarray(fisher_encode_ffi(xs, mask, w, mu, var))
    assert out.dtype == np.float32
    assert np.isfinite(out).all()


def test_gmm_em_ffi_matches_jitted_em():
    # same init -> the C++ double-accumulation EM and the jitted EM must
    # agree (the EncEval-EM parity check; init stays in Python)
    import jax.numpy as jnp

    from keystone_tpu.models.gmm import _em_steps
    from keystone_tpu.ops.fisher_ffi import ffi_available, gmm_em_ffi

    if not ffi_available("em"):
        import pytest

        pytest.skip("FFI library unavailable")
    rng = np.random.default_rng(0)
    n, d, k = 200, 6, 3
    centers = rng.normal(scale=4.0, size=(k, d))
    x = (centers[rng.integers(0, k, n)] + rng.normal(size=(n, d))).astype(
        np.float32
    )
    mask = np.ones((n,), np.float32)
    w0 = np.full((k,), 1.0 / k, np.float32)
    mu0 = x[:k].copy()
    var0 = np.ones((k, d), np.float32)

    w_j, mu_j, var_j = _em_steps(
        jnp.asarray(x), jnp.float32(n), jnp.asarray(mask),
        jnp.asarray(w0), jnp.asarray(mu0), jnp.asarray(var0), 10, 1e-6,
    )
    w_c, mu_c, var_c = gmm_em_ffi(x, mask, w0, mu0, var0, iters=10)
    np.testing.assert_allclose(np.asarray(w_j), np.asarray(w_c), atol=2e-5)
    np.testing.assert_allclose(np.asarray(mu_j), np.asarray(mu_c), atol=2e-4)
    np.testing.assert_allclose(np.asarray(var_j), np.asarray(var_c), atol=2e-4)
    assert abs(float(np.sum(np.asarray(w_c))) - 1.0) < 1e-5
