"""Request-scoped tracing, flight recorder & live ops surface (ISSUE 9).

Tier-1 coverage the ISSUE pins:

- ACCEPTANCE: with the JSONL ledger OFF, a deliberately shed request's
  full causal chain (ingress → queue → batch → replica → shed) is
  reconstructable from ``GET /requestz/<id>`` via the flight recorder
  alone;
- tail-based retention: shed/error/slow traces survive the happy-path
  flood; the rings stay bounded;
- request-id echo in every HTTP response (200 and 429/503/504 error
  bodies alike), honoring a client-supplied ``X-Request-Id``;
- trace continuity across a blue/green ``swap()`` under load, with the
  swap itself visible as a control-plane span;
- byte-identity pins: solver HLO is unchanged with the recorder on;
  ``recorder=False`` runs the PR-5 single-batcher path (no recorder
  object, no generated ids, ops endpoints answer 409);
- ``GET /statusz``: windowed percentiles, per-replica view, SLO
  error-budget burn rate;
- ``tools/trace_report.py`` renders the same chains from a recorder
  dump and from a run ledger (``serve.batch`` spans carrying rider ids
  as span links).
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.obs import ledger, metrics
from keystone_tpu.obs.recorder import FlightRecorder, new_request_id
from keystone_tpu.ops.stats import NormalizeRows
from keystone_tpu.serve import Overloaded, serve, serve_http
from keystone_tpu.utils import guard
from keystone_tpu.workflow import Dataset, Pipeline

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    ),
)

pytestmark = [pytest.mark.serve, pytest.mark.obs]

DIM = 6


@pytest.fixture(autouse=True)
def _ledger_off(monkeypatch):
    """The recorder must work with the JSONL ledger fully inert — the
    acceptance precondition — and tests must not leak an active run."""
    monkeypatch.delenv(ledger.ENV_DIR, raising=False)
    ledger.attach(None)
    assert ledger.active() is None
    yield
    ledger.stop_run()
    ledger.attach(None)


def _pipeline(scale: float = 2.0) -> Pipeline:
    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * scale)
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


def _service(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 20.0)
    kw.setdefault("queue_bound", 64)
    kw.setdefault("example", np.zeros(DIM, np.float32))
    return serve(_pipeline(), **kw)


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _post_json(url, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=dict(headers or {})
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


# ----------------------------------------------------- recorder unit tests


def test_recorder_roundtrip_and_event_order():
    rec = FlightRecorder()
    rec.annotate("r1", "http.ingress", path="/predict")
    rec.annotate("r1", "serve.enqueue", queue_depth=3)
    rec.finish("r1", "completed", replica=0)
    tr = rec.request("r1")
    assert tr["outcome"] == "completed"
    assert [e["name"] for e in tr["events"]] == [
        "http.ingress",
        "serve.enqueue",
        "serve.completed",
    ]
    assert tr["seconds"] >= 0.0 and not tr["open"]
    # event offsets are monotone within the trace
    ts = [e["t"] for e in tr["events"]]
    assert ts == sorted(ts)


def test_recorder_ids_unique_and_cheap():
    ids = {new_request_id() for _ in range(2000)}
    assert len(ids) == 2000


def test_tail_based_retention_pins_interesting_traces():
    """Shed/error traces survive a happy-path flood that evicts their
    contemporaries; the rings stay bounded."""
    rec = FlightRecorder(capacity=16, pinned_capacity=8)
    rec.annotate("bad-1", "serve.enqueue", queue_depth=1)
    rec.finish("bad-1", "shed", replica=0)
    rec.finish("err-1", "error", error="boom")
    for i in range(200):  # flood: evicts everything happy
        rec.finish(f"ok-{i}", "completed")
    stats = rec.stats()
    assert stats["recent"] <= 16 and stats["pinned"] <= 8
    assert rec.request("ok-0") is None  # evicted with the flood
    # the interesting traces are still resolvable
    assert rec.request("bad-1")["outcome"] == "shed"
    assert rec.request("err-1")["outcome"] == "error"
    shed_ids = [t["request_id"] for t in rec.tracez(filter="shed")]
    assert "bad-1" in shed_ids and "err-1" not in shed_ids


def test_slow_traces_pinned_by_explicit_threshold():
    rec = FlightRecorder(capacity=4, slow_ms=0.0001)  # everything is slow
    rec.annotate("s1", "serve.enqueue", queue_depth=0)
    time.sleep(0.002)
    rec.finish("s1", "completed")
    tr = rec.request("s1")
    assert tr["slow"] is True
    assert [t["request_id"] for t in rec.tracez(filter="slow")] == ["s1"]
    # and the happy filter still excludes nothing for outcome
    assert rec.tracez(filter="completed")[0]["request_id"] == "s1"


def test_batch_records_join_requests():
    """The flush is recorded ONCE with rider ids as span links; each
    rider's /requestz view joins the batch record back in."""
    rec = FlightRecorder()
    for rid in ("a", "b"):
        rec.annotate(rid, "serve.replica", batch="b7", replica=2)
    rec.batch("b7", ["a", "b"], replica=2, rows=2)
    rec.batch_update("b7", seconds=0.004, bucket=8, degraded=False)
    rec.finish("a", "completed", batch="b7", replica=2)
    tr = rec.request("a")
    assert tr["batches"] == ["b7"]
    (b,) = tr["batch_records"]
    assert b["request_ids"] == ["a", "b"]
    assert b["seconds"] == 0.004 and b["bucket"] == 8


def test_none_request_id_is_inert():
    rec = FlightRecorder()
    rec.annotate(None, "serve.enqueue", queue_depth=1)
    rec.finish(None, "completed")
    assert rec.stats()["finished"] == 0 and rec.stats()["live"] == 0


# ------------------------------------------------- service + HTTP surface


def test_shed_request_chain_from_requestz_with_ledger_off():
    """THE acceptance test: ledger off, a deliberately shed request's
    full causal chain — ingress → queue → batch → replica → shed — is
    reconstructable from GET /requestz/<id> via the recorder alone."""
    assert ledger.active() is None
    with _service(max_batch=4, max_wait_ms=5.0) as svc:
        with serve_http(svc, port=0) as front:
            base = f"http://127.0.0.1:{front.port}"
            # an expired deadline guarantees the shed decision at flush
            code = None
            try:
                _post_json(
                    base + "/predict",
                    {"instance": [1.0] * DIM, "deadline_ms": 0.0001},
                    headers={"X-Request-Id": "doomed-http"},
                )
            except urllib.error.HTTPError as e:
                code = e.code
                body = json.loads(e.read())
            assert code == 504
            assert body["request_id"] == "doomed-http"
            status, tr = _get_json(base + "/requestz/doomed-http")
            assert status == 200
    assert tr["outcome"] == "shed"
    names = [e["name"] for e in tr["events"]]
    assert names == [
        "http.ingress",   # ingress
        "serve.enqueue",  # queue
        "serve.batch",    # flush arrival on the replica worker
        "serve.shed",     # terminal outcome
    ]
    # the chain names the replica and the batch it rode: the batch event
    # carries replica/batch/queue-wait, the batch record carries the
    # rider span links — ingress → queue → batch → replica → shed is
    # fully reconstructable from the recorder alone
    batch_ev = tr["events"][2]["attrs"]
    assert batch_ev["replica"] == 0 and batch_ev["batch"] in tr["batches"]
    assert batch_ev["queue_wait_seconds"] >= 0.0
    assert tr["events"][3]["attrs"]["replica"] == 0
    (b,) = tr["batch_records"]
    assert "doomed-http" in b["request_ids"]
    assert b["replica"] == 0


def test_completed_chain_and_tracez_filtering():
    with _service(max_batch=4, max_wait_ms=5.0) as svc:
        fut = svc.submit(np.ones(DIM, np.float32), request_id="ok-1")
        fut.result(timeout=30)
        doomed = svc.submit(
            np.ones(DIM, np.float32), deadline=-0.01, request_id="doomed-1"
        )
        with pytest.raises(guard.DeadlineExceeded):
            doomed.result(timeout=30)
        rec = svc.recorder
        tr = rec.request("ok-1")
        assert tr["outcome"] == "completed"
        names = [e["name"] for e in tr["events"]]
        assert names[0] == "serve.enqueue" and names[-1] == "serve.completed"
        # queue wait + apply seconds land in the chain (trace_report's
        # critical-path inputs)
        rep = next(e for e in tr["events"] if e["name"] == "serve.batch")
        assert rep["attrs"]["queue_wait_seconds"] >= 0.0
        assert tr["events"][-1]["attrs"]["apply_seconds"] > 0.0
        shed_ids = [t["request_id"] for t in rec.tracez(filter="shed")]
        assert shed_ids == ["doomed-1"]
        all_ids = [t["request_id"] for t in rec.tracez()]
        assert "ok-1" in all_ids and "doomed-1" in all_ids


def test_rejected_request_is_traced():
    svc = _service(max_batch=64, max_wait_ms=10_000.0, queue_bound=2)
    try:
        svc.submit(np.ones(DIM, np.float32))
        svc.submit(np.ones(DIM, np.float32))
        with pytest.raises(Overloaded):
            svc.submit(np.ones(DIM, np.float32), request_id="rej-1")
        tr = svc.recorder.request("rej-1")
        assert tr["outcome"] == "rejected"
        assert tr["events"][-1]["name"] == "serve.rejected"
    finally:
        svc.close()


def test_http_echoes_request_id_everywhere():
    """The echo satellite: 200 bodies, 429/503 error bodies, and the
    X-Request-Id response header all quote the id /requestz resolves."""
    with _service(max_batch=4, max_wait_ms=5.0) as svc:
        with serve_http(svc, port=0) as front:
            base = f"http://127.0.0.1:{front.port}"
            # 200: generated id echoed in body + header
            status, body, headers = _post_json(
                base + "/predict", {"instance": [1.0] * DIM}
            )
            assert status == 200
            rid = body["request_id"]
            assert rid and headers["X-Request-Id"] == rid
            assert svc.recorder.request(rid)["outcome"] == "completed"
            # client-supplied id honored + multi-instance sub-ids
            status, body, _ = _post_json(
                base + "/predict",
                {"instances": [[1.0] * DIM, [2.0] * DIM]},
                headers={"X-Request-Id": "mine-1"},
            )
            assert body["request_id"] == "mine-1"
            assert body["request_ids"] == ["mine-1/0", "mine-1/1"]
            assert svc.recorder.request("mine-1/1")["outcome"] == "completed"
            # 400: malformed body still echoes an id
            with pytest.raises(urllib.error.HTTPError) as err:
                _post_json(base + "/predict", {"nope": 1})
            assert err.value.code == 400
            assert json.loads(err.value.read())["request_id"]
            # a client id that needs percent-encoding still resolves:
            # /requestz unquotes the path segment
            _post_json(
                base + "/predict",
                {"instance": [1.0] * DIM},
                headers={"X-Request-Id": "order 7f3a"},
            )
            status, tr = _get_json(base + "/requestz/order%207f3a")
            assert status == 200 and tr["request_id"] == "order 7f3a"

    # 429: fill a tiny queue, overflow echoes the id
    svc = _service(max_batch=64, max_wait_ms=10_000.0, queue_bound=1)
    front = serve_http(svc, port=0)
    try:
        base = f"http://127.0.0.1:{front.port}"
        svc.submit(np.ones(DIM, np.float32))
        with pytest.raises(urllib.error.HTTPError) as err:
            _post_json(
                base + "/predict",
                {"instance": [1.0] * DIM},
                headers={"X-Request-Id": "too-many"},
            )
        assert err.value.code == 429
        body = json.loads(err.value.read())
        assert body["request_id"] == "too-many"
        assert svc.recorder.request("too-many")["outcome"] == "rejected"
    finally:
        front.stop()
        svc.close()
    # 503: a closed service echoes the id too
    front = serve_http(svc, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _post_json(
                f"http://127.0.0.1:{front.port}/predict",
                {"instance": [1.0] * DIM},
                headers={"X-Request-Id": "late-1"},
            )
        assert err.value.code == 503
        assert json.loads(err.value.read())["request_id"] == "late-1"
    finally:
        front.stop()


def test_recorder_off_is_the_pr5_path():
    """recorder=False: no recorder object, no generated ids (the id
    counter does not advance), ops endpoints answer 409, results are
    identical to the offline apply — the PR-5 single-batcher path."""
    x = np.random.default_rng(0).normal(size=(5, DIM)).astype(np.float32)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)[:5]
    before = new_request_id()
    with _service(recorder=False) as svc:
        assert svc.recorder is None
        futs = svc.submit_many(x)
        got = np.stack([f.result(timeout=30) for f in futs])
        with serve_http(svc, port=0) as front:
            base = f"http://127.0.0.1:{front.port}"
            for path in ("/tracez", "/requestz/whatever"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(base + path, timeout=10)
                assert err.value.code == 409
    after = new_request_id()
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)
    # only our own two probe calls advanced the id counter: the service
    # minted zero ids for the 5 untraced requests
    delta = int(after.rsplit("-", 1)[1], 16) - int(before.rsplit("-", 1)[1], 16)
    assert delta == 1


def test_solver_hlo_identical_with_recorder_on():
    """Tracing lives entirely outside jit: traced solver programs are
    byte-identical while a recorder-on service handles traffic."""
    import jax

    from keystone_tpu.models.block_ls import _bcd_epoch_body

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 16, 8)), jnp.float32
    )
    y = jnp.ones((16, 2), jnp.float32)
    w = jnp.zeros((2, 8, 2), jnp.float32)
    p = jnp.zeros((16, 2), jnp.float32)

    def step(xb, yb, wb, pb):
        return _bcd_epoch_body(xb, yb, jnp.float32(16.0), 1e-3, (wb, pb))

    plain = jax.jit(step).lower(x, y, w, p).as_text()
    with _service() as svc:
        assert svc.recorder is not None
        svc.submit(np.ones(DIM, np.float32)).result(timeout=30)
        tracing = jax.jit(step).lower(x, y, w, p).as_text()
    assert plain == tracing


def test_degraded_outcome_recorded():
    """A flush that degraded an optional stage finishes its riders with
    outcome 'degraded' — and degraded traces are pinned."""
    from keystone_tpu.workflow import Transformer

    class _Flaky(Transformer):
        optional = True

        def apply_one(self, x):
            raise RuntimeError("boom")

        def apply_batch(self, xs, mask=None):
            raise RuntimeError("boom")

    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * 3.0)
    pipe = Pipeline.of(_Flaky()) | LinearMapper(w)
    x = np.random.default_rng(2).normal(size=(DIM,)).astype(np.float32)
    with serve(
        pipe, max_batch=4, max_wait_ms=5.0, example=np.zeros(DIM, np.float32)
    ) as svc:
        out = np.asarray(
            svc.submit(x, request_id="deg-1").result(timeout=30)
        )
        np.testing.assert_allclose(out, x * 3.0, rtol=1e-6)
        tr = svc.recorder.request("deg-1")
    assert tr["outcome"] == "degraded"
    assert tr["events"][-1]["name"] == "serve.degraded"


def test_statusz_surface():
    with _service(
        max_batch=4, max_wait_ms=5.0, deadline_ms=5000.0, slo_ms=100.0
    ) as svc:
        futs = svc.submit_many(np.ones((6, DIM), np.float32))
        [f.result(timeout=30) for f in futs]
        # a shed request MUST burn the error budget: the worst latency
        # violation there is cannot hide from a completed-only window
        doomed = svc.submit(np.ones(DIM, np.float32), deadline=-0.01)
        with pytest.raises(guard.DeadlineExceeded):
            doomed.result(timeout=30)
        # a CLIENT fault (shape mismatch → 400 family) must NOT burn
        # the server's error budget
        with pytest.raises(TypeError):
            svc.submit(np.ones(DIM + 1, np.float32))
        with serve_http(svc, port=0) as front:
            status, st = _get_json(
                f"http://127.0.0.1:{front.port}/statusz"
            )
    assert status == 200
    assert st["latency_ms"]["count"] >= 6
    assert st["latency_ms"]["p50"] is not None
    assert st["latency_ms"]["p99"] >= st["latency_ms"]["p50"]
    assert st["batch_ms"]["count"] >= 1
    assert st["counters"]["completed"] >= 6
    assert st["replicas"][0]["replica"] == 0
    assert st["recorder"]["finished"] >= 7
    slo = st["slo"]
    assert slo["objective_ms"] == 100.0 and slo["target"] == 0.99
    # exactly the shed request failed in-window: the client-fault
    # TypeError above was exempted from the budget
    assert slo["window_failed"] == 1
    # the wire value rounds to 6 decimals — allow that epsilon
    assert slo["bad_fraction"] >= 1.0 / slo["window_requests"] - 1e-6
    assert slo["burn_rate"] > 0.0


def test_trace_continuity_across_swap_under_load():
    """The swap satellite: riders routed to the retiring generation keep
    a complete causal chain, and the swap itself appears as a
    control-plane span between them."""
    stop = threading.Event()
    failures = []
    outs = []

    with _service(max_batch=4, max_wait_ms=2.0) as svc:

        def pound():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    fut = svc.submit(
                        np.ones(DIM, np.float32), request_id=f"load-{i}"
                    )
                    outs.append((f"load-{i}", np.asarray(fut.result(timeout=30))))
                except Exception as e:  # pragma: no cover - fails the test
                    failures.append(e)
                    return

        t = threading.Thread(target=pound, daemon=True)
        t.start()
        time.sleep(0.15)
        info = svc.swap(_pipeline(scale=5.0), version="green")
        time.sleep(0.15)
        stop.set()
        t.join(30)
        assert not failures
        assert len(outs) > 4
        rec = svc.recorder
        # the swap is visible as a control-plane span with its version
        ops = [o for o in rec.ops_spans() if o["name"] == "serve.swap"]
        assert ops and ops[0]["version"] == "green"
        assert info["version"] == "green"
        # every completed rider — blue and green generations alike —
        # carries a full causal chain ending in a terminal outcome
        blue = green = 0
        for rid, out in outs:
            tr = rec.request(rid)
            if tr is None:
                continue  # evicted happy-path trace: retention, not loss
            assert tr["outcome"] == "completed"
            names = [e["name"] for e in tr["events"]]
            assert names[0] == "serve.enqueue"
            assert names[-1] == "serve.completed"
            assert "serve.batch" in names
            if abs(out[0] - 2.0 / np.sqrt(DIM)) < 1e-4:
                blue += 1
            else:
                green += 1
        # traffic straddled the swap: both generations actually served
        assert blue > 0 and green > 0


# ----------------------------------------------------------- trace_report


def test_trace_report_from_recorder_dump(tmp_path):
    import trace_report

    with _service(max_batch=4, max_wait_ms=5.0) as svc:
        futs = svc.submit_many(np.ones((5, DIM), np.float32))
        [f.result(timeout=30) for f in futs]
        doomed = svc.submit(np.ones(DIM, np.float32), deadline=-0.01)
        with pytest.raises(guard.DeadlineExceeded):
            doomed.result(timeout=30)
        dump = svc.recorder.dump()
    path = tmp_path / "dump.json"
    path.write_text(json.dumps(dump))
    summary = trace_report.summarize(trace_report.load(str(path)), top=3)
    assert summary["source"] == "recorder"
    assert summary["outcomes"]["completed"] >= 5
    assert summary["outcomes"]["shed"] == 1
    assert summary["critical_path_mean"]["queue_wait_s"] is not None
    assert summary["critical_path_mean"]["apply_s"] > 0.0
    assert summary["top_slow"] and summary["top_slow"][0]["seconds"] > 0.0
    assert "0" in summary["replica_timelines"]
    text = trace_report.render(summary)
    assert "top 3 slow requests" not in text or True
    assert "replica 0 timeline" in text
    # CLI smoke: exit 0 and prints the same report
    assert trace_report.main([str(path), "--json"]) == 0


def test_trace_report_from_ledger_with_span_links(tmp_path):
    """With a ledger active, serve.batch spans carry rider request ids
    as span links and serve.request events carry terminal outcomes —
    trace_report reconstructs the same chains from the JSONL alone."""
    import trace_report

    ledger.start_run(str(tmp_path))
    try:
        with _service(max_batch=4, max_wait_ms=5.0) as svc:
            fut = svc.submit(np.ones(DIM, np.float32), request_id="led-1")
            fut.result(timeout=30)
    finally:
        ledger.stop_run()
    (run_path,) = [
        os.path.join(tmp_path, p)
        for p in os.listdir(tmp_path)
        if p.endswith(".jsonl")
    ]
    events = [json.loads(line) for line in open(run_path)]
    spans = [
        e
        for e in events
        if e.get("kind") == "span_end" and e.get("name") == "serve.batch"
    ]
    assert any("led-1" in (s["attrs"].get("request_ids") or []) for s in spans)
    reqs = [
        e
        for e in events
        if e.get("kind") == "event" and e.get("name") == "serve.request"
    ]
    assert any(r["attrs"]["request_id"] == "led-1" for r in reqs)
    summary = trace_report.summarize(trace_report.load(run_path))
    assert summary["source"] == "ledger"
    assert summary["outcomes"].get("completed", 0) >= 1
    led = next(
        r for r in summary["top_slow"] if r["request_id"] == "led-1"
    )
    assert led["apply_s"] is not None and led["queue_wait_s"] is not None
    # a rotated segment (run_<id>.jsonl.000001) is still ledger mode —
    # the size-cap rotation ships alongside this tool
    seg = run_path + ".000001"
    os.rename(run_path, seg)
    assert trace_report.load(seg)["source"] == "ledger"


# ------------------------------------------- durable trace dump (ISSUE 18)


def test_tracez_dump_writes_durable_snapshot_trace_report_reads(tmp_path):
    """POST /tracez/dump snapshots the recorder durably (atomic write +
    checksum sidecar) in exactly the format tools/trace_report.py's
    recorder mode parses."""
    from keystone_tpu.utils import durable

    import trace_report

    with _service(max_batch=4, max_wait_ms=2.0) as svc:
        with serve_http(
            svc, port=0, trace_dump_dir=str(tmp_path)
        ) as front:
            base = f"http://127.0.0.1:{front.port}"
            _post_json(
                base + "/predict",
                {"instance": [1.0] * DIM},
                headers={"X-Request-Id": "dump-me"},
            )
            status, body, _ = _post_json(base + "/tracez/dump", {})
            assert status == 200
            path = body["path"]
            assert os.path.dirname(path) == str(tmp_path)
            assert path.endswith(".json")  # recorder-dump mode selector
            assert body["stats"]["finished"] >= 1
    assert durable.verify_checksum(path, required=True)
    report = trace_report.summarize(trace_report.load(path))
    assert report["source"] == "recorder"
    rids = [r["request_id"] for r in report["top_slow"]]
    assert "dump-me" in rids
    # an explicit body dir overrides the configured one
    with _service(max_batch=4, max_wait_ms=2.0) as svc:
        with serve_http(svc, port=0) as front:
            base = f"http://127.0.0.1:{front.port}"
            _post_json(base + "/predict", {"instance": [1.0] * DIM})
            override = str(tmp_path / "override")
            status, body, _ = _post_json(
                base + "/tracez/dump", {"dir": override}
            )
            assert status == 200
            assert os.path.dirname(body["path"]) == override


def test_tracez_dump_without_dir_or_recorder_is_409():
    with _service(recorder=False) as svc:
        with serve_http(svc, port=0) as front:
            base = f"http://127.0.0.1:{front.port}"
            code = None
            try:
                _post_json(base + "/tracez/dump", {})
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 409  # recorder off
    with _service() as svc:
        with serve_http(svc, port=0) as front:  # no trace_dump_dir
            base = f"http://127.0.0.1:{front.port}"
            code = None
            try:
                _post_json(base + "/tracez/dump", {})
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 409  # nowhere to write


def test_trace_report_decomposes_cross_process_chain(tmp_path):
    """trace_report folds the stitched batch-record fields (worker,
    host, wire accounting, aligned worker spans) into the per-request
    breakdown and a per-worker fleet rollup."""
    import trace_report

    dump = {
        "traces": [
            {
                "request_id": "r1",
                "ts": 100.0,
                "outcome": "completed",
                "slow": False,
                "seconds": 0.02,
                "events": [
                    {
                        "t": 0.002,
                        "name": "serve.batch",
                        "attrs": {
                            "batch": "b1",
                            "replica": 0,
                            "queue_wait_seconds": 0.002,
                        },
                    }
                ],
            }
        ],
        "batches": [
            {
                "batch": "b1",
                "rows": 2,
                "bucket": 4,
                "seconds": 0.01,
                "worker": "net0",
                "host": "hostA",
                "wire": {"rtt_s": 0.0015, "send_s": 0.0006, "recv_s": 0.0004},
                "worker_spans": [
                    {"name": "worker.attach", "t_off": 0.001, "seconds": 0.0005},
                    {"name": "worker.apply", "t_off": 0.0015, "seconds": 0.008},
                ],
            }
        ],
        "ops": [],
    }
    path = str(tmp_path / "dump.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(dump, f)
    summary = trace_report.summarize(trace_report.load(path))
    (r,) = summary["top_slow"]
    assert r["worker"] == "net0" and r["host"] == "hostA"
    assert r["wire_rtt_s"] == 0.0015
    assert r["worker_apply_s"] == 0.008
    assert summary["critical_path_mean"]["worker_apply_s"] == 0.008
    assert summary["critical_path_mean"]["wire_rtt_s"] == 0.0015
    fleet = summary["fleet"]["net0"]
    assert fleet["host"] == "hostA" and fleet["flushes"] == 1
    assert fleet["apply_s_mean"] == 0.008
    text = trace_report.render(summary)
    assert "worker net0@hostA" in text
    assert "fleet (worker-shipped spans, stitched per flush):" in text


def test_cli_trace_dump_refuses_no_recorder(tmp_path):
    from keystone_tpu.cli import _serve_main

    with pytest.raises(SystemExit):
        _serve_main(
            [
                "--model",
                str(tmp_path / "m.pkl"),
                "--trace-dump",
                str(tmp_path),
                "--no-recorder",
            ]
        )
