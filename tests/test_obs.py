"""Observability tests: metrics registry + run ledger + wiring.

Tier-1 coverage the ISSUE pins:

- metrics counters fire on blockstore read/write and durable retries;
- span nesting + JSONL schema round-trip;
- disabled-mode zero-event / zero-overhead guarantee (no env, no
  ledger ⇒ no file, no events; ``KEYSTONE_METRICS=0`` ⇒ no recording);
- a chaos run's ledger carries fault injected stats;
- REGRESSION: executor profile timings exclude retry backoff sleeps and
  failed attempts (they skewed ProfilingAutoCacheRule placement);
- e2e: a pipeline fit under ``KEYSTONE_OBS_DIR`` yields a ledger whose
  obs_report summary has per-stage spans, a solver convergence series,
  I/O counters, and memory watermarks.
"""

import glob
import json
import os
import sys

import jax
import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.obs import ledger, metrics
from keystone_tpu.workflow import Dataset, Pipeline

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
)

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    """Every test starts with a fresh registry, no active ledger, and no
    obs env — and leaves the process the same way."""
    monkeypatch.delenv(ledger.ENV_DIR, raising=False)
    monkeypatch.delenv(metrics.ENV_DISABLE, raising=False)
    ledger.attach(None)
    metrics.reset()
    yield
    ledger.stop_run()
    ledger.attach(None)
    metrics.reset()


def _events(path):
    return [json.loads(line) for line in open(path)]


def _run_events(directory):
    paths = glob.glob(os.path.join(directory, "run_*.jsonl"))
    assert len(paths) == 1, paths
    return paths[0], _events(paths[0])


# ------------------------------------------------------------- registry


def test_metrics_counters_gauges_histograms():
    metrics.inc("a.count")
    metrics.inc("a.count", 2, site="s")
    metrics.observe("a.lat", 0.02)
    metrics.gauge_max("a.peak", 10)
    metrics.gauge_max("a.peak", 4)  # watermark: lower sample is ignored
    snap = metrics.snapshot()
    assert snap["counters"]["a.count"] == 1.0
    assert snap["counters"]["a.count{site=s}"] == 2.0
    assert snap["gauges"]["a.peak"] == 10.0
    assert snap["histograms"]["a.lat"]["count"] == 1
    assert metrics.REGISTRY.counter_total("a.count") == 3.0
    text = metrics.REGISTRY.to_prometheus_text()
    assert 'a_count_total{site="s"} 2' in text
    assert "a_lat_bucket" in text


def test_metrics_disabled_records_nothing(monkeypatch):
    monkeypatch.setenv(metrics.ENV_DISABLE, "0")
    metrics.inc("x")
    metrics.observe("y", 1.0)
    metrics.gauge_max("z", 1.0)
    snap = metrics.snapshot()
    assert not snap["counters"] and not snap["gauges"] and not snap["histograms"]


def test_blockstore_read_write_counters_fire(tmp_path):
    from keystone_tpu.workflow.blockstore import FeatureBlockStore

    x = np.random.default_rng(0).normal(size=(32, 12)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "store"), x, 8)
    assert metrics.REGISTRY.counter_value("blockstore.writes") == 1.0
    written = metrics.REGISTRY.counter_value("blockstore.write_bytes")
    assert written == 2 * 32 * 8 * 4  # two zero-padded 8-wide f32 blocks
    store.read_block(0)
    assert metrics.REGISTRY.counter_value("blockstore.reads") == 1.0
    assert metrics.REGISTRY.counter_value("blockstore.read_bytes") == 32 * 8 * 4


def test_durable_retry_and_corruption_counters(tmp_path):
    from keystone_tpu.utils import durable

    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise OSError("transient")
        return "ok"

    assert durable.with_retries(flaky, retries=3, sleep=lambda _: None) == "ok"
    assert metrics.REGISTRY.counter_value("durable.retries") == 2.0

    p = tmp_path / "state.bin"
    p.write_bytes(b"payload")
    durable.write_checksum(str(p))
    p.write_bytes(b"tampered")
    with pytest.raises(durable.CorruptStateError):
        durable.verify_checksum(str(p))
    assert metrics.REGISTRY.counter_value("durable.corruption") == 1.0


# --------------------------------------------------------------- ledger


def test_span_nesting_and_jsonl_schema_roundtrip(tmp_path):
    led = ledger.start_run(str(tmp_path))
    with ledger.span("outer", node="A") as sp:
        sp.set(attempts=2)
        with ledger.span("inner"):
            ledger.event("tick", k=1)
    ledger.stop_run()

    path, events = _run_events(str(tmp_path))
    kinds = [e["kind"] for e in events]
    assert kinds == [
        "run_start",
        "span_start",
        "span_start",
        "event",
        "span_end",
        "span_end",
        "metrics",
        "run_end",
    ]
    # every event carries the required schema fields
    for e in events:
        assert {"ts", "run_id", "seq", "kind", "name"} <= set(e)
        assert e["run_id"] == led.run_id
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    outer_start = events[1]
    inner_start = events[2]
    tick = events[3]
    inner_end, outer_end = events[4], events[5]
    # nesting: inner's parent is outer's span id; the event nests in inner
    assert inner_start["parent"] == outer_start["span"]
    assert tick["parent"] == inner_start["span"]
    assert inner_end["span"] == inner_start["span"]
    # span_end carries duration and the attrs accumulated while open
    assert outer_end["seconds"] >= 0
    assert outer_end["attrs"]["attempts"] == 2
    assert outer_end["attrs"]["node"] == "A"


def test_disabled_mode_emits_nothing(tmp_path, monkeypatch):
    assert ledger.active() is None
    with ledger.span("s") as sp:
        assert sp is None
        ledger.event("e")
    ledger.solver_epoch("bcd", epoch=0)
    assert glob.glob(str(tmp_path / "*.jsonl")) == []
    # env-var activation flows through the same frontends
    monkeypatch.setenv(ledger.ENV_DIR, str(tmp_path))
    with ledger.span("s2") as sp:
        assert sp is not None
    assert len(glob.glob(str(tmp_path / "run_*.jsonl"))) == 1


def test_env_dir_activates_pipeline_fit_ledger(tmp_path, monkeypatch):
    """e2e: KEYSTONE_OBS_DIR + a real Pipeline.fit() ⇒ a JSONL ledger
    with a pipeline.fit span, per-stage executor spans, a solver
    convergence series, and a metrics snapshot obs_report can fold."""
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.ops import LinearRectifier

    monkeypatch.setenv(ledger.ENV_DIR, str(tmp_path))
    rng = np.random.default_rng(0)
    x = Dataset(rng.normal(size=(96, 24)).astype(np.float32))
    y = Dataset(rng.normal(size=(96, 3)).astype(np.float32))
    pipe = Pipeline.of(LinearRectifier(0.0)).and_then(
        BlockLeastSquaresEstimator(block_size=8, num_iter=3, lam=1e-3), x, y
    )
    pipe.fit().block_until_ready()
    jax.effects_barrier()
    # close the env ledger so the JSONL is flushed and later tests are
    # isolated (the autouse fixture detaches; this closes)
    led = ledger.active()
    led.close()

    path, events = _run_events(str(tmp_path))
    names = {e["name"] for e in events}
    assert "pipeline.fit" in names
    stage_spans = [
        e for e in events if e["kind"] == "span_end" and e["name"] == "executor.stage"
    ]
    assert stage_spans, "no executor stage spans in ledger"
    assert all("retries" in (e.get("attrs") or {}) for e in stage_spans)
    solver = [e for e in events if e["name"] == "solver.epoch"]
    assert len(solver) == 3  # one per BCD epoch
    epochs = [e["attrs"]["epoch"] for e in solver]
    assert epochs == [0, 1, 2]
    assert all("objective" in e["attrs"] for e in solver)

    from obs_report import render, summarize

    summary = summarize(path)
    assert summary["stage_top"], summary
    assert summary["convergence"]["bcd"], summary
    assert summary["memory"]["host_max_rss_bytes"] is not None
    text = render(summary)
    assert "top stages by time" in text and "solver convergence" in text


def test_out_of_core_fit_ledger_has_io_and_convergence(tmp_path):
    """Streamed (out-of-core) fit: the ledger's summary carries
    blockstore I/O totals, the spill span, and the per-epoch series."""
    from keystone_tpu.loaders.stream import batched
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.workflow.dataset import StreamDataset

    rng = np.random.default_rng(1)
    x = rng.normal(size=(128, 24)).astype(np.float32)
    y = rng.normal(size=(128, 3)).astype(np.float32)
    led = ledger.start_run(str(tmp_path))
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=1e-3)
    est.fit_dataset(StreamDataset(batched(x, 32), n=128), Dataset(y))
    jax.effects_barrier()
    path = led.path
    ledger.stop_run()

    from obs_report import summarize

    summary = summarize(path)
    assert summary["io"]["blockstore_read_bytes"] > 0
    assert summary["io"]["blockstore_write_bytes"] > 0
    series = summary["convergence"]["bcd.out_of_core"]
    assert [pt["epoch"] for pt in series] == [0, 1]
    assert all(pt["epoch_seconds"] > 0 for pt in series)
    names = {e["name"] for e in _events(path)}
    assert "solver.spill" in names


def test_chaos_run_ledger_contains_fault_stats(tmp_path):
    """A recovered chaos fit leaves (a) injected-fault counters in the
    unified registry (mirrored from faults.py) and (b) per-restart
    faults.stats events in the ledger, emitted BEFORE stats are lost to
    any reset between attempts."""
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.workflow import fit_with_recovery

    rng = np.random.default_rng(2)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    y = rng.normal(size=(64, 2)).astype(np.float32)
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=1e-3)

    led = ledger.start_run(str(tmp_path))
    faults.reset_stats()
    # times=1 fails the (retry-less) first fit attempt; the restart
    # runs with the budget exhausted and completes
    with faults.inject("executor.stage:times=1:raise"):
        fit_with_recovery(
            lambda: est.with_data(Dataset(x), Dataset(y)), max_restarts=1
        )
    led.metrics_snapshot()
    path = led.path
    ledger.stop_run()

    assert (
        metrics.REGISTRY.counter_value("faults.injected", site="executor.stage")
        == 1.0
    )
    events = _events(path)
    stats_events = [e for e in events if e["name"] == "faults.stats"]
    assert stats_events, "no per-restart faults.stats event in ledger"
    st = stats_events[0]["attrs"]["stats"]
    assert st["executor.stage"]["injected"] == 1

    from obs_report import summarize

    summary = summarize(path)
    assert summary["faults"]["executor.stage"]["injected"] == 1
    assert summary["fault_restarts"]


# ---------------------------------------------------- executor timing fix


def test_profile_timings_exclude_backoff_and_failed_attempts():
    """REGRESSION (ISSUE 3 satellite): profile-mode stage timings used to
    start the clock before the retry loop, charging failed attempts AND
    backoff sleeps (≥50 ms each) to the stage — skewing cache placement.
    With one injected stage fault + retry, the successful attempt of a
    trivial transform must time far under the backoff floor."""
    from keystone_tpu.ops import LinearRectifier
    from keystone_tpu.utils import tracing

    rng = np.random.default_rng(3)
    data = Dataset(rng.normal(size=(32, 8)).astype(np.float32))
    pipe = Pipeline.of(LinearRectifier(0.0))

    from keystone_tpu.workflow.pipeline import PipelineEnv

    # warm-up pass: pays the one-time trace/compile of the stage so the
    # faulted run below times pure (sub-ms) compute, not compilation
    warm = tracing.stage_timings(pipe(data))
    assert any("LinearRectifier" in k for k in warm)

    metrics.reset()
    PipelineEnv.node_retries = 2
    try:
        # stage calls run in topological order (Dataset first): after=1
        # pins the injection to the LinearRectifier stage itself
        with faults.inject("executor.stage:after=1:times=1:raise"):
            timings = tracing.stage_timings(pipe(data))
    finally:
        PipelineEnv.node_retries = None
    hit = [k for k in timings if "LinearRectifier" in k]
    assert hit, timings
    # backoff's first delay is >= 50 ms; a timing that included it (or
    # the failed attempt) cannot come in under 40 ms
    assert timings[hit[0]] < 0.04, (
        f"stage timing {timings[hit[0]]:.3f}s includes retry backoff"
    )
    assert metrics.REGISTRY.counter_value("executor.stage_retries") >= 1.0
    assert metrics.REGISTRY.counter_total("executor.failed_attempt_seconds") > 0


def test_stream_retry_and_bad_batch_metrics():
    from keystone_tpu.loaders.stream import resilient

    calls = {"n": 0}

    def source():
        calls["n"] += 1

        def gen():
            yield np.zeros((4, 2))
            if calls["n"] < 99:  # always fails: batch 1 gets dropped
                raise OSError("flaky batch")
            yield np.ones((4, 2))

        return gen()

    src = resilient(source, retries=1, max_bad_batches=1, sleep=lambda _: None)
    delivered = list(src())
    assert len(delivered) == 1
    assert metrics.REGISTRY.counter_value("stream.retries") == 1.0
    assert metrics.REGISTRY.counter_value("stream.bad_batches") == 1.0
    snap = metrics.snapshot()
    assert any(
        k.startswith("stream.batch_seconds") for k in snap["histograms"]
    )


def test_solver_obs_numerics_bit_identical(tmp_path):
    """The observed program must compute the same bits as the inert one
    (the static obs flag only adds callbacks)."""
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.models.gmm import GaussianMixtureModelEstimator

    rng = np.random.default_rng(4)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    y = rng.normal(size=(64, 2)).astype(np.float32)
    bcd = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=1e-3)
    gmm = GaussianMixtureModelEstimator(3, max_iterations=3)

    m0 = bcd.fit_dataset(Dataset(x), Dataset(y))
    g0 = gmm.fit_dataset(Dataset(x))
    ledger.start_run(str(tmp_path))
    m1 = bcd.fit_dataset(Dataset(x), Dataset(y))
    g1 = gmm.fit_dataset(Dataset(x))
    jax.effects_barrier()
    ledger.stop_run()
    np.testing.assert_array_equal(np.asarray(m0.weights), np.asarray(m1.weights))
    np.testing.assert_array_equal(np.asarray(g0.means), np.asarray(g1.means))


# ------------------------------------------- ledger rotation (ISSUE 9)


def test_ledger_rotation_bounds_disk(tmp_path):
    """A size-capped RunLedger rotates the active file into numbered
    segments and prunes past keep-N — a long-lived serve --watch process
    under KEYSTONE_OBS_DIR cannot fill the disk."""
    rot0 = metrics.REGISTRY.counter_value("obs.ledger_rotations")
    led = ledger.RunLedger(str(tmp_path), max_bytes=2000, keep_segments=2)
    for i in range(400):
        led.event("rotation.filler", seconds=float(i))
    led.close()
    segments = sorted(
        p for p in os.listdir(tmp_path) if ".jsonl." in p
    )
    assert len(segments) == 2, segments  # oldest pruned down to keep-N
    # every suffix is numeric and monotonically increasing
    suffixes = [int(p.rsplit(".", 1)[1]) for p in segments]
    assert suffixes == sorted(suffixes)
    rotations = metrics.REGISTRY.counter_value("obs.ledger_rotations") - rot0
    assert rotations > 2  # more rotations happened than segments kept
    # the active file plus every kept segment is valid JSONL
    for name in segments + [os.path.basename(led.path)]:
        for line in open(os.path.join(tmp_path, name)):
            json.loads(line)
    # each segment stayed near the cap (one event of slack)
    for name in segments:
        assert os.path.getsize(os.path.join(tmp_path, name)) < 2000 + 500


def test_ledger_reopen_resumes_rotation_state(tmp_path):
    """Reopening an EXISTING run id (a restarted serve --watch process)
    must resume the byte count from the active file and the segment
    numbering past the highest kept suffix — restarting both at zero
    would overwrite a retained segment on the first rotation."""
    led = ledger.RunLedger(
        str(tmp_path), run_id="stable", max_bytes=1500, keep_segments=4
    )
    for i in range(120):
        led.event("rotation.filler", seconds=float(i))
    led.close()
    before = sorted(p for p in os.listdir(tmp_path) if ".jsonl." in p)
    assert before  # at least one rotation happened
    sizes = {
        p: os.path.getsize(os.path.join(tmp_path, p)) for p in before
    }
    led2 = ledger.RunLedger(
        str(tmp_path), run_id="stable", max_bytes=1500, keep_segments=4
    )
    assert led2._segment == max(int(p.rsplit(".", 1)[1]) for p in before)
    assert led2._bytes > 0  # counted the existing active file
    for i in range(120):
        led2.event("rotation.filler", seconds=float(i))
    led2.close()
    after = sorted(p for p in os.listdir(tmp_path) if ".jsonl." in p)
    # the first process's segments were continued past, never replaced
    for p in before:
        if p in after:  # not pruned by keep-N
            assert os.path.getsize(os.path.join(tmp_path, p)) == sizes[p]
    assert len(after) > len(before) or set(after) != set(before)


def test_ledger_rotation_env_knobs(tmp_path, monkeypatch):
    """KEYSTONE_OBS_MAX_BYTES / KEYSTONE_OBS_KEEP_SEGMENTS configure the
    env-activated ledger (the zero-code route)."""
    monkeypatch.setenv(ledger.ENV_MAX_BYTES, "1500")
    monkeypatch.setenv(ledger.ENV_KEEP_SEGMENTS, "1")
    led = ledger.RunLedger(str(tmp_path))
    assert led.max_bytes == 1500 and led.keep_segments == 1
    for i in range(200):
        led.event("rotation.filler", seconds=float(i))
    led.close()
    segments = [p for p in os.listdir(tmp_path) if ".jsonl." in p]
    assert len(segments) == 1
    # unset = unbounded (the historical default)
    monkeypatch.delenv(ledger.ENV_MAX_BYTES)
    led2 = ledger.RunLedger(str(tmp_path))
    assert led2.max_bytes is None
    led2.close()


# ------------------------- per-metric buckets + windowed histograms


def test_register_buckets_gives_ms_resolution():
    """Registered bounds apply to new histograms of that name and ride
    into the Prometheus rendering; unregistered names keep defaults."""
    metrics.register_buckets("bucketed.latency_seconds", metrics.LATENCY_MS_BUCKETS)
    metrics.observe("bucketed.latency_seconds", 0.003)
    metrics.observe("plain.latency_seconds", 0.003)
    text = metrics.REGISTRY.to_prometheus_text()
    assert 'bucketed_latency_seconds_bucket{le="0.0025"} 0' in text
    assert 'bucketed_latency_seconds_bucket{le="0.005"} 1' in text
    # the default grid has no 0.0025 bound
    assert 'plain_latency_seconds_bucket{le="0.0025"}' not in text
    assert 'plain_latency_seconds_bucket{le="0.005"} 1' in text


def test_register_buckets_preserves_kind_conflict_check():
    metrics.register_buckets("conflicted.seconds", (0.1, 1.0))
    with pytest.raises(metrics.MetricKindError):
        metrics.inc("conflicted.seconds")
    # and the registration (plus its histogram-kind claim) survives reset
    metrics.reset()
    with pytest.raises(metrics.MetricKindError):
        metrics.REGISTRY.set_gauge("conflicted.seconds", 1.0)
    assert metrics.REGISTRY.bucket_bounds("conflicted.seconds") == (0.1, 1.0)


def test_windowed_histogram_expires_old_intervals():
    """The ring covers only the window: samples older than
    window_seconds stop influencing the merged percentiles."""
    t = [0.0]
    wh = metrics.WindowedHistogram(
        "windowed.latency_seconds",
        window_seconds=10.0,
        intervals=5,
        bounds=metrics.LATENCY_MS_BUCKETS,
        clock=lambda: t[0],
    )
    for _ in range(50):
        wh.observe(4.0)  # slow epoch
    t[0] = 1.0
    for _ in range(50):
        wh.observe(0.002)
    m = wh.merged()
    assert m.count == 100
    assert wh.percentile(99) > 1.0  # the slow epoch dominates p99
    t[0] = 12.0  # the slow interval has aged out of the window
    for _ in range(50):
        wh.observe(0.002)
    assert wh.merged().count == 50
    assert wh.percentile(99) < 0.01
    # the cumulative registry series kept everything (feeds /metrics)
    snap = metrics.snapshot()["histograms"]["windowed.latency_seconds"]
    assert snap["count"] == 150


def test_windowed_histogram_percentiles_and_fraction():
    t = [0.0]
    wh = metrics.WindowedHistogram(
        "pct.latency_seconds",
        window_seconds=60.0,
        intervals=6,
        bounds=metrics.LATENCY_MS_BUCKETS,
        clock=lambda: t[0],
    )
    assert wh.percentile(99) is None  # empty window
    for v in (0.001, 0.002, 0.003, 0.004, 0.100):
        wh.observe(v)
    p50 = wh.percentile(50)
    assert 0.001 <= p50 <= 0.01
    assert wh.percentile(99) <= 0.100
    frac = wh.fraction_above(0.010)
    assert 0.1 <= frac <= 0.3  # 1 of 5 samples above 10 ms
    s = wh.summary()
    assert s["count"] == 5 and s["max"] == 0.100


def test_obs_report_covers_ingress_and_fleet_sections(tmp_path):
    """ISSUE 18: the offline report folds the front-end ingress block
    and the worker-shipped fleet series out of a metrics snapshot —
    per-label lines, never aggregated across workers."""
    led = ledger.start_run(str(tmp_path))
    reg = metrics.REGISTRY
    reg.inc("ingress.accepts", 3)
    reg.inc("ingress.bin_conns", 2)
    reg.inc("ingress.frames", 5)
    reg.inc("ingress.batch_rows", 40)
    reg.inc("ingress.frame_errors", 2, kind="magic")
    reg.observe("ingress.parse_seconds", 0.001)
    reg.observe("ingress.admit_seconds", 0.002)
    reg.observe(
        "serve.fleet.apply_seconds", 0.004, worker="w0", host="hA"
    )
    reg.observe(
        "serve.fleet.wire_rtt_seconds", 0.001, worker="w0", host="hA"
    )
    led.metrics_snapshot()
    path = led.path
    ledger.stop_run()

    from obs_report import render, summarize

    summary = summarize(path)
    ing = summary["ingress"]
    assert ing["accepts"] >= 3 and ing["bin_conns"] >= 2
    assert ing["frame_errors"].get("magic", 0) >= 2
    assert ing["parse_seconds"]["count"] >= 1
    fleet = summary["fleet"]
    apply_series = fleet["apply_seconds"]
    assert any("worker=w0" in k and "host=hA" in k for k in apply_series)
    assert any("worker=w0" in k for k in fleet["wire_rtt_seconds"])
    text = render(summary)
    assert "== ingress ==" in text
    assert "== fleet (worker-shipped) ==" in text
    assert "worker=w0" in text
