"""Mixed-precision (bf16 matmul + f32 accumulation) parity tests.

Policy: utils/precision.py — bf16 applies ONLY where it is a measured
bandwidth win: the SIFT windowing convs, the Pallas FV kernel's HBM
descriptor stream, and the PCA projection.  Ops where bf16 lost on TPU
(FV einsums, Convolver) or is numerically unsafe (CosineRandomFeatures)
are excluded and must be bit-identical under both modes.  Solvers pin
true-f32 MXU passes regardless of policy (sdot/solver_precision).

Documented tolerances vs the f32 path (bf16 has an 8-bit mantissa,
~0.4% relative rounding per input; f32 accumulation keeps reduction
error from growing with contraction length):

  - SIFT descriptors (L2-normalized, clamped 0.2): atol 2e-2
  - Pallas FV (bf16 descriptor stream):             atol 2e-2 · scale
  - PCA projection:                                 rtol 2e-2 + atol 2e-2·scale
  - End-to-end accuracy on the test problems:       unchanged
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.utils import precision


@pytest.fixture(autouse=True)
def _restore_policy():
    before = precision._MODE  # preserve an env-pinned KEYSTONE_MATMUL
    yield
    precision.set_matmul(before)


def _tol(ref, atol_frac=2e-2):
    return float(atol_frac * np.abs(np.asarray(ref)).max() + 1e-7)


def test_policy_modes():
    assert precision.matmul_mode() in ("bf16", "f32")
    with precision.matmul("bf16"):
        assert precision.matmul_mode() == "bf16"
        assert precision.fdtype() == jnp.bfloat16
        with precision.matmul("f32"):
            assert precision.matmul_mode() == "f32"
        assert precision.matmul_mode() == "bf16"
    with pytest.raises(ValueError):
        precision.set_matmul("fp8")


def test_sift_bf16_parity():
    from keystone_tpu.ops import SIFTExtractor

    rng = np.random.default_rng(0)
    imgs = rng.uniform(0, 1, (2, 48, 48)).astype(np.float32)
    sift = SIFTExtractor(step=6, bin_sizes=(4,))
    with precision.matmul("f32"):
        d32, _ = sift.apply_batch(imgs)
    with precision.matmul("bf16"):
        d16, _ = sift.apply_batch(imgs)
    np.testing.assert_allclose(np.asarray(d16), np.asarray(d32), atol=2e-2)


def test_fisher_einsum_excluded_from_policy():
    """The FV einsum path is output-bound — bf16 casts measured 0.64× on
    TPU — so it must be bit-identical under both modes."""
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.ops.fisher import FisherVector

    rng = np.random.default_rng(1)
    k, d, t, n = 8, 16, 64, 4
    gmm = GaussianMixtureModel(
        jnp.full((k,), 1.0 / k),
        jnp.asarray(rng.normal(size=(k, d)), jnp.float32),
        jnp.ones((k, d), jnp.float32),
    )
    xs = rng.normal(size=(n, t, d)).astype(np.float32)
    fv = FisherVector(gmm, use_pallas=False)
    with precision.matmul("f32"):
        f32_out = np.asarray(fv.apply_batch(jnp.asarray(xs)))
    with precision.matmul("bf16"):
        bf16_out = np.asarray(fv.apply_batch(jnp.asarray(xs)))
    np.testing.assert_array_equal(bf16_out, f32_out)


def test_fisher_pallas_bf16_parity():
    """Interpret-mode kernel: bf16 descriptor stream vs f32."""
    from keystone_tpu.ops.fisher_pallas import fisher_encode_pallas

    rng = np.random.default_rng(2)
    k, d, t, n = 8, 16, 128, 2
    xs = jnp.asarray(rng.normal(size=(n, t, d)), jnp.float32)
    mask = jnp.ones((n, t), jnp.float32)
    w = jnp.full((k,), 1.0 / k)
    mu = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    var = jnp.ones((k, d), jnp.float32)
    f32_out = np.asarray(
        fisher_encode_pallas(xs, mask, w, mu, var, interpret=True, mxu="f32")
    )
    bf16_out = np.asarray(
        fisher_encode_pallas(xs, mask, w, mu, var, interpret=True, mxu="bf16")
    )
    np.testing.assert_allclose(bf16_out, f32_out, atol=_tol(f32_out))


def test_convolver_excluded_from_policy():
    """Convolver is compute-bound (bf16 measured 0.94× on TPU): excluded,
    bit-identical under both modes."""
    from keystone_tpu.ops import Convolver

    rng = np.random.default_rng(3)
    imgs = rng.uniform(0, 1, (2, 16, 16, 3)).astype(np.float32)
    filt = rng.normal(size=(8, 5, 5, 3)).astype(np.float32)
    conv = Convolver(jnp.asarray(filt))
    with precision.matmul("f32"):
        o32 = np.asarray(conv.apply_batch(jnp.asarray(imgs)))
    with precision.matmul("bf16"):
        o16 = np.asarray(conv.apply_batch(jnp.asarray(imgs)))
    np.testing.assert_array_equal(o16, o32)


def test_cosine_features_excluded_from_policy():
    """CosineRandomFeatures is phase-sensitive (unbounded xWᵀ wraps
    through cos), so it must stay f32 under the bf16 policy."""
    from keystone_tpu.ops import CosineRandomFeatures

    rng = np.random.default_rng(4)
    xs = rng.normal(size=(16, 32)).astype(np.float32) * 4.0
    crf = CosineRandomFeatures.init(32, 64, gamma=1.0, seed=0)
    with precision.matmul("f32"):
        o32 = np.asarray(crf.apply_batch(jnp.asarray(xs)))
    with precision.matmul("bf16"):
        o16 = np.asarray(crf.apply_batch(jnp.asarray(xs)))
    np.testing.assert_array_equal(o16, o32)


def test_block_predict_excluded_from_policy():
    from keystone_tpu.models import BlockLeastSquaresEstimator

    rng = np.random.default_rng(5)
    x = rng.normal(size=(128, 40)).astype(np.float32)
    w = rng.normal(size=(40, 4)).astype(np.float32)
    lbl = (x @ w).argmax(1)
    y = -np.ones((128, 4), np.float32)
    y[np.arange(128), lbl] = 1.0
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=3, lam=1e-3)
    model = est.fit_arrays(x, y)
    with precision.matmul("f32"):
        s32 = np.asarray(model.apply_batch(jnp.asarray(x)))
    with precision.matmul("bf16"):
        s16 = np.asarray(model.apply_batch(jnp.asarray(x)))
    np.testing.assert_array_equal(s16, s32)


def test_solver_fit_unaffected_by_policy():
    """Gramians/Cholesky never downcast: fitted weights are identical
    under both policies (fit consumes raw arrays, no featurize matmuls)."""
    from keystone_tpu.models import BlockWeightedLeastSquaresEstimator

    rng = np.random.default_rng(6)
    x = rng.normal(size=(96, 24)).astype(np.float32)
    lbl = rng.integers(0, 3, size=96)
    y = -np.ones((96, 3), np.float32)
    y[np.arange(96), lbl] = 1.0
    est = BlockWeightedLeastSquaresEstimator(block_size=8, num_iter=2, lam=1e-2)
    with precision.matmul("bf16"):
        w16 = np.asarray(est.fit_arrays(x, y).flat_weights)
    with precision.matmul("f32"):
        w32 = np.asarray(est.fit_arrays(x, y).flat_weights)
    np.testing.assert_allclose(w16, w32, atol=1e-6)


def test_jit_cache_retraces_on_policy_flip():
    """The per-transformer jit cache keys on the policy mode: flipping it
    must produce the (slightly) different bf16 result, not a stale f32
    executable's output."""
    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.workflow import Dataset

    rng = np.random.default_rng(7)
    xs = rng.normal(size=(32, 64)).astype(np.float32)
    pca = PCATransformer(jnp.asarray(rng.normal(size=(64, 16)), jnp.float32))
    ds = Dataset(xs)
    with precision.matmul("f32"):
        o32 = pca.apply_dataset(ds).numpy()
    with precision.matmul("bf16"):
        o16 = pca.apply_dataset(ds).numpy()
    assert not np.array_equal(o16, o32), "policy flip reused a stale executable"
    np.testing.assert_allclose(o16, o32, rtol=2e-2, atol=_tol(o32))


def test_end_to_end_accuracy_unchanged_bf16():
    """The CIFAR-style conv pipeline reaches the same test accuracy under
    bf16 featurize as under f32."""
    from keystone_tpu.ops import Convolver, Pooler, SymmetricRectifier
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.workflow import Dataset, Pipeline, transformer

    rng = np.random.default_rng(8)
    n, hw, c, k = 96, 12, 3, 3
    imgs = rng.uniform(0, 1, (n, hw, hw, c)).astype(np.float32)
    lbl = rng.integers(0, k, size=n)
    for i in range(n):  # class-dependent planted pattern
        imgs[i, :4, :4, lbl[i] % c] += 1.5
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lbl] = 1.0
    filt = rng.normal(size=(8, 4, 4, c)).astype(np.float32)

    def build():
        return (
            Pipeline.of(Convolver(jnp.asarray(filt)))
            .and_then(SymmetricRectifier())
            .and_then(Pooler(3, 3))
            .and_then(transformer(lambda v: v.reshape(-1), name="Flatten"))
        )

    accs = {}
    for mode in ("f32", "bf16"):
        with precision.matmul(mode):
            pipe = build().and_then(
                BlockLeastSquaresEstimator(block_size=32, num_iter=3, lam=1e-3),
                Dataset(imgs),
                Dataset(y),
            )
            fitted = pipe.fit()
            pred = fitted(Dataset(imgs)).get().numpy()
            accs[mode] = (pred.argmax(1) == lbl).mean()
    assert accs["bf16"] == accs["f32"] == 1.0, accs
