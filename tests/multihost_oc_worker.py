"""Worker for the per-process-sharded out-of-core store test.

Two processes: each spills ONLY its row slice of a global feature
matrix to a local-disk FeatureBlockStore, then the weighted BCD fit
sweeps globally-staged blocks (multihost.global_rows_from_local) and
must match the exact in-memory fit of the FULL data — no process ever
holds the whole matrix (the pod analogue of per-executor spilled
feature partitions).
"""

import os
import sys


def main() -> None:
    coordinator, num_procs, pid, tmpdir = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from keystone_tpu.parallel import multihost, set_mesh

    multihost.initialize(
        coordinator_address=coordinator, num_processes=num_procs, process_id=pid
    )
    import numpy as np

    mesh = multihost.hybrid_mesh(model_parallelism=1)
    set_mesh(mesh)

    from keystone_tpu.models import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.workflow.blockstore import FeatureBlockStore

    rng = np.random.default_rng(0)
    n, d, k = 128, 48, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    lbl = rng.choice(k, size=n, p=[0.6, 0.2, 0.12, 0.08])
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lbl] = 1.0

    # each process spills ONLY its slice to its "local" disk
    sl = multihost.process_batch_slice(n)
    store = FeatureBlockStore.from_array(
        os.path.join(tmpdir, f"shard{pid}"), x[sl], block_size=16
    )
    labels = multihost.make_global_dataset(y[sl], global_n=n)

    est = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=3, lam=1e-2, mixture_weight=0.5
    )
    oc = est.fit_store(store, labels)
    ref = est.fit_arrays(x, y)  # in-memory fit of the FULL data
    err = np.abs(
        np.asarray(multihost.gather_to_host(oc.flat_weights))
        - np.asarray(ref.flat_weights)
    ).max()
    assert err < 5e-4, f"sharded-store fit mismatch: {err}"
    print(f"MULTIHOST_OC_OK pid={pid} err={err:.2e}", flush=True)


if __name__ == "__main__":
    main()
