"""L-BFGS mid-fit checkpoint/resume (VERDICT r3 weak-3).

Both BCD solvers checkpoint per epoch; the L-BFGS family previously had
no mid-fit checkpoint at all — the one solver family where a kill lost
everything.  These tests pin: (1) the chunked resumable driver matches
the single-scan jitted fit, (2) an interrupted fit RESUMES from the
carry (not from scratch) and lands on the uninterrupted result, (3) a
different problem's checkpoint is rejected by fingerprint, (4) the
sparse path at vocab scale round-trips through the checkpoint.
"""

import os

import numpy as np
import pytest

import keystone_tpu.models.lbfgs as lb
from keystone_tpu.models.lbfgs import DenseLBFGSwithL2, SparseLBFGSwithL2
from keystone_tpu.workflow import Dataset


def _dense_problem(n=96, d=12, k=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    y = (x @ w + 0.01 * rng.normal(size=(n, k))).astype(np.float32)
    return x, y


def test_dense_checkpointed_matches_plain_fit(tmp_path, mesh):
    x, y = _dense_problem()
    est = DenseLBFGSwithL2(lam=1e-3, num_iterations=25, history=5)
    plain = est.fit_dataset(Dataset(x), Dataset(y))
    ckpt = est.fit_checkpointed(
        Dataset(x), Dataset(y), checkpoint_dir=str(tmp_path), checkpoint_every=7
    )
    np.testing.assert_allclose(
        np.asarray(ckpt.weights), np.asarray(plain.weights), atol=2e-4
    )
    assert os.path.exists(tmp_path / "lbfgs_dense.npz")


def test_dense_interrupted_resumes_and_matches(tmp_path, mesh):
    """Kill the fit mid-chunk; the rerun must RESUME (load_cb hit at
    it>0, fewer chunks executed) and land on the uninterrupted model."""
    x, y = _dense_problem()
    est = DenseLBFGSwithL2(lam=1e-3, num_iterations=24, history=5)
    control = est.fit_checkpointed(
        Dataset(x), Dataset(y),
        checkpoint_dir=str(tmp_path / "control"), checkpoint_every=6,
    )

    # crash injection: die after 2 completed chunks (12 iterations)
    orig = lb.lbfgs_minimize_resumable
    state = {"chunks": 0}

    def crashing(vag, data, x0, **kw):
        real_save = kw.get("save_cb")

        def counting_save(it, carry):
            real_save(it, carry)
            state["chunks"] += 1
            if state["chunks"] == 2:
                raise RuntimeError("injected mid-fit kill")

        kw["save_cb"] = counting_save
        return orig(vag, data, x0, **kw)

    lb.lbfgs_minimize_resumable = crashing
    try:
        with pytest.raises(RuntimeError, match="injected"):
            est.fit_checkpointed(
                Dataset(x), Dataset(y),
                checkpoint_dir=str(tmp_path / "crash"), checkpoint_every=6,
            )
    finally:
        lb.lbfgs_minimize_resumable = orig

    # the carry survived at iteration 12
    with np.load(tmp_path / "crash" / "lbfgs_dense.npz") as z:
        assert int(z["it"]) == 12
        assert int(z["count"]) > 0  # real s/y history, not a fresh carry

    # resume: instrument the chunk loop via save_cb call count — a
    # resumed 24-iteration fit with every=6 from it=12 saves exactly
    # twice (18, 24); from scratch it would save 4 times
    saves = []
    orig2 = lb._lbfgs_checkpoint_callbacks

    def counting_callbacks(*a, **kw):
        load_cb, save_cb = orig2(*a, **kw)

        def save(it, carry):
            saves.append(it)
            save_cb(it, carry)

        return load_cb, save

    lb._lbfgs_checkpoint_callbacks = counting_callbacks
    try:
        resumed = est.fit_checkpointed(
            Dataset(x), Dataset(y),
            checkpoint_dir=str(tmp_path / "crash"), checkpoint_every=6,
        )
    finally:
        lb._lbfgs_checkpoint_callbacks = orig2
    assert saves == [18, 24], saves
    np.testing.assert_allclose(
        np.asarray(resumed.weights), np.asarray(control.weights), atol=1e-5
    )


def test_checkpoint_rejected_for_different_problem(tmp_path, mesh):
    """A checkpoint from different data/λ must not be resumed."""
    x, y = _dense_problem(seed=0)
    est = DenseLBFGSwithL2(lam=1e-3, num_iterations=10, history=4)
    est.fit_checkpointed(
        Dataset(x), Dataset(y), checkpoint_dir=str(tmp_path), checkpoint_every=5
    )
    x2, y2 = _dense_problem(seed=7)
    plain = est.fit_dataset(Dataset(x2), Dataset(y2))
    ckpt = est.fit_checkpointed(
        Dataset(x2), Dataset(y2),
        checkpoint_dir=str(tmp_path), checkpoint_every=5,
    )
    np.testing.assert_allclose(
        np.asarray(ckpt.weights), np.asarray(plain.weights), atol=2e-4
    )
    # λ change likewise restarts (fingerprint covers the objective)
    est2 = DenseLBFGSwithL2(lam=1e-1, num_iterations=10, history=4)
    plain2 = est2.fit_dataset(Dataset(x2), Dataset(y2))
    ckpt2 = est2.fit_checkpointed(
        Dataset(x2), Dataset(y2),
        checkpoint_dir=str(tmp_path), checkpoint_every=5,
    )
    np.testing.assert_allclose(
        np.asarray(ckpt2.weights), np.asarray(plain2.weights), atol=2e-4
    )


def test_completed_checkpoint_not_reused_for_shorter_fit(tmp_path, mesh):
    """A completed 16-iteration fit leaves its carry on disk; a later
    8-iteration request on the same problem must refit from scratch,
    never silently return the more-iterated weights."""
    x, y = _dense_problem()
    long = DenseLBFGSwithL2(lam=1e-3, num_iterations=16, history=4)
    long_model = long.fit_checkpointed(
        Dataset(x), Dataset(y), checkpoint_dir=str(tmp_path), checkpoint_every=4
    )
    short = DenseLBFGSwithL2(lam=1e-3, num_iterations=8, history=4)
    fresh = short.fit_dataset(Dataset(x), Dataset(y))
    got = short.fit_checkpointed(
        Dataset(x), Dataset(y), checkpoint_dir=str(tmp_path), checkpoint_every=4
    )
    np.testing.assert_allclose(
        np.asarray(got.weights), np.asarray(fresh.weights), atol=2e-4
    )
    # and the 8-iter weights genuinely differ from the 16-iter ones
    assert np.abs(
        np.asarray(got.weights) - np.asarray(long_model.weights)
    ).max() > 1e-6


def test_sparse_checkpointed_vocab_scale_resumes(tmp_path, mesh):
    """Sparse path at vocab scale (d=50k here; the pattern is the 1M
    fit): interrupted fit resumes from the saved carry and matches the
    uninterrupted checkpointed fit exactly, and the plain jitted fit to
    solver tolerance."""
    import scipy.sparse as sp

    rng = np.random.default_rng(1)
    n, d, k, nnz = 192, 50_000, 3, 8
    rows = []
    for _ in range(n):
        idx = rng.choice(d, size=nnz, replace=False)
        rows.append(
            sp.csr_matrix(
                (rng.normal(size=nnz).astype(np.float32), (np.zeros(nnz), idx)),
                shape=(1, d),
            )
        )
    y = rng.normal(size=(n, k)).astype(np.float32)

    est = SparseLBFGSwithL2(lam=1e-2, num_iterations=12, history=4)
    plain = est.fit_dataset(
        Dataset(rows), Dataset(y)
    )
    control = est.fit_checkpointed(
        Dataset(rows), Dataset(y),
        checkpoint_dir=str(tmp_path / "control"), checkpoint_every=4,
    )
    np.testing.assert_allclose(
        np.asarray(control.weights), np.asarray(plain.weights), atol=5e-4
    )

    # interrupt after the first save, then resume
    orig = lb._lbfgs_checkpoint_callbacks

    def crashing_callbacks(*a, **kw):
        load_cb, save_cb = orig(*a, **kw)

        def save(it, carry):
            save_cb(it, carry)
            if it == 4:
                raise RuntimeError("injected mid-fit kill")

        return load_cb, save

    lb._lbfgs_checkpoint_callbacks = crashing_callbacks
    try:
        with pytest.raises(RuntimeError, match="injected"):
            est.fit_checkpointed(
                Dataset(rows), Dataset(y),
                checkpoint_dir=str(tmp_path / "crash"), checkpoint_every=4,
            )
    finally:
        lb._lbfgs_checkpoint_callbacks = orig
    with np.load(tmp_path / "crash" / "lbfgs_sparse.npz") as z:
        assert int(z["it"]) == 4

    resumed = est.fit_checkpointed(
        Dataset(rows), Dataset(y),
        checkpoint_dir=str(tmp_path / "crash"), checkpoint_every=4,
    )
    np.testing.assert_allclose(
        np.asarray(resumed.weights), np.asarray(control.weights), atol=1e-5
    )
