"""Parity: the native fused text chain (ops/nlp_native +
native/keystone_native.cpp ks_text_*) against the pure-Python
per-doc chain it replaces (VERDICT r4 item 6).

The df TIE order is documented as divergent (Python Counter.most_common
inherits process-salted set iteration; native is deterministic by
(-df, first-doc, term)), so df parity is asserted on the full
term→count MAP and featurize parity on rows given one shared vocab."""

import collections

import numpy as np
import pytest

from keystone_tpu.ops import nlp_native
from keystone_tpu.ops.nlp import (
    CommonSparseFeatures,
    LowerCase,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trimmer,
    log_tf,
)
from keystone_tpu.workflow.dataset import StreamDataset

pytestmark = pytest.mark.skipif(
    not nlp_native.available(), reason="native text library unavailable"
)

DOCS = [
    "  Hello, world! hello AGAIN ",
    "the quick brown fox, the quick",
    "it's a test; it's ONLY a test",
    "numbers 123 and 123 and letters",
    "",
    "    ",
    "don't DON'T don't",
    "unicode café stays café split",
] * 3


def _chained_stream(docs, batch=4):
    def src():
        for i in range(0, len(docs), batch):
            yield docs[i : i + batch]

    out = StreamDataset(src, n=len(docs), host=True)
    stages = [
        Trimmer(),
        LowerCase(),
        Tokenizer(),
        NGramsFeaturizer((1, 2)),
        TermFrequency(log_tf),
    ]
    for t in stages:
        out = t.apply_dataset(out)
    return out, stages


def _py_dicts(docs):
    t, lc, tok, ng, tf = (
        Trimmer(), LowerCase(), Tokenizer(), NGramsFeaturizer((1, 2)),
        TermFrequency(log_tf),
    )
    return [tf.apply_one(ng.apply_one(tok.apply_one(lc.apply_one(t.apply_one(d)))))
            for d in docs]


def test_df_counts_match_python():
    out, stages = _chained_stream(DOCS)
    cfg = nlp_native.chain_config(stages)
    assert cfg is not None
    acc = nlp_native.DfAccumulator(cfg)
    for i in range(0, len(DOCS), 4):
        acc.update(DOCS[i : i + 4])
    native = dict(acc.topn(100000))
    acc.close()

    df = collections.Counter()
    for d in _py_dicts(DOCS):
        df.update(set(d.keys()))
    assert native == dict(df)


def test_fit_through_stream_uses_native_and_matches():
    out, _ = _chained_stream(DOCS)
    model = CommonSparseFeatures(64, sparse_output=False).fit_dataset(out)
    # every Python-counted term's df rank set must match on distinct dfs;
    # here just assert the vocab covers the same term SET as Python's
    # top-64 (the corpus has < 64 distinct terms, so no tie pressure)
    df = collections.Counter()
    for d in _py_dicts(DOCS):
        df.update(set(d.keys()))
    assert set(model.vocab) == set(df)


@pytest.mark.parametrize("sparse", [False, True])
def test_featurize_rows_match_python(sparse):
    out, _ = _chained_stream(DOCS)
    dicts = _py_dicts(DOCS)
    model = CommonSparseFeatures(128, sparse_output=sparse).fit_arrays(dicts)
    assert model._apply_native_stream(out) is not None  # gate engaged
    want = np.stack(
        [
            (r.toarray()[0] if sparse else r)
            for r in (model.apply_one(d) for d in dicts)
        ]
    )
    feat = model.apply_dataset(out)
    rows = []
    for b in feat.batches():
        for r in b:
            rows.append(r.toarray()[0] if sparse else np.asarray(r))
    got = np.stack(rows)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_nondefault_pattern_falls_back_to_python():
    def src():
        yield ["a-b c", "d-e f"]

    out = StreamDataset(src, n=2, host=True)
    stages = [Tokenizer(pattern=r"[^a-z-]+"), NGramsFeaturizer((1,)),
              TermFrequency(None)]
    for t in stages:
        out = t.apply_dataset(out)
    assert nlp_native.chain_config(stages) is None  # unsupported pattern
    model = CommonSparseFeatures(16).fit_dataset(out)  # python path, no crash
    assert ("a-b",) in model.vocab


@pytest.mark.parametrize("sparse", [False, True])
def test_hashtf_rows_match_python(sparse):
    """Native blake2b(repr(term)) must reproduce stable_term_hash
    exactly — including apostrophe tokens, whose Python repr switches to
    double quotes — and collision accumulation must match to 1e-6."""
    from keystone_tpu.ops.nlp import HashingTF

    out, _ = _chained_stream(DOCS)
    dicts = _py_dicts(DOCS)
    model = HashingTF(num_features=128, sparse_output=sparse)  # force collisions
    # the native gate must actually ENGAGE for this chain — otherwise the
    # comparison below is vacuously Python-vs-Python
    assert model._apply_native_stream(out) is not None
    want = np.stack(
        [
            (r.toarray()[0] if sparse else r)
            for r in (model.apply_one(d) for d in dicts)
        ]
    )
    feat = model.apply_dataset(out)
    rows = []
    for b in feat.batches():
        for r in b:
            rows.append(r.toarray()[0] if sparse else np.asarray(r))
    got = np.stack(rows)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_in_memory_chain_engages_native_and_matches():
    """Non-stream apps (synthetic/loaded host Datasets) ride the same
    native path via with_items provenance: fit + featurize must match
    the per-item Python chain."""
    from keystone_tpu.ops.nlp import CommonSparseFeatures, HashingTF
    from keystone_tpu.workflow.dataset import Dataset

    ds = Dataset(list(DOCS))
    out = ds
    for t in (Trimmer(), LowerCase(), Tokenizer(), NGramsFeaturizer((1, 2)),
              TermFrequency(log_tf)):
        out = t.apply_dataset(out)
    dicts = _py_dicts(DOCS)

    est = CommonSparseFeatures(64, sparse_output=False)
    assert est._fit_native_items(out) is not None  # gate engaged
    model = est.fit_dataset(out)
    import collections

    df = collections.Counter()
    for d in dicts:
        df.update(set(d.keys()))
    assert set(model.vocab) == set(df)

    model_py = CommonSparseFeatures(128).fit_arrays(dicts)
    assert model_py._apply_native_items(out) is not None
    got = np.asarray(model_py.apply_dataset(out).array)
    want = np.stack([model_py.apply_one(d) for d in dicts])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)

    h = HashingTF(num_features=128)
    assert h._apply_native_items(out) is not None
    got = np.asarray(h.apply_dataset(out).array)
    want = np.stack([h.apply_one(d) for d in dicts])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_native_text_property_random_docs():
    """Property guard: random printable-ASCII docs (incl. apostrophes,
    digits, punctuation, odd whitespace) must produce IDENTICAL df maps
    and featurize rows on the native and Python chains."""
    import collections

    from hypothesis import given, settings
    from hypothesis import strategies as st

    from keystone_tpu.ops.nlp import CommonSparseFeatures, HashingTF

    alphabet = st.sampled_from(
        list("abcXYZ019'!.,;- \t\n") + ["don't", "  ", "café"]
    )
    docs_strategy = st.lists(
        st.lists(alphabet, max_size=30).map("".join), min_size=1, max_size=8
    )

    @settings(max_examples=25, deadline=None)
    @given(docs_strategy)
    def check(docs):
        out, stages = _chained_stream(docs, batch=3)
        cfg = nlp_native.chain_config(stages)
        dicts = _py_dicts(docs)

        acc = nlp_native.DfAccumulator(cfg)
        for i in range(0, len(docs), 3):
            acc.update(docs[i : i + 3])
        native_df = dict(acc.topn(100000))
        acc.close()
        df = collections.Counter()
        for d in dicts:
            df.update(set(d.keys()))
        assert native_df == dict(df)

        model = CommonSparseFeatures(64).fit_arrays(dicts)
        want = np.stack([model.apply_one(d) for d in dicts])
        got = np.concatenate(
            [np.asarray(b) for b in model.apply_dataset(out).batches()], axis=0
        )
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)

        h = HashingTF(num_features=64)
        wanth = np.stack([h.apply_one(d) for d in dicts])
        goth = np.concatenate(
            [np.asarray(b) for b in h.apply_dataset(out).batches()], axis=0
        )
        np.testing.assert_allclose(goth, wanth, rtol=1e-6, atol=1e-7)

    check()
