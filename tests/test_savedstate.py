"""Cross-process pipeline checkpoint/resume.

Reference: workflow/SavedStateLoadRule.scala + ExtractSaveablePrefixes —
a later RUN (new JVM there, new Python process here) reloads previously
materialized pipeline prefixes from the state dir instead of recomputing
(SURVEY.md §5 "Checkpoint/resume").  Loader datasets are named, which is
what keeps prefix signatures stable across processes.
"""

import os
import re
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(__file__), "savedstate_worker.py")


def _run(phase: str, state_dir: str):
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=cwd + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    return subprocess.run(
        [sys.executable, WORKER, phase, state_dir],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=cwd,
    )


def _checksum(out: str) -> str:
    m = re.search(r"checksum=([0-9.]+)", out)
    assert m, out
    return m.group(1)


def test_saved_prefixes_reload_in_new_process(tmp_path):
    state = str(tmp_path / "state")
    save = _run("save", state)
    assert save.returncode == 0, save.stderr[-2000:]
    assert "SAVED n=" in save.stdout and "SAVED n=0" not in save.stdout
    assert os.listdir(state), "no state files written"

    load = _run("load", state)
    assert load.returncode == 0, load.stderr[-2000:]
    # the optimizer must have RELOADED the prefix, not recomputed it
    assert "reloaded saved prefix" in (load.stderr + load.stdout), (
        load.stderr[-2000:]
    )
    assert _checksum(load.stdout) == _checksum(save.stdout)
