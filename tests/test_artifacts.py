"""AOT freeze artifacts: the pre-lowered-executable tier and its
fallback ladder (ISSUE 11).

What must hold:

- an exported-then-installed bucket program produces BIT-IDENTICAL
  predictions to the freshly-compiled executor walk;
- any mismatch — jax version skew, backend skew, signature drift, a
  corrupt blob or manifest — silently falls one rung down the ladder
  (artifact → compile cache → fresh compile), counted as
  ``serve.artifact_fallbacks``, and NEVER fails a deploy/swap/heal;
- the supervisor's heal primes replacements from artifacts (no fresh
  compile-tier primes — compile time must not be recovery time);
- with no artifacts installed the path is inert (one empty-dict check;
  solver HLO unchanged with the machinery exercised).
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.obs import metrics
from keystone_tpu.ops.stats import NormalizeRows
from keystone_tpu.serve import ModelRegistry, RegistryWatcher, serve
from keystone_tpu.workflow import ArtifactMismatch, Dataset, Pipeline
from keystone_tpu.workflow.pipeline import FrozenApplier

pytestmark = pytest.mark.serve

DIM = 8
CLASSES = 3
BUCKETS = (2, 4)


def _pipeline(seed: int = 0):
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(DIM, CLASSES)).astype(np.float32))
    return (Pipeline.of(NormalizeRows()) | LinearMapper(w)).fit()


def _example():
    return np.zeros((DIM,), np.float32)


def _one_device():
    """Pin serve tests to one explicit device: the fleet placement
    discipline, and exact bucket shapes — the session's 4x2 test mesh
    would otherwise pad/shard deviceless batches past the buckets."""
    import jax

    return [jax.devices()[0]]


def _ds(x):
    """An UNSHARDED dataset at the batch's exact shape (what the fleet
    path feeds the applier); the test mesh would pad a bare array."""
    return Dataset(x, shard=False)


def _counter(name: str) -> float:
    return metrics.REGISTRY.counter_total(name)


def _prime_count(source: str) -> int:
    hists = metrics.snapshot().get("histograms") or {}
    h = hists.get(f"serve.prime_seconds{{source={source}}}") or {}
    return int(h.get("count") or 0)


@pytest.fixture(scope="module")
def exported():
    """One pipeline + its exported bundle, shared across the module
    (exports re-trace the whole graph; one is plenty)."""
    pipe = _pipeline()
    frozen = pipe.freeze()
    bundle = frozen.export_artifacts(example=_example(), buckets=BUCKETS)
    return pipe, frozen, bundle


@pytest.fixture()
def registry(tmp_path, exported):
    pipe, _frozen, bundle = exported
    reg = ModelRegistry(str(tmp_path / "registry"))
    version = reg.publish(pipe, artifacts=bundle)
    return reg, version


# ----------------------------------------------------------- roundtrip


def test_roundtrip_bit_parity_vs_fresh_compile(exported):
    """The installed AOT program and the freshly-compiled walk must
    agree bit-for-bit at every bucket shape."""
    pipe, frozen, bundle = exported
    fresh = pipe.freeze()  # a separate applier: pure walk, no programs
    target = pipe.freeze()
    assert target.install_artifacts(bundle) == len(BUCKETS)
    rng = np.random.default_rng(1)
    for b in BUCKETS:
        x = rng.normal(size=(b, DIM)).astype(np.float32)
        via_artifact = np.asarray(target(_ds(x)).array)
        via_walk = np.asarray(fresh(_ds(x)).array)
        assert via_artifact.tobytes() == via_walk.tobytes()


def test_non_bucket_shape_rides_the_walk(exported):
    """A shape with no installed program silently uses the executor
    walk — artifacts narrow nothing."""
    pipe, _frozen, bundle = exported
    ap = pipe.freeze()
    ap.install_artifacts(bundle)
    x = np.random.default_rng(2).normal(size=(3, DIM)).astype(np.float32)
    out = np.asarray(ap(_ds(x)).array)
    assert out.shape == (3, CLASSES)


def test_registry_artifacts_roundtrip(registry, exported):
    _pipe, _frozen, bundle = exported
    reg, version = registry
    loaded = reg.load_artifacts(version)
    assert loaded is not None
    assert loaded["manifest"]["signature"] == bundle["manifest"]["signature"]
    assert set(loaded["blobs"]) == set(bundle["blobs"])
    for key, blob in bundle["blobs"].items():
        assert bytes(loaded["blobs"][key]) == bytes(blob)


# ------------------------------------------------------ fallback ladder


def test_jax_version_skew_falls_back(exported):
    pipe, _frozen, bundle = exported
    skewed = {
        "manifest": {**bundle["manifest"], "jax_version": "0.0.1"},
        "blobs": bundle["blobs"],
    }
    ap = pipe.freeze()
    f0 = _counter("serve.artifact_fallbacks")
    assert ap.install_artifacts(skewed) == 0
    assert ap.installed_buckets() == 0
    assert _counter("serve.artifact_fallbacks") == f0 + 1
    with pytest.raises(ArtifactMismatch):
        ap.install_artifacts(skewed, strict=True)


def test_backend_skew_falls_back(exported):
    pipe, _frozen, bundle = exported
    skewed = {
        "manifest": {**bundle["manifest"], "platforms": ["tpu"]},
        "blobs": bundle["blobs"],
    }
    ap = pipe.freeze()
    f0 = _counter("serve.artifact_fallbacks")
    assert ap.install_artifacts(skewed) == 0
    assert _counter("serve.artifact_fallbacks") == f0 + 1


def test_signature_drift_falls_back(exported):
    """Another pipeline's artifacts (different weights) must never be
    replayed — a silent stale-model serve is the one unacceptable
    failure mode."""
    _pipe, _frozen, bundle = exported
    other = _pipeline(seed=9).freeze()
    f0 = _counter("serve.artifact_fallbacks")
    assert other.install_artifacts(bundle) == 0
    assert _counter("serve.artifact_fallbacks") == f0 + 1


def test_corrupt_blob_tolerated_on_registry_load(registry):
    """A damaged blob drops only its bucket; the rest of the bundle
    still installs."""
    reg, version = registry
    adir = reg.artifacts_dir(version)
    victim = os.path.join(adir, f"b{BUCKETS[0]:05d}.hlo")
    with open(victim, "r+b") as f:
        f.seek(10)
        f.write(b"\xff" * 16)
    f0 = _counter("serve.artifact_fallbacks")
    loaded = reg.load_artifacts(version)
    assert _counter("serve.artifact_fallbacks") == f0 + 1
    assert loaded is not None
    assert f"b{BUCKETS[0]:05d}" not in loaded["blobs"]
    assert f"b{BUCKETS[1]:05d}" in loaded["blobs"]


def test_corrupt_manifest_drops_the_whole_tier(registry):
    reg, version = registry
    mpath = os.path.join(reg.artifacts_dir(version), "MANIFEST.json")
    with open(mpath, "r+b") as f:
        f.seek(2)
        f.write(b"\x00\x00")
    assert reg.load_artifacts(version) is None


def test_artifact_load_fault_site_degrades(registry):
    """An injected ``serve.artifact_load`` failure degrades the load to
    'no artifact tier' — it never raises out of the registry."""
    reg, version = registry
    with faults.inject("serve.artifact_load:raise"):
        assert reg.load_artifacts(version) is None
    assert reg.load_artifacts(version) is not None  # plan gone, tier back


def test_runtime_program_failure_falls_back_to_walk(exported):
    """A bucket program that fails at CALL time is dropped for good and
    the walk serves — one bad executable must not fail serving."""
    pipe, _frozen, bundle = exported
    ap = pipe.freeze()
    ap.install_artifacts(bundle)
    key = ((BUCKETS[0], DIM), "float32")
    assert key in ap._bucket_programs

    def boom(x):
        raise RuntimeError("poisoned program")

    ap._bucket_programs[key] = boom
    f0 = _counter("serve.artifact_fallbacks")
    x = np.random.default_rng(3).normal(size=(BUCKETS[0], DIM))
    out = np.asarray(ap(_ds(x.astype(np.float32))).array)
    assert out.shape == (BUCKETS[0], CLASSES)
    assert key not in ap._bucket_programs  # dropped, not retried per call
    assert _counter("serve.artifact_fallbacks") == f0 + 1


def test_stream_dataset_never_hits_bucket_programs(exported):
    """A StreamDataset must ride the walk untouched: the fast path
    keying on ``.array`` would materialize an out-of-core stream just
    to compute a dict key.  Programs are poisoned so a fast-path
    attempt is observable (drop + fallback counter)."""
    from keystone_tpu.workflow import StreamDataset

    pipe, _frozen, bundle = exported
    ap = pipe.freeze()
    ap.install_artifacts(bundle)
    n_installed = ap.installed_buckets()

    def boom(x):
        raise RuntimeError("bucket program ran on a stream")

    for k in list(ap._bucket_programs):
        ap._bucket_programs[k] = boom
    xs = np.random.default_rng(12).normal(size=(BUCKETS[0], DIM))
    xs = xs.astype(np.float32)

    def batches():
        yield xs

    f0 = _counter("serve.artifact_fallbacks")
    out = ap(StreamDataset(batches, n=BUCKETS[0]))
    vals = np.concatenate([np.asarray(b) for b in out.batches()])
    assert vals.shape == (BUCKETS[0], CLASSES)
    # the poisoned programs were never consulted: nothing dropped,
    # nothing counted
    assert _counter("serve.artifact_fallbacks") == f0
    assert ap.installed_buckets() == n_installed


def test_stable_repr_collapses_only_the_offending_element():
    """Two pipelines differing only in a scalar param NEXT TO an
    address-bearing object must hash differently — collapsing the whole
    container would alias them (the stale-artifact hazard)."""
    from keystone_tpu.utils.hashing import _stable_repr

    class Opaque:
        pass  # default repr carries a process-local address

    a = _stable_repr((0.5, Opaque()))
    b = _stable_repr((0.7, Opaque()))
    assert a != b
    assert "0x" not in a and "0x" not in b  # still process-stable


def test_degradable_pipeline_warms_the_walk_too(registry):
    """A degradation-declaring pipeline routes deadline-carrying
    flushes to the executor walk even with artifacts installed —
    prime() must warm BOTH tiers, so the first deadline-carrying
    request after a cold start/heal pays no in-band compile."""
    import jax

    from keystone_tpu.models.linear import LinearMapper as LM

    rng = np.random.default_rng(13)
    w = jnp.asarray(rng.normal(size=(DIM, CLASSES)).astype(np.float32))
    head = NormalizeRows()
    head.optional = True  # declares degradation -> _degradable applier
    pipe = (Pipeline.of(head) | LM(w)).fit()
    bundle = pipe.freeze().export_artifacts(
        example=_example(), buckets=BUCKETS
    )
    a0 = _prime_count("artifact")
    svc = serve(
        pipe,
        max_batch=BUCKETS[-1],
        buckets=BUCKETS,
        example=_example(),
        deadline_ms=30000.0,
        name="degr_art",
        supervise=False,
        devices=[jax.devices()[0]],
        artifacts=bundle,
    )
    try:
        assert _prime_count("artifact") == a0 + len(BUCKETS)
        x = rng.normal(size=(DIM,)).astype(np.float32)
        # deadline-carrying request -> walk path (degradable): must be
        # served from warm programs, well inside the budget
        y = np.asarray(svc.submit(x, deadline=30.0).result(timeout=30))
        assert np.all(np.isfinite(y))
    finally:
        svc.close()


def test_deadline_contract_survives_the_artifact_path(exported):
    """A deadline-carrying call on the bucket-program path keeps the
    walk's contract: a generous budget runs the program (bit-identical
    to the no-deadline call), an expired one raises the typed
    ``DeadlineExceeded`` — and the program is NOT dropped (a timeout is
    not a broken executable)."""
    from keystone_tpu.utils import guard

    pipe, _frozen, bundle = exported
    ap = pipe.freeze()
    ap.install_artifacts(bundle)
    key = ((BUCKETS[0], DIM), "float32")
    x = np.random.default_rng(5).normal(size=(BUCKETS[0], DIM))
    x = x.astype(np.float32)
    y_plain = np.asarray(ap(_ds(x)).array)
    y_budget = np.asarray(ap(_ds(x), deadline=30.0).array)
    assert y_plain.tobytes() == y_budget.tobytes()
    with pytest.raises(guard.DeadlineExceeded):
        ap(_ds(x), deadline=guard.Deadline.after(0.0))
    assert key in ap._bucket_programs  # kept: timeouts are not corruption


# ------------------------------------------------------------- serving


def test_serve_primes_from_artifacts_and_matches(registry):
    """A service built with the bundle primes every bucket from the
    artifact tier, and serves predictions bit-identical to a
    freshly-compiled service."""
    reg, version = registry
    fitted, v = reg.load()
    arts = reg.load_artifacts(v)
    a0 = _prime_count("artifact")
    h0 = _counter("serve.artifact_hits")
    svc = serve(
        fitted,
        max_batch=BUCKETS[-1],
        buckets=BUCKETS,
        example=_example(),
        name="art_serve",
        supervise=False,
        devices=_one_device(),
        artifacts=arts,
    )
    try:
        assert _prime_count("artifact") == a0 + len(BUCKETS)
        assert _counter("serve.artifact_hits") == h0 + len(BUCKETS)
        x = np.random.default_rng(4).normal(size=(DIM,)).astype(np.float32)
        y_art = np.asarray(svc.submit(x).result(timeout=30))
        st = svc.status()
        assert st["artifacts"]["configured"] is True
        assert st["artifacts"]["installed_buckets"] == len(BUCKETS)
        assert st["artifacts"]["prime_seconds"]["artifact"]["count"] >= len(
            BUCKETS
        )
    finally:
        svc.close()
    svc2 = serve(
        _pipeline(),
        max_batch=BUCKETS[-1],
        buckets=BUCKETS,
        example=_example(),
        name="cmp_serve",
        supervise=False,
        devices=_one_device(),
    )
    try:
        y_cmp = np.asarray(svc2.submit(x).result(timeout=30))
    finally:
        svc2.close()
    assert y_art.tobytes() == y_cmp.tobytes()


def test_swap_survives_damaged_artifacts(registry, tmp_path):
    """A hot-swap whose new version carries corrupt artifacts commits
    anyway (the staged generation compiles) — degraded, never failed.
    Also pins the staged-prime miss accounting: the service SERVES an
    artifact-bearing generation, but the staged generation got no
    bundle, so its primes must not count as artifact_misses (the
    pool's live-generation flag would mislabel them)."""
    reg, version = registry
    fitted, v = reg.load()
    svc = serve(
        fitted,
        max_batch=BUCKETS[-1],
        buckets=BUCKETS,
        example=_example(),
        name="swap_art",
        supervise=False,
        devices=_one_device(),
        artifacts=reg.load_artifacts(v),
    )
    try:
        new_pipe = _pipeline(seed=5)
        new_bundle = new_pipe.freeze().export_artifacts(
            example=_example(), buckets=BUCKETS
        )
        v2 = reg.publish(new_pipe, artifacts=new_bundle)
        adir = reg.artifacts_dir(v2)
        for name in os.listdir(adir):
            if name.endswith(".hlo"):
                with open(os.path.join(adir, name), "r+b") as f:
                    f.seek(5)
                    f.write(b"\xff" * 8)
        arts = reg.load_artifacts(v2)  # every blob skipped -> None
        assert arts is None
        m0 = _counter("serve.artifact_misses")
        info = svc.swap(fitted, version=v2, artifacts=arts)
        assert info["version"] == v2
        # bundle-less staged generation: no artifact_misses lies
        assert _counter("serve.artifact_misses") == m0
        x = np.random.default_rng(6).normal(size=(DIM,)).astype(np.float32)
        assert np.all(
            np.isfinite(np.asarray(svc.submit(x).result(timeout=30)))
        )
    finally:
        svc.close()


def test_watcher_swap_ships_artifacts(registry):
    """A watcher-driven rollout installs the new version's artifacts:
    the staged generation's prime rides the artifact tier."""
    reg, version = registry
    fitted, v = reg.load()
    svc = serve(
        fitted,
        max_batch=BUCKETS[-1],
        buckets=BUCKETS,
        example=_example(),
        name="watch_art",
        supervise=False,
        devices=_one_device(),
    )
    watcher = RegistryWatcher(svc, reg, poll_seconds=60.0)
    try:
        new_pipe = _pipeline(seed=7)
        bundle = new_pipe.freeze().export_artifacts(
            example=_example(), buckets=BUCKETS
        )
        v2 = reg.publish(new_pipe, artifacts=bundle)
        a0 = _prime_count("artifact")
        watcher._poll_once()
        assert svc.version == v2
        assert _prime_count("artifact") == a0 + len(BUCKETS)
    finally:
        svc.close()


def test_admin_swap_endpoint_ships_artifacts(registry):
    """POST /swap must load the target version's artifacts like the
    watcher does — an admin swap silently dropping the artifact tier
    would also cost every later supervisor heal (the bundle moves with
    the generation at commit)."""
    import urllib.request

    from keystone_tpu.serve import serve_http

    reg, version = registry
    fitted, v = reg.load()
    svc = serve(
        fitted,
        max_batch=BUCKETS[-1],
        buckets=BUCKETS,
        example=_example(),
        name="httpswap_art",
        supervise=False,
        devices=_one_device(),
    )
    try:
        new_pipe = _pipeline(seed=21)
        bundle = new_pipe.freeze().export_artifacts(
            example=_example(), buckets=BUCKETS
        )
        v2 = reg.publish(new_pipe, artifacts=bundle)
        a0 = _prime_count("artifact")
        with serve_http(svc, port=0, registry=reg) as front:
            req = urllib.request.Request(
                f"http://127.0.0.1:{front.port}/swap",
                data=json.dumps({"version": v2}).encode(),
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                info = json.loads(resp.read().decode())
        assert info["version"] == v2
        assert svc.version == v2
        # the staged generation primed from the new version's bundle
        assert _prime_count("artifact") == a0 + len(BUCKETS)
        assert svc._pool.has_artifacts
    finally:
        svc.close()


def test_supervisor_heal_consumes_artifacts(registry):
    """The heal path's compile-count pin: a replacement replica primes
    every bucket from the artifact tier — zero compile/cache-tier
    primes during recovery (compile time must not be recovery time)."""
    reg, version = registry
    fitted, v = reg.load()
    arts = reg.load_artifacts(v)
    svc = serve(
        fitted,
        max_batch=BUCKETS[-1],
        buckets=BUCKETS,
        example=_example(),
        name="heal_art",
        replicas=2,
        supervise=True,
        supervise_interval_s=0.05,
        artifacts=arts,
    )
    import time

    x = np.random.default_rng(8).normal(size=(DIM,)).astype(np.float32)
    try:
        for _ in range(3):
            svc.submit(x).result(timeout=30)
        a0 = _prime_count("artifact")
        c0 = _prime_count("compile")
        k0 = _prime_count("cache")
        with faults.inject("serve.worker:ctx.replica=0:raise:times=1"):
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    svc.submit(x).result(timeout=10)
                except Exception:
                    pass
                if svc.supervisor.restarts_total >= 1:
                    break
                time.sleep(0.01)
        assert svc.supervisor.restarts_total >= 1
        # the replacement primed from artifacts, and ONLY from artifacts
        assert _prime_count("artifact") == a0 + len(BUCKETS)
        assert _prime_count("compile") == c0
        assert _prime_count("cache") == k0
        assert np.all(
            np.isfinite(np.asarray(svc.submit(x).result(timeout=30)))
        )
    finally:
        svc.close()


# ----------------------------------------------------------- inert path


def test_no_artifacts_is_inert(exported):
    """Without a bundle the applier holds zero programs and the call
    path is the pre-artifact walk (one empty-dict check)."""
    pipe, _frozen, _bundle = exported
    ap = pipe.freeze()
    assert ap.installed_buckets() == 0
    x = np.ones((BUCKETS[0], DIM), np.float32)
    assert np.asarray(ap(_ds(x)).array).shape == (BUCKETS[0], CLASSES)
    assert ap.installed_buckets() == 0


def test_solver_hlo_identical_with_artifacts_installed(exported):
    """Exporting/installing artifacts must not perturb traced solver
    programs — the machinery lives entirely outside solver jit."""
    import jax

    from keystone_tpu.models.block_ls import _bcd_epoch_body

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 16, 8)), jnp.float32
    )
    y = jnp.ones((16, 2), jnp.float32)
    w = jnp.zeros((2, 8, 2), jnp.float32)
    p = jnp.zeros((16, 2), jnp.float32)

    def step(xb, yb, wb, pb):
        return _bcd_epoch_body(xb, yb, jnp.float32(16.0), 1e-3, (wb, pb))

    plain = jax.jit(step).lower(x, y, w, p).as_text()
    pipe, _frozen, bundle = exported
    ap = pipe.freeze()
    ap.install_artifacts(bundle)
    np.asarray(ap(np.ones((BUCKETS[0], DIM), np.float32)).array)
    after = jax.jit(step).lower(x, y, w, p).as_text()
    assert plain == after


def test_pickled_applier_drops_programs(exported):
    """Jitted bucket programs are process-local: a pickled applier
    round-trips WITHOUT them (and without error) — clones re-install
    from the bundle via the pool."""
    import pickle

    pipe, _frozen, bundle = exported
    ap = pipe.freeze()
    ap.install_artifacts(bundle)
    clone = pickle.loads(pickle.dumps(ap))
    assert clone.installed_buckets() == 0
    # and the clone can re-install (its fingerprint survives the trip)
    assert clone.install_artifacts(bundle) == len(BUCKETS)


# ------------------------------------------------------------------ CLI


def test_cli_export_writes_bundle_dir(tmp_path, exported):
    """``keystone export --model ... --out DIR`` writes a loadable
    manifest + checksummed blobs."""
    pipe, _frozen, _bundle = exported
    model = str(tmp_path / "model.pkl")
    pipe.save(model)
    out_dir = str(tmp_path / "bundle")
    from keystone_tpu import cli

    rc = cli.main(
        [
            "export",
            "--model",
            model,
            "--example-shape",
            str(DIM),
            "--buckets",
            ",".join(str(b) for b in BUCKETS),
            "--out",
            out_dir,
        ]
    )
    assert rc == 0
    man = json.loads(open(os.path.join(out_dir, "MANIFEST.json")).read())
    assert man["buckets"] == list(BUCKETS)
    for ent in man["entries"].values():
        blob = os.path.join(out_dir, ent["file"])
        assert os.path.exists(blob)
        assert os.path.exists(blob + ".b2")  # durable sidecar


def test_cli_export_publishes_registry_version(tmp_path, exported):
    pipe, _frozen, _bundle = exported
    model = str(tmp_path / "model.pkl")
    pipe.save(model)
    root = str(tmp_path / "reg")
    from keystone_tpu import cli

    rc = cli.main(
        [
            "export",
            "--model",
            model,
            "--model-dir",
            root,
            "--example-shape",
            str(DIM),
            "--buckets",
            ",".join(str(b) for b in BUCKETS),
        ]
    )
    assert rc == 0
    reg = ModelRegistry(root)
    fitted, version = reg.load()
    arts = reg.load_artifacts(version)
    assert arts is not None and len(arts["blobs"]) == len(BUCKETS)
    # the published pair actually serves from the artifact tier
    ap = fitted.freeze()
    assert ap.install_artifacts(arts) == len(BUCKETS)


# ------------------------------------------- pre-seeded compile cache tier
def test_export_captures_and_seeds_compile_cache(tmp_path, monkeypatch):
    """With a persistent compile cache active, export_artifacts ships
    the backend-compile cache entries alongside the bucket programs;
    seed_compile_cache installs them byte-identically on a fresh host's
    cache dir — the ladder's last cold rung."""
    import jax

    from keystone_tpu.utils.compile_cache import (
        enable_compilation_cache,
        seed_compile_cache,
    )

    cache_dir = str(tmp_path / "xla-cache")
    prev = jax.config.jax_compilation_cache_dir
    try:
        enable_compilation_cache(cache_dir)
        # a UNIQUE pipeline (fresh weights → fresh HLO): a program this
        # process already compiled hits jax's in-memory cache and never
        # touches the on-disk cache, so capture finds nothing to ship
        bundle = _pipeline(seed=41).freeze().export_artifacts(
            example=_example(), buckets=BUCKETS
        )
        cache_ents = {
            k: e
            for k, e in bundle["manifest"]["entries"].items()
            if e.get("kind") == "compile_cache"
        }
        assert cache_ents, "active cache during export must capture entries"
        for k, e in cache_ents.items():
            assert e["file"].startswith("cache")
            assert bundle["blobs"][k]
        shipped = {e["name"]: bundle["blobs"][k] for k, e in cache_ents.items()}

        # a "fresh host": empty cache dir — seeding installs the files
        fresh = str(tmp_path / "fresh-cache")
        jax.config.update("jax_compilation_cache_dir", fresh)
        os.makedirs(fresh, exist_ok=True)
        n = seed_compile_cache(bundle)
        assert n == len(cache_ents)
        for name, data in shipped.items():
            with open(os.path.join(fresh, name), "rb") as f:
                assert f.read() == data
        # idempotent: a second seed never clobbers (and writes nothing)
        assert seed_compile_cache(bundle) == 0
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_cache_entries_never_register_as_bucket_programs(tmp_path):
    """install_artifacts skips compile-cache entries: only row-keyed
    bucket programs register, and the bundle stays install-compatible
    with pre-seed readers (rows entries unchanged)."""
    import jax

    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    try:
        enable_compilation_cache(str(tmp_path / "xla-cache"))
        frozen = _pipeline().freeze()
        bundle = frozen.export_artifacts(example=_example(), buckets=BUCKETS)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    target = _pipeline().freeze()
    # identical pipeline params → identical signature; install succeeds
    n = target.install_artifacts(
        bundle, signature=bundle["manifest"]["signature"]
    )
    assert n == len(BUCKETS)
    assert target.installed_buckets() == len(BUCKETS)


def test_registry_roundtrips_cache_entries(tmp_path):
    """Cache entries ride the registry's durable artifact layout like
    any other blob (checksummed, corrupt-tolerant)."""
    import jax

    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    try:
        enable_compilation_cache(str(tmp_path / "xla-cache"))
        pipe = _pipeline(seed=42)  # unique HLO: see the capture test
        bundle = pipe.freeze().export_artifacts(
            example=_example(), buckets=BUCKETS
        )
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    n_cache = sum(
        1
        for e in bundle["manifest"]["entries"].values()
        if e.get("kind") == "compile_cache"
    )
    assert n_cache >= 1
    reg = ModelRegistry(str(tmp_path / "registry"))
    v = reg.publish(pipe, artifacts=bundle)
    loaded = reg.load_artifacts(v)
    got_cache = {
        k: e
        for k, e in loaded["manifest"]["entries"].items()
        if e.get("kind") == "compile_cache"
    }
    assert len(got_cache) == n_cache
    for k in got_cache:
        assert loaded["blobs"][k] == bundle["blobs"][k]


def test_export_without_cache_ships_no_cache_entries(monkeypatch):
    """No active persistent cache → the bundle simply has no cache
    rung (and nothing fails)."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        bundle = _pipeline().freeze().export_artifacts(
            example=_example(), buckets=BUCKETS
        )
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    assert not any(
        e.get("kind") == "compile_cache"
        for e in bundle["manifest"]["entries"].values()
    )
