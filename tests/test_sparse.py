"""Sparse-gradient text path (VERDICT r1 item 7).

The reference's LBFGS.scala § LeastSquaresSparseGradient computes
least-squares gradients from CSR without densifying n×d; the TPU
analogue is padded-COO gather/scatter (ops/sparse.py).  These tests pin:
solver parity with the dense solver on the same data, the huge-vocab
memory win, and the end-to-end Sparsify → SparseLBFGS → sparse-scoring
pipeline flow.
"""

import numpy as np

import jax.numpy as jnp

from keystone_tpu.workflow import Dataset, Pipeline


def _sparse_problem(rng, n, d, k, nnz):
    """Random sparse rows + targets from a sparse ground-truth model."""
    idx = np.stack([rng.choice(d, size=nnz, replace=False) for _ in range(n)])
    val = rng.normal(size=(n, nnz)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32) * 0.3
    dense = np.zeros((n, d), np.float32)
    for i in range(n):
        dense[i, idx[i]] = val[i]
    y = (dense @ w_true + 0.05 * rng.normal(size=(n, k))).astype(np.float32)
    return idx.astype(np.int32), val, dense, y


def test_padded_sparse_rows_roundtrip_and_matmul():
    from keystone_tpu.ops.sparse import PaddedSparseRows

    rng = np.random.default_rng(0)
    idx, val, dense, _ = _sparse_problem(rng, 32, 200, 3, 7)
    sp = PaddedSparseRows(idx, val, 200)
    np.testing.assert_allclose(sp.toarray(), dense, atol=1e-6)
    w = rng.normal(size=(200, 5)).astype(np.float32)
    got = np.asarray(sp.matmul(jnp.asarray(w)))[: sp.n]
    np.testing.assert_allclose(got, dense @ w, rtol=1e-4, atol=1e-4)


def test_sparse_lbfgs_matches_dense_lbfgs():
    """Same data, same loss: the sparse-gradient solver must land on the
    dense solver's optimum (overlapping vocab = every feature here)."""
    from keystone_tpu.models import DenseLBFGSwithL2, SparseLBFGSwithL2
    from keystone_tpu.ops.sparse import PaddedSparseRows

    rng = np.random.default_rng(1)
    idx, val, dense, y = _sparse_problem(rng, 256, 400, 4, 12)
    lam = 1e-2

    dense_model = DenseLBFGSwithL2(lam=lam, num_iterations=80).fit_arrays(dense, y)
    sp = PaddedSparseRows(idx, val, 400)
    sparse_model = SparseLBFGSwithL2(lam=lam, num_iterations=80).fit_sparse(
        sp, jnp.asarray(y)
    )
    wd = np.asarray(dense_model.weights)
    ws = np.asarray(sparse_model.weights)
    scale = np.abs(wd).max() + 1e-9
    assert np.abs(ws - wd).max() / scale < 2e-2, np.abs(ws - wd).max() / scale


def test_sparse_fit_at_huge_vocab_without_densifying():
    """d = 200k: the dense matrix would be ~400 MB; the padded-COO form
    is ~3 orders smaller and the fit still runs and predicts."""
    from keystone_tpu.models import SparseLBFGSwithL2
    from keystone_tpu.ops.sparse import PaddedSparseRows

    rng = np.random.default_rng(2)
    n, d, k, nnz = 512, 200_000, 4, 24
    idx = np.stack([rng.choice(d, size=nnz, replace=False) for _ in range(n)])
    val = np.abs(rng.normal(size=(n, nnz))).astype(np.float32)
    lab = rng.integers(0, k, size=n)
    # class-dependent signal: shift indices into a class-specific band
    idx = (idx // k) * k + lab[:, None]
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0

    sp = PaddedSparseRows(idx.astype(np.int32), val, d)
    dense_bytes = n * d * 4
    assert sp.nbytes * 100 < dense_bytes, (sp.nbytes, dense_bytes)

    model = SparseLBFGSwithL2(lam=1e-3, num_iterations=30).fit_sparse(
        sp, jnp.asarray(y)
    )
    w = np.asarray(model.weights)
    assert np.isfinite(w).all()
    pred = np.argmax(np.asarray(sp.matmul(model.weights)), axis=1)[:n]
    assert (pred == lab).mean() > 0.9


def test_node_choice_swaps_dense_solvers_to_sparse():
    """The optimizer's physical choice (NodeOptimizationRule analogue):
    on host CSR samples, exact LS and dense LBFGS route to the
    sparse-gradient solver; dense samples keep the original."""
    import scipy.sparse as sp

    from keystone_tpu.models import (
        DenseLBFGSwithL2,
        LinearMapEstimator,
        SparseLBFGSwithL2,
    )

    rows = [sp.csr_matrix(np.eye(1, 50, k=i, dtype=np.float32)) for i in range(4)]
    sparse_sample = Dataset(rows)
    dense_sample = Dataset(np.ones((4, 50), np.float32))

    chosen = LinearMapEstimator(lam=0.3).choose_physical(sparse_sample)
    assert isinstance(chosen, SparseLBFGSwithL2) and chosen.lam == 0.3
    assert LinearMapEstimator(lam=0.3).choose_physical(dense_sample).__class__ \
        is LinearMapEstimator

    d = DenseLBFGSwithL2(lam=0.1, fit_intercept=False)
    assert isinstance(d.choose_physical(sparse_sample), SparseLBFGSwithL2)
    assert d.choose_physical(dense_sample) is d
    # intercept-fitting dense LBFGS keeps the dense path (no centering sparse)
    di = DenseLBFGSwithL2(lam=0.1, fit_intercept=True)
    assert di.choose_physical(sparse_sample) is di
    # already-sparse stays put
    s = SparseLBFGSwithL2(lam=0.1)
    assert s.choose_physical(sparse_sample) is s


def test_linear_map_fit_dataset_routes_sparse_without_optimizer():
    """LinearMapEstimator.fit_dataset on a host CSR dataset must fit via
    the sparse solver even when no optimizer rule rewired it."""
    import scipy.sparse as sp

    from keystone_tpu.models import LinearMapEstimator

    rng = np.random.default_rng(5)
    n, d, k = 64, 80, 2
    dense = (rng.uniform(size=(n, d)) < 0.1) * rng.normal(size=(n, d))
    dense = dense.astype(np.float32)
    lab = (dense.sum(axis=1) > 0).astype(np.int32)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0
    rows = [sp.csr_matrix(dense[i : i + 1]) for i in range(n)]
    model = LinearMapEstimator(lam=1e-3).fit_dataset(Dataset(rows), Dataset(y))
    pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(dense))), axis=1)
    assert (pred == lab).mean() > 0.9


def test_common_sparse_features_sparse_output_pipeline():
    """CommonSparseFeatures(sparse_output=True) keeps CSR rows through
    the DAG; the default optimizer's node choice then fits the LS head
    with the sparse solver, end to end — a pipeline whose dense route
    would materialize n×d."""
    from keystone_tpu.models import LinearMapEstimator
    from keystone_tpu.ops import MaxClassifier
    from keystone_tpu.ops.nlp import CommonSparseFeatures

    rng = np.random.default_rng(4)
    vocab = [f"w{i}" for i in range(64)]
    n, k = 96, 3
    lab = rng.integers(0, k, size=n).astype(np.int32)
    docs = []
    for i in range(n):
        terms = {f"c{lab[i]}": 3.0}  # class-indicative token
        for w in rng.choice(vocab, size=5, replace=False):
            terms[w] = 1.0
        docs.append(terms)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0

    pipe = Pipeline.of(
        # identity host stage so the estimator sees the featurized docs
        CommonSparseFeatures(67, sparse_output=True)
        .fit_arrays(docs)
    ).and_then(
        LinearMapEstimator(lam=1e-3), Dataset(docs), Dataset(y)
    ).and_then(MaxClassifier())
    fitted = pipe.fit()
    pred = fitted(Dataset(docs)).get().numpy().ravel()[:n]
    assert (pred == lab).mean() > 0.95


def test_sparse_naive_bayes_matches_dense():
    """NB on CSR rows (scatter-add counts) must equal the dense fit, and
    its model must score sparse datasets."""
    import scipy.sparse as sp

    from keystone_tpu.models import NaiveBayesEstimator

    rng = np.random.default_rng(7)
    n, d, k = 128, 200, 4
    dense = (rng.uniform(size=(n, d)) < 0.1) * rng.integers(1, 5, size=(n, d))
    dense = dense.astype(np.float32)
    lab = rng.integers(0, k, size=n).astype(np.int32)
    rows = [sp.csr_matrix(dense[i : i + 1]) for i in range(n)]

    dm = NaiveBayesEstimator(k, lam=1.0).fit_arrays(dense, lab)
    sm = NaiveBayesEstimator(k, lam=1.0).fit_dataset(Dataset(rows), Dataset(lab))
    np.testing.assert_allclose(
        np.asarray(sm.log_cond), np.asarray(dm.log_cond), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(sm.log_prior), np.asarray(dm.log_prior), rtol=1e-6
    )
    scored = sm.apply_dataset(Dataset(rows)).numpy()
    want = np.asarray(dm.apply_batch(jnp.asarray(dense)))
    np.testing.assert_allclose(scored, want, rtol=1e-4, atol=1e-4)


def test_sparse_logreg_matches_dense_and_runs_amazon():
    """Sparse logistic regression (gather/scatter gradients) matches the
    dense fit on identical data, and the Amazon app runs end-to-end with
    CSR hashed features."""
    import scipy.sparse as sp

    from keystone_tpu.models import LogisticRegressionEstimator

    rng = np.random.default_rng(6)
    n, d, k = 256, 300, 3
    dense = ((rng.uniform(size=(n, d)) < 0.08) * rng.normal(size=(n, d))).astype(
        np.float32
    )
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    lab = np.argmax(dense @ w_true, axis=1).astype(np.int32)

    est = LogisticRegressionEstimator(k, lam=1e-3, num_iters=120)
    dm = est.fit_arrays(dense, lab)
    rows = [sp.csr_matrix(dense[i : i + 1]) for i in range(n)]
    sm = est.fit_dataset(Dataset(rows), Dataset(lab))
    wd, ws = np.asarray(dm.weights), np.asarray(sm.weights)
    scale = np.abs(wd).max() + 1e-9
    assert np.abs(ws - wd).max() / scale < 3e-2, np.abs(ws - wd).max() / scale

    # sparse scoring path through the model
    scored = sm.apply_dataset(Dataset(rows)).numpy()
    np.testing.assert_allclose(
        scored, dense @ ws, rtol=1e-4, atol=1e-4
    )

    from keystone_tpu.pipelines.amazon_reviews import AmazonReviewsPipeline, Config

    out = AmazonReviewsPipeline.run(Config(num_features=20000, synthetic_n=300))
    assert out["accuracy"] > 0.9, out


def test_sparsify_to_sparse_lbfgs_pipeline_and_scoring():
    """End-to-end DSL flow: dense rows → Sparsify (host CSR items) →
    SparseLBFGSwithL2 (sparse gradient fit) → sparse gather scoring →
    MaxClassifier, without densifying inside the solver."""
    from keystone_tpu.models import SparseLBFGSwithL2
    from keystone_tpu.ops import MaxClassifier, Sparsify

    rng = np.random.default_rng(3)
    n, d, k = 128, 300, 3
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    dense = (rng.uniform(size=(n, d)) < 0.05).astype(np.float32) * rng.normal(
        size=(n, d)
    ).astype(np.float32)
    lab = np.argmax(dense @ w_true, axis=1).astype(np.int32)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0

    pipe = Pipeline.of(Sparsify()).and_then(
        SparseLBFGSwithL2(lam=1e-4, num_iterations=60),
        Dataset(dense),
        Dataset(y),
    ).and_then(MaxClassifier())
    fitted = pipe.fit()
    pred = fitted(Dataset(dense)).get().numpy().ravel()[:n]
    assert (pred == lab).mean() > 0.95
