"""Sparse-gradient text path (VERDICT r1 item 7).

The reference's LBFGS.scala § LeastSquaresSparseGradient computes
least-squares gradients from CSR without densifying n×d; the TPU
analogue is padded-COO gather/scatter (ops/sparse.py).  These tests pin:
solver parity with the dense solver on the same data, the huge-vocab
memory win, and the end-to-end Sparsify → SparseLBFGS → sparse-scoring
pipeline flow.
"""

import numpy as np

import jax.numpy as jnp

from keystone_tpu.workflow import Dataset, Pipeline


def _sparse_problem(rng, n, d, k, nnz):
    """Random sparse rows + targets from a sparse ground-truth model."""
    idx = np.stack([rng.choice(d, size=nnz, replace=False) for _ in range(n)])
    val = rng.normal(size=(n, nnz)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32) * 0.3
    dense = np.zeros((n, d), np.float32)
    for i in range(n):
        dense[i, idx[i]] = val[i]
    y = (dense @ w_true + 0.05 * rng.normal(size=(n, k))).astype(np.float32)
    return idx.astype(np.int32), val, dense, y


def test_padded_sparse_rows_roundtrip_and_matmul():
    from keystone_tpu.ops.sparse import PaddedSparseRows

    rng = np.random.default_rng(0)
    idx, val, dense, _ = _sparse_problem(rng, 32, 200, 3, 7)
    sp = PaddedSparseRows(idx, val, 200)
    np.testing.assert_allclose(sp.toarray(), dense, atol=1e-6)
    w = rng.normal(size=(200, 5)).astype(np.float32)
    got = np.asarray(sp.matmul(jnp.asarray(w)))[: sp.n]
    np.testing.assert_allclose(got, dense @ w, rtol=1e-4, atol=1e-4)


def test_sparse_lbfgs_matches_dense_lbfgs():
    """Same data, same loss: the sparse-gradient solver must land on the
    dense solver's optimum (overlapping vocab = every feature here)."""
    from keystone_tpu.models import DenseLBFGSwithL2, SparseLBFGSwithL2
    from keystone_tpu.ops.sparse import PaddedSparseRows

    rng = np.random.default_rng(1)
    idx, val, dense, y = _sparse_problem(rng, 256, 400, 4, 12)
    lam = 1e-2

    dense_model = DenseLBFGSwithL2(lam=lam, num_iterations=80).fit_arrays(dense, y)
    sp = PaddedSparseRows(idx, val, 400)
    sparse_model = SparseLBFGSwithL2(lam=lam, num_iterations=80).fit_sparse(
        sp, jnp.asarray(y)
    )
    wd = np.asarray(dense_model.weights)
    ws = np.asarray(sparse_model.weights)
    scale = np.abs(wd).max() + 1e-9
    assert np.abs(ws - wd).max() / scale < 2e-2, np.abs(ws - wd).max() / scale


def test_sparse_fit_at_huge_vocab_without_densifying():
    """d = 200k: the dense matrix would be ~400 MB; the padded-COO form
    is ~3 orders smaller and the fit still runs and predicts."""
    from keystone_tpu.models import SparseLBFGSwithL2
    from keystone_tpu.ops.sparse import PaddedSparseRows

    rng = np.random.default_rng(2)
    n, d, k, nnz = 512, 200_000, 4, 24
    idx = np.stack([rng.choice(d, size=nnz, replace=False) for _ in range(n)])
    val = np.abs(rng.normal(size=(n, nnz))).astype(np.float32)
    lab = rng.integers(0, k, size=n)
    # class-dependent signal: shift indices into a class-specific band
    idx = (idx // k) * k + lab[:, None]
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0

    sp = PaddedSparseRows(idx.astype(np.int32), val, d)
    dense_bytes = n * d * 4
    assert sp.nbytes * 100 < dense_bytes, (sp.nbytes, dense_bytes)

    model = SparseLBFGSwithL2(lam=1e-3, num_iterations=30).fit_sparse(
        sp, jnp.asarray(y)
    )
    w = np.asarray(model.weights)
    assert np.isfinite(w).all()
    pred = np.argmax(np.asarray(sp.matmul(model.weights)), axis=1)[:n]
    assert (pred == lab).mean() > 0.9


def test_node_choice_swaps_dense_solvers_to_sparse():
    """The optimizer's physical choice (NodeOptimizationRule analogue):
    on host CSR samples, exact LS and dense LBFGS route to the
    sparse-gradient solver; dense samples keep the original."""
    import scipy.sparse as sp

    from keystone_tpu.models import (
        DenseLBFGSwithL2,
        LinearMapEstimator,
        SparseLBFGSwithL2,
    )

    rows = [sp.csr_matrix(np.eye(1, 50, k=i, dtype=np.float32)) for i in range(4)]
    sparse_sample = Dataset(rows)
    dense_sample = Dataset(np.ones((4, 50), np.float32))

    chosen = LinearMapEstimator(lam=0.3).choose_physical(sparse_sample)
    assert isinstance(chosen, SparseLBFGSwithL2) and chosen.lam == 0.3
    assert LinearMapEstimator(lam=0.3).choose_physical(dense_sample).__class__ \
        is LinearMapEstimator

    d = DenseLBFGSwithL2(lam=0.1, fit_intercept=False)
    assert isinstance(d.choose_physical(sparse_sample), SparseLBFGSwithL2)
    assert d.choose_physical(dense_sample) is d
    # intercept now survives the swap (constant-column intercept)
    di = DenseLBFGSwithL2(lam=0.1, fit_intercept=True)
    chosen_i = di.choose_physical(sparse_sample)
    assert isinstance(chosen_i, SparseLBFGSwithL2) and chosen_i.fit_intercept
    # already-sparse stays put
    s = SparseLBFGSwithL2(lam=0.1)
    assert s.choose_physical(sparse_sample) is s


def test_linear_map_fit_dataset_routes_sparse_without_optimizer():
    """LinearMapEstimator.fit_dataset on a host CSR dataset must fit via
    the sparse solver even when no optimizer rule rewired it."""
    import scipy.sparse as sp

    from keystone_tpu.models import LinearMapEstimator

    rng = np.random.default_rng(5)
    n, d, k = 64, 80, 2
    dense = (rng.uniform(size=(n, d)) < 0.1) * rng.normal(size=(n, d))
    dense = dense.astype(np.float32)
    lab = (dense.sum(axis=1) > 0).astype(np.int32)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0
    rows = [sp.csr_matrix(dense[i : i + 1]) for i in range(n)]
    model = LinearMapEstimator(lam=1e-3).fit_dataset(Dataset(rows), Dataset(y))
    pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(dense))), axis=1)
    assert (pred == lab).mean() > 0.9


def test_common_sparse_features_sparse_output_pipeline():
    """CommonSparseFeatures(sparse_output=True) keeps CSR rows through
    the DAG; the default optimizer's node choice then fits the LS head
    with the sparse solver, end to end — a pipeline whose dense route
    would materialize n×d."""
    from keystone_tpu.models import LinearMapEstimator
    from keystone_tpu.ops import MaxClassifier
    from keystone_tpu.ops.nlp import CommonSparseFeatures

    rng = np.random.default_rng(4)
    vocab = [f"w{i}" for i in range(64)]
    n, k = 96, 3
    lab = rng.integers(0, k, size=n).astype(np.int32)
    docs = []
    for i in range(n):
        terms = {f"c{lab[i]}": 3.0}  # class-indicative token
        for w in rng.choice(vocab, size=5, replace=False):
            terms[w] = 1.0
        docs.append(terms)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0

    pipe = Pipeline.of(
        # identity host stage so the estimator sees the featurized docs
        CommonSparseFeatures(67, sparse_output=True)
        .fit_arrays(docs)
    ).and_then(
        LinearMapEstimator(lam=1e-3), Dataset(docs), Dataset(y)
    ).and_then(MaxClassifier())
    fitted = pipe.fit()
    pred = fitted(Dataset(docs)).get().numpy().ravel()[:n]
    assert (pred == lab).mean() > 0.95


def test_sparse_naive_bayes_matches_dense():
    """NB on CSR rows (scatter-add counts) must equal the dense fit, and
    its model must score sparse datasets."""
    import scipy.sparse as sp

    from keystone_tpu.models import NaiveBayesEstimator

    rng = np.random.default_rng(7)
    n, d, k = 128, 200, 4
    dense = (rng.uniform(size=(n, d)) < 0.1) * rng.integers(1, 5, size=(n, d))
    dense = dense.astype(np.float32)
    lab = rng.integers(0, k, size=n).astype(np.int32)
    rows = [sp.csr_matrix(dense[i : i + 1]) for i in range(n)]

    dm = NaiveBayesEstimator(k, lam=1.0).fit_arrays(dense, lab)
    sm = NaiveBayesEstimator(k, lam=1.0).fit_dataset(Dataset(rows), Dataset(lab))
    np.testing.assert_allclose(
        np.asarray(sm.log_cond), np.asarray(dm.log_cond), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(sm.log_prior), np.asarray(dm.log_prior), rtol=1e-6
    )
    scored = sm.apply_dataset(Dataset(rows)).numpy()
    want = np.asarray(dm.apply_batch(jnp.asarray(dense)))
    np.testing.assert_allclose(scored, want, rtol=1e-4, atol=1e-4)


def test_sparse_logreg_matches_dense_and_runs_amazon():
    """Sparse logistic regression (gather/scatter gradients) matches the
    dense fit on identical data, and the Amazon app runs end-to-end with
    CSR hashed features."""
    import scipy.sparse as sp

    from keystone_tpu.models import LogisticRegressionEstimator

    rng = np.random.default_rng(6)
    n, d, k = 256, 300, 3
    dense = ((rng.uniform(size=(n, d)) < 0.08) * rng.normal(size=(n, d))).astype(
        np.float32
    )
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    lab = np.argmax(dense @ w_true, axis=1).astype(np.int32)

    est = LogisticRegressionEstimator(k, lam=1e-3, num_iters=120)
    dm = est.fit_arrays(dense, lab)
    rows = [sp.csr_matrix(dense[i : i + 1]) for i in range(n)]
    sm = est.fit_dataset(Dataset(rows), Dataset(lab))
    wd, ws = np.asarray(dm.weights), np.asarray(sm.weights)
    scale = np.abs(wd).max() + 1e-9
    assert np.abs(ws - wd).max() / scale < 3e-2, np.abs(ws - wd).max() / scale

    # sparse scoring path through the model
    scored = sm.apply_dataset(Dataset(rows)).numpy()
    np.testing.assert_allclose(
        scored, dense @ ws, rtol=1e-4, atol=1e-4
    )

    from keystone_tpu.pipelines.amazon_reviews import AmazonReviewsPipeline, Config

    out = AmazonReviewsPipeline.run(Config(num_features=20000, synthetic_n=300))
    assert out["accuracy"] > 0.9, out


def test_sparsify_to_sparse_lbfgs_pipeline_and_scoring():
    """End-to-end DSL flow: dense rows → Sparsify (host CSR items) →
    SparseLBFGSwithL2 (sparse gradient fit) → sparse gather scoring →
    MaxClassifier, without densifying inside the solver."""
    from keystone_tpu.models import SparseLBFGSwithL2
    from keystone_tpu.ops import MaxClassifier, Sparsify

    rng = np.random.default_rng(3)
    n, d, k = 128, 300, 3
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    dense = (rng.uniform(size=(n, d)) < 0.05).astype(np.float32) * rng.normal(
        size=(n, d)
    ).astype(np.float32)
    lab = np.argmax(dense @ w_true, axis=1).astype(np.int32)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0

    pipe = Pipeline.of(Sparsify()).and_then(
        SparseLBFGSwithL2(lam=1e-4, num_iterations=60),
        Dataset(dense),
        Dataset(y),
    ).and_then(MaxClassifier())
    fitted = pipe.fit()
    pred = fitted(Dataset(dense)).get().numpy().ravel()[:n]
    assert (pred == lab).mean() > 0.95


# ------------------------------------------------- bucketing + chunking


def _random_csr_rows(rng, n, d, nnz_per_row):
    import scipy.sparse as sp

    rows = []
    for i in range(n):
        nz = int(nnz_per_row[i])
        cols = rng.choice(d, size=max(nz, 1), replace=False)
        vals = rng.normal(size=max(nz, 1)).astype(np.float32)
        rows.append(
            sp.csr_matrix((vals, ([0] * len(cols), cols)), shape=(1, d))
        )
    return rows


def test_bucketed_kills_global_padding_cliff():
    """One dense row must NOT inflate every row's padding (VERDICT r2):
    bucketed memory stays near Σnnz while global padding blows up n×max."""
    from keystone_tpu.ops.sparse import BucketedSparseRows, PaddedSparseRows

    rng = np.random.default_rng(0)
    n, d = 256, 5000
    nnz = np.full(n, 8)
    nnz[0] = 4000  # the one dense-ish document
    rows = _random_csr_rows(rng, n, d, nnz)
    padded = PaddedSparseRows.from_scipy_rows(rows)
    bucketed = BucketedSparseRows.from_scipy_rows(rows)
    assert padded.nnz_max >= 4000
    # padded: every row pays 4000 entries; bucketed: ~8-entry buckets + one
    assert bucketed.nbytes < padded.nbytes / 20
    # and the math agrees with the dense product
    dense = np.concatenate([r.toarray() for r in rows]).astype(np.float32)
    w = rng.normal(size=(d, 3)).astype(np.float32)
    np.testing.assert_allclose(
        bucketed.matmul(w), dense @ w, atol=2e-3
    )


def test_bucketed_matmul_restores_row_order():
    from keystone_tpu.ops.sparse import BucketedSparseRows

    rng = np.random.default_rng(1)
    n, d = 40, 100
    nnz = rng.integers(1, 60, size=n)  # spans several pow2 buckets
    rows = _random_csr_rows(rng, n, d, nnz)
    sp_m = BucketedSparseRows.from_scipy_rows(rows)
    assert len(sp_m.buckets) > 1
    dense = np.concatenate([r.toarray() for r in rows]).astype(np.float32)
    w = rng.normal(size=(d, 4)).astype(np.float32)
    np.testing.assert_allclose(sp_m.matmul(w), dense @ w, atol=2e-3)


def test_bucketed_max_buckets_cap():
    from keystone_tpu.ops.sparse import BucketedSparseRows

    rng = np.random.default_rng(2)
    n, d = 128, 4096
    nnz = 2 ** rng.integers(0, 11, size=n)  # 11 natural pow2 caps
    rows = _random_csr_rows(rng, n, d, nnz)
    sp_m = BucketedSparseRows.from_scipy_rows(rows, max_buckets=4)
    assert len(sp_m.buckets) <= 4


def test_chunked_ops_match_unchunked(monkeypatch):
    """sparse_matmul / sparse_grad with a tiny chunk budget must agree
    with the single-shot path bit-for-bit-ish."""
    import keystone_tpu.ops.sparse as sparse_mod

    rng = np.random.default_rng(3)
    rows, nnz, d, k = 300, 13, 70, 5
    idx = rng.integers(0, d, size=(rows, nnz)).astype(np.int32)
    vals = rng.normal(size=(rows, nnz)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    r = rng.normal(size=(rows, k)).astype(np.float32)
    big_mm = np.asarray(sparse_mod.sparse_matmul(idx, vals, w))
    big_g = np.asarray(sparse_mod.sparse_grad(idx, vals, r, d))
    monkeypatch.setattr(sparse_mod, "_auto_chunk", lambda *a: 64)
    small_mm = np.asarray(sparse_mod.sparse_matmul(idx, vals, w))
    small_g = np.asarray(sparse_mod.sparse_grad(idx, vals, r, d))
    np.testing.assert_allclose(small_mm, big_mm, atol=1e-5)
    np.testing.assert_allclose(small_g, big_g, atol=1e-4)


def test_sparse_lbfgs_heavy_tailed_nnz_property():
    """Property test (VERDICT r2 item 4): a heavy-tailed nnz corpus fits
    through the bucketed path and matches the dense solver."""
    from keystone_tpu.models import DenseLBFGSwithL2, SparseLBFGSwithL2

    rng = np.random.default_rng(4)
    n, d, k = 192, 400, 3
    # log-normal-ish tail: most rows tiny, a few near-dense
    nnz = np.minimum((rng.pareto(1.0, size=n) * 5 + 1).astype(int), d - 1)
    rows = _random_csr_rows(rng, n, d, nnz)
    dense = np.concatenate([r.toarray() for r in rows]).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    lab = np.argmax(dense @ w_true, axis=1)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0

    sparse_model = SparseLBFGSwithL2(lam=1e-3, num_iterations=150).fit_dataset(
        Dataset(rows), Dataset(y)
    )
    dense_model = DenseLBFGSwithL2(
        lam=1e-3, num_iterations=150, fit_intercept=False
    ).fit_arrays(dense, y)
    # both near the shared optimum; heavy-tailed nnz makes the problem
    # ill-conditioned, so allow loose convergence slack
    np.testing.assert_allclose(
        np.asarray(sparse_model.weights),
        np.asarray(dense_model.weights),
        atol=1e-2,
    )


def test_sparse_lbfgs_intercept_matches_dense():
    """The constant-column intercept must reproduce the dense solver's
    centered intercept (same objective, different parameterization)."""
    from keystone_tpu.models import DenseLBFGSwithL2, SparseLBFGSwithL2

    rng = np.random.default_rng(5)
    n, d, k = 160, 90, 3
    dense = ((rng.uniform(size=(n, d)) < 0.2) * rng.normal(size=(n, d))).astype(
        np.float32
    )
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    shift = np.array([1.0, -2.0, 0.5], np.float32)
    scores = dense @ w_true + shift
    lab = np.argmax(scores, axis=1)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lab] = 1.0

    import scipy.sparse as sp_

    rows = [sp_.csr_matrix(dense[i : i + 1]) for i in range(n)]
    m_sp = SparseLBFGSwithL2(
        lam=1e-3, num_iterations=150, fit_intercept=True
    ).fit_dataset(Dataset(rows), Dataset(y))
    m_d = DenseLBFGSwithL2(
        lam=1e-3, num_iterations=150, fit_intercept=True
    ).fit_arrays(dense, y)
    assert m_sp.intercept is not None
    np.testing.assert_allclose(
        np.asarray(m_sp.weights), np.asarray(m_d.weights), atol=2e-2
    )
    np.testing.assert_allclose(
        np.asarray(m_sp.intercept), np.asarray(m_d.intercept), atol=2e-2
    )


# ------------------------------------- node-choice breadth (VERDICT r2 3)


def test_node_choice_local_vs_distributed_ls():
    """Size-based physical choice: a small full problem swaps the sharded
    normal-equations estimator for the local single-device solve; a large
    one keeps the distributed path."""
    from keystone_tpu.models import (
        LinearMapEstimator,
        LocalLeastSquaresEstimator,
    )

    rng = np.random.default_rng(0)
    small = Dataset(rng.normal(size=(64, 16)).astype(np.float32))
    est = LinearMapEstimator(lam=1e-2)
    chosen = est.choose_physical(small, full_n=64)
    assert isinstance(chosen, LocalLeastSquaresEstimator)
    assert chosen.lam == est.lam and chosen.fit_intercept == est.fit_intercept
    # big full_n (sample is still small) keeps the distributed solve
    assert est.choose_physical(small, full_n=1_000_000) is est
    # no size information -> no swap
    assert est.choose_physical(small) is est


def test_node_choice_fires_through_optimizer_pipeline(caplog):
    """Both r3 choices fire from SAMPLED stats inside the default
    optimizer: local-LS swap on a small pipeline, Convolver strategy
    pinned from the sampled image shape."""
    import logging

    import jax.numpy as jnp

    from keystone_tpu.models import LinearMapEstimator
    from keystone_tpu.ops import MaxClassifier
    from keystone_tpu.ops.images import Convolver, _pick_conv_strategy
    from keystone_tpu.workflow import transformer as transformer_fn

    rng = np.random.default_rng(1)
    n, hw, kf = 48, 16, 8
    imgs = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
    filters = rng.normal(size=(kf, 3, 3, 3)).astype(np.float32)
    lab = rng.integers(0, 3, size=n)
    y = -np.ones((n, 3), np.float32)
    y[np.arange(n), lab] = 1.0

    conv = Convolver(filters)  # strategy="auto"
    assert conv.strategy == "auto"
    pool = transformer_fn(lambda v: v.mean(axis=(1, 2)))
    pipe = (
        Pipeline.of(conv)
        .and_then(pool)
        .and_then(LinearMapEstimator(lam=1e-3), Dataset(imgs), Dataset(y))
        .and_then(MaxClassifier())
    )
    with caplog.at_level(logging.INFO, "keystone_tpu.workflow.optimizer"):
        fitted = pipe.fit()
    choices = [r.message for r in caplog.records if "node choice" in r.message]
    assert any("LocalLeastSquaresEstimator" in m for m in choices), choices
    assert any("Convolver" in m for m in choices), choices
    pred = fitted(Dataset(imgs)).get().numpy().ravel()[:n]
    assert np.isfinite(pred).all()
    # the pinning itself (auto -> measured concrete strategy):
    sample = Dataset(imgs)
    pinned = conv.choose_physical(sample)
    assert pinned is not conv
    assert pinned.strategy == _pick_conv_strategy(hw, hw, filters.shape, 1)
    assert pinned.strategy in ("direct", "im2col")
    # a pinned convolver does not re-pin
    assert pinned.choose_physical(sample) is pinned


def test_nb_and_logistic_bucketed_heavy_tailed_match_dense():
    """NB and logistic now route through the bucketed representation:
    a corpus with one near-dense document must fit cheaply and match
    the dense fits (counts and CE loss are row-permutation invariant)."""
    from keystone_tpu.models import LogisticRegressionEstimator, NaiveBayesEstimator

    rng = np.random.default_rng(11)
    n, d, k = 96, 500, 3
    nnz = np.full(n, 6)
    nnz[0] = 400  # the dense-ish document
    rows = _random_csr_rows(rng, n, d, nnz)
    # make values positive (NB counts)
    for r in rows:
        r.data = np.abs(r.data) + 0.5
    dense = np.concatenate([r.toarray() for r in rows]).astype(np.float32)
    lab = rng.integers(0, k, size=n).astype(np.int32)

    nb_sp = NaiveBayesEstimator(k, lam=1.0).fit_dataset(
        Dataset(rows), Dataset(lab)
    )
    nb_d = NaiveBayesEstimator(k, lam=1.0).fit_arrays(dense, lab)
    np.testing.assert_allclose(
        np.asarray(nb_sp.log_cond), np.asarray(nb_d.log_cond), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(nb_sp.log_prior), np.asarray(nb_d.log_prior), atol=1e-5
    )

    lr_sp = LogisticRegressionEstimator(k, lam=1e-2, num_iters=40).fit_dataset(
        Dataset(rows), Dataset(lab)
    )
    lr_d = LogisticRegressionEstimator(k, lam=1e-2, num_iters=40).fit_arrays(
        dense, lab
    )
    np.testing.assert_allclose(
        np.asarray(lr_sp.weights), np.asarray(lr_d.weights), atol=5e-3
    )


def test_bucketize_handles_padded_dataset_rows():
    """A host Dataset may carry padding rows beyond its true n; rows past
    n must be excluded from masks/labels, not crash or train (review
    finding: the old padded paths masked these, the bucketed path must
    too)."""
    import scipy.sparse as sp_

    from keystone_tpu.models import NaiveBayesEstimator
    from keystone_tpu.ops.sparse import BucketedSparseRows, bucketize_with_labels

    rng = np.random.default_rng(0)
    rows = _random_csr_rows(rng, 12, 30, np.full(12, 4))
    for r in rows:
        r.data = np.abs(r.data) + 0.5
    n_true = 9  # last 3 rows are Dataset padding
    lab = rng.integers(0, 3, size=n_true).astype(np.int32)

    sp_m = BucketedSparseRows.from_scipy_rows(rows)
    y = np.zeros((n_true, 3), np.float32)
    y[np.arange(n_true), lab] = 1.0
    bidx, bvals, by, n, d, brow_ok = bucketize_with_labels(sp_m, y, n=n_true)
    assert n == n_true
    assert sum(float(np.asarray(m).sum()) for m in brow_ok) == n_true

    # end to end: NB over the padded host Dataset matches the dense fit
    # restricted to the true rows
    ds = Dataset(rows)
    ds.n = n_true
    nb_sp = NaiveBayesEstimator(3, lam=1.0).fit_dataset(ds, Dataset(lab))
    dense = np.concatenate([r.toarray() for r in rows[:n_true]]).astype(np.float32)
    nb_d = NaiveBayesEstimator(3, lam=1.0).fit_arrays(dense, lab)
    np.testing.assert_allclose(
        np.asarray(nb_sp.log_cond), np.asarray(nb_d.log_cond), atol=1e-5
    )
