"""Auto out-of-core: no fit() may OOM the chip (VERDICT r4 item 2).

The profiled materialization pass holds the footprint estimate; fit()'s
pre-flight acts on it — auto-spilling large array sources to the
streaming path (features spill to the FeatureBlockStore) or, with
KEYSTONE_AUTO_SPILL=0, refusing cleanly with the predicted bytes.
Reference: workflow/AutoCacheRule.scala (memory-budget decisions belong
to the optimizer, not the user)."""

import numpy as np
import pytest

from keystone_tpu.loaders.imagenet import ImageNetLoader
from keystone_tpu.pipelines.imagenet_sift_lcs_fv import Config, ImageNetSiftLcsFV
from keystone_tpu.workflow.pipeline import PreflightOOMError


def _cfg():
    return Config(
        num_classes=4,
        synthetic_n=128,
        image_size=64,
        gmm_k=4,
        pca_dims=8,
        descriptor_samples_per_image=8,
        gmm_iters=2,
        num_epochs=1,
        solver_block_size=64,
    )


def _fit_predict(cfg, train, test_imgs):
    fitted = ImageNetSiftLcsFV.build(cfg, train.data, train.labels).fit()
    return fitted(test_imgs).get().numpy()


def test_auto_spill_completes_and_matches_in_memory(monkeypatch):
    cfg = _cfg()
    train = ImageNetLoader.synthetic(
        cfg.synthetic_n, cfg.num_classes, size=(64, 64), seed=1
    )
    test = ImageNetLoader.synthetic(16, cfg.num_classes, size=(64, 64), seed=2)
    want = _fit_predict(cfg, train, test.data)

    # shrink the HBM budget so the (1.6 MB) image source is over budget:
    # fit must COMPLETE via auto-spill, bit-matching the in-memory fit
    # (the stream path's parity is the e2e-tested --stream machinery)
    monkeypatch.setenv("KEYSTONE_HBM_BUDGET_BYTES", str(200_000))
    got = _fit_predict(cfg, train, test.data)
    np.testing.assert_array_equal(got, want)


def test_auto_spill_disabled_refuses_cleanly(monkeypatch):
    cfg = _cfg()
    train = ImageNetLoader.synthetic(
        cfg.synthetic_n, cfg.num_classes, size=(64, 64), seed=1
    )
    monkeypatch.setenv("KEYSTONE_HBM_BUDGET_BYTES", str(200_000))
    monkeypatch.setenv("KEYSTONE_AUTO_SPILL", "0")
    with pytest.raises(PreflightOOMError) as ei:
        ImageNetSiftLcsFV.build(cfg, train.data, train.labels).fit()
    msg = str(ei.value)
    assert "GB" in msg and "--stream" in msg  # predicted bytes + pointer
