"""Worker for the fault-injection test (test_faulttol.py).

Two Gloo-connected processes fit a BlockLeastSquares solver with
per-epoch checkpointing.  In "crash" mode, process 1 calls ``os._exit``
before launching its 4th epoch sweep — mid-fit, between collectives —
simulating a host failure.  In "resume" mode the workers relaunch with
the same checkpoint dir, must resume from the last completed epoch
(asserted: the checkpoint exists and its epoch > 0), finish the fit,
and print a digest of the final weights.  The parent test compares the
resumed digest against an uninterrupted control run's digest — recovery
must land on EXACTLY the same model.
"""

import hashlib
import os
import sys


def main() -> None:
    coordinator, num_procs, pid, mode, ckpt_dir = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],  # crash | resume | control
        sys.argv[5],
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from keystone_tpu.parallel import multihost, set_mesh

    multihost.initialize(
        coordinator_address=coordinator, num_processes=num_procs, process_id=pid
    )
    mesh = multihost.hybrid_mesh(model_parallelism=1)
    set_mesh(mesh)

    import numpy as np

    import keystone_tpu.models.block_ls as bls

    rng = np.random.default_rng(0)
    n, d, k = 256, 48, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.normal(size=(n, k))).astype(np.float32)

    sl = multihost.process_batch_slice(n)
    data = multihost.make_global_dataset(x[sl], global_n=n)
    labels = multihost.make_global_dataset(y[sl], global_n=n)

    crash_after = 3  # completed epoch sweeps before the injected death
    if mode == "crash" and pid == 1:
        orig = bls._bcd_epoch
        calls = {"n": 0}

        def crashing(*args):
            if calls["n"] >= crash_after:
                sys.stderr.write("FAULT: injected crash before epoch %d\n" % calls["n"])
                sys.stderr.flush()
                os._exit(42)
            calls["n"] += 1
            return orig(*args)

        bls._bcd_epoch = crashing

    ckpt_path = os.path.join(ckpt_dir, "bcd_epoch.npz")
    if mode == "resume":
        # recovery must actually RESUME: the crash run left epochs 0..2
        assert os.path.exists(ckpt_path), "no checkpoint survived the crash"
        with np.load(ckpt_path) as z:
            resumed_epoch = int(z["epoch"])
        assert resumed_epoch >= 1, resumed_epoch
        print(f"RESUMED_FROM {resumed_epoch}", flush=True)

    est = bls.BlockLeastSquaresEstimator(
        block_size=16, num_iter=6, lam=1e-3, fit_intercept=False
    )
    model = est.fit_checkpointed(data, labels, checkpoint_dir=ckpt_dir)

    w = np.asarray(model.flat_weights, np.float64)
    digest = hashlib.sha256(np.round(w, 4).tobytes()).hexdigest()[:16]
    err = np.abs(w[:d] - np.linalg.solve(
        x.astype(np.float64).T @ x + 1e-3 * n * np.eye(d),
        x.astype(np.float64).T @ y,
    )).max()
    print(f"FAULTTOL_OK pid={pid} mode={mode} digest={digest} err={err:.2e}", flush=True)


if __name__ == "__main__":
    main()
