"""Worker for the fault-injection test (test_faulttol.py).

Two Gloo-connected processes fit a BlockLeastSquares solver with
per-epoch checkpointing.  In "crash" mode, process 1 calls ``os._exit``
before launching its 4th epoch sweep — mid-fit, between collectives —
simulating a host failure.  In "resume" mode the workers relaunch with
the same checkpoint dir, must resume from the last completed epoch
(asserted: the checkpoint exists and its epoch > 0), finish the fit,
and print a digest of the final weights.  The parent test compares the
resumed digest against an uninterrupted control run's digest — recovery
must land on EXACTLY the same model.
"""

import hashlib
import os
import sys


def main() -> None:
    coordinator, num_procs, pid, mode, ckpt_dir = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],  # crash | resume | control
        sys.argv[5],
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from keystone_tpu.parallel import multihost, set_mesh

    multihost.initialize(
        coordinator_address=coordinator, num_processes=num_procs, process_id=pid
    )
    mesh = multihost.hybrid_mesh(model_parallelism=1)
    set_mesh(mesh)

    import numpy as np

    import keystone_tpu.models.block_ls as bls

    if mode.startswith("sparse-"):
        _sparse_lbfgs_leg(mode.split("-", 1)[1], ckpt_dir, pid)
        return

    rng = np.random.default_rng(0)
    n, d, k = 256, 48, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.normal(size=(n, k))).astype(np.float32)

    sl = multihost.process_batch_slice(n)
    data = multihost.make_global_dataset(x[sl], global_n=n)
    labels = multihost.make_global_dataset(y[sl], global_n=n)

    crash_after = 3  # completed epoch sweeps before the injected death
    if mode == "crash" and pid == 1:
        orig = bls._bcd_epoch
        calls = {"n": 0}

        def crashing(*args):
            if calls["n"] >= crash_after:
                sys.stderr.write("FAULT: injected crash before epoch %d\n" % calls["n"])
                sys.stderr.flush()
                os._exit(42)
            calls["n"] += 1
            return orig(*args)

        bls._bcd_epoch = crashing

    ckpt_path = os.path.join(ckpt_dir, "bcd_epoch.npz")
    if mode == "resume":
        # recovery must actually RESUME: the crash run left epochs 0..2
        assert os.path.exists(ckpt_path), "no checkpoint survived the crash"
        with np.load(ckpt_path) as z:
            resumed_epoch = int(z["epoch"])
        assert resumed_epoch >= 1, resumed_epoch
        print(f"RESUMED_FROM {resumed_epoch}", flush=True)

    est = bls.BlockLeastSquaresEstimator(
        block_size=16, num_iter=6, lam=1e-3, fit_intercept=False
    )
    model = est.fit_checkpointed(data, labels, checkpoint_dir=ckpt_dir)

    w = np.asarray(model.flat_weights, np.float64)
    digest = hashlib.sha256(np.round(w, 4).tobytes()).hexdigest()[:16]
    err = np.abs(w[:d] - np.linalg.solve(
        x.astype(np.float64).T @ x + 1e-3 * n * np.eye(d),
        x.astype(np.float64).T @ y,
    )).max()
    print(f"FAULTTOL_OK pid={pid} mode={mode} digest={digest} err={err:.2e}", flush=True)


def _sparse_lbfgs_leg(submode: str, ckpt_dir: str, pid: int) -> None:
    """Sparse L-BFGS mid-fit kill/resume at vocab scale (VERDICT r3
    weak-3: the L-BFGS family previously had NO mid-fit checkpoint —
    the reference's Amazon-scale text fits are hours of work).  Both
    Gloo processes fit the same bucketed 20k-vocab problem through
    SparseLBFGSwithL2.fit_checkpointed; in "crash" submode process 1
    dies after the first carry save, mid-chunk-loop, between
    collectives."""
    import hashlib

    import numpy as np
    import scipy.sparse as sparse

    import keystone_tpu.models.lbfgs as lb
    from keystone_tpu.workflow import Dataset

    rng = np.random.default_rng(0)
    n, d, k, nnz = 128, 20_000, 3, 8
    rows = []
    for _ in range(n):
        idx = rng.choice(d, size=nnz, replace=False)
        rows.append(
            sparse.csr_matrix(
                (rng.normal(size=nnz).astype(np.float32), (np.zeros(nnz), idx)),
                shape=(1, d),
            )
        )
    y = rng.normal(size=(n, k)).astype(np.float32)

    if submode == "crash" and pid == 1:
        orig = lb._lbfgs_checkpoint_callbacks

        def crashing_callbacks(*a, **kw):
            load_cb, save_cb = orig(*a, **kw)

            def save(it, carry):
                save_cb(it, carry)
                if it >= 4:
                    sys.stderr.write(
                        "FAULT: injected crash after carry save at it=%d\n" % it
                    )
                    sys.stderr.flush()
                    os._exit(42)

            return load_cb, save

        lb._lbfgs_checkpoint_callbacks = crashing_callbacks

    ckpt_path = os.path.join(ckpt_dir, "lbfgs_sparse.npz")
    if submode == "resume":
        assert os.path.exists(ckpt_path), "no L-BFGS carry survived the crash"
        with np.load(ckpt_path) as z:
            resumed_it = int(z["it"])
        assert resumed_it >= 4, resumed_it
        print(f"RESUMED_FROM {resumed_it}", flush=True)

    est = lb.SparseLBFGSwithL2(lam=1e-2, num_iterations=12, history=4)
    model = est.fit_checkpointed(
        Dataset(rows),
        Dataset(y, shard=False),
        checkpoint_dir=ckpt_dir,
        checkpoint_every=4,
    )
    w = np.asarray(model.weights, np.float64)
    digest = hashlib.sha256(np.round(w, 4).tobytes()).hexdigest()[:16]
    print(
        f"FAULTTOL_OK pid={pid} mode=sparse-{submode} digest={digest}",
        flush=True,
    )


if __name__ == "__main__":
    main()
