"""Process fleet (serve/wire.py, serve/worker.py, serve/procfleet.py):
the wire protocol's framing and slab discipline, and the promoted
worker-process replicas behind the PR-8 router — spawn/ready, remote
applies bit-identical to the threaded path, SIGKILL mid-flush healing
with zero lost futures, and live scale up/down.

Process-spawning tests share one module-scoped service (each spawn pays
a fresh interpreter + jax import); protocol tests are pure in-process.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

from keystone_tpu.serve import wire

pytestmark = pytest.mark.serve

DIM = 6


# ------------------------------------------------------------- framing
def test_frame_roundtrip():
    msg = {"op": "apply", "n": 3, "deadline_s": 0.25, "ref": {"slab": "x"}}
    assert wire.unpack_frame(wire.pack_frame(msg)) == msg


def test_frame_rejects_bad_magic_version_truncation():
    good = wire.pack_frame({"op": "ping"})
    with pytest.raises(wire.WireError):
        wire.unpack_frame(b"XXXX" + good[4:])
    with pytest.raises(wire.WireError):
        wire.unpack_frame(good[: len(wire.MAGIC)])  # truncated
    tampered = bytearray(good)
    tampered[len(wire.MAGIC)] = 99  # foreign protocol version
    with pytest.raises(wire.WireError):
        wire.unpack_frame(bytes(tampered))
    with pytest.raises(wire.WireError):
        wire.unpack_frame(wire.MAGIC + bytes([wire.VERSION]) + b"not json")
    with pytest.raises(wire.WireError):
        wire.pack_frame(["not", "a", "dict"])


def test_frame_rejects_unserializable_body():
    with pytest.raises(wire.WireError):
        wire.pack_frame({"arr": np.zeros(3)})  # arrays never ride frames


# ---------------------------------------------------------------- slabs
def test_slab_pool_reuses_across_buckets():
    pool = wire.SlabPool(prefix="t0")
    try:
        big = pool.acquire(1 << 20)  # 1 MiB class
        name = big.name
        pool.release(big)
        # a smaller payload REUSES the free larger slab instead of
        # creating a new one (slab classes mirror padding buckets)
        small = pool.acquire(1 << 12)
        assert small.name == name
        assert pool.stats()["created"] == 1
        assert pool.stats()["reused"] == 1
        pool.release(small)
    finally:
        pool.close()


def test_slab_pool_rejects_oversized_payload():
    pool = wire.SlabPool(prefix="t1", max_slab_bytes=1 << 16)
    try:
        with pytest.raises(wire.PayloadTooLarge):
            pool.acquire((1 << 16) + 1)
        # the refusal is a client-shaped ValueError: never bisected as
        # poison, never charged to infrastructure
        assert issubclass(wire.PayloadTooLarge, ValueError)
    finally:
        pool.close()


def test_write_array_attach_roundtrip():
    pool = wire.SlabPool(prefix="t2")
    attacher = wire.SlabAttacher()
    try:
        arr = np.arange(24, dtype=np.float32).reshape(4, 6) * 0.5
        slab, ref = wire.write_array(pool, arr)
        out = attacher.read(ref)
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == arr.dtype
        # the copy owns its memory: slab reuse cannot corrupt it
        slab2, ref2 = wire.write_array(pool, np.zeros_like(arr))
        np.testing.assert_array_equal(out, arr)
        pool.release(slab)
        pool.release(slab2)
    finally:
        attacher.close()
        pool.close()


def test_attacher_rejects_overclaiming_ref():
    pool = wire.SlabPool(prefix="t3")
    attacher = wire.SlabAttacher()
    try:
        slab, ref = wire.write_array(pool, np.zeros(8, np.float32))
        bad = dict(ref, nbytes=slab.capacity + 1, shape=[slab.capacity + 1])
        with pytest.raises(wire.WireError):
            attacher.view(bad)
    finally:
        attacher.close()
        pool.close()


# ----------------------------------------------------- process fleet e2e
def _pipeline(scale: float = 2.0):
    import jax.numpy as jnp

    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.ops.stats import NormalizeRows
    from keystone_tpu.workflow import Pipeline

    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * scale)
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


@pytest.fixture(scope="module")
def proc_service():
    """One workers=2 process fleet shared by the e2e tests (each spawn
    pays a fresh interpreter + jax import; healing respawns keep the
    fixture valid across tests)."""
    from keystone_tpu.serve import serve

    svc = serve(
        _pipeline(),
        workers=2,
        max_batch=8,
        max_wait_ms=2.0,
        queue_bound=512,
        example=np.zeros(DIM, np.float32),
        name="procfleet_t",
        supervise_interval_s=0.1,
        heartbeat_s=10.0,
        restart_limit=1000,
    )
    yield svc
    svc.close()


def _rows(k: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).normal(size=(k, DIM)).astype(np.float32)


def test_process_fleet_serves_and_matches_threaded(proc_service):
    """Predictions from the process fleet are BIT-identical to the
    threaded single-replica service over the same pipeline — the
    promotion is a transport change, never a numerics change."""
    from keystone_tpu.serve import serve

    x = _rows(12, seed=3)
    got = np.stack(
        [f.result(timeout=60) for f in [proc_service.submit(r) for r in x]]
    )
    ref_svc = serve(
        _pipeline(),
        max_batch=8,
        max_wait_ms=2.0,
        example=np.zeros(DIM, np.float32),
        name="procfleet_ref",
        supervise=False,
    )
    try:
        want = np.stack(
            [f.result(timeout=60) for f in [ref_svc.submit(r) for r in x]]
        )
    finally:
        ref_svc.close()
    assert got.tobytes() == want.tobytes()


def test_process_fleet_status_exposes_workers(proc_service):
    st = proc_service.status()
    assert st["backend"] == "process"
    assert st["workers"] == proc_service.replicas
    reps = st["replicas"]
    assert all(r["backend"] == "process" for r in reps)
    assert all(isinstance(r["pid"], int) for r in reps)
    alive = [r for r in reps if r["worker_alive"]]
    assert alive, "no live worker process in status"
    # the child-side heartbeat is beating
    ages = [
        r["worker_heartbeat_age_s"]
        for r in alive
        if r["worker_heartbeat_age_s"] is not None
    ]
    assert ages and min(ages) < 5.0


def test_worker_sigkill_mid_flight_loses_nothing(proc_service):
    """SIGKILL a live worker while requests are in flight: the claim
    machinery un-claims and requeues the killed worker's flush, the
    supervisor spawns a replacement, and EVERY submitted future
    resolves with a correct result — zero lost, zero hung."""
    from keystone_tpu.obs import metrics

    svc = proc_service
    restarts0 = metrics.REGISTRY.counter_total("serve.replica_restarts")
    x = _rows(200, seed=4)
    killed = []

    def killer():
        time.sleep(0.05)
        pids = [
            r["pid"] for r in svc.replica_statuses() if r.get("worker_alive")
        ]
        if pids:
            os.kill(pids[0], signal.SIGKILL)
            killed.append(pids[0])

    t = threading.Thread(target=killer)
    t.start()
    futs = []
    for i in range(x.shape[0]):
        try:
            futs.append(svc.submit(x[i]))
        except Exception:
            pass  # a fully-down instant refuses typed; acceptable
        time.sleep(0.001)
    t.join()
    done = 0
    for f in futs:
        r = f.result(timeout=120)  # TimeoutError here = a LOST future
        assert abs(float(np.linalg.norm(r)) - 2.0) < 1e-4
        done += 1
    assert killed, "the killer thread found no live worker to SIGKILL"
    assert done == len(futs)
    # wait out the heal so the fixture is whole for later tests
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if (
            metrics.REGISTRY.counter_total("serve.replica_restarts")
            > restarts0
            and sum(
                1
                for r in svc.replica_statuses()
                if r.get("worker_alive")
            )
            >= 2
        ):
            break
        time.sleep(0.1)
    assert (
        metrics.REGISTRY.counter_total("serve.replica_restarts") > restarts0
    ), "supervisor never restarted the killed worker"


def test_scale_up_and_down_live(proc_service):
    """scale_to grows the fleet (spawn → prime → admit) and shrinks it
    gracefully (drain → join) while traffic keeps completing."""
    svc = proc_service
    n0 = svc.replicas
    x = _rows(8, seed=5)
    svc.scale_to(n0 + 1)
    assert svc.replicas == n0 + 1
    outs = [
        f.result(timeout=60) for f in [svc.submit(r) for r in x]
    ]
    assert all(abs(float(np.linalg.norm(o)) - 2.0) < 1e-4 for o in outs)
    svc.scale_to(n0)
    assert svc.replicas == n0
    outs = [
        f.result(timeout=60) for f in [svc.submit(r) for r in x]
    ]
    assert all(abs(float(np.linalg.norm(o)) - 2.0) < 1e-4 for o in outs)


def test_multi_tenant_refuses_process_backend():
    from keystone_tpu.serve import serve_multi

    with pytest.raises(NotImplementedError):
        serve_multi({"a": _pipeline()}, workers=2)


def test_workers_and_replicas_are_exclusive():
    from keystone_tpu.serve import serve

    with pytest.raises(ValueError):
        serve(_pipeline(), workers=2, replicas=2)


# ------------------------------------------- fleet telemetry (ISSUE 18)


def test_apply_frame_trace_key_and_slab_ref_pins(monkeypatch):
    """Frame-level byte pins: without trace context the apply control
    frame has EXACTLY the pre-tracing keys (recorder-off wire is
    unchanged), and the slab-ref fast path still ships the CALLER's
    reference — telemetry added zero copies to zero-copy dispatch."""
    from keystone_tpu.serve import procfleet as pf

    h = object.__new__(pf.WorkerHandle)
    h.name = "pin"
    h._lock = threading.Lock()
    h._closed = False
    h._conn = object()
    h._pool = None
    h.telemetry = None
    sent = []
    monkeypatch.setattr(pf.wire, "send_frame", lambda conn, m: sent.append(m))
    monkeypatch.setattr(pf.wire, "recv_frame", lambda conn: {"op": "pong"})
    ref = {"slab": "s0", "count": 2}
    h.apply(None, 2, slab_ref=ref)
    assert set(sent[0]) == {"op", "n", "deadline_s", "ref"}
    assert sent[0]["ref"] is ref
    ctx = {"batch": "b1", "request_ids": ["r1"]}
    h.apply(None, 2, slab_ref=ref, trace=ctx)
    assert sent[1]["trace"] == ctx
    assert set(sent[1]) == {"op", "n", "deadline_s", "ref", "trace"}


def test_process_fleet_stitches_cross_process_trace(proc_service):
    """E2E acceptance: a traced request served by a spawned worker
    process shows the TRUE cross-process chain on /requestz — the
    stitched batch record names the worker and host and carries the
    worker-side apply span aligned to the router clock (non-negative,
    inside the exchange window)."""
    rid = "proc-trace-e2e"
    x = _rows(4, seed=21)
    futs = [proc_service.submit(x[0], request_id=rid)]
    futs += [proc_service.submit(r) for r in x[1:]]
    for f in futs:
        f.result(timeout=60)
    rec = proc_service.recorder
    assert rec is not None
    tr = rec.request(rid)
    assert tr is not None and tr["batch_records"]
    stitched = [b for b in tr["batch_records"] if b.get("worker")]
    assert stitched, f"unstitched batch records: {tr['batch_records']}"
    b = stitched[0]
    assert b.get("host")
    assert "wire" in b
    names = [s["name"] for s in b.get("worker_spans", [])]
    assert "worker.apply" in names
    for s in b["worker_spans"]:
        assert s["seconds"] >= 0.0 and s["t_off"] >= 0.0
    # the ops surface sees the fleet: /statusz fleet block + labeled
    # series in the router registry
    st = proc_service.status()
    assert st.get("fleet", {}).get("workers")
    from keystone_tpu.obs import metrics

    series = metrics.REGISTRY.histogram_series("serve.fleet.apply_seconds")
    assert series
    assert all(lb.get("worker") and lb.get("host") for lb, _ in series)
