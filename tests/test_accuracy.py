"""Accuracy tests that can FAIL (VERDICT r1 item 5).

Round-1's end-to-end tests asserted acc==1.0 on separable synthetic data,
which cannot catch subtle solver bugs (a wrong λ scaling or a dropped
class weight still hits 1.0).  This module adds:

  (a) a NON-separable problem with a computable Bayes rate — the fitted
      pipeline's accuracy must land in a band around the Bayes optimum
      (too low = broken solver, too high = leakage/bug in the harness);
  (b) cross-checks of the solvers/decompositions against
      scipy/scikit-learn closed-form results on fixed seeds, at
      tolerances tight enough that a λ-convention or class-weight
      formula change fails the test;
  (c) a real-format golden dataset: deterministic textured JPEGs packed
      into a real tar, decoded through ImageNetLoader, validated against
      an independent PIL decode, and fitted end to end.

The sklearn cross-checks pin the λ conventions documented in the model
docstrings: LinearMapEstimator solves (XᵀX + λnI)w = Xᵀy →
sklearn.Ridge(alpha=λ·n); LogisticRegressionEstimator minimizes
mean-CE + ½λ‖w‖² → sklearn C = 1/(λ·n).
"""

import io
import tarfile

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.workflow import Dataset, Pipeline


# ------------------------------------------------------------------ (a) Bayes


def test_linear_pipeline_hits_bayes_band():
    """Two overlapping Gaussians, ‖μ₁−μ₀‖ = 2, identity covariance: the
    Bayes rate is Φ(1) ≈ 0.841 and LDA (≈ ridge on ±1 targets) is Bayes
    optimal.  Held-out accuracy of the FULL PIPELINE (DSL fit → predict)
    must land in a band around the Bayes rate — a solver bug drops it
    below; train-set leakage or a harness bug pushes it above."""
    from scipy.stats import norm

    from keystone_tpu.models import LinearMapEstimator
    from keystone_tpu.ops import ClassLabelIndicators, LinearRectifier, MaxClassifier

    rng = np.random.default_rng(7)
    d, n_train, n_test = 8, 4096, 4096
    mu = np.zeros(d)
    mu[0] = 1.0  # means ±e0 → class-mean distance 2 → Bayes acc Φ(1)

    def draw(n):
        lab = rng.integers(0, 2, size=n)
        x = rng.normal(size=(n, d)) + (2 * lab[:, None] - 1) * mu[None, :]
        return x.astype(np.float32), lab.astype(np.int32)

    xtr, ytr = draw(n_train)
    xte, yte = draw(n_test)
    bayes = float(norm.cdf(1.0))

    labels_pm1 = ClassLabelIndicators(2)(Dataset(ytr))
    pipe = Pipeline.of(LinearRectifier(-1e9)).and_then(
        LinearMapEstimator(lam=1e-4), Dataset(xtr), labels_pm1
    ).and_then(MaxClassifier())
    fitted = pipe.fit()
    pred = fitted(Dataset(xte)).get().numpy()
    acc = float((pred[: yte.shape[0]].ravel() == yte).mean())
    assert bayes - 0.04 <= acc <= bayes + 0.04, (acc, bayes)


def _indicators(labels, k):
    y = -np.ones((labels.shape[0], k), np.float32)
    y[np.arange(labels.shape[0]), labels] = 1.0
    return y


def test_linear_estimator_hits_bayes_band():
    from scipy.stats import norm

    from keystone_tpu.models import LinearMapEstimator

    rng = np.random.default_rng(7)
    d, n_train, n_test = 8, 4096, 4096
    mu = np.zeros(d)
    mu[0] = 1.0

    def draw(n):
        lab = rng.integers(0, 2, size=n)
        x = rng.normal(size=(n, d)) + (2 * lab[:, None] - 1) * mu[None, :]
        return x.astype(np.float32), lab.astype(np.int32)

    xtr, ytr = draw(n_train)
    xte, yte = draw(n_test)
    bayes = float(norm.cdf(1.0))  # ≈ 0.8413

    model = LinearMapEstimator(lam=1e-4).fit_arrays(xtr, _indicators(ytr, 2))
    pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(xte))), axis=1)
    acc = float((pred == yte).mean())
    assert bayes - 0.04 <= acc <= bayes + 0.04, (acc, bayes)


def test_weighted_solver_rebalances_skewed_classes():
    """9:1 imbalanced overlapping classes: mixture_weight=1 (fully
    balanced) must lift minority-class recall well above the unweighted
    solver's.  Fails if class_weights stops weighting."""
    from keystone_tpu.models import (
        BlockLeastSquaresEstimator,
        BlockWeightedLeastSquaresEstimator,
    )

    rng = np.random.default_rng(3)
    d = 8
    n_maj, n_min = 3600, 400
    x = np.concatenate(
        [
            rng.normal(size=(n_maj, d)) - 0.75,
            rng.normal(size=(n_min, d)) + 0.75,
        ]
    ).astype(np.float32)
    lab = np.concatenate([np.zeros(n_maj, np.int32), np.ones(n_min, np.int32)])
    perm = rng.permutation(lab.shape[0])
    x, lab = x[perm], lab[perm]
    y = _indicators(lab, 2)

    xte = np.concatenate(
        [rng.normal(size=(1000, d)) - 0.75, rng.normal(size=(1000, d)) + 0.75]
    ).astype(np.float32)
    yte = np.concatenate([np.zeros(1000, np.int32), np.ones(1000, np.int32)])

    def minority_recall(model):
        pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(xte))), axis=1)
        return float((pred[yte == 1] == 1).mean())

    plain = BlockLeastSquaresEstimator(
        block_size=8, num_iter=4, lam=1e-3
    ).fit_arrays(x, y)
    balanced = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iter=4, lam=1e-3, mixture_weight=1.0
    ).fit_arrays(x, y)
    r_plain, r_bal = minority_recall(plain), minority_recall(balanced)
    assert r_bal > r_plain + 0.05, (r_plain, r_bal)


# --------------------------------------------------- (b) sklearn cross-checks


def test_ridge_lambda_convention_matches_sklearn():
    """LinearMapEstimator(λ) must equal sklearn Ridge(alpha=λ·n) exactly
    (same normal equations).  A changed λ scaling fails this at once."""
    from sklearn.linear_model import Ridge

    from keystone_tpu.models import LinearMapEstimator

    rng = np.random.default_rng(0)
    n, d, k = 512, 24, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    lam = 0.37

    model = LinearMapEstimator(lam=lam).fit_arrays(x, y)
    sk = Ridge(alpha=lam * n, fit_intercept=True).fit(x, y)
    np.testing.assert_allclose(
        np.asarray(model.weights), sk.coef_.T, rtol=2e-3, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(model.intercept), sk.intercept_, rtol=2e-3, atol=2e-4
    )


def test_weighted_ls_matches_f64_weighted_normal_equations():
    """BlockWeightedLeastSquares (converged BCD) must equal the direct
    f64 weighted ridge solve with the documented α formula.  Fails if
    the class-weight formula or its centering changes."""
    from keystone_tpu.models import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.models.block_weighted_ls import class_weights

    rng = np.random.default_rng(1)
    n, d, k = 600, 16, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    lab = rng.choice(k, size=n, p=[0.6, 0.3, 0.1])
    y = _indicators(lab, k)
    lam, mw = 1e-2, 0.5

    est = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iter=30, lam=lam, mixture_weight=mw
    )
    model = est.fit_arrays(x, y)

    # independent f64 reference with the documented formula
    alpha = np.asarray(class_weights(jnp.asarray(y), np.float32(n), mw), np.float64)
    xd, yd = x.astype(np.float64), y.astype(np.float64)
    xm = alpha @ xd / alpha.sum()
    ym = alpha @ yd / alpha.sum()
    xc, yc = xd - xm, yd - ym
    w_ref = np.linalg.solve(
        xc.T @ (alpha[:, None] * xc) + lam * n * np.eye(d),
        xc.T @ (alpha[:, None] * yc),
    )
    got = np.asarray(model.flat_weights)[:d]
    np.testing.assert_allclose(got, w_ref, rtol=5e-3, atol=5e-4)
    # and the intercept folds the weighted means: b = ym − xm·W
    np.testing.assert_allclose(
        np.asarray(model.apply_batch(jnp.asarray(xm[None].astype(np.float32))))[0],
        ym,
        atol=5e-3,
    )


def test_logreg_matches_sklearn():
    """mean-CE + ½λ‖w‖² ⇒ sklearn C = 1/(λ·n), fit_intercept=False."""
    from sklearn.linear_model import LogisticRegression

    from keystone_tpu.models import LogisticRegressionEstimator

    rng = np.random.default_rng(2)
    n, d, k = 800, 10, 3
    w_true = rng.normal(size=(d, k))
    x = rng.normal(size=(n, d)).astype(np.float32)
    lab = np.array([rng.choice(k, p=p) for p in
                    np.exp(x @ w_true) / np.exp(x @ w_true).sum(1, keepdims=True)],
                   np.int32)
    lam = 1e-2

    model = LogisticRegressionEstimator(k, lam=lam, num_iters=300).fit_arrays(x, lab)
    sk = LogisticRegression(
        C=1.0 / (lam * n), fit_intercept=False, tol=1e-8, max_iter=2000
    ).fit(x, lab)
    # softmax weights are identifiable up to a per-row constant shift;
    # compare after centering columns per feature
    got = np.asarray(model.weights)
    want = sk.coef_.T
    got = got - got.mean(axis=1, keepdims=True)
    want = want - want.mean(axis=1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-3)


def test_pca_matches_sklearn_subspace():
    from sklearn.decomposition import PCA as SKPCA

    from keystone_tpu.models import PCAEstimator

    rng = np.random.default_rng(4)
    n, d, q = 400, 20, 5
    x = (rng.normal(size=(n, q)) @ rng.normal(size=(q, d)) * 3.0
         + rng.normal(size=(n, d)) * 0.1).astype(np.float32)

    ours = PCAEstimator(q).fit_arrays(x)
    p_ours = np.asarray(ours.components)  # (d, q)
    p_sk = SKPCA(n_components=q).fit(x).components_.T  # (d, q)
    # subspaces equal ⇔ projection operators equal (basis sign/rotation-free)
    np.testing.assert_allclose(
        p_ours @ p_ours.T, p_sk @ p_sk.T, atol=1e-3
    )


def test_kmeans_matches_sklearn_centers():
    from sklearn.cluster import KMeans as SKKMeans

    from keystone_tpu.models import KMeansPlusPlusEstimator

    rng = np.random.default_rng(5)
    k, d = 4, 6
    centers = rng.normal(size=(k, d)) * 6.0
    x = np.concatenate(
        [c + rng.normal(size=(200, d)) * 0.3 for c in centers]
    ).astype(np.float32)

    ours = KMeansPlusPlusEstimator(k, max_iterations=20, seed=0).fit_arrays(x)
    sk = SKKMeans(n_clusters=k, n_init=10, random_state=0).fit(x)
    got = np.asarray(ours.centers)
    want = sk.cluster_centers_
    # match up to permutation: greedy nearest pairing must be tight
    dist = np.linalg.norm(got[:, None, :] - want[None, :, :], axis=-1)
    order = dist.argmin(axis=1)
    assert sorted(order.tolist()) == list(range(k)), "centers not a permutation"
    assert float(dist[np.arange(k), order].max()) < 0.15


def test_gmm_matches_sklearn_means_and_loglik():
    from sklearn.mixture import GaussianMixture

    from keystone_tpu.models import GaussianMixtureModelEstimator

    rng = np.random.default_rng(6)
    k, d = 3, 4
    centers = np.array([[-4.0] * d, [0.0] * d, [4.0] * d])
    x = np.concatenate(
        [c + rng.normal(size=(300, d)) * (0.5 + i * 0.25)
         for i, c in enumerate(centers)]
    ).astype(np.float32)

    ours = GaussianMixtureModelEstimator(k, max_iterations=60, seed=0).fit_arrays(x)
    sk = GaussianMixture(
        n_components=k, covariance_type="diag", n_init=5, random_state=0
    ).fit(x)
    got = np.asarray(ours.means)
    want = sk.means_
    dist = np.linalg.norm(got[:, None, :] - want[None, :, :], axis=-1)
    order = dist.argmin(axis=1)
    assert sorted(order.tolist()) == list(range(k))
    assert float(dist[np.arange(k), order].max()) < 0.25
    # average log-likelihood within 1% of sklearn's (f64 numpy, model params)
    from scipy.special import logsumexp

    w = np.asarray(ours.weights, np.float64)
    m = np.asarray(ours.means, np.float64)
    v = np.asarray(ours.variances, np.float64)
    xd = x.astype(np.float64)
    lg = (
        np.log(w)[None, :]
        - 0.5 * np.sum(np.log(2 * np.pi * v), axis=1)[None, :]
        - 0.5 * np.sum(
            (xd[:, None, :] - m[None, :, :]) ** 2 / v[None, :, :], axis=2
        )
    )
    ll_ours = float(np.mean(logsumexp(lg, axis=1)))
    ll_sk = float(sk.score(x))
    assert abs(ll_ours - ll_sk) < 0.01 * abs(ll_sk), (ll_ours, ll_sk)


# ------------------------------------------------ (c) real-format golden data


def _textured_jpeg(rng, kind: str, hw: int = 64) -> bytes:
    """Textured JPEG: a patchwork of oriented gratings whose orientation
    MIX depends on the class (kind 'h': mostly horizontal tiles, 'v':
    mostly vertical).  Fisher vectors discriminate via per-component
    descriptor OCCUPANCY, so the classes must differ in descriptor
    *distribution* — a single pure tone per image makes every descriptor
    identical and FV encodes only noise residuals (anticorrelated across
    a class, which defeats any classifier)."""
    from PIL import Image as PILImage

    tile = 16
    p_h = 0.92 if kind == "h" else 0.08
    img = np.zeros((hw, hw))
    y, x = np.mgrid[0:tile, 0:tile]
    grat_h = 127 + 90 * np.sin(y * 0.9 + 0.5)
    grat_v = 127 + 90 * np.sin(x * 0.9 + 0.5)
    for ty in range(0, hw, tile):
        for tx in range(0, hw, tile):
            img[ty:ty + tile, tx:tx + tile] = (
                grat_h if rng.uniform() < p_h else grat_v
            )
    img = (img + rng.normal(scale=5.0, size=(hw, hw))).clip(0, 255)
    arr = np.stack([img] * 3, axis=-1).astype(np.uint8)
    buf = io.BytesIO()
    PILImage.fromarray(arr).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def test_imagenet_golden_tar_pixels_and_fit(tmp_path):
    """Real tar of real JPEGs: (1) loader pixels must match an
    independent PIL decode; (2) the SIFT→PCA→FV→weighted-LS pipeline
    must separate the two texture classes on held-out images."""
    from PIL import Image as PILImage

    from keystone_tpu.loaders import ImageNetLoader
    from keystone_tpu.models import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.models.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.models.pca import PCAEstimator
    from keystone_tpu.ops import GrayScaler, NormalizeRows, SIFTExtractor, SignedHellingerMapper
    from keystone_tpu.ops.fisher import FisherVector

    rng = np.random.default_rng(0)
    per_class, hw = 10, 64
    blobs = {}
    for synset, kind in (("horiz", "h"), ("vert", "v")):
        with tarfile.open(tmp_path / f"{synset}.tar", "w") as tf:
            for i in range(per_class):
                blob = _textured_jpeg(rng, kind, hw)
                blobs[f"{synset}_{i}"] = blob
                info = tarfile.TarInfo(f"{synset}_{i}.JPEG")
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))

    ld = ImageNetLoader.load(str(tmp_path), size=(hw, hw))
    assert ld.data.n == 2 * per_class
    labels = np.asarray(ld.labels.numpy())
    assert (labels == 0).sum() == per_class and (labels == 1).sum() == per_class

    # (1) pixel parity with an independent PIL decode (identical codec
    # bytes, so tolerance only covers decoder rounding)
    imgs = np.asarray(ld.data.numpy())
    ref0 = np.asarray(
        PILImage.open(io.BytesIO(blobs["horiz_0"])).convert("RGB"), np.float32
    )
    scale = imgs.max()
    want = ref0 / (255.0 if scale <= 1.001 else 1.0)
    err = np.abs(imgs[0].astype(np.float32) - want).mean()
    assert err < 2.0 * (1.0 if scale > 1.001 else 1 / 255.0), err

    # (2) end-to-end fit on 8/class, eval on held-out 2/class
    x = imgs.astype(np.float32)
    if x.max() > 1.001:
        x = x / 255.0
    tr = np.concatenate([np.arange(0, 8), np.arange(per_class, per_class + 8)])
    te = np.array([8, 9, per_class + 8, per_class + 9])

    gray = GrayScaler()
    sift = SIFTExtractor(step=6, bin_sizes=(4,))
    g = gray.apply_batch(jnp.asarray(x))
    desc, mask = sift.apply_batch(g)
    flat = np.asarray(desc).reshape(-1, desc.shape[-1])
    mflat = np.asarray(mask).reshape(-1) > 0
    pca = PCAEstimator(16).fit_arrays(flat[mflat][:4000])
    d2, m2 = pca.apply_batch(desc, mask=mask)
    gmm = GaussianMixtureModelEstimator(8, max_iterations=30, seed=0).fit_arrays(
        np.asarray(d2).reshape(-1, 16)[np.asarray(m2).reshape(-1) > 0][:4000]
    )
    fv = FisherVector(gmm)
    feats = fv.apply_batch(d2, mask=m2)
    feats = NormalizeRows().apply_batch(SignedHellingerMapper().apply_batch(feats))
    feats = np.asarray(feats)

    model = BlockWeightedLeastSquaresEstimator(
        block_size=64, num_iter=3, lam=1e-2, mixture_weight=0.5
    ).fit_arrays(feats[tr], _indicators(labels[tr], 2))
    pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(feats[te]))), axis=1)
    assert (pred == labels[te]).mean() == 1.0, (pred, labels[te])
