"""Accuracy tests that can FAIL (VERDICT r1 item 5).

Round-1's end-to-end tests asserted acc==1.0 on separable synthetic data,
which cannot catch subtle solver bugs (a wrong λ scaling or a dropped
class weight still hits 1.0).  This module adds:

  (a) a NON-separable problem with a computable Bayes rate — the fitted
      pipeline's accuracy must land in a band around the Bayes optimum
      (too low = broken solver, too high = leakage/bug in the harness);
  (b) cross-checks of the solvers/decompositions against
      scipy/scikit-learn closed-form results on fixed seeds, at
      tolerances tight enough that a λ-convention or class-weight
      formula change fails the test;
  (c) a real-format golden dataset: deterministic textured JPEGs packed
      into a real tar, decoded through ImageNetLoader, validated against
      an independent PIL decode, and fitted end to end.

The sklearn cross-checks pin the λ conventions documented in the model
docstrings: LinearMapEstimator solves (XᵀX + λnI)w = Xᵀy →
sklearn.Ridge(alpha=λ·n); LogisticRegressionEstimator minimizes
mean-CE + ½λ‖w‖² → sklearn C = 1/(λ·n).
"""

import io
import tarfile

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.workflow import Dataset, Pipeline


# ------------------------------------------------------------------ (a) Bayes


def test_linear_pipeline_hits_bayes_band():
    """Two overlapping Gaussians, ‖μ₁−μ₀‖ = 2, identity covariance: the
    Bayes rate is Φ(1) ≈ 0.841 and LDA (≈ ridge on ±1 targets) is Bayes
    optimal.  Held-out accuracy of the FULL PIPELINE (DSL fit → predict)
    must land in a band around the Bayes rate — a solver bug drops it
    below; train-set leakage or a harness bug pushes it above."""
    from scipy.stats import norm

    from keystone_tpu.models import LinearMapEstimator
    from keystone_tpu.ops import ClassLabelIndicators, LinearRectifier, MaxClassifier

    rng = np.random.default_rng(7)
    d, n_train, n_test = 8, 4096, 4096
    mu = np.zeros(d)
    mu[0] = 1.0  # means ±e0 → class-mean distance 2 → Bayes acc Φ(1)

    def draw(n):
        lab = rng.integers(0, 2, size=n)
        x = rng.normal(size=(n, d)) + (2 * lab[:, None] - 1) * mu[None, :]
        return x.astype(np.float32), lab.astype(np.int32)

    xtr, ytr = draw(n_train)
    xte, yte = draw(n_test)
    bayes = float(norm.cdf(1.0))

    labels_pm1 = ClassLabelIndicators(2)(Dataset(ytr))
    pipe = Pipeline.of(LinearRectifier(-1e9)).and_then(
        LinearMapEstimator(lam=1e-4), Dataset(xtr), labels_pm1
    ).and_then(MaxClassifier())
    fitted = pipe.fit()
    pred = fitted(Dataset(xte)).get().numpy()
    acc = float((pred[: yte.shape[0]].ravel() == yte).mean())
    assert bayes - 0.04 <= acc <= bayes + 0.04, (acc, bayes)


def _indicators(labels, k):
    y = -np.ones((labels.shape[0], k), np.float32)
    y[np.arange(labels.shape[0]), labels] = 1.0
    return y


def test_linear_estimator_hits_bayes_band():
    from scipy.stats import norm

    from keystone_tpu.models import LinearMapEstimator

    rng = np.random.default_rng(7)
    d, n_train, n_test = 8, 4096, 4096
    mu = np.zeros(d)
    mu[0] = 1.0

    def draw(n):
        lab = rng.integers(0, 2, size=n)
        x = rng.normal(size=(n, d)) + (2 * lab[:, None] - 1) * mu[None, :]
        return x.astype(np.float32), lab.astype(np.int32)

    xtr, ytr = draw(n_train)
    xte, yte = draw(n_test)
    bayes = float(norm.cdf(1.0))  # ≈ 0.8413

    model = LinearMapEstimator(lam=1e-4).fit_arrays(xtr, _indicators(ytr, 2))
    pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(xte))), axis=1)
    acc = float((pred == yte).mean())
    assert bayes - 0.04 <= acc <= bayes + 0.04, (acc, bayes)


def test_weighted_solver_rebalances_skewed_classes():
    """9:1 imbalanced overlapping classes: mixture_weight=1 (fully
    balanced) must lift minority-class recall well above the unweighted
    solver's.  Fails if class_weights stops weighting."""
    from keystone_tpu.models import (
        BlockLeastSquaresEstimator,
        BlockWeightedLeastSquaresEstimator,
    )

    rng = np.random.default_rng(3)
    d = 8
    n_maj, n_min = 3600, 400
    x = np.concatenate(
        [
            rng.normal(size=(n_maj, d)) - 0.75,
            rng.normal(size=(n_min, d)) + 0.75,
        ]
    ).astype(np.float32)
    lab = np.concatenate([np.zeros(n_maj, np.int32), np.ones(n_min, np.int32)])
    perm = rng.permutation(lab.shape[0])
    x, lab = x[perm], lab[perm]
    y = _indicators(lab, 2)

    xte = np.concatenate(
        [rng.normal(size=(1000, d)) - 0.75, rng.normal(size=(1000, d)) + 0.75]
    ).astype(np.float32)
    yte = np.concatenate([np.zeros(1000, np.int32), np.ones(1000, np.int32)])

    def minority_recall(model):
        pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(xte))), axis=1)
        return float((pred[yte == 1] == 1).mean())

    plain = BlockLeastSquaresEstimator(
        block_size=8, num_iter=4, lam=1e-3
    ).fit_arrays(x, y)
    balanced = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iter=4, lam=1e-3, mixture_weight=1.0
    ).fit_arrays(x, y)
    r_plain, r_bal = minority_recall(plain), minority_recall(balanced)
    assert r_bal > r_plain + 0.05, (r_plain, r_bal)


# --------------------------------------------------- (b) sklearn cross-checks


def test_ridge_lambda_convention_matches_sklearn():
    """LinearMapEstimator(λ) must equal sklearn Ridge(alpha=λ·n) exactly
    (same normal equations).  A changed λ scaling fails this at once."""
    from sklearn.linear_model import Ridge

    from keystone_tpu.models import LinearMapEstimator

    rng = np.random.default_rng(0)
    n, d, k = 512, 24, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    lam = 0.37

    model = LinearMapEstimator(lam=lam).fit_arrays(x, y)
    sk = Ridge(alpha=lam * n, fit_intercept=True).fit(x, y)
    np.testing.assert_allclose(
        np.asarray(model.weights), sk.coef_.T, rtol=2e-3, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(model.intercept), sk.intercept_, rtol=2e-3, atol=2e-4
    )


def test_weighted_ls_matches_f64_weighted_normal_equations():
    """BlockWeightedLeastSquares (converged BCD) must equal the direct
    f64 weighted ridge solve with the documented α formula.  Fails if
    the class-weight formula or its centering changes."""
    from keystone_tpu.models import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.models.block_weighted_ls import class_weights

    rng = np.random.default_rng(1)
    n, d, k = 600, 16, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    lab = rng.choice(k, size=n, p=[0.6, 0.3, 0.1])
    y = _indicators(lab, k)
    lam, mw = 1e-2, 0.5

    est = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iter=30, lam=lam, mixture_weight=mw
    )
    model = est.fit_arrays(x, y)

    # independent f64 reference with the documented formula
    alpha = np.asarray(class_weights(jnp.asarray(y), np.float32(n), mw), np.float64)
    xd, yd = x.astype(np.float64), y.astype(np.float64)
    xm = alpha @ xd / alpha.sum()
    ym = alpha @ yd / alpha.sum()
    xc, yc = xd - xm, yd - ym
    w_ref = np.linalg.solve(
        xc.T @ (alpha[:, None] * xc) + lam * n * np.eye(d),
        xc.T @ (alpha[:, None] * yc),
    )
    got = np.asarray(model.flat_weights)[:d]
    np.testing.assert_allclose(got, w_ref, rtol=5e-3, atol=5e-4)
    # and the intercept folds the weighted means: b = ym − xm·W
    np.testing.assert_allclose(
        np.asarray(model.apply_batch(jnp.asarray(xm[None].astype(np.float32))))[0],
        ym,
        atol=5e-3,
    )


def test_logreg_matches_sklearn():
    """mean-CE + ½λ‖w‖² ⇒ sklearn C = 1/(λ·n), fit_intercept=False."""
    from sklearn.linear_model import LogisticRegression

    from keystone_tpu.models import LogisticRegressionEstimator

    rng = np.random.default_rng(2)
    n, d, k = 800, 10, 3
    w_true = rng.normal(size=(d, k))
    x = rng.normal(size=(n, d)).astype(np.float32)
    lab = np.array([rng.choice(k, p=p) for p in
                    np.exp(x @ w_true) / np.exp(x @ w_true).sum(1, keepdims=True)],
                   np.int32)
    lam = 1e-2

    model = LogisticRegressionEstimator(k, lam=lam, num_iters=300).fit_arrays(x, lab)
    sk = LogisticRegression(
        C=1.0 / (lam * n), fit_intercept=False, tol=1e-8, max_iter=2000
    ).fit(x, lab)
    # softmax weights are identifiable up to a per-row constant shift;
    # compare after centering columns per feature
    got = np.asarray(model.weights)
    want = sk.coef_.T
    got = got - got.mean(axis=1, keepdims=True)
    want = want - want.mean(axis=1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-3)


def test_pca_matches_sklearn_subspace():
    from sklearn.decomposition import PCA as SKPCA

    from keystone_tpu.models import PCAEstimator

    rng = np.random.default_rng(4)
    n, d, q = 400, 20, 5
    x = (rng.normal(size=(n, q)) @ rng.normal(size=(q, d)) * 3.0
         + rng.normal(size=(n, d)) * 0.1).astype(np.float32)

    ours = PCAEstimator(q).fit_arrays(x)
    p_ours = np.asarray(ours.components)  # (d, q)
    p_sk = SKPCA(n_components=q).fit(x).components_.T  # (d, q)
    # subspaces equal ⇔ projection operators equal (basis sign/rotation-free)
    np.testing.assert_allclose(
        p_ours @ p_ours.T, p_sk @ p_sk.T, atol=1e-3
    )


def test_kmeans_matches_sklearn_centers():
    from sklearn.cluster import KMeans as SKKMeans

    from keystone_tpu.models import KMeansPlusPlusEstimator

    rng = np.random.default_rng(5)
    k, d = 4, 6
    centers = rng.normal(size=(k, d)) * 6.0
    x = np.concatenate(
        [c + rng.normal(size=(200, d)) * 0.3 for c in centers]
    ).astype(np.float32)

    ours = KMeansPlusPlusEstimator(k, max_iterations=20, seed=0).fit_arrays(x)
    sk = SKKMeans(n_clusters=k, n_init=10, random_state=0).fit(x)
    got = np.asarray(ours.centers)
    want = sk.cluster_centers_
    # match up to permutation: greedy nearest pairing must be tight
    dist = np.linalg.norm(got[:, None, :] - want[None, :, :], axis=-1)
    order = dist.argmin(axis=1)
    assert sorted(order.tolist()) == list(range(k)), "centers not a permutation"
    assert float(dist[np.arange(k), order].max()) < 0.15


def test_gmm_matches_sklearn_means_and_loglik():
    from sklearn.mixture import GaussianMixture

    from keystone_tpu.models import GaussianMixtureModelEstimator

    rng = np.random.default_rng(6)
    k, d = 3, 4
    centers = np.array([[-4.0] * d, [0.0] * d, [4.0] * d])
    x = np.concatenate(
        [c + rng.normal(size=(300, d)) * (0.5 + i * 0.25)
         for i, c in enumerate(centers)]
    ).astype(np.float32)

    ours = GaussianMixtureModelEstimator(k, max_iterations=60, seed=0).fit_arrays(x)
    sk = GaussianMixture(
        n_components=k, covariance_type="diag", n_init=5, random_state=0
    ).fit(x)
    got = np.asarray(ours.means)
    want = sk.means_
    dist = np.linalg.norm(got[:, None, :] - want[None, :, :], axis=-1)
    order = dist.argmin(axis=1)
    assert sorted(order.tolist()) == list(range(k))
    assert float(dist[np.arange(k), order].max()) < 0.25
    # average log-likelihood within 1% of sklearn's (f64 numpy, model params)
    from scipy.special import logsumexp

    w = np.asarray(ours.weights, np.float64)
    m = np.asarray(ours.means, np.float64)
    v = np.asarray(ours.variances, np.float64)
    xd = x.astype(np.float64)
    lg = (
        np.log(w)[None, :]
        - 0.5 * np.sum(np.log(2 * np.pi * v), axis=1)[None, :]
        - 0.5 * np.sum(
            (xd[:, None, :] - m[None, :, :]) ** 2 / v[None, :, :], axis=2
        )
    )
    ll_ours = float(np.mean(logsumexp(lg, axis=1)))
    ll_sk = float(sk.score(x))
    assert abs(ll_ours - ll_sk) < 0.01 * abs(ll_sk), (ll_ours, ll_sk)


# ------------------------------------------------ (c) real-format golden data


def _textured_jpeg(rng, kind: str, hw: int = 64) -> bytes:
    """Textured JPEG: a patchwork of oriented gratings whose orientation
    MIX depends on the class (kind 'h': mostly horizontal tiles, 'v':
    mostly vertical).  Fisher vectors discriminate via per-component
    descriptor OCCUPANCY, so the classes must differ in descriptor
    *distribution* — a single pure tone per image makes every descriptor
    identical and FV encodes only noise residuals (anticorrelated across
    a class, which defeats any classifier)."""
    from PIL import Image as PILImage

    tile = 16
    p_h = 0.92 if kind == "h" else 0.08
    img = np.zeros((hw, hw))
    y, x = np.mgrid[0:tile, 0:tile]
    grat_h = 127 + 90 * np.sin(y * 0.9 + 0.5)
    grat_v = 127 + 90 * np.sin(x * 0.9 + 0.5)
    for ty in range(0, hw, tile):
        for tx in range(0, hw, tile):
            img[ty:ty + tile, tx:tx + tile] = (
                grat_h if rng.uniform() < p_h else grat_v
            )
    img = (img + rng.normal(scale=5.0, size=(hw, hw))).clip(0, 255)
    arr = np.stack([img] * 3, axis=-1).astype(np.uint8)
    buf = io.BytesIO()
    PILImage.fromarray(arr).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def test_imagenet_golden_tar_pixels_and_fit(tmp_path):
    """Real tar of real JPEGs: (1) loader pixels must match an
    independent PIL decode; (2) the SIFT→PCA→FV→weighted-LS pipeline
    must separate the two texture classes on held-out images."""
    from PIL import Image as PILImage

    from keystone_tpu.loaders import ImageNetLoader
    from keystone_tpu.models import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.models.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.models.pca import PCAEstimator
    from keystone_tpu.ops import GrayScaler, NormalizeRows, SIFTExtractor, SignedHellingerMapper
    from keystone_tpu.ops.fisher import FisherVector

    rng = np.random.default_rng(0)
    per_class, hw = 10, 64
    blobs = {}
    for synset, kind in (("horiz", "h"), ("vert", "v")):
        with tarfile.open(tmp_path / f"{synset}.tar", "w") as tf:
            for i in range(per_class):
                blob = _textured_jpeg(rng, kind, hw)
                blobs[f"{synset}_{i}"] = blob
                info = tarfile.TarInfo(f"{synset}_{i}.JPEG")
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))

    ld = ImageNetLoader.load(str(tmp_path), size=(hw, hw))
    assert ld.data.n == 2 * per_class
    labels = np.asarray(ld.labels.numpy())
    assert (labels == 0).sum() == per_class and (labels == 1).sum() == per_class

    # (1) pixel parity with an independent PIL decode (identical codec
    # bytes, so tolerance only covers decoder rounding)
    imgs = np.asarray(ld.data.numpy())
    ref0 = np.asarray(
        PILImage.open(io.BytesIO(blobs["horiz_0"])).convert("RGB"), np.float32
    )
    scale = imgs.max()
    want = ref0 / (255.0 if scale <= 1.001 else 1.0)
    err = np.abs(imgs[0].astype(np.float32) - want).mean()
    assert err < 2.0 * (1.0 if scale > 1.001 else 1 / 255.0), err

    # (2) end-to-end fit on 8/class, eval on held-out 2/class
    x = imgs.astype(np.float32)
    if x.max() > 1.001:
        x = x / 255.0
    tr = np.concatenate([np.arange(0, 8), np.arange(per_class, per_class + 8)])
    te = np.array([8, 9, per_class + 8, per_class + 9])

    gray = GrayScaler()
    sift = SIFTExtractor(step=6, bin_sizes=(4,))
    g = gray.apply_batch(jnp.asarray(x))
    desc, mask = sift.apply_batch(g)
    flat = np.asarray(desc).reshape(-1, desc.shape[-1])
    mflat = np.asarray(mask).reshape(-1) > 0
    pca = PCAEstimator(16).fit_arrays(flat[mflat][:4000])
    d2, m2 = pca.apply_batch(desc, mask=mask)
    gmm = GaussianMixtureModelEstimator(8, max_iterations=30, seed=0).fit_arrays(
        np.asarray(d2).reshape(-1, 16)[np.asarray(m2).reshape(-1) > 0][:4000]
    )
    fv = FisherVector(gmm)
    feats = fv.apply_batch(d2, mask=m2)
    feats = NormalizeRows().apply_batch(SignedHellingerMapper().apply_batch(feats))
    feats = np.asarray(feats)

    model = BlockWeightedLeastSquaresEstimator(
        block_size=64, num_iter=3, lam=1e-2, mixture_weight=0.5
    ).fit_arrays(feats[tr], _indicators(labels[tr], 2))
    pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(feats[te]))), axis=1)
    assert (pred == labels[te]).mean() == 1.0, (pred, labels[te])


# ---------------------------------------------------------------------------
# App-level accuracy bands (VERDICT r2 item 6): skewed non-separable
# synthetic through the APP entry points — sensitive enough that
# perturbing mixture_weight or λ in the app config fails the band.
# ---------------------------------------------------------------------------


def _skewed_gaussian_problem(tmp_path, K=6, D=40, n=6144):
    """Heavily skewed Gaussian prototypes with overlap; returns the
    on-disk paths the Timit app loads plus ORACLE metrics computed from
    the true generative model (nearest-prototype rules)."""
    priors = np.array([0.80] + [0.04] * (K - 1))
    protos = np.zeros((K, D), np.float32)
    for c in range(K):
        protos[c, c] = 1.5
    sigma = 1.0

    def draw(n_, seed):
        r = np.random.default_rng(seed)
        lab = r.choice(K, size=n_, p=priors)
        x = protos[lab] + sigma * r.normal(size=(n_, D)).astype(np.float32)
        return x.astype(np.float32), lab.astype(np.int64)

    xtr, ytr = draw(n, 1)
    xte, yte = draw(n, 2)
    paths = {}
    for name, arr in [
        ("ftr", xtr), ("ltr", ytr), ("fte", xte), ("lte", yte)
    ]:
        p = str(tmp_path / f"{name}.npy")
        np.save(p, arr)
        paths[name] = p

    def macro_f1(pred, y):
        f1 = []
        for c in range(K):
            tp = ((pred == c) & (y == c)).sum()
            fp = ((pred == c) & (y != c)).sum()
            fn = ((pred != c) & (y == c)).sum()
            p_ = tp / max(tp + fp, 1)
            r_ = tp / max(tp + fn, 1)
            f1.append(2 * p_ * r_ / max(p_ + r_, 1e-9))
        return float(np.mean(f1))

    d2 = ((xte[:, None, :] - protos[None]) ** 2).sum(-1)
    balanced = np.argmin(d2, axis=1)  # the balanced-cost Bayes rule
    oracle = {
        "balanced_macro_f1": macro_f1(balanced, yte),
        "balanced_acc": float((balanced == yte).mean()),
    }
    return paths, oracle, K


def _timit_cfg(paths, K, **kw):
    from keystone_tpu.pipelines.timit import Config

    base = dict(
        features_path=paths["ftr"],
        labels_path=paths["ltr"],
        test_features_path=paths["fte"],
        test_labels_path=paths["lte"],
        num_cosine_features=512,
        cosine_block_size=256,
        num_classes=K,
        num_epochs=3,
        lam=1e-3,
        mixture_weight=0.9,
    )
    base.update(kw)
    return Config(**base)


def test_timit_app_macro_band_and_config_sensitivity(tmp_path):
    """TimitPipeline through run(): with a high mixture_weight the
    macro-F1 must land in a band around the BALANCED Bayes oracle
    (calibrated: app 0.428 vs oracle 0.433 on this problem) — and the
    band must catch config wiring bugs: mixture_weight dropped to 0
    lands ≈0.30, λ=10 lands ≈0.15, both far outside."""
    from keystone_tpu.pipelines.timit import TimitPipeline

    paths, oracle, K = _skewed_gaussian_problem(tmp_path)
    lo = oracle["balanced_macro_f1"] - 0.06
    hi = oracle["balanced_macro_f1"] + 0.04

    out = TimitPipeline.run(_timit_cfg(paths, K))
    assert lo <= out["macro_f1"] <= hi, (out["macro_f1"], lo, hi)
    # accuracy sanity: between the balanced rule's and the skew ceiling
    assert oracle["balanced_acc"] - 0.05 <= out["accuracy"] <= 0.90

    # the band is SENSITIVE: each perturbed config falls out of band
    broken_mw = TimitPipeline.run(_timit_cfg(paths, K, mixture_weight=0.0))
    assert broken_mw["macro_f1"] < lo, broken_mw["macro_f1"]
    broken_lam = TimitPipeline.run(_timit_cfg(paths, K, lam=10.0))
    assert broken_lam["macro_f1"] < lo, broken_lam["macro_f1"]


def _write_newsgroups_fixture(root, num_classes=3, docs_per_class=120, seed=0):
    """Directory-tree fixture with OVERLAPPING topic vocabularies: each
    doc draws 70% of its topic terms from its own class and 30% from the
    others, plus shared filler — non-separable on purpose."""
    import os

    rng = np.random.default_rng(seed)
    shared = [f"word{i}" for i in range(60)]
    topics = [[f"topic{c}term{i}" for i in range(25)] for c in range(num_classes)]
    for c in range(num_classes):
        gdir = os.path.join(root, f"group{c}")
        os.makedirs(gdir, exist_ok=True)
        for j in range(docs_per_class):
            words = []
            for _ in range(int(rng.integers(12, 28))):
                if rng.random() < 0.7:
                    words.append(str(rng.choice(topics[c])))
                else:
                    other = int(rng.choice([o for o in range(num_classes) if o != c]))
                    words.append(str(rng.choice(topics[other])))
            words += [str(w) for w in rng.choice(shared, size=int(rng.integers(10, 25)))]
            rng.shuffle(words)
            with open(os.path.join(gdir, f"doc{j:04d}.txt"), "w") as f:
                f.write(" ".join(words))
    return root


def test_newsgroups_app_sparse_route_matches_sklearn(tmp_path):
    """NewsgroupsPipeline (ls head, real CSR route: num_features ≥ 16384
    engages sparse_output + the sparse-gradient solver) must match
    sklearn Ridge solving the IDENTICAL objective on the IDENTICAL
    features — same featurizer chain, same λ convention (alpha = λ·n),
    no intercept — within solver-convergence slack."""
    import scipy.sparse as sp_
    from sklearn.linear_model import Ridge

    from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
    from keystone_tpu.ops.nlp import (
        CommonSparseFeatures,
        LowerCase,
        NGramsFeaturizer,
        TermFrequency,
        Tokenizer,
        Trimmer,
        log_tf,
    )
    from keystone_tpu.pipelines.newsgroups import Config, NewsgroupsPipeline

    root = _write_newsgroups_fixture(str(tmp_path / "ng"))
    lam = 1e-2
    out = NewsgroupsPipeline.run(
        Config(data_path=root, head="ls", ls_lam=lam, num_features=16384)
    )
    acc_app = out["accuracy"]

    # identical features, outside the app: same loader, same split,
    # same chain, same vocab-fit-on-train
    data = NewsgroupsDataLoader.load(root)
    train, test = data.split(0.8, seed=0)

    def featurize_docs(docs, csf_model):
        rows = []
        for doc in docs:
            d = doc
            for t in (Trimmer(), LowerCase(), Tokenizer(),
                      NGramsFeaturizer((1, 2)), TermFrequency(log_tf)):
                d = t.apply_one(d)
            rows.append(csf_model.apply_one(d))
        return sp_.vstack(rows).tocsr()

    term_dicts = []
    for doc in train.data.items:
        d = doc
        for t in (Trimmer(), LowerCase(), Tokenizer(),
                  NGramsFeaturizer((1, 2)), TermFrequency(log_tf)):
            d = t.apply_one(d)
        term_dicts.append(d)
    csf = CommonSparseFeatures(16384, sparse_output=True).fit_arrays(term_dicts)
    xtr = featurize_docs(train.data.items, csf)
    xte = featurize_docs(test.data.items, csf)
    ytr = train.labels.numpy()
    yte = test.labels.numpy()
    k = int(ytr.max()) + 1
    y_pm1 = -np.ones((len(ytr), k), np.float32)
    y_pm1[np.arange(len(ytr)), ytr] = 1.0
    # our objective 1/(2n)‖XW−Y‖² + λ/2‖W‖² == sklearn Ridge with
    # alpha = λ·n (and no intercept, like the sparse route)
    skl = Ridge(alpha=lam * xtr.shape[0], fit_intercept=False)
    skl.fit(xtr, y_pm1)
    acc_skl = float((np.argmax(xte @ skl.coef_.T, axis=1) == yte).mean())

    assert abs(acc_app - acc_skl) <= 0.03, (acc_app, acc_skl)
    # non-separable fixture: neither should be perfect, both well above chance
    assert 0.5 < acc_skl < 0.999, acc_skl


def _write_voc_fixture(root, n=60, size=(48, 48), seed=0, noise=0.15):
    """VOC-format disk fixture (JPEGs + XML): per-class oriented-grating
    blobs with ``noise`` label dropout — mAP has an IRREDUCIBLE ceiling
    (~0.89 measured: perfect presence knowledge vs the noisy labels)."""
    import os

    from PIL import Image as PILImage

    from keystone_tpu.loaders.voc import NUM_CLASSES, VOC_CLASSES

    rng = np.random.default_rng(seed)
    img_dir, ann_dir = os.path.join(root, "img"), os.path.join(root, "ann")
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(ann_dir, exist_ok=True)
    h, w = size
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    angles = [0.0, np.pi / 3, 2 * np.pi / 3]
    true = np.zeros((n, NUM_CLASSES), np.float32)
    noisy = np.zeros((n, NUM_CLASSES), np.float32)
    for i in range(n):
        present = rng.random(3) < 0.45
        if not present.any():
            present[rng.integers(0, 3)] = True
        img = np.full((h, w, 3), 110.0) + rng.normal(0, 6, (h, w, 3))
        for c in np.nonzero(present)[0]:
            x0 = rng.integers(0, w // 2)
            y0 = rng.integers(0, h // 2)
            a = angles[c]
            grat = 110 + 90 * np.sin(
                0.9 * (np.cos(a) * xx + np.sin(a) * yy)
                + rng.uniform(0, 2 * np.pi)
            )
            img[y0 : y0 + h // 2, x0 : x0 + w // 2] = grat[
                y0 : y0 + h // 2, x0 : x0 + w // 2, None
            ]
            true[i, c] = 1.0
            if rng.random() > noise:
                noisy[i, c] = 1.0
        if not noisy[i].any():
            noisy[i, int(np.nonzero(present)[0][0])] = 1.0
        pil = PILImage.fromarray(np.clip(img, 0, 255).astype(np.uint8))
        pil.save(os.path.join(img_dir, f"im{i:04d}.jpg"), quality=95)
        objs = "".join(
            f"<object><name>{VOC_CLASSES[c]}</name></object>"
            for c in np.nonzero(noisy[i])[0]
        )
        with open(os.path.join(ann_dir, f"im{i:04d}.xml"), "w") as f:
            f.write(f"<annotation>{objs}</annotation>")
    return img_dir, ann_dir


def test_voc_app_map_band_on_noisy_fixture(tmp_path):
    """VOCSIFTFisher through run() on a NON-separable disk fixture: the
    label-dropout noise caps mAP at ~0.89 (measured ceiling: perfect
    presence knowledge scored against the noisy labels), so a band
    [0.80, 0.93] catches both broken featurization/solver wiring (below)
    and evaluation leaks toward 1.0 (above)."""
    from keystone_tpu.pipelines.voc_sift_fisher import Config, VOCSIFTFisher

    img_dir, ann_dir = _write_voc_fixture(str(tmp_path / "voc"))
    out = VOCSIFTFisher.run(
        Config(
            images_dir=img_dir,
            annotations_dir=ann_dir,
            image_size=48,
            gmm_k=8,
            pca_dims=16,
            descriptor_samples_per_image=16,
            solver_block_size=256,
            num_epochs=2,
            lam=1e-4,
        )
    )
    assert 0.80 <= out["mean_ap"] <= 0.93, out["mean_ap"]
