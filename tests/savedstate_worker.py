"""Worker for the cross-process saved-state test (test_savedstate.py).

Phase "save": featurize named loader data, persist every saveable prefix.
Phase "load": in a NEW process, set PipelineEnv.state_dir and apply the
same pipeline — the SavedStateLoadRule must reload the featurized prefix
(named datasets keep prefix signatures stable across processes) instead
of recomputing.  Prints the feature checksum either way; the parent
asserts the checksums match and that the load phase logged a reload.
"""

import logging
import os
import sys


def build(data):
    from keystone_tpu.ops import LinearRectifier, PaddedFFT, RandomSignNode

    from keystone_tpu.workflow import Pipeline

    dim = data.array.shape[1]
    pipe = (
        Pipeline.of(RandomSignNode.init(dim, seed=7))
        .and_then(PaddedFFT())
        .and_then(LinearRectifier(0.0))
    )
    return pipe(data)


def main() -> None:
    phase, state_dir = sys.argv[1], sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    logging.basicConfig(level=logging.INFO)

    import numpy as np

    from keystone_tpu.loaders.mnist import MnistLoader
    from keystone_tpu.workflow import PipelineEnv

    data = MnistLoader.synthetic(64, seed=3).data  # named dataset
    if phase == "save":
        from keystone_tpu.workflow.state import save_pipeline_state

        result = build(data)
        saved = save_pipeline_state(result, state_dir)
        out = result.get().numpy()
        print(f"SAVED n={saved} checksum={np.abs(out).sum():.4f}", flush=True)
    else:
        PipelineEnv.state_dir = state_dir
        out = build(data).get().numpy()
        print(f"LOADED checksum={np.abs(out).sum():.4f}", flush=True)


if __name__ == "__main__":
    main()
