"""Guards the headline benchmark program (bench.py).

bench.py only executes on the real chip at round end; this smoke test
compiles and runs the exact same forward on the CPU mesh so a regression
in any stage (SIFT → PCA → FV → normalize → block-linear) is caught by
the suite, not by the driver.
"""

import sys
import os

import jax.numpy as jnp
import numpy as np
import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def test_bench_forward_compiles_and_is_finite():
    fwd = jax.jit(bench.build_forward())
    imgs = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (4, bench.IMAGE_HW, bench.IMAGE_HW, 3)),
        jnp.float32,
    )
    out = fwd(imgs)
    assert out.shape == (4, bench.NUM_CLASSES)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_bench_forward_batch_invariance():
    # per-image results must not depend on batch packing (pure map semantics,
    # the reference's Transformer.apply(RDD) contract)
    fwd = jax.jit(bench.build_forward())
    imgs = jnp.asarray(
        np.random.default_rng(1).uniform(0, 1, (6, bench.IMAGE_HW, bench.IMAGE_HW, 3)),
        jnp.float32,
    )
    full = fwd(imgs)
    half = fwd(imgs[:3])
    np.testing.assert_allclose(np.asarray(full[:3]), np.asarray(half), rtol=2e-4, atol=2e-4)


def test_measure_ips_runs_on_cpu():
    ips = bench.measure_ips(batch=2, run_lengths=(1, 2, 3), reps=1, warmup=1)
    assert ips > 0


def test_bench_multiscale_forward_compiles():
    """The multi-scale leg's forward (vl_phow bins + smoothing) must
    compile and stay finite — it is a first-class bench metric since r4."""
    fwd = jax.jit(
        bench.build_forward(
            bin_sizes=bench.MS_BIN_SIZES, smoothing_magnif=bench.MS_SMOOTHING
        )
    )
    imgs = jnp.asarray(
        np.random.default_rng(2).uniform(
            0, 1, (2, bench.IMAGE_HW, bench.IMAGE_HW, 3)
        ),
        jnp.float32,
    )
    out = fwd(imgs)
    assert out.shape == (2, bench.NUM_CLASSES)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_solver_flops_matches_hand_count():
    """2·MACs accounting for the weighted-BCD solve: Gramian + target
    products over blocks x epochs."""
    n, d, k, bs, e = 64, 96, 4, 32, 2
    nb = 3
    want = e * (2 * n * bs * bs * nb + 6 * n * bs * k * nb)
    assert bench.solver_flops(n, d, k, bs, e) == want
    # ragged tail: d=80 → blocks (32, 32, 16); the last block must be
    # charged its TRUE width, not bs (the docstring's honesty guard)
    n, d = 64, 80
    want = e * sum(2 * n * w * w + 6 * n * w * k for w in (32, 32, 16))
    assert bench.solver_flops(n, d, k, bs, e) == want


def test_kernel_flops_matches_hand_count():
    """2·MACs accounting for the blockwise KRR sweep: kernel column
    gemm + F update + block target + Cholesky, over blocks × epochs."""
    n, d, k, bs, e = 96, 12, 4, 32, 2
    nb = 3
    want = e * nb * (
        2 * n * bs * d + 2 * n * bs * k + 2 * bs * bs * k + bs**3 / 3
    )
    assert bench.kernel_flops(n, d, k, bs, e) == want


def test_measure_kernel_at_scale_runs_on_cpu(monkeypatch):
    """The kernel_at_scale leg (scaled down) on CPU: both sweeps run,
    the A/B r² gate holds, and the OC dataflow accounts are populated
    (the acceptance fields)."""
    monkeypatch.setattr(bench, "KERNEL_N", 160)
    monkeypatch.setattr(bench, "KERNEL_D", 16)
    monkeypatch.setattr(bench, "KERNEL_K", 3)
    monkeypatch.setattr(bench, "KERNEL_BLOCK", 32)
    monkeypatch.setattr(bench, "KERNEL_EPOCHS", 2)
    monkeypatch.setattr(bench, "KERNEL_GAMMA", 0.02)
    out = bench.measure_kernel_at_scale()
    assert out["kernel_tflops"] > 0 and out["oc_kernel_tflops"] > 0
    assert out["oc_vs_incore_r2"] >= 0.999
    assert out["transfer_seconds"] > 0
    assert out["device_busy_fraction"] is not None
    assert out["oc_store_bytes"] > 0 and out["oc_over_resident_x"] > 0


def test_measure_solver_runs_on_cpu(monkeypatch):
    """The solver-phase leg runs (scaled down) on the CPU mesh and
    reports positive TFLOP/s."""
    monkeypatch.setattr(bench, "FIT_N", 64)
    monkeypatch.setattr(bench, "FIT_CLASSES", 4)
    monkeypatch.setattr(bench, "FIT_GMM_K", 4)
    monkeypatch.setattr(bench, "FIT_SOLVER_BLOCK", 64)
    out = bench.measure_solver()
    assert out["solver_tflops"] > 0
    assert out["solver_seconds"] > 0


def test_flops_accounting_tracks_real_descriptor_count():
    """MFU honesty guard: the analytic FLOP count must use the actual
    SIFT grid size (a hand-derived T once overcounted it by ~4%), and
    the FV term must dominate as documented."""
    from keystone_tpu.ops.sift import sift_output_count

    t = sift_output_count(bench.IMAGE_HW, bench.IMAGE_HW, bench.SIFT_STEP, (4,))
    total = bench.flops_per_image()
    fv = 4 * 2 * t * bench.PCA_DIMS * bench.GMM_K
    assert fv < total < 3 * fv
