"""Cost-based physical planner (ISSUE 20).

What must hold:

- the sampled cost model is DETERMINISTIC under a fixed seed (injected
  timing): two builds emit byte-identical plans;
- a ``plan.sample`` fault-site delay on one candidate flips the winner
  (the cost model believes its measurements) — in both directions;
- the plan ships: freeze -> manifest -> ModelRegistry.publish ->
  load_artifacts -> install re-installs the IDENTICAL plan (fingerprint
  equality), and the pickled applier a process worker spawns from
  carries it too;
- precedence at every site is explicit arg > env > installed plan >
  static default, and the no-plan path is byte-identical to the legacy
  path;
- the PlanTuner retunes safe knobs from telemetry, bakes every retune
  under the rollback discipline (burn -> revert, quiet -> commit into
  the plan), including under the workload zoo's ``drift`` scenario;
- the analysis ``plan`` pass flags stale plans and unrunnable
  candidates as warnings, and is inert with no plan installed.
"""

import json
import pickle

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import faults, planner
from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.ops.stats import NormalizeRows
from keystone_tpu.planner import registry as plans
from keystone_tpu.planner.cost import fit_curve, price, select_knobs
from keystone_tpu.planner.plan import PhysicalPlan, StageChoice, stage_signature
from keystone_tpu.serve import ModelRegistry, serve
from keystone_tpu.serve.autoscale import Signals
from keystone_tpu.utils import precision
from keystone_tpu.workflow import Dataset, Pipeline

pytestmark = pytest.mark.serve

DIM = 8
CLASSES = 3


@pytest.fixture(autouse=True)
def _no_installed_plan():
    """Every test starts AND ends on the legacy no-plan path."""
    planner.clear_plan()
    yield
    planner.clear_plan()


def _pipeline(seed: int = 0):
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(DIM, CLASSES)).astype(np.float32))
    return (Pipeline.of(NormalizeRows()) | LinearMapper(w)).fit()


def _X(n: int = 64, seed: int = 0):
    return np.random.default_rng(seed).normal(size=(n, DIM)).astype(np.float32)


def _one_device():
    import jax

    return [jax.devices()[0]]


def _flat_runner(costs):
    """Injected deterministic timer: ``costs[(gate, candidate)]`` is the
    (a, b) of a seconds = a + b*n line."""

    def run(fn, *, gate, candidate, n, **_kw):
        a, b = costs.get((gate, candidate), (1e-3, 1e-6))
        return a + b * float(n)

    return run


# ------------------------------------------------------------ cost model
def test_fit_curve_recovers_linear_cost():
    a, b = fit_curve([(8, 1.8), (32, 4.2), (128, 13.8)])
    assert a == pytest.approx(1.0, abs=1e-6)
    assert b == pytest.approx(0.1, abs=1e-6)
    assert price((a, b), 64) == pytest.approx(1.0 + 6.4, abs=1e-5)
    # degenerate sets collapse to a flat curve, never explode
    assert fit_curve([]) == (0.0, 0.0)
    assert fit_curve([(32, 2.0)]) == (2.0, 0.0)


def test_cost_model_is_deterministic_under_a_fixed_seed():
    fitted = _pipeline()
    X = _X(64)
    run = _flat_runner({("matmul", "auto"): (1e-3, 1e-6),
                        ("matmul", "f32"): (2e-3, 2e-6)})
    p1 = planner.build_plan(fitted, example=X, seed=7, runner=run)
    p2 = planner.build_plan(fitted, example=X, seed=7, runner=run)
    assert p1.to_json() == p2.to_json()
    assert p1.fingerprint() == p2.fingerprint()
    # the sampled schedule rides the seed: it is part of plan identity
    p3 = planner.build_plan(fitted, example=X, seed=8, runner=run)
    assert p3.seed == 8
    # and the plan is honest about what it measured
    assert p1.backend == plans.current_backend()
    assert p1.choice_for("matmul") == "auto"
    assert any(s.gate == "matmul" for s in p1.stages)
    for s in p1.stages:
        for c in s.candidates:
            assert c.samples, f"candidate {c.name} shipped no samples"


def test_fault_site_delay_flips_the_winner_both_ways():
    """Stalling one candidate's timed region through the ``plan.sample``
    fault site makes the OTHER candidate win — the cost model picks from
    measurements, not priors."""
    fitted = _pipeline()
    X = _X(32)
    kw = dict(example=X, batch_sizes=(4, 8), full_batch=8, seed=0,
              candidates={"matmul": ("auto", "f32")})
    with faults.inject("plan.sample:ctx.candidate=auto:delay=0.05"):
        slow_auto = planner.build_plan(fitted, **kw)
    assert slow_auto.choice_for("matmul") == "f32"
    (stage,) = [s for s in slow_auto.stages if s.gate == "matmul"]
    by_name = {c.name: c for c in stage.candidates}
    assert by_name["auto"].full_seconds >= 0.05
    with faults.inject("plan.sample:ctx.candidate=f32:delay=0.05"):
        slow_f32 = planner.build_plan(fitted, **kw)
    assert slow_f32.choice_for("matmul") == "auto"


def test_select_knobs_from_forward_curve():
    knobs = select_knobs((0.002, 0.0001), max_batch=32)
    ok, coerced, why = plans.validate_knob("buckets", knobs["buckets"])
    assert ok, why
    assert coerced[-1] == 32
    # ~2 fixed overheads, clamped to [1, 20] ms
    assert knobs["max_wait_ms"] == pytest.approx(4.0, abs=0.5)
    assert knobs["dispatch_window"] == 2
    assert knobs["pool_budget_bytes"] >= 1 << 20
    assert knobs["hedge_ms"] >= 50.0
    # no curve: the knob set stays conservative
    bare = select_knobs(None, max_batch=32)
    assert bare["max_wait_ms"] == 5.0
    assert "hedge_ms" not in bare


# ------------------------------------------------------- plan + registry
def test_plan_json_roundtrip_and_validation():
    plan = planner.build_plan(
        _pipeline(), example=_X(32), seed=3,
        runner=_flat_runner({}),
    )
    back = PhysicalPlan.from_json(plan.to_json())
    assert back.fingerprint() == plan.fingerprint()
    assert back.to_dict() == plan.to_dict()
    # a fresh same-backend plan validates clean
    assert plan.validate(backend=plans.current_backend()) == []
    # format drift is rejected loudly (never half-read)
    d = plan.to_dict()
    d["format"] = 99
    with pytest.raises(ValueError):
        PhysicalPlan.from_dict(d)
    # unknown gates / non-candidates / unrunnable winners / bad knobs
    bad = PhysicalPlan(
        backend="cpu",
        stages=[
            StageChoice(gate="nope", signature="s", label="l",
                        winner="x", why=""),
            StageChoice(gate="matmul", signature="s", label="l",
                        winner="fp4", why=""),
            StageChoice(gate="gram_pallas", signature="s", label="l",
                        winner="pallas", why=""),
        ],
        knobs={"max_wait_ms": 1e9},
    )
    codes = [c for c, _ in bad.validate(backend="cpu")]
    assert codes.count("bad-plan-candidate") == 4


def test_registry_precedence_forced_over_plan_over_nothing():
    assert plans.planned_gate("matmul") is None
    assert plans.planned_knob("max_wait_ms") is None
    assert plans.plan_status() is None
    plan = PhysicalPlan(
        backend="cpu",
        stages=[StageChoice(gate="matmul", signature="s", label="l",
                            winner="f32", why="test")],
        knobs={"max_wait_ms": 2.5, "buckets": [4, 2]},
        source="test",
    )
    planner.install_plan(plan)
    assert plans.planned_gate("matmul") == "f32"
    assert plans.planned_knob("max_wait_ms") == 2.5
    assert plans.planned_knob("buckets") == (2, 4)  # coerced sorted set
    assert plans.planned_knob("hedge_ms") is None  # plan doesn't carry it
    status = plans.plan_status()
    assert status["source"] == "install"
    assert status["choices"] == {"matmul": "f32"}
    assert status["fingerprint"] == plan.fingerprint()
    # the cost model's sampling lever sits ABOVE the plan
    with plans.forced("matmul", "bf16"):
        assert plans.planned_gate("matmul") == "bf16"
    assert plans.planned_gate("matmul") == "f32"
    # a corrupt/foreign plan never forces a bad dispatch
    plan.stages[0].winner = "not-a-candidate"
    assert plans.planned_gate("matmul") is None
    plan.knobs["max_wait_ms"] = -4.0
    assert plans.planned_knob("max_wait_ms") is None
    planner.clear_plan()
    assert plans.plan_status() is None


def test_matmul_mode_explicit_wins_over_plan(monkeypatch):
    monkeypatch.setattr(precision, "_MODE", "auto")
    monkeypatch.setattr(precision, "_MODE_EXPLICIT", False)
    assert precision.matmul_mode() == "f32"  # auto resolves off-TPU
    planner.install_plan(PhysicalPlan(
        backend="cpu",
        stages=[StageChoice(gate="matmul", signature="s", label="l",
                            winner="bf16", why="test")],
    ))
    assert precision.matmul_mode() == "bf16"  # the plan tier applies
    with precision.matmul("auto"):  # explicit masks the plan...
        assert precision.matmul_mode() == "f32"
    assert precision.matmul_mode() == "bf16"  # ...and unmasks on exit


# ------------------------------------------------- shipping (the tentpole)
def test_plan_ships_freeze_manifest_registry_spawn(tmp_path):
    fitted = _pipeline()
    X = _X(32)
    frozen = fitted.freeze(plan=True, example=X)
    plan = frozen.plan
    assert plan is not None
    fp = plan.fingerprint()
    assert plans.plan_status()["source"] == "freeze"

    bundle = frozen.export_artifacts(example=X[0], buckets=(2, 4))
    assert bundle["manifest"]["plan"] == plan.to_dict()

    reg = ModelRegistry(str(tmp_path / "registry"))
    version = reg.publish(fitted, artifacts=bundle)
    arts = reg.load_artifacts(version)
    assert arts is not None
    assert arts["manifest"]["plan"] == plan.to_dict()

    # a fresh host (no plan installed) installs the bundle: the shipped
    # plan re-installs verbatim
    planner.clear_plan()
    loaded, got_version = reg.load()
    assert got_version == version
    ap2 = loaded.freeze()
    assert ap2.install_artifacts(arts) > 0
    assert ap2.plan.fingerprint() == fp
    assert planner.current_plan().fingerprint() == fp
    assert plans.plan_status()["source"] == "artifacts"

    # the pickled applier (replica clone / process-worker spawn payload)
    # carries the plan even without artifacts
    planner.clear_plan()
    ap3 = pickle.loads(pickle.dumps(frozen))
    assert ap3.plan.fingerprint() == fp
    planner.install_plan(ap3.plan, source="spawn")
    assert plans.plan_status()["source"] == "spawn"

    # and the planned freeze serves the same bytes as the legacy path
    planner.clear_plan()
    y_legacy = np.asarray(fitted.freeze()(Dataset(X, shard=False)).array)
    planner.install_plan(plan)
    y_planned = np.asarray(frozen(Dataset(X, shard=False)).array)
    assert np.array_equal(y_legacy, y_planned)


def test_service_knobs_resolve_explicit_over_plan_over_default():
    fitted = _pipeline()
    example = np.zeros((DIM,), np.float32)
    plan = PhysicalPlan(
        backend="cpu",
        knobs={"buckets": [2, 4], "max_wait_ms": 2.5, "dispatch_window": 3},
        source="test",
    )
    planner.install_plan(plan)
    svc = serve(fitted, max_batch=8, example=example, name="plan_knobs",
                supervise=False, devices=_one_device())
    try:
        # planned tier; max_batch is always appended as the top bucket
        assert svc.buckets == (2, 4, 8)
        assert svc.max_wait_s == pytest.approx(0.0025)
        assert svc._pool.window == 3
        assert svc.status()["plan"]["fingerprint"] == plan.fingerprint()
    finally:
        svc.close()
    svc = serve(fitted, max_batch=8, example=example, name="plan_knobs2",
                supervise=False, devices=_one_device(),
                max_wait_ms=7.0, buckets=(8,))
    try:
        # explicit args beat the installed plan
        assert svc.buckets == (8,)
        assert svc.max_wait_s == pytest.approx(0.007)
    finally:
        svc.close()
    planner.clear_plan()
    svc = serve(fitted, max_batch=8, example=example, name="plan_knobs3",
                supervise=False, devices=_one_device())
    try:
        # no plan: the historical static defaults, byte-identical
        assert svc.max_wait_s == pytest.approx(0.005)
        assert svc.buckets == (8,)
        assert svc.status()["plan"] is None
    finally:
        svc.close()


def test_retune_buckets_guardrails():
    fitted = _pipeline()
    svc = serve(fitted, max_batch=8, example=np.zeros((DIM,), np.float32),
                name="plan_retune", supervise=False, devices=_one_device())
    try:
        assert svc.retune_buckets((2, 4)) == (2, 4, 8)
        out = np.asarray(
            svc.submit(np.ones((DIM,), np.float32)).result(timeout=30)
        )
        assert np.all(np.isfinite(out))
        with pytest.raises(ValueError):
            svc.retune_buckets(())
        with pytest.raises(ValueError):
            svc.retune_buckets((0,))
        assert svc.buckets == (2, 4, 8)  # a rejected retune changes nothing
    finally:
        svc.close()


# --------------------------------------------------------------- PlanTuner
class _StubService:
    """The tuner's service surface, minus the threads: buckets +
    retune_buckets, no process workers, an autoscaler holding the
    window knob (so only the bucket branch is in play)."""

    name = "stub"
    workers = 0
    _closing = False
    recorder = None

    def __init__(self):
        self.buckets = (8, 32)
        self.max_batch = 32
        self.autoscaler = object()  # the window knob is owned elsewhere

    def retune_buckets(self, buckets):
        self.buckets = tuple(buckets)
        return self.buckets


def _idle_signals():
    return Signals(workers=1, queue_depth=0, queue_bound=64,
                   occupancy=0.2, burn_rate=0.0, pool_hit_rate=None)


def _tuner(svc, plan, clock, rows, burn):
    return planner.PlanTuner(
        svc, plan=plan, clock=clock,
        signal_source=_idle_signals,
        rows_source=rows, burn_source=burn,
        bake_s=1.0, bake_max_burn=2.0, min_samples=2, cooldown_s=0.0,
    )


def test_tuner_retunes_bakes_and_commits_into_the_plan():
    svc = _StubService()
    plan = PhysicalPlan(backend="cpu", knobs={"buckets": [8, 32]})
    now = [0.0]
    hist = {"count": 0.0, "sum": 0.0}

    def rows():  # every tick: 10 flushes averaging 1.4 rows
        hist["count"] += 10
        hist["sum"] += 14.0
        return dict(hist)

    burn = {"burn_rate": 0.0, "window_requests": 50}
    tuner = _tuner(svc, plan, lambda: now[0], rows, lambda: dict(burn))
    assert tuner.tick() is None  # first read only establishes the base
    now[0] = 0.1
    assert tuner.tick() == "retune"
    assert svc.buckets == (4, 8, 32)
    assert tuner.status()["baking"]["knob"] == "buckets"
    now[0] = 0.5
    assert tuner.tick() is None  # baking, burn quiet
    now[0] = 1.2  # past bake_s
    assert tuner.tick() == "commit"
    assert tuner.commits == 1 and tuner.reverts == 0
    assert plan.knobs["buckets"] == [4, 8, 32]  # the refined model ships
    assert tuner.last_action["outcome"] == "kept"
    status = tuner.status()
    assert status["retunes"] == 1 and status["baking"] is None
    assert status["plan"] == plan.fingerprint()


def test_tuner_reverts_a_retune_that_burns_the_budget():
    svc = _StubService()
    plan = PhysicalPlan(backend="cpu", knobs={"buckets": [8, 32]})
    now = [0.0]
    hist = {"count": 0.0, "sum": 0.0}

    def rows():
        hist["count"] += 10
        hist["sum"] += 14.0
        return dict(hist)

    burn = {"burn_rate": 0.0, "window_requests": 50}
    tuner = _tuner(svc, plan, lambda: now[0], rows, lambda: dict(burn))
    tuner.tick()
    now[0] = 0.1
    assert tuner.tick() == "retune"
    burn["burn_rate"] = 5.0  # the bake window burns
    now[0] = 0.2
    assert tuner.tick() == "revert"
    assert svc.buckets == (8, 32)  # the pre-retune ladder is restored
    assert tuner.reverts == 1 and tuner.commits == 0
    assert plan.knobs["buckets"] == [8, 32]  # nothing committed
    assert tuner.last_action["outcome"] == "reverted"
    # too few windowed samples must NOT trigger a revert
    hist2 = {"count": 0.0, "sum": 0.0}

    def rows2():
        hist2["count"] += 10
        hist2["sum"] += 14.0
        return dict(hist2)

    svc2 = _StubService()
    tuner2 = _tuner(svc2, plan, lambda: now[0], rows2,
                    lambda: {"burn_rate": 5.0, "window_requests": 1})
    tuner2.tick()
    now[0] += 0.1
    assert tuner2.tick() == "retune"
    now[0] += 0.1
    assert tuner2.tick() is None  # n < min_samples: keep baking
    assert tuner2.reverts == 0


def test_tuner_revert_on_burn_under_the_zoo_drift_scenario():
    """The PR-19 drill: telemetry derived from the workload zoo's
    ``drift`` scenario (payload mean shifting across the window) drives
    the tuner; the retune committed while traffic was clean is followed
    by one that reverts when the drifted half burns the budget — and no
    event is ever lost (bucket retunes only change padding)."""
    from tools.workloads import make_scenario, payload, play

    scenario = make_scenario("drift", seed=3, duration_s=2.0, qps=100,
                             dim=DIM)
    served = []

    def submit(event, x):
        served.append(x.shape[0])
        return x.shape[0]

    results = play(scenario, submit, time_scale=0.0)
    assert len(results) == len(scenario.events)
    assert sum(served) == sum(e["rows"] for e in scenario.events)

    # fold the replay into tick-by-tick telemetry: flush occupancy from
    # the event sizes, burn from the drifted fraction of each slice
    ticks = 8
    per = max(1, len(scenario.events) // ticks)
    slices = [scenario.events[i * per:(i + 1) * per] for i in range(ticks)]
    state = {"i": 0, "count": 0.0, "sum": 0.0}

    def rows():
        sl = slices[min(state["i"], ticks - 1)]
        state["count"] += len(sl)
        state["sum"] += float(sum(e["rows"] for e in sl))
        return {"count": state["count"], "sum": state["sum"]}

    def burn():
        sl = slices[min(state["i"], ticks - 1)]
        drifted = sum(1 for e in sl if (e.get("shift") or 0.0) > 2.0)
        return {"burn_rate": 6.0 if drifted > len(sl) / 2 else 0.0,
                "window_requests": len(sl)}

    svc = _StubService()
    plan = PhysicalPlan(backend="cpu", knobs={"buckets": [8, 32]})
    now = [0.0]
    tuner = _tuner(svc, plan, lambda: now[0], rows, burn)
    outcomes = []
    for i in range(ticks):
        state["i"] = i
        now[0] = i * 0.45
        out = tuner.tick()
        if out:
            outcomes.append(out)
        if out == "revert":
            # the rollback restored exactly the pre-retune ladder
            assert svc.buckets == tuple(tuner.last_action["new"])
    assert "retune" in outcomes
    assert "revert" in outcomes  # the drifted window burned the bake
    assert tuner.reverts >= 1


# --------------------------------------------------------- analysis pass
def test_analysis_plan_pass_inert_clean_and_stale():
    from keystone_tpu.analysis import plan as plan_pass

    fitted = _pipeline()
    # inert with no plan anywhere
    assert plan_pass.run(fitted.graph, pipeline=fitted) == []
    # a fresh plan for THIS pipeline audits clean
    fresh = planner.build_plan(fitted, example=_X(32),
                               runner=_flat_runner({}))
    assert plan_pass.run(fitted.graph, pipeline=fitted, plan=fresh) == []
    # the same plan against a DIFFERENT pipeline is stale
    rng = np.random.default_rng(9)
    other = (
        Pipeline.of(NormalizeRows())
        | LinearMapper(jnp.asarray(
            rng.normal(size=(DIM, CLASSES + 1)).astype(np.float32)))
    ).fit()
    findings = plan_pass.run(other.graph, pipeline=other, plan=fresh)
    assert findings, "a foreign plan must be flagged"
    assert {f.code for f in findings} == {"stale-plan"}
    assert all(f.severity == "warning" for f in findings)
    # an unrunnable winner is a bad-plan-candidate finding
    bad = PhysicalPlan(
        backend="cpu",
        stages=[StageChoice(
            gate="gram_pallas",
            signature=stage_signature(NormalizeRows()),
            label="NormalizeRows", winner="pallas", why="")],
    )
    codes = {f.code for f in plan_pass.run(fitted.graph, pipeline=fitted,
                                           plan=bad)}
    assert "bad-plan-candidate" in codes


def test_validate_freeze_runs_the_plan_pass():
    """A stale installed plan surfaces at freeze-validate time (warning:
    freeze still succeeds — dispatch re-validates)."""
    from keystone_tpu.analysis import validate_freeze

    fitted = _pipeline()
    stale = PhysicalPlan(
        backend="cpu",
        stages=[StageChoice(gate="matmul", signature="Gone:000000000000",
                            label="Gone", winner="f32", why="")],
    )
    planner.install_plan(stale)
    report = validate_freeze(fitted, example=np.zeros((DIM,), np.float32))
    assert any(f.code == "stale-plan" for f in report.findings)
    # and the pipeline still freezes + serves (warnings never block)
    frozen = fitted.freeze()
    y = np.asarray(frozen(Dataset(_X(4), shard=False)).array)
    assert y.shape == (4, CLASSES)


# ----------------------------------------------------------------- CLI
def test_cli_plan_renders_and_explains(tmp_path, capsys):
    from keystone_tpu import cli

    plan = planner.build_plan(
        _pipeline(), example=_X(32), seed=1,
        runner=_flat_runner({("matmul", "auto"): (1e-3, 1e-6)}),
    )
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(plan.to_dict(), sort_keys=True))
    assert cli.main(["plan", "--file", str(path)]) == 0
    out = capsys.readouterr().out
    assert plan.fingerprint() in out
    assert "matmul" in out
    assert cli.main(["plan", "--file", str(path), "--explain"]) == 0
    out = capsys.readouterr().out
    assert "winner=" in out and "serving knobs" in out
    assert cli.main(["plan", "--file", str(path), "--json"]) == 0
    assert json.loads(capsys.readouterr().out) == plan.to_dict()
