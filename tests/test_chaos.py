"""Chaos tests: injected faults (keystone_tpu/faults.py) against the
hardened durable-state layer (utils/durable.py) — tier-1, single
process, CPU.  The multi-process kill tests live in test_faulttol.py;
these lock the per-layer survival contracts:

- a corrupt epoch checkpoint (injected via KEYSTONE_FAULTS, the
  acceptance scenario) resumes from the newest VALID checkpoint and
  bit-matches the uninterrupted fit;
- a truncated blockstore block is detected before its bytes reach a
  solver, and a retried fit re-spills and recovers;
- a flaky stream source retries/drops per its quota;
- injected read flakiness is absorbed by the bounded-retry layer.
"""

import os

import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.utils import durable
from keystone_tpu.utils.durable import CorruptStateError

pytestmark = pytest.mark.chaos


def _problem(seed=0, n=96, d=24, k=3):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    return x, y


def test_corrupt_epoch_checkpoint_resumes_from_last_good_bitmatch(
    tmp_path, monkeypatch
):
    """The acceptance scenario: a BCD fit whose newest epoch checkpoint
    is corrupted (via the KEYSTONE_FAULTS env plan, exactly what a
    kill-worker harness would export) resumes from the newest *valid*
    checkpoint and produces exactly the model of an uninterrupted run."""
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.workflow import Dataset

    x, y = _problem()
    est = BlockLeastSquaresEstimator(
        block_size=8, num_iter=5, lam=1e-3, fit_intercept=False
    )

    # --- control: uninterrupted 5-epoch fit
    ref = est.fit_checkpointed(
        Dataset(x), Dataset(y), checkpoint_dir=str(tmp_path / "ref")
    )

    # --- interrupted: 3 epochs, with the 3rd (newest) epoch checkpoint
    # corrupted after it durably publishes
    ckpt = str(tmp_path / "chaos")
    monkeypatch.setenv(faults.ENV_VAR, "ckpt.save:after=2:times=1:corrupt")
    short = BlockLeastSquaresEstimator(
        block_size=8, num_iter=3, lam=1e-3, fit_intercept=False
    )
    short.fit_checkpointed(Dataset(x), Dataset(y), checkpoint_dir=ckpt)
    monkeypatch.delenv(faults.ENV_VAR)

    path = os.path.join(ckpt, "bcd_epoch.npz")
    with pytest.raises(CorruptStateError):
        durable.verify_checksum(path)  # the newest save really is damaged
    assert os.path.exists(path + ".1")  # … and a last-good sibling exists

    # --- resume: the scan must skip the corrupt epoch-2 file, fall back
    # to epoch 1, and re-run epochs 2..4 — landing on the control model
    # EXACTLY (same epoch program, same state; gather/restore round-trips
    # preserve float32 bits)
    out = est.fit_checkpointed(Dataset(x), Dataset(y), checkpoint_dir=ckpt)
    np.testing.assert_array_equal(
        np.asarray(out.weights), np.asarray(ref.weights)
    )


def test_corrupt_lbfgs_checkpoint_falls_back_bitmatch(tmp_path):
    """Same contract for the L-BFGS carry checkpoints (the other solver
    family): corrupt the newest chunk checkpoint, resume a longer fit,
    match the uninterrupted trajectory exactly."""
    from keystone_tpu.models.lbfgs import DenseLBFGSwithL2
    from keystone_tpu.workflow import Dataset

    x, y = _problem(seed=1, n=64, d=10, k=2)

    def fit(num_iter, ckpt_dir):
        est = DenseLBFGSwithL2(lam=1e-3, num_iterations=num_iter, history=4)
        return est.fit_checkpointed(
            Dataset(x),
            Dataset(y),
            checkpoint_dir=ckpt_dir,
            checkpoint_every=2,
        )

    ref = fit(8, str(tmp_path / "ref"))

    ckpt = str(tmp_path / "chaos")
    with faults.inject("ckpt.save:after=1:times=1:corrupt"):
        # saves land at it=2 and it=4; after=1 lets the first through and
        # corrupts the it=4 save — the newest on disk
        fit(4, ckpt)
    path = os.path.join(ckpt, "lbfgs_dense.npz")
    with pytest.raises(CorruptStateError):
        durable.verify_checksum(path)

    out = fit(8, ckpt)  # falls back to it=2, re-runs 2..8
    np.testing.assert_array_equal(
        np.asarray(out.weights), np.asarray(ref.weights)
    )


def test_truncated_block_detected_before_solver(tmp_path):
    from keystone_tpu.workflow.blockstore import FeatureBlockStore

    x, _ = _problem()
    store = FeatureBlockStore.from_array(str(tmp_path / "store"), x, block_size=8)
    good = np.array(store.read_block(1))
    path = store._block_path(store.directory, 1)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CorruptStateError, match="truncated"):
        store.read_block(1)
    # other blocks still verify and read
    np.testing.assert_array_equal(store.read_block(1 - 1).shape, good.shape)


def test_corrupt_block_content_caught_by_checksum(tmp_path):
    """Same-size corruption (no truncation to detect): only the sealed
    store's BLAKE2b sidecar can catch it."""
    from keystone_tpu.workflow.blockstore import FeatureBlockStore

    x, _ = _problem()
    store = FeatureBlockStore.from_array(str(tmp_path / "store"), x, block_size=8)
    with faults.inject("blockstore.read:corrupt:times=1"):
        with pytest.raises(CorruptStateError, match="checksum mismatch"):
            store.read_block(0)  # corrupted in place, caught immediately
    # the damage is persistent, not a one-read fluke
    with pytest.raises(CorruptStateError, match="checksum mismatch"):
        store.read_block(0)


def test_corrupt_write_caught_at_seal_time(tmp_path):
    """Corruption introduced by the write path itself (bytes flipped
    between buffer and disk) cannot be caught by a sidecar hashed from
    the file — finalize() verifies the on-disk payload against digests
    of the in-memory chunks instead, failing the spill immediately."""
    from keystone_tpu.workflow.blockstore import FeatureBlockStore

    x, _ = _problem()
    with faults.inject("blockstore.write:after=1:times=1:corrupt"):
        with pytest.raises(CorruptStateError, match="write verification"):
            FeatureBlockStore.from_array(
                str(tmp_path / "store"), x, block_size=8
            )


def test_truncated_spill_recovers_via_refit(tmp_path):
    """End-to-end: a spill torn mid-write (injected truncate on
    blockstore.write) fails the fit attempt, and fit_with_recovery's
    rebuild re-spills and completes — no user intervention."""
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.workflow import Dataset, StreamDataset, fit_with_recovery
    from keystone_tpu.loaders.stream import batched

    x, y = _problem()
    est = BlockLeastSquaresEstimator(
        block_size=8, num_iter=2, lam=1e-3, fit_intercept=False
    )

    def build():
        # one batch per spill: the injected truncation below hits the
        # LAST write of a block, so the torn tail is never rewritten by
        # a later append (that benign case heals by construction —
        # np.memmap re-extends the file — and injects no failure)
        return est.with_data(
            StreamDataset(batched(x, x.shape[0]), n=x.shape[0]), Dataset(y)
        )

    ref = build().fit()(Dataset(x)).get().numpy()  # uninterrupted OOC fit

    with faults.inject("blockstore.write:after=2:times=1:truncate"):
        fitted, attempts = fit_with_recovery(build, max_restarts=2)
    assert attempts >= 1  # the torn spill really did cost an attempt
    got = fitted(Dataset(x)).get().numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_flaky_stream_source_retries_transparently():
    from keystone_tpu.loaders.stream import resilient

    state = {"fails": 0}

    def src():
        def it():
            for i in range(5):
                if i == 2 and state["fails"] < 2:
                    state["fails"] += 1
                    raise OSError("flaky read")
                yield np.full((4, 3), i, np.float32)

        return it()

    out = list(resilient(src, retries=2, base_delay=0.0)())
    assert state["fails"] == 2  # it really failed twice …
    assert len(out) == 5  # … and the consumer never noticed
    np.testing.assert_array_equal(out[2], np.full((4, 3), 2, np.float32))


class _SkippableSource:
    """Batch-resumable source (each fetch independent — the file-per-batch
    reader shape), where a bad batch can actually be skipped."""

    def __init__(self, n, bad, fail_always=True):
        self.n, self.bad = n, bad

    def __call__(self):
        return _SkippableIter(self.n, self.bad)


class _SkippableIter:
    def __init__(self, n, bad):
        self.i, self.n, self.bad = 0, n, bad

    def __iter__(self):
        return self

    def __next__(self):
        if self.i >= self.n:
            raise StopIteration
        i = self.i
        self.i += 1
        if i == self.bad:
            raise OSError(f"batch {i} is rotten")
        return i


def test_retry_budget_is_per_batch_not_pooled():
    """Transient failures at DIFFERENT positions must not pool into one
    budget: batch 3 failing once and batch 1 failing once (on replay)
    are each within retries=1 and the stream must complete."""
    from collections import defaultdict

    from keystone_tpu.loaders.stream import resilient

    counts = defaultdict(int)

    class It:
        def __init__(self):
            self.i = 0

        def __iter__(self):
            return self

        def __next__(self):
            if self.i >= 5:
                raise StopIteration
            i = self.i
            self.i += 1
            counts[i] += 1
            if i == 3 and counts[3] == 1:
                raise OSError("transient at 3")
            if i == 1 and counts[1] == 2:
                raise OSError("transient at 1, during replay")
            return i

    out = list(resilient(It, retries=1, base_delay=0.0)())
    assert out == [0, 1, 2, 3, 4]
    assert counts[3] >= 2 and counts[1] >= 3  # both really failed


def test_bad_batch_quota_drops_then_fails():
    from keystone_tpu.loaders.stream import resilient

    # quota 1: the deterministically-bad batch is dropped, rest delivered
    out = list(
        resilient(
            _SkippableSource(5, bad=2),
            retries=1,
            max_bad_batches=1,
            base_delay=0.0,
        )()
    )
    assert out == [0, 1, 3, 4]

    # quota 0 (default): retries exhaust, the error propagates
    with pytest.raises(OSError, match="rotten"):
        list(
            resilient(
                _SkippableSource(5, bad=2), retries=1, base_delay=0.0
            )()
        )


def test_injected_read_flakiness_absorbed_by_retries(tmp_path):
    """blockstore.read faults within the retry budget are survived — the
    exact contract FaultInjected-is-an-OSError exists to guarantee."""
    from keystone_tpu.workflow.blockstore import FeatureBlockStore

    x, _ = _problem()
    store = FeatureBlockStore.from_array(str(tmp_path / "store"), x, block_size=8)
    faults.reset_stats()
    with faults.inject("blockstore.read:every=2:raise"):
        for b in range(store.num_blocks):
            block = store.read_block(b)  # retry absorbs every injection
            assert block.shape == (store.n, store.block_size)
    st = faults.stats()
    assert st["blockstore.read"]["injected"] >= store.num_blocks // 2


def test_stream_dataset_retries_injected_batch_faults(monkeypatch):
    """env-plan chaos through a real StreamDataset: one injected batch
    fault, absorbed by the dataset's own resilient wrapper."""
    from keystone_tpu.loaders.stream import batched
    from keystone_tpu.workflow.dataset import StreamDataset

    x, _ = _problem()
    monkeypatch.setenv(faults.ENV_VAR, "stream.batch:after=2:times=1:raise")
    ds = StreamDataset(batched(x, 32), n=x.shape[0], retries=2)
    rows = np.concatenate([np.asarray(b) for b in ds.batches()])
    np.testing.assert_array_equal(rows, x)


def test_executor_stage_faults_survived_with_retries():
    """Injected stage faults ride the same retry budget as real ones."""
    from keystone_tpu.workflow import Dataset, GraphExecutor, Pipeline, Transformer

    class AddOne(Transformer):
        def params(self):
            return ()

        def apply_dataset(self, ds):
            return ds.with_array(ds.array + 1.0)

    lazy = Pipeline.of(AddOne())(Dataset(np.ones((4, 2), np.float32)))
    with faults.inject("executor.stage:times=2:raise"):
        ex = GraphExecutor(lazy.graph, node_retries=2)
        out = ex.execute(lazy.graph.sinks[0])
    np.testing.assert_allclose(np.asarray(out.dataset.array), 2.0)

    with faults.inject("executor.stage:times=3:raise"):
        ex = GraphExecutor(lazy.graph, node_retries=1)
        with pytest.raises(faults.FaultInjected):
            ex.execute(lazy.graph.sinks[0])


def test_purge_invalid_state_quarantines_only_corrupt(tmp_path):
    from keystone_tpu.workflow.recovery import purge_invalid_state, scan_state_dir

    good = str(tmp_path / "good.npz")
    bad = str(tmp_path / "bad.npz")
    durable.save_npz(good, {"w": np.ones(4)})
    durable.save_npz(bad, {"w": np.ones(4)})
    size = os.path.getsize(bad)
    with open(bad, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff\xff\xff\xff")
    scan = scan_state_dir(str(tmp_path))
    assert scan["valid"] == [good]
    assert scan["corrupt"] == [bad]
    quarantined = purge_invalid_state(str(tmp_path))
    assert quarantined == [bad + ".corrupt"]
    assert not os.path.exists(bad)
    assert os.path.exists(good)
