"""Out-of-core end to end: loader → StreamDataset → app → CLI.

The reference's scaling story starts at the loader (ImageNetLoader
streams tar shards through RDD partitions into the whole pipeline —
SURVEY.md §2.5/§3.4); these tests pin the TPU analogue: tar shards →
StreamDataset → two-branch SIFT/LCS+FV featurization → out-of-core
BlockWeightedLS spill-fit, producing the SAME model as the in-memory
path, with the feature matrix never materialized in device memory.
"""

import io
import logging
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu.loaders.csv_loader import CsvDataLoader
from keystone_tpu.loaders.imagenet import ImageNetLoader
from keystone_tpu.loaders.timit import TimitFeaturesDataLoader
from keystone_tpu.workflow import Dataset, StreamDataset


def _write_jpeg_tars(root, num_tars=3, per_tar=4, size=(48, 48), seed=0):
    """A multi-tar fixture of decodable JPEGs, one synset per tar."""
    from PIL import Image as PILImage

    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    # per-SYNSET base colors, well separated, so classes are learnable
    anchors = np.array(
        [[200, 60, 60], [60, 200, 60], [60, 60, 200], [200, 200, 60]],
        np.float32,
    )
    for t in range(num_tars):
        path = os.path.join(root, f"n{t:08d}.tar")
        base_color = anchors[t % len(anchors)]
        with tarfile.open(path, "w") as tf:
            for j in range(per_tar):
                # low-frequency texture so JPEG decode is near-lossless
                base = base_color + rng.uniform(-15, 15, size=(3,))
                img = np.tile(base, (*size, 1)) + rng.normal(0, 8, (*size, 3))
                pil = PILImage.fromarray(
                    np.clip(img, 0, 255).astype(np.uint8)
                )
                buf = io.BytesIO()
                pil.save(buf, format="JPEG", quality=95)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=f"n{t:08d}_{j}.JPEG")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    return root


# ------------------------------------------------------------- loaders


def test_imagenet_index_counts_members(tmp_path):
    root = _write_jpeg_tars(str(tmp_path / "tars"), num_tars=3, per_tar=4)
    entries = ImageNetLoader.index(root)
    assert len(entries) == 12
    labels = [e[3] for e in entries]
    assert labels == [0] * 4 + [1] * 4 + [2] * 4


def test_imagenet_stream_matches_load(tmp_path, mesh):
    root = _write_jpeg_tars(str(tmp_path / "tars"))
    size = (48, 48)
    mem = ImageNetLoader.load(root, size=size)
    st = ImageNetLoader.stream(root, size=size, batch_size=5)
    assert isinstance(st.data, StreamDataset)
    assert st.data.n == mem.data.n
    np.testing.assert_array_equal(st.labels.numpy(), mem.labels.numpy())
    got = np.concatenate(list(st.data.batches()))
    np.testing.assert_array_equal(got, mem.data.numpy())
    # re-iterable: a second sweep decodes the same pixels
    again = np.concatenate(list(st.data.batches()))
    np.testing.assert_array_equal(again, got)


def test_imagenet_stream_limit(tmp_path):
    root = _write_jpeg_tars(str(tmp_path / "tars"))
    st = ImageNetLoader.stream(root, size=(48, 48), batch_size=4, limit=7)
    assert st.data.n == 7 and st.labels.n == 7


def test_synthetic_stream_pixel_identical_to_synthetic(mesh):
    st = ImageNetLoader.synthetic_stream(24, 4, size=(48, 48), seed=1, batch_size=7)
    mem = ImageNetLoader.synthetic(24, 4, size=(48, 48), seed=1)
    np.testing.assert_array_equal(
        np.concatenate(list(st.data.batches())), mem.data.numpy()
    )
    np.testing.assert_array_equal(st.labels.numpy(), mem.labels.numpy())


def test_csv_stream_matches_load(tmp_path, mesh):
    rng = np.random.default_rng(0)
    mat = np.column_stack(
        [rng.integers(0, 5, size=23), rng.normal(size=(23, 7))]
    )
    path = str(tmp_path / "rows.csv")
    np.savetxt(path, mat, delimiter=",", fmt="%.6f")
    mem = CsvDataLoader.load(path)
    st = CsvDataLoader.stream(path, batch_size=6)
    assert st.data.n == 23
    np.testing.assert_array_equal(st.labels.numpy(), mem.labels.numpy())
    np.testing.assert_allclose(
        np.concatenate(list(st.data.batches())), mem.data.numpy(), rtol=1e-6
    )


def test_timit_stream_matches_load_npy(tmp_path, mesh):
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(31, 12)).astype(np.float32)
    labs = rng.integers(0, 9, size=31).astype(np.int64)
    fp, lp = str(tmp_path / "f.npy"), str(tmp_path / "l.npy")
    np.save(fp, feats)
    np.save(lp, labs)
    mem = TimitFeaturesDataLoader.load(fp, lp)
    st = TimitFeaturesDataLoader.stream(fp, lp, batch_size=8)
    np.testing.assert_array_equal(st.labels.numpy(), mem.labels.numpy())
    np.testing.assert_allclose(
        np.concatenate(list(st.data.batches())), mem.data.numpy()
    )


def test_column_sampler_stream_matches_inmemory(mesh):
    from keystone_tpu.ops import ColumnSampler

    rng = np.random.default_rng(3)
    descs = rng.normal(size=(20, 15, 6)).astype(np.float32)
    masks = (rng.uniform(size=(20, 15)) < 0.7).astype(np.float32)
    masks[:, 0] = 1.0  # every item keeps at least one valid descriptor
    cs = ColumnSampler(8, seed=5)
    mem = cs.apply_dataset(Dataset(descs, mask=Dataset(masks).array))
    batches = [
        (descs[:7], masks[:7]),
        (descs[7:12], masks[7:12]),
        (descs[12:], masks[12:]),
    ]
    st = cs.apply_dataset(StreamDataset(batches, n=20))
    np.testing.assert_allclose(st.numpy(), mem.numpy(), rtol=1e-6)


def test_column_sampler_host_stream_raises_typeerror(mesh):
    """A host-payload stream (text docs) must fail with the descriptive
    'featurize first' TypeError, not an AttributeError on list.ndim
    (ADVICE r3 low)."""
    from keystone_tpu.ops import ColumnSampler

    host = StreamDataset([["a doc", "b doc"]], n=2, host=True)
    with pytest.raises(TypeError, match="[Ff]eaturize to arrays"):
        ColumnSampler(4, seed=0).apply_dataset(host)


# ------------------------------------------------- end-to-end app parity


def _fv_config(stream: bool, **kw):
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import Config

    base = dict(
        num_classes=4,
        synthetic_n=24,
        image_size=48,
        gmm_k=4,
        pca_dims=16,
        num_epochs=2,
        descriptor_samples_per_image=16,
        solver_block_size=64,
        stream=stream,
        stream_batch_size=7,
    )
    base.update(kw)
    return Config(**base)


def test_imagenet_fv_stream_fit_matches_inmemory(mesh, caplog, monkeypatch):
    """The north-star gate: tar-shard-style streaming through the FULL
    two-branch pipeline produces the in-memory model's predictions,
    the features spill through a FeatureBlockStore, and the big stream
    is never materialized into device memory."""
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import ImageNetSiftLcsFV
    from keystone_tpu.workflow import blockstore

    cfg = _fv_config(stream=False)
    train_mem = ImageNetLoader.synthetic(24, 4, size=(48, 48), seed=1)
    test = ImageNetLoader.synthetic(8, 4, size=(48, 48), seed=2)
    fitted_mem = ImageNetSiftLcsFV.build(
        cfg, train_mem.data, train_mem.labels
    ).fit()
    pred_mem = fitted_mem(test.data).get().numpy()

    spills = []
    orig = blockstore.FeatureBlockStore.from_batches.__func__

    def spy(cls, directory, batches, n, block_size, dtype="float32"):
        store = orig(cls, directory, batches, n, block_size, dtype=dtype)
        spills.append((n, store.d))
        return store

    monkeypatch.setattr(
        blockstore.FeatureBlockStore, "from_batches", classmethod(spy)
    )
    train_st = ImageNetLoader.synthetic_stream(
        24, 4, size=(48, 48), seed=1, batch_size=7
    )
    with caplog.at_level(logging.WARNING, "keystone_tpu.workflow.dataset"):
        fitted_st = ImageNetSiftLcsFV.build(
            _fv_config(stream=True), train_st.data, train_st.labels
        ).fit()
        pred_st = fitted_st(test.data).get().numpy()
    assert spills and spills[0][0] == 24  # out-of-core spill path engaged
    assert not [
        r for r in caplog.records if "materializing StreamDataset" in r.message
    ], "a pipeline stage materialized the stream"
    np.testing.assert_array_equal(pred_st, pred_mem)


def test_imagenet_fv_app_entry_stream(mesh):
    """Through the app's run() entry point (the user-facing command)."""
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import ImageNetSiftLcsFV

    out = ImageNetSiftLcsFV.run(_fv_config(stream=True))
    assert out["pipeline"] == "ImageNetSiftLcsFV"
    assert 0.0 <= out["top5_error"] <= 1.0
    # the synthetic textures are learnable: streaming must not break fit
    assert out["accuracy"] > 0.5


def test_imagenet_fv_app_from_tar_fixture_stream(tmp_path, mesh):
    """One command fits from multi-tar shards via --stream: the loader
    indexes the tars, streams decode, and the fit goes out-of-core."""
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import ImageNetSiftLcsFV

    root = _write_jpeg_tars(
        str(tmp_path / "tars"), num_tars=3, per_tar=6, size=(48, 48)
    )
    cfg = _fv_config(
        stream=True, train_path=root, test_path=root, num_classes=3
    )
    out = ImageNetSiftLcsFV.run(cfg)
    # 3 flat-color synsets are separable by the LCS branch's color stats
    assert out["accuracy"] > 0.9


def test_voc_synthetic_stream_matches_synthetic(mesh):
    """Loader-level: VOC's synthetic stream is pixel- and label-identical
    to the in-memory synthetic set (the parity convention every loader
    follows)."""
    from keystone_tpu.loaders.voc import VOCLoader

    mem = VOCLoader.synthetic(18, size=(48, 48), seed=1)
    st = VOCLoader.synthetic_stream(18, size=(48, 48), seed=1, batch_size=5)
    np.testing.assert_array_equal(st.labels.numpy(), mem.labels.numpy())
    np.testing.assert_array_equal(
        np.concatenate(list(st.data.batches())), mem.data.numpy()
    )


def test_voc_app_stream_matches_inmemory(mesh):
    """VOCSIFTFisher --stream (the last of the eight apps, VERDICT r3
    weak-4): the streamed fit produces the in-memory fit's scores."""
    from keystone_tpu.pipelines.voc_sift_fisher import Config, VOCSIFTFisher

    base = dict(
        synthetic_n=18,
        image_size=48,
        gmm_k=4,
        pca_dims=16,
        descriptor_samples_per_image=16,
        solver_block_size=64,
        num_epochs=2,
    )
    out_mem = VOCSIFTFisher.run(Config(**base))
    out_st = VOCSIFTFisher.run(
        Config(**base, stream=True, stream_batch_size=5)
    )
    assert out_st["pipeline"] == "VOCSIFTFisher"
    # identical training pixels + deterministic fit → identical mAP
    np.testing.assert_allclose(out_st["mean_ap"], out_mem["mean_ap"], atol=1e-6)


def test_imagenet_augmented_eval_composes_with_stream(mesh):
    """--augmented-eval × --stream (VERDICT r3 next-6): the 10-view
    augmented evaluation must run against a model fit from the streamed
    loader, matching the in-memory augmented run."""
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import ImageNetSiftLcsFV

    out_mem = ImageNetSiftLcsFV.run(_fv_config(stream=False, augmented_eval=True))
    out_st = ImageNetSiftLcsFV.run(_fv_config(stream=True, augmented_eval=True))
    np.testing.assert_allclose(
        out_st["top5_error"], out_mem["top5_error"], atol=1e-6
    )
    np.testing.assert_allclose(
        out_st["accuracy"], out_mem["accuracy"], atol=1e-6
    )


def test_timit_app_stream_matches_inmemory(mesh):
    from keystone_tpu.pipelines.timit import Config, TimitPipeline

    base = dict(
        num_cosine_features=256,
        cosine_block_size=128,
        num_classes=8,
        synthetic_n=256,
        num_epochs=2,
    )
    out_mem = TimitPipeline.run(Config(**base))
    out_st = TimitPipeline.run(Config(**base, stream=True, stream_batch_size=64))
    assert abs(out_st["accuracy"] - out_mem["accuracy"]) < 0.05


def test_cli_stream_flag(tmp_path, mesh, capsys):
    """bin-level: the CLI routes --stream through to the app."""
    from keystone_tpu import cli

    rc = cli.main(
        [
            "ImageNetSiftLcsFV",
            "--stream",
            "--synthetic-n",
            "16",
            "--num-classes",
            "4",
            "--image-size",
            "48",
            "--gmm-k",
            "4",
            "--pca-dims",
            "16",
        ]
    )
    assert rc == 0
    assert "ImageNetSiftLcsFV" in capsys.readouterr().out


def test_cifar_stream_matches_load(tmp_path, mesh):
    from keystone_tpu.loaders.cifar import RECORD, CifarLoader

    rng = np.random.default_rng(0)
    recs = rng.integers(0, 255, size=(37, RECORD)).astype(np.uint8)
    recs[:, 0] = rng.integers(0, 10, size=37)
    path = str(tmp_path / "batch.bin")
    recs.tofile(path)
    mem = CifarLoader.load(path)
    st = CifarLoader.stream(path, batch_size=8)
    assert st.data.n == 37
    np.testing.assert_array_equal(st.labels.numpy(), mem.labels.numpy())
    np.testing.assert_allclose(
        np.concatenate(list(st.data.batches())), mem.data.numpy()
    )


def test_imagenet_stream_undecodable_member_substitutes_zero(tmp_path, caplog):
    """An undecodable tar member must keep its label slot as a zero
    image (the index pass fixed the row/label alignment), with a
    warning — unlike load(), which may skip it."""
    import logging
    import tarfile

    root = _write_jpeg_tars(str(tmp_path / "tars"), num_tars=1, per_tar=3)
    tar = os.path.join(root, os.listdir(root)[0])
    with tarfile.open(tar, "a") as tf:
        bad = b"not a jpeg at all"
        info = tarfile.TarInfo(name="broken.JPEG")
        info.size = len(bad)
        tf.addfile(info, io.BytesIO(bad))
    st = ImageNetLoader.stream(root, size=(48, 48), batch_size=4)
    assert st.data.n == 4  # index counts all members
    with caplog.at_level(logging.WARNING, "keystone_tpu.loaders.imagenet"):
        imgs = np.concatenate(list(st.data.batches()))
    assert imgs.shape[0] == 4
    assert (imgs[-1] == 0).all()  # the broken member became a zero image
    assert any("undecodable" in r.message for r in caplog.records)


# ----------------------------------------------------- host text streams


def test_newsgroups_text_stream_matches_inmemory(tmp_path, mesh):
    """Host-stage text streaming: raw documents stream from disk through
    tokenize→n-gram→tf→vocab-fit→CSR→sparse solver without the corpus
    ever materializing; predictions must match the in-memory fit on the
    SAME training tree."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_accuracy import _write_newsgroups_fixture

    from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
    from keystone_tpu.pipelines.newsgroups import Config, NewsgroupsPipeline

    train_root = _write_newsgroups_fixture(
        str(tmp_path / "train"), num_classes=3, docs_per_class=40, seed=0
    )
    test_root = _write_newsgroups_fixture(
        str(tmp_path / "test"), num_classes=3, docs_per_class=10, seed=1
    )
    out_stream = NewsgroupsPipeline.run(
        Config(
            data_path=train_root,
            test_path=test_root,
            head="ls",
            ls_lam=1e-2,
            num_features=16384,  # engages the real sparse route
            stream=True,
            stream_batch_size=16,
        )
    )
    # reference: in-memory fit on the SAME training tree, same test tree
    train = NewsgroupsDataLoader.load(train_root)
    test = NewsgroupsDataLoader.load(test_root)
    cfg = Config(head="ls", ls_lam=1e-2, num_features=16384, num_classes=3)
    fitted = NewsgroupsPipeline.build(cfg, train.data, train.labels).fit()
    preds = fitted(test.data).get().numpy().ravel()[: test.labels.n]
    acc_mem = float((preds == test.labels.numpy()).mean())
    assert abs(out_stream["accuracy"] - acc_mem) < 1e-6, (
        out_stream["accuracy"],
        acc_mem,
    )


def test_host_stream_never_materializes_through_featurizer(mesh):
    """The raw-text stream must stay lazy through the host transformer
    chain: only the featurized CSR rows may be collected."""
    from keystone_tpu.ops.nlp import (
        CommonSparseFeatures,
        LowerCase,
        Tokenizer,
    )

    reads = []

    def batches():
        for i in range(0, 30, 10):
            reads.append(i)
            yield [f"word{j} word{j} common" for j in range(i, i + 10)]

    ds = StreamDataset(batches, n=30, host=True)
    assert ds.is_host
    mapped = Tokenizer().apply_dataset(LowerCase().apply_dataset(ds))
    assert isinstance(mapped, StreamDataset) and mapped.is_host
    assert reads == []  # nothing consumed yet: lazy end to end
    csf = CommonSparseFeatures(8, sparse_output=True)
    from keystone_tpu.ops.nlp import TermFrequency, log_tf

    tf = TermFrequency(log_tf).apply_dataset(mapped)
    model = csf.fit_dataset(tf)  # ONE streaming df sweep
    assert reads == [0, 10, 20]
    rows_stream = model.apply_dataset(tf)
    assert isinstance(rows_stream, StreamDataset)
    rows = rows_stream.items  # CSR collection is the intended small sink
    assert len(rows) == 30 and hasattr(rows[0], "tocoo")


def test_newsgroups_text_stream_dense_nb_head(tmp_path, mesh):
    """Dense featurizer output (num_features < sparse threshold) over a
    text stream must become a DEVICE stream the NB head can consume
    (review finding: it used to dead-end as a host stream)."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_accuracy import _write_newsgroups_fixture

    from keystone_tpu.pipelines.newsgroups import Config, NewsgroupsPipeline

    train_root = _write_newsgroups_fixture(
        str(tmp_path / "train"), num_classes=3, docs_per_class=25, seed=0
    )
    test_root = _write_newsgroups_fixture(
        str(tmp_path / "test"), num_classes=3, docs_per_class=8, seed=1
    )
    out = NewsgroupsPipeline.run(
        Config(
            data_path=train_root,
            test_path=test_root,
            head="nb",
            num_features=512,  # dense route
            stream=True,
            stream_batch_size=16,
        )
    )
    assert out["accuracy"] > 0.5  # learnable; must not crash


def test_amazon_text_stream_matches_inmemory(tmp_path, mesh):
    """Amazon reviews: JSON-lines texts stream through HashingTF (host
    stream, no vocab fit needed) into the sparse logistic head; stream
    predictions match the in-memory fit on the same file."""
    import json as json_mod

    from keystone_tpu.loaders.amazon import AmazonReviewsDataLoader
    from keystone_tpu.pipelines.amazon_reviews import (
        AmazonReviewsPipeline,
        Config,
    )

    def write_jsonl(path, n, seed):
        data = AmazonReviewsDataLoader.synthetic(n, seed=seed)
        with open(path, "w") as f:
            for text, lab in zip(data.data.items, data.labels.numpy()):
                f.write(
                    json_mod.dumps(
                        {"reviewText": text, "overall": 5.0 if lab else 1.0}
                    )
                    + "\n"
                )
        return path

    train_path = write_jsonl(str(tmp_path / "train.jsonl"), 120, 1)
    test_path = write_jsonl(str(tmp_path / "test.jsonl"), 40, 2)
    out = AmazonReviewsPipeline.run(
        Config(
            data_path=train_path,
            test_path=test_path,
            stream=True,
            stream_batch_size=32,
            num_features=16384,
            num_iters=30,
        )
    )
    # reference: in-memory fit on the SAME file
    train = AmazonReviewsDataLoader.load(train_path)
    test = AmazonReviewsDataLoader.load(test_path)
    cfg = Config(num_features=16384, num_iters=30)
    fitted = AmazonReviewsPipeline.build(cfg, train.data, train.labels).fit()
    preds = fitted(test.data).get().numpy().ravel()[: test.labels.n]
    acc_mem = float((preds == test.labels.numpy()).mean())
    assert abs(out["accuracy"] - acc_mem) < 1e-6, (out["accuracy"], acc_mem)


def test_voc_stream_matches_load(tmp_path, mesh):
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_accuracy import _write_voc_fixture

    from keystone_tpu.loaders.voc import VOCLoader

    img_dir, ann_dir = _write_voc_fixture(str(tmp_path / "voc"), n=15)
    mem = VOCLoader.load(img_dir, ann_dir, size=(48, 48))
    st = VOCLoader.stream(img_dir, ann_dir, size=(48, 48), batch_size=4)
    assert st.data.n == mem.data.n == 15
    np.testing.assert_array_equal(st.labels.numpy(), mem.labels.numpy())
    np.testing.assert_array_equal(
        np.concatenate(list(st.data.batches())), mem.data.numpy()
    )

    # index-subset loads: rows/labels follow the subset, and the Dataset
    # NAMES are distinct per subset — names feed CSE/saved-state keys,
    # so train/test subsets of one directory must never alias
    idx = VOCLoader.index(img_dir, ann_dir)
    a = VOCLoader.load(img_dir, ann_dir, size=(48, 48), indices=[0, 2, 4], index=idx)
    b = VOCLoader.load(img_dir, ann_dir, size=(48, 48), indices=[1, 3], index=idx)
    np.testing.assert_array_equal(a.data.numpy(), mem.data.numpy()[[0, 2, 4]])
    np.testing.assert_array_equal(b.labels.numpy(), mem.labels.numpy()[[1, 3]])
    assert a.data.name != b.data.name != mem.data.name
    sa = VOCLoader.stream(
        img_dir, ann_dir, size=(48, 48), batch_size=2, indices=[0, 2, 4], index=idx
    )
    np.testing.assert_array_equal(
        np.concatenate(list(sa.data.batches())), mem.data.numpy()[[0, 2, 4]]
    )


def test_mnist_app_stream_matches_inmemory(tmp_path, mesh):
    """MnistRandomFFT --stream: CSV rows re-parse per sweep; the exact
    solver's streaming sufficient statistics must reproduce the
    in-memory fit through the app entry point."""
    from keystone_tpu.loaders.mnist import MnistLoader
    from keystone_tpu.pipelines.mnist_random_fft import Config, MnistRandomFFT

    # write a small CSV in the app's format (label, 784 pixels)
    synth = MnistLoader.synthetic(192, seed=3)
    mat = np.column_stack(
        [synth.labels.numpy().astype(np.float32), synth.data.numpy()]
    )
    train_csv = str(tmp_path / "train.csv")
    np.savetxt(train_csv, mat, delimiter=",", fmt="%.4f")
    test_synth = MnistLoader.synthetic(64, seed=4)
    test_csv = str(tmp_path / "test.csv")
    np.savetxt(
        test_csv,
        np.column_stack(
            [test_synth.labels.numpy().astype(np.float32), test_synth.data.numpy()]
        ),
        delimiter=",",
        fmt="%.4f",
    )
    base = dict(
        train_path=train_csv, test_path=test_csv, num_ffts=2, lam=1e-2
    )
    out_stream = MnistRandomFFT.run(
        Config(**base, stream=True, stream_batch_size=48)
    )
    out_mem = MnistRandomFFT.run(Config(**base))
    assert abs(out_stream["accuracy"] - out_mem["accuracy"]) < 0.02, (
        out_stream["accuracy"],
        out_mem["accuracy"],
    )


def test_timit_stream_csv_features(tmp_path, mesh):
    """TIMIT stream's CSV branch (the npy branch is covered above)."""
    rng = np.random.default_rng(1)
    feats = rng.normal(size=(25, 6)).astype(np.float32)
    labs = rng.integers(0, 4, size=25)
    fp, lp = str(tmp_path / "f.csv"), str(tmp_path / "l.txt")
    np.savetxt(fp, feats, delimiter=",", fmt="%.6f")
    np.savetxt(lp, labs, fmt="%d")
    mem = TimitFeaturesDataLoader.load(fp, lp)
    st = TimitFeaturesDataLoader.stream(fp, lp, batch_size=7)
    np.testing.assert_array_equal(st.labels.numpy(), mem.labels.numpy())
    np.testing.assert_allclose(
        np.concatenate(list(st.data.batches())), mem.data.numpy(), rtol=1e-5
    )


def test_linear_pixels_app_stream_matches_inmemory(tmp_path, mesh):
    """LinearPixels --stream: CIFAR records re-read per sweep through
    ImageVectorizer into the exact solver's streaming fit."""
    from keystone_tpu.loaders.cifar import RECORD
    from keystone_tpu.pipelines.linear_pixels import Config, LinearPixels

    def write_records(path, n, seed):
        r = np.random.default_rng(seed)
        recs = r.integers(0, 255, size=(n, RECORD)).astype(np.uint8)
        recs[:, 0] = r.integers(0, 10, size=n)
        # class-dependent brightness so the baseline is learnable
        recs[:, 1:] = np.clip(
            recs[:, 1:] // 4 + recs[:, :1] * 20, 0, 255
        ).astype(np.uint8)
        recs.tofile(path)
        return path

    train_bin = write_records(str(tmp_path / "train.bin"), 160, 1)
    test_bin = write_records(str(tmp_path / "test.bin"), 48, 2)
    base = dict(train_path=train_bin, test_path=test_bin, lam=1e-3)
    out_stream = LinearPixels.run(
        Config(**base, stream=True, stream_batch_size=32)
    )
    out_mem = LinearPixels.run(Config(**base))
    assert abs(out_stream["accuracy"] - out_mem["accuracy"]) < 0.03, (
        out_stream["accuracy"],
        out_mem["accuracy"],
    )
    # --stream without --test-path must refuse rather than eagerly load
    with pytest.raises(ValueError, match="test-path"):
        LinearPixels.run(Config(train_path=train_bin, stream=True))
