"""Pallas gram-block megakernel tests (interpret mode on CPU — the TPU
lowering is exercised by bench/verify runs on hardware)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator
from keystone_tpu.ops import gram_pallas
from keystone_tpu.ops.gram_pallas import (
    _gram_block_xla,
    _gram_tile,
    gram_block,
    gram_block_pallas,
)


def _setup(n=37, m=21, d=12, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    z = jnp.asarray(rng.normal(size=(m, d)).astype(np.float32))
    return x, z


def test_gram_pallas_matches_generator_f32():
    x, z = _setup()
    ref = np.asarray(GaussianKernelGenerator(0.3)(x, z))
    got = np.asarray(gram_block_pallas(x, z, 0.3, interpret=True))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_gram_pallas_multi_tile(monkeypatch):
    """tiles > 1 on both grid axes exercises the 128-multiple tiling
    and the output-slice unpadding (padding tiles compute exp(0)=1
    garbage that must never leak into the returned block)."""
    monkeypatch.setattr(gram_pallas, "_VMEM_BUDGET", 1 << 17)
    x, z = _setup(n=300, m=260, d=16)
    tile = _gram_tile(300, 16)
    assert tile % 128 == 0 and -(-300 // tile) >= 2
    ref = np.asarray(GaussianKernelGenerator(0.2)(x, z))
    got = np.asarray(gram_block_pallas(x, z, 0.2, interpret=True))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_gram_pallas_bf16_stream_tolerance():
    """bf16 operand streaming (the bandwidth lever): compute stays f32
    in VMEM, so the error is bounded by the input rounding alone."""
    x, z = _setup(d=16)
    ref = np.asarray(GaussianKernelGenerator(0.3)(x, z))
    got = np.asarray(gram_block_pallas(x, z, 0.3, interpret=True, mxu="bf16"))
    np.testing.assert_allclose(got, ref, atol=0.06)
    assert not np.array_equal(got, ref)  # the stream really narrowed


def test_xla_fallback_bit_identical_to_generator():
    """The dispatcher's CPU path must emit EXACTLY the generator's
    graph — solver-grade and scoring variants both."""
    x, z = _setup()
    for solver_grade in (True, False):
        ref = np.asarray(
            GaussianKernelGenerator(0.4, solver_grade=solver_grade)(x, z)
        )
        got = np.asarray(_gram_block_xla(x, z, 0.4, solver_grade=solver_grade))
        np.testing.assert_array_equal(got, ref)
    # the public dispatcher on a CPU backend routes to that chain
    ref = np.asarray(GaussianKernelGenerator(0.4)(x, z))
    np.testing.assert_array_equal(np.asarray(gram_block(x, z, 0.4)), ref)


def test_dispatcher_routing(monkeypatch):
    """gram_block routes to Pallas exactly when the backend is capable,
    the escape hatch is open, and d fits the VMEM budget."""
    calls = []

    def fake_pallas(x, z, gamma, interpret=False, mxu="f32"):
        calls.append(mxu)
        return _gram_block_xla(x, z, gamma)

    monkeypatch.setattr(gram_pallas, "gram_block_pallas", fake_pallas)
    monkeypatch.setattr(gram_pallas, "pallas_supported", lambda x=None: True)
    x, z = _setup()

    gram_block(x, z, 0.3)
    assert calls == ["f32"]

    # env escape hatch wins over a capable backend
    monkeypatch.setenv("KEYSTONE_GRAM_PALLAS", "0")
    calls.clear()
    gram_block(x, z, 0.3)
    assert calls == []
    monkeypatch.delenv("KEYSTONE_GRAM_PALLAS")

    # an over-budget feature dim falls back to the XLA chain
    assert not gram_pallas.gram_pallas_enabled(gram_pallas.GRAM_MAX_D + 1)
    assert gram_pallas.gram_pallas_enabled(64)

    # explicit False always wins
    calls.clear()
    gram_block(x, z, 0.3, use_pallas=False)
    assert calls == []


def test_oc_sweep_routes_through_pallas(monkeypatch):
    """The out-of-core KRR sweep consumes the megakernel when enabled:
    use_pallas=True dispatches every gram through gram_block_pallas
    (interpret-shimmed here) and the fit matches the XLA-chain sweep."""
    import tempfile

    from keystone_tpu.models.kernel_ridge import (
        KernelRidgeRegressionEstimator,
        _oc_krr_fit,
    )
    from keystone_tpu.workflow.blockstore import RowBlockStore

    rng = np.random.default_rng(3)
    n, d, k = 96, 8, 2
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    store = RowBlockStore.from_array(tempfile.mkdtemp(), x, 32)

    ref = _oc_krr_fit(store, jnp.asarray(y), float(n), 0.1, 1e-3, 2,
                      use_pallas=False)

    calls = []
    orig = gram_pallas.gram_block_pallas

    def interp(xa, za, gamma, interpret=False, mxu="f32"):
        calls.append(mxu)
        return orig(xa, za, gamma, interpret=True, mxu=mxu)

    monkeypatch.setattr(gram_pallas, "gram_block_pallas", interp)
    got = _oc_krr_fit(store, jnp.asarray(y), float(n), 0.1, 1e-3, 2,
                      use_pallas=True)
    assert calls and set(calls) == {"f32"}  # solver path streams f32
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_block_kernel_matrix_routes_through_pallas(monkeypatch):
    """BlockKernelMatrix's gram compute rides the megakernel for
    Gaussian generators on capable backends; duck-typed generators keep
    their own math."""
    from keystone_tpu.models.kernel_matrix import BlockKernelMatrix

    calls = []
    orig = gram_pallas.gram_block_pallas

    def interp(xa, za, gamma, interpret=False, mxu="f32"):
        calls.append(mxu)
        return orig(xa, za, gamma, interpret=True, mxu=mxu)

    monkeypatch.setattr(gram_pallas, "gram_block_pallas", interp)
    monkeypatch.setattr(gram_pallas, "pallas_supported", lambda x=None: True)

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(64, 8)).astype(np.float32))
    kern = GaussianKernelGenerator(0.2)
    km = BlockKernelMatrix(kern, x, block_size=16)
    col = np.asarray(km.column_block(1))
    assert calls == ["f32"]  # solver_grade generator → f32 stream
    np.testing.assert_allclose(
        col, np.asarray(kern(x, x[16:32])), atol=1e-5
    )

    class OtherKernel:
        gamma = 0.2

        def __call__(self, a, b):
            return jnp.ones((a.shape[0], b.shape[0]), jnp.float32)

    calls.clear()
    km2 = BlockKernelMatrix(OtherKernel(), x, block_size=16)
    out = np.asarray(km2.column_block(0))
    assert calls == [] and (out == 1.0).all()


# --------------------------------------------- polynomial / linear kernels
def test_poly_pallas_matches_generator_f32():
    from keystone_tpu.models.kernel_ridge import PolynomialKernelGenerator
    from keystone_tpu.ops.gram_pallas import poly_block_pallas

    x, z = _setup(d=10)
    gen = PolynomialKernelGenerator(degree=3, alpha=0.5, c=1.25)
    ref = np.asarray(gen(x, z))
    got = np.asarray(
        poly_block_pallas(x, z, 0.5, 1.25, 3, interpret=True)
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_poly_and_linear_xla_fallback_bit_identical():
    """The dispatcher's CPU path IS the generator for the new kernels
    too — solver-grade and scoring variants both."""
    from keystone_tpu.models.kernel_ridge import (
        LinearKernelGenerator,
        PolynomialKernelGenerator,
    )
    from keystone_tpu.ops.gram_pallas import (
        linear_gram_block,
        poly_gram_block,
    )

    x, z = _setup()
    for solver_grade in (True, False):
        pg = PolynomialKernelGenerator(
            degree=2, alpha=0.7, c=0.3, solver_grade=solver_grade
        )
        np.testing.assert_array_equal(
            np.asarray(
                poly_gram_block(
                    x, z, alpha=0.7, c=0.3, degree=2,
                    solver_grade=solver_grade, use_pallas=False,
                )
            ),
            np.asarray(pg(x, z)),
        )
        lg = LinearKernelGenerator(solver_grade=solver_grade)
        np.testing.assert_array_equal(
            np.asarray(
                linear_gram_block(
                    x, z, solver_grade=solver_grade, use_pallas=False
                )
            ),
            np.asarray(lg(x, z)),
        )


def test_linear_rides_poly_megakernel_identity():
    """linear = poly at (α=1, c=0, degree=1): the interpret-mode kernel
    matches the generator to f32 rounding."""
    from keystone_tpu.models.kernel_ridge import LinearKernelGenerator
    from keystone_tpu.ops.gram_pallas import poly_block_pallas

    x, z = _setup(d=8)
    ref = np.asarray(LinearKernelGenerator()(x, z))
    got = np.asarray(poly_block_pallas(x, z, 1.0, 0.0, 1, interpret=True))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_gram_block_for_routes_every_first_class_generator(monkeypatch):
    """The generator-dispatch entry covers Gaussian, polynomial, and
    linear under one gating; unknown generators return None (caller
    falls back to the generator itself)."""
    from keystone_tpu.models.kernel_ridge import (
        LinearKernelGenerator,
        PolynomialKernelGenerator,
    )

    x, z = _setup(d=8)
    # off-pallas: bit-identical to each generator
    for gen in (
        GaussianKernelGenerator(0.2),
        PolynomialKernelGenerator(degree=2, alpha=0.9, c=0.1),
        LinearKernelGenerator(),
    ):
        got = gram_pallas.gram_block_for(gen, x, z, use_pallas=False)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(gen(x, z)))

    class Duck:
        def __call__(self, a, b):
            return jnp.zeros((a.shape[0], b.shape[0]), jnp.float32)

    assert gram_pallas.gram_block_for(Duck(), x, z) is None


def test_block_kernel_matrix_routes_poly_and_linear(monkeypatch):
    """BlockKernelMatrix rides the poly megakernel for the new
    generators on capable backends (same gating as Gaussian)."""
    from keystone_tpu.models.kernel_matrix import BlockKernelMatrix
    from keystone_tpu.models.kernel_ridge import (
        LinearKernelGenerator,
        PolynomialKernelGenerator,
    )

    calls = []
    orig = gram_pallas.poly_block_pallas

    def interp(xa, za, alpha, c, degree, interpret=False, mxu="f32"):
        calls.append((alpha, c, degree, mxu))
        return orig(xa, za, alpha, c, degree, interpret=True, mxu=mxu)

    monkeypatch.setattr(gram_pallas, "poly_block_pallas", interp)
    monkeypatch.setattr(gram_pallas, "pallas_supported", lambda x=None: True)

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(48, 8)).astype(np.float32))
    pg = PolynomialKernelGenerator(degree=2, alpha=0.5, c=1.0)
    km = BlockKernelMatrix(pg, x, block_size=16)
    col = np.asarray(km.column_block(0))
    assert calls == [(0.5, 1.0, 2, "f32")]
    np.testing.assert_allclose(col, np.asarray(pg(x, x[:16])), rtol=1e-5, atol=1e-5)

    calls.clear()
    km2 = BlockKernelMatrix(LinearKernelGenerator(), x, block_size=16)
    np.testing.assert_allclose(
        np.asarray(km2.column_block(1)),
        np.asarray(LinearKernelGenerator()(x, x[16:32])),
        rtol=1e-5,
        atol=1e-5,
    )
    assert calls == [(1.0, 0.0, 1, "f32")]
