"""Compiled-HLO sharding-semantics gate (VERDICT r3 missing-2).

The numerics gates (dryrun mesh-sweep parity, multihost tests) cannot
distinguish a correctly sharded program from one that silently fell back
to full replication — on parity shapes both produce identical numbers.
This gate pins the SCALING claim itself, on the 8-device CPU mesh, by
inspecting the SPMD-partitioned executables of the four hot programs
(the reference's per-partition-gemm + treeReduce semantics, SURVEY.md
§3.2: collectives carry *small* Gramians/gradients/moments, never the
feature matrix):

  - ``models/block_ls.py § _bcd_fit``          (dense BCD hot loop)
  - ``models/block_ls.py § _oc_block_step``    (out-of-core BCD step)
  - ``models/lbfgs.py § _lbfgs_sparse_least_squares`` (sparse L-BFGS)
  - ``models/gmm.py § _gmm_fit``               (GMM fit: seeding + EM)

Assertions per program:

  1. every row-dimensioned input is sharded 1/n_data over 'data'
     (per-device shard shape from the compiled input shardings);
  2. at least one all-reduce exists (the treeReduce analogue);
  3. NO collective's output is O(n): every all-reduce/all-gather/
     reduce-scatter/all-to-all result has fewer elements than the
     global row count — test shapes are chosen so every legitimate
     collective payload (Gramian bs², weights bs·k, moments K·d) is
     far below n, while a gathered feature/residual operand is far
     above it.

The gate is proven live by mutation (`test_gate_detects_dropped_
constraints`): re-jitting the same program with ``constrain`` degraded
to full replication must trip the gate.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.parallel.mesh import DATA_AXIS

# collective HLO opcodes whose payload size we police.  collective-permute
# is included: a point-to-point reshard of the feature operand is just as
# much a scaling bug as a gather of it.  The opcode must be followed by
# '(' (instruction position) — operand references are %names ('%all-
# reduce.12)') and never match.
_OP_RE = re.compile(
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(?:-start|-done)?\("
)
_SHAPE_RE = re.compile(r"\b(?:f|s|u|bf|pred|c)\d*\[([\d,]*)\]")


def _collective_lines(hlo_text):
    """(line, result_elems) for every collective instruction.

    HLO instructions read ``%name = <result-shape(s)> opcode(operands)``;
    the result shape — plain ``f32[16,4]{0,1}`` or a tuple
    ``(f32[16,16]{1,0}, f32[16,2]{0,1})`` — sits BETWEEN '=' and the
    opcode.  Parsing is self-checked by the caller: a collective line on
    which no shape parses is an error, not a silent skip."""
    out = []
    for ln in hlo_text.splitlines():
        m = _OP_RE.search(ln)
        if not m:
            continue
        eq = ln.find("=")
        if eq < 0 or eq > m.start():
            continue  # not an instruction definition
        shapes_txt = ln[eq + 1 : m.start()]
        elems = []
        for sm in _SHAPE_RE.finditer(shapes_txt):
            dims = sm.group(1)
            elems.append(
                int(np.prod([int(d) for d in dims.split(",")]))
                if dims
                else 1
            )
        assert elems, (
            "collective line with no parseable result shape — the gate's "
            f"HLO parser needs updating:\n{ln.strip()[:300]}"
        )
        out.append((ln, elems))
    return out


def _assert_gate(compiled, args, n_global, label):
    """The three assertions above, against one compiled executable."""
    txt = compiled.as_text()
    coll = _collective_lines(txt)

    # (2) the treeReduce analogue must exist
    assert any(
        "all-reduce" in ln or "reduce-scatter" in ln for ln, _ in coll
    ), f"{label}: no all-reduce in compiled HLO — program is not aggregating over 'data'"

    # (3) no O(n) collective payloads
    for ln, elems_list in coll:
        for elems in elems_list:
            assert elems < n_global, (
                f"{label}: collective with {elems} >= n={n_global} result "
                f"elements — a feature/residual-sized operand is crossing "
                f"the interconnect:\n{ln.strip()[:300]}"
            )

    # (1) row-dimensioned inputs are sharded 1/n_data on 'data'
    from keystone_tpu.parallel import mesh as _mesh

    mesh = _mesh.current_mesh()
    dsize = mesh.shape[DATA_AXIS]
    leaves = jax.tree_util.tree_leaves(args)
    shardings = jax.tree_util.tree_leaves(compiled.input_shardings[0])
    assert len(leaves) == len(shardings), (
        f"{label}: {len(leaves)} arg leaves vs {len(shardings)} compiled "
        "input shardings — pass the program's FULL runtime argument list"
    )
    checked = 0
    for leaf, sh in zip(leaves, shardings):
        shape = np.shape(leaf)
        if not shape or n_global not in shape:
            continue
        ax = shape.index(n_global)
        shard = sh.shard_shape(shape)
        assert shard[ax] == n_global // dsize, (
            f"{label}: row-dimensioned input {shape} has per-device shard "
            f"{shard} — axis {ax} is not 1/{dsize} over 'data' (silent "
            f"replication fallback)"
        )
        checked += 1
    assert checked > 0, f"{label}: no row-dimensioned input found to check"
    return txt


# test shapes: n >> every legitimate collective payload (bs², bs·k, K·d,
# d·k) so assertion (3) has wide separation in both directions
_N = 512


def test_bcd_fit_stays_sharded(mesh):
    from keystone_tpu.models.block_ls import _bcd_fit

    rng = np.random.default_rng(0)
    nb, bs, k = 2, 16, 4
    xb = jnp.asarray(rng.normal(size=(nb, _N, bs)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(_N, k)).astype(np.float32))
    compiled = _bcd_fit.lower(xb, y, _N, 1e-3, 2).compile()
    _assert_gate(compiled, (xb, y, _N, 1e-3), _N, "_bcd_fit")


def test_oc_block_step_stays_sharded(mesh):
    from keystone_tpu.models.block_ls import _oc_block_step

    rng = np.random.default_rng(1)
    bs, k = 16, 4
    a_raw = jnp.asarray(rng.normal(size=(_N, bs)).astype(np.float32))
    xm_b = jnp.zeros((bs,), jnp.float32)
    yc = jnp.asarray(rng.normal(size=(_N, k)).astype(np.float32))
    sa = jnp.ones((_N,), jnp.float32)
    row_ok = jnp.ones((_N,), jnp.float32)
    p = jnp.zeros((_N, k), jnp.float32)
    wb = jnp.zeros((bs, k), jnp.float32)
    args = (a_raw, xm_b, yc, sa, row_ok, p, wb, jnp.float32(1e-2))
    compiled = _oc_block_step.lower(*args).compile()
    _assert_gate(compiled, args, _N, "_oc_block_step")


def test_sparse_lbfgs_stays_sharded(mesh):
    from keystone_tpu.models.lbfgs import _lbfgs_sparse_least_squares

    rng = np.random.default_rng(2)
    nnz, d, k = 8, 64, 4
    bidx = (jnp.asarray(rng.integers(0, d, size=(_N, nnz)).astype(np.int32)),)
    bvals = (jnp.asarray(rng.normal(size=(_N, nnz)).astype(np.float32)),)
    by = (jnp.asarray(rng.normal(size=(_N, k)).astype(np.float32)),)
    compiled = _lbfgs_sparse_least_squares.lower(
        bidx, bvals, by, jnp.float32(_N), d, 1e-3, 3, 4, False
    ).compile()
    _assert_gate(
        compiled,
        (bidx, bvals, by, jnp.float32(_N), 1e-3),
        _N,
        "_lbfgs_sparse_least_squares",
    )


def test_gmm_em_stays_sharded(mesh):
    # gate _gmm_fit, the jitted program actually executed: the inner
    # _em_steps relies on _gmm_fit's constrain for its sharding (compiled
    # standalone with replicated args it is legitimately unsharded)
    from keystone_tpu.models.gmm import _gmm_fit

    rng = np.random.default_rng(3)
    K, d = 8, 16
    x = jnp.asarray(rng.normal(size=(_N, d)).astype(np.float32))
    row_ok = jnp.ones((_N,), jnp.float32)
    compiled = _gmm_fit.lower(
        x, jnp.float32(_N), row_ok, K, 2, 1e-4, 0, 2
    ).compile()
    _assert_gate(
        compiled,
        (x, jnp.float32(_N), row_ok, 1e-4, 0),
        _N,
        "_gmm_fit",
    )


def test_gate_detects_dropped_constraints(mesh, monkeypatch):
    """Mutation proof: the SAME program re-jitted with ``constrain``
    degraded to full replication must TRIP the gate — otherwise the gate
    could not protect against a dropped with_sharding_constraint."""
    import keystone_tpu.models.block_ls as bls

    def replicate(x, *spec):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P())
        )

    monkeypatch.setattr(bls, "constrain", replicate)
    # a NEW function identity wrapping the unjitted body: jax's jaxpr
    # cache is keyed on the underlying callable, so re-jitting
    # __wrapped__ directly would silently reuse the UNMUTATED trace
    # when the clean test compiled the same shapes first
    mutated = jax.jit(
        lambda xb, y, n, lam, num_iter: bls._bcd_fit.__wrapped__(
            xb, y, n, lam, num_iter
        ),
        static_argnames=("num_iter",),
    )
    rng = np.random.default_rng(0)
    nb, bs, k = 2, 16, 4
    xb = jnp.asarray(rng.normal(size=(nb, _N, bs)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(_N, k)).astype(np.float32))
    compiled = mutated.lower(xb, y, _N, 1e-3, 2).compile()
    with pytest.raises(AssertionError, match="all-reduce|replication"):
        _assert_gate(compiled, (xb, y, _N, 1e-3), _N, "_bcd_fit[mutated]")


def test_shared_traced_param_apply_stays_sharded(mesh):
    """r5: the class-shared traced-parameter apply programs (scoring
    path — Transformer.traced_attrs) must keep the batch axis sharded
    and must introduce NO collectives: parameters ride as (replicated)
    arguments now, and a silent replication fallback or an inserted
    gather here would materialize the full feature matrix per device."""
    import importlib

    from keystone_tpu.models.pca import PCATransformer
    from keystone_tpu.parallel.mesh import shard_batch

    T = importlib.import_module("keystone_tpu.workflow.transformer")
    rng = np.random.default_rng(5)
    d, k = 16, 4
    comp = jnp.asarray(rng.normal(size=(d, k)).astype(np.float32))
    p = PCATransformer(comp, None)
    x = shard_batch(rng.normal(size=(_N, d)).astype(np.float32))
    # drive through the production path so the SHARED wrapper compiles
    out = p._apply_batch_jitted(x, None)
    assert out.shape == (_N, k)
    keys = [
        kk
        for kk in T._SHARED_APPLY_CACHE
        if kk[0] is PCATransformer and callable(T._SHARED_APPLY_CACHE[kk])
    ]
    assert keys, "shared apply did not compile"
    # lower the same wrapper at the same signature and gate the HLO
    fn = T._SHARED_APPLY_CACHE[keys[-1]]
    params = {"components": comp, "mean": None}
    compiled = fn.lower(params, x, None).compile()
    txt = compiled.as_text()
    assert not _collective_lines(txt), (
        "shared apply introduced a collective — the per-row map must "
        "stay local to each shard"
    )
    leaves = jax.tree_util.tree_leaves((params, x))
    shardings = jax.tree_util.tree_leaves(compiled.input_shardings[0])
    assert len(leaves) == len(shardings)
    from keystone_tpu.parallel import mesh as _mesh

    dsize = _mesh.current_mesh().shape[DATA_AXIS]
    for leaf, sh in zip(leaves, shardings):
        shape = np.shape(leaf)
        if shape and _N in shape:
            ax = shape.index(_N)
            assert sh.shard_shape(shape)[ax] == _N // dsize, (
                f"batch input {shape} not sharded 1/{dsize} over 'data'"
            )
