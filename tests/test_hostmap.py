"""Parallel host text maps (VERDICT r3 weak-5: the host text stage was
single-threaded pure Python).  Threads can't help — the GIL serializes
pure-Python tokenization (libjpeg's thread pool worked because C decode
releases the GIL) — so host_map forks processes.  These tests pin
result parity (pooled == sequential), the fallbacks, and the wired
paths through the NLP featurizers."""

import numpy as np
import pytest

from keystone_tpu.ops.nlp import (
    CommonSparseFeatures,
    HashingTF,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    log_tf,
    stable_term_hash,
)
from keystone_tpu.utils.hostmap import host_map, host_workers
from keystone_tpu.workflow import Dataset


def _docs(n=64, seed=0):
    rng = np.random.default_rng(seed)
    vocab = [f"w{i}" for i in range(200)]
    return [" ".join(rng.choice(vocab, size=30)) for _ in range(n)]


def test_host_map_pool_matches_sequential():
    tok = Tokenizer()
    docs = _docs(64)
    seq = [tok.apply_one(d) for d in docs]
    par = host_map(tok.apply_one, docs, workers=2, min_items=2)
    assert par == seq  # order AND content


def test_host_map_unpicklable_falls_back():
    captured = []
    fn = lambda x: (captured.append(x), x * 2)[1]  # noqa: E731
    out = host_map(fn, list(range(10)), workers=4, min_items=2)
    assert out == [i * 2 for i in range(10)]
    assert len(captured) == 10  # ran in THIS process (sequential fallback)


def test_host_map_small_input_stays_sequential():
    tok = Tokenizer()
    out = host_map(tok.apply_one, ["a b", "c d"], workers=4, min_items=1024)
    assert out == [["a", "b"], ["c", "d"]]


def test_host_workers_env(monkeypatch):
    monkeypatch.setenv("KEYSTONE_HOST_WORKERS", "3")
    assert host_workers() == 3
    monkeypatch.setenv("KEYSTONE_HOST_WORKERS", "nope")
    assert host_workers() == 1


def test_text_chain_pooled_matches_sequential(monkeypatch, mesh):
    """The wired path: the full tokenize→ngram→tf→featurize chain over
    an eager host Dataset under forced 2-worker pooling reproduces the
    single-worker rows exactly."""
    from keystone_tpu.utils import hostmap

    docs = _docs(48, seed=3)
    chain = (
        Tokenizer()
        .and_then(NGramsFeaturizer((1, 2)))
        .and_then(TermFrequency(log_tf))
    )
    terms = chain(Dataset(docs)).get()
    csf = CommonSparseFeatures(512, sparse_output=True).fit_dataset(terms)
    seq_rows = csf.apply_dataset(terms)

    monkeypatch.setattr(hostmap, "host_workers", lambda: 2)
    monkeypatch.setattr(
        hostmap.host_map, "__defaults__", (None, 2)
    )  # min_items=2 so the 48-doc input engages the pool
    par_terms = chain(Dataset(docs)).get()
    par_rows = csf.apply_dataset(par_terms)
    assert [d for d in par_terms.items] == [d for d in terms.items]
    for a, b in zip(par_rows.items, seq_rows.items):
        np.testing.assert_array_equal(a.toarray(), b.toarray())


def test_hashing_tf_memo_is_transparent():
    """stable_term_hash memoization must be value-invisible (cached ==
    uncached) and HashingTF rows unchanged by cache state."""
    from keystone_tpu.ops import nlp

    t1 = ("alpha", "beta")
    h_cold = stable_term_hash(t1)
    assert stable_term_hash(t1) == h_cold  # warm hit
    nlp._TERM_HASH_MEMO.clear()
    assert stable_term_hash(t1) == h_cold  # recomputed identically
    h = HashingTF(256, sparse_output=True)
    row1 = h.apply_one({t1: 2.0, ("gamma",): 1.0}).toarray()
    nlp._TERM_HASH_MEMO.clear()
    row2 = h.apply_one({t1: 2.0, ("gamma",): 1.0}).toarray()
    np.testing.assert_array_equal(row1, row2)


def _boom(x):
    if x == 3:
        raise ValueError("bad doc 3")
    return x * 2


def test_host_map_fn_error_propagates():
    """A data error raised by fn must propagate unchanged (sequential
    semantics), never disable the pool or silently retry."""
    from keystone_tpu.utils import hostmap

    with pytest.raises(ValueError, match="bad doc 3"):
        host_map(_boom, list(range(8)), workers=2, min_items=2)
    # the pool survives a fn error: the next map still works pooled
    out = host_map(_boom, [0, 1, 2], workers=2, min_items=2)
    assert out == [0, 2, 4]
    assert hostmap._EXECUTOR is not None


def test_host_map_broken_pool_falls_back_sequentially(monkeypatch):
    """BrokenProcessPool IS a RuntimeError subclass — the data-error
    re-raise filter must not swallow the broken-pool fallback (a killed
    worker must complete the map sequentially and tear the pool down
    for rebuild on next use)."""
    from concurrent.futures.process import BrokenProcessPool

    from keystone_tpu.utils import hostmap

    class _DeadFuture:
        def result(self):
            raise BrokenProcessPool(
                "A process in the process pool was terminated abruptly"
            )

    class _DeadPool:
        def submit(self, *a, **k):
            return _DeadFuture()

        def shutdown(self, **k):
            pass

    monkeypatch.setattr(
        hostmap, "_get_executor", lambda w: (_DeadPool(), w)
    )
    out = host_map(_boom, [0, 1, 2], workers=2, min_items=2)
    assert out == [0, 2, 4]  # completed sequentially in THIS process


def test_trivial_host_ops_opt_out_of_pool(monkeypatch):
    """Trimmer/LowerCase (one str method per item) must not ship the
    corpus through IPC — parallel_host=False keeps them sequential."""
    from keystone_tpu.ops.nlp import LowerCase, Trimmer
    from keystone_tpu.utils import hostmap

    assert Trimmer.parallel_host is False
    assert LowerCase.parallel_host is False

    def never(*a, **k):  # pragma: no cover - failing is the assert
        raise AssertionError("trivial op reached the worker pool")

    monkeypatch.setattr(hostmap, "host_map", never)
    out = Trimmer().apply_dataset(Dataset(["  a ", " b"]))
    assert out.items == ["a", "b"]


def test_csr_row_rejects_out_of_bounds_columns():
    """The direct CSR constructor skips scipy's validation, so _csr_row
    reinstates it: a vocab/num_features mismatch raises instead of
    silently zeroing features."""
    from keystone_tpu.ops.nlp import _csr_row

    with pytest.raises(ValueError, match="out of bounds"):
        _csr_row([600], [1.0], 512)
    with pytest.raises(ValueError, match="out of bounds"):
        _csr_row([-1], [1.0], 512)


def test_csr_row_direct_matches_coo_semantics():
    """_csr_row (direct constructor, no COO sort/dedup pass) must build
    the same matrix scipy's COO path would for vocab rows."""
    import scipy.sparse as sp

    from keystone_tpu.ops.nlp import _csr_row

    cols, vals, d = [7, 2, 30], [1.5, 2.0, 0.5], 64
    direct = _csr_row(cols, vals, d)
    coo = sp.csr_matrix(
        (vals, ([0] * len(cols), cols)), shape=(1, d), dtype=np.float32
    )
    np.testing.assert_array_equal(direct.toarray(), coo.toarray())
    assert direct.dtype == np.float32
