"""Tracing/profiling helpers + CLI end-to-end.

Reference analogues: the Logging-trait stage timings and Spark event-log
timeline (SURVEY.md §5); bin/run-pipeline.sh CLI entry.
"""

import glob
import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops import LinearRectifier, RandomSignNode
from keystone_tpu.utils import tracing
from keystone_tpu.utils.test_utils import gen_image, gen_image_batch, load_test_image
from keystone_tpu.workflow import Dataset, Pipeline


def _toy_result():
    data = Dataset(np.random.default_rng(0).normal(size=(32, 16)).astype(np.float32))
    pipe = Pipeline.of(RandomSignNode.init(16, seed=0)).and_then(LinearRectifier(0.0))
    return pipe(data)


def test_stage_timings_labels_every_node():
    timings = tracing.stage_timings(_toy_result())
    assert timings, "no stages timed"
    labels = " ".join(timings)
    assert "RandomSignNode" in labels
    assert "LinearRectifier" in labels
    assert all(t >= 0 for t in timings.values())


def test_stage_timings_synchronizes_fit_nodes():
    """A fit node's solve must be charged to the fit node itself, not
    dispatched async and absorbed by the next dataset-producing node."""
    from keystone_tpu.models import LinearMapEstimator
    from keystone_tpu.ops import ClassLabelIndicators

    rng = np.random.default_rng(0)
    x = Dataset(rng.normal(size=(512, 128)).astype(np.float32))
    y = ClassLabelIndicators(4)(
        Dataset(rng.integers(0, 4, size=(512,)).astype(np.int32))
    )
    pipe = Pipeline.of(LinearRectifier(0.0)).and_then(
        LinearMapEstimator(lam=1e-2), x, y
    )
    result = pipe(x)
    timings = tracing.stage_timings(result)
    # NodeChoiceRule may legitimately swap the small problem to the
    # local solve (r3); either physical form must appear in the timings
    fit_keys = [k for k in timings if "LeastSquares" in k or "LinearMap" in k]
    assert fit_keys, f"fit node missing from timings: {list(timings)}"
    assert timings[fit_keys[0]] >= 0


def test_trace_context_writes_profile(tmp_path):
    logdir = str(tmp_path / "trace")
    with tracing.trace(logdir, annotation="toy-pipeline"):
        with tracing.step_annotation(0):
            _toy_result().get()
    produced = glob.glob(os.path.join(logdir, "**", "*"), recursive=True)
    assert any(os.path.isfile(p) for p in produced), "no trace artifacts written"


def test_gen_image_deterministic_and_shaped():
    a = gen_image(8, 10, 3, seed=7)
    b = gen_image(8, 10, 3, seed=7)
    assert a.metadata.shape == (8, 10, 3)
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
    batch = gen_image_batch(5, 8, 8, 1, seed=3)
    assert batch.shape == (5, 8, 8, 1)


def test_load_test_image_variants():
    for name in ("gradient", "checkerboard", "blobs"):
        img = load_test_image(name, size=16)
        assert img.metadata.shape == (16, 16, 3)
        arr = np.asarray(img.data)
        assert np.isfinite(arr).all()
        assert arr.std() > 0  # known non-trivial content
    # gradient channel 0 ramps along x
    g = np.asarray(load_test_image("gradient", size=16).data)
    assert (np.diff(g[:, 0, 0]) > 0).all()


def test_cli_runs_mnist_end_to_end():
    """python -m keystone_tpu.cli MnistRandomFFT … on a tiny synthetic set
    (the bin/run-pipeline.sh path, minus the shell wrapper)."""
    env = dict(
        os.environ,
        KEYSTONE_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "keystone_tpu.cli",
            "MnistRandomFFT",
            "--synthetic-n",
            "256",
            "--num-ffts",
            "2",
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "accuracy" in proc.stdout
