"""Test fixture: a virtual 8-device CPU mesh.

The reference tests all distributed code paths on a LocalSparkContext
("local[N]" threads in one JVM; SURVEY.md §4).  The analogue here is
XLA's virtual CPU devices: 8 host devices exercise the same
sharding/collective code paths as an 8-chip TPU slice without hardware.
Must be set before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The environment's sitecustomize registers the axon TPU backend and forces
# jax_platforms="axon,cpu" programmatically; point the config back at cpu
# (must happen before any backend is touched).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def mesh():
    """Process-global 4x2 (data x model) mesh over the 8 virtual devices."""
    from keystone_tpu.parallel import default_mesh, set_mesh

    m = default_mesh(model_parallelism=2)
    set_mesh(m)
    yield m
    set_mesh(None)


@pytest.fixture
def rng_key():
    return jax.random.PRNGKey(0)
