"""Property-based invariant tests (hypothesis).

SURVEY.md §4 notes the reference tests numerics against closed forms on
small matrices; hypothesis generalizes that pattern — each op's defining
algebraic invariant is checked over randomized inputs.  Shapes are fixed
per test (values vary) so each property compiles one XLA program.
"""

import numpy as np
from hypothesis import assume, given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

from keystone_tpu.ops import (
    ClassLabelIndicators,
    Convolver,
    GrayScaler,
    LinearRectifier,
    MaxClassifier,
    NormalizeRows,
    PaddedFFT,
    Pooler,
    RandomSignNode,
    SignedHellingerMapper,
    StandardScaler,
    SymmetricRectifier,
    TopKClassifier,
    VectorCombiner,
    VectorSplitter,
)
from keystone_tpu.utils.matrix import matrix_to_rows, rows_to_matrix

SETTINGS = dict(max_examples=15, deadline=None)

# subnormals excluded: XLA flushes them to zero (FTZ), which is correct
# hardware behavior but breaks exact sign/involution comparisons
floats = st.floats(
    min_value=-100.0,
    max_value=100.0,
    allow_nan=False,
    allow_subnormal=False,
    width=32,
)


def batch(rows=8, cols=12):
    return arrays(np.float32, (rows, cols), elements=floats)


@given(batch())
@settings(**SETTINGS)
def test_random_sign_is_an_involution(x):
    node = RandomSignNode.init(x.shape[1], seed=3)
    twice = np.asarray(node.apply_batch(node.apply_batch(x)))
    np.testing.assert_allclose(twice, x, rtol=1e-6)


@given(batch(), batch(), st.floats(-3, 3, width=32), st.floats(-3, 3, width=32))
@settings(**SETTINGS)
def test_padded_fft_is_linear(x, y, a, b):
    fft = PaddedFFT()
    lhs = np.asarray(fft.apply_batch(a * x + b * y))
    rhs = a * np.asarray(fft.apply_batch(x)) + b * np.asarray(fft.apply_batch(y))
    np.testing.assert_allclose(lhs, rhs, atol=1e-2)
    padded = 1 << (x.shape[1] - 1).bit_length()
    assert lhs.shape == (x.shape[0], 2 * (padded // 2 + 1))


@given(batch(), st.floats(-2, 2, width=32), st.floats(-2, 2, width=32))
@settings(**SETTINGS)
def test_linear_rectifier_bounds(x, max_val, alpha):
    out = np.asarray(LinearRectifier(max_val, alpha).apply_batch(x))
    assert (out >= max_val - 1e-6).all()
    active = (x - alpha) >= max_val
    # atol below the smallest f32 normal (~1.18e-38): x−alpha can land in
    # the subnormal range even for normal inputs, and XLA flushes those
    # to zero while numpy keeps them
    np.testing.assert_allclose(
        out[active], (x - alpha)[active], rtol=1e-6, atol=1e-37
    )


@given(batch())
@settings(**SETTINGS)
def test_signed_hellinger_preserves_sign_and_squares_back(x):
    out = np.asarray(SignedHellingerMapper().apply_batch(x))
    assert (np.sign(out) == np.sign(x)).all()
    np.testing.assert_allclose(out * out, np.abs(x), rtol=1e-4, atol=1e-5)


@given(batch())
@settings(**SETTINGS)
def test_normalize_rows_gives_unit_norms(x):
    assume((np.linalg.norm(x, axis=1) > 1e-3).all())
    out = np.asarray(NormalizeRows().apply_batch(x))
    np.testing.assert_allclose(
        np.linalg.norm(out, axis=1), np.ones(x.shape[0]), rtol=1e-4
    )


@given(batch(rows=6, cols=13), st.integers(1, 16))
@settings(**SETTINGS)
def test_vector_split_combine_roundtrip(x, block_size):
    blocks = VectorSplitter(block_size).apply_batch(x)
    combined = np.asarray(VectorCombiner().apply_batch(blocks))
    d = x.shape[1]
    np.testing.assert_array_equal(combined[:, :d], x)
    assert (combined[:, d:] == 0).all()  # zero padding, never garbage


@given(st.lists(st.integers(0, 6), min_size=1, max_size=20))
@settings(**SETTINGS)
def test_class_label_indicators_one_hot_pm1(labels):
    y = np.asarray(labels, np.int32)
    out = np.asarray(ClassLabelIndicators(7).apply_batch(y))
    assert out.shape == (len(labels), 7)
    assert set(np.unique(out)) <= {-1.0, 1.0}
    assert (out.argmax(axis=1) == y).all()
    np.testing.assert_allclose(out.sum(axis=1), 2.0 - 7.0)


@given(arrays(np.float32, (9, 5), elements=floats), st.integers(1, 5))
@settings(**SETTINGS)
def test_topk_scores_are_the_k_largest(scores, k):
    # compare VALUES, not indices: ties make index order implementation-
    # defined, but the multiset of selected scores is fully determined
    top = np.asarray(TopKClassifier(k).apply_batch(scores))
    argmax = np.asarray(MaxClassifier().apply_batch(scores))
    assert top.shape == (9, k)
    picked = np.take_along_axis(scores, top, axis=1)
    expected = np.sort(scores, axis=1)[:, ::-1][:, :k]
    np.testing.assert_array_equal(np.sort(picked, axis=1), np.sort(expected, axis=1))
    head = np.take_along_axis(scores, top[:, :1], axis=1)[:, 0]
    argmax_scores = np.take_along_axis(scores, argmax[:, None], axis=1)[:, 0]
    np.testing.assert_array_equal(head, argmax_scores)


@given(arrays(np.float32, (32, 6), elements=floats))
@settings(**SETTINGS)
def test_standard_scaler_centers_and_scales(x):
    assume((x.std(axis=0) > 1e-2).all())
    model = StandardScaler().fit_arrays(x)
    out = np.asarray(model.apply_batch(x))
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-3)
    np.testing.assert_allclose(out.std(axis=0, ddof=1), 1.0, atol=1e-2)


@given(batch(rows=10, cols=4))
@settings(**SETTINGS)
def test_rows_to_matrix_roundtrip(x):
    rows = [r for r in x]
    m = rows_to_matrix(rows)
    back = matrix_to_rows(m)
    np.testing.assert_array_equal(np.stack([np.asarray(r) for r in back]), x)


images = arrays(np.float32, (3, 12, 12, 2), elements=floats)


@given(images)
@settings(**SETTINGS)
def test_sum_pooler_conserves_total_when_tiling(x):
    """Non-overlapping sum pooling that tiles the image exactly preserves
    the total sum per image/channel."""
    out = np.asarray(Pooler(stride=4, pool_size=4).apply_batch(x))
    assert out.shape == (3, 3, 3, 2)
    np.testing.assert_allclose(
        out.sum(axis=(1, 2)), x.sum(axis=(1, 2)), rtol=1e-4, atol=1e-3
    )


@given(images)
@settings(**SETTINGS)
def test_max_pooler_bounded_by_extremes(x):
    out = np.asarray(
        Pooler(stride=4, pool_size=4, pool_mode="max").apply_batch(x)
    )
    # per-image, per-channel bounds: a regression that mixes batch or
    # channel slices would still satisfy global extremes
    hi = x.max(axis=(1, 2), keepdims=True)
    lo = x.min(axis=(1, 2), keepdims=True)
    assert (out <= hi + 1e-6).all() and (out >= lo - 1e-6).all()


@given(images, st.floats(0, 2, width=32))
@settings(**SETTINGS)
def test_symmetric_rectifier_doubles_channels_nonnegative(x, alpha):
    out = np.asarray(SymmetricRectifier(alpha=alpha).apply_batch(x))
    assert out.shape == (3, 12, 12, 4)  # channel doubling
    assert (out >= 0).all()
    # pos and neg halves never both active past alpha at the same pixel
    pos, neg = out[..., :2], out[..., 2:]
    assert not np.logical_and(pos > alpha + 1e-6, neg > alpha + 1e-6).any()


@given(images)
@settings(**SETTINGS)
def test_gray_scaler_is_channel_mean_within_range(x):
    g = np.asarray(GrayScaler().apply_batch(x))
    np.testing.assert_allclose(g, x.mean(axis=-1), rtol=1e-5, atol=1e-5)


@given(
    arrays(np.float32, (2, 10, 10, 1), elements=floats),
    arrays(np.float32, (2, 10, 10, 1), elements=floats),
    st.floats(-2, 2, width=32),
)
@settings(**SETTINGS)
def test_convolver_is_linear(x, y, a):
    rng = np.random.default_rng(0)
    filters = rng.normal(size=(4, 3, 3, 1)).astype(np.float32)
    conv = Convolver(filters)
    lhs = np.asarray(conv.apply_batch(a * x + y))
    rhs = a * np.asarray(conv.apply_batch(x)) + np.asarray(conv.apply_batch(y))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-3, atol=1e-2)


@given(
    arrays(np.float32, (48, 6), elements=floats),
    arrays(np.float32, (48, 2), elements=floats),
    st.floats(0.25, 4.0, width=32),
)
@settings(**SETTINGS)
def test_ridge_is_linear_in_targets(x, y, c):
    """Scaling the targets scales the ridge solution (weights AND
    intercept) by the same factor — solver scale equivariance."""
    from keystone_tpu.models import LinearMapEstimator

    assume(np.linalg.matrix_rank(x - x.mean(0)) == x.shape[1])
    base = LinearMapEstimator(lam=0.1).fit_arrays(x, y)
    scaled = LinearMapEstimator(lam=0.1).fit_arrays(x, c * y)
    np.testing.assert_allclose(
        np.asarray(scaled.weights), c * np.asarray(base.weights),
        rtol=5e-3, atol=5e-3,
    )
    np.testing.assert_allclose(
        np.asarray(scaled.intercept), c * np.asarray(base.intercept),
        rtol=5e-3, atol=5e-3,
    )


@given(
    arrays(
        np.float32, (60, 3),
        elements=st.floats(-10, 10, allow_nan=False, allow_subnormal=False,
                           width=32),
    ),
    st.floats(-50, 50, width=32),
)
@settings(**SETTINGS)
def test_kmeans_is_translation_equivariant(x, t):
    """k-means++ with a fixed seed: translating every point translates
    every center (distances, hence seeding and assignments, are
    translation-invariant).

    CPU-only BY DESIGN: the ‖x‖²−2x·c+‖c‖² gemm expansion loses exact
    translation invariance under TPU matmul precision (‖x+t‖² ≈ t²
    dwarfs the informative differences), which can flip a k-means++
    categorical draw and move centers macroscopically.  That is a
    documented property of the distance expansion, not a solver bug —
    the invariant is only exact in full f32 accumulation."""
    import jax

    if jax.default_backend() != "cpu":
        import pytest as _pytest

        _pytest.skip("translation invariance of the distance gemm "
                     "expansion requires full-precision matmul (CPU)")
    from keystone_tpu.models import KMeansPlusPlusEstimator

    # near-duplicate point sets make k-means++ seeding a TIE between
    # duplicate candidates: the categorical draw then flips under the
    # f32 rounding of the translated distance expansion (hypothesis
    # found 59×(2,2,2) + one near-duplicate).  That is a property of
    # tie-broken sampling under finite precision, not of the solver —
    # require ≥ k well-separated distinct points for the equivariance
    # claim to be exact.
    distinct = np.unique(np.round(x, 2), axis=0)
    assume(distinct.shape[0] >= 8)

    est = lambda: KMeansPlusPlusEstimator(4, max_iterations=8, seed=7)
    c0 = np.sort(np.asarray(est().fit_arrays(x).centers), axis=0)
    c1 = np.sort(np.asarray(est().fit_arrays(x + t).centers), axis=0)
    np.testing.assert_allclose(c1, c0 + t, rtol=1e-3, atol=1e-2)


# ------------------------------------------------------------- sparse ops
@given(
    st.integers(2, 12),  # rows
    st.integers(4, 40),  # d
    st.integers(1, 6),  # nnz
    st.integers(1, 4),  # k
    st.integers(0, 2**31 - 1),
)
@settings(max_examples=25, deadline=None)
def test_sparse_matmul_and_grad_match_dense(rows, d, nnz, k, seed):
    """sparse_matmul == dense X @ w and sparse_grad == dense Xᵀ r for any
    padded-COO matrix, INCLUDING duplicate indices (which accumulate)."""
    import jax.numpy as jnp

    from keystone_tpu.ops.sparse import sparse_grad, sparse_matmul

    rng = np.random.default_rng(seed)
    nnz = min(nnz, d)
    idx = rng.integers(0, d, size=(rows, nnz)).astype(np.int32)  # dups allowed
    val = rng.normal(size=(rows, nnz)).astype(np.float32)
    # random padding entries must be inert
    pad_mask = rng.uniform(size=(rows, nnz)) < 0.3
    val[pad_mask] = 0.0
    dense = np.zeros((rows, d), np.float32)
    for i in range(rows):
        np.add.at(dense[i], idx[i], val[i])
    w = rng.normal(size=(d, k)).astype(np.float32)
    r = rng.normal(size=(rows, k)).astype(np.float32)

    got_mm = np.asarray(sparse_matmul(jnp.asarray(idx), jnp.asarray(val), jnp.asarray(w)))
    np.testing.assert_allclose(got_mm, dense @ w, rtol=2e-4, atol=2e-4)
    got_g = np.asarray(sparse_grad(jnp.asarray(idx), jnp.asarray(val), jnp.asarray(r), d))
    np.testing.assert_allclose(got_g, dense.T @ r, rtol=2e-4, atol=2e-4)


@given(
    st.integers(2, 10),
    st.integers(3, 30),
    st.integers(0, 2**31 - 1),
)
@settings(max_examples=20, deadline=None)
def test_padded_sparse_rows_roundtrip_property(rows, d, seed):
    """from_dense → toarray is the identity for any dense matrix."""
    from keystone_tpu.ops.sparse import PaddedSparseRows

    rng = np.random.default_rng(seed)
    x = ((rng.uniform(size=(rows, d)) < 0.4) * rng.normal(size=(rows, d))).astype(
        np.float32
    )
    sp = PaddedSparseRows.from_dense(x)
    np.testing.assert_allclose(sp.toarray(), x, atol=1e-6)


# ----------------------------------------------------- bucketed sparse ops


@settings(max_examples=20, deadline=None)
@given(
    nnz_counts=st.lists(st.integers(1, 60), min_size=4, max_size=24),
    seed=st.integers(0, 2**16),
)
def test_bucketed_sparse_matmul_equals_dense(nnz_counts, seed):
    """For ANY nnz profile (uniform, heavy-tailed, constant), bucketed
    matmul must equal the dense product in the original row order."""
    import scipy.sparse as sp

    from keystone_tpu.ops.sparse import BucketedSparseRows

    rng = np.random.default_rng(seed)
    d, k = 80, 3
    rows = []
    for nz in nnz_counts:
        cols = rng.choice(d, size=min(nz, d), replace=False)
        vals = rng.normal(size=cols.size).astype(np.float32)
        rows.append(
            sp.csr_matrix((vals, ([0] * cols.size, cols)), shape=(1, d))
        )
    bk = BucketedSparseRows.from_scipy_rows(rows)
    dense = np.concatenate([r.toarray() for r in rows]).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    np.testing.assert_allclose(bk.matmul(w), dense @ w, atol=5e-4)
    # permutation is a true permutation of all original indices
    assert sorted(bk.perm.tolist()) == list(range(len(rows)))
    # bucket caps are powers of two and every row's nnz fits its cap
    start = 0
    for b in bk.buckets:
        cap = b.indices.shape[1]
        assert cap & (cap - 1) == 0
        for orig in bk.perm[start : start + b.n]:
            assert min(nnz_counts[orig], d) <= cap
        start += b.n


@settings(max_examples=15, deadline=None)
@given(
    chunk=st.sampled_from([16, 32, 48, 64, 128]),  # bounded compile count
    seed=st.integers(0, 2**16),
)
def test_chunked_sparse_ops_chunk_invariant(chunk, seed):
    """sparse_matmul / sparse_grad results must not depend on the chunk
    size (the scan restructuring is purely an execution strategy).
    Generalizes tests/test_sparse.py::test_chunked_ops_match_unchunked,
    which stays as the fast fixed-chunk smoke variant."""
    import keystone_tpu.ops.sparse as sparse_mod

    rng = np.random.default_rng(seed)
    rows, nnz, d, k = 200, 9, 50, 4
    idx = rng.integers(0, d, size=(rows, nnz)).astype(np.int32)
    vals = rng.normal(size=(rows, nnz)).astype(np.float32)
    w = rng.normal(size=(d, k)).astype(np.float32)
    r = rng.normal(size=(rows, k)).astype(np.float32)
    ref_mm = np.asarray(sparse_mod.sparse_matmul(idx, vals, w))
    ref_g = np.asarray(sparse_mod.sparse_grad(idx, vals, r, d))
    orig = sparse_mod._auto_chunk
    sparse_mod._auto_chunk = lambda *a: chunk
    try:
        got_mm = np.asarray(sparse_mod.sparse_matmul(idx, vals, w))
        got_g = np.asarray(sparse_mod.sparse_grad(idx, vals, r, d))
    finally:
        sparse_mod._auto_chunk = orig
    np.testing.assert_allclose(got_mm, ref_mm, atol=1e-5)
    np.testing.assert_allclose(got_g, ref_g, atol=1e-4)
