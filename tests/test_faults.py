"""Fault-injection subsystem (keystone_tpu/faults.py): plan grammar,
deterministic replay, phase handling, env activation."""

import os

import pytest

from keystone_tpu import faults
from keystone_tpu.faults import (
    FaultInjected,
    FaultPlanError,
    fault_point,
    inject,
    parse_plan,
)


def test_plan_grammar_round_trip():
    p = parse_plan(
        "ckpt.save:after=3:raise;blockstore.read:p=0.2:seed=7;"
        "stream.batch:every=2:times=3:truncate;executor.stage:exit=9"
    )
    by_site = {s.site: s for s in p.specs}
    assert by_site["ckpt.save"].after == 3
    assert by_site["ckpt.save"].action == "raise"
    assert by_site["blockstore.read"].p == 0.2
    assert by_site["blockstore.read"].seed == 7
    assert by_site["stream.batch"].every == 2
    assert by_site["stream.batch"].times == 3
    assert by_site["stream.batch"].action == "truncate"
    assert by_site["executor.stage"].action == "exit"
    assert by_site["executor.stage"].exit_code == 9


def test_plan_rejects_unknown_site_and_token():
    with pytest.raises(FaultPlanError, match="unknown fault site"):
        parse_plan("ckpt.svae:raise")
    with pytest.raises(FaultPlanError, match="bad fault token"):
        parse_plan("ckpt.save:bogus=1")


def test_after_every_times_triggers():
    with inject("executor.stage:after=2:every=2:times=2") as plan:
        fired = []
        for i in range(10):
            try:
                fault_point("executor.stage")
                fired.append(False)
            except FaultInjected:
                fired.append(True)
        # skip 2, then every 2nd, capped at 2 fires: calls 3 and 5
        assert fired == [False, False, True, False, True] + [False] * 5
        assert plan.specs[0].fired == 2


def test_probabilistic_injection_is_deterministic():
    def run():
        pattern = []
        with inject("stream.batch:p=0.3:seed=11"):
            for _ in range(40):
                try:
                    fault_point("stream.batch")
                    pattern.append(0)
                except FaultInjected:
                    pattern.append(1)
        return pattern

    a, b = run(), run()
    assert a == b  # same plan + same call sequence = same injections
    assert 0 < sum(a) < 40  # it actually fires, and not always


def test_env_plan_activates_and_replays(monkeypatch):
    faults.reset_stats()
    monkeypatch.setenv(faults.ENV_VAR, "ckpt.load:after=1:raise")
    # first call passes, second raises — then flip the env off and on
    # again: the counters restart, so the pattern REPLAYS identically
    # (what a relaunched kill-worker sees)
    for _round in range(2):
        fault_point("ckpt.load")
        with pytest.raises(FaultInjected):
            fault_point("ckpt.load")
        monkeypatch.delenv(faults.ENV_VAR)
        fault_point("ckpt.load")  # no plan: never fires
        monkeypatch.setenv(faults.ENV_VAR, "ckpt.load:after=1:raise")
    stats = faults.stats()
    assert stats["ckpt.load"]["calls"] == 6
    assert stats["ckpt.load"]["injected"] == 2


def test_fault_injected_is_transient_oserror():
    # retry layers absorb OSError; injected faults must ride that path
    assert issubclass(FaultInjected, OSError)
    err = FaultInjected("blockstore.read")
    assert err.site == "blockstore.read"


def test_publish_phase_actions_wait_for_publish(tmp_path):
    """corrupt/truncate fire on the publish phase of two-phase sites and
    count operations (not phases) against their triggers."""
    victim = tmp_path / "state.bin"

    def one_save():
        victim.write_bytes(b"x" * 64)
        fault_point("ckpt.save", path=str(victim), phase="write")
        fault_point("ckpt.save", path=str(victim), phase="publish")

    with inject("ckpt.save:after=1:times=1:truncate"):
        one_save()
        assert victim.stat().st_size == 64  # first save untouched
        one_save()
        assert victim.stat().st_size == 32  # second save truncated
        one_save()
        assert victim.stat().st_size == 64  # times=1: done


def test_raise_actions_fire_on_write_phase(tmp_path):
    victim = tmp_path / "state.bin"
    victim.write_bytes(b"y" * 10)
    with inject("ckpt.save:raise"):
        with pytest.raises(FaultInjected):
            fault_point("ckpt.save", path=str(victim), phase="write")
        # and never double-fire on the publish half of the same save
        fault_point("ckpt.save", path=str(victim), phase="publish")


def test_corrupt_action_flips_bytes(tmp_path):
    victim = tmp_path / "blob.bin"
    victim.write_bytes(bytes(range(100)))
    with inject("blockstore.read:corrupt"):
        fault_point("blockstore.read", path=str(victim))
    data = victim.read_bytes()
    assert len(data) == 100  # same size …
    assert data != bytes(range(100))  # … different content


def test_nested_inject_innermost_wins_and_pops():
    with inject("stream.batch:after=100:raise"):
        with inject("stream.batch:raise"):
            with pytest.raises(FaultInjected):
                fault_point("stream.batch")
        fault_point("stream.batch")  # inner popped; outer still waiting
