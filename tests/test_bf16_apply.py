"""The opt-in bf16 APPLY policy (utils/precision.py § bf16_apply).

Contract under test, per converted contraction:

  1. INERT off-chip: with ``set_matmul("bf16_apply")`` on a CPU mesh the
     policy resolves to f32 and every op is BIT-identical to the f32
     mode — the tier-1 gate that keeps test meshes honest.
  2. PARITY when active: with the on-TPU gate force-lifted
     (``precision.force_bf16_apply``) each converted op matches its f32
     output within a tolerance set by bf16's 8-bit mantissa (~0.4%
     relative per input; f32 accumulation keeps reduction error from
     growing with contraction length).
  3. Solver math never inherits the cast: fits are bit-identical with
     the policy on, active or not.
  4. End-to-end: a pipeline trained in f32 and applied in both modes
     keeps its top-1 accuracy.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.utils import precision


@pytest.fixture(autouse=True)
def _restore_policy():
    before = precision._MODE  # preserve an env-pinned KEYSTONE_MATMUL
    yield
    precision.set_matmul(before)


def _tol(ref, frac=2e-2):
    return float(frac * np.abs(np.asarray(ref)).max() + 1e-6)


def _f32_vs_inert_vs_forced(apply_fn):
    """Run ``apply_fn`` under the three policy states; returns arrays."""
    with precision.matmul("f32"):
        ref = np.asarray(apply_fn())
    with precision.matmul("bf16_apply"):
        inert = np.asarray(apply_fn())  # CPU: the gate keeps this f32
    with precision.matmul("bf16_apply"), precision.force_bf16_apply():
        active = np.asarray(apply_fn())
    return ref, inert, active


# ------------------------------------------------------------- resolution


def test_mode_resolution_gates_on_tpu():
    """bf16_apply is a legal mode that resolves INERT off-chip; the
    force override (the parity suite's lever) lifts the gate."""
    with precision.matmul("bf16_apply"):
        assert precision.matmul_mode() == "f32"  # CPU mesh: inert
        assert precision.apply_mode() == "f32"
        assert precision.adtype() == jnp.float32
        with precision.force_bf16_apply():
            assert precision.matmul_mode() == "bf16_apply"
            assert precision.apply_mode() == "bf16_apply"
            assert precision.adtype() == jnp.bfloat16
            # the apply policy is a superset of the featurize policy
            assert precision.fdtype() == jnp.bfloat16
    # featurize-only modes never activate the apply helpers
    with precision.matmul("bf16"):
        assert precision.apply_mode() == "f32"
        assert precision.adtype() == jnp.float32


def test_helpers_inert_path_is_plain_f32():
    rng = np.random.default_rng(0)
    a = rng.normal(size=(8, 16)).astype(np.float32)
    b = rng.normal(size=(16, 4)).astype(np.float32)
    with precision.matmul("f32"):
        got = np.asarray(precision.apply_dot(a, b))
        ein = np.asarray(precision.apply_einsum("ij,jk->ik", a, b))
    want = np.asarray(
        jnp.dot(a, b, preferred_element_type=jnp.float32)
    )
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(ein, want)


def test_helpers_active_cast_to_bf16_with_f32_accumulation():
    rng = np.random.default_rng(1)
    a = rng.normal(size=(32, 64)).astype(np.float32)
    b = rng.normal(size=(64, 8)).astype(np.float32)
    with precision.matmul("bf16_apply"), precision.force_bf16_apply():
        got = precision.apply_dot(a, b)
    assert got.dtype == jnp.float32  # result stays f32
    ref = a @ b
    assert not np.array_equal(np.asarray(got), ref)  # inputs were rounded
    np.testing.assert_allclose(np.asarray(got), ref, atol=_tol(ref))


# ------------------------------------------------- per-op parity + inertness


def test_sift_bf16_apply():
    from keystone_tpu.ops import SIFTExtractor

    imgs = np.random.default_rng(2).uniform(0, 1, (2, 48, 48)).astype(np.float32)
    sift = SIFTExtractor(step=6, bin_sizes=(4, 6))  # engages the blur too
    ref, inert, active = _f32_vs_inert_vs_forced(
        lambda: sift.apply_batch(imgs)[0]
    )
    np.testing.assert_array_equal(inert, ref)
    np.testing.assert_allclose(active, ref, atol=2e-2)


def test_blur_einsums_bf16_apply():
    from keystone_tpu.ops.filters import separable_gaussian_blur

    x = np.random.default_rng(3).uniform(0, 1, (2, 32, 32, 3)).astype(np.float32)
    ref = np.asarray(separable_gaussian_blur(jnp.asarray(x), 1.2, mxu="f32"))
    act = np.asarray(
        separable_gaussian_blur(jnp.asarray(x), 1.2, mxu="bf16_apply")
    )
    np.testing.assert_allclose(act, ref, atol=_tol(ref))
    # featurize-only bf16 stays out of the blur (inert helper mode)
    feat = np.asarray(separable_gaussian_blur(jnp.asarray(x), 1.2, mxu="bf16"))
    np.testing.assert_array_equal(feat, ref)


@pytest.mark.parametrize("strategy", ["direct", "im2col"])
def test_convolver_bf16_apply(strategy):
    from keystone_tpu.ops import Convolver

    rng = np.random.default_rng(4)
    imgs = rng.uniform(0, 1, (2, 16, 16, 3)).astype(np.float32)
    filt = rng.normal(size=(8, 5, 5, 3)).astype(np.float32)
    conv = Convolver(jnp.asarray(filt), strategy=strategy)
    ref, inert, active = _f32_vs_inert_vs_forced(
        lambda: conv.apply_batch(jnp.asarray(imgs))
    )
    np.testing.assert_array_equal(inert, ref)
    np.testing.assert_allclose(active, ref, atol=_tol(ref))
    assert not np.array_equal(active, ref)  # the cast really engaged


def test_fisher_einsum_bf16_apply():
    from keystone_tpu.models.gmm import GaussianMixtureModel
    from keystone_tpu.ops.fisher import FisherVector

    rng = np.random.default_rng(5)
    k, d, t, n = 8, 16, 64, 4
    gmm = GaussianMixtureModel(
        jnp.full((k,), 1.0 / k),
        jnp.asarray(rng.normal(size=(k, d)), jnp.float32),
        jnp.ones((k, d), jnp.float32),
    )
    xs = jnp.asarray(rng.normal(size=(n, t, d)), jnp.float32)
    fv = FisherVector(gmm, use_pallas=False)
    ref, inert, active = _f32_vs_inert_vs_forced(lambda: fv.apply_batch(xs))
    np.testing.assert_array_equal(inert, ref)
    # posterior gemms + s1/s2 einsums under the policy: γ is a softmax
    # (bounded [0,1]) and Φ is normalized, so 4% of scale bounds it
    np.testing.assert_allclose(active, ref, atol=_tol(ref, 4e-2))


def test_fisher_pallas_accepts_bf16_apply_mode():
    """The Pallas kernel treats bf16_apply like bf16 for its descriptor
    stream (interpret mode; skipped where this jax lacks the kernel —
    the same pre-existing gap as tests/test_pallas.py)."""
    from keystone_tpu.ops.fisher_pallas import fisher_encode_pallas

    rng = np.random.default_rng(6)
    k, d, t, n = 8, 16, 128, 2
    xs = jnp.asarray(rng.normal(size=(n, t, d)), jnp.float32)
    mask = jnp.ones((n, t), jnp.float32)
    w = jnp.full((k,), 1.0 / k)
    mu = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    var = jnp.ones((k, d), jnp.float32)
    try:
        ref = np.asarray(
            fisher_encode_pallas(xs, mask, w, mu, var, interpret=True, mxu="f32")
        )
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"pallas interpret unavailable here: {e!r}")
    got = np.asarray(
        fisher_encode_pallas(
            xs, mask, w, mu, var, interpret=True, mxu="bf16_apply"
        )
    )
    np.testing.assert_allclose(got, ref, atol=_tol(ref))


def test_lcs_bf16_apply():
    from keystone_tpu.ops.lcs import LCSExtractor

    imgs = (
        np.random.default_rng(7).uniform(0, 1, (2, 40, 40, 3)).astype(np.float32)
    )
    lcs = LCSExtractor(step=5, subpatch_size=4)
    ref, inert, active = _f32_vs_inert_vs_forced(
        lambda: lcs.apply_batch(imgs)[0]
    )
    np.testing.assert_array_equal(inert, ref)
    np.testing.assert_allclose(active, ref, atol=_tol(ref))


def test_sparse_scoring_bf16_apply():
    from keystone_tpu.ops.sparse import PaddedSparseRows, sparse_matmul

    rng = np.random.default_rng(8)
    dense = (rng.random((12, 30)) * (rng.random((12, 30)) > 0.7)).astype(
        np.float32
    )
    sp = PaddedSparseRows.from_dense(dense)
    w = rng.normal(size=(30, 5)).astype(np.float32)
    ref, inert, active = _f32_vs_inert_vs_forced(lambda: sp.matmul(w))
    np.testing.assert_array_equal(inert, ref)
    np.testing.assert_allclose(active, ref, atol=_tol(ref, 4e-2))
    # the bare kernel's default is INERT regardless of policy — the
    # solver gradient paths (logistic / L-BFGS) rely on it
    with precision.matmul("bf16_apply"), precision.force_bf16_apply():
        bare = np.asarray(sparse_matmul(sp.indices, sp.values, jnp.asarray(w)))
    np.testing.assert_array_equal(bare, ref)


def test_block_predict_bf16_apply():
    from keystone_tpu.models import BlockLeastSquaresEstimator

    rng = np.random.default_rng(9)
    x = rng.normal(size=(128, 40)).astype(np.float32)
    w = rng.normal(size=(40, 4)).astype(np.float32)
    lbl = (x @ w).argmax(1)
    y = -np.ones((128, 4), np.float32)
    y[np.arange(128), lbl] = 1.0
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=3, lam=1e-3)
    model = est.fit_arrays(x, y)
    ref, inert, active = _f32_vs_inert_vs_forced(
        lambda: model.apply_batch(jnp.asarray(x))
    )
    np.testing.assert_array_equal(inert, ref)
    np.testing.assert_allclose(active, ref, atol=_tol(ref, 4e-2))
    # scoring precision must not flip predictions on a separated problem
    assert (active.argmax(1) == ref.argmax(1)).all()


def test_bench_forward_inert_on_cpu():
    """Tier-1 gate: the FULL headline forward program (SIFT → PCA → FV →
    normalize → block scoring) is bit-identical on a CPU mesh with the
    policy set — bf16_apply may not perturb any off-chip result."""
    import os
    import sys

    import jax

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench

    imgs = jnp.asarray(
        np.random.default_rng(10).uniform(
            0, 1, (2, bench.IMAGE_HW, bench.IMAGE_HW, 3)
        ),
        jnp.float32,
    )
    with precision.matmul("f32"):
        ref = np.asarray(jax.jit(bench.build_forward())(imgs))
    with precision.matmul("bf16_apply"):
        got = np.asarray(jax.jit(bench.build_forward())(imgs))
    np.testing.assert_array_equal(got, ref)


# ------------------------------------------------------------ solver guard


def test_solver_fit_bit_identical_under_active_policy():
    """Gramians / normal equations / Cholesky never inherit the apply
    cast: fitted weights are bit-identical with bf16_apply ACTIVE."""
    from keystone_tpu.models import BlockWeightedLeastSquaresEstimator

    rng = np.random.default_rng(11)
    x = rng.normal(size=(96, 24)).astype(np.float32)
    lbl = rng.integers(0, 3, size=96)
    y = -np.ones((96, 3), np.float32)
    y[np.arange(96), lbl] = 1.0
    est = BlockWeightedLeastSquaresEstimator(block_size=8, num_iter=2, lam=1e-2)
    with precision.matmul("f32"):
        w32 = np.asarray(est.fit_arrays(x, y).flat_weights)
    with precision.matmul("bf16_apply"), precision.force_bf16_apply():
        w16 = np.asarray(est.fit_arrays(x, y).flat_weights)
    np.testing.assert_array_equal(w16, w32)


# ------------------------------------------------------------- end to end


def test_end_to_end_accuracy_gate_bf16_apply():
    """Train f32, apply in f32 vs active bf16_apply: top-1 must hold on
    the planted-pattern problem (the ISSUE's accuracy gate, CPU-sized)."""
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.ops import Convolver, Pooler, SymmetricRectifier
    from keystone_tpu.workflow import Dataset, Pipeline, transformer

    rng = np.random.default_rng(12)
    n, hw, c, k = 96, 12, 3, 3
    imgs = rng.uniform(0, 1, (n, hw, hw, c)).astype(np.float32)
    lbl = rng.integers(0, k, size=n)
    for i in range(n):  # class-dependent planted pattern
        imgs[i, :4, :4, lbl[i] % c] += 1.5
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lbl] = 1.0
    filt = rng.normal(size=(8, 4, 4, c)).astype(np.float32)

    pipe = (
        Pipeline.of(Convolver(jnp.asarray(filt)))
        .and_then(SymmetricRectifier())
        .and_then(Pooler(3, 3))
        .and_then(transformer(lambda v: v.reshape(-1), name="Flatten"))
        .and_then(
            BlockLeastSquaresEstimator(block_size=32, num_iter=3, lam=1e-3),
            Dataset(imgs),
            Dataset(y),
        )
    )
    with precision.matmul("f32"):
        fitted = pipe.fit()
        acc_f32 = (
            fitted(Dataset(imgs)).get().numpy().argmax(1) == lbl
        ).mean()
    with precision.matmul("bf16_apply"), precision.force_bf16_apply():
        acc_bf16 = (
            fitted(Dataset(imgs)).get().numpy().argmax(1) == lbl
        ).mean()
    assert acc_f32 == 1.0
    assert acc_bf16 >= acc_f32 - 0.02, (acc_f32, acc_bf16)
