"""Loader tests (reference `src/test/scala/loaders/*Suite.scala` — tiny
fixture files exercising each on-disk format, SURVEY.md §4).

Each loader is tested against a hand-built fixture file in the format the
reference consumes, plus the synthetic() constructors used when no
datasets ship with the environment.
"""

import io
import json
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu.loaders import (
    AmazonReviewsDataLoader,
    CifarLoader,
    CsvDataLoader,
    ImageNetLoader,
    LabeledData,
    MnistLoader,
    NewsgroupsDataLoader,
    TimitFeaturesDataLoader,
    VOCLoader,
)
from keystone_tpu.loaders.stream import batched, prefetched
from keystone_tpu.workflow.dataset import Dataset


def _jpeg_bytes(h=32, w=32, color=(255, 0, 0)):
    from PIL import Image as PILImage

    img = PILImage.new("RGB", (w, h), color)
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


# ---------------------------------------------------------------- CSV / MNIST


def test_csv_loader_labelled(tmp_path):
    p = tmp_path / "mnist.csv"
    rows = np.array(
        [[3, 0.5, 1.5, 2.5], [7, 4.0, 5.0, 6.0], [1, -1.0, 0.0, 1.0]],
        np.float32,
    )
    np.savetxt(p, rows, delimiter=",")
    ld = CsvDataLoader.load(str(p), label_col=0)
    assert ld.n == 3
    np.testing.assert_array_equal(ld.labels.numpy(), [3, 7, 1])
    np.testing.assert_allclose(ld.data.numpy(), rows[:, 1:], rtol=1e-6)


def test_csv_loader_unlabeled_and_delimiter(tmp_path):
    p = tmp_path / "data.tsv"
    p.write_text("1.0\t2.0\n3.0\t4.0\n")
    ds = CsvDataLoader.load_unlabeled(str(p), delimiter="\t")
    np.testing.assert_allclose(ds.numpy(), [[1, 2], [3, 4]])


def test_csv_loader_single_row(tmp_path):
    p = tmp_path / "one.csv"
    p.write_text("5,1.0,2.0\n")
    ld = CsvDataLoader.load(str(p))
    assert ld.n == 1 and int(ld.labels.numpy()[0]) == 5


def test_mnist_loader_reads_csv(tmp_path):
    p = tmp_path / "mnist.csv"
    n, d = 4, 784
    rng = np.random.default_rng(0)
    mat = np.concatenate(
        [rng.integers(0, 10, (n, 1)), rng.uniform(0, 255, (n, d))], axis=1
    )
    np.savetxt(p, mat, delimiter=",")
    ld = MnistLoader.load(str(p))
    assert ld.data.numpy().shape == (n, d)
    np.testing.assert_array_equal(ld.labels.numpy(), mat[:, 0].astype(np.int32))


def test_mnist_synthetic_separable_structure():
    tr = MnistLoader.synthetic(n=256, seed=0)
    te = MnistLoader.synthetic(n=128, seed=1)
    assert tr.data.numpy().shape == (256, 784)
    assert te.labels.numpy().min() >= 0 and te.labels.numpy().max() < 10
    # train/test share class prototypes: per-class means should correlate
    xtr, ytr = tr.data.numpy(), tr.labels.numpy()
    xte, yte = te.data.numpy(), te.labels.numpy()
    for c in range(3):
        if (ytr == c).sum() > 4 and (yte == c).sum() > 4:
            mtr = xtr[ytr == c].mean(0)
            mte = xte[yte == c].mean(0)
            r = np.corrcoef(mtr, mte)[0, 1]
            assert r > 0.5


# -------------------------------------------------------------------- CIFAR


def _write_cifar(path, n=6, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n).astype(np.uint8)
    pixels = rng.integers(0, 256, (n, 3, 32, 32)).astype(np.uint8)
    recs = np.concatenate([labels[:, None], pixels.reshape(n, -1)], axis=1)
    recs.tofile(path)
    return labels, pixels


def test_cifar_loader_binary_format(tmp_path):
    p = tmp_path / "data_batch.bin"
    labels, pixels = _write_cifar(str(p))
    ld = CifarLoader.load(str(p))
    np.testing.assert_array_equal(ld.labels.numpy(), labels)
    x = ld.data.numpy()
    assert x.shape == (6, 32, 32, 3)
    # channel-major planes → NHWC, scaled to [0,1]
    np.testing.assert_allclose(
        x, pixels.transpose(0, 2, 3, 1).astype(np.float32) / 255.0, atol=1e-6
    )


def test_cifar_loader_rejects_truncated(tmp_path, monkeypatch):
    from keystone_tpu import native

    # force the pure-python path so its validation is what's under test
    monkeypatch.setattr(native, "read_cifar", lambda path: None)
    p = tmp_path / "bad.bin"
    p.write_bytes(b"\x00" * 100)
    with pytest.raises(ValueError):
        CifarLoader.load(str(p))


# ------------------------------------------------------------------- TIMIT


def test_timit_loader_npy_and_csv(tmp_path):
    n = 5
    feats = np.random.default_rng(0).normal(size=(n, 440)).astype(np.float32)
    labels = np.arange(n, dtype=np.int64)
    fp, lp = tmp_path / "f.npy", tmp_path / "l.npy"
    np.save(fp, feats)
    np.save(lp, labels)
    ld = TimitFeaturesDataLoader.load(str(fp), str(lp))
    np.testing.assert_allclose(ld.data.numpy(), feats, rtol=1e-6)
    np.testing.assert_array_equal(ld.labels.numpy(), labels)

    fc, lc = tmp_path / "f.csv", tmp_path / "l.txt"
    np.savetxt(fc, feats, delimiter=",")
    np.savetxt(lc, labels, fmt="%d")
    ld2 = TimitFeaturesDataLoader.load(str(fc), str(lc))
    np.testing.assert_allclose(ld2.data.numpy(), feats, rtol=1e-5)
    np.testing.assert_array_equal(ld2.labels.numpy(), labels)


# -------------------------------------------------------------- Newsgroups


def test_newsgroups_directory_tree(tmp_path):
    for gi, g in enumerate(["alt.atheism", "sci.space"]):
        d = tmp_path / g
        d.mkdir()
        for k in range(3):
            (d / f"{1000 + k}").write_text(f"post {k} about group {gi}")
    ld = NewsgroupsDataLoader.load(str(tmp_path))
    assert ld.n == 6
    np.testing.assert_array_equal(ld.labels.numpy(), [0, 0, 0, 1, 1, 1])
    assert "post 0" in ld.data.items[0]


def test_newsgroups_explicit_group_order(tmp_path):
    for g in ["b.group", "a.group"]:
        d = tmp_path / g
        d.mkdir()
        (d / "1").write_text(g)
    ld = NewsgroupsDataLoader.load(str(tmp_path), groups=["b.group", "a.group"])
    assert ld.data.items[0] == "b.group"
    assert list(ld.labels.numpy()) == [0, 1]


# ------------------------------------------------------------------ Amazon


def test_amazon_reviews_jsonl(tmp_path):
    p = tmp_path / "reviews.json"
    recs = [
        {"reviewText": "love it", "overall": 5.0},
        {"reviewText": "meh", "overall": 3.0},
        {"text": "alt key", "rating": 4.0},  # alternate field names
    ]
    p.write_text("\n".join(json.dumps(r) for r in recs) + "\n\n")
    ld = AmazonReviewsDataLoader.load(str(p))
    assert ld.n == 3
    np.testing.assert_array_equal(ld.labels.numpy(), [1, 0, 1])
    assert ld.data.items[2] == "alt key"


# ---------------------------------------------------------------- ImageNet


def test_imagenet_tar_labels_and_decode(tmp_path):
    colors = {"n001": (255, 0, 0), "n002": (0, 255, 0)}
    for synset, color in colors.items():
        with tarfile.open(tmp_path / f"{synset}.tar", "w") as tf:
            for k in range(2):
                blob = _jpeg_bytes(16, 16, color)
                info = tarfile.TarInfo(f"{synset}_{k}.JPEG")
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))
    ld = ImageNetLoader.load(str(tmp_path), size=(16, 16))
    assert ld.data.numpy().shape == (4, 16, 16, 3)
    np.testing.assert_array_equal(ld.labels.numpy(), [0, 0, 1, 1])
    # pixels ship as uint8 (device-side PixelScaler does the [0,1] cast)
    x = ld.data.numpy()
    assert x.dtype == np.uint8
    # red synset decodes red-dominant, green synset green-dominant
    assert x[0, ..., 0].mean() > 0.8 * 255 and x[0, ..., 1].mean() < 0.2 * 255
    assert x[2, ..., 1].mean() > 0.8 * 255 and x[2, ..., 0].mean() < 0.2 * 255


def test_imagenet_limit_and_label_map(tmp_path):
    with tarfile.open(tmp_path / "syn.tar", "w") as tf:
        for k in range(5):
            blob = _jpeg_bytes(8, 8)
            info = tarfile.TarInfo(f"img{k}.JPEG")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    ld = ImageNetLoader.load(
        str(tmp_path / "syn.tar"), label_map={"syn": 7}, size=(8, 8), limit=3
    )
    assert ld.n == 3
    assert set(ld.labels.numpy().tolist()) == {7}


def test_imagenet_skips_undecodable_members(tmp_path):
    with tarfile.open(tmp_path / "syn.tar", "w") as tf:
        good = _jpeg_bytes(8, 8)
        for name, blob in [("a.JPEG", good), ("bad.JPEG", b"not a jpeg"), ("c.JPEG", good)]:
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    ld = ImageNetLoader.load(str(tmp_path / "syn.tar"), size=(8, 8))
    assert ld.n == 2


def test_imagenet_synthetic_class_signal():
    ld = ImageNetLoader.synthetic(n=8, num_classes=4, size=(32, 32), seed=0)
    x = ld.data.numpy()
    assert x.shape == (8, 32, 32, 3)
    assert x.dtype == np.uint8


# --------------------------------------------------------------------- VOC


def test_voc_loader_multilabel(tmp_path):
    imgs = tmp_path / "JPEGImages"
    anns = tmp_path / "Annotations"
    imgs.mkdir()
    anns.mkdir()
    (imgs / "000001.jpg").write_bytes(_jpeg_bytes(16, 16))
    (anns / "000001.xml").write_text(
        "<annotation><object><name>dog</name></object>"
        "<object><name>cat</name></object>"
        "<object><name>notaclass</name></object></annotation>"
    )
    # annotation without a matching jpg is skipped
    (anns / "000002.xml").write_text(
        "<annotation><object><name>dog</name></object></annotation>"
    )
    ld = VOCLoader.load(str(imgs), str(anns), size=(16, 16))
    assert ld.n == 1
    y = ld.labels.numpy()[0]
    from keystone_tpu.loaders.voc import VOC_CLASSES

    assert y[VOC_CLASSES.index("dog")] == 1.0
    assert y[VOC_CLASSES.index("cat")] == 1.0
    assert y.sum() == 2.0


def test_voc_synthetic_multilabel():
    ld = VOCLoader.synthetic(n=16, size=(32, 32), seed=0)
    y = ld.labels.numpy()
    assert y.shape == (16, 20)
    assert ((y == 0) | (y == 1)).all()
    assert (y.sum(axis=1) >= 1).all()


# ------------------------------------------------------------- LabeledData


def test_labeled_data_split_deterministic():
    x = np.arange(40, dtype=np.float32).reshape(20, 2)
    y = np.arange(20, dtype=np.int32)
    ld = LabeledData(Dataset(x), Dataset(y))
    a1, b1 = ld.split(0.75, seed=3)
    a2, b2 = ld.split(0.75, seed=3)
    assert a1.n == 15 and b1.n == 5
    np.testing.assert_array_equal(a1.labels.numpy(), a2.labels.numpy())
    # rows stay paired with their labels
    np.testing.assert_array_equal(a1.data.numpy()[:, 0], a1.labels.numpy() * 2)
    # no overlap, full coverage
    assert set(a1.labels.numpy()) | set(b1.labels.numpy()) == set(range(20))


def test_labeled_data_split_host_items():
    texts = [f"doc{i}" for i in range(10)]
    ld = LabeledData(Dataset(texts), Dataset(np.arange(10, dtype=np.int32)))
    a, b = ld.split(0.5, seed=0)
    assert a.n == 5 and b.n == 5
    for t, lab in zip(a.data.items, a.labels.numpy()):
        assert t == f"doc{lab}"


# ------------------------------------------------------------------ stream


def test_prefetched_order_and_transform():
    data = np.arange(64, dtype=np.float32).reshape(16, 4)
    src = prefetched(batched(data, 8), transform=lambda b: b * 2)
    out = np.concatenate([np.asarray(b) for b in src()])
    np.testing.assert_allclose(out, data * 2)


def test_prefetched_reiterable():
    data = np.arange(16, dtype=np.float32).reshape(8, 2)
    src = prefetched(batched(data, 4))
    first = [np.asarray(b) for b in src()]
    second = [np.asarray(b) for b in src()]
    assert len(first) == len(second) == 2
    np.testing.assert_allclose(np.concatenate(first), np.concatenate(second))


def test_prefetched_propagates_worker_error():
    def bad_source():
        yield np.zeros((4, 2), np.float32)
        raise RuntimeError("decode failed")

    # a one-shot iterator is fine here: the error fires on first iteration
    src = prefetched(bad_source())
    with pytest.raises(RuntimeError, match="decode failed"):
        list(src())


def test_stream_dataset_prefetch_param(mesh):
    from keystone_tpu.workflow import StreamDataset

    data = np.arange(64, dtype=np.float32).reshape(16, 4)
    ds = StreamDataset(batched(data, 8), n=16, prefetch=2)
    out = np.concatenate(list(ds.batches()))
    np.testing.assert_allclose(out, data)
    # re-iterable through the prefetch wrapper too
    out2 = np.concatenate(list(ds.batches()))
    np.testing.assert_allclose(out2, data)


def test_stream_app_helpers(mesh):
    """The shared --stream app plumbing: guard, 4-way source selection,
    argparse block."""
    import argparse
    import dataclasses

    from keystone_tpu.loaders.labeled import LabeledData
    from keystone_tpu.loaders.stream import (
        add_stream_args,
        require_stream_test_path,
        resolve_train_source,
        stream_labeled,
    )
    from keystone_tpu.workflow import Dataset, StreamDataset

    @dataclasses.dataclass
    class Cfg:
        train_path: str = None
        test_path: str = None
        stream: bool = False
        stream_batch_size: int = 8

    # guard fires only for stream+train without test
    require_stream_test_path(Cfg())
    require_stream_test_path(Cfg(train_path="x", test_path="y", stream=True))
    with pytest.raises(ValueError, match="test-path"):
        require_stream_test_path(Cfg(train_path="x", stream=True))

    calls = []
    synth = LabeledData(
        Dataset(np.arange(12, dtype=np.float32).reshape(6, 2)),
        Dataset(np.arange(6, dtype=np.int32)),
    )

    def load(p):
        calls.append(("load", p))
        return synth

    def stream(p, batch_size):
        calls.append(("stream", p, batch_size))
        return synth

    out = resolve_train_source(
        Cfg(train_path="t", stream=True), load, stream, lambda: synth
    )
    assert calls[-1] == ("stream", "t", 8) and out is synth
    out = resolve_train_source(Cfg(train_path="t"), load, stream, lambda: synth)
    assert calls[-1] == ("load", "t")
    out = resolve_train_source(Cfg(stream=True), load, stream, lambda: synth)
    assert isinstance(out.data, StreamDataset)  # synthetic-as-stream
    np.testing.assert_allclose(
        np.concatenate(list(out.data.batches())), synth.data.numpy()
    )
    out = resolve_train_source(Cfg(), load, stream, lambda: synth)
    assert out is synth

    p = argparse.ArgumentParser()
    add_stream_args(p, default_batch_size=77, noun="things")
    a = p.parse_args(["--out-of-core"])
    assert a.stream and a.stream_batch_size == 77

    # stream_labeled preserves n and labels
    wrapped = stream_labeled(synth, batch_size=4)
    assert wrapped.data.n == 6 and wrapped.labels is synth.labels
    # item_shape: stream-safe dim derivation
    assert wrapped.data.item_shape == (2,)
    assert synth.data.item_shape == (2,)
