"""End-to-end pipeline integration tests on synthetic data (SURVEY.md §4:
mini pipelines in local mode asserting accuracy above a threshold)."""

import os

import numpy as np
import pytest

from keystone_tpu.pipelines import (
    AmazonReviewsPipeline,
    ImageNetSiftLcsFV,
    KernelCifarPipeline,
    KernelTimitPipeline,
    LinearPixels,
    MnistRandomFFT,
    NewsgroupsPipeline,
    RandomPatchCifar,
    TimitPipeline,
    VOCSIFTFisher,
)


def test_mnist_random_fft_e2e():
    cfg = MnistRandomFFT.Config(num_ffts=2, lam=1e-2, synthetic_n=512)
    result = MnistRandomFFT.run(cfg)
    assert result["accuracy"] > 0.8, result


def test_linear_pixels_e2e():
    cfg = LinearPixels.Config(lam=1e-3, synthetic_n=256)
    result = LinearPixels.run(cfg)
    assert result["accuracy"] > 0.8, result


def test_random_patch_cifar_e2e():
    cfg = RandomPatchCifar.Config(
        num_filters=64,
        patches_per_image=4,
        block_size=256,
        num_iter=2,
        synthetic_n=192,
    )
    result = RandomPatchCifar.run(cfg)
    assert result["accuracy"] > 0.6, result


def test_newsgroups_nb_e2e():
    cfg = NewsgroupsPipeline.Config(
        num_features=2000, head="nb", num_classes=4, synthetic_n=300
    )
    result = NewsgroupsPipeline.run(cfg)
    assert result["accuracy"] > 0.9, result


def test_newsgroups_ls_e2e():
    cfg = NewsgroupsPipeline.Config(
        num_features=2000, head="ls", num_classes=4, synthetic_n=300
    )
    result = NewsgroupsPipeline.run(cfg)
    assert result["accuracy"] > 0.9, result


def test_timit_e2e():
    cfg = TimitPipeline.Config(
        num_cosine_features=1024,
        cosine_block_size=512,
        num_epochs=2,
        num_classes=20,
        synthetic_n=1024,
        lam=1e-4,
        gamma=0.02,
    )
    result = TimitPipeline.run(cfg)
    assert result["accuracy"] > 0.5, result


def test_kernel_timit_e2e():
    """The Nyström kernel variant (ISSUE 13) learns the same synthetic
    TIMIT task the random-feature variant does, and its out-of-core
    stream path reproduces the in-core metrics exactly (landmark draw
    and solver route are stream-invariant)."""
    cfg = KernelTimitPipeline.Config(
        num_landmarks=96,
        solver_block_size=96,
        num_epochs=2,
        num_classes=8,
        synthetic_n=512,
    )
    result = KernelTimitPipeline.run(cfg)
    assert result["accuracy"] > 0.5, result
    streamed = KernelTimitPipeline.run(
        KernelTimitPipeline.Config(
            num_landmarks=96,
            solver_block_size=96,
            num_epochs=2,
            num_classes=8,
            synthetic_n=512,
            stream=True,
            stream_batch_size=128,
        )
    )
    assert streamed["accuracy"] == result["accuracy"], (streamed, result)


def test_kernel_cifar_e2e():
    cfg = KernelCifarPipeline.Config(
        num_landmarks=64,
        solver_block_size=64,
        num_epochs=2,
        synthetic_n=256,
    )
    result = KernelCifarPipeline.run(cfg)
    assert result["accuracy"] > 0.5, result


def test_imagenet_sift_lcs_fv_e2e():
    cfg = ImageNetSiftLcsFV.Config(
        num_classes=4,
        gmm_k=4,
        gmm_iters=4,
        pca_dims=16,
        descriptor_samples_per_image=32,
        solver_block_size=512,
        synthetic_n=48,
        image_size=48,
        sift_step=8,
        lcs_step=8,
    )
    result = ImageNetSiftLcsFV.run(cfg)
    assert result["top5_error"] <= result["top1_error"] + 1e-9, result
    assert result["accuracy"] > 0.5, result


def test_imagenet_augmented_view_eval():
    """The reference's 10-view test path: CenterCornerPatcher views,
    scores averaged per image id (AugmentedExamplesEvaluator) before
    classification (SURVEY §3.4)."""
    cfg = ImageNetSiftLcsFV.Config(
        num_classes=4,
        gmm_k=4,
        gmm_iters=4,
        pca_dims=16,
        descriptor_samples_per_image=32,
        solver_block_size=512,
        synthetic_n=40,  # → 10 test images: non-divisible on the 4-wide
        image_size=48,   # data axis, exercising the padded-rows crop
        sift_step=8,
        lcs_step=8,
        augmented_eval=True,
    )
    result = ImageNetSiftLcsFV.run(cfg)
    assert 0.0 <= result["top5_error"] <= result["top1_error"] + 1e-9, result
    assert result["accuracy"] > 0.5, result


def test_voc_sift_fisher_e2e():
    cfg = VOCSIFTFisher.Config(
        gmm_k=4,
        gmm_iters=4,
        pca_dims=16,
        descriptor_samples_per_image=32,
        solver_block_size=512,
        synthetic_n=36,
        image_size=48,
        sift_step=8,
    )
    result = VOCSIFTFisher.run(cfg)
    assert result["mean_ap"] > 0.2, result


def test_amazon_reviews_e2e():
    cfg = AmazonReviewsPipeline.Config(num_features=4096, synthetic_n=400)
    result = AmazonReviewsPipeline.run(cfg)
    assert result["accuracy"] > 0.9, result


def test_cli_list(capsys):
    from keystone_tpu.cli import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "MnistRandomFFT" in out and "ImageNetSiftLcsFV" in out


@pytest.mark.parametrize(
    "app_cfg",
    [
        lambda mp: (MnistRandomFFT, MnistRandomFFT.Config(
            num_ffts=2, synthetic_n=256, model_path=mp)),
        lambda mp: (LinearPixels, LinearPixels.Config(
            synthetic_n=256, model_path=mp)),
        lambda mp: (TimitPipeline, TimitPipeline.Config(
            synthetic_n=256, num_classes=8, num_cosine_features=512,
            model_path=mp)),
        lambda mp: (AmazonReviewsPipeline, AmazonReviewsPipeline.Config(
            synthetic_n=200, model_path=mp)),
        lambda mp: (NewsgroupsPipeline, NewsgroupsPipeline.Config(
            synthetic_n=160, num_classes=3, model_path=mp)),
        lambda mp: (RandomPatchCifar, RandomPatchCifar.Config(
            synthetic_n=128, num_filters=32, block_size=256, model_path=mp)),
        lambda mp: (VOCSIFTFisher, VOCSIFTFisher.Config(
            synthetic_n=24, gmm_k=4, gmm_iters=3, pca_dims=8,
            descriptor_samples_per_image=16, solver_block_size=128,
            image_size=48, model_path=mp)),
        lambda mp: (KernelTimitPipeline, KernelTimitPipeline.Config(
            synthetic_n=256, num_classes=8, num_landmarks=64,
            solver_block_size=64, num_epochs=1, model_path=mp)),
        lambda mp: (KernelCifarPipeline, KernelCifarPipeline.Config(
            synthetic_n=96, num_landmarks=48, solver_block_size=48,
            num_epochs=1, model_path=mp)),
    ],
)
def test_model_path_roundtrip_across_apps(app_cfg, tmp_path):
    """Every converted app: fit+save, then load-not-refit with equal
    metrics (compared generically — apps report different metric keys)."""
    app, cfg = app_cfg(str(tmp_path / "model.pkl"))
    r1 = app.run(cfg)
    assert r1["model_loaded"] is False
    r2 = app.run(cfg)
    assert r2["model_loaded"] is True
    skip = ("fit_seconds", "model_loaded")
    assert {k: v for k, v in r2.items() if k not in skip} == {
        k: v for k, v in r1.items() if k not in skip
    }


def test_mnist_model_path_roundtrip(tmp_path):
    """--model-path: first run fits and saves; second run loads the
    fitted pipeline and only scores; a changed config refuses to reuse
    the stale model instead of silently reporting its metrics."""
    mp = str(tmp_path / "mnist-model.pkl")
    cfg = MnistRandomFFT.Config(num_ffts=2, synthetic_n=256, model_path=mp)
    r1 = MnistRandomFFT.run(cfg)
    assert os.path.exists(mp) and r1["model_loaded"] is False
    r2 = MnistRandomFFT.run(cfg)
    assert r2["model_loaded"] is True  # load, not refit
    assert r2["accuracy"] == r1["accuracy"]
    stale = MnistRandomFFT.Config(num_ffts=4, synthetic_n=256, model_path=mp)
    with pytest.raises(ValueError, match="different\n?.*config|different config"):
        MnistRandomFFT.run(stale)
