"""Self-healing serving fleet (ISSUE 10): replica supervision (dead/
wedged worker restart + quarantine), batch-failure bisection with
poison-request quarantine, hedged dispatch, fleet-unavailable
fail-fast, registry-watcher backoff, and the chaos soak.

All tier-1 except the long soak (slow): conftest forces 8 host-platform
devices, so multi-replica pools run in-process on CPU.
"""

import math
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import faults
from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.obs import metrics
from keystone_tpu.ops.stats import NormalizeRows
from keystone_tpu.serve import (
    FleetUnavailable,
    PoisonRequest,
    serve,
    serve_http,
)
from keystone_tpu.utils import guard
from keystone_tpu.workflow import Pipeline
from keystone_tpu.workflow.transformer import Transformer

pytestmark = pytest.mark.serve

DIM = 6
MARK = np.float32(123.0)


class PoisonGate(Transformer):
    """Host stage that raises when a row's first element is the marker —
    a deterministic, content-attributable (request-shaped) failure the
    bisection machinery must isolate.  Host-side (sequential) so the
    error raises cleanly on the flush thread, outside any XLA program."""

    is_host = True
    parallel_host = False

    def params(self):
        return ()

    def apply_one(self, x):
        x = np.asarray(x)
        if x[0] == MARK:
            raise ValueError("poison marker row")
        return x


def _pipeline(scale: float = 2.0, poison_gate: bool = True) -> Pipeline:
    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * scale)
    head = Pipeline.of(PoisonGate()) if poison_gate else Pipeline.of(NormalizeRows())
    if poison_gate:
        return head | NormalizeRows() | LinearMapper(w)
    return head | LinearMapper(w)


def _poison_row() -> np.ndarray:
    row = np.ones(DIM, np.float32)
    row[0] = MARK
    return row


def _rows(k: int, seed: int = 0) -> np.ndarray:
    return (
        np.random.default_rng(seed).normal(size=(k, DIM)).astype(np.float32)
    )


def _counter(name: str) -> float:
    return metrics.REGISTRY.counter_total(name)


# ---------------------------------------------------------------- units
def test_heartbeat_renewal_and_expiry():
    hb = guard.Heartbeat(0.1)
    assert not hb.expired()
    time.sleep(0.15)
    assert hb.expired()
    hb.beat()
    assert not hb.expired()
    assert hb.remaining() > 0.0


def test_breaker_seconds_until_probe():
    clock = [0.0]
    b = guard.CircuitBreaker("selfheal.probe", threshold=1, reset_timeout=10.0, clock=lambda: clock[0])
    assert b.seconds_until_probe() == 0.0
    b.record_failure()
    assert b.state() == "open"
    assert b.seconds_until_probe() == pytest.approx(10.0)
    clock[0] = 4.0
    assert b.seconds_until_probe() == pytest.approx(6.0)
    clock[0] = 10.0
    assert b.state() == "half_open"
    assert b.seconds_until_probe() == 0.0


def test_fault_plan_ctx_match_grammar():
    """``ctx.<key>=<value>`` clauses restrict a spec to matching site
    contexts, and non-matching calls do not advance its triggers."""
    plan = faults.parse_plan("serve.replica:ctx.replica=1:raise:times=2")
    (spec,) = plan.specs
    assert spec.match == {"replica": "1"}
    assert spec.matches({"replica": 1})
    assert not spec.matches({"replica": 0})
    with faults.inject("serve.replica:ctx.replica=1:raise:times=1") as p:
        faults.fault_point("serve.replica", replica=0)  # no match, no count
        assert p.specs[0].calls == 0
        with pytest.raises(faults.FaultInjected):
            faults.fault_point("serve.replica", replica=1)
    with pytest.raises(faults.FaultPlanError):
        faults.parse_plan("serve.replica:ctx.replica=")
    assert "serve.worker" in faults.SITES


# ------------------------------------------------------------ bisection
def test_bisection_isolates_poison_innocents_complete():
    """One poison rider in a full batch: bisection fails IT alone
    (typed), every innocent completes with the right value, and the
    quarantine cache short-circuits the same content at admission."""
    svc = serve(
        _pipeline(),
        max_batch=8,
        max_wait_ms=40.0,
        queue_bound=64,
        example=np.zeros(DIM, np.float32),
        name="selfheal_bisect",
        supervise=False,
    )
    try:
        x = _rows(7, seed=1)
        b0 = _counter("serve.bisections")
        futs = svc.submit_many(list(x) + [_poison_row()])
        excs = [f.exception(timeout=60) for f in futs]
        assert excs[:7] == [None] * 7, excs
        assert isinstance(excs[7], PoisonRequest), excs[7]
        # innocents got REAL results (norm == 2 fingerprint)
        for f in futs[:7]:
            assert np.linalg.norm(np.asarray(f.result())) == pytest.approx(
                2.0, rel=1e-4
            )
        assert _counter("serve.bisections") == b0 + 1
        # the same content is refused at admission now — no device time
        pb0 = _counter("serve.poison_blocked")
        with pytest.raises(PoisonRequest):
            svc.submit(_poison_row())
        assert _counter("serve.poison_blocked") == pb0 + 1
    finally:
        svc.close()


def test_bisection_infra_errors_are_not_bisected():
    """An OSError-family flush failure (injected fault) fails the whole
    batch exactly as before — bisection only fires on content-shaped
    errors."""
    svc = serve(
        _pipeline(),
        max_batch=4,
        max_wait_ms=20.0,
        queue_bound=64,
        example=np.zeros(DIM, np.float32),
        name="selfheal_infra",
        supervise=False,
    )
    try:
        b0 = _counter("serve.bisections")
        with faults.inject("serve.batch:raise:times=1"):
            futs = svc.submit_many(_rows(4, seed=2))
            errs = [f.exception(timeout=30) for f in futs]
        assert all(isinstance(e, faults.FaultInjected) for e in errs), errs
        assert _counter("serve.bisections") == b0
    finally:
        svc.close()


def test_poison_http_422_and_pinned_trace():
    """HTTP contract: a poison request answers 422 (not 500) with its
    request id, and its trace is pinned with outcome ``poison``."""
    svc = serve(
        _pipeline(),
        max_batch=4,
        max_wait_ms=5.0,
        queue_bound=64,
        example=np.zeros(DIM, np.float32),
        name="selfheal_http",
        supervise=False,
    )
    front = serve_http(svc, port=0)
    try:
        import json
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        url = f"http://127.0.0.1:{front.port}"
        body = json.dumps({"instance": _poison_row().tolist()}).encode()
        req = Request(
            url + "/predict",
            data=body,
            headers={"X-Request-Id": "poison-1"},
            method="POST",
        )
        with pytest.raises(HTTPError) as ei:
            urlopen(req, timeout=60)
        assert ei.value.code == 422
        payload = json.loads(ei.value.read())
        assert payload["request_id"] == "poison-1"
        assert "poison" in payload["error"]
        # the trace is pinned and resolvable with the poison outcome
        trace = json.loads(
            urlopen(url + "/requestz/poison-1", timeout=30).read()
        )
        assert trace["outcome"] == "poison"
        assert "poison-1" in [
            t["request_id"]
            for t in svc.recorder.tracez(filter="poison", limit=50)
        ]
        # an innocent request still answers 200
        ok = json.loads(
            urlopen(
                Request(
                    url + "/predict",
                    data=json.dumps(
                        {"instance": _rows(1, seed=3)[0].tolist()}
                    ).encode(),
                    method="POST",
                ),
                timeout=60,
            ).read()
        )
        assert "predictions" in ok
    finally:
        front.stop()
        svc.close()


# ----------------------------------------------------------- supervisor
def test_acceptance_crash_plus_poison_chaos():
    """The ISSUE-10 chaos acceptance scenario: a seeded plan crashes one
    replica worker mid-load while one poison request rides a full batch.
    The supervisor restarts the crashed replica (visible in /statusz
    and as a recorder ops span), bisection isolates the poison within
    <= ceil(log2(max_batch)) halving levels, every innocent co-batched
    rider completes, and ZERO futures are lost."""
    max_batch = 8
    svc = serve(
        _pipeline(),
        max_batch=max_batch,
        max_wait_ms=30.0,
        queue_bound=512,
        example=np.zeros(DIM, np.float32),
        name="selfheal_accept",
        replicas=2,
        supervise_interval_s=0.1,
    )
    try:
        r0 = _counter("serve.replica_restarts")
        futs = []
        with faults.inject("serve.worker:raise:after=2:times=1"):
            for wave in range(3):
                batch = list(_rows(max_batch - 1, seed=wave))
                if wave == 1:
                    # the poison rides co-batched with innocents
                    batch.append(_poison_row())
                futs.extend(svc.submit_many(batch))
                time.sleep(0.05)
            excs = [f.exception(timeout=120) for f in futs]
        # zero futures lost: every single one resolved...
        assert all(f.done() for f in futs)
        poisons = [e for e in excs if isinstance(e, PoisonRequest)]
        others = [
            e for e in excs if e is not None and not isinstance(e, PoisonRequest)
        ]
        # ...the poison alone failed (typed), every innocent completed
        assert len(poisons) == 1, excs
        assert others == [], others
        # the supervisor restarted the crashed replica, visibly
        assert _counter("serve.replica_restarts") >= r0 + 1
        status = svc.status()  # what GET /statusz serves
        assert status["supervisor"]["restarts"] >= 1
        assert status["supervisor"]["last_restart"]["reason"] == "dead"
        assert any(s["restarts"] > 0 for s in status["replicas"])
        # the aggregate bisect/restart ops spans are emitted on the
        # worker thread AFTER future delivery — poll briefly rather
        # than race them (per-REQUEST traces finalize before delivery;
        # the ops ring is the aggregate view)
        deadline = time.monotonic() + 10.0
        restarts = bisects = []
        while (not restarts or not bisects) and time.monotonic() < deadline:
            ops = svc.recorder.ops_spans(limit=50)
            restarts = [o for o in ops if o["name"] == "replica.restart"]
            bisects = [o for o in ops if o["name"] == "serve.bisect"]
            if not restarts or not bisects:
                time.sleep(0.05)
        assert restarts and restarts[0]["reason"] == "dead"
        # bisection bound: depth <= ceil(log2(max_batch))
        assert bisects, svc.recorder.ops_spans(limit=50)
        assert bisects[0]["depth"] <= math.ceil(math.log2(max_batch))
    finally:
        svc.close()


def test_wedged_worker_restarted_queued_work_survives():
    """A wedged worker (stall injected in the worker loop, heartbeat
    expired with a flush in hand) is swapped out: its QUEUED flushes
    transfer to the replacement and complete; the in-hand flush's
    riders fail typed (callers unblock) instead of hanging."""
    svc = serve(
        _pipeline(poison_gate=False),
        max_batch=2,
        max_wait_ms=2.0,
        queue_bound=64,
        example=np.zeros(DIM, np.float32),
        name="selfheal_wedge",
        replicas=1,
        heartbeat_s=0.3,
        supervise_interval_s=0.1,
    )
    try:
        x = _rows(2, seed=5)
        with faults.inject("serve.worker:delay=1.0:times=1"):
            stuck = svc.submit_many(x)  # first flush: wedges the worker
            time.sleep(0.1)
            queued = svc.submit_many(x)  # second flush: queued behind it
            # the supervisor declares the wedge and heals
            errs = [f.exception(timeout=30) for f in stuck]
            assert all(isinstance(e, FleetUnavailable) for e in errs), errs
            got = [f.result(timeout=30) for f in queued]
        assert len(got) == 2
        st = svc.status()
        assert st["supervisor"]["restarts"] >= 1
        assert st["supervisor"]["last_restart"]["reason"] == "wedged"
    finally:
        svc.close()


def test_quarantine_after_restart_budget_and_swap_readmits():
    """Restart budget exhausted -> the slot is quarantined (gauge set,
    recorder ops span); with every replica quarantined the fleet fails
    fast: submit raises typed, /healthz answers 503 with Retry-After,
    and a blue/green swap() re-admits traffic."""
    import json
    from urllib.error import HTTPError
    from urllib.request import urlopen

    svc = serve(
        _pipeline(poison_gate=False),
        max_batch=4,
        max_wait_ms=2.0,
        queue_bound=64,
        example=np.zeros(DIM, np.float32),
        name="selfheal_quar",
        replicas=1,
        restart_limit=1,
        restart_window_s=60.0,
        supervise_interval_s=0.1,
    )
    front = serve_http(svc, port=0)
    try:
        url = f"http://127.0.0.1:{front.port}"
        x = _rows(2, seed=6)
        q0 = _counter("serve.replica_restarts")
        with faults.inject("serve.worker:raise:times=2"):
            # first crash -> restart (budget spent); second -> quarantine
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    for f in svc.submit_many(x):
                        f.exception(timeout=15)
                except Exception:
                    pass  # refusals while crashing/healing are expected
                if svc._pool.replicas[0].quarantined:
                    break
                time.sleep(0.05)
        assert svc._pool.replicas[0].quarantined, svc.replica_statuses()
        assert _counter("serve.replica_restarts") >= q0 + 1
        assert (
            metrics.REGISTRY.gauge_value("serve.quarantined", replica=0) == 1.0
        )
        assert any(
            o["name"] == "replica.quarantine"
            for o in svc.recorder.ops_spans(limit=50)
        )
        # the whole fleet is down: typed refusal + non-200 healthz
        assert svc.available is False
        with pytest.raises(FleetUnavailable):
            svc.submit_many(x)
        with pytest.raises(HTTPError) as ei:
            urlopen(url + "/healthz", timeout=30)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
        assert json.loads(ei.value.read())["status"] == "unavailable"
        # a blue/green swap is the quarantine reset: traffic flows again
        svc.swap(_pipeline(3.0, poison_gate=False), version="healed")
        assert svc.available is True
        got = [f.result(timeout=30) for f in svc.submit_many(x)]
        assert np.linalg.norm(np.asarray(got[0])) == pytest.approx(
            3.0, rel=1e-4
        )
        health = json.loads(urlopen(url + "/healthz", timeout=30).read())
        assert health["status"] == "ok"
    finally:
        front.stop()
        svc.close()


# -------------------------------------------------------------- hedging
def test_hedge_rescues_straggler_single_resolution():
    """A straggling worker's queued flush is hedged onto the healthy
    replica and completes fast; every rider resolves EXACTLY once (the
    loser pop is a claim-skip), the loser reaches the recorder as
    ``cancelled`` (not error), and the loser replica's breaker is
    charged neutrally."""
    svc = serve(
        _pipeline(poison_gate=False),
        max_batch=4,
        max_wait_ms=2.0,
        queue_bound=256,
        example=np.zeros(DIM, np.float32),
        name="selfheal_hedge",
        replicas=2,
        hedge_ms=20.0,
        supervise=False,
    )
    try:
        h0 = _counter("serve.hedges")
        c0 = _counter("serve.hedge_cancelled")
        x = _rows(4, seed=7)
        with faults.inject("serve.worker:ctx.replica=0:delay=0.3"):
            futs = []
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.6:
                futs.extend(svc.submit_many(x))
                time.sleep(0.01)
            got = [f.result(timeout=60) for f in futs]
        assert len(got) == len(futs)
        assert _counter("serve.hedges") > h0
        # every fired hedge eventually resolves its LOSER copy as a
        # cancelled claim-skip once the stalled worker pops it late
        deadline = time.monotonic() + 15.0
        while (
            _counter("serve.hedge_cancelled") <= c0
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert _counter("serve.hedge_cancelled") > c0
        losers = [
            o
            for o in svc.recorder.ops_spans(limit=100)
            if o["name"] == "serve.hedge" and o.get("outcome") == "cancelled"
        ]
        assert losers, svc.recorder.ops_spans(limit=20)
        # loser pops charged NEUTRALLY: no replica accumulated errors
        # and every breaker stayed closed throughout
        statuses = svc.replica_statuses()
        assert sum(s["errors"] for s in statuses) == 0, statuses
        assert all(s["breaker"] == "closed" for s in statuses), statuses
    finally:
        svc.close()


def test_hedging_disabled_is_pr9_dispatch_path():
    """hedge_ms=None (the default): no hedge monitor thread exists, no
    hedge metric moves, and the dispatch path serves identically to the
    PR-9 fleet — the opt-out really is the old path."""
    before_threads = {t.name for t in threading.enumerate()}
    svc = serve(
        _pipeline(poison_gate=False),
        max_batch=4,
        max_wait_ms=2.0,
        queue_bound=64,
        example=np.zeros(DIM, np.float32),
        name="selfheal_nohedge",
        replicas=2,
        supervise=False,
    )
    try:
        assert svc._hedge is None
        assert not any(
            "selfheal_nohedge-hedge" in t.name for t in threading.enumerate()
        )
        h0 = _counter("serve.hedges")
        x = _rows(4, seed=8)
        ref = None
        for _ in range(4):
            got = np.stack(
                [f.result(timeout=30) for f in svc.submit_many(x)]
            )
            if ref is None:
                ref = got
            np.testing.assert_array_equal(got, ref)
        assert _counter("serve.hedges") == h0
    finally:
        svc.close()
    # no thread leaked relative to the baseline set
    leaked = {
        t.name
        for t in threading.enumerate()
        if "hedge" in t.name and t.name not in before_threads
    }
    assert not leaked, leaked


# ------------------------------------------------------ watcher backoff
class _FlakyRegistry:
    """current() raises until told otherwise — the backoff driver."""

    def __init__(self):
        self.fail = True
        self.polls = 0

    def current(self, strict=False):
        self.polls += 1
        if self.fail:
            raise OSError("registry storage down")
        return None  # healthy, nothing new


def test_watcher_backs_off_on_consecutive_errors():
    from keystone_tpu.serve.registry import RegistryWatcher

    class _Svc:
        version = "v0"
        recorder = None

    reg = _FlakyRegistry()
    w = RegistryWatcher(_Svc(), reg, poll_seconds=0.1, max_backoff_seconds=2.0)
    # unit: the wait schedule grows exponentially, jittered, capped
    assert w.next_wait() == pytest.approx(0.1)
    w._consecutive_errors = 1
    w1 = w.next_wait()
    assert 0.1 <= w1 <= 0.3
    w._consecutive_errors = 3
    w3 = w.next_wait()
    assert 0.4 <= w3 <= 1.2
    w._consecutive_errors = 30
    assert w.next_wait() <= 2.0  # capped
    assert metrics.REGISTRY.gauge_value("serve.watch_backoff_seconds") > 0.0
    w._consecutive_errors = 0
    assert w.next_wait() == pytest.approx(0.1)
    assert metrics.REGISTRY.gauge_value("serve.watch_backoff_seconds") == 0.0
    # integration: errors accumulate consecutively, a success resets
    e0 = _counter("serve.watch_errors")
    w = RegistryWatcher(_Svc(), reg, poll_seconds=0.02, max_backoff_seconds=0.2)
    w.start()
    try:
        deadline = time.monotonic() + 10.0
        while w._consecutive_errors < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert w._consecutive_errors >= 3
        assert _counter("serve.watch_errors") >= e0 + 3
        reg.fail = False
        deadline = time.monotonic() + 10.0
        while w._consecutive_errors != 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert w._consecutive_errors == 0
    finally:
        w.stop()


def test_watcher_strict_current_counts_corrupt_pointer(tmp_path):
    """A corrupt CURRENT pointer is a poll ERROR for the watcher (it
    backs off) while the plain deploy path still treats it as no-news."""
    from keystone_tpu.serve.registry import ModelRegistry

    reg = ModelRegistry(str(tmp_path))
    reg.publish(_pipeline(poison_gate=False))
    # damage CURRENT in place: checksum sidecar no longer matches
    with open(reg._current_path(), "r+b") as f:
        f.seek(0)
        f.write(b"vXXXX")
    assert reg.current() is None  # lenient: no news
    with pytest.raises(Exception):
        reg.current(strict=True)  # watcher mode: a real error


# ----------------------------------------------------------------- soak
@pytest.mark.soak
@pytest.mark.chaos
def test_soak_short_deterministic():
    """The tier-1 soak gate: a short seeded randomized multi-site chaos
    loop against a live 2-replica fleet — zero hung/lost futures and a
    fleet that still serves a clean wave afterwards."""
    from tools.chaos import run_soak

    report = run_soak(seconds=1.2, seed=0, replicas=2, wave=16)
    assert report["hung"] == 0, report
    assert report["healthy_after_soak"], report
    assert report["ok"], report
    assert report["iterations"] >= 1


@pytest.mark.soak
@pytest.mark.chaos
@pytest.mark.slow
def test_soak_long():
    """The tier-2 soak: a longer randomized window, same invariants."""
    from tools.chaos import run_soak

    report = run_soak(seconds=20.0, seed=1, replicas=2, wave=48)
    assert report["hung"] == 0, report
    assert report["healthy_after_soak"], report
    assert report["ok"], report
