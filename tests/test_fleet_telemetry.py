"""Fleet-wide distributed tracing & metrics (ISSUE 18) — unit tier.

What this file pins:

- :class:`ClockSync`: NTP four-timestamp math (offset recovered under
  a known skew), minimum-delay sample selection with decay, rejection
  of negative-delay samples (a retransmit answered by an earlier
  send's reply);
- :func:`clamp_span`: skew tolerance — however wrong the offset
  estimate, aligned spans stay inside the router's observed
  ``[t_send, t_recv]`` window, stay ordered, never have negative
  duration;
- :class:`WorkerTelemetry`: bounded span ring (dropped-not-queued),
  ``ship()`` body shape, metric-delta export with throttle and
  ship-only-what-fits baseline advance;
- the delta wire format roundtrip: worker ``metrics_entries()`` →
  router ``merge_entries(..., worker=, host=)`` reproduces counters /
  gauges / histograms under fleet labels, and a second delta merges
  only the change;
- :class:`FleetTelemetry`: stitches worker spans + wire accounting
  into the FlightRecorder batch record that ``/requestz`` joins;
  old-peer shipments (absent/garbage ``telemetry``) are no-ops, never
  errors; ``fleet_status()`` has the ``/statusz`` block shape.

Cross-process e2e lives with each transport's suite
(tests/test_procfleet.py, tests/test_netfleet.py); the wire-level
frame pins (no ``trace`` key recorder-off) live in
tests/test_netfleet.py next to the _FakeWorker scripting.
"""

import pytest

from keystone_tpu.obs import metrics
from keystone_tpu.obs.recorder import FlightRecorder
from keystone_tpu.serve.telemetry import (
    ClockSync,
    FleetTelemetry,
    WorkerTelemetry,
    clamp_span,
)

pytestmark = [pytest.mark.serve, pytest.mark.obs]


# --------------------------------------------------------------- ClockSync


SKEW = 5.0  # worker_clock - router_clock in every synthetic exchange


def _exchange(sync, t_send, wire_s, compute_s, skew=SKEW):
    """One synthetic four-timestamp exchange with symmetric wire time."""
    t_rx = t_send + wire_s / 2.0 + skew
    t_tx = t_rx + compute_s
    t_recv = t_send + wire_s + compute_s
    return sync.observe(t_send, t_recv, t_rx, t_tx)


def test_clock_sync_recovers_known_skew():
    sync = ClockSync()
    delay = _exchange(sync, 10.0, wire_s=0.004, compute_s=0.002)
    assert delay == pytest.approx(0.004)
    assert sync.offset == pytest.approx(SKEW)
    assert sync.to_router(100.0 + SKEW) == pytest.approx(100.0)


def test_clock_sync_min_delay_sample_wins():
    """A slower exchange carries a worse offset bound — it must not
    displace the best sample even when its (asymmetric) offset
    estimate differs."""
    sync = ClockSync()
    _exchange(sync, 10.0, wire_s=0.002, compute_s=0.001)
    best = sync.offset
    # asymmetric slow sample: all the wire time on the send leg, so
    # its naive offset estimate is off by ~wire/2
    sync.observe(20.0, 20.102, 20.1 + SKEW, 20.101 + SKEW)
    assert sync.offset == best  # kept the tight sample
    assert sync.samples == 2


def test_clock_sync_rejects_negative_delay():
    """The reply to an EARLIER retransmitted send can pair with a later
    t_send, making measured delay negative — unusable, rejected."""
    sync = ClockSync()
    assert sync.observe(10.0, 10.001, 15.0, 15.005) is None
    assert sync.offset is None and sync.samples == 0


def test_clock_sync_decay_readmits_samples_after_drift():
    """The kept delay bound grows per rejected sample, so a drifted
    clock re-syncs instead of trusting one ancient lucky sample."""
    sync = ClockSync()
    _exchange(sync, 0.0, wire_s=0.001, compute_s=0.0)
    first_best = sync.best_delay
    for i in range(200):
        _exchange(sync, float(i + 1), wire_s=0.0015, compute_s=0.0)
    assert sync.best_delay > first_best  # decayed upward...
    # ...far enough that a typical sample finally won and refreshed
    # the offset (the 0.0015 samples carry the same SKEW, so the
    # offset stays correct either way)
    assert sync.offset == pytest.approx(SKEW, abs=1e-3)


# --------------------------------------------------------------- clamp_span


def test_clamp_span_bounds_order_and_duration():
    sync = ClockSync()
    _exchange(sync, 10.0, wire_s=0.004, compute_s=0.010)
    t_send, t_recv = 10.0, 10.014
    # a worker span genuinely inside the window aligns inside it
    r0, r1 = clamp_span(sync, 10.003 + SKEW, 10.011 + SKEW, t_send, t_recv)
    assert t_send <= r0 <= r1 <= t_recv
    assert (r1 - r0) == pytest.approx(0.008, abs=1e-6)


def test_clamp_span_tolerates_wildly_wrong_offset():
    """Force a badly wrong offset: the aligned span must still land
    inside [t_send, t_recv], ordered, with non-negative duration."""
    sync = ClockSync()
    sync.observe(0.0, 0.001, 1000.0, 1000.001)  # offset ~ +1000, "valid"
    t_send, t_recv = 50.0, 50.01
    r0, r1 = clamp_span(sync, 50.001, 50.009, t_send, t_recv)  # true skew 0
    assert t_send <= r0 <= r1 <= t_recv


def test_clamp_span_without_sync_preserves_duration():
    sync = ClockSync()
    t_send, t_recv = 5.0, 5.5
    r0, r1 = clamp_span(sync, 99.0, 99.2, t_send, t_recv)
    assert r0 == t_send and (r1 - r0) == pytest.approx(0.2)
    # duration longer than the window clamps to the window
    r0, r1 = clamp_span(sync, 99.0, 100.0, t_send, t_recv)
    assert (r0, r1) == (t_send, t_recv)


# --------------------------------------------------------- WorkerTelemetry


def test_worker_spans_drop_oldest_never_queue():
    tel = WorkerTelemetry(registry=metrics.MetricsRegistry(), max_spans=4)
    for i in range(10):
        tel.add_span(f"s{i}", float(i), float(i) + 0.5)
    blob = tel.ship(t_rx=1.0)
    assert [s["name"] for s in blob["spans"]] == ["s6", "s7", "s8", "s9"]
    assert blob["t_rx"] == 1.0 and "t_tx" in blob
    # drained: the next ship carries no spans key at all
    assert "spans" not in tel.ship()


def test_worker_span_recorded_even_when_block_raises():
    tel = WorkerTelemetry(registry=metrics.MetricsRegistry())
    with pytest.raises(RuntimeError):
        with tel.span("worker.apply", n=3):
            raise RuntimeError("boom")
    (sp,) = tel.ship()["spans"]
    assert sp["name"] == "worker.apply" and sp["attrs"] == {"n": 3}
    assert sp["t1"] >= sp["t0"]


def test_metrics_delta_roundtrip_under_fleet_labels():
    wreg = metrics.MetricsRegistry()
    rreg = metrics.MetricsRegistry()
    tel = WorkerTelemetry(registry=wreg)
    wreg.inc("serve.applies", 3.0)
    wreg.set_gauge("serve.occupancy", 0.5, replica=0)
    wreg.observe("serve.apply_seconds", 0.004)
    entries = tel.metrics_entries(min_interval_s=0.0)
    assert entries
    merged = rreg.merge_entries(entries, worker="w0", host="hA")
    assert merged == len(entries)
    assert rreg.counter_value("serve.applies", worker="w0", host="hA") == 3.0
    assert (
        rreg.gauge_value("serve.occupancy", replica=0, worker="w0", host="hA")
        == 0.5
    )
    h = rreg.histogram_summary("serve.apply_seconds", worker="w0", host="hA")
    assert h is not None and h["count"] == 1
    # second delta ships only the change
    wreg.inc("serve.applies", 2.0)
    wreg.observe("serve.apply_seconds", 0.006)
    entries2 = tel.metrics_entries(min_interval_s=0.0)
    rreg.merge_entries(entries2, worker="w0", host="hA")
    assert rreg.counter_value("serve.applies", worker="w0", host="hA") == 5.0
    h2 = rreg.histogram_summary("serve.apply_seconds", worker="w0", host="hA")
    assert h2["count"] == 2


def test_metrics_delta_throttle_window():
    wreg = metrics.MetricsRegistry()
    tel = WorkerTelemetry(registry=wreg, min_metrics_interval_s=3600.0)
    wreg.inc("serve.applies")
    assert tel.metrics_entries() is not None  # first ship goes out
    wreg.inc("serve.applies")
    assert tel.metrics_entries() is None  # inside the window: held
    assert tel.metrics_entries(min_interval_s=0.0) is not None  # override


def test_capped_delta_export_ships_remainder_next_round():
    """Baselines advance only for entries that made the cut — a capped
    export loses nothing, it just ships the rest next time."""
    wreg = metrics.MetricsRegistry()
    tel = WorkerTelemetry(registry=wreg, max_entries=1)
    wreg.inc("serve.a", 1.0)
    wreg.inc("serve.b", 2.0)
    first = tel.metrics_entries(min_interval_s=0.0)
    second = tel.metrics_entries(min_interval_s=0.0)
    assert len(first) == 1 and len(second) == 1
    names = {e[1] for e in first} | {e[1] for e in second}
    assert names == {"serve.a", "serve.b"}


def test_merge_entries_skips_malformed_and_kind_conflicts():
    rreg = metrics.MetricsRegistry()
    rreg.inc("serve.x")  # counter; a gauge shipment for it must not raise
    merged = rreg.merge_entries(
        [
            "not-a-list",
            ["c", "serve.ok", [], 2.0],
            ["g", "serve.x", [], 1.0],  # kind conflict: dropped
            ["h", "serve.bad", [], {"bounds": "garbage"}],
            ["?", "serve.unknown", [], 1.0],
        ],
        worker="w0",
    )
    assert merged == 1
    assert rreg.counter_value("serve.ok", worker="w0") == 2.0


# ---------------------------------------------------------- FleetTelemetry


def _shipped(spans=None, t_rx=10.0 + SKEW + 0.001, t_tx=10.0 + SKEW + 0.003):
    blob = {"t_rx": t_rx, "t_tx": t_tx}
    if spans is not None:
        blob["spans"] = spans
    return blob


def test_fleet_telemetry_stitches_batch_record_for_requestz():
    rec = FlightRecorder()
    reg = metrics.MetricsRegistry()
    fleet = FleetTelemetry(registry=reg, recorder=rec)
    rec.annotate("r1", "serve.replica", batch="b1", replica=0)
    rec.batch("b1", ["r1"], replica=0, rows=1)
    spans = [
        {"name": "worker.attach", "t0": 10.0 + SKEW + 0.0012, "t1": 10.0 + SKEW + 0.0015},
        {"name": "worker.apply", "t0": 10.0 + SKEW + 0.0015, "t1": 10.0 + SKEW + 0.0028, "attrs": {"n": 1}},
    ]
    fleet.on_exchange(
        "net0", "hostA", 10.0, 10.004, _shipped(spans), trace={"batch": "b1"}
    )
    rec.finish("r1", "completed", batch="b1")
    (b,) = rec.request("r1")["batch_records"]
    assert b["worker"] == "net0" and b["host"] == "hostA"
    assert b["wire"]["rtt_s"] == pytest.approx(0.002, abs=1e-6)
    names = [s["name"] for s in b["worker_spans"]]
    assert names == ["worker.attach", "worker.apply"]
    for s in b["worker_spans"]:
        assert s["seconds"] >= 0.0
        assert 0.0 <= s["t_off"] <= 0.004
    # the apply span also fed the labeled fleet series
    h = reg.histogram_summary(
        "serve.fleet.apply_seconds", worker="net0", host="hostA"
    )
    assert h is not None and h["count"] == 1
    rtt = reg.histogram_summary(
        "serve.fleet.wire_rtt_seconds", worker="net0", host="hostA"
    )
    assert rtt is not None and rtt["count"] == 1


def test_fleet_telemetry_old_peer_is_a_silent_noop():
    rec = FlightRecorder()
    reg = metrics.MetricsRegistry()
    fleet = FleetTelemetry(registry=reg, recorder=rec)
    fleet.on_exchange("w0", None, 1.0, 2.0, None)  # old worker: no body
    fleet.on_exchange("w0", None, 1.0, 2.0, "garbage")
    fleet.on_exchange("w0", None, 1.0, 2.0, {"spans": "garbage", "t_rx": "x"})
    fleet.on_beat("w0", None, None)
    fleet.on_beat("w0", None, {"metrics": "garbage"})
    assert fleet.known_workers() in ([], ["w0"])  # never raised
    assert rec.stats()["live"] == 0


def test_fleet_telemetry_without_recorder_still_aggregates():
    reg = metrics.MetricsRegistry()
    fleet = FleetTelemetry(registry=reg, recorder=None)
    fleet.on_exchange(
        "p0",
        None,
        10.0,
        10.004,
        {
            **_shipped([{"name": "worker.apply", "t0": 10.0 + SKEW + 0.0015, "t1": 10.0 + SKEW + 0.0028}]),
            "metrics": [["c", "serve.applies", [], 4.0]],
        },
        trace={"batch": "b9"},  # recorder off: stitching skipped, no error
    )
    assert reg.counter_value("serve.applies", worker="p0", host="local") == 4.0
    h = reg.histogram_summary(
        "serve.fleet.apply_seconds", worker="p0", host="local"
    )
    assert h is not None and h["count"] == 1


def test_fleet_status_block_shape():
    reg = metrics.MetricsRegistry()
    fleet = FleetTelemetry(registry=reg, recorder=None)
    fleet.on_exchange("net0", "hostA", 10.0, 10.004, _shipped(
        [{"name": "worker.apply", "t0": 10.0 + SKEW + 0.001, "t1": 10.0 + SKEW + 0.003}]
    ))
    st = fleet.fleet_status()
    entry = st["workers"]["net0"]
    assert entry["host"] == "hostA"
    assert entry["clock_samples"] == 1
    assert entry["clock_offset_s"] == pytest.approx(SKEW, abs=1e-3)
    assert entry["apply_ms"]["count"] == 1 and entry["apply_ms"]["p50"] is not None
    assert entry["wire_rtt_ms"]["count"] == 1
