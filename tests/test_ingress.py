"""Zero-copy async ingress (serve/ingress.py): batch-frame hardening
(the wire-v2 taxonomy — garbage magic, version skew, truncation, CRC
damage, oversize refusal, mid-frame stall — every one a typed verdict,
never a hang), protocol sniffing (HTTP/JSON on the same port), slab-
direct admission (preformed flushes, zero admission copies), typed
admission refusals that keep the connection, and the bit-identity pin:
binary-batch predictions match the HTTP/JSON slow path byte for byte.

All tier-1 (seconds-scale, CPU): the ingress is host-side selector
threading over the same tiny device programs as test_serve.py.
"""

import json
import socket
import struct
import threading
import time
import urllib.request
import zlib

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.models.linear import LinearMapper
from keystone_tpu.obs import metrics
from keystone_tpu.ops.stats import NormalizeRows
from keystone_tpu.serve import serve, wire
from keystone_tpu.serve import ingress as ing
from keystone_tpu.workflow import Dataset, Pipeline

pytestmark = pytest.mark.serve

DIM = 6


def _pipeline(scale: float = 2.0) -> Pipeline:
    w = jnp.asarray(np.eye(DIM, dtype=np.float32) * scale)
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


def _service(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("queue_bound", 64)
    kw.setdefault("example", np.zeros(DIM, np.float32))
    return serve(_pipeline(), **kw)


def _counter(name: str, **labels) -> float:
    return metrics.REGISTRY.counter_value(name, **labels)


@pytest.fixture(scope="module")
def served():
    """One module-scoped service + single-shard ingress: frame fuzzing
    and protocol tests don't need fresh state per test."""
    with _service() as svc:
        srv = ing.serve_ingress(svc, port=0, shards=1, stall_timeout_s=0.5)
        try:
            yield svc, srv
        finally:
            srv.stop()


def _dial(srv) -> socket.socket:
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=10.0)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _recv(s, timeout=10.0):
    return ing.recv_batch_frame(s, timeout=timeout)


def _assert_hangup(s):
    """A condemned connection ends in FIN or RST (the server may close
    with unread bytes still queued, which the kernel turns into RST) —
    either way the peer sees a hard hangup, never a hang."""
    try:
        assert s.recv(1) == b""
    except ConnectionResetError:
        pass


# -------------------------------------------------------- frame packing


def test_batch_frame_roundtrip_through_pack_and_recv():
    a, b = socket.socketpair()
    try:
        payload = np.arange(12, dtype=np.float32).tobytes()
        msg = {"op": "predict", "count": 2, "dtype": "<f4", "shape": [DIM]}
        a.sendall(ing.pack_batch_frame(msg, payload))
        got, gpayload = ing.recv_batch_frame(b, timeout=5.0)
        assert got == msg and gpayload == payload
    finally:
        a.close()
        b.close()


def test_batch_magic_is_distinct_from_worker_wire_magic():
    # a batch client dialing a worker port (or vice versa) must fail
    # the MAGIC check, not a confusing length parse
    assert ing.BATCH_MAGIC != wire.MAGIC
    assert len(ing.BATCH_MAGIC) == len(wire.MAGIC) == 4


def test_client_recv_rejects_garbage_magic():
    a, b = socket.socketpair()
    try:
        frame = ing.pack_batch_frame({"op": "ping"})
        a.sendall(b"XXXX" + frame[4:])
        with pytest.raises(wire.WireError, match="magic"):
            ing.recv_batch_frame(b, timeout=5.0)
    finally:
        a.close()
        b.close()


def test_client_recv_rejects_version_skew():
    a, b = socket.socketpair()
    try:
        frame = bytearray(ing.pack_batch_frame({"op": "ping"}))
        frame[len(ing.BATCH_MAGIC)] = ing.BATCH_VERSION + 1
        a.sendall(bytes(frame))
        with pytest.raises(wire.WireError, match="version"):
            ing.recv_batch_frame(b, timeout=5.0)
    finally:
        a.close()
        b.close()


def test_client_recv_rejects_truncation_and_crc_damage():
    # close mid-body: torn, not a clean goodbye
    a, b = socket.socketpair()
    try:
        frame = ing.pack_batch_frame({"op": "predict"}, b"payload-bytes")
        a.sendall(frame[:-3])
        a.close()
        with pytest.raises(wire.WireError, match="truncated"):
            ing.recv_batch_frame(b, timeout=5.0)
    finally:
        b.close()

    # flip a payload bit: CRC verdict
    a, b = socket.socketpair()
    try:
        frame = bytearray(ing.pack_batch_frame({"op": "predict"}, b"abcdef"))
        frame[-1] ^= 0x40
        a.sendall(bytes(frame))
        with pytest.raises(wire.WireError, match="CRC"):
            ing.recv_batch_frame(b, timeout=5.0)
    finally:
        a.close()
        b.close()


def test_client_recv_refuses_oversize_before_allocating():
    a, b = socket.socketpair()
    try:
        a.sendall(ing.pack_batch_frame({"op": "predict"}, b"x" * 256))
        with pytest.raises(wire.WireError, match="cap"):
            ing.recv_batch_frame(b, timeout=5.0, max_frame_bytes=64)
    finally:
        a.close()
        b.close()


# ------------------------------------------------- server frame hardening


def test_server_rejects_garbage_magic_with_typed_error(served):
    # garbage on a FRESH connection sniffs as HTTP; bad_magic is a
    # mid-stream verdict — frame one must be valid binary first
    _, srv = served
    s = _dial(srv)
    try:
        before = _counter("ingress.frame_errors", kind="bad_magic")
        s.sendall(ing.pack_batch_frame({"op": "ping"}))
        reply, _ = _recv(s)
        assert reply["op"] == "pong"
        s.sendall(b"XXXX" + b"\x00" * 32)
        reply, _ = _recv(s)
        assert reply["op"] == "error" and reply["kind"] == "bad_magic"
        # framing violation condemns the connection
        _assert_hangup(s)
        assert _counter("ingress.frame_errors", kind="bad_magic") == before + 1
    finally:
        s.close()


def test_server_rejects_version_skew_with_typed_error(served):
    _, srv = served
    s = _dial(srv)
    try:
        frame = bytearray(ing.pack_batch_frame({"op": "ping"}))
        frame[len(ing.BATCH_MAGIC)] = ing.BATCH_VERSION + 7
        s.sendall(bytes(frame))
        reply, _ = _recv(s)
        assert reply["kind"] == "version_skew"
        _assert_hangup(s)
    finally:
        s.close()


def test_server_rejects_crc_damage_with_typed_error(served):
    _, srv = served
    x = np.ones((2, DIM), np.float32)
    msg = {"op": "predict", "count": 2, "dtype": x.dtype.str, "shape": [DIM]}
    s = _dial(srv)
    try:
        frame = bytearray(ing.pack_batch_frame(msg, x.tobytes()))
        frame[-1] ^= 0x40
        s.sendall(bytes(frame))
        reply, _ = _recv(s)
        assert reply["kind"] == "crc_mismatch"
        _assert_hangup(s)
    finally:
        s.close()


def test_server_refuses_oversize_frame_before_reading_it(served):
    _, srv = served
    s = _dial(srv)
    try:
        # a prefix CLAIMING a huge frame — no bytes behind it; the
        # refusal must come from the header alone
        huge = srv.max_frame_bytes + 1
        prefix = (
            ing.BATCH_MAGIC
            + bytes([ing.BATCH_VERSION])
            + ing._HEADER.pack(64, huge, 0)
        )
        s.sendall(prefix)
        reply, _ = _recv(s)
        assert reply["kind"] == "oversize"
        _assert_hangup(s)
    finally:
        s.close()


def test_server_rejects_unparseable_body_and_unknown_op(served):
    _, srv = served
    s = _dial(srv)
    try:
        body = b"not json at all"
        crc = zlib.crc32(body) & 0xFFFFFFFF
        s.sendall(
            ing.BATCH_MAGIC
            + bytes([ing.BATCH_VERSION])
            + ing._HEADER.pack(len(body), 0, crc)
            + body
        )
        reply, _ = _recv(s)
        assert reply["kind"] == "bad_body"
    finally:
        s.close()

    s = _dial(srv)
    try:
        s.sendall(ing.pack_batch_frame({"op": "launder"}))
        reply, _ = _recv(s)
        assert reply["kind"] == "bad_op"
    finally:
        s.close()


def test_server_rejects_header_payload_length_mismatch(served):
    _, srv = served
    s = _dial(srv)
    try:
        x = np.ones((2, DIM), np.float32)
        msg = {
            "op": "predict",
            "count": 3,  # claims 3 rows, payload carries 2
            "dtype": x.dtype.str,
            "shape": [DIM],
        }
        s.sendall(ing.pack_batch_frame(msg, x.tobytes()))
        reply, _ = _recv(s)
        assert reply["kind"] == "bad_body" and "claims" in reply["error"]
    finally:
        s.close()


def test_server_refuses_non_numeric_dtypes_with_typed_error(served):
    """The wire dtype is attacker-controlled and must be allowlisted:
    dtype "O" over the shared-memory slab would reinterpret raw socket
    bytes as PyObject pointers (a remote segfault on first deref);
    strings/datetimes/void are refused with the same typed verdict."""
    _, srv = served
    for spec in ["O", "U4", "M8[ns]", "S8", "V16"]:
        itemsize = np.dtype(spec).itemsize
        s = _dial(srv)
        try:
            msg = {"op": "predict", "count": 1, "dtype": spec, "shape": [2]}
            s.sendall(ing.pack_batch_frame(msg, b"\x00" * (2 * itemsize)))
            reply, _ = _recv(s)
            assert reply["kind"] == "bad_body", spec
            assert "not admissible" in reply["error"], spec
        finally:
            s.close()


def test_server_refuses_overflow_and_nonpositive_dims_typed(served):
    """Header dims are validated with overflow-safe Python-int math: a
    product that wraps a fixed-width accumulator into matching
    payload_len, negative dims that cancel, and zero dims must all get
    a typed bad_body refusal — never an untyped alloc failure."""
    _, srv = served
    cases = [
        ([1 << 31, 1 << 33], b""),  # int64 product wraps to exactly 0
        ([-1, -1], b"\x00" * 4),  # negatives cancel to a +1 product
        ([0], b""),  # zero-size rows
    ]
    for shape, payload in cases:
        s = _dial(srv)
        try:
            msg = {
                "op": "predict",
                "count": 1,
                "dtype": "<f4",
                "shape": shape,
            }
            s.sendall(ing.pack_batch_frame(msg, payload))
            reply, _ = _recv(s)
            assert reply["kind"] == "bad_body", shape
        finally:
            s.close()


def test_partial_magic_stall_is_condemned_and_does_not_spin(served):
    """A peer sending a strict prefix of the magic then stalling used
    to sit unconsumed under MSG_PEEK — invisible to the stall sweep,
    and spinning the level-triggered selector at full CPU.  The bytes
    are now consumed into the frame buffer, so the conn is mid-frame:
    the sweep condemns it bounded, and the drained socket stops waking
    the selector (the wait must cost ~no process CPU)."""
    _, srv = served
    before = _counter("ingress.frame_errors", kind="mid_frame_stall")
    s = _dial(srv)
    try:
        s.sendall(ing.BATCH_MAGIC[:2])
        t0, c0 = time.monotonic(), time.process_time()
        assert s.recv(1, socket.MSG_WAITALL) == b""  # server hangs up
        wall, cpu = time.monotonic() - t0, time.process_time() - c0
        assert wall < 10.0  # bounded, never a hang
        assert cpu < 0.4  # a spinning shard loop would burn ~wall CPU
        assert (
            _counter("ingress.frame_errors", kind="mid_frame_stall")
            == before + 1
        )
    finally:
        s.close()


def test_magic_split_across_sniff_still_parses(served):
    """Bytes consumed during the sniff must flow into the prefix
    parser: a client trickling the magic a byte at a time still gets
    its frame served."""
    _, srv = served
    s = _dial(srv)
    try:
        frame = ing.pack_batch_frame({"op": "ping"})
        for i in range(len(ing.BATCH_MAGIC)):
            s.sendall(frame[i : i + 1])
            time.sleep(0.02)
        s.sendall(frame[len(ing.BATCH_MAGIC) :])
        reply, _ = _recv(s)
        assert reply["op"] == "pong"
    finally:
        s.close()


def test_shard_loop_survives_internal_handler_error(served, monkeypatch):
    """An unanticipated exception escaping the per-connection path
    drops that conn (counted as kind=internal) but must never kill the
    shard loop — the listener keeps serving everyone else."""
    _, srv = served
    before = _counter("ingress.frame_errors", kind="internal")

    def boom(*a, **kw):
        raise RuntimeError("synthetic handler bug")

    monkeypatch.setattr(srv, "_parse_prefix", boom)
    s = _dial(srv)
    try:
        s.sendall(ing.pack_batch_frame({"op": "ping"}))
        _assert_hangup(s)
    finally:
        s.close()
    monkeypatch.undo()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if _counter("ingress.frame_errors", kind="internal") == before + 1:
            break
        time.sleep(0.01)
    assert _counter("ingress.frame_errors", kind="internal") == before + 1
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        assert cli.ping()["op"] == "pong"  # the shard loop is alive


def test_server_mid_frame_stall_is_condemned_never_a_hang(served):
    """A peer that starts a frame and goes silent holds a TORN channel:
    the stall sweep (stall_timeout_s=0.5 here) condemns it bounded."""
    _, srv = served
    s = _dial(srv)
    try:
        before = _counter("ingress.frame_errors", kind="mid_frame_stall")
        frame = ing.pack_batch_frame(
            {"op": "predict", "count": 1, "dtype": "<f4", "shape": [DIM]},
            np.ones(DIM, np.float32).tobytes(),
        )
        s.sendall(frame[:20])  # past the prefix, then silence
        t0 = time.monotonic()
        assert s.recv(1, socket.MSG_WAITALL) == b""  # server hangs up
        assert time.monotonic() - t0 < 10.0  # bounded, never a hang
        assert (
            _counter("ingress.frame_errors", kind="mid_frame_stall")
            == before + 1
        )
    finally:
        s.close()


def test_server_half_frame_then_eof_counts_truncated(served):
    _, srv = served
    before = _counter("ingress.frame_errors", kind="truncated")
    s = _dial(srv)
    frame = ing.pack_batch_frame(
        {"op": "predict", "count": 1, "dtype": "<f4", "shape": [DIM]},
        np.ones(DIM, np.float32).tobytes(),
    )
    s.sendall(frame[:-5])
    s.close()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if _counter("ingress.frame_errors", kind="truncated") == before + 1:
            return
        time.sleep(0.01)
    raise AssertionError("truncated EOF never counted")


# ------------------------------------------------------ predict semantics


def test_binary_predict_matches_offline_apply(served):
    svc, srv = served
    x = np.random.default_rng(0).normal(size=(5, DIM)).astype(np.float32)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)[:5]
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        got = cli.predict(x)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)


def test_binary_predict_is_bit_identical_to_http_json(served):
    """THE bit-identity pin: the zero-copy binary path and the JSON
    slow path — same port — return byte-for-byte equal predictions.
    float32 survives the JSON text round-trip exactly, so any
    difference would be a real numeric divergence."""
    _, srv = served
    x = np.random.default_rng(7).normal(size=(4, DIM)).astype(np.float32)
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        got_bin = cli.predict(x)
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/predict",
        data=json.dumps({"instances": x.tolist()}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30.0) as resp:
        got_http = np.asarray(
            json.loads(resp.read())["predictions"], np.float32
        )
    assert got_bin.tobytes() == got_http.tobytes()


def test_keep_alive_many_frames_one_connection(served):
    _, srv = served
    x = np.random.default_rng(3).normal(size=(3, DIM)).astype(np.float32)
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        assert cli.ping()["op"] == "pong"
        first = cli.predict(x)
        for _ in range(4):
            np.testing.assert_array_equal(cli.predict(x), first)
        assert cli.ping()["shards"] == 1


def test_admission_refusal_is_typed_and_keeps_the_connection(served):
    _, srv = served
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        with pytest.raises(ing.IngressError) as ei:
            cli.predict(np.ones((2, DIM + 1), np.float32))  # wrong width
        assert ei.value.kind == "bad_request"
        # the stream is fine — the REQUEST was refused; next frame works
        out = cli.predict(np.ones((2, DIM), np.float32))
        assert out.shape == (2, DIM)


def test_expired_deadline_is_a_typed_deadline_refusal(served):
    _, srv = served
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        with pytest.raises(ing.IngressError) as ei:
            cli.predict(np.ones((2, DIM), np.float32), deadline_ms=0.0001)
        assert ei.value.kind == "deadline"
        assert cli.ping()["op"] == "pong"


def test_preformed_flush_counts_and_admission_is_zero_copy(served):
    """An exact-bucket binary batch flushes PREFORMED (no stack, no
    re-pad) and admission itself copies nothing — the copy counters
    charge only the response assembly, never the request path."""
    svc, srv = served
    flushes0 = _counter("serve.preformed_flushes")
    copied0 = _counter("ingress.bytes_copied")
    x = np.random.default_rng(5).normal(
        size=(svc.max_batch, DIM)
    ).astype(np.float32)
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        cli.predict(x)
    assert _counter("serve.preformed_flushes") >= flushes0 + 1
    assert _counter("ingress.bytes_copied") == copied0  # HTTP-only counter


def test_batch_wider_than_max_batch_spans_flushes(served):
    svc, srv = served
    n = svc.max_batch * 2 + 3
    x = np.random.default_rng(9).normal(size=(n, DIM)).astype(np.float32)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)[:n]
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        got = cli.predict(x)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)


def test_http_sniff_delegates_same_port(served):
    _, srv = served
    with urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}/healthz", timeout=10.0
    ) as resp:
        assert resp.status == 200


def test_concurrent_binary_clients_all_complete(served):
    _, srv = served
    x = np.random.default_rng(11).normal(size=(4, DIM)).astype(np.float32)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)[:4]
    errs = []

    def run():
        try:
            with ing.BinaryClient("127.0.0.1", srv.port) as cli:
                for _ in range(5):
                    np.testing.assert_allclose(
                        cli.predict(x), ref, rtol=1e-6, atol=1e-7
                    )
        except Exception as e:  # surfaced below
            errs.append(e)

    ts = [threading.Thread(target=run) for _ in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60.0)
    assert not errs, errs


# ----------------------------------------------------------------- shards


def test_two_shards_serve_one_port():
    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("platform lacks SO_REUSEPORT")
    x = np.random.default_rng(2).normal(size=(3, DIM)).astype(np.float32)
    ref = np.asarray(_pipeline()(Dataset(x)).get().array)[:3]
    with _service() as svc:
        srv = ing.serve_ingress(svc, port=0, shards=2)
        try:
            assert srv.shards == 2
            clis = [ing.BinaryClient("127.0.0.1", srv.port) for _ in range(4)]
            try:
                for cli in clis:
                    assert cli.ping()["shards"] == 2
                    np.testing.assert_allclose(
                        cli.predict(x), ref, rtol=1e-6, atol=1e-7
                    )
            finally:
                for cli in clis:
                    cli.close()
        finally:
            srv.stop()


def test_stop_is_idempotent_and_unbinds():
    with _service() as svc:
        srv = ing.serve_ingress(svc, port=0, shards=1)
        port = srv.port
        srv.stop()
        srv.stop()
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", port), timeout=0.5)


# --------------------------------------- request-id parity (ISSUE 18)


def test_binary_request_id_parity_with_http(served):
    """KSBB has the HTTP front end's request-id contract: a supplied
    ``request_id`` is honored (fanned out per row, exactly the HTTP
    multi-instance rule), an absent one is minted server-side, and the
    ids come back in success bodies AND typed refusals alike."""
    svc, srv = served
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        cli.predict(np.ones((2, DIM), np.float32), request_id="order-9")
        assert cli.last_request_ids == ["order-9/0", "order-9/1"]
        cli.predict(np.ones((1, DIM), np.float32), request_id="solo-1")
        assert cli.last_request_ids == ["solo-1"]
        cli.predict(np.ones((2, DIM), np.float32))
        minted = cli.last_request_ids
        assert len(minted) == 2 and all(minted)
        # a typed refusal names the rows it refused
        with pytest.raises(ing.IngressError) as ei:
            cli.predict(
                np.ones((2, DIM), np.float32),
                deadline_ms=0.0001,
                request_id="doomed-bin",
            )
        assert ei.value.kind == "deadline"
        assert ei.value.request_ids == ["doomed-bin/0", "doomed-bin/1"]
        # the ids enter the same /requestz loop as HTTP ids
        if svc.recorder is not None:
            assert svc.recorder.request("order-9/0") is not None


def test_statusz_ingress_block_covers_binary_front_end(served):
    svc, srv = served
    with ing.BinaryClient("127.0.0.1", srv.port) as cli:
        cli.predict(np.ones((2, DIM), np.float32))
    blk = svc.status().get("ingress")
    assert blk is not None
    assert blk["accepts"] >= 1 and blk["bin_conns"] >= 1
    assert blk["frames"] >= 1 and blk["batch_rows"] >= 2
    assert isinstance(blk["frame_errors"], dict)
    assert blk["parse_ms"] is None or blk["parse_ms"]["count"] >= 1
    assert blk["admit_ms"] is None or blk["admit_ms"]["count"] >= 1
    assert "bytes_copied" in blk
