"""Guarded rollouts (ISSUE 19): canary-fraction swaps with automatic
rollback (serve/rollout.py), registry bad-version quarantine, the
windowed SLO-burn knob, HTTP parity (/rolloutz, /rollback, canary
bodies on /swap), the seeded workload zoo's replay pin, and the
end-to-end chaos drill.

All tier-1 (seconds-scale, CPU): conftest forces 8 host-platform
devices, so multi-replica pools run in-process.
"""

import threading
import time

import numpy as np
import pytest

from keystone_tpu.obs import metrics
from keystone_tpu.serve import (
    ModelRegistry,
    RegistryWatcher,
    RolloutConfig,
    serve,
    serve_http,
)
from keystone_tpu.serve.rollout import CanaryController, canary_hash, guarded_swap
from keystone_tpu.utils import durable
from tools.workloads import MARK, build_zoo_pipeline, make_scenario, payload

pytestmark = pytest.mark.serve

DIM = 6


def _pipeline(scale: float = 2.0, gate: bool = False):
    return build_zoo_pipeline(dim=DIM, scale=scale, gate=gate)


def _service(replicas: int, name: str, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("queue_bound", 512)
    kw.setdefault("example", np.zeros(DIM, np.float32))
    kw.setdefault("version", "v0001")
    return serve(_pipeline(), replicas=replicas, name=name, **kw)


def _rows(k: int, seed: int = 0) -> np.ndarray:
    return (
        np.random.default_rng(seed).normal(size=(k, DIM)).astype(np.float32)
    )


def _norm(out) -> float:
    return float(np.linalg.norm(np.asarray(out)))


def _counter(name: str) -> float:
    return metrics.REGISTRY.counter_total(name)


class _Pump:
    """Background traffic: submit rows until stopped, collect futures."""

    def __init__(self, svc, make_rows):
        self.svc = svc
        self.make_rows = make_rows
        self.futs = []
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        i = 0
        while not self._stop.is_set():
            for row in self.make_rows(i):
                try:
                    f = self.svc.submit(row)
                except Exception:
                    continue
                with self._lock:
                    self.futs.append(f)
            i += 1
            time.sleep(0.005)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=10.0)

    def resolve_all(self, timeout=30.0) -> int:
        """Resolve every submitted future; returns the HUNG count (a
        typed failure is an acceptable terminal, a hang never is)."""
        from concurrent.futures import TimeoutError as FutTimeout

        with self._lock:
            futs = list(self.futs)
        hung = 0
        for f in futs:
            try:
                f.result(timeout=timeout)
            except FutTimeout:
                hung += 1
            except Exception:
                pass
        return hung


# ----------------------------------------------------------- determinism
def test_canary_hash_seeded_replay_pin():
    """The routing split is a pure function of (seed, request id) —
    pinned to literal values so the hash can never silently change
    (a changed split makes every recorded canary episode unreplayable)."""
    assert canary_hash(0, "req-000") == 0.22911944990885413
    assert canary_hash(7, "req-000") == 0.9493967629409243
    ids = [f"r{i}" for i in range(200)]
    split = [i for i, r in enumerate(ids) if canary_hash(3, r) < 0.25]
    assert len(split) == 48
    assert split[:12] == [2, 3, 4, 5, 7, 13, 20, 29, 31, 32, 35, 36]
    assert all(0.0 <= canary_hash(11, r) < 1.0 for r in ids)


def test_workload_zoo_seeded_replay():
    """Same (name, seed) = identical schedule, digest, and payload
    bytes; a different seed diverges.  The zoo's whole value is that a
    scenario that killed a canary replays bit-exactly."""
    a = make_scenario("poison_flood", seed=7)
    b = make_scenario("poison_flood", seed=7)
    assert a.trace_digest() == b.trace_digest()
    assert a.trace() == b.trace()
    assert a.trace_digest() != make_scenario("poison_flood", seed=8).trace_digest()
    for ea, eb in zip(a.events[:8], b.events[:8]):
        np.testing.assert_array_equal(payload(ea, a.dim), payload(eb, b.dim))
    poison = [e for e in a.events if e["kind"] == "poison"]
    assert poison, "poison_flood produced no poison events"
    assert all(payload(e, a.dim)[:, 0][0] == MARK for e in poison[:4])
    digests = set()
    for name in ("bursty", "diurnal", "heavy_tailed", "tenant_skewed", "drift"):
        sc = make_scenario(name, seed=3)
        assert sc.events
        assert sc.trace_digest() == make_scenario(name, seed=3).trace_digest()
        digests.add(sc.trace_digest())
    assert len(digests) == 5  # scenarios don't collapse onto one schedule


def test_rollout_config_validation():
    with pytest.raises(ValueError):
        RolloutConfig(canary=0.0)
    with pytest.raises(ValueError):
        RolloutConfig(canary=1.5)
    with pytest.raises(ValueError):
        RolloutConfig(insufficient="explode")
    cfg = RolloutConfig.from_request(
        {"canary": 0.25, "min_samples": 5, "version": "v0002", "junk": 1}
    )
    assert cfg.canary == 0.25 and cfg.min_samples == 5
    with pytest.raises(ValueError):
        RolloutConfig.from_request({"canary": "a lot"})
    assert RolloutConfig(canary=None).canary is None


# -------------------------------------------------------------- episodes
def test_canary_catches_poison_flood(tmp_path):
    """The tentpole contract: a bad version (fails marker rows) canaried
    under a poison flood is rolled back on the error-rate guardrail —
    the live generation keeps serving, the version is durably
    quarantined, and no future hangs across the abandoned generation."""
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    v2 = reg.publish(_pipeline(3.0, gate=True), set_current=False)
    svc = _service(2, "rollout_poison", version=v1)

    def poison_wave(i):
        rows = _rows(3, seed=1000 + i)
        rows[0, 0] = MARK  # one marker row per wave, distinct content
        return rows

    rollbacks0 = _counter("serve.rollout.rollbacks")
    try:
        with _Pump(svc, poison_wave) as pump:
            cfg = RolloutConfig(
                canary=1.0,  # every flush canaried: deterministic drill
                min_samples=8,
                decide_s=20.0,
                max_error_rate=0.2,
                p99_ratio=None,
                insufficient="rollback",
            )
            info = CanaryController(svc, cfg, registry=reg).run(
                reg.load(v2)[0], version=v2
            )
            assert info["verdict"] == "rolled_back", info
            assert info["reason"] == "error_rate", info
            assert info["canary"]["canary"]["bad"] > 0
        assert pump.resolve_all() == 0  # zero hung futures
        assert svc.version == v1
        # the live generation still answers with the OLD fingerprint
        y = svc.submit(_rows(1, seed=5)[0]).result(timeout=30.0)
        assert abs(_norm(y) - 2.0) < 1e-3
        # durable condemnation: the registry carries the BAD mark and
        # the deploy walk refuses the version
        assert reg.quarantined(v2) is not None
        assert reg.load()[1] == v1
        assert _counter("serve.rollout.rollbacks") > rollbacks0
        hist = svc.rollout_status()["history"]
        assert hist and hist[-1]["verdict"] == "rolled_back"
        assert svc.rollout_status()["active"] is None
    finally:
        svc.close()


def test_canary_passes_clean_commits(tmp_path):
    """A healthy version under clean traffic commits: the service flips
    to the new generation, CURRENT follows, and the info dict is a
    superset of the plain swap's."""
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    v2 = reg.publish(_pipeline(3.0), set_current=False)
    svc = _service(2, "rollout_clean", version=v1)
    commits0 = _counter("serve.rollout.commits")
    try:
        with _Pump(svc, lambda i: _rows(3, seed=2000 + i)) as pump:
            cfg = RolloutConfig(
                canary=0.5,
                seed=3,
                min_samples=8,
                decide_s=20.0,
                p99_ratio=None,
                insufficient="rollback",
            )
            info = CanaryController(svc, cfg, registry=reg).run(
                reg.load(v2)[0], version=v2
            )
            assert info["verdict"] == "committed", info
            assert info["reason"] == "guardrails_clean"
            assert {"pause_seconds", "prime_seconds", "replicas"} <= set(info)
        assert pump.resolve_all() == 0
        assert svc.version == v2
        y = svc.submit(_rows(1, seed=6)[0]).result(timeout=30.0)
        assert abs(_norm(y) - 3.0) < 1e-3
        assert reg.current() == v2  # CURRENT moved with the commit
        assert reg.quarantined(v2) is None
        assert _counter("serve.rollout.commits") > commits0
        assert v1 in svc.rollout_status()["prior_versions"]
    finally:
        svc.close()


def test_canary_insufficient_samples_decides_conservatively(tmp_path):
    """No traffic in the judge window: the default refuses to commit on
    noise (rollback); insufficient='commit' is the operator's explicit
    opt-out.  Single-use controllers cannot be replayed."""
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    v2 = reg.publish(_pipeline(3.0), set_current=False)
    svc = _service(1, "rollout_quiet", version=v1)
    try:
        cfg = RolloutConfig(
            canary=0.5, min_samples=10_000, decide_s=0.3, insufficient="rollback"
        )
        ctl = CanaryController(svc, cfg, registry=reg)
        info = ctl.run(reg.load(v2)[0], version=v2)
        assert info["verdict"] == "rolled_back"
        assert info["reason"] == "insufficient_samples"
        assert svc.version == v1
        with pytest.raises(RuntimeError):
            ctl.run(reg.load(v2)[0], version=v2)  # single-use
        # the quarantined mark from the rollback blocks the deploy walk
        assert reg.quarantined(v2) is not None
        reg.clear_quarantine(v2)
        cfg2 = RolloutConfig(
            canary=0.5, min_samples=10_000, decide_s=0.3, insufficient="commit"
        )
        info2 = CanaryController(svc, cfg2, registry=reg).run(
            reg.load(v2)[0], version=v2
        )
        assert info2["verdict"] == "committed"
        assert info2["reason"] == "insufficient_samples"
        assert svc.version == v2
    finally:
        svc.close()


def test_bake_rollback_on_sustained_burn(tmp_path):
    """Post-commit bake: the committed version passes its canary window
    (drift hasn't bitten yet) but burns the SLO during the bake — the
    RollbackGuard reverts to the prior generation and quarantines the
    baked version.  The drift scenario's shifted payloads drive the
    traffic; a microscopic objective makes the burn deterministic."""
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    v2 = reg.publish(_pipeline(3.0), set_current=False)
    svc = _service(
        2,
        "rollout_bake",
        version=v1,
        slo_ms=1e-4,  # everything breaches: burn is deterministic
        slo_target=0.99,
    )
    drift = make_scenario("drift", seed=4, duration_s=2.0, qps=100.0, dim=DIM)
    drift_rows = [payload(e, DIM) for e in drift.events[:64]]
    bake_rb0 = _counter("serve.rollout.bake_rollbacks")
    try:
        cfg = RolloutConfig(
            canary=1.0,
            min_samples=4,
            decide_s=0.2,
            insufficient="commit",  # skip the canary judge into the bake
            max_burn=float("inf"),
            max_error_rate=1.1,
            p99_ratio=None,
            bake_s=30.0,
            bake_max_burn=1.0,
            bake_sustain_s=0.1,
        )
        info = CanaryController(svc, cfg, registry=reg).run(
            reg.load(v2)[0], version=v2
        )
        assert info["verdict"] == "committed", info
        assert svc.version == v2
        state = svc.rollout_status()["active"]
        assert state is not None and state["phase"] == "bake"
        # drift-era traffic burns the objective; the guard must revert
        deadline = time.monotonic() + 30.0
        i = 0
        while svc.version != v1 and time.monotonic() < deadline:
            rows = drift_rows[i % len(drift_rows)]
            for f in svc.submit_many(rows):
                try:
                    f.result(timeout=30.0)
                except Exception:
                    pass
            i += 1
        assert svc.version == v1, "bake guard never reverted"
        y = svc.submit(_rows(1, seed=8)[0]).result(timeout=30.0)
        assert abs(_norm(y) - 2.0) < 1e-3
        assert _counter("serve.rollout.bake_rollbacks") > bake_rb0
        assert reg.quarantined(v2) is not None
        assert reg.current() == v1
        hist = svc.rollout_status()["history"]
        assert hist[-1]["reason"] == "bake_burn"
        deadline = time.monotonic() + 5.0
        while svc._rollout_guard is not None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert svc._rollout_guard is None  # guard cleared itself
    finally:
        svc.close()


def test_canary_fallback_when_no_staged_capacity():
    """take() never blocks and never fails a flush: with no routable
    staged replica the flush falls back to the live generation and the
    fallback is counted."""

    class _Flush:
        riders = ()
        bid = "b-fallback"

    svc = _service(1, "rollout_fallback")
    try:
        ctl = CanaryController(svc, RolloutConfig(canary=1.0))
        ctl._open = True  # window open, but zero staged replicas
        before = _counter("serve.rollout.canary_fallbacks")
        assert ctl.take(_Flush()) is False
        assert ctl.snapshot()["canary_fallbacks"] == 1
        assert _counter("serve.rollout.canary_fallbacks") > before
    finally:
        svc.close()


# ------------------------------------------------------ swap path pinned
def test_plain_swap_surface_pinned():
    """With canary=None nothing of the rollout machinery runs: the swap
    info dict is exactly the PR-8/11 surface (no rollout keys), and
    guarded_swap degrades to the identical call."""
    svc = _service(2, "rollout_pinned")
    try:
        info = svc.swap(_pipeline(3.0), version="v0002")
        assert set(info) == {
            "version",
            "pause_seconds",
            "prime_seconds",
            "replicas",
        }
        info2 = guarded_swap(svc, _pipeline(4.0), version="v0003", config=None)
        assert set(info2) == set(info)
        info3 = guarded_swap(
            svc,
            _pipeline(5.0),
            version="v0004",
            config=RolloutConfig(canary=None),
        )
        assert set(info3) == set(info)
        assert svc.version == "v0004"
        # internal: swap history accumulated for /rollback anyway
        assert svc.rollout_status()["prior_versions"] == [
            "v0001",
            "v0002",
            "v0003",
        ]
    finally:
        svc.close()


# ---------------------------------------------------------- slo windowing
def test_slo_burn_windowing_knob():
    """slo_window_s sizes the burn window, and slo_burn() reports
    window_requests so a judge can refuse to decide on too-few
    samples."""
    svc = _service(1, "rollout_slo", slo_ms=250.0, slo_window_s=5.0)
    try:
        detail = svc.slo_burn()
        assert detail["window_seconds"] == 5.0
        assert detail["window_requests"] == 0
        assert detail["burn_rate"] == 0.0
        for f in svc.submit_many(_rows(4, seed=3)):
            f.result(timeout=30.0)
        detail = svc.slo_burn()
        assert detail["window_requests"] >= 4
        assert svc.slo_burn_rate() == detail["burn_rate"]
        assert {"objective_ms", "target", "bad_fraction"} <= set(detail)
    finally:
        svc.close()
    # no objective -> no burn block at all
    svc2 = _service(1, "rollout_noslo")
    try:
        assert svc2.slo_burn() is None
        assert svc2.slo_burn_rate() is None
    finally:
        svc2.close()


# ------------------------------------------------------------- registry
def test_registry_quarantine_checksummed_sidecar(tmp_path):
    """The BAD mark is durable (checksummed sidecar), fail-safe (an
    unreadable mark still condemns), skipped by the deploy walk but not
    the forensic path, and cleared by republish or the explicit API."""
    reg = ModelRegistry(str(tmp_path))
    reg.publish(_pipeline(2.0))
    v2 = reg.publish(_pipeline(3.0))
    assert reg.current() == v2
    with pytest.raises(Exception):
        reg.quarantine("v9999")  # unpublished: typed refusal
    reg.quarantine(v2, reason="rollout rollback: error_rate")
    assert "error_rate" in reg.quarantined(v2)
    import os

    assert os.path.exists(reg.bad_path(v2) + durable.CHECKSUM_SUFFIX)
    # deploy walk skips it (CURRENT still points at it)
    skips0 = _counter("serve.registry_quarantine_skips")
    fitted, ver = reg.load()
    assert ver == "v0001"
    assert _counter("serve.registry_quarantine_skips") > skips0
    # forensic path still reads the condemned version strictly
    assert reg.load(v2)[1] == v2
    # an unreadable mark is still a mark (fail-safe)
    with open(reg.bad_path(v2), "w") as f:
        f.write("torn garbage")
    assert reg.quarantined(v2) is not None
    # explicit clear, then republish-clears
    assert reg.clear_quarantine(v2) is True
    assert reg.quarantined(v2) is None
    assert reg.clear_quarantine(v2) is False
    reg.quarantine(v2, reason="again")
    reg.publish(_pipeline(3.0), version=v2)
    assert reg.quarantined(v2) is None
    assert reg.load()[1] == v2


def test_watcher_skips_quarantined_version(tmp_path):
    """The poll watcher refuses to deploy a version carrying the BAD
    mark even when CURRENT points straight at it, and deploys it after
    the mark clears."""
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    svc = _service(1, "rollout_watch", version=v1)
    try:
        v2 = reg.publish(_pipeline(3.0))  # CURRENT -> v2
        reg.quarantine(v2, reason="rollout rollback: slo_burn")
        w = RegistryWatcher(svc, reg, poll_seconds=3600.0)
        skips0 = _counter("serve.watch_quarantine_skips")
        w._poll_once()
        assert svc.version == v1  # refused
        assert _counter("serve.watch_quarantine_skips") > skips0
        w._poll_once()  # idempotent: still refused, no crash
        assert svc.version == v1
        reg.clear_quarantine(v2)
        w._poll_once()
        assert svc.version == v2
    finally:
        svc.close()


def test_watcher_guarded_rollout_path(tmp_path):
    """A watcher built with a rollout config canaries new versions
    instead of hard-swapping: a version that fails the judge is rolled
    back + quarantined, and the next poll does not retry it."""
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    svc = _service(1, "rollout_watch_canary", version=v1)
    try:
        cfg = RolloutConfig(
            canary=1.0, min_samples=10_000, decide_s=0.2, insufficient="rollback"
        )
        w = RegistryWatcher(svc, reg, poll_seconds=3600.0, rollout=cfg)
        v2 = reg.publish(_pipeline(3.0))
        rb0 = _counter("serve.watch_rollbacks")
        w._poll_once()
        assert svc.version == v1  # judged insufficient -> rolled back
        assert _counter("serve.watch_rollbacks") > rb0
        assert reg.quarantined(v2) is not None
        assert reg.current() == v1  # rollback restored the pointer
        # even with CURRENT forced back at the condemned version (a
        # crashed deploy, a confused operator) the watcher refuses
        reg.set_current(v2)
        skips0 = _counter("serve.watch_quarantine_skips")
        w._poll_once()
        assert svc.version == v1
        assert _counter("serve.watch_quarantine_skips") > skips0
    finally:
        svc.close()


# ------------------------------------------------------------------ http
def test_http_rollout_endpoints(tmp_path):
    """HTTP parity: GET /rolloutz mirrors rollout_status(), POST
    /rollback walks the swap history (409 with nothing to revert to),
    and POST /swap grows the canary body (400 on a bad config; a
    guarded verdict comes back 200 either way)."""
    import json
    import urllib.error
    import urllib.request

    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish(_pipeline(2.0))
    v2 = reg.publish(_pipeline(3.0), set_current=False)
    with _service(2, "rollout_http", version=v1) as svc:
        with serve_http(svc, port=0, registry=reg) as front:
            base = f"http://127.0.0.1:{front.port}"

            def post(path, body):
                req = urllib.request.Request(
                    base + path, data=json.dumps(body).encode()
                )
                return json.load(urllib.request.urlopen(req, timeout=60))

            rz = json.load(
                urllib.request.urlopen(base + "/rolloutz", timeout=10)
            )
            assert rz["version"] == v1
            assert rz["history"] == [] and rz["prior_versions"] == []
            # nothing to roll back to yet
            with pytest.raises(urllib.error.HTTPError) as err:
                post("/rollback", {})
            assert err.value.code == 409
            # plain swap to v2, then /rollback reverts to v1
            info = post("/swap", {"version": v2})
            assert svc.version == v2 and info["version"] == v2
            assert reg.current() == v2
            info = post("/rollback", {})
            assert info["rolled_back_to"] == v1
            assert info["rolled_back_from"] == v2
            assert svc.version == v1
            assert reg.current() == v1
            # history consumed: a second rollback has nowhere to go
            with pytest.raises(urllib.error.HTTPError) as err:
                post("/rollback", {})
            assert err.value.code == 409
            # canary body: a bad config is a 400, not a 502
            with pytest.raises(urllib.error.HTTPError) as err:
                post("/swap", {"version": v2, "canary": 2.0})
            assert err.value.code == 400
            # guarded swap that rolls back still answers 200 + verdict
            info = post(
                "/swap",
                {
                    "version": v2,
                    "canary": 1.0,
                    "min_samples": 10_000,
                    "decide_s": 0.2,
                    "insufficient": "rollback",
                },
            )
            assert info["verdict"] == "rolled_back"
            assert svc.version == v1
            assert reg.quarantined(v2) is not None
            rz = json.load(
                urllib.request.urlopen(base + "/rolloutz", timeout=10)
            )
            assert rz["history"][-1]["verdict"] == "rolled_back"
            # clear_bad: the operator's explicit override rides /swap
            info = post("/swap", {"version": v2, "clear_bad": True})
            assert svc.version == v2
            assert reg.quarantined(v2) is None


# ------------------------------------------------------------ chaos drill
@pytest.mark.chaos
def test_rollout_chaos_drill(tmp_path):
    """The tier-1 end-to-end drill: tools/chaos.py --workload rollout —
    a bad version canaried under the seeded poison-flood zoo scenario
    is rolled back, quarantined, refused by the watcher, and loses no
    futures (the workload raises on any violated invariant)."""
    from tools.chaos import WORKLOADS

    WORKLOADS["rollout"](str(tmp_path), 1)
