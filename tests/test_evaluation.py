import numpy as np

from keystone_tpu.evaluation import (
    AugmentedExamplesEvaluator,
    BinaryClassifierEvaluator,
    MeanAveragePrecisionEvaluator,
    MulticlassClassifierEvaluator,
)


def test_multiclass_evaluator():
    preds = np.array([0, 1, 2, 1, 0])
    labels = np.array([0, 1, 1, 1, 2])
    m = MulticlassClassifierEvaluator(3).evaluate(preds, labels)
    assert abs(m.accuracy - 3 / 5) < 1e-9
    assert m.confusion_matrix.sum() == 5
    assert m.confusion_matrix[1, 1] == 2  # actual 1 predicted 1
    assert m.confusion_matrix[1, 2] == 1  # actual 1 predicted 2
    assert 0 <= m.macro_f1 <= 1


def test_binary_evaluator():
    preds = np.array([1, 1, 0, 0, 1])
    labels = np.array([1, 0, 0, 1, 1])
    m = BinaryClassifierEvaluator().evaluate(preds, labels)
    assert m.tp == 2 and m.fp == 1 and m.tn == 1 and m.fn == 1
    assert abs(m.precision - 2 / 3) < 1e-9
    assert abs(m.recall - 2 / 3) < 1e-9


def test_map_evaluator_perfect_ranking():
    scores = np.array([[0.9, 0.1], [0.8, 0.2], [0.1, 0.9], [0.2, 0.8]])
    labels = np.array([[1, 0], [1, 0], [0, 1], [0, 1]])
    ap = MeanAveragePrecisionEvaluator(2).evaluate(scores, labels)
    assert abs(ap - 1.0) < 1e-9


def test_map_evaluator_partial():
    scores = np.array([[0.9], [0.8], [0.7]])
    labels = np.array([[0], [1], [1]])
    # ranking: doc0 (neg), doc1 (pos, P=1/2), doc2 (pos, P=2/3)
    ap = MeanAveragePrecisionEvaluator(1).evaluate(scores, labels)
    assert abs(ap - (0.5 + 2 / 3) / 2) < 1e-9


def test_augmented_examples_evaluator():
    # two images, two views each; views disagree, average decides
    scores = np.array(
        [[0.9, 0.1], [0.2, 0.8], [0.1, 0.9], [0.4, 0.6]], np.float64
    )
    ids = np.array([7, 7, 3, 3])
    labels_per_image = np.array([1, 1])  # uniq order: [3, 7]
    m = AugmentedExamplesEvaluator(2).evaluate(scores, ids, labels_per_image)
    # image 3: mean [0.25, 0.75] → 1 ✓; image 7: mean [0.55, 0.45] → 0 ✗
    assert abs(m.accuracy - 0.5) < 1e-9


def test_augmented_examples_evaluator_unsorted_ids():
    # ids occur as img9 first, img1 second; labels in occurrence order
    scores = np.array([[1.0, 0.0], [1.0, 0.0], [0.0, 1.0], [0.0, 1.0]])
    ids = np.array([9, 9, 1, 1])
    labels_occurrence_order = np.array([0, 1])  # img9 -> 0, img1 -> 1
    m = AugmentedExamplesEvaluator(2).evaluate(scores, ids, labels_occurrence_order)
    assert m.accuracy == 1.0


def test_map_evaluator_hand_computed_multiclass():
    """Hand-computed 3-class fixture (VERDICT r2 item 6).

    Class 0, score order d0>d2>d1, labels [1,0,1]:
      rank1 d0 pos P=1/1; rank2 d2 neg; rank3 d1 pos P=2/3
      AP0 = (1 + 2/3)/2 = 5/6
    Class 1, order d1>d0>d3, labels (by doc) d1=0, d0=1, d3=1:
      rank2 d0 pos P=1/2; rank3 d3 pos P=2/3 -> AP1 = (1/2+2/3)/2 = 7/12
    Class 2, order d3>d2, labels d3=1, d2=0, d0/d1 scored lowest (neg):
      rank1 d3 pos P=1 -> AP2 = 1
    mAP = (5/6 + 7/12 + 1)/3 = 29/36
    """
    scores = np.array(
        [
            # class0 class1 class2
            [0.9, 0.5, 0.05],  # d0
            [0.2, 0.8, 0.01],  # d1
            [0.5, 0.0, 0.30],  # d2
            [0.1, 0.4, 0.90],  # d3
        ]
    )
    labels = np.array(
        [
            [1, 1, 0],
            [1, 0, 0],
            [0, 0, 0],
            [0, 1, 1],
        ]
    )
    ap = MeanAveragePrecisionEvaluator(3).evaluate(scores, labels)
    assert abs(ap - 29 / 36) < 1e-9, ap
