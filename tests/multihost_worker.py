"""Worker process for the multi-host integration test (test_multihost.py).

Each of two processes runs this script: initialize the distributed
runtime through keystone_tpu.parallel.multihost, build the hybrid mesh,
feed only this host's slice of a deterministic global dataset, fit the
normal-equations solver, and compare the (replicated) weights against
the exact local solve of the FULL data.  Prints "MULTIHOST_OK" on
success — the parent test asserts it from both processes.

This is the closest single-machine analogue of a 2-host DCN job: two OS
processes, Gloo collectives between them, 4 virtual devices each.
"""

import os
import sys


def main() -> None:
    coordinator, num_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=4"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from keystone_tpu.parallel import multihost, set_mesh

    multihost.initialize(
        coordinator_address=coordinator, num_processes=num_procs, process_id=pid
    )
    assert jax.process_count() == num_procs, jax.process_count()

    import numpy as np

    mesh = multihost.hybrid_mesh(model_parallelism=1)
    set_mesh(mesh)

    # deterministic GLOBAL problem, identical on every host
    rng = np.random.default_rng(0)
    n, d, k = 256, 32, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, k)).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.normal(size=(n, k))).astype(np.float32)

    # each host loads ONLY its slice (the per-host data feeding pattern)
    sl = multihost.process_batch_slice(n)
    data = multihost.make_global_dataset(x[sl], global_n=n)
    labels = multihost.make_global_dataset(y[sl], global_n=n)

    from keystone_tpu.models import LinearMapEstimator

    lam = 0.1
    model = LinearMapEstimator(lam=lam).fit_dataset(data, labels)

    # reference: exact ridge solve of the full data (the reference repo's
    # own "distributed == exact local" golden pattern, across processes)
    xc = x - x.mean(0)
    yc = y - y.mean(0)
    w_ref = np.linalg.solve(
        xc.T @ xc + lam * n * np.eye(d), xc.T @ yc
    )
    got = np.asarray(model.weights)
    err = np.abs(got - w_ref).max()
    assert err < 2e-3, f"weights mismatch: max err {err}"
    print(f"MULTIHOST_OK pid={pid} err={err:.2e}", flush=True)


if __name__ == "__main__":
    main()
