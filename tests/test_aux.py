"""Auxiliary subsystem tests: profiling auto-cache, saved-state reload,
DOT viz, solver checkpointing, multihost helpers, debug, interop."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.workflow import Dataset, Pipeline, Transformer


class Expensive(Transformer):
    """Side-effect execution counter (the reference's fake-node pattern).

    Counts via ``jax.debug.callback`` so every EXECUTION of the compiled
    program bumps the counter — node-level execution runs through a
    jitted wrapper now, where a bare Python increment would fire once at
    trace time regardless of how many times the program runs.  Read the
    count through :func:`expensive_calls` (callbacks land async)."""

    calls = 0

    def __init__(self, tag: str):
        self.tag = tag

    def params(self):
        return (self.tag,)

    @staticmethod
    def _bump():
        Expensive.calls += 1

    def apply_batch(self, xs, mask=None):
        import jax

        jax.debug.callback(Expensive._bump)
        return xs * 2.0


def expensive_calls() -> int:
    """Expensive.calls after flushing pending host callbacks."""
    import jax

    jax.effects_barrier()
    return Expensive.calls


class AddC(Transformer):
    def __init__(self, c):
        self.c = float(c)

    def params(self):
        return (self.c,)

    def apply_batch(self, xs, mask=None):
        return xs + self.c


def test_profiling_collects_node_costs():
    from keystone_tpu.workflow.profiling import profile_graph

    p = Pipeline.gather(
        [Expensive("x") | AddC(1.0), Expensive("x") | AddC(2.0)]
    )
    lazy = p(Dataset(np.ones((64, 8), np.float32)))
    profiles = profile_graph(lazy.graph, sample_size=16)
    assert len(profiles) >= 2
    assert all(pr.output_bytes > 0 for pr in profiles.values())
    assert all(pr.scale >= 1.0 for pr in profiles.values())


def test_profiling_autocache_rule_within_budget():
    from keystone_tpu.workflow.optimizer import EquivalentNodeMergeRule
    from keystone_tpu.workflow.profiling import ProfilingAutoCacheRule
    from keystone_tpu.workflow.transformer import Cacher
    from keystone_tpu.workflow import TransformerOperator

    p = Pipeline.gather([Expensive("x") | AddC(1.0), Expensive("x") | AddC(2.0)])
    lazy = p(Dataset(np.ones((64, 8), np.float32)))
    g = EquivalentNodeMergeRule().apply(lazy.graph)
    g2 = ProfilingAutoCacheRule(budget_bytes=1 << 30, sample_size=16).apply(g)
    cachers = [
        op
        for op in g2.operators.values()
        if isinstance(op, TransformerOperator) and isinstance(op.transformer, Cacher)
    ]
    assert len(cachers) == 1  # the shared Expensive output got pinned


def test_profile_graph_targets_restricts_profiled_nodes():
    """targets= limits profiling to the given nodes (ancestors still
    execute, memoized, to produce their inputs) — the cache rule passes
    the shared set here so the sampling pass doesn't price (or run)
    subgraphs the placement decision never reads."""
    from keystone_tpu.workflow.profiling import profile_graph

    p = Pipeline.gather([Expensive("x") | AddC(1.0), Expensive("x") | AddC(2.0)])
    lazy = p(Dataset(np.ones((64, 8), np.float32)))
    all_profiles = profile_graph(lazy.graph, sample_size=16)
    target = next(iter(all_profiles))
    only = profile_graph(lazy.graph, sample_size=16, targets=frozenset([target]))
    assert set(only) == {target}
    assert only[target].output_bytes == all_profiles[target].output_bytes


def test_profiling_autocache_skips_sampling_without_shared_nodes():
    """A linear pipeline has nothing to place — the rule must return the
    graph untouched WITHOUT running the sampled profiling pass (it was
    ~60% of north-star fit wall-clock before r4's shared-only restriction)."""
    import keystone_tpu.workflow.profiling as prof_mod
    from keystone_tpu.workflow.profiling import ProfilingAutoCacheRule

    calls = {"n": 0}
    orig = prof_mod.profile_graph

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    p = Expensive("lin") | AddC(1.0) | AddC(2.0)
    lazy = p(Dataset(np.ones((32, 4), np.float32)))
    prof_mod.profile_graph = counting
    try:
        g2 = ProfilingAutoCacheRule(budget_bytes=1 << 30, sample_size=16).apply(
            lazy.graph
        )
    finally:
        prof_mod.profile_graph = orig
    assert calls["n"] == 0
    assert g2.operators.keys() == lazy.graph.operators.keys()


def test_profiling_autocache_over_budget_sets_no_memoize():
    from keystone_tpu.workflow.optimizer import EquivalentNodeMergeRule
    from keystone_tpu.workflow.profiling import ProfilingAutoCacheRule
    from keystone_tpu.workflow import GraphExecutor, TransformerOperator

    Expensive.calls = 0
    p = Pipeline.gather([Expensive("x") | AddC(1.0), Expensive("x") | AddC(2.0)])
    lazy = p(Dataset(np.ones((64, 8), np.float32)))
    g = EquivalentNodeMergeRule().apply(lazy.graph)
    g2 = ProfilingAutoCacheRule(budget_bytes=1, sample_size=16).apply(g)
    flagged = [
        op
        for op in g2.operators.values()
        if getattr(op, "no_memoize", False)
    ]
    assert len(flagged) == 1
    # executing recomputes the shared node once per consumer
    Expensive.calls = 0
    ex = GraphExecutor(g2)
    ex.execute(g2.sinks[0])
    assert expensive_calls() == 2


def test_saved_state_roundtrip(tmp_path):
    from keystone_tpu.workflow.optimizer import Optimizer, Once, RuleBatch
    from keystone_tpu.workflow.state import SavedStateLoadRule, save_pipeline_state

    state_dir = str(tmp_path / "state")
    data = Dataset(np.ones((16, 4), np.float32), name="train-data")
    p = Pipeline.of(AddC(1.0)) | AddC(2.0)
    lazy = p(data)
    saved = save_pipeline_state(lazy, state_dir)
    assert saved >= 1

    # a fresh identical pipeline over the SAME named dataset reloads
    Expensive.calls = 0
    data2 = Dataset(np.ones((16, 4), np.float32), name="train-data")
    lazy2 = (Pipeline.of(AddC(1.0)) | AddC(2.0))(data2)
    g = SavedStateLoadRule(state_dir).apply(lazy2.graph)
    from keystone_tpu.workflow import DatasetOperator, GraphExecutor

    ds_ops = [op for op in g.operators.values() if isinstance(op, DatasetOperator)]
    assert len(ds_ops) >= 1
    out = GraphExecutor(g).execute(g.sinks[0])
    np.testing.assert_allclose(out.dataset.numpy(), 4.0)


def test_to_dot():
    from keystone_tpu.workflow.viz import to_dot

    p = AddC(1.0) | AddC(2.0)
    dot = to_dot(p.graph)
    assert dot.startswith("digraph") and "AddC" in dot and "->" in dot


def test_block_ls_fit_checkpointed_resumes(tmp_path):
    from keystone_tpu.models import BlockLeastSquaresEstimator

    rng = np.random.default_rng(0)
    x = rng.normal(size=(48, 6)).astype(np.float32)
    y = rng.normal(size=(48, 2)).astype(np.float32)
    est = BlockLeastSquaresEstimator(block_size=3, num_iter=6, lam=0.1)
    ckpt = str(tmp_path / "ck")
    m1 = est.fit_checkpointed(Dataset(x), Dataset(y), ckpt)
    # resume from final state: must produce identical weights without work
    m2 = est.fit_checkpointed(Dataset(x), Dataset(y), ckpt)
    np.testing.assert_allclose(
        np.asarray(m1.flat_weights), np.asarray(m2.flat_weights), atol=1e-6
    )
    # and equals the un-checkpointed fit
    m3 = est.fit_arrays(x, y)
    np.testing.assert_allclose(
        np.asarray(m1.flat_weights), np.asarray(m3.flat_weights), atol=1e-4
    )
    # partial checkpoint resumes to the same answer as a full run
    import numpy as _np

    with _np.load(os.path.join(ckpt, "bcd_epoch.npz")) as z:
        assert int(z["epoch"]) == 5


def test_multihost_helpers_single_process(mesh):
    from keystone_tpu.parallel import multihost

    m = multihost.hybrid_mesh(model_parallelism=2)
    assert m.shape["data"] * m.shape["model"] == 8
    sl = multihost.process_batch_slice(100)
    assert sl == slice(0, 100)
    d = multihost.make_global_dataset(np.ones((8, 2), np.float32))
    assert d.numpy().shape == (8, 2)


def test_debug_helpers():
    from keystone_tpu.utils.debug import assert_all_finite, checked

    assert_all_finite(np.ones(3))
    with pytest.raises(FloatingPointError):
        assert_all_finite(np.array([1.0, np.nan]))

    def f(x):
        return jnp.log(x)

    import jax

    with pytest.raises(Exception):
        checked(f)(jnp.asarray(-1.0))


def test_interop():
    import torch

    from keystone_tpu.utils.interop import to_jax, to_numpy, to_torch

    t = torch.ones(3, 2)
    j = to_jax(t)
    assert j.shape == (3, 2)
    back = to_torch(j)
    assert back.shape == (3, 2)
    import scipy.sparse as sp

    s = sp.csr_matrix(np.eye(3, dtype=np.float32))
    assert to_jax(s).shape == (3, 3)
    assert to_numpy(t).shape == (3, 2)


def test_ngram_indexer():
    from keystone_tpu.ops.nlp import NGramIndexer

    idx = NGramIndexer()
    k1 = idx.pack(("the", "cat"))
    k2 = idx.pack(("the", "dog"))
    assert k1 != k2
    assert idx.pack(("the", "cat")) == k1  # deterministic
    assert idx.unpack(k1, 2) == ("the", "cat")


def test_image_utils():
    from keystone_tpu.utils.image import crop, flip_horizontal, pixel_stats

    imgs = jnp.asarray(np.arange(2 * 4 * 4 * 3, dtype=np.float32).reshape(2, 4, 4, 3))
    c = crop(imgs, 1, 1, 2, 2)
    assert c.shape == (2, 2, 2, 3)
    f = flip_horizontal(imgs)
    np.testing.assert_allclose(np.asarray(f[:, :, 0]), np.asarray(imgs[:, :, -1]))
    mean, std = pixel_stats(imgs)
    assert mean.shape == (3,)


def test_block_kernel_matrix():
    from keystone_tpu.models import GaussianKernelGenerator
    from keystone_tpu.models.kernel_matrix import BlockKernelMatrix

    rng = np.random.default_rng(0)
    x = rng.normal(size=(40, 5)).astype(np.float32)
    kern = GaussianKernelGenerator(0.3)
    bk = BlockKernelMatrix(kern, x, block_size=16)
    full = np.asarray(kern(jnp.asarray(x), jnp.asarray(x)))
    np.testing.assert_allclose(np.asarray(bk.block(0, 1)), full[:16, 16:32], atol=1e-6)
    np.testing.assert_allclose(np.asarray(bk.column_block(2)), full[:, 32:], atol=1e-6)
    v = rng.normal(size=(40, 2)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(bk.matvec(jnp.asarray(v))), full @ v, atol=1e-4)
    _ = bk.block(0, 1)  # cached path


def test_pipeline_env_state_dir_roundtrip(tmp_path):
    from keystone_tpu.workflow import Pipeline, PipelineEnv
    from keystone_tpu.workflow.state import save_pipeline_state

    state = str(tmp_path / "env-state")
    data = Dataset(np.full((8, 3), 2.0, np.float32), name="env-train")
    pipe = Expensive("env") | AddC(1.0)
    save_pipeline_state(pipe(data), state)
    try:
        PipelineEnv.state_dir = state
        Expensive.calls = 0  # reload must NOT recompute the prefix
        out = (Expensive("env") | AddC(1.0))(
            Dataset(np.full((8, 3), 2.0, np.float32), name="env-train")
        ).get()
        np.testing.assert_allclose(out.numpy(), 5.0)
        assert expensive_calls() == 0
    finally:
        PipelineEnv.state_dir = None


def test_pipeline_env_user_optimizer_not_overwritten(tmp_path):
    from keystone_tpu.workflow import Optimizer, PipelineEnv

    custom = Optimizer([])
    try:
        PipelineEnv.set_optimizer(custom)
        PipelineEnv.state_dir = str(tmp_path)
        assert PipelineEnv.get_optimizer() is custom
    finally:
        PipelineEnv.set_optimizer(None)
        PipelineEnv.state_dir = None


def test_pipeline_env_direct_assignment_honored(tmp_path):
    # assigning the public attribute (without set_optimizer) must survive
    # a later state_dir change — the state-dir wiring only replaces
    # optimizers it built itself
    from keystone_tpu.workflow import Optimizer, PipelineEnv

    custom = Optimizer([])
    try:
        PipelineEnv.optimizer = custom
        PipelineEnv.state_dir = str(tmp_path)
        assert PipelineEnv.get_optimizer() is custom
    finally:
        PipelineEnv.set_optimizer(None)
        PipelineEnv.state_dir = None


def test_cached_fingerprint_invalidates_on_reassignment():
    # a transformer whose weights are swapped must change identity, or
    # CSE/saved-state rules would alias nodes with different weights
    import jax.numpy as jnp

    from keystone_tpu.ops import Convolver

    f1 = jnp.ones((2, 3, 3, 1), jnp.float32)
    f2 = jnp.zeros((2, 3, 3, 1), jnp.float32)
    conv = Convolver(f1)
    fp1 = conv.params()
    conv.filters = f2
    fp2 = conv.params()
    assert fp1 != fp2
    # and same content produces the same fingerprint across instances
    assert Convolver(f1).params() == Convolver(jnp.ones((2, 3, 3, 1))).params()


def test_pipeline_env_inplace_extension_honored(tmp_path):
    # extending the auto-built default in place is a user customization;
    # a later state_dir change must not silently rebuild over it
    from keystone_tpu.workflow import PipelineEnv
    from keystone_tpu.workflow.optimizer import Once, RuleBatch

    try:
        PipelineEnv.set_optimizer(None)
        opt = PipelineEnv.get_optimizer()
        opt.batches.append(RuleBatch("custom", Once(), []))
        PipelineEnv.state_dir = str(tmp_path)
        assert PipelineEnv.get_optimizer() is opt
    finally:
        PipelineEnv.set_optimizer(None)
        PipelineEnv.state_dir = None


def test_hlo_stage_cost_counts_matmul_flops():
    import jax

    from keystone_tpu.workflow.profiling import hlo_stage_cost

    a = jax.ShapeDtypeStruct((256, 128), jnp.float32)
    b = jax.ShapeDtypeStruct((128, 64), jnp.float32)
    cost = hlo_stage_cost(lambda x, y: x @ y, a, b)
    assert cost is not None
    # 2*m*n*k flops, allow XLA accounting slack
    assert cost["flops"] >= 256 * 128 * 64
    assert cost["seconds_est"] > 0


def test_profile_graph_static_cost_ranks_heavier_node_higher():
    from keystone_tpu.workflow import transformer
    from keystone_tpu.workflow.profiling import profile_graph

    big = transformer(lambda x: (x @ jnp.ones((64, 512))) @ jnp.ones((512, 8)))
    small = transformer(lambda x: x[:8] * 2.0)  # per-example, vmapped
    p = Pipeline.gather([Pipeline.of(big), Pipeline.of(small)])
    lazy = p(Dataset(np.ones((2048, 64), np.float32)))
    profiles = profile_graph(lazy.graph, sample_size=16, static_cost=True)
    static = {
        n: pr for n, pr in profiles.items() if pr.hlo_seconds is not None
    }
    assert len(static) >= 2
    times = sorted(pr.hlo_seconds for pr in static.values())
    assert times[-1] > times[0]  # the matmul chain prices above the slice


def test_compilation_cache_enable_and_disable(tmp_path, monkeypatch):
    import jax

    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    monkeypatch.delenv("KEYSTONE_COMPILE_CACHE", raising=False)
    try:
        d = str(tmp_path / "xla-cache")
        got = enable_compilation_cache(d)
        assert got == d and os.path.isdir(d)
        assert jax.config.jax_compilation_cache_dir == d

        monkeypatch.setenv("KEYSTONE_COMPILE_CACHE", "off")
        assert enable_compilation_cache() is None

        monkeypatch.setenv("KEYSTONE_COMPILE_CACHE", str(tmp_path / "env-cache"))
        got = enable_compilation_cache()
        assert got == str(tmp_path / "env-cache") and os.path.isdir(got)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


def test_default_optimizer_uses_profiled_materialization():
    """VERDICT r1 item 8: the HBM-budgeted profiling cache rule is the
    DEFAULT materialization pass, with the budget read from the device."""
    from keystone_tpu.workflow.optimizer import (
        ProfiledMaterializeRule,
        default_optimizer,
    )
    from keystone_tpu.workflow.profiling import device_hbm_budget

    import keystone_tpu.workflow.profiling as prof_mod

    opt = default_optimizer()
    rules = [r for b in opt.batches for r in b.rules]
    assert any(isinstance(r, ProfiledMaterializeRule) for r in rules)
    assert device_hbm_budget() > 0

    # on a shared-prefix graph the default pass must place a Cacher VIA
    # THE PROFILED PATH — the structural fallback also places one, so
    # record that the profiling rule actually ran and did not fall back
    from keystone_tpu.workflow import Cacher, TransformerOperator

    ran = []
    orig = prof_mod.ProfilingAutoCacheRule.apply

    def counting_apply(self, graph):
        out = orig(self, graph)
        ran.append(True)
        return out

    prof_mod.ProfilingAutoCacheRule.apply = counting_apply
    try:
        b1 = Pipeline.of(AddC(1.0)) | AddC(2.0)
        b2 = Pipeline.of(AddC(1.0)) | AddC(3.0)
        p = Pipeline.gather([b1, b2])
        lazy = p(Dataset(np.ones((16, 4), np.float32)))
        g = opt.execute(lazy.graph)
    finally:
        prof_mod.ProfilingAutoCacheRule.apply = orig
    assert ran, "profiled materialization fell back to the structural rule"
    assert any(
        isinstance(op, TransformerOperator) and isinstance(op.transformer, Cacher)
        for op in g.operators.values()
    )


def test_saved_state_orbax_mesh_mismatch_restores_replicated(tmp_path):
    """A prefix saved (mesh-padded) on one mesh must still restore under a
    mesh whose 'data' axis doesn't divide the saved leading dim — via the
    host-restore + re-shard fallback, not silent recompute (ADVICE r1)."""
    import jax

    from keystone_tpu.parallel import default_mesh, use_mesh
    from keystone_tpu.workflow.state import (
        load_dataset_orbax,
        save_dataset_orbax,
    )

    path = str(tmp_path / "mismatch.orbax")
    # n=6 padded for the session mesh (data=4) -> 8 rows
    ds = Dataset(np.arange(6 * 3, dtype=np.float32).reshape(6, 3), n=6)
    save_dataset_orbax(ds, path)
    saved_rows = ds.array.shape[0]

    # ragged dataset: the mask must be re-padded in lockstep with the array
    ragged_path = str(tmp_path / "mismatch-ragged.orbax")
    base = Dataset(np.ones((6, 5, 2), np.float32), n=6)  # padded to 8 rows
    rag = base.with_array(
        base.array, mask=jnp.ones((base.array.shape[0], 5), bool)
    )
    save_dataset_orbax(rag, ragged_path)

    three = default_mesh(jax.devices("cpu")[:3], model_parallelism=1)
    assert saved_rows % 3 != 0  # the mismatch this test is about
    with use_mesh(three):
        restored = load_dataset_orbax(path)
        assert restored.n == 6
        np.testing.assert_allclose(
            restored.numpy(), np.arange(6 * 3, dtype=np.float32).reshape(6, 3)
        )
        # re-sharded for the CURRENT mesh: leading dim divisible by 3
        assert restored.array.shape[0] % 3 == 0

        rrag = load_dataset_orbax(ragged_path)
        assert rrag.mask is not None
        assert rrag.mask.shape[0] == rrag.array.shape[0]  # aligned padding
        assert rrag.array.shape[0] % 3 == 0


def test_saved_state_orbax_backend_roundtrip(tmp_path):
    """Tensorstore-backed stage checkpoints (SURVEY §5): save with
    backend="orbax", reload via the same SavedStateLoadRule."""
    from keystone_tpu.workflow import DatasetOperator, GraphExecutor
    from keystone_tpu.workflow.state import SavedStateLoadRule, save_pipeline_state

    state_dir = str(tmp_path / "orbax-state")
    data = Dataset(np.full((16, 4), 3.0, np.float32), name="orbax-train")
    lazy = (Pipeline.of(AddC(1.0)) | AddC(2.0))(data)
    saved = save_pipeline_state(lazy, state_dir, backend="orbax")
    assert saved >= 1
    assert any(f.endswith(".orbax") for f in os.listdir(state_dir))

    lazy2 = (Pipeline.of(AddC(1.0)) | AddC(2.0))(
        Dataset(np.full((16, 4), 3.0, np.float32), name="orbax-train")
    )
    g = SavedStateLoadRule(state_dir).apply(lazy2.graph)
    assert any(isinstance(op, DatasetOperator) for op in g.operators.values())
    out = GraphExecutor(g).execute(g.sinks[0])
    np.testing.assert_allclose(out.dataset.numpy(), 6.0)

    with pytest.raises(ValueError, match="unknown state backend"):
        save_pipeline_state(lazy, state_dir, backend="bogus")

    # newest save wins: re-saving with npz must remove the orbax sibling
    save_pipeline_state(lazy, state_dir, backend="npz")
    assert not any(f.endswith(".orbax") for f in os.listdir(state_dir))
    assert any(f.endswith(".npz") for f in os.listdir(state_dir))
    g = SavedStateLoadRule(state_dir).apply(lazy2.graph)
    out = GraphExecutor(g).execute(g.sinks[0])
    np.testing.assert_allclose(out.dataset.numpy(), 6.0)


def test_old_pickle_missing_new_attrs_still_applies():
    """Pipelines pickled before smoothing_magnif / sparse_output existed
    must unpickle to the behavior they were fitted with (ADVICE r2)."""
    import numpy as np

    from keystone_tpu.ops.nlp import CommonSparseFeaturesModel, HashingTF
    from keystone_tpu.ops.sift import SIFTExtractor

    # Simulate an old pickle: bypass __init__, drop the new attributes.
    sift = SIFTExtractor.__new__(SIFTExtractor)
    sift.step = 8
    sift.bin_sizes = (4,)
    assert sift.smoothing_magnif == 0.0  # class-level default
    img = np.random.default_rng(0).uniform(size=(1, 32, 32)).astype(np.float32)
    d, m = sift.apply_batch(img)
    assert np.all(np.isfinite(np.asarray(d)))

    csf = CommonSparseFeaturesModel.__new__(CommonSparseFeaturesModel)
    csf.vocab = {"a": 0, "b": 1}
    csf.num_features = 2
    assert csf.sparse_output is False
    row = csf.apply_one({"a": 2.0})
    assert isinstance(row, np.ndarray) and row[0] == 2.0

    tf = HashingTF.__new__(HashingTF)
    tf.num_features = 16
    assert tf.sparse_output is False
    assert isinstance(tf.apply_one({"x": 1.0}), np.ndarray)


def test_from_scipy_rows_width_mismatch_raises():
    import scipy.sparse as sp

    from keystone_tpu.ops.sparse import PaddedSparseRows

    rows = [sp.csr_matrix(([1.0], ([0], [3])), shape=(1, 10))]
    with pytest.raises(ValueError, match="width"):
        PaddedSparseRows.from_scipy_rows(rows, num_features=7)
    # Matching width still fine.
    PaddedSparseRows.from_scipy_rows(rows, num_features=10)
