"""Feature-op tests — hand-computable small inputs and golden values,
mirroring ConvolverSuite / PoolerSuite / PaddedFFTSuite etc. (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops import (
    CenterCornerPatcher,
    ClassLabelIndicators,
    CommonSparseFeatures,
    Convolver,
    CosineRandomFeatures,
    DaisyExtractor,
    FisherVector,
    GMMFisherVectorEstimator,
    GrayScaler,
    HashingTF,
    LCSExtractor,
    LinearRectifier,
    LowerCase,
    MaxClassifier,
    NGramsFeaturizer,
    NormalizeRows,
    PaddedFFT,
    Pooler,
    RandomPatcher,
    RandomSignNode,
    SIFTExtractor,
    SignedHellingerMapper,
    StandardScaler,
    StupidBackoffLM,
    TermFrequency,
    Tokenizer,
    TopKClassifier,
    VectorCombiner,
    VectorSplitter,
    Windower,
)
from keystone_tpu.ops.sift import sift_output_count
from keystone_tpu.workflow import Dataset


def test_cosine_random_features():
    t = CosineRandomFeatures.init(8, 16, gamma=0.5, seed=1)
    x = np.random.default_rng(0).normal(size=(5, 8)).astype(np.float32)
    out = np.asarray(t.apply_batch(jnp.asarray(x)))
    assert out.shape == (5, 16)
    expect = np.cos(x @ np.asarray(t.w).T + np.asarray(t.b))
    np.testing.assert_allclose(out, expect, atol=1e-5)
    assert (out >= -1).all() and (out <= 1).all()


def test_random_sign_and_padded_fft():
    rs = RandomSignNode.init(10, seed=3)
    signs = np.asarray(rs.signs)
    assert set(np.unique(signs)) <= {-1.0, 1.0}
    x = np.random.default_rng(1).normal(size=(4, 10)).astype(np.float32)
    flipped = np.asarray(rs.apply_batch(jnp.asarray(x)))
    np.testing.assert_allclose(flipped, x * signs, atol=1e-6)

    fft = PaddedFFT()
    out = np.asarray(fft.apply_batch(jnp.asarray(x)))
    spec = np.fft.rfft(np.pad(x, ((0, 0), (0, 6))), axis=-1, norm="ortho")  # pad 10->16
    expect = np.concatenate([spec.real, spec.imag], axis=-1)
    np.testing.assert_allclose(out, expect, atol=1e-3)


def test_linear_rectifier_and_hellinger_and_normalize():
    x = jnp.asarray([[-2.0, 0.5, 4.0]])
    assert np.allclose(
        np.asarray(LinearRectifier(0.0, 1.0).apply_batch(x)), [[0.0, 0.0, 3.0]]
    )
    sh = np.asarray(SignedHellingerMapper().apply_batch(x))
    np.testing.assert_allclose(sh, [[-np.sqrt(2), np.sqrt(0.5), 2.0]], atol=1e-6)
    nr = np.asarray(NormalizeRows().apply_batch(x))
    np.testing.assert_allclose(np.linalg.norm(nr, axis=1), [1.0], atol=1e-6)


def test_standard_scaler_matches_numpy():
    rng = np.random.default_rng(2)
    x = rng.normal(3.0, 2.0, size=(37, 5)).astype(np.float32)  # 37: padding case
    model = StandardScaler().fit_dataset(Dataset(x))
    np.testing.assert_allclose(np.asarray(model.mean), x.mean(0), atol=1e-4)
    np.testing.assert_allclose(np.asarray(model.std), x.std(0, ddof=1), atol=1e-4)
    out = np.asarray(model.apply_batch(jnp.asarray(x)))
    np.testing.assert_allclose(out.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(out.std(0, ddof=1), 1.0, atol=1e-3)


def test_convolver_matches_manual():
    rng = np.random.default_rng(3)
    img = rng.normal(size=(1, 5, 5, 2)).astype(np.float32)
    filt = rng.normal(size=(3, 2, 2, 2)).astype(np.float32)  # 3 filters 2x2x2
    out = np.asarray(Convolver(filt).apply_batch(jnp.asarray(img)))
    assert out.shape == (1, 4, 4, 3)
    # manual correlation at (1,2)
    patch = img[0, 1:3, 2:4, :]
    expect = np.array([(patch * filt[f]).sum() for f in range(3)])
    np.testing.assert_allclose(out[0, 1, 2], expect, atol=1e-4)


def test_pooler_sum():
    img = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1))
    out = np.asarray(Pooler(2, 2).apply_batch(img))
    assert out.shape == (1, 2, 2, 1)
    np.testing.assert_allclose(out[0, :, :, 0], [[10.0, 18.0], [42.0, 50.0]])


def test_symmetric_rectifier_doubles_channels():
    from keystone_tpu.ops import SymmetricRectifier

    img = jnp.asarray(np.array([[[[1.0], [-2.0]]]], np.float32))
    out = np.asarray(SymmetricRectifier(alpha=0.5).apply_batch(img))
    assert out.shape == (1, 1, 2, 2)
    np.testing.assert_allclose(out[0, 0], [[0.5, 0.0], [0.0, 1.5]])


def test_windower_matches_manual_slices():
    rng = np.random.default_rng(4)
    img = rng.normal(size=(1, 4, 4, 1)).astype(np.float32)
    out = np.asarray(Windower(2, 2).apply_batch(jnp.asarray(img)))
    assert out.shape == (1, 4, 4)  # 2x2 windows of 2*2*1
    np.testing.assert_allclose(out[0, 0], img[0, 0:2, 0:2, 0].reshape(-1), atol=1e-6)
    np.testing.assert_allclose(out[0, 3], img[0, 2:4, 2:4, 0].reshape(-1), atol=1e-6)


def test_random_patcher_and_center_corner():
    rng = np.random.default_rng(5)
    imgs = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    out = np.asarray(RandomPatcher(4, 3, 3, seed=0).apply_batch(jnp.asarray(imgs)))
    assert out.shape == (8, 27)

    views = np.asarray(
        CenterCornerPatcher(4, 4, horizontal_flips=True).apply_batch(jnp.asarray(imgs))
    )
    assert views.shape == (2, 10, 4, 4, 3)
    np.testing.assert_allclose(views[0, 0], imgs[0, :4, :4, :])  # top-left
    np.testing.assert_allclose(views[0, 5], imgs[0, :4, :4, :][:, ::-1, :])


def test_grayscaler():
    imgs = np.random.default_rng(6).normal(size=(2, 3, 3, 3)).astype(np.float32)
    out = np.asarray(GrayScaler().apply_batch(jnp.asarray(imgs)))
    np.testing.assert_allclose(out, imgs.mean(-1), atol=1e-6)


def test_classifier_heads():
    scores = jnp.asarray([[0.1, 0.9, 0.3], [0.8, 0.2, 0.5]])
    assert np.asarray(MaxClassifier().apply_batch(scores)).tolist() == [1, 0]
    topk = np.asarray(TopKClassifier(2).apply_batch(scores))
    assert topk.tolist() == [[1, 2], [0, 2]]
    ind = np.asarray(ClassLabelIndicators(3).apply_batch(jnp.asarray([0, 2])))
    np.testing.assert_allclose(ind, [[1, -1, -1], [-1, -1, 1]])


def test_vector_splitter_combiner_roundtrip():
    x = jnp.asarray(np.arange(20, dtype=np.float32).reshape(2, 10))
    blocks = VectorSplitter(4).apply_batch(x)
    assert blocks.shape == (2, 3, 4)  # padded to 12
    back = VectorCombiner().apply_batch(blocks)
    np.testing.assert_allclose(np.asarray(back)[:, :10], np.asarray(x))


def test_sift_shapes_and_properties():
    rng = np.random.default_rng(7)
    imgs = rng.normal(size=(2, 32, 32)).astype(np.float32)
    # smoothing off: these pin the unsmoothed descriptor core
    ext = SIFTExtractor(step=4, bin_sizes=(4,), smoothing_magnif=0)
    desc, mask = ext.apply_batch(jnp.asarray(imgs))
    k = sift_output_count(32, 32, 4, (4,))
    assert desc.shape == (2, k, 128)
    assert mask.shape == (2, k)
    d = np.asarray(desc)
    norms = np.linalg.norm(d, axis=-1)
    assert (norms <= 1.01).all()
    assert norms.max() > 0.5  # normalized descriptors on noisy input

    # uniform image → zero gradients → zero descriptors
    flat = np.ones((1, 32, 32), np.float32)
    d0, _ = ext.apply_batch(jnp.asarray(flat))
    assert np.abs(np.asarray(d0)).max() < 1e-6

    # vertical edge: energy concentrates in horizontal-gradient bins
    edge = np.zeros((1, 32, 32), np.float32)
    edge[:, :, 16:] = 1.0
    de, _ = ext.apply_batch(jnp.asarray(edge))
    assert np.abs(np.asarray(de)).max() > 0.1


def test_lcs_constant_image():
    img = np.full((1, 40, 40, 3), 0.7, np.float32)
    desc, mask = LCSExtractor(step=6, subpatch_size=4).apply_batch(jnp.asarray(img))
    d = np.asarray(desc)
    assert d.shape[-1] == 2 * 3 * 16
    means = d.reshape(d.shape[0], d.shape[1], 16, 6)[..., :3]
    stds = d.reshape(d.shape[0], d.shape[1], 16, 6)[..., 3:]
    np.testing.assert_allclose(means, 0.7, atol=1e-5)
    # f32 cancellation in E[x²]−mean² bounds the achievable zero to ~√eps
    np.testing.assert_allclose(stds, 0.0, atol=1e-3)


def test_daisy_shapes():
    rng = np.random.default_rng(8)
    imgs = rng.normal(size=(1, 64, 64)).astype(np.float32)
    ext = DaisyExtractor(step=8, radius=8, rings=2, ring_points=4, orientations=4)
    desc, mask = ext.apply_batch(jnp.asarray(imgs))
    assert desc.shape[-1] == (1 + 2 * 4) * 4
    assert desc.shape[0] == 1 and desc.shape[1] > 0
    # histograms are L2-normalized per block
    d = np.asarray(desc).reshape(1, desc.shape[1], -1, 4)
    norms = np.linalg.norm(d, axis=-1)
    assert (norms <= 1.01).all()


def test_fisher_vector_matches_numpy_reference():
    rng = np.random.default_rng(9)
    k, d, t = 3, 4, 20
    w = np.array([0.5, 0.3, 0.2], np.float32)
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = (0.5 + rng.random((k, d))).astype(np.float32)
    from keystone_tpu.models.gmm import GaussianMixtureModel

    gmm = GaussianMixtureModel(jnp.asarray(w), jnp.asarray(mu), jnp.asarray(var))
    x = rng.normal(size=(1, t, d)).astype(np.float32)
    fv = np.asarray(FisherVector(gmm).apply_batch(jnp.asarray(x))[0])

    # float64 reference
    sigma = np.sqrt(var)
    logp = np.zeros((t, k))
    for j in range(k):
        logp[:, j] = (
            np.log(w[j])
            - 0.5 * np.sum(np.log(2 * np.pi * var[j]))
            - 0.5 * np.sum(((x[0] - mu[j]) / sigma[j]) ** 2, axis=1)
        )
    gamma = np.exp(logp - logp.max(1, keepdims=True))
    gamma /= gamma.sum(1, keepdims=True)
    phi1 = np.zeros((k, d))
    phi2 = np.zeros((k, d))
    for j in range(k):
        z = (x[0] - mu[j]) / sigma[j]
        phi1[j] = (gamma[:, j : j + 1] * z).sum(0) / (t * np.sqrt(w[j]))
        phi2[j] = (gamma[:, j : j + 1] * (z * z - 1)).sum(0) / (t * np.sqrt(2 * w[j]))
    expect = np.concatenate([phi1.ravel(), phi2.ravel()])
    np.testing.assert_allclose(fv, expect, atol=2e-4)


def test_fisher_vector_respects_mask():
    rng = np.random.default_rng(10)
    from keystone_tpu.models.gmm import GaussianMixtureModel

    k, d = 2, 3
    gmm = GaussianMixtureModel(
        jnp.asarray([0.6, 0.4]),
        jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32),
        jnp.ones((k, d), jnp.float32),
    )
    x = rng.normal(size=(1, 10, d)).astype(np.float32)
    mask = np.zeros((1, 10), np.float32)
    mask[:, :6] = 1.0
    fv_masked = np.asarray(
        FisherVector(gmm).apply_batch(jnp.asarray(x), mask=jnp.asarray(mask))
    )
    fv_trunc = np.asarray(FisherVector(gmm).apply_batch(jnp.asarray(x[:, :6])))
    np.testing.assert_allclose(fv_masked, fv_trunc, atol=1e-5)


def test_gmm_fisher_vector_estimator_pipeline():
    rng = np.random.default_rng(11)
    descs = rng.normal(size=(200, 4)).astype(np.float32)
    fv_t = GMMFisherVectorEstimator(k=2, max_iterations=5).fit_arrays(descs)
    out = fv_t.apply_batch(jnp.asarray(rng.normal(size=(3, 17, 4)).astype(np.float32)))
    assert np.asarray(out).shape == (3, 2 * 2 * 4)


def test_nlp_chain():
    docs = ["The cat sat, the cat ran!", "A dog sat."]
    tok = Tokenizer()
    low = LowerCase()
    toks = [tok.apply_one(low.apply_one(d)) for d in docs]
    assert toks[0] == ["the", "cat", "sat", "the", "cat", "ran"]
    ng = NGramsFeaturizer((1, 2))
    grams = ng.apply_one(toks[0])
    assert ("the", "cat") in grams and ("cat",) in grams
    tf = TermFrequency()
    counts = tf.apply_one(grams)
    assert counts[("cat",)] == 2 and counts[("the", "cat")] == 2

    import math

    tf_log = TermFrequency(lambda v: math.log(v + 1))
    assert abs(tf_log.apply_one(grams)[("cat",)] - math.log(3)) < 1e-9


def test_common_sparse_features():
    docs = [
        {("a",): 2.0, ("b",): 1.0},
        {("a",): 1.0, ("c",): 1.0},
        {("a",): 3.0, ("b",): 2.0},
    ]
    model = CommonSparseFeatures(2).fit_arrays(docs)
    assert ("a",) in model.vocab  # highest doc frequency
    rows = model.apply_dataset(Dataset(docs)).numpy()
    assert rows.shape == (3, 2)
    a_col = model.vocab[("a",)]
    np.testing.assert_allclose(rows[:, a_col], [2.0, 1.0, 3.0])


def test_hashing_tf_deterministic():
    h = HashingTF(32)
    r1 = h.apply_one({("x", "y"): 2.0, ("z",): 1.0})
    r2 = h.apply_one({("x", "y"): 2.0, ("z",): 1.0})
    np.testing.assert_allclose(r1, r2)
    assert r1.sum() == 3.0


def test_stupid_backoff():
    counts = {
        ("the",): 10,
        ("cat",): 5,
        ("sat",): 3,
        ("the", "cat"): 4,
        ("cat", "sat"): 2,
    }
    lm = StupidBackoffLM(counts)
    # seen bigram: count(bigram)/context-count("the"->4)
    assert abs(lm.score(("the", "cat")) - 4 / 4) < 1e-9
    # unseen bigram backs off: 0.4 * P(dog) = 0.4 * 0
    assert lm.score(("the", "dog")) == 0.0
    # unseen context backs off to unigram
    assert abs(lm.score(("sat", "cat")) - 0.4 * (5 / 18)) < 1e-9


def test_ragged_flow_sift_to_fv():
    """SIFT → (ragged) → FV through the Dataset/Transformer mask plumbing."""
    rng = np.random.default_rng(12)
    imgs = rng.normal(size=(2, 24, 24)).astype(np.float32)
    ds = Dataset(imgs)
    sift_ds = SIFTExtractor(step=6, bin_sizes=(3,)).apply_dataset(ds)
    assert sift_ds.mask is not None
    fv_est = GMMFisherVectorEstimator(k=2, max_iterations=3)
    from keystone_tpu.ops import ColumnSampler

    sampled = ColumnSampler(8, seed=0).apply_dataset(sift_ds)
    fv_t = fv_est.fit_dataset(sampled)
    fv_ds = fv_t.apply_dataset(sift_ds)
    assert fv_ds.numpy().shape == (2, 2 * 2 * 128)


def test_sift_matches_independent_numpy_reference():
    # independent slow implementation of the same dense-SIFT spec
    # (loops + np.convolve vs the jitted conv program) — the golden-value
    # pattern the reference uses for its image ops (SURVEY §4)
    from keystone_tpu.ops.sift import (
        SIFTExtractor,
        _keypoint_grid,
        _triangular_kernel,
    )

    rng = np.random.default_rng(0)
    h = w = 32
    step, bin_size, o, grid = 4, 4, 8, 4
    img = rng.uniform(0, 1, (h, w)).astype(np.float32)

    # gradients (central differences, zero at borders)
    dy = np.zeros((h, w), np.float32)
    dx = np.zeros((h, w), np.float32)
    dy[1:-1, :] = (img[2:, :] - img[:-2, :]) * 0.5
    dx[:, 1:-1] = (img[:, 2:] - img[:, :-2]) * 0.5
    mag = np.sqrt(dx * dx + dy * dy)
    ang = np.arctan2(dy, dx) % (2 * np.pi)

    # soft orientation binning
    theta = ang * (o / (2 * np.pi))
    lo = np.floor(theta).astype(int) % o
    hi = (lo + 1) % o
    frac = theta - np.floor(theta)
    omap = np.zeros((h, w, o), np.float32)
    for yy in range(h):
        for xx in range(w):
            omap[yy, xx, lo[yy, xx]] += mag[yy, xx] * (1 - frac[yy, xx])
            omap[yy, xx, hi[yy, xx]] += mag[yy, xx] * frac[yy, xx]

    # separable triangular window, SAME padding
    k1 = _triangular_kernel(bin_size)
    sm = np.zeros_like(omap)
    for c in range(o):
        tmp = np.zeros((h, w), np.float32)
        for xx in range(w):
            tmp[:, xx] = np.convolve(omap[:, xx, c], k1, mode="same")
        for yy in range(h):
            sm[yy, :, c] = np.convolve(tmp[yy, :], k1, mode="same")

    ys = _keypoint_grid(h, step, bin_size)
    xs_ = _keypoint_grid(w, step, bin_size)
    offs = ((np.arange(grid) - (grid - 1) / 2.0) * bin_size).astype(int)
    descs = []
    for cy in ys:
        for cx in xs_:
            # canonical (y_bin, x_bin, orientation) feature order — the
            # r5 descriptor-layout contract (ops/sift._DESCRIPTOR_ORDER)
            d = np.stack(
                [sm[cy + oy, cx + ox] for oy in offs for ox in offs]
            ).reshape(-1)
            n1 = max(np.linalg.norm(d), 1e-8)
            d = np.minimum(d / n1, 0.2)
            d = d / max(np.linalg.norm(d), 1e-8)
            descs.append(d)
    ref = np.stack(descs)

    out, mask = SIFTExtractor(
        step=step, bin_sizes=(bin_size,), smoothing_magnif=0
    ).apply_batch(img[None])
    np.testing.assert_allclose(np.asarray(out[0]), ref, atol=2e-5, rtol=2e-4)


def test_pixel_scaler_only_if_integer():
    import jax.numpy as jnp

    from keystone_tpu.ops import PixelScaler

    u8 = np.full((2, 4, 4, 3), 128, np.uint8)
    f01 = np.full((2, 4, 4, 3), 0.5, np.float32)
    guard = PixelScaler(only_if_integer=True)
    np.testing.assert_allclose(np.asarray(guard.apply_batch(u8)), 128 / 255.0)
    # pre-normalized floats pass through unscaled (no silent /255 collapse)
    np.testing.assert_allclose(np.asarray(guard.apply_batch(f01)), 0.5)
    # the default stays unconditional: float [0,255] CSV pixels divide
    np.testing.assert_allclose(
        np.asarray(PixelScaler().apply_batch(f01 * 255.0)), 0.5
    )
    assert guard.params() != PixelScaler().params()  # distinct CSE identity


def test_sift_scale_too_large_for_image_yields_zero_keypoints():
    """A bin size whose support exceeds the image contributes an empty
    descriptor set (VLFeat drops such scales), not a crash."""
    from keystone_tpu.ops import SIFTExtractor
    from keystone_tpu.ops.sift import sift_output_count

    imgs = np.random.default_rng(0).uniform(0, 1, (2, 32, 32)).astype(np.float32)
    d, m = SIFTExtractor(step=4, bin_sizes=(8,)).apply_batch(jnp.asarray(imgs))
    assert d.shape == (2, 0, 128) and m.shape == (2, 0)
    # multi-scale: the feasible scale still contributes
    d2, _ = SIFTExtractor(step=4, bin_sizes=(4, 8)).apply_batch(jnp.asarray(imgs))
    assert d2.shape[1] == sift_output_count(32, 32, 4, (4, 8))
    assert d2.shape[1] == sift_output_count(32, 32, 4, (4,))


def test_sift_per_scale_gaussian_smoothing():
    """VLFeat applies per-scale Gaussian smoothing before gradients
    (σ = √((bin/magnif)² − 0.25), magnif=6 default).  Pin: the σ
    schedule, that smoothing is ON by default and changes descriptors,
    and that it equals blur-then-unsmoothed-extract (self-consistency)."""
    from scipy.ndimage import gaussian_filter

    from keystone_tpu.ops import SIFTExtractor

    ext = SIFTExtractor(step=5, bin_sizes=(4, 8))
    assert ext._sigma(4) == pytest.approx(np.sqrt((4 / 6) ** 2 - 0.25), abs=1e-6)
    assert ext._sigma(8) == pytest.approx(np.sqrt((8 / 6) ** 2 - 0.25), abs=1e-6)
    assert SIFTExtractor(step=5, smoothing_magnif=0)._sigma(4) == 0.0

    rng = np.random.default_rng(5)
    imgs = rng.uniform(0, 1, (2, 40, 40)).astype(np.float32)
    smoothed, _ = SIFTExtractor(step=5, bin_sizes=(8,)).apply_batch(
        jnp.asarray(imgs)
    )
    plain, _ = SIFTExtractor(
        step=5, bin_sizes=(8,), smoothing_magnif=0
    ).apply_batch(jnp.asarray(imgs))
    assert np.abs(np.asarray(smoothed) - np.asarray(plain)).max() > 1e-3

    # self-consistency: smoothing inside == scipy blur outside + no smoothing
    sigma = ext._sigma(8)
    blurred = np.stack(
        [
            gaussian_filter(im, sigma, mode="constant", truncate=3.0)
            for im in imgs
        ]
    ).astype(np.float32)  # mode="constant" = the conv's SAME zero padding
    via_scipy, _ = SIFTExtractor(
        step=5, bin_sizes=(8,), smoothing_magnif=0
    ).apply_batch(jnp.asarray(blurred))
    np.testing.assert_allclose(
        np.asarray(smoothed), np.asarray(via_scipy), atol=2e-3
    )


def test_blur_matmul_matches_conv_and_scipy():
    """The banded-matrix blur (r4 default) must equal the depthwise-conv
    form and scipy's mode='constant' Gaussian — same truncation, same
    zero-padding edge semantics, both axes."""
    from scipy.ndimage import gaussian_filter

    from keystone_tpu.ops.filters import separable_gaussian_blur

    rng = np.random.default_rng(7)
    x = rng.uniform(0, 1, (2, 33, 47, 3)).astype(np.float32)
    for sigma in (0.45, 1.3):
        mm = np.asarray(separable_gaussian_blur(jnp.asarray(x), sigma))
        cv = np.asarray(
            separable_gaussian_blur(jnp.asarray(x), sigma, strategy="conv")
        )
        np.testing.assert_allclose(mm, cv, atol=2e-5)
        sp = np.stack(
            [
                np.stack(
                    [
                        gaussian_filter(
                            x[i, :, :, c], sigma, mode="constant", truncate=3.0
                        )
                        for c in range(3)
                    ],
                    axis=-1,
                )
                for i in range(2)
            ]
        )
        np.testing.assert_allclose(mm, sp, atol=2e-3)


def test_sift_multiscale_concatenates_per_scale_descriptors():
    """Multiple bin sizes (the reference's multi-scale dense SIFT): output
    is the per-scale descriptor sets concatenated along the keypoint axis."""
    from keystone_tpu.ops import SIFTExtractor
    from keystone_tpu.ops.sift import sift_output_count

    rng = np.random.default_rng(3)
    imgs = rng.normal(size=(2, 40, 40)).astype(np.float32)
    multi, m_mask = SIFTExtractor(step=5, bin_sizes=(3, 5)).apply_batch(
        jnp.asarray(imgs)
    )
    k = sift_output_count(40, 40, 5, (3, 5))
    assert multi.shape == (2, k, 128) and m_mask.shape == (2, k)
    s3, _ = SIFTExtractor(step=5, bin_sizes=(3,)).apply_batch(jnp.asarray(imgs))
    s5, _ = SIFTExtractor(step=5, bin_sizes=(5,)).apply_batch(jnp.asarray(imgs))
    np.testing.assert_allclose(
        np.asarray(multi),
        np.concatenate([np.asarray(s3), np.asarray(s5)], axis=1),
        atol=1e-6,
    )


def test_hashing_tf_stable_across_process_hash_seeds():
    """Python's hash(str) is salted per process; HashingTF must not be,
    or saved models score garbage in any other process (--model-path)."""
    import os
    import subprocess
    import sys

    from keystone_tpu.ops import HashingTF

    tf = HashingTF(64)
    here = np.asarray(tf.apply_one({"alpha": 1.0, ("bi", "gram"): 2.0}))
    code = (
        "import numpy as np\n"
        "from keystone_tpu.ops import HashingTF\n"
        "row = HashingTF(64).apply_one({'alpha': 1.0, ('bi', 'gram'): 2.0})\n"
        "print(','.join(str(int(i)) for i in np.nonzero(np.asarray(row))[0]))\n"
    )
    env = dict(
        os.environ,
        PYTHONHASHSEED="12345",  # force a DIFFERENT salt
        JAX_PLATFORMS="cpu",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr[-1000:]
    other = [int(i) for i in out.stdout.strip().split(",")]
    assert sorted(np.nonzero(here)[0].tolist()) == sorted(other)


def test_sift_matmul_windowing_matches_conv():
    """The MXU-matmul windowing path (r3 default) must reproduce the
    depthwise-conv path to fp tolerance across shapes/scales/smoothing."""
    from keystone_tpu.ops.sift import _dsift

    rng = np.random.default_rng(0)
    for hw, step, b, sigma in [(64, 4, 4, 0.0), (48, 6, 4, 0.55), (33, 5, 3, 0.0)]:
        imgs = jnp.asarray(rng.uniform(size=(2, hw, hw)).astype(np.float32))
        conv = np.asarray(_dsift(imgs, step, b, sigma=sigma, windowing="conv"))
        mm = np.asarray(_dsift(imgs, step, b, sigma=sigma, windowing="matmul"))
        assert conv.shape == mm.shape
        np.testing.assert_allclose(mm, conv, atol=1e-6)


def test_sift_old_pickle_defaults_to_conv_windowing():
    from keystone_tpu.ops.sift import SIFTExtractor

    s = SIFTExtractor.__new__(SIFTExtractor)
    assert s.windowing == "conv"  # pre-windowing pickles keep their path
    assert SIFTExtractor().windowing == "matmul"
