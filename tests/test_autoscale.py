"""Autoscaler (serve/autoscale.py) unit coverage: the pure policy
(thresholds, hysteresis, cooldowns, the pool-hit-rate capacity credit),
and the controller loop against an injectable clock + signal source —
no worker processes, no sleeps."""

import pytest

from keystone_tpu.serve.autoscale import (
    AutoscalePolicy,
    Autoscaler,
    Signals,
)

pytestmark = pytest.mark.serve


def sig(
    workers=1,
    queue_depth=0,
    queue_bound=100,
    occupancy=0.0,
    burn_rate=None,
    pool_hit_rate=None,
):
    return Signals(
        workers=workers,
        queue_depth=queue_depth,
        queue_bound=queue_bound,
        occupancy=occupancy,
        burn_rate=burn_rate,
        pool_hit_rate=pool_hit_rate,
    )


# ---------------------------------------------------------------- policy
def test_policy_scales_up_on_queue_pressure():
    p = AutoscalePolicy(min_workers=1, max_workers=4)
    assert p.decide(sig(queue_depth=60), 0, 1e9, 1e9) == "up"


def test_policy_scales_up_on_slo_burn():
    p = AutoscalePolicy(min_workers=1, max_workers=4)
    assert p.decide(sig(burn_rate=2.0), 0, 1e9, 1e9) == "up"


def test_policy_scales_up_on_occupancy():
    p = AutoscalePolicy(min_workers=1, max_workers=4)
    assert p.decide(sig(occupancy=0.95), 0, 1e9, 1e9) == "up"


def test_policy_respects_max_workers_and_up_cooldown():
    p = AutoscalePolicy(min_workers=1, max_workers=2, up_cooldown_s=5.0)
    pressed = sig(workers=2, queue_depth=90)
    assert p.decide(pressed, 0, 1e9, 1e9) is None  # at the ceiling
    fresh = sig(workers=1, queue_depth=90)
    assert p.decide(fresh, 0, 1.0, 1e9) is None  # inside the cooldown
    assert p.decide(fresh, 0, 6.0, 1e9) == "up"


def test_pool_hit_rate_lifts_the_occupancy_bar():
    """A hot shared pool means occupancy overstates marginal cost: the
    same occupancy that scales an unshared fleet up does NOT scale a
    fully-hitting one."""
    p = AutoscalePolicy(
        min_workers=1,
        max_workers=4,
        up_occupancy=0.85,
        pool_occupancy_credit=0.10,
    )
    s = sig(occupancy=0.90)
    assert p.decide(s, 0, 1e9, 1e9) == "up"
    shared = sig(occupancy=0.90, pool_hit_rate=0.9)
    assert p.decide(shared, 0, 1e9, 1e9) is None


def test_policy_scales_down_only_after_hysteresis_and_cooldown():
    p = AutoscalePolicy(
        min_workers=1, max_workers=4, down_ticks=3, down_cooldown_s=10.0
    )
    idle = sig(workers=3, occupancy=0.05, burn_rate=0.0)
    assert p.decide(idle, 0, 1e9, 1e9) is None  # not enough idle ticks
    assert p.decide(idle, 2, 1e9, 5.0) is None  # inside the cooldown
    assert p.decide(idle, 2, 1e9, 20.0) == "down"
    floor = sig(workers=1, occupancy=0.05, burn_rate=0.0)
    assert p.decide(floor, 10, 1e9, 1e9) is None  # never below the floor


def test_window_retune_band():
    p = AutoscalePolicy(
        min_workers=1, max_workers=2, window_min=2, window_max=4
    )
    # maxed out + deep queue: deepen the window
    hot = sig(workers=2, queue_depth=90)
    assert p.window_for(hot, 2) == 3
    assert p.window_for(hot, 4) is None  # at the band's top
    # calm: tighten back
    calm = sig(workers=2, occupancy=0.05)
    assert p.window_for(calm, 4) == 3
    assert p.window_for(calm, 2) is None  # at the band's floor


# ------------------------------------------------------------ controller
class FakeService:
    """The minimal surface Autoscaler touches."""

    name = "fake"
    _closing = False
    _obs_ctx = None
    recorder = None

    def __init__(self, workers=1, window=2):
        self.workers = workers
        self.scaled_to = []
        self.windows = []
        self._pool = self
        self.queue_bound = 100
        self.queue_depth = 0

    # pool surface
    @property
    def size(self):
        return self.workers

    @property
    def window(self):
        return 2

    # service surface
    def scale_to(self, n):
        self.scaled_to.append(n)
        self.workers = n
        return n

    def set_dispatch_window(self, n):
        self.windows.append(n)
        return n

    def occupancy(self):
        return 0.0

    def slo_burn_rate(self):
        return None


def make_scaler(svc, signals, **kw):
    clock_box = [0.0]
    scaler = Autoscaler(
        svc,
        interval_s=1.0,
        clock=lambda: clock_box[0],
        signal_source=signals,
        **kw,
    )
    return scaler, clock_box


def test_tick_scales_up_then_respects_cooldown():
    svc = FakeService(workers=1)
    state = {"s": sig(workers=1, queue_depth=80)}
    scaler, clock = make_scaler(
        svc, lambda: state["s"], min_workers=1, max_workers=3,
        up_cooldown_s=5.0,
    )
    clock[0] = 100.0
    assert scaler.tick() == "up"
    assert svc.scaled_to == [2]
    state["s"] = sig(workers=2, queue_depth=80)
    clock[0] = 102.0  # inside the cooldown: no second spawn storm
    assert scaler.tick() != "up"
    clock[0] = 106.0
    assert scaler.tick() == "up"
    assert svc.scaled_to == [2, 3]


def test_tick_scales_down_after_idle_run():
    svc = FakeService(workers=2)
    state = {"s": sig(workers=2, occupancy=0.01, burn_rate=0.0)}
    scaler, clock = make_scaler(
        svc, lambda: state["s"], min_workers=1, max_workers=3,
        down_ticks=3, down_cooldown_s=0.0,
    )
    clock[0] = 100.0
    results = [scaler.tick() for _ in range(3)]
    assert results[-1] == "down"
    assert svc.scaled_to == [1]


def test_dry_run_records_but_does_not_touch_the_fleet():
    svc = FakeService(workers=1)
    scaler, clock = make_scaler(
        svc,
        lambda: sig(workers=1, queue_depth=80),
        min_workers=1,
        max_workers=3,
        apply=False,
    )
    clock[0] = 50.0
    assert scaler.tick() == "up"
    assert svc.scaled_to == []  # advisor mode: decision only
    assert scaler.status()["last_action"]["action"] == "up"


def test_status_shape():
    svc = FakeService()
    scaler, clock = make_scaler(
        svc, lambda: sig(), min_workers=1, max_workers=2
    )
    scaler.tick()
    st = scaler.status()
    for key in (
        "min_workers",
        "max_workers",
        "ups",
        "downs",
        "window_retunes",
        "last_signals",
    ):
        assert key in st
    assert st["last_signals"]["workers"] == 1


def test_bad_bounds_refused():
    with pytest.raises(ValueError):
        Autoscaler(FakeService(), min_workers=0)
    with pytest.raises(ValueError):
        Autoscaler(FakeService(), min_workers=3, max_workers=2)
