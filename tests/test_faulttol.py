"""Fault injection + recovery (VERDICT r1 item 6; SURVEY §5 "failure
detection / elastic recovery").

The reference inherited fault tolerance from Spark (lineage recompute,
task retry).  The rebuild's decomposition: executor-level stage retry
(GraphExecutor node_retries) + process-restart recovery from durable
state (solver epoch checkpoints, saved pipeline prefixes;
workflow/recovery.py).  The multi-process test here is the real thing:
one of two Gloo-connected processes is killed MID-FIT, both relaunch,
and the fit must resume from the epoch checkpoint and land on exactly
the model an uninterrupted run produces.
"""

import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "faulttol_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(mode, ckpt_dir, n_procs=2):
    coordinator = f"127.0.0.1:{_free_port()}"
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=cwd + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    return [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, str(n_procs), str(pid),
             mode, ckpt_dir],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=cwd,
        )
        for pid in range(n_procs)
    ]


def _drain(procs, timeout=300):
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def test_gloo_process_killed_midfit_recovers_from_checkpoint(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    control_ckpt = str(tmp_path / "control-ckpt")
    os.makedirs(ckpt, exist_ok=True)
    os.makedirs(control_ckpt, exist_ok=True)

    # --- control: uninterrupted 2-process fit; record the model digest
    control = _drain(_launch("control", control_ckpt))
    for rc, out, err in control:
        assert rc == 0, f"control worker failed (rc={rc}):\n{err[-2000:]}"
    control_digest = set(
        re.findall(r"digest=(\w+)", "".join(o for _, o, _ in control))
    )
    assert len(control_digest) == 1  # both processes agree

    # --- crash: process 1 dies (os._exit) before its 4th epoch sweep
    procs = _launch("crash", ckpt)
    rc1 = procs[1].wait(timeout=300)
    assert rc1 == 42, f"expected injected crash rc=42, got {rc1}"
    # the survivor is now blocked in (or erroring out of) a collective
    # whose peer is gone — kill it, as a job scheduler would
    try:
        procs[0].wait(timeout=20)
    except subprocess.TimeoutExpired:
        procs[0].kill()
    procs[0].communicate()
    procs[1].communicate()

    # durable state survived: the last COMPLETED epoch's checkpoint
    assert os.path.exists(os.path.join(ckpt, "bcd_epoch.npz"))
    with np.load(os.path.join(ckpt, "bcd_epoch.npz")) as z:
        assert int(z["epoch"]) >= 1

    # --- resume: relaunch BOTH processes (SPMD jobs restart together);
    # the fit must resume from the checkpoint and match the control model
    resumed = _drain(_launch("resume", ckpt))
    for rc, out, err in resumed:
        assert rc == 0, f"resume worker failed (rc={rc}):\n{err[-2000:]}"
    resumed_out = "".join(o for _, o, _ in resumed)
    resumed_from = [int(e) for e in re.findall(r"RESUMED_FROM (\d+)", resumed_out)]
    assert resumed_from and all(e >= 1 for e in resumed_from), resumed_from
    resumed_digest = set(re.findall(r"digest=(\w+)", resumed_out))
    assert resumed_digest == control_digest, (resumed_digest, control_digest)


def test_gloo_process_killed_mid_sparse_lbfgs_resumes(tmp_path):
    """VERDICT r3 weak-3 + next-4: the sparse L-BFGS fit (the vocab-scale
    text solver) killed mid-fit across 2 Gloo processes resumes from the
    persisted optimizer carry and matches the uninterrupted model."""
    ckpt = str(tmp_path / "ckpt")
    control_ckpt = str(tmp_path / "control-ckpt")
    os.makedirs(ckpt, exist_ok=True)
    os.makedirs(control_ckpt, exist_ok=True)

    control = _drain(_launch("sparse-control", control_ckpt))
    for rc, out, err in control:
        assert rc == 0, f"control worker failed (rc={rc}):\n{err[-2000:]}"
    control_digest = set(
        re.findall(r"digest=(\w+)", "".join(o for _, o, _ in control))
    )
    assert len(control_digest) == 1

    procs = _launch("sparse-crash", ckpt)
    rc1 = procs[1].wait(timeout=300)
    assert rc1 == 42, f"expected injected crash rc=42, got {rc1}"
    try:
        procs[0].wait(timeout=20)
    except subprocess.TimeoutExpired:
        procs[0].kill()
    procs[0].communicate()
    procs[1].communicate()

    # the optimizer carry survived (iterate + s/y history + count)
    assert os.path.exists(os.path.join(ckpt, "lbfgs_sparse.npz"))
    with np.load(os.path.join(ckpt, "lbfgs_sparse.npz")) as z:
        assert int(z["it"]) >= 4
        assert z["s_hist"].ndim == 2  # real history buffers persisted

    resumed = _drain(_launch("sparse-resume", ckpt))
    for rc, out, err in resumed:
        assert rc == 0, f"resume worker failed (rc={rc}):\n{err[-2000:]}"
    resumed_out = "".join(o for _, o, _ in resumed)
    resumed_from = [int(e) for e in re.findall(r"RESUMED_FROM (\d+)", resumed_out)]
    assert resumed_from and all(e >= 4 for e in resumed_from), resumed_from
    resumed_digest = set(re.findall(r"digest=(\w+)", resumed_out))
    assert resumed_digest == control_digest, (resumed_digest, control_digest)


def test_executor_stage_retry_recovers_transient_failure():
    """A stage that fails transiently succeeds under node_retries; with
    retries exhausted the error propagates."""
    import jax.numpy as jnp

    from keystone_tpu.workflow import Dataset, GraphExecutor, Pipeline, Transformer

    class Flaky(Transformer):
        fails = 0
        budget = 0

        def params(self):
            return ()

        def apply_batch(self, xs, mask=None):
            if Flaky.fails < Flaky.budget:
                Flaky.fails += 1
                raise RuntimeError("transient device loss")
            return xs + 1.0

        # keep the failure OUTSIDE jit so it happens per execution
        def apply_dataset(self, ds):
            if Flaky.fails < Flaky.budget:
                Flaky.fails += 1
                raise RuntimeError("transient device loss")
            return ds.with_array(ds.array + 1.0)

    Flaky.fails, Flaky.budget = 0, 2
    lazy = Pipeline.of(Flaky())(Dataset(np.ones((4, 2), np.float32)))
    ex = GraphExecutor(lazy.graph, node_retries=2)
    out = ex.execute(lazy.graph.sinks[0])
    np.testing.assert_allclose(np.asarray(out.dataset.array), 2.0)

    Flaky.fails, Flaky.budget = 0, 3
    lazy = Pipeline.of(Flaky())(Dataset(np.ones((4, 2), np.float32)))
    with pytest.raises(RuntimeError, match="transient"):
        GraphExecutor(lazy.graph, node_retries=2).execute(lazy.graph.sinks[0])

    # and the knob is reachable from the NORMAL pipeline path
    from keystone_tpu.workflow.pipeline import PipelineEnv

    prev = PipelineEnv.node_retries
    PipelineEnv.node_retries = 2
    try:
        Flaky.fails, Flaky.budget = 0, 2
        out = Pipeline.of(Flaky())(Dataset(np.ones((4, 2), np.float32))).get()
        np.testing.assert_allclose(np.asarray(out.array), 2.0)
    finally:
        PipelineEnv.node_retries = prev


def test_stage_retries_env_parsing(monkeypatch):
    """KEYSTONE_STAGE_RETRIES is parsed lazily and tolerantly: malformed
    values warn and resolve to 0 instead of crashing imports; post-import
    changes take effect; PipelineEnv.node_retries overrides."""
    from keystone_tpu.workflow.pipeline import PipelineEnv

    monkeypatch.setattr(PipelineEnv, "node_retries", None)
    monkeypatch.setenv("KEYSTONE_STAGE_RETRIES", "3")
    assert PipelineEnv.stage_retries() == 3
    monkeypatch.setenv("KEYSTONE_STAGE_RETRIES", "two")
    assert PipelineEnv.stage_retries() == 0
    monkeypatch.setenv("KEYSTONE_STAGE_RETRIES", "-4")
    assert PipelineEnv.stage_retries() == 0
    monkeypatch.setattr(PipelineEnv, "node_retries", 5)
    assert PipelineEnv.stage_retries() == 5


def test_gather_and_scatter_host_roundtrip_single_process():
    """gather_to_host / global_from_host: the single-process legs (the
    multi-process legs are exercised by the Gloo fault test)."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.parallel import shard_batch
    from keystone_tpu.parallel.multihost import gather_to_host, global_from_host

    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    sharded = shard_batch(x)
    host = gather_to_host(sharded)
    np.testing.assert_allclose(host, x)
    back = global_from_host(host, sharded.sharding)
    assert isinstance(back, jax.Array)
    np.testing.assert_allclose(np.asarray(back), x)


def test_fit_with_recovery_reuses_saved_featurize_prefix(tmp_path):
    """The composed recovery story: an expensive featurize prefix saved
    via save_pipeline_state is RELOADED (not recomputed) by every fit
    attempt under fit_with_recovery — the Spark lineage-reuse analogue."""
    from test_aux import Expensive, expensive_calls

    from keystone_tpu.models import LinearMapEstimator
    from keystone_tpu.workflow import Dataset, Pipeline, fit_with_recovery
    from keystone_tpu.workflow.state import save_pipeline_state

    state_dir = str(tmp_path / "state")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = rng.normal(size=(32, 2)).astype(np.float32)

    featurizer = Pipeline.of(Expensive("prefix"))
    lazy = featurizer(Dataset(x, name="rec-train"))
    Expensive.calls = 0
    save_pipeline_state(lazy, state_dir)
    assert expensive_calls() >= 1  # materialized once to save

    attempt = {"n": 0}

    def build():
        attempt["n"] += 1
        if attempt["n"] == 1:
            raise RuntimeError("injected pre-fit failure")
        return featurizer.and_then(
            LinearMapEstimator(lam=1e-3),
            Dataset(x, name="rec-train"),
            Dataset(y),
        )

    Expensive.calls = 0
    fitted, attempts = fit_with_recovery(build, state_dir=state_dir, max_restarts=2)
    assert attempts == 1
    # the saved prefix replaced the Expensive node before execution AND
    # before the optimizer's sampling passes: zero re-executions
    assert expensive_calls() == 0, expensive_calls()
    pred = fitted(Dataset(x, name="rec-train")).get().numpy()
    assert np.isfinite(pred).all()


def test_fit_with_recovery_restarts_and_resumes(tmp_path):
    """fit_with_recovery: a build_fn whose first attempt dies mid-fit is
    restarted; the solver's epoch checkpoint makes attempt 2 RESUME (the
    checkpoint's epoch advances, and the final model matches an
    uninterrupted fit)."""
    import jax.numpy as jnp

    import keystone_tpu.models.block_ls as bls
    from keystone_tpu.models import BlockLeastSquaresEstimator
    from keystone_tpu.workflow import Dataset, fit_with_recovery

    rng = np.random.default_rng(0)
    n, d, k = 128, 24, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, k)).astype(np.float32)
    ckpt = str(tmp_path / "solver-ckpt")

    class CheckpointedBLS(BlockLeastSquaresEstimator):
        """Estimator that routes fit through fit_checkpointed."""

        def fit_dataset(self, data, labels=None):
            return self.fit_checkpointed(data, labels, checkpoint_dir=ckpt)

    est = CheckpointedBLS(block_size=8, num_iter=5, lam=1e-3, fit_intercept=False)
    reference = BlockLeastSquaresEstimator(
        block_size=8, num_iter=5, lam=1e-3, fit_intercept=False
    ).fit_arrays(x, y)

    # crash injection: die after 2 epoch sweeps, once
    state = {"sweeps": 0, "crashed": False}
    orig = bls._bcd_epoch

    def flaky_epoch(*args):
        if state["sweeps"] == 2 and not state["crashed"]:
            state["crashed"] = True
            raise RuntimeError("injected mid-fit failure")
        state["sweeps"] += 1
        return orig(*args)

    bls._bcd_epoch = flaky_epoch
    try:
        fitted, attempts = fit_with_recovery(
            lambda: est.with_data(Dataset(x), Dataset(y)),
            max_restarts=1,
        )
    finally:
        bls._bcd_epoch = orig
    assert attempts == 1  # one failure, one successful restart
    # resumed, not recomputed: 2 sweeps before the crash + 3 after
    assert state["sweeps"] == 5
    got = fitted(Dataset(x)).get().numpy()
    want = np.asarray(reference.apply_batch(jnp.asarray(x)))[:n]
    np.testing.assert_allclose(got, want, atol=1e-5)
