"""Out-of-core block solvers: FeatureBlockStore + StreamDataset + OC BCD.

The correctness pattern is the reference's own (SURVEY.md §4): the
out-of-core solver must match the in-memory solve on the same data to
tight tolerance — the disk tier changes WHERE blocks live, not the math.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.models import (
    BlockLeastSquaresEstimator,
    BlockWeightedLeastSquaresEstimator,
)
from keystone_tpu.workflow import Dataset, FeatureBlockStore, StreamDataset
from keystone_tpu.workflow import Pipeline, transformer


def _problem(n=96, d=37, k=5, seed=0, skew=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    if skew:  # imbalanced classes so the weighted path is non-trivial
        probs = np.array([0.6, 0.2, 0.1, 0.06, 0.04])[:k]
        probs = probs / probs.sum()
        lbl = rng.choice(k, size=n, p=probs)
    else:
        lbl = rng.integers(0, k, size=n)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lbl] = 1.0
    return x, y, lbl


# ------------------------------------------------------------------ store


def test_store_roundtrip(tmp_path):
    x = np.arange(60, dtype=np.float32).reshape(10, 6)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=4)
    assert store.num_blocks == 2 and store.n == 10 and store.d == 6
    b0 = store.read_block(0)
    b1 = store.read_block(1)
    np.testing.assert_array_equal(b0, x[:, :4])
    np.testing.assert_array_equal(b1[:, :2], x[:, 4:])
    np.testing.assert_array_equal(b1[:, 2:], 0)  # column padding


def test_store_from_batches_matches_from_array(tmp_path):
    x = np.random.default_rng(1).normal(size=(23, 9)).astype(np.float32)
    s1 = FeatureBlockStore.from_array(str(tmp_path / "a"), x, block_size=4)
    batches = [x[:7], x[7:15], x[15:]]
    s2 = FeatureBlockStore.from_batches(str(tmp_path / "b"), batches, 23, 4)
    for b in range(s1.num_blocks):
        np.testing.assert_array_equal(s1.read_block(b), s2.read_block(b))


def test_store_row_count_mismatch(tmp_path):
    with pytest.raises(ValueError, match="produced"):
        FeatureBlockStore.from_batches(
            str(tmp_path / "c"), [np.zeros((3, 4), np.float32)], 5, 2
        )


def test_store_prefetch_order(tmp_path):
    x = np.random.default_rng(2).normal(size=(8, 12)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "d"), x, block_size=4)
    order = [0, 1, 2, 0, 1, 2]
    seen = [(b, blk.copy()) for b, blk in store.iter_blocks(order)]
    assert [b for b, _ in seen] == order
    for b, blk in seen:
        np.testing.assert_array_equal(blk, store.read_block(b))


# ------------------------------------------------- OC solver == in-memory


@pytest.mark.parametrize("fit_intercept", [True, False])
def test_oc_unweighted_matches_inmemory(tmp_path, fit_intercept):
    x, y, _ = _problem()
    est = BlockLeastSquaresEstimator(
        block_size=16, num_iter=3, lam=1e-2, fit_intercept=fit_intercept
    )
    ref = est.fit_arrays(x, y)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    oc = est.fit_store(store, Dataset(y, n=y.shape[0]))
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-4
    )
    if fit_intercept:
        np.testing.assert_allclose(
            np.asarray(oc.intercept), np.asarray(ref.intercept), atol=2e-4
        )


def test_oc_weighted_matches_inmemory(tmp_path):
    x, y, _ = _problem(skew=True)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=3, lam=1e-2, mixture_weight=0.5
    )
    ref = est.fit_arrays(x, y)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    oc = est.fit_store(store, Dataset(y, n=y.shape[0]))
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(oc.intercept), np.asarray(ref.intercept), atol=2e-4
    )


def test_oc_checkpoint_resume(tmp_path):
    """A fit interrupted between epochs resumes and matches the straight
    run — the coarse fault-tolerance story (SURVEY.md §5)."""
    x, y, _ = _problem(seed=3)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    ckpt = str(tmp_path / "ckpt")
    labels = Dataset(y, n=y.shape[0])
    # run only 2 of 4 epochs (simulated interruption), then resume to 4
    partial = BlockWeightedLeastSquaresEstimator(block_size=16, num_iter=2, lam=1e-2)
    partial.fit_store(store, labels, checkpoint_dir=ckpt)
    full = BlockWeightedLeastSquaresEstimator(block_size=16, num_iter=4, lam=1e-2)
    resumed = full.fit_store(store, labels, checkpoint_dir=ckpt)
    straight = full.fit_store(store, labels)  # no checkpoint
    np.testing.assert_allclose(
        np.asarray(resumed.flat_weights),
        np.asarray(straight.flat_weights),
        atol=2e-4,
    )


# --------------------------------------------------- StreamDataset in DAG


def test_stream_through_pipeline_dag(tmp_path):
    """A StreamDataset flows through transformers and the block solver
    fits out-of-core — the DEFAULT path, not a side API."""
    x, y, lbl = _problem(n=128, d=40, k=4)
    batches = lambda: iter([x[i : i + 32] for i in range(0, 128, 32)])
    stream = StreamDataset(batches, n=128)
    scale = transformer(lambda v: v * 0.5, name="Half")
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=3, lam=1e-3)
    pipe = Pipeline.of(scale).and_then(est, stream, Dataset(y, n=128))
    fitted = pipe.fit()
    pred = fitted(Dataset(x, n=128)).get().numpy()
    # reference: in-memory fit on the same (scaled) features
    ref = est.fit_arrays(x * 0.5, y)
    ref_pred = np.asarray(ref.apply_batch(jnp.asarray(x * 0.5)))
    np.testing.assert_allclose(pred, ref_pred[:128], atol=5e-4)


def test_stream_gather_two_branches():
    """Gather over stream branches zips and concats per batch."""
    x = np.random.default_rng(5).normal(size=(20, 6)).astype(np.float32)
    stream = StreamDataset(lambda: iter([x[:8], x[8:20]]), n=20)
    a = stream.map_batches(lambda v, m: v * 2.0)
    b = stream.map_batches(lambda v, m: v + 1.0)
    gathered = StreamDataset.zip_concat([a, b])
    out = np.concatenate(list(gathered.batches()), axis=0)
    np.testing.assert_allclose(out, np.concatenate([x * 2, x + 1], axis=-1), rtol=1e-6)


def test_stream_materialize_fallback():
    """Consumers without a streaming path still work via .array."""
    x = np.random.default_rng(6).normal(size=(10, 4)).astype(np.float32)
    stream = StreamDataset(lambda: iter([x[:4], x[4:]]), n=10)
    np.testing.assert_allclose(stream.numpy(), x, rtol=1e-6)


def test_oc_checkpoint_fingerprint_sensitive(tmp_path):
    """A checkpoint from different hyperparameters must not be resumed:
    changing mixture_weight (or labels, λ, ...) restarts the fit."""
    x, y, _ = _problem(seed=7, skew=True)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    labels = Dataset(y, n=y.shape[0])
    ckpt = str(tmp_path / "ckpt")
    a = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=2, lam=1e-2, mixture_weight=0.5
    )
    a.fit_store(store, labels, checkpoint_dir=ckpt)  # leaves epoch-1 state
    b = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=2, lam=1e-2, mixture_weight=0.9
    )
    stale_aware = b.fit_store(store, labels, checkpoint_dir=ckpt)
    fresh = b.fit_store(store, labels)
    np.testing.assert_allclose(
        np.asarray(stale_aware.flat_weights),
        np.asarray(fresh.flat_weights),
        atol=2e-4,
    )


def test_stream_fit_cleans_spill(tmp_path):
    x, y, _ = _problem(n=64, d=24, k=3)
    stream = StreamDataset(lambda: iter([x[:32], x[32:]]), n=64)
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=1e-3)
    est.fit_stream_dataset(stream, Dataset(y, n=64), spill_dir=str(tmp_path / "sp"))
    import os

    leftovers = [
        p for p in os.listdir(tmp_path / "sp") if p.startswith("kst_spill_")
    ]
    assert leftovers == []


def test_stream_rejects_one_shot_iterator():
    gen = (np.zeros((2, 3), np.float32) for _ in range(2))
    with pytest.raises(ValueError, match="re-iterable"):
        StreamDataset(gen, n=4)


def test_stream_host_transformer_rejected():
    from keystone_tpu.workflow.transformer import LambdaTransformer

    stream = StreamDataset(lambda: iter([np.zeros((2, 3), np.float32)]), n=2)
    host_t = LambdaTransformer(lambda s: s, name="HostOp", host=True)
    with pytest.raises(TypeError, match="host transformer"):
        host_t.apply_dataset(stream)


# -------------------------------------------------------- bf16 spill tier


def test_store_bf16_roundtrip(tmp_path):
    import ml_dtypes

    x = np.random.default_rng(5).normal(size=(10, 6)).astype(np.float32)
    store = FeatureBlockStore.from_array(
        str(tmp_path / "b"), x, block_size=4, dtype="bfloat16"
    )
    assert store.dtype == "bfloat16"
    b0 = store.read_block(0)
    assert b0.dtype == ml_dtypes.bfloat16
    # values round-trip at bf16 precision (8-bit mantissa)
    np.testing.assert_allclose(
        b0.astype(np.float32), x[:, :4].astype(ml_dtypes.bfloat16).astype(np.float32)
    )
    # half the disk footprint of an f32 store
    f32 = FeatureBlockStore.from_array(str(tmp_path / "f"), x, block_size=4)
    assert store.nbytes() * 2 == f32.nbytes()


def test_store_meta_backcompat_missing_dtype(tmp_path):
    """Stores written before the dtype option must load as float32."""
    import json
    import os

    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=4)
    meta_path = os.path.join(store.directory, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta.pop("dtype")
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    reloaded = FeatureBlockStore(store.directory)
    assert reloaded.dtype == "float32"
    np.testing.assert_array_equal(reloaded.read_block(0), x)


def test_store_invalid_dtype_raises(tmp_path):
    with pytest.raises(ValueError, match="dtype"):
        FeatureBlockStore.create(str(tmp_path / "s"), 4, 4, 2, dtype="float16")


@pytest.mark.parametrize("weighted", [False, True])
def test_oc_bf16_spill_matches_inmemory(tmp_path, weighted):
    """bf16 spill halves sweep IO; the fitted model must still match the
    in-memory f32 fit to bf16-quantization tolerance (weights are O(1),
    bf16 has ~3 decimal digits -> atol ~1e-2 after 3 BCD epochs)."""
    x, y, _ = _problem(seed=7, skew=weighted)
    cls = (
        BlockWeightedLeastSquaresEstimator if weighted else BlockLeastSquaresEstimator
    )
    est = cls(block_size=16, num_iter=3, lam=1e-2)
    ref = est.fit_arrays(x, y)
    store = FeatureBlockStore.from_array(
        str(tmp_path / "s"), x, block_size=16, dtype="bfloat16"
    )
    oc = est.fit_store(store, Dataset(y, n=y.shape[0]))
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-2
    )
    np.testing.assert_allclose(
        np.asarray(oc.intercept), np.asarray(ref.intercept), atol=2e-2
    )


def test_oc_spill_dtype_plumbed_through_stream_fit(tmp_path, monkeypatch):
    """StreamDataset -> fit_stream_dataset spills at the estimator's
    spill_dtype."""
    from keystone_tpu.workflow import blockstore as bs_mod

    seen = []
    orig = bs_mod.FeatureBlockStore.from_batches.__func__

    def spy(cls, directory, batches, n, block_size, dtype="float32"):
        seen.append(dtype)
        return orig(cls, directory, batches, n, block_size, dtype=dtype)

    monkeypatch.setattr(
        bs_mod.FeatureBlockStore, "from_batches", classmethod(spy)
    )
    x, y, _ = _problem(seed=9)
    est = BlockLeastSquaresEstimator(
        block_size=16, num_iter=2, lam=1e-2, spill_dtype="bfloat16"
    )
    stream = StreamDataset([x[:32], x[32:64], x[64:]], n=x.shape[0])
    oc = est.fit_stream_dataset(stream, Dataset(y, n=y.shape[0]))
    assert seen == ["bfloat16"]
    ref = est.fit_arrays(x, y)
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-2
    )


# ------------------------------------------------- prefetch + thread hygiene


def _prefetch_spy(monkeypatch):
    """Record the prefetch depth every iter_blocks call receives."""
    from keystone_tpu.workflow import blockstore as bs_mod

    seen = []
    orig = bs_mod.FeatureBlockStore.iter_blocks

    def spy(self, order, prefetch=2):
        seen.append(prefetch)
        return orig(self, order, prefetch=prefetch)

    monkeypatch.setattr(bs_mod.FeatureBlockStore, "iter_blocks", spy)
    return seen


def test_oc_prefetch_plumbed_explicit(tmp_path, monkeypatch):
    """fit_store(prefetch=) reaches every iter_blocks call of the sweep."""
    seen = _prefetch_spy(monkeypatch)
    x, y, _ = _problem(seed=11)
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=2, lam=1e-2)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    oc = est.fit_store(store, Dataset(y, n=y.shape[0]), prefetch=3)
    assert seen and all(p == 3 for p in seen), seen
    ref = est.fit_arrays(x, y)
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-4
    )


def test_oc_prefetch_env_override(tmp_path, monkeypatch):
    """KEYSTONE_OC_PREFETCH governs the depth when the caller passes
    nothing; an explicit argument still wins over the env."""
    from keystone_tpu.models.block_ls import _oc_prefetch

    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", "5")
    assert _oc_prefetch() == 5
    assert _oc_prefetch(3) == 3

    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", "4")
    seen = _prefetch_spy(monkeypatch)
    x, y, _ = _problem(seed=12)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=1, lam=1e-2, mixture_weight=0.25
    )
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    est.fit_store(store, Dataset(y, n=y.shape[0]))
    assert seen and all(p == 4 for p in seen), seen


@pytest.mark.parametrize("bad", ["junk", "eight", "0", "-3", "100000", "2.5"])
def test_oc_prefetch_rejects_garbage_env(monkeypatch, bad):
    """Garbage KEYSTONE_OC_PREFETCH values used to be silently coerced
    to the default — the operator believed the tuning was in effect
    while the sweep ran at depth 2 (or, for a huge depth, pinned
    n×block_size host blocks until the OOM killer fired).  Now they
    raise a ValueError naming the variable."""
    from keystone_tpu.models.block_ls import _oc_prefetch

    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", bad)
    with pytest.raises(ValueError, match="KEYSTONE_OC_PREFETCH"):
        _oc_prefetch()
    # an explicit caller value is still authoritative over a bad env
    assert _oc_prefetch(3) == 3


def test_oc_prefetch_defaults_and_bounds(monkeypatch):
    from keystone_tpu.models.block_ls import _OC_PREFETCH_MAX, _oc_prefetch

    monkeypatch.delenv("KEYSTONE_OC_PREFETCH", raising=False)
    assert _oc_prefetch() == 2  # unset → the measured default
    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", "")
    assert _oc_prefetch() == 2  # empty string counts as unset
    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", str(_OC_PREFETCH_MAX))
    assert _oc_prefetch() == _OC_PREFETCH_MAX  # inclusive upper bound
    # the explicit fit argument rides the SAME bound as the env var —
    # fit_store(prefetch=100000) is the identical OOM footgun
    monkeypatch.delenv("KEYSTONE_OC_PREFETCH", raising=False)
    with pytest.raises(ValueError, match="prefetch=100000"):
        _oc_prefetch(100000)
    with pytest.raises(ValueError, match="prefetch=0"):
        _oc_prefetch(0)


def test_oc_row_mismatch_raises_before_sweep(tmp_path):
    """The hoisted row-count validation: a label array whose padded rows
    cannot match the staged store blocks fails up front (once), not from
    inside the per-(epoch, block) hot loop."""
    from keystone_tpu.models.block_ls import _oc_bcd_fit

    x, y, _ = _problem(seed=13)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    y_padded = np.pad(y, ((0, 4), (0, 0)))  # 4 extra pad rows vs the store
    alpha = (np.arange(y_padded.shape[0]) < y.shape[0]).astype(np.float32)
    with pytest.raises(ValueError, match="store rows pad to"):
        _oc_bcd_fit(
            store,
            jnp.asarray(y_padded),
            jnp.asarray(alpha),
            float(y.shape[0]),
            1e-2,
            1,
            False,
        )


# ------------------------------------------- async device feed + donation


def test_iter_device_blocks_order_and_values(tmp_path):
    """The staged feed yields the same (index, block) sequence as the
    host iterator, cast to f32 on device (bf16 stores included)."""
    import ml_dtypes

    x = np.random.default_rng(21).normal(size=(12, 20)).astype(np.float32)
    for dtype in ("float32", "bfloat16"):
        store = FeatureBlockStore.from_array(
            str(tmp_path / dtype), x, block_size=8, dtype=dtype
        )
        order = [0, 2, 1, 0]
        seen = list(store.iter_device_blocks(order, prefetch=2))
        assert [b for b, _ in seen] == order
        for b, dev in seen:
            assert dev.dtype == jnp.float32
            want = np.asarray(store.read_block(b), np.float32)
            if dtype == "bfloat16":
                want = x[:, b * 8 : (b + 1) * 8].astype(
                    ml_dtypes.bfloat16
                ).astype(np.float32)
                want = np.pad(want, ((0, 0), (0, 8 - want.shape[1])))
            np.testing.assert_allclose(np.asarray(dev), want)


def test_iter_device_blocks_keeps_blocks_in_flight(tmp_path):
    """The overlap pin: when the consumer takes block b, the feed has
    already DISPATCHED the staging of at least one later block — the
    double-buffering that lets transfer b+1 overlap compute b."""
    x = np.random.default_rng(22).normal(size=(8, 40)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=8)
    staged_at_yield = []
    staged = []

    def spy_stage(blk):
        staged.append(len(staged))
        return jnp.asarray(blk)

    gen = store.iter_device_blocks(range(5), prefetch=2, stage=spy_stage)
    for i, (b, dev) in enumerate(gen):
        staged_at_yield.append(len(staged))
    # at the first yield, ≥ 2 blocks were already staged (the in-flight
    # window); every later yield keeps ≥ 1 block ahead until the tail
    assert staged_at_yield[0] >= 2, staged_at_yield
    assert all(
        s > i + 1 for i, s in enumerate(staged_at_yield[:-2])
    ), staged_at_yield


def test_iter_device_blocks_bounds_inflight_window(tmp_path):
    """Backpressure: the feed never runs more than `window` staged
    blocks ahead of the consumer (pinned host buffers stay bounded)."""
    x = np.random.default_rng(23).normal(size=(8, 80)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=8)
    staged = []

    def spy_stage(blk):
        staged.append(1)
        return jnp.asarray(blk)

    consumed = 0
    for b, dev in store.iter_device_blocks(
        range(10), prefetch=2, stage=spy_stage, window=2
    ):
        consumed += 1
        assert len(staged) - consumed <= 2, (len(staged), consumed)


def test_iter_blocks_error_carries_block_index(tmp_path, monkeypatch):
    """A failing read mid-sweep must say WHICH block died — and keep its
    exception type (retry/except dispatch downstream keys on it)."""
    from keystone_tpu.utils.durable import CorruptStateError

    x = np.random.default_rng(24).normal(size=(8, 24)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=8)
    orig = FeatureBlockStore.read_block

    def failing(self, b):
        if b == 2:
            raise CorruptStateError("checksum mismatch")
        return orig(self, b)

    monkeypatch.setattr(FeatureBlockStore, "read_block", failing)
    with pytest.raises(CorruptStateError, match="block 2") as ei:
        list(store.iter_blocks([0, 1, 2]))
    assert "checksum mismatch" in str(ei.value)


def test_iter_blocks_oserror_carries_block_index(tmp_path, monkeypatch):
    """OSError is the primary disk-failure class and renders str() from
    errno/strerror, not args — the block tag must land on strerror (so
    the operator sees it) while args stay (errno, strerror) shaped (so
    cross-process reconstruction is not corrupted)."""
    import errno

    x = np.random.default_rng(24).normal(size=(8, 24)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=8)
    orig = FeatureBlockStore.read_block

    def failing(self, b):
        if b == 1:
            raise FileNotFoundError(
                errno.ENOENT, "No such file or directory", "blk_00001.bin"
            )
        return orig(self, b)

    monkeypatch.setattr(FeatureBlockStore, "read_block", failing)
    with pytest.raises(FileNotFoundError, match="block 1") as ei:
        list(store.iter_blocks([0, 1, 2]))
    e = ei.value
    assert "No such file" in str(e)
    assert e.errno == errno.ENOENT  # reconstruction fields intact
    assert e.args[0] == errno.ENOENT
    assert e.filename == "blk_00001.bin"


def test_oc_block_step_donates_carry(tmp_path):
    """The donation pin: the carried (p, w_b) buffers are CONSUMED by
    the step (is_deleted under live references — refcount alone could
    never do that), so the epoch loop cannot grow live device state."""
    import jax

    from keystone_tpu.models.block_ls import _oc_block_step

    n, bs, k = 16, 8, 3
    rng = np.random.default_rng(25)
    a = jnp.asarray(rng.normal(size=(n, bs)).astype(np.float32))
    xm_b = jnp.zeros((bs,), jnp.float32)
    yc = jnp.asarray(rng.normal(size=(n, k)).astype(np.float32))
    sa = jnp.ones((n,), jnp.float32)
    row_ok = jnp.ones((n,), jnp.float32)
    p = jnp.zeros((n, k), jnp.float32)
    wb = jnp.zeros((bs, k), jnp.float32)
    wb2, p2, tick = _oc_block_step(
        a, xm_b, yc, sa, row_ok, p, wb, jnp.float32(0.1)
    )
    jax.block_until_ready(p2)
    assert p.is_deleted() and wb.is_deleted()
    assert not yc.is_deleted() and not a.is_deleted()
    # the tick (the sweep's flow-control handle) is NOT donated: it must
    # stay waitable after later steps consume the real outputs
    wb3, p3, _ = _oc_block_step(
        a, xm_b, yc, sa, row_ok, p2, wb2, jnp.float32(0.1)
    )
    assert not tick.is_deleted()
    jax.block_until_ready(tick)

    # the live-buffer pin: repeated steps do not accumulate device arrays
    import gc

    gc.collect()
    baseline = len(jax.live_arrays())
    for _ in range(4):
        wb3, p3, tick = _oc_block_step(
            a, xm_b, yc, sa, row_ok, p3, wb3, jnp.float32(0.1)
        )
    jax.block_until_ready(p3)
    del tick
    gc.collect()
    assert len(jax.live_arrays()) <= baseline + 1  # no per-epoch growth


def test_bcd_epoch_donates_carry():
    import jax

    from keystone_tpu.models.block_ls import _bcd_epoch, blockify

    rng = np.random.default_rng(26)
    x = rng.normal(size=(16, 12)).astype(np.float32)
    y = rng.normal(size=(16, 3)).astype(np.float32)
    xb = blockify(jnp.asarray(x), 8)
    w = jnp.zeros((xb.shape[0], 8, 3), jnp.float32)
    p = jnp.zeros((16, 3), jnp.float32)
    w2, p2 = _bcd_epoch(xb, jnp.asarray(y), jnp.float32(16.0), 1e-3, w, p)
    jax.block_until_ready(w2)
    assert w.is_deleted() and p.is_deleted()
    assert not xb.is_deleted()


def test_lbfgs_chunk_donates_carry(tmp_path):
    """The resumable L-BFGS driver's scan carry is donated between
    chunks: all carry leaves are consumed, so the 2·m weight-sized
    history buffers never exist twice across a chunk boundary."""
    import jax

    from keystone_tpu.models.lbfgs import lbfgs_minimize_resumable

    rng = np.random.default_rng(27)
    x = jnp.asarray(rng.normal(size=(32, 6)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(32, 2)).astype(np.float32))

    captured = []

    def save_cb(it, carry):
        captured.append(tuple(carry))

    def vag(data, w):
        xd, yd = data
        r = xd @ w - yd
        return 0.5 * jnp.vdot(r, r), xd.T @ r

    w = lbfgs_minimize_resumable(
        vag,
        (x, y),
        jnp.zeros((6, 2), jnp.float32),
        max_iter=6,
        history=3,
        checkpoint_every=3,
        save_cb=save_cb,
    )
    jax.block_until_ready(w)
    assert len(captured) == 2
    # the first chunk's carry was donated INTO the second chunk
    assert all(leaf.is_deleted() for leaf in captured[0])
    # the final carry is live (its iterate was just returned)
    assert not captured[1][0].is_deleted()


def test_oc_fit_dataflow_in_obs_summary(tmp_path):
    """An out-of-core fit under a run ledger reports the dataflow
    accounts (device-busy + transfer seconds) the bench artifact embeds."""
    from keystone_tpu.obs import ledger, metrics
    from tools.obs_report import summarize

    metrics.REGISTRY.reset()
    x, y, _ = _problem(seed=31)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    led = ledger.start_run(str(tmp_path / "obs"))
    try:
        est = BlockLeastSquaresEstimator(block_size=16, num_iter=2, lam=1e-2)
        est.fit_store(store, Dataset(y, n=y.shape[0]))
        path = led.path
    finally:
        ledger.stop_run()
    s = summarize(path)
    df = s["dataflow"]
    assert df["device_busy_seconds"] > 0
    assert df["transfer_seconds"] > 0
    assert 0 < df["device_busy_fraction"] or df["device_busy_fraction"] == 0


def test_iter_blocks_close_joins_producer(tmp_path):
    """Abandoning the generator mid-sweep must terminate the prefetch
    thread promptly (releasing its parked in-flight block), not leave a
    parked daemon thread behind."""
    import threading
    import time

    def prefetch_threads():
        return [
            t
            for t in threading.enumerate()
            if t.name == "blockstore-prefetch" and t.is_alive()
        ]

    x = np.random.default_rng(3).normal(size=(16, 24)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=4)
    assert not prefetch_threads()
    order = list(range(store.num_blocks)) * 50  # long sweep, tiny consumer
    gen = store.iter_blocks(order, prefetch=2)
    b, blk = next(gen)
    assert b == order[0]
    gen.close()  # consumer abandons the sweep
    deadline = time.monotonic() + 15.0
    while prefetch_threads() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not prefetch_threads(), "prefetch thread leaked after close()"
