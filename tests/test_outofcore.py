"""Out-of-core block solvers: FeatureBlockStore + StreamDataset + OC BCD.

The correctness pattern is the reference's own (SURVEY.md §4): the
out-of-core solver must match the in-memory solve on the same data to
tight tolerance — the disk tier changes WHERE blocks live, not the math.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.models import (
    BlockLeastSquaresEstimator,
    BlockWeightedLeastSquaresEstimator,
)
from keystone_tpu.workflow import Dataset, FeatureBlockStore, StreamDataset
from keystone_tpu.workflow import Pipeline, transformer


def _problem(n=96, d=37, k=5, seed=0, skew=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    if skew:  # imbalanced classes so the weighted path is non-trivial
        probs = np.array([0.6, 0.2, 0.1, 0.06, 0.04])[:k]
        probs = probs / probs.sum()
        lbl = rng.choice(k, size=n, p=probs)
    else:
        lbl = rng.integers(0, k, size=n)
    y = -np.ones((n, k), np.float32)
    y[np.arange(n), lbl] = 1.0
    return x, y, lbl


# ------------------------------------------------------------------ store


def test_store_roundtrip(tmp_path):
    x = np.arange(60, dtype=np.float32).reshape(10, 6)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=4)
    assert store.num_blocks == 2 and store.n == 10 and store.d == 6
    b0 = store.read_block(0)
    b1 = store.read_block(1)
    np.testing.assert_array_equal(b0, x[:, :4])
    np.testing.assert_array_equal(b1[:, :2], x[:, 4:])
    np.testing.assert_array_equal(b1[:, 2:], 0)  # column padding


def test_store_from_batches_matches_from_array(tmp_path):
    x = np.random.default_rng(1).normal(size=(23, 9)).astype(np.float32)
    s1 = FeatureBlockStore.from_array(str(tmp_path / "a"), x, block_size=4)
    batches = [x[:7], x[7:15], x[15:]]
    s2 = FeatureBlockStore.from_batches(str(tmp_path / "b"), batches, 23, 4)
    for b in range(s1.num_blocks):
        np.testing.assert_array_equal(s1.read_block(b), s2.read_block(b))


def test_store_row_count_mismatch(tmp_path):
    with pytest.raises(ValueError, match="produced"):
        FeatureBlockStore.from_batches(
            str(tmp_path / "c"), [np.zeros((3, 4), np.float32)], 5, 2
        )


def test_store_prefetch_order(tmp_path):
    x = np.random.default_rng(2).normal(size=(8, 12)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "d"), x, block_size=4)
    order = [0, 1, 2, 0, 1, 2]
    seen = [(b, blk.copy()) for b, blk in store.iter_blocks(order)]
    assert [b for b, _ in seen] == order
    for b, blk in seen:
        np.testing.assert_array_equal(blk, store.read_block(b))


# ------------------------------------------------- OC solver == in-memory


@pytest.mark.parametrize("fit_intercept", [True, False])
def test_oc_unweighted_matches_inmemory(tmp_path, fit_intercept):
    x, y, _ = _problem()
    est = BlockLeastSquaresEstimator(
        block_size=16, num_iter=3, lam=1e-2, fit_intercept=fit_intercept
    )
    ref = est.fit_arrays(x, y)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    oc = est.fit_store(store, Dataset(y, n=y.shape[0]))
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-4
    )
    if fit_intercept:
        np.testing.assert_allclose(
            np.asarray(oc.intercept), np.asarray(ref.intercept), atol=2e-4
        )


def test_oc_weighted_matches_inmemory(tmp_path):
    x, y, _ = _problem(skew=True)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=3, lam=1e-2, mixture_weight=0.5
    )
    ref = est.fit_arrays(x, y)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    oc = est.fit_store(store, Dataset(y, n=y.shape[0]))
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(oc.intercept), np.asarray(ref.intercept), atol=2e-4
    )


def test_oc_checkpoint_resume(tmp_path):
    """A fit interrupted between epochs resumes and matches the straight
    run — the coarse fault-tolerance story (SURVEY.md §5)."""
    x, y, _ = _problem(seed=3)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    ckpt = str(tmp_path / "ckpt")
    labels = Dataset(y, n=y.shape[0])
    # run only 2 of 4 epochs (simulated interruption), then resume to 4
    partial = BlockWeightedLeastSquaresEstimator(block_size=16, num_iter=2, lam=1e-2)
    partial.fit_store(store, labels, checkpoint_dir=ckpt)
    full = BlockWeightedLeastSquaresEstimator(block_size=16, num_iter=4, lam=1e-2)
    resumed = full.fit_store(store, labels, checkpoint_dir=ckpt)
    straight = full.fit_store(store, labels)  # no checkpoint
    np.testing.assert_allclose(
        np.asarray(resumed.flat_weights),
        np.asarray(straight.flat_weights),
        atol=2e-4,
    )


# --------------------------------------------------- StreamDataset in DAG


def test_stream_through_pipeline_dag(tmp_path):
    """A StreamDataset flows through transformers and the block solver
    fits out-of-core — the DEFAULT path, not a side API."""
    x, y, lbl = _problem(n=128, d=40, k=4)
    batches = lambda: iter([x[i : i + 32] for i in range(0, 128, 32)])
    stream = StreamDataset(batches, n=128)
    scale = transformer(lambda v: v * 0.5, name="Half")
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=3, lam=1e-3)
    pipe = Pipeline.of(scale).and_then(est, stream, Dataset(y, n=128))
    fitted = pipe.fit()
    pred = fitted(Dataset(x, n=128)).get().numpy()
    # reference: in-memory fit on the same (scaled) features
    ref = est.fit_arrays(x * 0.5, y)
    ref_pred = np.asarray(ref.apply_batch(jnp.asarray(x * 0.5)))
    np.testing.assert_allclose(pred, ref_pred[:128], atol=5e-4)


def test_stream_gather_two_branches():
    """Gather over stream branches zips and concats per batch."""
    x = np.random.default_rng(5).normal(size=(20, 6)).astype(np.float32)
    stream = StreamDataset(lambda: iter([x[:8], x[8:20]]), n=20)
    a = stream.map_batches(lambda v, m: v * 2.0)
    b = stream.map_batches(lambda v, m: v + 1.0)
    gathered = StreamDataset.zip_concat([a, b])
    out = np.concatenate(list(gathered.batches()), axis=0)
    np.testing.assert_allclose(out, np.concatenate([x * 2, x + 1], axis=-1), rtol=1e-6)


def test_stream_materialize_fallback():
    """Consumers without a streaming path still work via .array."""
    x = np.random.default_rng(6).normal(size=(10, 4)).astype(np.float32)
    stream = StreamDataset(lambda: iter([x[:4], x[4:]]), n=10)
    np.testing.assert_allclose(stream.numpy(), x, rtol=1e-6)


def test_oc_checkpoint_fingerprint_sensitive(tmp_path):
    """A checkpoint from different hyperparameters must not be resumed:
    changing mixture_weight (or labels, λ, ...) restarts the fit."""
    x, y, _ = _problem(seed=7, skew=True)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    labels = Dataset(y, n=y.shape[0])
    ckpt = str(tmp_path / "ckpt")
    a = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=2, lam=1e-2, mixture_weight=0.5
    )
    a.fit_store(store, labels, checkpoint_dir=ckpt)  # leaves epoch-1 state
    b = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=2, lam=1e-2, mixture_weight=0.9
    )
    stale_aware = b.fit_store(store, labels, checkpoint_dir=ckpt)
    fresh = b.fit_store(store, labels)
    np.testing.assert_allclose(
        np.asarray(stale_aware.flat_weights),
        np.asarray(fresh.flat_weights),
        atol=2e-4,
    )


def test_stream_fit_cleans_spill(tmp_path):
    x, y, _ = _problem(n=64, d=24, k=3)
    stream = StreamDataset(lambda: iter([x[:32], x[32:]]), n=64)
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=1e-3)
    est.fit_stream_dataset(stream, Dataset(y, n=64), spill_dir=str(tmp_path / "sp"))
    import os

    leftovers = [
        p for p in os.listdir(tmp_path / "sp") if p.startswith("kst_spill_")
    ]
    assert leftovers == []


def test_stream_rejects_one_shot_iterator():
    gen = (np.zeros((2, 3), np.float32) for _ in range(2))
    with pytest.raises(ValueError, match="re-iterable"):
        StreamDataset(gen, n=4)


def test_stream_host_transformer_rejected():
    from keystone_tpu.workflow.transformer import LambdaTransformer

    stream = StreamDataset(lambda: iter([np.zeros((2, 3), np.float32)]), n=2)
    host_t = LambdaTransformer(lambda s: s, name="HostOp", host=True)
    with pytest.raises(TypeError, match="host transformer"):
        host_t.apply_dataset(stream)


# -------------------------------------------------------- bf16 spill tier


def test_store_bf16_roundtrip(tmp_path):
    import ml_dtypes

    x = np.random.default_rng(5).normal(size=(10, 6)).astype(np.float32)
    store = FeatureBlockStore.from_array(
        str(tmp_path / "b"), x, block_size=4, dtype="bfloat16"
    )
    assert store.dtype == "bfloat16"
    b0 = store.read_block(0)
    assert b0.dtype == ml_dtypes.bfloat16
    # values round-trip at bf16 precision (8-bit mantissa)
    np.testing.assert_allclose(
        b0.astype(np.float32), x[:, :4].astype(ml_dtypes.bfloat16).astype(np.float32)
    )
    # half the disk footprint of an f32 store
    f32 = FeatureBlockStore.from_array(str(tmp_path / "f"), x, block_size=4)
    assert store.nbytes() * 2 == f32.nbytes()


def test_store_meta_backcompat_missing_dtype(tmp_path):
    """Stores written before the dtype option must load as float32."""
    import json
    import os

    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=4)
    meta_path = os.path.join(store.directory, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta.pop("dtype")
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    reloaded = FeatureBlockStore(store.directory)
    assert reloaded.dtype == "float32"
    np.testing.assert_array_equal(reloaded.read_block(0), x)


def test_store_invalid_dtype_raises(tmp_path):
    with pytest.raises(ValueError, match="dtype"):
        FeatureBlockStore.create(str(tmp_path / "s"), 4, 4, 2, dtype="float16")


@pytest.mark.parametrize("weighted", [False, True])
def test_oc_bf16_spill_matches_inmemory(tmp_path, weighted):
    """bf16 spill halves sweep IO; the fitted model must still match the
    in-memory f32 fit to bf16-quantization tolerance (weights are O(1),
    bf16 has ~3 decimal digits -> atol ~1e-2 after 3 BCD epochs)."""
    x, y, _ = _problem(seed=7, skew=weighted)
    cls = (
        BlockWeightedLeastSquaresEstimator if weighted else BlockLeastSquaresEstimator
    )
    est = cls(block_size=16, num_iter=3, lam=1e-2)
    ref = est.fit_arrays(x, y)
    store = FeatureBlockStore.from_array(
        str(tmp_path / "s"), x, block_size=16, dtype="bfloat16"
    )
    oc = est.fit_store(store, Dataset(y, n=y.shape[0]))
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-2
    )
    np.testing.assert_allclose(
        np.asarray(oc.intercept), np.asarray(ref.intercept), atol=2e-2
    )


def test_oc_spill_dtype_plumbed_through_stream_fit(tmp_path, monkeypatch):
    """StreamDataset -> fit_stream_dataset spills at the estimator's
    spill_dtype."""
    from keystone_tpu.workflow import blockstore as bs_mod

    seen = []
    orig = bs_mod.FeatureBlockStore.from_batches.__func__

    def spy(cls, directory, batches, n, block_size, dtype="float32"):
        seen.append(dtype)
        return orig(cls, directory, batches, n, block_size, dtype=dtype)

    monkeypatch.setattr(
        bs_mod.FeatureBlockStore, "from_batches", classmethod(spy)
    )
    x, y, _ = _problem(seed=9)
    est = BlockLeastSquaresEstimator(
        block_size=16, num_iter=2, lam=1e-2, spill_dtype="bfloat16"
    )
    stream = StreamDataset([x[:32], x[32:64], x[64:]], n=x.shape[0])
    oc = est.fit_stream_dataset(stream, Dataset(y, n=y.shape[0]))
    assert seen == ["bfloat16"]
    ref = est.fit_arrays(x, y)
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-2
    )


# ------------------------------------------------- prefetch + thread hygiene


def _prefetch_spy(monkeypatch):
    """Record the prefetch depth every iter_blocks call receives."""
    from keystone_tpu.workflow import blockstore as bs_mod

    seen = []
    orig = bs_mod.FeatureBlockStore.iter_blocks

    def spy(self, order, prefetch=2):
        seen.append(prefetch)
        return orig(self, order, prefetch=prefetch)

    monkeypatch.setattr(bs_mod.FeatureBlockStore, "iter_blocks", spy)
    return seen


def test_oc_prefetch_plumbed_explicit(tmp_path, monkeypatch):
    """fit_store(prefetch=) reaches every iter_blocks call of the sweep."""
    seen = _prefetch_spy(monkeypatch)
    x, y, _ = _problem(seed=11)
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=2, lam=1e-2)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    oc = est.fit_store(store, Dataset(y, n=y.shape[0]), prefetch=3)
    assert seen and all(p == 3 for p in seen), seen
    ref = est.fit_arrays(x, y)
    np.testing.assert_allclose(
        np.asarray(oc.flat_weights), np.asarray(ref.flat_weights), atol=2e-4
    )


def test_oc_prefetch_env_override(tmp_path, monkeypatch):
    """KEYSTONE_OC_PREFETCH governs the depth when the caller passes
    nothing; an explicit argument still wins over the env."""
    from keystone_tpu.models.block_ls import _oc_prefetch

    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", "5")
    assert _oc_prefetch() == 5
    assert _oc_prefetch(3) == 3
    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", "junk")
    assert _oc_prefetch() == 2  # malformed env falls back, with a warning

    monkeypatch.setenv("KEYSTONE_OC_PREFETCH", "4")
    seen = _prefetch_spy(monkeypatch)
    x, y, _ = _problem(seed=12)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=16, num_iter=1, lam=1e-2, mixture_weight=0.25
    )
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    est.fit_store(store, Dataset(y, n=y.shape[0]))
    assert seen and all(p == 4 for p in seen), seen


def test_oc_row_mismatch_raises_before_sweep(tmp_path):
    """The hoisted row-count validation: a label array whose padded rows
    cannot match the staged store blocks fails up front (once), not from
    inside the per-(epoch, block) hot loop."""
    from keystone_tpu.models.block_ls import _oc_bcd_fit

    x, y, _ = _problem(seed=13)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=16)
    y_padded = np.pad(y, ((0, 4), (0, 0)))  # 4 extra pad rows vs the store
    alpha = (np.arange(y_padded.shape[0]) < y.shape[0]).astype(np.float32)
    with pytest.raises(ValueError, match="store rows pad to"):
        _oc_bcd_fit(
            store,
            jnp.asarray(y_padded),
            jnp.asarray(alpha),
            float(y.shape[0]),
            1e-2,
            1,
            False,
        )


def test_iter_blocks_close_joins_producer(tmp_path):
    """Abandoning the generator mid-sweep must terminate the prefetch
    thread promptly (releasing its parked in-flight block), not leave a
    parked daemon thread behind."""
    import threading
    import time

    def prefetch_threads():
        return [
            t
            for t in threading.enumerate()
            if t.name == "blockstore-prefetch" and t.is_alive()
        ]

    x = np.random.default_rng(3).normal(size=(16, 24)).astype(np.float32)
    store = FeatureBlockStore.from_array(str(tmp_path / "s"), x, block_size=4)
    assert not prefetch_threads()
    order = list(range(store.num_blocks)) * 50  # long sweep, tiny consumer
    gen = store.iter_blocks(order, prefetch=2)
    b, blk = next(gen)
    assert b == order[0]
    gen.close()  # consumer abandons the sweep
    deadline = time.monotonic() + 15.0
    while prefetch_threads() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not prefetch_threads(), "prefetch thread leaked after close()"
