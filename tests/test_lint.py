"""tools/lint.py — the repo-invariant AST linter, enforced in tier-1.

Two halves: (1) the whole ``keystone_tpu/`` tree lints clean (the CI
gate — a new unregistered fault site, misnamed metric, wall-clock call
in supervised code, or ungated obs hook fails the suite the commit it
appears); (2) a seeded-violation corpus proving every rule actually
fires, so the gate can't rot into a vacuous pass."""

import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

import lint  # noqa: E402


@pytest.fixture(scope="module")
def sites():
    return lint.load_registered_sites()


@pytest.fixture(scope="module")
def attr_vocab():
    return lint.load_attr_vocabulary()


def _run(
    src,
    sites,
    supervised=False,
    metric_kinds=None,
    solver_scoped=False,
    attr_vocab=None,
    gate_env=None,
):
    return lint.lint_source(
        "seeded.py",
        src,
        sites,
        metric_kinds if metric_kinds is not None else {},
        supervised=supervised,
        solver_scoped=solver_scoped,
        attr_vocab=attr_vocab,
        gate_env=gate_env,
    )


# ------------------------------------------------------------- the gate
def test_repo_lints_clean():
    """The tier-1 invariant: the whole package passes the linter."""
    violations = lint.lint_paths([os.path.join(REPO_ROOT, "keystone_tpu")])
    assert not violations, "\n".join(str(v) for v in violations)


def test_sites_registry_parsed_without_import(sites):
    # parsed from the AST (no package import) and matches the live set
    from keystone_tpu import faults

    assert sites == frozenset(faults.SITES)
    assert "executor.stage" in sites


# ------------------------------------------------- seeded: fault-site
def test_fault_site_rule_fires(sites):
    v = _run('fault_point("bogus.site")', sites)
    assert [x.rule for x in v] == ["fault-site"]
    v = _run('faults.fault_point("executor.stage")', sites)
    assert not v
    v = _run('SiteSpec("another.bogus", action="raise")', sites)
    assert [x.rule for x in v] == ["fault-site"]


# ------------------------------------------------- seeded: metric rules
def test_metric_name_rule_fires(sites):
    assert [x.rule for x in _run('metrics.inc("BadName")', sites)] == [
        "metric-name"
    ]
    assert [x.rule for x in _run('metrics.observe("nodots", 1.0)', sites)] == [
        "metric-name"
    ]
    assert not _run('metrics.inc("executor.stage_retries")', sites)
    assert not _run('metrics.set_gauge("serve.queue_depth", 3)', sites)


def test_metric_label_convention_rejects_index_in_name(sites):
    """Per-replica fan-out rides labels, never the metric name: an
    underscore-delimited integer segment mints one series per entity."""
    v = _run('metrics.inc("serve.replica_0_flushes")', sites)
    assert [x.rule for x in v] == ["metric-name"]
    v = _run('metrics.set_gauge("serve.replica_12", 1.0)', sites)
    assert [x.rule for x in v] == ["metric-name"]
    # the blessed form: one name, entity via label
    assert not _run('metrics.inc("serve.replica_flushes", replica=3)', sites)
    # digits glued to a word (no underscore delimiter) are legitimate
    assert not _run('metrics.observe("serve.p99_seconds", 0.1)', sites)
    assert not _run('metrics.inc("solver.bf16_casts")', sites)


def test_metric_label_convention_rejects_interpolated_name(sites):
    """An f-string / concatenated metric name is the dynamic form of
    the same violation (the entity index lands in the name at runtime,
    invisible to the literal checks)."""
    v = _run('metrics.inc(f"serve.replica{i}.flushes")', sites)
    assert [x.rule for x in v] == ["metric-name"]
    v = _run('metrics.observe("serve." + kind, 1.0)', sites)
    assert [x.rule for x in v] == ["metric-name"]
    v = _run('metrics.inc("serve.replica_{}_flushes".format(i))', sites)
    assert [x.rule for x in v] == ["metric-name"]
    # escape hatch stays available, visibly
    assert not _run(
        'metrics.inc(f"serve.{x}")  # lint: allow-metric-name', sites
    )
    # a plain variable is not flagged (could be a validated constant)
    assert not _run("metrics.inc(name)", sites)


def test_metric_kind_rule_fires_across_files(sites):
    mk = {}
    assert not _run('metrics.inc("x.y")', sites, metric_kinds=mk)
    v = _run('metrics.set_gauge("x.y", 1.0)', sites, metric_kinds=mk)
    assert [x.rule for x in v] == ["metric-kind"]
    # same kind from two files is fine
    assert not _run('metrics.inc("x.y", 2.0)', sites, metric_kinds=mk)


# ------------------------------------------------- seeded: wall-clock
def test_wall_clock_rule_scoped_to_supervised(sites):
    src = "import time\nt0 = time.time()\n"
    assert [x.rule for x in _run(src, sites, supervised=True)] == [
        "wall-clock"
    ]
    # outside the supervised set the same code is fine (app-level wall
    # timing is legitimate)
    assert not _run(src, sites, supervised=False)
    # monotonic clocks pass; the annotated escape hatch passes visibly
    assert not _run(
        "import time\nt0 = time.monotonic()", sites, supervised=True
    )
    assert not _run(
        "import time\nts = time.time()  # lint: allow-wall-clock",
        sites,
        supervised=True,
    )


def test_supervised_prefixes_cover_guard_layer():
    assert lint._is_supervised("keystone_tpu/utils/guard.py")
    assert lint._is_supervised("keystone_tpu/serve/service.py")
    assert not lint._is_supervised("keystone_tpu/pipelines/timit.py")


# ------------------------------------------------- seeded: host-sync
def test_host_sync_rule_fires_in_solver_loops(sites):
    src = "for b in order:\n    bound = np.asarray(w[:1, :1])\n"
    assert [x.rule for x in _run(src, sites, solver_scoped=True)] == [
        "host-sync"
    ]
    # same code outside the solver sweep modules is not the rule's business
    assert not _run(src, sites)
    # .tolist() in a while loop is the same stall
    v = _run(
        "while not done:\n    vals = p.tolist()\n", sites, solver_scoped=True
    )
    assert [x.rule for x in v] == ["host-sync"]


def test_host_sync_rule_scoping_and_escape(sites):
    # outside a loop: checkpoint restores legitimately np.asarray host data
    assert not _run("w = np.asarray(z['w'])\n", sites, solver_scoped=True)
    # the visible escape hatch for deliberate, obs-gated reads
    assert not _run(
        "for e in range(n):\n"
        "    obj = np.asarray(objective)  # lint: allow-host-sync\n",
        sites,
        solver_scoped=True,
    )
    # nested loops must not double-report one call
    v = _run(
        "for e in range(n):\n"
        "    for b in range(nb):\n"
        "        x = np.asarray(w)\n",
        sites,
        solver_scoped=True,
    )
    assert [x.rule for x in v] == ["host-sync"]


def test_solver_sync_prefixes_cover_solver_modules():
    assert lint._is_solver_sweep("keystone_tpu/models/block_ls.py")
    assert lint._is_solver_sweep("keystone_tpu/models/block_weighted_ls.py")
    assert lint._is_solver_sweep("keystone_tpu/models/lbfgs.py")
    assert not lint._is_solver_sweep("keystone_tpu/workflow/executor.py")


# ------------------------------------------------- seeded: attr keys
def test_attr_vocabulary_parsed_without_import(attr_vocab):
    from keystone_tpu.obs import ledger

    assert attr_vocab == frozenset(ledger.ATTR_VOCABULARY)
    assert "request_id" in attr_vocab and "seconds" in attr_vocab


def test_attr_rule_fires_on_unregistered_key(sites, attr_vocab):
    v = _run(
        'ledger.event("serve.request", request_idd=rid)',
        sites,
        attr_vocab=attr_vocab,
    )
    assert [x.rule for x in v] == ["attr"]
    assert "request_idd" in v[0].message
    # same typo class at a flight-recorder emit site
    v = _run(
        'rec.annotate(rid, "serve.enqueue", queue_dep=3)',
        sites,
        attr_vocab=attr_vocab,
    )
    assert [x.rule for x in v] == ["attr"]
    # registered keys pass, on both receivers
    assert not _run(
        'ledger.event("serve.request", request_id=rid, outcome="shed")',
        sites,
        attr_vocab=attr_vocab,
    )
    assert not _run(
        'rec.finish(rid, "shed", replica=0, waited_seconds=w)',
        sites,
        attr_vocab=attr_vocab,
    )
    # recorder API control flags are exempt WITHOUT being vocabulary
    # members (the vocabulary documents only what lands in the stream)
    assert "only_live" not in attr_vocab
    assert not _run(
        'rec.finish(rid, "shed", only_live=True, replica=0)',
        sites,
        attr_vocab=attr_vocab,
    )
    # ...but the exemption is per recorder method, not global
    v = _run(
        'ledger.event("x.y", only_live=True)', sites, attr_vocab=attr_vocab
    )
    assert [x.rule for x in v] == ["attr"]


def test_attr_rule_requires_snake_case(sites, attr_vocab):
    v = _run(
        'with ledger.span("serve.batch", Rows=k): pass',
        sites,
        attr_vocab=attr_vocab,
    )
    assert [x.rule for x in v] == ["attr"]


def test_attr_rule_scoping_and_escape(sites, attr_vocab):
    # a **splat is dynamic — not the literal rule's business
    assert not _run(
        'ledger.event("solver.epoch", **series)', sites, attr_vocab=attr_vocab
    )
    # unrelated receivers with the same method names are not emit sites
    assert not _run(
        "m.span(1, 2)\nq.event(name, weird_key=1)",
        sites,
        attr_vocab=attr_vocab,
    )
    # the visible escape hatch
    assert not _run(
        'ledger.event("x.y", oneoff_key=1)  # lint: allow-attr',
        sites,
        attr_vocab=attr_vocab,
    )
    # rule off entirely when no vocabulary is supplied
    assert not _run('ledger.event("x.y", bogus_key=1)', sites)


# ------------------------------------------------- seeded: obs-gating
def test_obs_gating_rule_fires(sites):
    bad = 'def f():\n    led = ledger.active()\n    led.event("x")\n'
    assert [x.rule for x in _run(bad, sites)] == ["obs-gating"]


@pytest.mark.parametrize(
    "src",
    [
        # guarded suite
        'def f():\n    led = ledger.active()\n    if led is not None:\n'
        '        led.event("x")\n',
        # early-exit guard
        "def f():\n    led = ledger.active()\n    if led is None:\n"
        '        return\n    led.event("x")\n',
        # pure None-comparison (the inert check itself)
        "def f():\n    obs = ledger.active() is not None\n    return obs\n",
        # conditional expression guard
        "def f():\n    led = ledger.active()\n"
        '    return led.path if led is not None else None\n',
    ],
)
def test_obs_gating_accepts_guarded_forms(src, sites):
    assert not _run(src, sites)


# ------------------------------------------------------- CLI behavior
def test_main_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert lint.main([str(clean)]) == 0
    dirty = tmp_path / "dirty.py"
    dirty.write_text('fault_point("typo.site")\n')
    assert lint.main([str(dirty)]) == 1
    out = capsys.readouterr().out
    assert "fault-site" in out


# ------------------------------------------------------------ proc-spawn
def test_proc_spawn_rule_fires_on_import_and_fork(sites):
    src = "import multiprocessing\n"
    vs = _run(src, sites)
    assert any(v.rule == "proc-spawn" for v in vs)
    src = "from multiprocessing import shared_memory\n"
    vs = _run(src, sites)
    assert any(v.rule == "proc-spawn" for v in vs)
    src = "import os\npid = os.fork()\n"
    vs = _run(src, sites)
    assert any(v.rule == "proc-spawn" for v in vs)


def test_proc_spawn_rule_scoped_to_the_worker_fence(sites):
    src = "import multiprocessing\n"
    # the fenced worker modules may touch multiprocessing directly
    vs = lint.lint_source(
        "keystone_tpu/serve/procfleet.py", src, sites, {}, attr_vocab=None
    )
    assert not [v for v in vs if v.rule == "proc-spawn"]
    # explicit override hook for tests
    vs = lint.lint_source(
        "elsewhere.py", src, sites, {}, attr_vocab=None, proc_fenced=False
    )
    assert not [v for v in vs if v.rule == "proc-spawn"]


def test_proc_spawn_allow_comment_escapes(sites):
    src = "import multiprocessing as mp  # lint: allow-proc-spawn\n"
    vs = _run(src, sites)
    assert not [v for v in vs if v.rule == "proc-spawn"]


def test_proc_spawn_rule_catches_aliased_and_from_import_fork(sites):
    for src in (
        "import os as _os\npid = _os.fork()\n",
        "from os import fork\npid = fork()\n",
        "from os import forkpty\n",
    ):
        vs = _run(src, sites)
        assert any(v.rule == "proc-spawn" for v in vs), src


# --------------------------------------------------------------- socket
def test_socket_rule_fires_on_import_forms(sites):
    for src in (
        "import socket\n",
        "import socket as sk\n",
        "from socket import create_connection\n",
    ):
        vs = _run(src, sites)
        assert any(v.rule == "socket" for v in vs), src


def test_socket_rule_scoped_to_the_transport_fence(sites):
    src = "import socket\n"
    # the transport trio may import socket directly
    for fenced in (
        "keystone_tpu/serve/net.py",
        "keystone_tpu/serve/wire.py",
        "keystone_tpu/serve/ingress.py",
    ):
        vs = lint.lint_source(fenced, src, sites, {}, attr_vocab=None)
        assert not [v for v in vs if v.rule == "socket"], fenced
    # explicit override hook for tests
    vs = lint.lint_source(
        "elsewhere.py", src, sites, {}, attr_vocab=None, socket_fenced=False
    )
    assert not [v for v in vs if v.rule == "socket"]


def test_socket_allow_comment_escapes(sites):
    src = "import socket  # lint: allow-socket\n"
    vs = _run(src, sites)
    assert not [v for v in vs if v.rule == "socket"]


def test_socket_rule_ignores_lookalike_modules(sites):
    # socketserver / websockets are not the raw-socket fence's concern
    for src in (
        "import socketserver\n",
        "from websockets import connect\n",
    ):
        vs = _run(src, sites)
        assert not [v for v in vs if v.rule == "socket"], src


def test_metric_fleet_label_rule_fires(sites):
    """Fleet-scoped series (worker-shipped, ``fleet`` in the name) must
    carry their fan-out as worker=/host= labels — a fleet series
    without either silently aggregates every worker into one line."""
    v = _run('metrics.observe("serve.fleet.apply_seconds", 0.1)', sites)
    assert [x.rule for x in v] == ["metric-name"]
    v = _run('metrics.inc("serve.fleet_exchanges")', sites)
    assert [x.rule for x in v] == ["metric-name"]
    # either label satisfies the rule
    assert not _run(
        'metrics.observe("serve.fleet.apply_seconds", 0.1, worker=w)', sites
    )
    assert not _run(
        'metrics.inc("serve.fleet_exchanges", host=h)', sites
    )
    # non-fleet names with "fleet" as a word fragment are untouched
    assert not _run('metrics.inc("serve.fleetingly")', sites)
    assert not _run('metrics.set_gauge("serve.workers", 2)', sites)
    # escape hatch stays available, visibly
    assert not _run(
        'metrics.observe("serve.fleet.x", 1.0)  '
        "# lint: allow-metric-name",
        sites,
    )


# --------------------------------------------------------- seeded: gate
@pytest.fixture(scope="module")
def gate_env():
    return lint.load_gate_env()


def test_gate_env_parsed_without_import(gate_env):
    """The allowed set comes from the planner registry's literals — the
    GATES/KNOBS ``env`` values plus OPERATIONAL_ENV — without importing
    the package (the fault-site registry discipline)."""
    from keystone_tpu.planner import registry

    expected = set(registry.OPERATIONAL_ENV)
    expected.update(
        s["env"] for s in registry.GATES.values() if s.get("env")
    )
    expected.update(
        s["env"] for s in registry.KNOBS.values() if s.get("env")
    )
    assert gate_env == frozenset(expected)
    assert "KEYSTONE_MATMUL" in gate_env
    assert "KEYSTONE_POOL_BUDGET_BYTES" in gate_env


def test_gate_rule_fires_on_unregistered_env(sites, gate_env):
    """Every literal KEYSTONE_* read form is caught: .get, getenv,
    subscript, and membership tests."""
    for src in (
        'os.environ.get("KEYSTONE_SECRET_GATE", "1")',
        'os.getenv("KEYSTONE_SECRET_GATE")',
        'os.environ["KEYSTONE_SECRET_GATE"]',
        '"KEYSTONE_SECRET_GATE" in os.environ',
        '"KEYSTONE_SECRET_GATE" not in os.environ',
        'os.environ.pop("KEYSTONE_SECRET_GATE", None)',
        'os.environ.setdefault("KEYSTONE_SECRET_GATE", "1")',
    ):
        v = _run(src, sites, gate_env=gate_env)
        assert [x.rule for x in v] == ["gate"], src


def test_gate_rule_accepts_registered_env(sites, gate_env):
    for src in (
        'os.environ.get("KEYSTONE_MATMUL", "auto")',
        'os.environ.get("KEYSTONE_FUSED_FV", "1")',
        '"KEYSTONE_MATMUL" in os.environ',
        'os.environ.get("KEYSTONE_POOL_BUDGET_BYTES")',
        # non-KEYSTONE env is out of scope entirely
        'os.environ.get("JAX_PLATFORMS", "")',
        'os.environ["HOME"]',
    ):
        assert not _run(src, sites, gate_env=gate_env), src


def test_gate_rule_scoping_and_escape(sites, gate_env):
    # gate_env=None (no registry loaded) skips the rule
    assert not _run('os.environ.get("KEYSTONE_SECRET_GATE")', sites)
    # the escape hatch allowlists one line, visibly
    assert not _run(
        'os.environ.get("KEYSTONE_SECRET_GATE")  # lint: allow-gate',
        sites,
        gate_env=gate_env,
    )
