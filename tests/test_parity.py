"""Parity guard: every component named in SURVEY.md §2's inventory must
resolve to a public symbol (reference class names included, via aliases
where our canonical name differs — e.g. BlockWeightedLeastSquares for
nodes/learning/BlockWeightedLeastSquares.scala)."""

import importlib

import pytest

INVENTORY = {
    "keystone_tpu.workflow": [
        "Transformer", "Estimator", "LabelEstimator", "Pipeline", "Dataset",
        "transformer", "Cacher", "PipelineEnv",
    ],
    "keystone_tpu.workflow.graph": [
        "Graph", "NodeId", "SourceId", "SinkId", "TransformerOperator",
        "EstimatorOperator", "DatasetOperator", "DatumOperator",
        "DelegatingOperator", "GatherOperator",
    ],
    "keystone_tpu.workflow.executor": ["GraphExecutor"],
    "keystone_tpu.workflow.optimizer": [
        "Optimizer", "Rule", "RuleBatch", "Once", "FixedPoint",
        "EquivalentNodeMergeRule", "AutoMaterializeRule", "NodeChoiceRule",
        "StageFusionRule", "FusedTransformer",
    ],
    "keystone_tpu.workflow.profiling": ["ProfilingAutoCacheRule"],
    "keystone_tpu.workflow.state": [
        "SavedStateLoadRule", "ExtractSaveablePrefixes", "save_pipeline_state",
    ],
    "keystone_tpu.models": [
        "LinearMapEstimator", "LinearMapper", "BlockLinearMapper",
        "BlockLeastSquaresEstimator", "BlockWeightedLeastSquaresEstimator",
        "BlockWeightedLeastSquares", "DenseLBFGSwithL2", "SparseLBFGSwithL2",
        "LocalLeastSquaresEstimator", "KernelRidgeRegressionEstimator",
        "KernelRidgeRegression", "KernelBlockLinearMapper",
        "OutOfCoreKernelBlockLinearMapper", "NystromFeatures",
        "NystromFeatureMap",
        "GaussianKernelGenerator", "PCAEstimator", "DistributedPCAEstimator",
        "PCATransformer", "ZCAWhitenerEstimator", "GaussianMixtureModel",
        "GaussianMixtureModelEstimator", "KMeansPlusPlusEstimator",
        "KMeansModel", "NaiveBayesEstimator", "LogisticRegressionEstimator",
    ],
    "keystone_tpu.models.kernel_matrix": ["BlockKernelMatrix"],
    "keystone_tpu.ops": [
        "Convolver", "Windower", "RandomPatcher", "CenterCornerPatcher",
        "Pooler", "SymmetricRectifier", "GrayScaler", "ImageVectorizer",
        "PixelScaler", "DaisyExtractor", "LCSExtractor", "SIFTExtractor",
        "FisherVector", "GMMFisherVectorEstimator", "CosineRandomFeatures",
        "PaddedFFT", "RandomSignNode", "LinearRectifier", "StandardScaler",
        "Sampler", "ColumnSampler", "SignedHellingerMapper", "NormalizeRows",
        "TermFrequency", "CommonSparseFeatures", "Tokenizer", "LowerCase",
        "Trimmer", "NGramsFeaturizer", "NGramsCounts", "StupidBackoffLM",
        "ClassLabelIndicators", "MaxClassifier", "TopKClassifier",
        "VectorSplitter", "VectorCombiner", "Densify", "Sparsify",
    ],
    "keystone_tpu.ops.nlp": ["NGramIndexer"],
    "keystone_tpu.loaders": [
        "ImageNetLoader", "CifarLoader", "CsvDataLoader",
        "TimitFeaturesDataLoader", "NewsgroupsDataLoader",
        "AmazonReviewsDataLoader", "VOCLoader", "LabeledData", "MnistLoader",
    ],
    "keystone_tpu.evaluation": [
        "MulticlassClassifierEvaluator", "BinaryClassifierEvaluator",
        "MeanAveragePrecisionEvaluator", "AugmentedExamplesEvaluator",
    ],
    "keystone_tpu.utils": ["Image", "ImageMetadata"],
    "keystone_tpu.utils.matrix": [
        "rows_to_matrix", "matrix_to_rows", "matrix_to_row_array",
    ],
    "keystone_tpu.utils.stats": ["about_eq"],
    "keystone_tpu.pipelines": [],
}

PIPELINES = [
    "mnist_random_fft", "linear_pixels", "random_patch_cifar", "newsgroups",
    "timit", "imagenet_sift_lcs_fv", "voc_sift_fisher", "amazon_reviews",
    "kernel_timit", "kernel_cifar",
]


@pytest.mark.parametrize("module", sorted(INVENTORY))
def test_inventory_symbols_resolve(module):
    m = importlib.import_module(module)
    missing = [s for s in INVENTORY[module] if not hasattr(m, s)]
    assert not missing, f"{module} missing {missing}"


@pytest.mark.parametrize("name", PIPELINES)
def test_pipeline_modules_have_mains(name):
    m = importlib.import_module(f"keystone_tpu.pipelines.{name}")
    assert callable(getattr(m, "main"))
