// keystone_tpu native IO library.
//
// The reference ships C/C++ behind JNI for its hot host-side work
// (utils/external/EncEval.scala, VLFeat.scala; src/main/cpp shims —
// SURVEY.md §2.8).  On TPU the *compute* hot loops live in XLA, so the
// native tier's job moves to the input pipeline: feeding the chip.  This
// library provides the host-side fast paths the Python loaders bind via
// ctypes (keystone_tpu/native):
//
//   ks_read_csv      — mmap'd single-pass float CSV parser
//   ks_read_cifar    — CIFAR binary records -> (labels, NHWC float pixels)
//   ks_tar_index     — POSIX tar member table (offset/size) for record reads
//   ks_decode_jpegs  — libjpeg batch decode + bilinear resize, thread pool
//
// Build: make -C native   (produces libkeystone_native.so)

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

#include <jpeglib.h>
#include <csetjmp>
#include <cmath>

// Branch-light float parser: [-]int[.frac][e[-]exp].  Strictly bounded by
// `end` (the mmap'd region is NOT NUL-terminated, so strtof would read
// past it) and never crosses newlines (so a short/ragged row zero-fills
// instead of misaligning the rest of the file).  Unusual forms (nan, inf,
// hex) parse as no-progress -> caller zero-fills the cell.
static inline float ks_parse_float(const char** pp, const char* end) {
  const char* p = *pp;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) { neg = (*p == '-'); p++; }
  if (p >= end || ((*p < '0' || *p > '9') && *p != '.')) {
    return 0.0f;  // no progress; caller detects *pp unchanged
  }
  double mant = 0.0;
  while (p < end && *p >= '0' && *p <= '9') { mant = mant * 10.0 + (*p - '0'); p++; }
  if (p < end && *p == '.') {
    p++;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') { mant += (*p - '0') * scale; scale *= 0.1; p++; }
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    p++;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); p++; }
    int ex = 0;
    while (p < end && *p >= '0' && *p <= '9') { ex = ex * 10 + (*p - '0'); p++; }
    static const double pow10[] = {1e0,1e1,1e2,1e3,1e4,1e5,1e6,1e7,1e8,1e9,
                                   1e10,1e11,1e12,1e13,1e14,1e15};
    double f = ex < 16 ? pow10[ex] : std::pow(10.0, ex);
    mant = eneg ? mant / f : mant * f;
  }
  *pp = p;
  return (float)(neg ? -mant : mant);
}

extern "C" {

// ---------------------------------------------------------------- CSV
// Counts rows/cols on first pass, parses with strtof on second.
// Returns 0 on success. Caller frees *out with ks_free.
int ks_read_csv(const char* path, float** out, int64_t* rows, int64_t* cols) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  size_t size = (size_t)st.st_size;
  char* data = (char*)mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (data == MAP_FAILED) return -3;

  // first non-comment line -> column count; newline count -> row bound
  size_t i = 0;
  while (i < size && (data[i] == '#' || data[i] == '\n' || data[i] == '\r')) {
    while (i < size && data[i] != '\n') i++;  // skip comment line
    if (i < size) i++;
  }
  int64_t ncols = 1;
  while (i < size && data[i] != '\n') {
    if (data[i] == ',') ncols++;
    i++;
  }
  int64_t nrows_bound = 0;
  for (size_t j = 0; j < size; j++) nrows_bound += (data[j] == '\n');
  if (size > 0 && data[size - 1] != '\n') nrows_bound++;

  float* buf = (float*)malloc(sizeof(float) * (size_t)nrows_bound * ncols);
  if (!buf) { munmap(data, size); return -4; }

  int64_t r = 0;
  const char* p = data;
  const char* end = data + size;
  while (p < end && r < nrows_bound) {
    // skip empty lines and '#' comment lines (np.loadtxt parity)
    while (p < end && (*p == '\n' || *p == '\r' || *p == '#')) {
      if (*p == '#') {
        while (p < end && *p != '\n') p++;
      } else {
        p++;
      }
    }
    if (p >= end) break;
    float* row = buf + r * ncols;
    for (int64_t c = 0; c < ncols; c++) {
      const char* before = p;
      row[c] = ks_parse_float(&p, end);
      if (p == before) row[c] = 0.0f;  // malformed cell: zero-fill
      while (p < end && *p != ',' && *p != '\n') p++;
      if (p < end && *p == ',') p++;
    }
    while (p < end && *p != '\n') p++;
    r++;
  }
  munmap(data, size);
  *out = buf;
  *rows = r;
  *cols = ncols;
  return 0;
}

// --------------------------------------------------------------- CIFAR
// Binary records: 1 label byte + 3072 channel-major pixel bytes.
// Emits labels (int32) and NHWC float32 pixels in [0, 1].
int ks_read_cifar(const char* path, float** pixels, int32_t** labels,
                  int64_t* count) {
  const int64_t H = 32, W = 32, C = 3, REC = 1 + H * W * C;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  if (st.st_size % REC != 0) { close(fd); return -5; }
  int64_t n = st.st_size / REC;
  uint8_t* data = (uint8_t*)mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (data == MAP_FAILED) return -3;

  float* px = (float*)malloc(sizeof(float) * n * H * W * C);
  int32_t* lb = (int32_t*)malloc(sizeof(int32_t) * n);
  if (!px || !lb) { munmap(data, st.st_size); free(px); free(lb); return -4; }
  const float inv = 1.0f / 255.0f;
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* rec = data + i * REC;
    lb[i] = rec[0];
    const uint8_t* chan = rec + 1; // channel-major: R plane, G, B
    float* out = px + i * H * W * C;
    for (int64_t y = 0; y < H; y++)
      for (int64_t x = 0; x < W; x++)
        for (int64_t c = 0; c < C; c++)
          out[(y * W + x) * C + c] = chan[c * H * W + y * W + x] * inv;
  }
  munmap(data, st.st_size);
  *pixels = px;
  *labels = lb;
  *count = n;
  return 0;
}

// ----------------------------------------------------------------- tar
// POSIX/ustar member index: name (100 bytes), offset, size per member.
int ks_tar_index(const char* path, char** names, int64_t** offsets,
                 int64_t** sizes, int64_t* count) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  uint8_t* data = (uint8_t*)mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (data == MAP_FAILED) return -3;

  std::vector<int64_t> offs, szs;
  std::vector<char> nm;
  int64_t pos = 0;
  while (pos + 512 <= st.st_size) {
    const uint8_t* hdr = data + pos;
    if (hdr[0] == 0) break; // end-of-archive zero block
    // require the ustar magic: rejects gzip'd tars and non-tar bytes so
    // the Python side falls back to tarfile's auto-detection
    if (memcmp(hdr + 257, "ustar", 5) != 0) {
      munmap(data, st.st_size);
      return -6;
    }
    char szfield[13];
    memcpy(szfield, hdr + 124, 12);
    szfield[12] = 0;
    int64_t sz = strtoll(szfield, nullptr, 8);
    char type = hdr[156];
    if (type == '0' || type == 0) {
      offs.push_back(pos + 512);
      szs.push_back(sz);
      char name[101];
      memcpy(name, hdr, 100);
      name[100] = 0;
      nm.insert(nm.end(), name, name + 101);
    }
    pos += 512 + ((sz + 511) / 512) * 512;
  }
  munmap(data, st.st_size);
  int64_t n = (int64_t)offs.size();
  *offsets = (int64_t*)malloc(sizeof(int64_t) * n);
  *sizes = (int64_t*)malloc(sizeof(int64_t) * n);
  *names = (char*)malloc(nm.size() > 0 ? nm.size() : 1);
  memcpy(*offsets, offs.data(), sizeof(int64_t) * n);
  memcpy(*sizes, szs.data(), sizeof(int64_t) * n);
  if (!nm.empty()) memcpy(*names, nm.data(), nm.size());
  *count = n;
  return 0;
}

// ---------------------------------------------------------------- JPEG
struct KsJpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

static void ks_jpeg_error_exit(j_common_ptr cinfo) {
  KsJpegErr* err = (KsJpegErr*)cinfo->err;
  longjmp(err->jb, 1);
}

// decode one JPEG into out (target_h, target_w, 3) uint8 via bilinear
// resize (resampling in float, rounded to the nearest byte).  uint8 output
// keeps the host buffer and the host->device transfer at 1 byte/pixel;
// the on-device PixelScaler does the [0,1] cast.  Returns 0 on success.
static int decode_one(const uint8_t* buf, int64_t len, int64_t th, int64_t tw,
                      uint8_t* out) {
  jpeg_decompress_struct cinfo;
  KsJpegErr jerr;
  // raw buffer, not std::vector: longjmp from the error handler must not
  // skip a non-trivial destructor (UB + leak); freed on both paths
  uint8_t* volatile imgbuf = nullptr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = ks_jpeg_error_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    free(imgbuf);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, (unsigned long)len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  int64_t h = cinfo.output_height, w = cinfo.output_width;
  imgbuf = (uint8_t*)malloc((size_t)h * w * 3);
  if (!imgbuf) {
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }
  uint8_t* img = imgbuf;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* rowp = img + (size_t)cinfo.output_scanline * w * 3;
    jpeg_read_scanlines(&cinfo, &rowp, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  // bilinear resize to (th, tw)
  for (int64_t y = 0; y < th; y++) {
    float sy = th > 1 ? (float)y * (h - 1) / (th - 1) : 0.0f;
    int64_t y0 = (int64_t)sy;
    int64_t y1 = y0 + 1 < h ? y0 + 1 : h - 1;
    float fy = sy - y0;
    for (int64_t x = 0; x < tw; x++) {
      float sx = tw > 1 ? (float)x * (w - 1) / (tw - 1) : 0.0f;
      int64_t x0 = (int64_t)sx;
      int64_t x1 = x0 + 1 < w ? x0 + 1 : w - 1;
      float fx = sx - x0;
      for (int64_t c = 0; c < 3; c++) {
        float v00 = img[(y0 * w + x0) * 3 + c];
        float v01 = img[(y0 * w + x1) * 3 + c];
        float v10 = img[(y1 * w + x0) * 3 + c];
        float v11 = img[(y1 * w + x1) * 3 + c];
        float v = (1 - fy) * ((1 - fx) * v00 + fx * v01) +
                  fy * ((1 - fx) * v10 + fx * v11);
        out[(y * tw + x) * 3 + c] = (uint8_t)(v + 0.5f);
      }
    }
  }
  free(imgbuf);
  return 0;
}

// Batch decode with a thread pool.  buffers: concatenated JPEG bytes with
// per-item offsets/sizes.  out: (n, th, tw, 3) uint8, caller-allocated
// by us.  ok[i] = 0 on success per image.
int ks_decode_jpegs(const uint8_t* blob, const int64_t* offsets,
                    const int64_t* sizes, int64_t n, int64_t th, int64_t tw,
                    int threads, uint8_t** out, int32_t** ok) {
  uint8_t* buf = (uint8_t*)malloc((size_t)n * th * tw * 3);
  int32_t* st = (int32_t*)malloc(sizeof(int32_t) * (n > 0 ? n : 1));
  if (!buf || !st) { free(buf); free(st); return -4; }
  if (threads < 1) threads = (int)std::thread::hardware_concurrency();
  if (threads < 1) threads = 1;
  if ((int64_t)threads > n) threads = (int)n;  // never more threads than items
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    while (true) {
      int64_t i = next.fetch_add(1);
      if (i >= n) break;
      st[i] = decode_one(blob + offsets[i], sizes[i], th, tw,
                         buf + (size_t)i * th * tw * 3);
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < threads; t++) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
  *out = buf;
  *ok = st;
  return 0;
}

void ks_free(void* p) { free(p); }

// ABI version: bump whenever an exported signature changes (v2 =
// ks_decode_jpegs emits uint8 pixels; v1 emitted float).  The ctypes
// loader refuses mismatched binaries instead of reading garbage.
int ks_version() { return 2; }

}  // extern "C"
