// keystone_tpu native IO library.
//
// The reference ships C/C++ behind JNI for its hot host-side work
// (utils/external/EncEval.scala, VLFeat.scala; src/main/cpp shims —
// SURVEY.md §2.8).  On TPU the *compute* hot loops live in XLA, so the
// native tier's job moves to the input pipeline: feeding the chip.  This
// library provides the host-side fast paths the Python loaders bind via
// ctypes (keystone_tpu/native):
//
//   ks_read_csv      — mmap'd single-pass float CSV parser
//   ks_read_cifar    — CIFAR binary records -> (labels, NHWC float pixels)
//   ks_tar_index     — POSIX tar member table (offset/size) for record reads
//   ks_decode_jpegs  — libjpeg batch decode + bilinear resize, thread pool
//
// Build: make -C native   (produces libkeystone_native.so)

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

#include <jpeglib.h>
#include <csetjmp>
#include <cmath>

// Branch-light float parser: [-]int[.frac][e[-]exp].  Strictly bounded by
// `end` (the mmap'd region is NOT NUL-terminated, so strtof would read
// past it) and never crosses newlines (so a short/ragged row zero-fills
// instead of misaligning the rest of the file).  Unusual forms (nan, inf,
// hex) parse as no-progress -> caller zero-fills the cell.
static inline float ks_parse_float(const char** pp, const char* end) {
  const char* p = *pp;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) { neg = (*p == '-'); p++; }
  if (p >= end || ((*p < '0' || *p > '9') && *p != '.')) {
    return 0.0f;  // no progress; caller detects *pp unchanged
  }
  double mant = 0.0;
  while (p < end && *p >= '0' && *p <= '9') { mant = mant * 10.0 + (*p - '0'); p++; }
  if (p < end && *p == '.') {
    p++;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') { mant += (*p - '0') * scale; scale *= 0.1; p++; }
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    p++;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); p++; }
    int ex = 0;
    while (p < end && *p >= '0' && *p <= '9') { ex = ex * 10 + (*p - '0'); p++; }
    static const double pow10[] = {1e0,1e1,1e2,1e3,1e4,1e5,1e6,1e7,1e8,1e9,
                                   1e10,1e11,1e12,1e13,1e14,1e15};
    double f = ex < 16 ? pow10[ex] : std::pow(10.0, ex);
    mant = eneg ? mant / f : mant * f;
  }
  *pp = p;
  return (float)(neg ? -mant : mant);
}

extern "C" {

// ---------------------------------------------------------------- CSV
// Counts rows/cols on first pass, parses with strtof on second.
// Returns 0 on success. Caller frees *out with ks_free.
int ks_read_csv(const char* path, float** out, int64_t* rows, int64_t* cols) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  size_t size = (size_t)st.st_size;
  char* data = (char*)mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (data == MAP_FAILED) return -3;

  // first non-comment line -> column count; newline count -> row bound
  size_t i = 0;
  while (i < size && (data[i] == '#' || data[i] == '\n' || data[i] == '\r')) {
    while (i < size && data[i] != '\n') i++;  // skip comment line
    if (i < size) i++;
  }
  int64_t ncols = 1;
  while (i < size && data[i] != '\n') {
    if (data[i] == ',') ncols++;
    i++;
  }
  int64_t nrows_bound = 0;
  for (size_t j = 0; j < size; j++) nrows_bound += (data[j] == '\n');
  if (size > 0 && data[size - 1] != '\n') nrows_bound++;

  float* buf = (float*)malloc(sizeof(float) * (size_t)nrows_bound * ncols);
  if (!buf) { munmap(data, size); return -4; }

  int64_t r = 0;
  const char* p = data;
  const char* end = data + size;
  while (p < end && r < nrows_bound) {
    // skip empty lines and '#' comment lines (np.loadtxt parity)
    while (p < end && (*p == '\n' || *p == '\r' || *p == '#')) {
      if (*p == '#') {
        while (p < end && *p != '\n') p++;
      } else {
        p++;
      }
    }
    if (p >= end) break;
    float* row = buf + r * ncols;
    for (int64_t c = 0; c < ncols; c++) {
      const char* before = p;
      row[c] = ks_parse_float(&p, end);
      if (p == before) row[c] = 0.0f;  // malformed cell: zero-fill
      while (p < end && *p != ',' && *p != '\n') p++;
      if (p < end && *p == ',') p++;
    }
    while (p < end && *p != '\n') p++;
    r++;
  }
  munmap(data, size);
  *out = buf;
  *rows = r;
  *cols = ncols;
  return 0;
}

// --------------------------------------------------------------- CIFAR
// Binary records: 1 label byte + 3072 channel-major pixel bytes.
// Emits labels (int32) and NHWC float32 pixels in [0, 1].
int ks_read_cifar(const char* path, float** pixels, int32_t** labels,
                  int64_t* count) {
  const int64_t H = 32, W = 32, C = 3, REC = 1 + H * W * C;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  if (st.st_size % REC != 0) { close(fd); return -5; }
  int64_t n = st.st_size / REC;
  uint8_t* data = (uint8_t*)mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (data == MAP_FAILED) return -3;

  float* px = (float*)malloc(sizeof(float) * n * H * W * C);
  int32_t* lb = (int32_t*)malloc(sizeof(int32_t) * n);
  if (!px || !lb) { munmap(data, st.st_size); free(px); free(lb); return -4; }
  const float inv = 1.0f / 255.0f;
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* rec = data + i * REC;
    lb[i] = rec[0];
    const uint8_t* chan = rec + 1; // channel-major: R plane, G, B
    float* out = px + i * H * W * C;
    for (int64_t y = 0; y < H; y++)
      for (int64_t x = 0; x < W; x++)
        for (int64_t c = 0; c < C; c++)
          out[(y * W + x) * C + c] = chan[c * H * W + y * W + x] * inv;
  }
  munmap(data, st.st_size);
  *pixels = px;
  *labels = lb;
  *count = n;
  return 0;
}

// ----------------------------------------------------------------- tar
// POSIX/ustar member index: name (100 bytes), offset, size per member.
int ks_tar_index(const char* path, char** names, int64_t** offsets,
                 int64_t** sizes, int64_t* count) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -2; }
  uint8_t* data = (uint8_t*)mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (data == MAP_FAILED) return -3;

  std::vector<int64_t> offs, szs;
  std::vector<char> nm;
  int64_t pos = 0;
  while (pos + 512 <= st.st_size) {
    const uint8_t* hdr = data + pos;
    if (hdr[0] == 0) break; // end-of-archive zero block
    // require the ustar magic: rejects gzip'd tars and non-tar bytes so
    // the Python side falls back to tarfile's auto-detection
    if (memcmp(hdr + 257, "ustar", 5) != 0) {
      munmap(data, st.st_size);
      return -6;
    }
    char szfield[13];
    memcpy(szfield, hdr + 124, 12);
    szfield[12] = 0;
    int64_t sz = strtoll(szfield, nullptr, 8);
    char type = hdr[156];
    if (type == '0' || type == 0) {
      offs.push_back(pos + 512);
      szs.push_back(sz);
      char name[101];
      memcpy(name, hdr, 100);
      name[100] = 0;
      nm.insert(nm.end(), name, name + 101);
    }
    pos += 512 + ((sz + 511) / 512) * 512;
  }
  munmap(data, st.st_size);
  int64_t n = (int64_t)offs.size();
  *offsets = (int64_t*)malloc(sizeof(int64_t) * n);
  *sizes = (int64_t*)malloc(sizeof(int64_t) * n);
  *names = (char*)malloc(nm.size() > 0 ? nm.size() : 1);
  memcpy(*offsets, offs.data(), sizeof(int64_t) * n);
  memcpy(*sizes, szs.data(), sizeof(int64_t) * n);
  if (!nm.empty()) memcpy(*names, nm.data(), nm.size());
  *count = n;
  return 0;
}

// ---------------------------------------------------------------- JPEG
struct KsJpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

static void ks_jpeg_error_exit(j_common_ptr cinfo) {
  KsJpegErr* err = (KsJpegErr*)cinfo->err;
  longjmp(err->jb, 1);
}

// decode one JPEG into out (target_h, target_w, 3) uint8 via bilinear
// resize (resampling in float, rounded to the nearest byte).  uint8 output
// keeps the host buffer and the host->device transfer at 1 byte/pixel;
// the on-device PixelScaler does the [0,1] cast.  Returns 0 on success.
static int decode_one(const uint8_t* buf, int64_t len, int64_t th, int64_t tw,
                      uint8_t* out) {
  jpeg_decompress_struct cinfo;
  KsJpegErr jerr;
  // raw buffer, not std::vector: longjmp from the error handler must not
  // skip a non-trivial destructor (UB + leak); freed on both paths
  uint8_t* volatile imgbuf = nullptr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = ks_jpeg_error_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    free(imgbuf);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, (unsigned long)len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  int64_t h = cinfo.output_height, w = cinfo.output_width;
  imgbuf = (uint8_t*)malloc((size_t)h * w * 3);
  if (!imgbuf) {
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }
  uint8_t* img = imgbuf;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* rowp = img + (size_t)cinfo.output_scanline * w * 3;
    jpeg_read_scanlines(&cinfo, &rowp, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  // bilinear resize to (th, tw)
  for (int64_t y = 0; y < th; y++) {
    float sy = th > 1 ? (float)y * (h - 1) / (th - 1) : 0.0f;
    int64_t y0 = (int64_t)sy;
    int64_t y1 = y0 + 1 < h ? y0 + 1 : h - 1;
    float fy = sy - y0;
    for (int64_t x = 0; x < tw; x++) {
      float sx = tw > 1 ? (float)x * (w - 1) / (tw - 1) : 0.0f;
      int64_t x0 = (int64_t)sx;
      int64_t x1 = x0 + 1 < w ? x0 + 1 : w - 1;
      float fx = sx - x0;
      for (int64_t c = 0; c < 3; c++) {
        float v00 = img[(y0 * w + x0) * 3 + c];
        float v01 = img[(y0 * w + x1) * 3 + c];
        float v10 = img[(y1 * w + x0) * 3 + c];
        float v11 = img[(y1 * w + x1) * 3 + c];
        float v = (1 - fy) * ((1 - fx) * v00 + fx * v01) +
                  fy * ((1 - fx) * v10 + fx * v11);
        out[(y * tw + x) * 3 + c] = (uint8_t)(v + 0.5f);
      }
    }
  }
  free(imgbuf);
  return 0;
}

// Batch decode with a thread pool.  buffers: concatenated JPEG bytes with
// per-item offsets/sizes.  out: (n, th, tw, 3) uint8, caller-allocated
// by us.  ok[i] = 0 on success per image.
int ks_decode_jpegs(const uint8_t* blob, const int64_t* offsets,
                    const int64_t* sizes, int64_t n, int64_t th, int64_t tw,
                    int threads, uint8_t** out, int32_t** ok) {
  uint8_t* buf = (uint8_t*)malloc((size_t)n * th * tw * 3);
  int32_t* st = (int32_t*)malloc(sizeof(int32_t) * (n > 0 ? n : 1));
  if (!buf || !st) { free(buf); free(st); return -4; }
  if (threads < 1) threads = (int)std::thread::hardware_concurrency();
  if (threads < 1) threads = 1;
  if ((int64_t)threads > n) threads = (int)n;  // never more threads than items
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    while (true) {
      int64_t i = next.fetch_add(1);
      if (i >= n) break;
      st[i] = decode_one(blob + offsets[i], sizes[i], th, tw,
                         buf + (size_t)i * th * tw * 3);
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < threads; t++) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
  *out = buf;
  *ok = st;
  return 0;
}

void ks_free(void* p) { free(p); }

}  // extern "C"

// ------------------------------------------------------------------ text
// Native host-text hot loop (SURVEY §2.10 text pipelines, §7(f); the
// reference's per-doc Scala maps — here the fused
// trim→lower→tokenize→n-gram→tf→{vocab-lookup | df} chain runs in C++
// with the GIL released (ctypes) and a thread pool over docs, replacing
// the measured 2-3k docs/s pure-Python per-doc loops (BASELINE.md
// "Host text stage").
//
// Parity contract with keystone_tpu/ops/nlp.py (pinned by
// tests/test_nlp_native.py):
//   - tokens = maximal runs of [A-Za-z0-9'] (the Python Tokenizer's
//     default split pattern); `lower` ASCII-lowercases first; `trim`
//     strips ASCII whitespace like str.strip().  KNOWN DIVERGENCE: a
//     handful of non-ASCII characters lowercase INTO ASCII in Python
//     (U+0130 'İ' -> 'i'+combining dot, U+212A Kelvin -> 'k'), so docs
//     containing them tokenize differently here (native treats the
//     original bytes as separators).  ASCII and ordinary UTF-8 text is
//     bit-identical; multilingual corpora needing Python's full Unicode
//     case mapping should use the Python path (it remains the fallback
//     — see ops/nlp_native.py).
//   - n-gram term key = tokens joined with '\x1f' (the Python side's
//     tuple <-> joined-string bridge).
//   - tf: raw counts or log(1+count) (TermFrequency(log_tf)).
//   - df top-N tie-break: (-df, first-doc-index, term) — DETERMINISTIC,
//     unlike Python Counter.most_common whose tie order inherits set
//     iteration (process-salted).  Documented difference; ties with
//     distinct dfs are identical.

#include <algorithm>
#include <string>
#include <unordered_map>

namespace {

struct TfEntry { int32_t col; float val; };

// transparent string_view lookup (C++20 P0919): global maps keyed by
// std::string but probed with views into per-doc arenas — a string is
// only constructed on first insertion, never per occurrence
struct SvHash {
  using is_transparent = void;
  size_t operator()(std::string_view s) const {
    return std::hash<std::string_view>{}(s);
  }
};
struct SvEq {
  using is_transparent = void;
  bool operator()(std::string_view a, std::string_view b) const {
    return a == b;
  }
};

// Reusable per-doc scratch: term keys live in one arena; counting is
// sort-views + run-length (beats a per-doc hash map: ~240 keys/doc ×
// 10⁵ docs was 24M small map allocations in the first cut).
struct DocScratch {
  std::string text;                             // trimmed/lowered copy
  std::vector<std::pair<size_t, size_t>> toks;  // (offset, len) in text
  std::string arena;                            // all n-gram keys, packed
  std::vector<std::pair<size_t, size_t>> keys;  // (offset, len) in arena
  std::vector<std::pair<std::string_view, int32_t>> counted;
};

// tokenize + n-grams into `ds.keys`, then sort + run-length into
// `ds.counted` (term view -> tf count, each term once)
static void doc_terms(const char* p, const char* end, bool lower, bool trim,
                      uint32_t orders_mask, DocScratch& ds) {
  if (trim) {
    while (p < end && (unsigned char)*p <= ' ') p++;
    while (end > p && (unsigned char)end[-1] <= ' ') end--;
  }
  ds.text.assign(p, end);
  if (lower)
    for (char& c : ds.text)
      if (c >= 'A' && c <= 'Z') c += 32;
  ds.toks.clear();
  const char* s = ds.text.data();
  size_t nbytes = ds.text.size();
  size_t i = 0;
  auto is_tok = [](char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9') || c == '\'';
  };
  while (i < nbytes) {
    while (i < nbytes && !is_tok(s[i])) i++;
    size_t start = i;
    while (i < nbytes && is_tok(s[i])) i++;
    if (i > start) ds.toks.emplace_back(start, i - start);
  }
  ds.arena.clear();
  ds.keys.clear();
  for (int order = 1; order <= 8; order++) {
    if (!(orders_mask & (1u << (order - 1)))) continue;
    if (ds.toks.size() < (size_t)order) continue;
    for (size_t t = 0; t + order <= ds.toks.size(); t++) {
      size_t start = ds.arena.size();
      for (int j = 0; j < order; j++) {
        if (j) ds.arena.push_back('\x1f');
        ds.arena.append(s + ds.toks[t + j].first, ds.toks[t + j].second);
      }
      ds.keys.emplace_back(start, ds.arena.size() - start);
    }
  }
  const char* a = ds.arena.data();
  std::sort(ds.keys.begin(), ds.keys.end(),
            [a](const auto& x, const auto& y) {
              return std::string_view(a + x.first, x.second) <
                     std::string_view(a + y.first, y.second);
            });
  ds.counted.clear();
  for (size_t k = 0; k < ds.keys.size();) {
    std::string_view key(a + ds.keys[k].first, ds.keys[k].second);
    size_t j = k + 1;
    while (j < ds.keys.size() &&
           std::string_view(a + ds.keys[j].first, ds.keys[j].second) == key)
      j++;
    ds.counted.emplace_back(key, (int32_t)(j - k));
    k = j;
  }
}

}  // namespace

namespace {

// ---- BLAKE2b (RFC 7693; unkeyed, sequential) — the native twin of
// ops/nlp.stable_term_hash: blake2b(repr(term), digest_size=8),
// little-endian.  Implemented from the spec; parity is pinned by
// tests/test_nlp_native.py against hashlib for adversarial tokens.
struct B2b {
  uint64_t h[8], t[2];
  uint8_t buf[128];
  size_t buflen;
};

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

static void b2b_compress(B2b* S, const uint8_t* block, bool last) {
  uint64_t v[16], m[16];
  for (int i = 0; i < 8; i++) v[i] = S->h[i];
  for (int i = 0; i < 8; i++) v[i + 8] = B2B_IV[i];
  v[12] ^= S->t[0];
  v[13] ^= S->t[1];
  if (last) v[14] = ~v[14];
  for (int i = 0; i < 16; i++) memcpy(&m[i], block + 8 * i, 8);  // LE host
  auto G = [&](int a, int b, int c, int d, uint64_t x, uint64_t y) {
    v[a] = v[a] + v[b] + x;
    v[d] = rotr64(v[d] ^ v[a], 32);
    v[c] = v[c] + v[d];
    v[b] = rotr64(v[b] ^ v[c], 24);
    v[a] = v[a] + v[b] + y;
    v[d] = rotr64(v[d] ^ v[a], 16);
    v[c] = v[c] + v[d];
    v[b] = rotr64(v[b] ^ v[c], 63);
  };
  for (int r = 0; r < 12; r++) {
    const uint8_t* s = B2B_SIGMA[r];
    G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    G(3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
  for (int i = 0; i < 8; i++) S->h[i] ^= v[i] ^ v[i + 8];
}

// unkeyed blake2b-64 (8-byte digest) of msg, returned as LE uint64
static uint64_t blake2b8(const uint8_t* msg, size_t len) {
  B2b S;
  for (int i = 0; i < 8; i++) S.h[i] = B2B_IV[i];
  S.h[0] ^= 0x01010000ULL ^ 8ULL;  // digest_length=8, fanout=1, depth=1
  S.t[0] = S.t[1] = 0;
  S.buflen = 0;
  while (len > 128) {  // full blocks (never the last one)
    S.t[0] += 128;
    if (S.t[0] < 128) S.t[1]++;
    b2b_compress(&S, msg, false);
    msg += 128;
    len -= 128;
  }
  memcpy(S.buf, msg, len);
  memset(S.buf + len, 0, 128 - len);
  S.t[0] += len;
  b2b_compress(&S, S.buf, true);
  return S.h[0];  // first 8 digest bytes == h[0] little-endian
}

// repr() of a tuple of ASCII token strings, exactly as CPython renders
// it for the token alphabet [A-Za-z0-9']: strings containing an
// apostrophe are double-quoted (they can never contain '"'), others
// single-quoted; 1-tuples carry the trailing comma, n-tuples separate
// with ", ".
static void py_tuple_repr(const std::vector<std::string_view>& toks,
                          std::string& out) {
  out.clear();
  out.push_back('(');
  for (size_t i = 0; i < toks.size(); i++) {
    if (i) out.append(", ");
    char q = toks[i].find('\'') != std::string_view::npos ? '"' : '\'';
    out.push_back(q);
    out.append(toks[i]);
    out.push_back(q);
  }
  if (toks.size() == 1) out.push_back(',');
  out.push_back(')');
}

}  // namespace

extern "C" {

// Raw docs -> CSR rows over a fixed vocabulary (the fused
// trim→lower→tokenize→ngram→tf→CommonSparseFeaturesModel chain).
// blob/doc_offs: concatenated UTF-8 docs, ndocs+1 offsets.
// vocab_blob/voc_offs: concatenated '\x1f'-joined term keys, vsize+1.
// orders_mask: bit (n-1) set => emit n-grams.  log_tf: 0 raw, 1 log1p.
// indptr: caller-allocated int64[ndocs+1].  out_indices/out_values:
// malloc'd here (ks_free), CSR column/value arrays sorted by column
// within each row.
int ks_text_featurize(const char* blob, const int64_t* doc_offs, int64_t ndocs,
                      const char* vocab_blob, const int64_t* voc_offs,
                      int64_t vsize, uint32_t orders_mask, int log_tf,
                      int lower, int trim, int threads,
                      int64_t* indptr, int32_t** out_indices,
                      float** out_values) {
  std::unordered_map<std::string, int32_t, SvHash, SvEq> vocab;
  vocab.reserve((size_t)vsize * 2);
  for (int64_t v = 0; v < vsize; v++)
    vocab.emplace(std::string(vocab_blob + voc_offs[v],
                              (size_t)(voc_offs[v + 1] - voc_offs[v])),
                  (int32_t)v);
  if (threads < 1) threads = (int)std::thread::hardware_concurrency();
  if (threads < 1) threads = 1;
  if ((int64_t)threads > ndocs) threads = ndocs > 0 ? (int)ndocs : 1;
  std::vector<std::vector<TfEntry>> rows((size_t)ndocs);
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    DocScratch ds;
    while (true) {
      int64_t d = next.fetch_add(1);
      if (d >= ndocs) break;
      doc_terms(blob + doc_offs[d], blob + doc_offs[d + 1], lower, trim,
                orders_mask, ds);
      auto& row = rows[(size_t)d];
      for (auto& kv : ds.counted) {
        auto it = vocab.find(kv.first);
        if (it == vocab.end()) continue;
        float v = (float)kv.second;
        if (log_tf) v = (float)std::log(1.0 + (double)kv.second);
        row.push_back({it->second, v});
      }
      std::sort(row.begin(), row.end(),
                [](const TfEntry& a, const TfEntry& b) { return a.col < b.col; });
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < threads; t++) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
  int64_t nnz = 0;
  indptr[0] = 0;
  for (int64_t d = 0; d < ndocs; d++) {
    nnz += (int64_t)rows[(size_t)d].size();
    indptr[d + 1] = nnz;
  }
  int32_t* idx = (int32_t*)malloc(sizeof(int32_t) * (size_t)(nnz > 0 ? nnz : 1));
  float* val = (float*)malloc(sizeof(float) * (size_t)(nnz > 0 ? nnz : 1));
  if (!idx || !val) { free(idx); free(val); return -4; }
  int64_t w = 0;
  for (int64_t d = 0; d < ndocs; d++)
    for (auto& e : rows[(size_t)d]) { idx[w] = e.col; val[w] = e.val; w++; }
  *out_indices = idx;
  *out_values = val;
  return 0;
}

// Raw docs -> hashed CSR rows (HashingTF over the fused chain): col =
// blake2b8(repr(term)) % num_features (the stable_term_hash contract),
// colliding terms' tf values ACCUMULATE.  Same output conventions as
// ks_text_featurize.  Float accumulation order on collisions is
// sorted-column here vs dict-insertion in Python — parity to 1e-6.
int ks_text_hashtf(const char* blob, const int64_t* doc_offs, int64_t ndocs,
                   uint32_t orders_mask, int log_tf, int lower, int trim,
                   int64_t num_features, int threads, int64_t* indptr,
                   int32_t** out_indices, float** out_values) {
  if (threads < 1) threads = (int)std::thread::hardware_concurrency();
  if (threads < 1) threads = 1;
  if ((int64_t)threads > ndocs) threads = ndocs > 0 ? (int)ndocs : 1;
  std::vector<std::vector<TfEntry>> rows((size_t)ndocs);
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    DocScratch ds;
    std::string reprbuf;
    std::vector<std::string_view> toks;
    std::unordered_map<int64_t, float> acc;
    // capped term->hash memo, the native twin of Python's
    // _TERM_HASH_MEMO (zipfian corpora re-hash the hot head ~5.5x,
    // measured); per-thread, probed with arena views
    std::unordered_map<std::string, uint64_t, SvHash, SvEq> hmemo;
    constexpr size_t kMemoCap = 1u << 17;
    while (true) {
      int64_t d = next.fetch_add(1);
      if (d >= ndocs) break;
      doc_terms(blob + doc_offs[d], blob + doc_offs[d + 1], lower, trim,
                orders_mask, ds);
      acc.clear();
      for (auto& kv : ds.counted) {
        uint64_t h;
        auto hit = hmemo.find(kv.first);
        if (hit != hmemo.end()) {
          h = hit->second;
        } else {
          // split the '\x1f'-joined key back into tokens for repr()
          toks.clear();
          std::string_view key = kv.first;
          size_t start = 0;
          while (true) {
            size_t sep = key.find('\x1f', start);
            if (sep == std::string_view::npos) {
              toks.push_back(key.substr(start));
              break;
            }
            toks.push_back(key.substr(start, sep - start));
            start = sep + 1;
          }
          py_tuple_repr(toks, reprbuf);
          h = blake2b8(
              reinterpret_cast<const uint8_t*>(reprbuf.data()), reprbuf.size());
          if (hmemo.size() < kMemoCap) hmemo.emplace(std::string(kv.first), h);
        }
        int64_t col = (int64_t)(h % (uint64_t)num_features);
        float v = (float)kv.second;
        if (log_tf) v = (float)std::log(1.0 + (double)kv.second);
        acc[col] += v;
      }
      auto& row = rows[(size_t)d];
      row.reserve(acc.size());
      for (auto& cv : acc) row.push_back({(int32_t)cv.first, cv.second});
      std::sort(row.begin(), row.end(),
                [](const TfEntry& a, const TfEntry& b) { return a.col < b.col; });
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < threads; t++) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
  int64_t nnz = 0;
  indptr[0] = 0;
  for (int64_t d = 0; d < ndocs; d++) {
    nnz += (int64_t)rows[(size_t)d].size();
    indptr[d + 1] = nnz;
  }
  int32_t* idx = (int32_t*)malloc(sizeof(int32_t) * (size_t)(nnz > 0 ? nnz : 1));
  float* val = (float*)malloc(sizeof(float) * (size_t)(nnz > 0 ? nnz : 1));
  if (!idx || !val) { free(idx); free(val); return -4; }
  int64_t w = 0;
  for (int64_t d = 0; d < ndocs; d++)
    for (auto& e : rows[(size_t)d]) { idx[w] = e.col; val[w] = e.val; w++; }
  *out_indices = idx;
  *out_values = val;
  return 0;
}

// Streaming document-frequency accumulator (CommonSparseFeatures.fit):
// new -> update(batch)* -> topn -> free.  df counts one per doc per
// distinct term; first-seen doc index is the deterministic tie-break.
struct KsDfState {
  // term -> (count, first_doc); probed with arena views (SvHash/SvEq)
  std::unordered_map<std::string, std::pair<int64_t, int64_t>, SvHash, SvEq> df;
  int64_t docs_seen = 0;
  uint32_t orders_mask;
  int lower, trim;
};

void* ks_text_df_new(uint32_t orders_mask, int lower, int trim) {
  KsDfState* st = new KsDfState();
  st->orders_mask = orders_mask;
  st->lower = lower;
  st->trim = trim;
  return st;
}

int ks_text_df_update(void* handle, const char* blob, const int64_t* doc_offs,
                      int64_t ndocs) {
  KsDfState* st = (KsDfState*)handle;
  DocScratch ds;
  for (int64_t d = 0; d < ndocs; d++) {
    doc_terms(blob + doc_offs[d], blob + doc_offs[d + 1], st->lower, st->trim,
              st->orders_mask, ds);
    int64_t doc_id = st->docs_seen + d;
    for (auto& kv : ds.counted) {
      auto it = st->df.find(kv.first);
      if (it == st->df.end())
        st->df.emplace(std::string(kv.first),
                       std::make_pair((int64_t)1, doc_id));
      else
        it->second.first++;
    }
  }
  st->docs_seen += ndocs;
  return 0;
}

// Top-N by (-df, first_doc, term); returns the joined term keys.
int ks_text_df_topn(void* handle, int64_t top_n, char** out_terms,
                    int64_t** out_offs, int64_t** out_counts,
                    int64_t* out_n) {
  KsDfState* st = (KsDfState*)handle;
  std::vector<const std::pair<const std::string, std::pair<int64_t, int64_t>>*> items;
  items.reserve(st->df.size());
  for (auto& kv : st->df) items.push_back(&kv);
  auto cmp = [](const auto* a, const auto* b) {
    if (a->second.first != b->second.first) return a->second.first > b->second.first;
    if (a->second.second != b->second.second) return a->second.second < b->second.second;
    return a->first < b->first;
  };
  int64_t n = std::min<int64_t>(top_n, (int64_t)items.size());
  std::partial_sort(items.begin(), items.begin() + n, items.end(), cmp);
  size_t blob_len = 0;
  for (int64_t i = 0; i < n; i++) blob_len += items[i]->first.size();
  char* terms = (char*)malloc(blob_len > 0 ? blob_len : 1);
  int64_t* offs = (int64_t*)malloc(sizeof(int64_t) * (size_t)(n + 1));
  int64_t* cnts = (int64_t*)malloc(sizeof(int64_t) * (size_t)(n > 0 ? n : 1));
  if (!terms || !offs || !cnts) { free(terms); free(offs); free(cnts); return -4; }
  size_t w = 0;
  offs[0] = 0;
  for (int64_t i = 0; i < n; i++) {
    memcpy(terms + w, items[i]->first.data(), items[i]->first.size());
    w += items[i]->first.size();
    offs[i + 1] = (int64_t)w;
    cnts[i] = items[i]->second.first;
  }
  *out_terms = terms;
  *out_offs = offs;
  *out_counts = cnts;
  *out_n = n;
  return 0;
}

void ks_text_df_free(void* handle) { delete (KsDfState*)handle; }

// ABI version: bump whenever an exported signature changes (v2 =
// ks_decode_jpegs emits uint8 pixels; v1 emitted float; v3 adds the
// text hot loop; v4 adds ks_text_hashtf — the bump makes a stale v3
// binary rebuild instead of AttributeError-ing mid-stream).  The
// ctypes loader refuses mismatched binaries instead of reading
// garbage.
int ks_version() { return 4; }

}  // extern "C"
