// XLA FFI host (CPU) implementation of Fisher-vector encoding.
//
// Role (SURVEY.md §2.8): the reference's Fisher-vector encode lives in
// EncEval, a C++ library computing in double precision on the host
// (utils/external/EncEval.scala JNI wrapper).  The TPU path here is f32
// (ops/fisher.py XLA einsums, ops/fisher_pallas.py fused kernel); this
// file is the first-class C++ equivalent of the reference's native tier:
// a double-accumulation host implementation registered as an XLA custom
// call, used as the precision reference in parity tests and as a CPU
// fallback.  Same math as ops/fisher.py § _fisher_encode:
//
//   γ_tk  = softmax_k( log w_k + log N(x_t; μ_k, σ²_k) ) · mask_t
//   Φ¹_k  = (Σγx − s0·μ)/σ / (T·√w_k)
//   Φ²_k  = ((Σγx² − 2μΣγx + s0μ²)/σ² − s0) / (T·√(2w_k))
//   out   = [Φ¹ flattened ; Φ² flattened]           (per image: 2·K·D)
//
// Built against the XLA FFI headers shipped in jaxlib (jax.ffi.include_dir());
// registered from Python via jax.ffi.register_ffi_target (ops/fisher_ffi.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

constexpr double kLog2Pi = 1.8378770664093453;

// One image's encode with double accumulators.  xs: (T, D) strided flat,
// mask: (T,), gmm arrays (K,)/(K, D); out: (2*K*D,).
template <typename In, typename Out>
void EncodeOne(const In* xs, const In* mask, const In* w, const In* mu,
               const In* var, int64_t t_len, int64_t k, int64_t d, Out* out,
               std::vector<double>& s0, std::vector<double>& s1,
               std::vector<double>& s2, std::vector<double>& logp,
               const std::vector<double>& log_norm) {
  std::fill(s0.begin(), s0.end(), 0.0);
  std::fill(s1.begin(), s1.end(), 0.0);
  std::fill(s2.begin(), s2.end(), 0.0);
  double count = 0.0;

  for (int64_t t = 0; t < t_len; ++t) {
    const double m = static_cast<double>(mask[t]);
    if (m == 0.0) continue;
    count += m;
    const In* x = xs + t * d;
    double mx = -INFINITY;
    for (int64_t kk = 0; kk < k; ++kk) {
      double quad = 0.0;
      const In* muk = mu + kk * d;
      const In* vk = var + kk * d;
      for (int64_t dd = 0; dd < d; ++dd) {
        const double diff = static_cast<double>(x[dd]) - static_cast<double>(muk[dd]);
        quad += diff * diff / static_cast<double>(vk[dd]);
      }
      logp[kk] = log_norm[kk] - 0.5 * quad;
      if (logp[kk] > mx) mx = logp[kk];
    }
    double z = 0.0;
    for (int64_t kk = 0; kk < k; ++kk) {
      logp[kk] = std::exp(logp[kk] - mx);
      z += logp[kk];
    }
    for (int64_t kk = 0; kk < k; ++kk) {
      const double gamma = m * logp[kk] / z;
      if (gamma == 0.0) continue;
      s0[kk] += gamma;
      double* s1k = s1.data() + kk * d;
      double* s2k = s2.data() + kk * d;
      for (int64_t dd = 0; dd < d; ++dd) {
        const double xv = static_cast<double>(x[dd]);
        s1k[dd] += gamma * xv;
        s2k[dd] += gamma * xv * xv;
      }
    }
  }

  const double tn = std::max(count, 1.0);
  for (int64_t kk = 0; kk < k; ++kk) {
    const double wk = static_cast<double>(w[kk]);
    const double n1 = tn * std::sqrt(wk);
    const double n2 = tn * std::sqrt(2.0 * wk);
    const In* muk = mu + kk * d;
    const In* vk = var + kk * d;
    Out* phi1 = out + kk * d;
    Out* phi2 = out + (k + kk) * d;
    for (int64_t dd = 0; dd < d; ++dd) {
      const double mukd = static_cast<double>(muk[dd]);
      const double vkd = static_cast<double>(vk[dd]);
      const double sigma = std::sqrt(vkd);
      const double a = (s1[kk * d + dd] - s0[kk] * mukd) / sigma / n1;
      const double b =
          ((s2[kk * d + dd] - 2.0 * mukd * s1[kk * d + dd] + s0[kk] * mukd * mukd) /
               vkd -
           s0[kk]) /
          n2;
      phi1[dd] = static_cast<Out>(a);
      phi2[dd] = static_cast<Out>(b);
    }
  }
}

template <ffi::DataType DT>
ffi::Error FisherEncodeImpl(ffi::Buffer<DT> xs, ffi::Buffer<DT> mask,
                            ffi::Buffer<DT> w, ffi::Buffer<DT> mu,
                            ffi::Buffer<DT> var, ffi::Result<ffi::Buffer<DT>> out) {
  auto xdims = xs.dimensions();
  if (xdims.size() != 3) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "xs must be (n, T, d)");
  }
  const int64_t n = xdims[0], t_len = xdims[1], d = xdims[2];
  auto mdims = mu.dimensions();
  if (mdims.size() != 2 || mdims[1] != d) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "mu must be (K, d)");
  }
  const int64_t k = mdims[0];
  if (mask.element_count() != n * t_len || w.element_count() != k ||
      var.element_count() != k * d || out->element_count() != n * 2 * k * d) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "shape mismatch");
  }

  using T = ffi::NativeType<DT>;
  const T* xp = xs.typed_data();
  const T* mp = mask.typed_data();
  const T* wp = w.typed_data();
  const T* mup = mu.typed_data();
  const T* vp = var.typed_data();
  T* op = out->typed_data();

  // per-component log normalizer: log w_k − ½(Σ_d log σ²_kd + D·log 2π)
  std::vector<double> log_norm(k);
  for (int64_t kk = 0; kk < k; ++kk) {
    double sum_log_var = 0.0;
    for (int64_t dd = 0; dd < d; ++dd) {
      sum_log_var += std::log(static_cast<double>(vp[kk * d + dd]));
    }
    log_norm[kk] = std::log(static_cast<double>(wp[kk])) -
                   0.5 * (sum_log_var + static_cast<double>(d) * kLog2Pi);
  }

  std::vector<double> s0(k), s1(k * d), s2(k * d), logp(k);
  for (int64_t i = 0; i < n; ++i) {
    EncodeOne<T, T>(xp + i * t_len * d, mp + i * t_len, wp, mup, vp, t_len, k,
                    d, op + i * 2 * k * d, s0, s1, s2, logp, log_norm);
  }
  return ffi::Error::Success();
}

}  // namespace

namespace {

// Diagonal-covariance GMM EM, double accumulators, from given initial
// parameters (initialization stays in Python — k-means++ there is seeded
// jax.random, which C++ can't reproduce; EM itself is deterministic).
// Mirrors models/gmm.py § _gmm_fit's em() body: responsibilities from the
// log-density, nk floored at 1e-10, variances floored at min_var, weights
// nk / Σmask.
template <ffi::DataType DT>
ffi::Error GmmEmImpl(ffi::Buffer<DT> x, ffi::Buffer<DT> mask,
                     ffi::Buffer<DT> w0, ffi::Buffer<DT> mu0,
                     ffi::Buffer<DT> var0, ffi::Result<ffi::Buffer<DT>> w_out,
                     ffi::Result<ffi::Buffer<DT>> mu_out,
                     ffi::Result<ffi::Buffer<DT>> var_out, int64_t iters,
                     double min_var) {
  auto xdims = x.dimensions();
  if (xdims.size() != 2) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "x must be (n, d)");
  }
  const int64_t n = xdims[0], d = xdims[1];
  const int64_t k = w0.element_count();
  if (mask.element_count() != n || mu0.element_count() != k * d ||
      var0.element_count() != k * d || w_out->element_count() != k ||
      mu_out->element_count() != k * d || var_out->element_count() != k * d) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "shape mismatch");
  }

  using T = ffi::NativeType<DT>;
  const T* xp = x.typed_data();
  const T* mp = mask.typed_data();

  std::vector<double> w(k), mu(k * d), var(k * d);
  for (int64_t i = 0; i < k; ++i) w[i] = static_cast<double>(w0.typed_data()[i]);
  for (int64_t i = 0; i < k * d; ++i) {
    mu[i] = static_cast<double>(mu0.typed_data()[i]);
    var[i] = static_cast<double>(var0.typed_data()[i]);
  }
  double count = 0.0;
  for (int64_t t = 0; t < n; ++t) count += static_cast<double>(mp[t]);
  if (count <= 0.0) count = 1.0;

  std::vector<double> log_norm(k), logp(k), nk(k), s1(k * d), s2(k * d);
  for (int64_t it = 0; it < iters; ++it) {
    for (int64_t kk = 0; kk < k; ++kk) {
      double sum_log_var = 0.0;
      for (int64_t dd = 0; dd < d; ++dd) sum_log_var += std::log(var[kk * d + dd]);
      log_norm[kk] =
          std::log(w[kk]) - 0.5 * (sum_log_var + static_cast<double>(d) * kLog2Pi);
    }
    std::fill(nk.begin(), nk.end(), 0.0);
    std::fill(s1.begin(), s1.end(), 0.0);
    std::fill(s2.begin(), s2.end(), 0.0);
    for (int64_t t = 0; t < n; ++t) {
      const double m = static_cast<double>(mp[t]);
      if (m == 0.0) continue;
      const T* xt = xp + t * d;
      double mx = -INFINITY;
      for (int64_t kk = 0; kk < k; ++kk) {
        double quad = 0.0;
        for (int64_t dd = 0; dd < d; ++dd) {
          const double diff =
              static_cast<double>(xt[dd]) - mu[kk * d + dd];
          quad += diff * diff / var[kk * d + dd];
        }
        logp[kk] = log_norm[kk] - 0.5 * quad;
        if (logp[kk] > mx) mx = logp[kk];
      }
      double z = 0.0;
      for (int64_t kk = 0; kk < k; ++kk) {
        logp[kk] = std::exp(logp[kk] - mx);
        z += logp[kk];
      }
      for (int64_t kk = 0; kk < k; ++kk) {
        const double r = m * logp[kk] / z;
        if (r == 0.0) continue;
        nk[kk] += r;
        for (int64_t dd = 0; dd < d; ++dd) {
          const double xv = static_cast<double>(xt[dd]);
          s1[kk * d + dd] += r * xv;
          s2[kk * d + dd] += r * xv * xv;
        }
      }
    }
    for (int64_t kk = 0; kk < k; ++kk) {
      const double nkk = std::max(nk[kk], 1e-10);
      for (int64_t dd = 0; dd < d; ++dd) {
        const double m1 = s1[kk * d + dd] / nkk;
        mu[kk * d + dd] = m1;
        var[kk * d + dd] =
            std::max(s2[kk * d + dd] / nkk - m1 * m1, min_var);
      }
      w[kk] = nkk / count;
    }
  }

  for (int64_t i = 0; i < k; ++i) w_out->typed_data()[i] = static_cast<T>(w[i]);
  for (int64_t i = 0; i < k * d; ++i) {
    mu_out->typed_data()[i] = static_cast<T>(mu[i]);
    var_out->typed_data()[i] = static_cast<T>(var[i]);
  }
  return ffi::Error::Success();
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(KsGmmEmF32, GmmEmImpl<ffi::DataType::F32>,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Ret<ffi::Buffer<ffi::DataType::F32>>()
                                  .Ret<ffi::Buffer<ffi::DataType::F32>>()
                                  .Ret<ffi::Buffer<ffi::DataType::F32>>()
                                  .Attr<int64_t>("iters")
                                  .Attr<double>("min_var"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(KsGmmEmF64, GmmEmImpl<ffi::DataType::F64>,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Ret<ffi::Buffer<ffi::DataType::F64>>()
                                  .Ret<ffi::Buffer<ffi::DataType::F64>>()
                                  .Ret<ffi::Buffer<ffi::DataType::F64>>()
                                  .Attr<int64_t>("iters")
                                  .Attr<double>("min_var"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(KsFisherEncodeF32,
                              FisherEncodeImpl<ffi::DataType::F32>,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F32>>()
                                  .Ret<ffi::Buffer<ffi::DataType::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(KsFisherEncodeF64,
                              FisherEncodeImpl<ffi::DataType::F64>,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Arg<ffi::Buffer<ffi::DataType::F64>>()
                                  .Ret<ffi::Buffer<ffi::DataType::F64>>());
