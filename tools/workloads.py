"""Seeded adversarial workload zoo for the serving fleet.

Every scenario is a DETERMINISTIC function of ``(name, seed)``: the
arrival schedule, batch sizes, tenant choices, poison placement, and
payload row seeds all derive from one BLAKE2b-seeded PRNG, so a
workload that kills a canary (or slips past one) replays exactly —
``trace_digest()`` pins the whole schedule to a hash the tests assert
on.  The zoo doubles as the guarded-rollout drill corpus
(``tools/chaos.py --workload rollout``) and a serve_bench leg
(``--scenario NAME``).

Scenarios::

    bursty        quiet baseline with seeded 10x arrival bursts
    diurnal       sinusoidal offered rate over the window
    heavy_tailed  Pareto-ish batch sizes: most tiny, a few huge
    poison_flood  clean warmup, then a window where a fraction of
                  rows carry the poison marker (``MARK`` in x[0])
    tenant_skewed zipf-ish tenant pick: one hot tenant dominates
    drift         payload distribution shifts steadily mid-window
                  (the slow-burn failure a post-commit bake catches)

Usage::

    JAX_PLATFORMS=cpu python tools/workloads.py --scenario poison_flood \
        --seed 7            # print the schedule summary + digest

``MarkerGate`` is the zoo's "bad model": a host stage that raises on
marker rows (the ``tests/test_selfheal.py`` PoisonGate idiom, importable
so registry-published pipelines unpickle).  A version carrying it fails
exactly the rows ``poison_flood`` floods — the canary-vs-guardrails
drill in ``tools/chaos.py`` publishes it as the staged version.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import random
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from keystone_tpu.workflow.transformer import Transformer  # noqa: E402

#: the poison marker (matches tests/test_selfheal.py): a row whose
#: first element is MARK trips MarkerGate
MARK = np.float32(123.0)

SCENARIOS = (
    "bursty",
    "diurnal",
    "heavy_tailed",
    "poison_flood",
    "tenant_skewed",
    "drift",
)


class MarkerGate(Transformer):
    """Host stage that raises when a row's first element is the poison
    marker — the zoo's deterministic bad model version.  Host-side
    (sequential) so the error raises cleanly on the flush thread,
    outside any XLA program; module-level so a registry-published
    pipeline carrying it unpickles by reference."""

    is_host = True
    parallel_host = False

    def params(self):
        return ()

    def apply_one(self, x):
        x = np.asarray(x)
        if x[0] == MARK:
            raise ValueError("poison marker row")
        return x


def build_zoo_pipeline(dim: int = 8, scale: float = 2.0, gate: bool = False):
    """The drill pipeline: NormalizeRows → LinearMapper(eye·scale), so
    a served row's output norm fingerprints WHICH version answered
    (norm == scale).  ``gate=True`` prepends :class:`MarkerGate` — the
    "bad" version that fails marker rows the good one passes."""
    import jax.numpy as jnp

    from keystone_tpu.models.linear import LinearMapper
    from keystone_tpu.ops.stats import NormalizeRows
    from keystone_tpu.workflow import Pipeline

    w = jnp.asarray(np.eye(dim, dtype=np.float32) * scale)
    if gate:
        return Pipeline.of(MarkerGate()) | NormalizeRows() | LinearMapper(w)
    return Pipeline.of(NormalizeRows()) | LinearMapper(w)


class Scenario:
    """A fully materialized, replayable workload: an ordered list of
    arrival events, each ``{"t", "kind", "tenant", "rows", "row_seed",
    "shift"}`` — everything :func:`payload` needs to rebuild the exact
    bytes.  Construct via :func:`make_scenario`."""

    __slots__ = ("name", "seed", "duration_s", "dim", "tenants", "events")

    def __init__(self, name, seed, duration_s, dim, tenants, events):
        self.name = name
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.dim = int(dim)
        self.tenants = int(tenants)
        self.events = events

    def trace(self) -> list:
        """The schedule as plain dicts (JSON-ready, digest input)."""
        return [dict(e) for e in self.events]

    def trace_digest(self) -> str:
        """BLAKE2b over the canonical-JSON schedule: two scenarios with
        the same digest submit byte-identical traffic."""
        blob = json.dumps(
            {
                "name": self.name,
                "seed": self.seed,
                "dim": self.dim,
                "events": self.trace(),
            },
            sort_keys=True,
            separators=(",", ":"),
        ).encode("utf-8")
        return hashlib.blake2b(blob, digest_size=16).hexdigest()

    def total_rows(self) -> int:
        return sum(e["rows"] for e in self.events)

    def poison_rows(self) -> int:
        return sum(e["rows"] for e in self.events if e["kind"] == "poison")

    def summary(self) -> dict:
        kinds: dict = {}
        for e in self.events:
            kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
        return {
            "name": self.name,
            "seed": self.seed,
            "duration_s": self.duration_s,
            "dim": self.dim,
            "events": len(self.events),
            "rows": self.total_rows(),
            "poison_rows": self.poison_rows(),
            "kinds": kinds,
            "digest": self.trace_digest(),
        }


def _zoo_rng(name: str, seed: int) -> random.Random:
    """One PRNG per (scenario, seed), derived through BLAKE2b so
    adjacent integer seeds don't produce correlated streams."""
    digest = hashlib.blake2b(
        f"{name}:{int(seed)}".encode("utf-8"), digest_size=8
    ).digest()
    return random.Random(int.from_bytes(digest, "big"))


def make_scenario(
    name: str,
    seed: int = 0,
    duration_s: float = 2.0,
    qps: float = 200.0,
    dim: int = 8,
    tenants: int = 4,
) -> Scenario:
    """Materialize one zoo scenario.  ``qps`` is the MEAN event rate;
    each scenario shapes arrivals/sizes/content its own way around it.
    Deterministic in ``(name, seed)`` for fixed knobs."""
    if name not in SCENARIOS:
        raise ValueError(f"unknown scenario {name!r}; one of {SCENARIOS}")
    rng = _zoo_rng(name, seed)
    duration_s = float(duration_s)
    n_events = max(1, int(round(qps * duration_s)))
    events = []

    def _event(t, kind="ok", tenant=0, rows=1, shift=0.0):
        events.append(
            {
                "t": round(float(t), 6),
                "kind": kind,
                "tenant": f"t{int(tenant)}",
                "rows": int(rows),
                "row_seed": rng.getrandbits(32),
                "shift": round(float(shift), 6),
            }
        )

    if name == "bursty":
        # quiet baseline + seeded bursts: ~1/8 of events arrive in
        # 10-event clumps at the same instant (queue-depth spikes the
        # admission/shedding layer must absorb)
        t = 0.0
        budget = n_events
        while budget > 0:
            t += rng.expovariate(qps)
            if t >= duration_s:
                t = duration_s * rng.random()
            if rng.random() < 0.125:
                clump = min(budget, 10)
                for _ in range(clump):
                    _event(t, rows=rng.choice((1, 1, 2)))
                budget -= clump
            else:
                _event(t, rows=rng.choice((1, 1, 2)))
                budget -= 1
        events.sort(key=lambda e: e["t"])
    elif name == "diurnal":
        # sinusoidal rate: thin-out by the instantaneous rate so the
        # peak-to-trough swing survives into the schedule
        for i in range(n_events * 2):
            t = duration_s * i / (n_events * 2)
            rate = 0.5 * (1.0 + math.sin(2.0 * math.pi * t / duration_s))
            if rng.random() < rate:
                _event(t, rows=1)
        if not events:
            _event(0.0, rows=1)
    elif name == "heavy_tailed":
        # Pareto-ish batch sizes: most events one row, the tail huge
        # (the oversized submit_many groups that stress max_batch
        # packing and padding buckets)
        t = 0.0
        for _ in range(n_events):
            t += rng.expovariate(qps)
            rows = min(64, max(1, int(rng.paretovariate(1.2))))
            _event(min(t, duration_s), rows=rows)
    elif name == "poison_flood":
        # clean warmup third, then a flood window where 40% of events
        # carry marker rows — against a gated version the canary
        # generation concentrates the failures
        t = 0.0
        for i in range(n_events):
            t += rng.expovariate(qps)
            t = min(t, duration_s)
            in_flood = i >= n_events // 3
            if in_flood and rng.random() < 0.4:
                _event(t, kind="poison", rows=rng.choice((1, 2)))
            else:
                _event(t, rows=rng.choice((1, 1, 2)))
    elif name == "tenant_skewed":
        # zipf-ish tenant pick: tenant 0 takes ~ half the traffic (the
        # fairness/starvation drill for the multi-tenant accountant)
        weights = [1.0 / (k + 1) for k in range(max(1, int(tenants)))]
        total = sum(weights)
        t = 0.0
        for _ in range(n_events):
            t += rng.expovariate(qps)
            r = rng.random() * total
            acc = 0.0
            pick = 0
            for k, w in enumerate(weights):
                acc += w
                if r <= acc:
                    pick = k
                    break
            _event(min(t, duration_s), tenant=pick, rows=1)
    elif name == "drift":
        # distribution drift: payload mean shifts linearly from 0 to 3
        # sigma across the window — the slow-burn regression a canary
        # window can miss and a post-commit bake must catch
        t = 0.0
        for _ in range(n_events):
            t += rng.expovariate(qps)
            t = min(t, duration_s)
            shift = 3.0 * (t / duration_s)
            _event(t, kind="drift" if shift > 0.5 else "ok", shift=shift)
    events.sort(key=lambda e: e["t"])
    return Scenario(name, seed, duration_s, dim, tenants, events)


def payload(event: dict, dim: int) -> np.ndarray:
    """Rebuild one event's exact rows from its recorded ``row_seed``:
    normal rows, plus the marker in x[0] for poison events and the
    recorded mean shift for drift events."""
    rows = int(event["rows"])
    x = (
        np.random.default_rng(int(event["row_seed"]))
        .normal(size=(rows, int(dim)))
        .astype(np.float32)
    )
    if event["kind"] == "poison":
        x[:, 0] = MARK
    shift = float(event.get("shift") or 0.0)
    if shift:
        x = (x + np.float32(shift)).astype(np.float32)
    return x


def play(scenario: Scenario, submit, time_scale: float = 1.0) -> list:
    """Drive ``submit(event, rows_array)`` along the scenario's
    schedule (``time_scale`` compresses it; 0 = as fast as possible)
    and return the per-event results.  ``submit`` exceptions are
    captured as results, not raised — an admission refusal is a
    scheduled outcome, not a replay failure."""
    out = []
    t0 = time.monotonic()
    for event in scenario.events:
        if time_scale > 0.0:
            due = t0 + event["t"] * time_scale
            now = time.monotonic()
            if due > now:
                time.sleep(due - now)
        try:
            out.append(submit(event, payload(event, scenario.dim)))
        except Exception as e:
            out.append(e)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="materialize a seeded zoo scenario and print its "
        "schedule summary + replay digest"
    )
    ap.add_argument("--scenario", default=None, choices=SCENARIOS)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--qps", type=float, default=200.0)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument(
        "--trace",
        action="store_true",
        help="dump the full event schedule, not just the summary",
    )
    args = ap.parse_args(argv)
    names = [args.scenario] if args.scenario else list(SCENARIOS)
    for name in names:
        sc = make_scenario(
            name,
            seed=args.seed,
            duration_s=args.duration,
            qps=args.qps,
            dim=args.dim,
            tenants=args.tenants,
        )
        print(json.dumps(sc.trace() if args.trace else sc.summary(), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
