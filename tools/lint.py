#!/usr/bin/env python
"""Repo-invariant AST linter (stdlib-only; enforced as a tier-1 test).

The codebase carries cross-cutting contracts no unit test sees locally:
fault sites must be registered or they never fire, metric names must
follow the registry convention or dashboards fragment, guard-supervised
code must use monotonic clocks or watchdog math breaks under wall-clock
steps, and obs hooks must stay inert (one ``None`` check) when no
ledger is attached.  This linter pins them at the AST level, so a
violation fails CI the commit it appears.

Rules:

- ``fault-site``   — every string-literal site passed to
  ``fault_point(...)`` / ``SiteSpec(...)`` appears in
  ``keystone_tpu/faults.py``'s ``SITES`` registry (parsed from the
  AST, so the linter never imports the package);
- ``metric-name``  — string-literal names in
  ``metrics.inc/observe/set_gauge/gauge_max/remove_gauge(...)`` (and
  ``REGISTRY.<same>``) match ``subsystem.metric_name`` — lowercase,
  dot-separated, underscore words.  Per-entity fan-out (per replica,
  per site, per rule) must ride LABELS
  (``metrics.inc("serve.replica_flushes", replica=i)``), never the
  name: an interpolated name (f-string/concat/``.format``) or an
  underscore-delimited integer segment (``serve.replica_0_flushes``)
  mints one metric series per entity, fragmenting dashboards and
  unbounding the registry — both are violations.  Tenant-scoped names
  (any ``tenant`` word segment, e.g. ``serve.tenant_submitted``) must
  additionally carry a ``tenant=`` label at the record site: tenant
  fan-out rides ``{tenant=}`` labels, never interpolated or
  per-tenant metric names;
- ``metric-kind``  — one metric name is used as one instrument kind
  across the whole tree (the static twin of
  ``obs.metrics.MetricKindError``);
- ``wall-clock``   — no bare ``time.time()`` inside guard-supervised
  modules (executor, guard, durable, blockstore, stream loaders, serve,
  recovery, multihost): intervals there feed deadline/watchdog/retry
  math and must use ``time.monotonic()``/``perf_counter()``.  Wall
  timestamps that are genuinely wanted take a trailing
  ``# lint: allow-wall-clock`` comment;
- ``obs-gating``   — a variable bound from ``ledger.active()`` is only
  dereferenced under an ``is not None`` guard (the inert-hook
  contract: one ``None`` check when obs is off);
- ``host-sync``    — no ``np.asarray(...)`` / ``.tolist()`` inside a
  ``for``/``while`` loop of the solver sweep modules (block_ls,
  block_weighted_ls, lbfgs): a host read of a device value there
  stalls the async dispatch pipeline the fit-path dataflow relies on
  (double-buffered staging + donated epoch carries).  A deliberate,
  obs-gated read takes a trailing ``# lint: allow-host-sync``;
- ``proc-spawn``   — no direct ``multiprocessing`` import (or
  ``os.fork``/``os.forkpty`` call) outside the serve worker modules
  (``serve/wire.py``, ``serve/worker.py``, ``serve/procfleet.py``):
  a forked JAX runtime inherits locked internals and deadlocks on
  first dispatch, so process management is fenced into the modules
  that enforce the ``spawn`` start method.  A deliberate, safe use
  (an explicit spawn/forkserver context) takes a trailing
  ``# lint: allow-proc-spawn``;
- ``socket``       — no direct ``socket`` import outside the
  cross-host transport modules (``serve/net.py``, ``serve/wire.py``,
  ``serve/ingress.py``):
  a raw socket anywhere else bypasses the heartbeat-lease/fencing
  discipline and the ``serve.net.*`` fault sites that make network
  failure injectable.  A deliberate use takes a trailing
  ``# lint: allow-socket``;
- ``gate``         — every literal ``KEYSTONE_*`` environment read
  (``os.environ.get/[]``/``in os.environ``/``os.getenv``) names a
  variable registered in ``keystone_tpu/planner/registry.py`` — either
  a gate's ``env`` (``GATES``) or the ``OPERATIONAL_ENV`` set (parsed
  from the AST, never imported).  A scattered un-registered gate is
  exactly what the cost-based planner consolidated: dispatch would
  read an env the plan registry doesn't know, so the plan could never
  own the choice and ``keystone plan`` would lie about precedence.
  One-off escape: ``# lint: allow-gate``;
- ``attr``         — literal keyword attribute keys at span/event emit
  sites (``ledger.span/event(...)``, flight-recorder
  ``rec.annotate/finish/batch/batch_update/ops(...)``) must be
  snake_case members of the registered vocabulary
  (``keystone_tpu/obs/ledger.py``'s ``ATTR_VOCABULARY``, parsed from
  the AST like the fault-site registry): a typo'd key vanishes
  silently into the JSONL/ring stream and every reader (obs_report,
  trace_report, jq) quietly reads nothing.  One-off escape:
  ``# lint: allow-attr``.

Escape hatch: a trailing ``# lint: allow-<rule>`` comment allowlists
one line, visibly.

Usage::

    python tools/lint.py [paths...]     # default: keystone_tpu/

Exit status 0 = clean, 1 = violations (printed one per line), 2 = usage.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_TARGET = os.path.join(REPO_ROOT, "keystone_tpu")
FAULTS_PATH = os.path.join(REPO_ROOT, "keystone_tpu", "faults.py")
OBS_LEDGER_PATH = os.path.join(REPO_ROOT, "keystone_tpu", "obs", "ledger.py")
PLANNER_REGISTRY_PATH = os.path.join(
    REPO_ROOT, "keystone_tpu", "planner", "registry.py"
)

#: span/event attribute keys must be snake_case (and registered)
ATTR_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*$")

#: ledger emit methods (receiver must look like the ledger module or a
#: bound active-ledger variable) and flight-recorder emit methods
#: (receiver must look like a recorder binding) whose literal keyword
#: names the ``attr`` rule checks against the registered vocabulary
_LEDGER_EMITS = frozenset({"span", "event"})
_LEDGER_RECEIVERS = frozenset({"ledger", "led", "_ledger"})
_RECORDER_EMITS = frozenset({"annotate", "finish", "batch", "batch_update", "ops"})
_RECORDER_RECEIVERS = frozenset({"rec", "recorder"})
#: named parameters of recorder emit methods that are API control
#: flags, not stream attributes — exempt from the vocabulary so the
#: vocabulary documents ONLY what actually appears in the stream
_RECORDER_CONTROL_KWARGS = frozenset({"only_live"})

#: registry-convention metric names: subsystem.name[.more], lowercase
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")

#: an underscore-delimited pure-integer word inside a name segment
#: (``replica_0``, ``shard_12_bytes``): an entity index baked into the
#: metric NAME — the per-replica label convention says fan-out rides
#: labels, one name per quantity (digits glued to a word — ``bf16``,
#: ``p99`` — are fine)
METRIC_INDEX_SEGMENT_RE = re.compile(r"(^|_)\d+(_|$)")

#: a tenant-scoped metric name (any ``tenant`` word in a segment:
#: ``serve.tenant_submitted``): per-tenant fan-out must ride a
#: ``tenant=`` LABEL on the same call — a tenant name baked into the
#: metric name (or a tenant-scoped series recorded without its label)
#: mints/merges series per tenant and fragments every dashboard
METRIC_TENANT_WORD_RE = re.compile(r"(^|[._])tenants?(_|$|\.)")

#: fleet-scoped metric names (``serve.fleet.apply_seconds``): series
#: aggregated from worker-shipped telemetry span every worker and host
#: in the fleet, so a write without ``worker=``/``host=`` labels merges
#: every peer into one indistinguishable series — the per-worker
#: breakdown is the entire point of shipping them
METRIC_FLEET_WORD_RE = re.compile(r"(^|[._])fleet(_|$|\.)")

#: metrics-registry write methods → instrument kind
_METRIC_KINDS = {
    "inc": "counter",
    "observe": "histogram",
    "set_gauge": "gauge",
    "gauge_max": "gauge",
    "remove_gauge": "gauge",
}

#: modules whose timing feeds deadline/watchdog/retry/backoff math —
#: wall clock steps (NTP, suspend) must not corrupt them.  Paths are
#: repo-root-relative prefixes.
SUPERVISED_PREFIXES = (
    "keystone_tpu/workflow/executor.py",
    "keystone_tpu/workflow/recovery.py",
    "keystone_tpu/workflow/blockstore.py",
    "keystone_tpu/utils/guard.py",
    "keystone_tpu/utils/durable.py",
    "keystone_tpu/loaders/stream.py",
    "keystone_tpu/parallel/multihost.py",
    "keystone_tpu/serve/",
)

#: the only modules that may touch ``multiprocessing`` directly: the
#: process-fleet worker modules, which enforce the spawn start method
#: (forked JAX runtimes deadlock).  Everything else goes through them.
PROC_SPAWN_ALLOWED = (
    "keystone_tpu/serve/wire.py",
    "keystone_tpu/serve/worker.py",
    "keystone_tpu/serve/procfleet.py",
)

#: the only modules that may import ``socket`` directly: the transport
#: trio — ``serve/net.py`` (lease-fenced cross-host connections, fault
#: sites on every connect/send/recv), ``serve/wire.py`` (CRC-checked
#: stream framing), and ``serve/ingress.py`` (the selector-driven front
#: end: non-blocking accept/sniff/recv_into is the whole point of the
#: module, and its frames ride the wire-v2 CRC discipline).  A raw
#: socket anywhere else bypasses the lease/fencing discipline and the
#: ``serve.net.*`` chaos surface, so network use routes through them.
SOCKET_ALLOWED = (
    "keystone_tpu/serve/net.py",
    "keystone_tpu/serve/wire.py",
    "keystone_tpu/serve/ingress.py",
)

#: solver modules whose BCD sweep / epoch loops ride the async fit-path
#: dataflow: an un-annotated host sync inside a loop there silently
#: re-serializes the double-buffered feed a future edit can't see
#: locally.  Scoped per-file like the wall-clock rule.
SOLVER_SYNC_PREFIXES = (
    "keystone_tpu/models/block_ls.py",
    "keystone_tpu/models/block_weighted_ls.py",
    "keystone_tpu/models/lbfgs.py",
    "keystone_tpu/models/kernel_ridge.py",
)

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow-([a-z-]+)")


class Violation:
    def __init__(self, path: str, line: int, rule: str, message: str):
        self.path = path
        self.line = line
        self.rule = rule
        self.message = message

    def __str__(self):
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


def load_registered_sites(faults_path: str = FAULTS_PATH) -> frozenset:
    """Parse ``SITES = {...}`` out of faults.py WITHOUT importing it —
    the linter must run in any environment, including ones where the
    package's dependencies are absent."""
    with open(faults_path) as f:
        tree = ast.parse(f.read(), filename=faults_path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "SITES":
                    if isinstance(node.value, ast.Set):
                        return frozenset(
                            e.value
                            for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        )
    raise RuntimeError(f"could not locate SITES registry in {faults_path}")


def load_attr_vocabulary(ledger_path: str = OBS_LEDGER_PATH) -> frozenset:
    """Parse ``ATTR_VOCABULARY = {...}`` out of obs/ledger.py WITHOUT
    importing the package (the :func:`load_registered_sites`
    discipline)."""
    with open(ledger_path) as f:
        tree = ast.parse(f.read(), filename=ledger_path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "ATTR_VOCABULARY":
                    if isinstance(node.value, ast.Set):
                        return frozenset(
                            e.value
                            for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        )
    raise RuntimeError(f"could not locate ATTR_VOCABULARY in {ledger_path}")


def load_gate_env(registry_path: str = PLANNER_REGISTRY_PATH) -> frozenset:
    """Parse the registered ``KEYSTONE_*`` environment variables out of
    ``planner/registry.py`` WITHOUT importing it: every ``"env"`` value
    in the ``GATES``/``KNOBS`` dict literals plus every member of the
    ``OPERATIONAL_ENV`` set literal."""
    with open(registry_path) as f:
        tree = ast.parse(f.read(), filename=registry_path)
    names: set = set()
    found_gates = found_ops = False
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id in ("GATES", "KNOBS") and isinstance(
                node.value, ast.Dict
            ):
                if t.id == "GATES":
                    found_gates = True
                for spec in node.value.values:
                    if not isinstance(spec, ast.Dict):
                        continue
                    for k, v in zip(spec.keys, spec.values):
                        if (
                            isinstance(k, ast.Constant)
                            and k.value == "env"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)
                        ):
                            names.add(v.value)
            elif t.id == "OPERATIONAL_ENV" and isinstance(
                node.value, ast.Set
            ):
                found_ops = True
                names.update(
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                )
    if not (found_gates and found_ops):
        raise RuntimeError(
            f"could not locate GATES/OPERATIONAL_ENV in {registry_path}"
        )
    return frozenset(names)


def _allowed(lines: List[str], lineno: int, rule: str) -> bool:
    if 1 <= lineno <= len(lines):
        m = _ALLOW_RE.search(lines[lineno - 1])
        if m and m.group(1) == rule:
            return True
    return False


def _receiver_name(func: ast.AST) -> Optional[Tuple[str, str]]:
    """('metrics'|'REGISTRY', method) for metrics-registry write calls."""
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    v = func.value
    if isinstance(v, ast.Name) and v.id in ("metrics", "REGISTRY"):
        return v.id, attr
    # metrics.REGISTRY.remove_gauge(...) — attribute chain ending REGISTRY
    if isinstance(v, ast.Attribute) and v.attr == "REGISTRY":
        return "REGISTRY", attr
    return None


def _str_arg0(call: ast.Call) -> Optional[Tuple[str, int]]:
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value, call.args[0].lineno
    return None


def _is_supervised(rel_path: str) -> bool:
    rel = rel_path.replace(os.sep, "/")
    return any(rel.startswith(p) or rel == p.rstrip("/") for p in SUPERVISED_PREFIXES)


def _is_solver_sweep(rel_path: str) -> bool:
    rel = rel_path.replace(os.sep, "/")
    return any(rel.startswith(p) for p in SOLVER_SYNC_PREFIXES)


def _proc_spawn_allowed(rel_path: str) -> bool:
    rel = rel_path.replace(os.sep, "/")
    return any(rel == p for p in PROC_SPAWN_ALLOWED)


def _socket_allowed(rel_path: str) -> bool:
    rel = rel_path.replace(os.sep, "/")
    return any(rel == p for p in SOCKET_ALLOWED)


# ------------------------------------------------------------ obs gating


def _guarded_uses(func_body: List[ast.stmt], var: str) -> List[int]:
    """Line numbers of UNGUARDED dereferences of ``var`` (attribute
    access / call / subscript on it) within ``func_body``, where a
    guard is any enclosing ``if var is not None`` (use in body),
    ``if var is None`` (use in orelse), a conditional expression with
    the same test, or a preceding early exit ``if var is None:
    return/raise/continue/break`` in the same suite."""

    def test_is(node: ast.AST, op_type) -> bool:
        return (
            isinstance(node, ast.Compare)
            and isinstance(node.left, ast.Name)
            and node.left.id == var
            and len(node.ops) == 1
            and isinstance(node.ops[0], op_type)
            and len(node.comparators) == 1
            and isinstance(node.comparators[0], ast.Constant)
            and node.comparators[0].value is None
        )

    def test_guards(node: ast.AST) -> bool:
        # `var is not None`, or conjunctions containing it
        if test_is(node, ast.IsNot):
            return True
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
            return any(test_guards(v) for v in node.values)
        return False

    bad: List[int] = []

    def deref_lines(node: ast.AST) -> List[int]:
        out = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and isinstance(
                sub.value, ast.Name
            ) and sub.value.id == var:
                out.append(sub.lineno)
            elif isinstance(sub, ast.Subscript) and isinstance(
                sub.value, ast.Name
            ) and sub.value.id == var:
                out.append(sub.lineno)
        return out

    def walk_suite(suite: List[ast.stmt], guarded: bool) -> None:
        g = guarded
        for stmt in suite:
            walk_stmt(stmt, g)
            # early exit establishes the guard for the REST of the suite
            if (
                isinstance(stmt, ast.If)
                and test_is(stmt.test, ast.Is)
                and stmt.body
                and all(
                    isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break))
                    for s in stmt.body[-1:]
                )
            ):
                g = True

    def walk_stmt(stmt: ast.stmt, guarded: bool) -> None:
        if isinstance(stmt, ast.If):
            if test_guards(stmt.test):
                walk_suite(stmt.body, True)
                walk_suite(stmt.orelse, guarded)
                return
            if test_is(stmt.test, ast.Is):
                walk_suite(stmt.body, guarded)
                walk_suite(stmt.orelse, True)
                return
            walk_suite(stmt.body, guarded)
            walk_suite(stmt.orelse, guarded)
            for line in deref_lines(stmt.test):
                if not guarded:
                    bad.append(line)
            return
        if isinstance(stmt, (ast.For, ast.While, ast.With, ast.Try)):
            for line in _stmt_header_derefs(stmt):
                if not guarded:
                    bad.append(line)
            for suite in _stmt_suites(stmt):
                walk_suite(suite, guarded)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_suite(stmt.body, guarded)  # nested fn: same discipline
            return
        if not guarded:
            # IfExp guards inline: `x.f() if x is not None else y`
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.IfExp) and test_guards(sub.test):
                    for line in deref_lines(sub.orelse):
                        bad.append(line)
                    break
            else:
                bad.extend(deref_lines(stmt))

    def _stmt_header_derefs(stmt) -> List[int]:
        headers = []
        if isinstance(stmt, ast.For):
            headers = deref_lines(stmt.iter)
        elif isinstance(stmt, ast.While):
            headers = deref_lines(stmt.test)
        elif isinstance(stmt, ast.With):
            headers = [ln for item in stmt.items for ln in deref_lines(item)]
        return headers

    def _stmt_suites(stmt) -> List[List[ast.stmt]]:
        suites = [getattr(stmt, "body", [])]
        suites.append(getattr(stmt, "orelse", []))
        suites.append(getattr(stmt, "finalbody", []))
        for h in getattr(stmt, "handlers", []):
            suites.append(h.body)
        return [s for s in suites if s]

    walk_suite(func_body, False)
    return sorted(set(bad))


# -------------------------------------------------------------- lint core


def lint_source(
    rel_path: str,
    source: str,
    sites: frozenset,
    metric_kinds: Dict[str, Tuple[str, str, int]],
    supervised: Optional[bool] = None,
    solver_scoped: Optional[bool] = None,
    attr_vocab: Optional[frozenset] = None,
    proc_fenced: Optional[bool] = None,
    socket_fenced: Optional[bool] = None,
    gate_env: Optional[frozenset] = None,
) -> List[Violation]:
    """Lint one file's source.  ``metric_kinds`` accumulates
    name → (kind, path, line) across files for the metric-kind rule.
    ``supervised`` overrides the path-based wall-clock scoping,
    ``solver_scoped`` the host-sync scoping, ``proc_fenced`` the
    proc-spawn scoping, and ``socket_fenced`` the socket scoping
    (tests).  ``attr_vocab``: the registered span/event attribute
    vocabulary — None skips the ``attr`` rule (``lint_paths`` loads it
    from obs/ledger.py).  ``gate_env``: the registered ``KEYSTONE_*``
    env names — None skips the ``gate`` rule (``lint_paths`` loads it
    from planner/registry.py)."""
    out: List[Violation] = []
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError as e:
        return [Violation(rel_path, e.lineno or 0, "syntax", str(e))]
    if supervised is None:
        supervised = _is_supervised(rel_path)
    if solver_scoped is None:
        solver_scoped = _is_solver_sweep(rel_path)
    if proc_fenced is None:
        proc_fenced = not _proc_spawn_allowed(rel_path)
    if socket_fenced is None:
        socket_fenced = not _socket_allowed(rel_path)

    # ---- socket: a raw socket import outside the transport fence
    if socket_fenced:
        for node in ast.walk(tree):
            bad_line = None
            what = None
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "socket":
                        bad_line, what = node.lineno, f"import {alias.name}"
                        break
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "socket":
                    bad_line = node.lineno
                    what = f"from {node.module} import"
            if bad_line is not None and not _allowed(
                lines, bad_line, "socket"
            ):
                out.append(
                    Violation(
                        rel_path,
                        bad_line,
                        "socket",
                        f"{what} outside the cross-host transport fence "
                        "(serve/net.py, serve/wire.py, serve/ingress.py) "
                        "— a raw socket "
                        "bypasses the lease/fencing discipline and the "
                        "serve.net.* fault sites; route network use "
                        "through the net fleet (or annotate "
                        "'# lint: allow-socket' for a deliberate, "
                        "fenced use)",
                    )
                )

    # ---- proc-spawn: multiprocessing/os.fork outside the worker fence
    if proc_fenced:
        for node in ast.walk(tree):
            bad_line = None
            what = None
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "multiprocessing":
                        bad_line, what = node.lineno, f"import {alias.name}"
                        break
            elif isinstance(node, ast.ImportFrom):
                mod = (node.module or "").split(".")[0]
                if mod == "multiprocessing":
                    bad_line, what = node.lineno, f"from {node.module} import"
                elif mod == "os":
                    # `from os import fork` escapes the attribute check
                    forked = [
                        a.name
                        for a in node.names
                        if a.name in ("fork", "forkpty")
                    ]
                    if forked:
                        bad_line = node.lineno
                        what = f"from os import {', '.join(forked)}"
            elif isinstance(node, ast.Call):
                f = node.func
                # ANY <name>.fork()/<name>.forkpty() — aliased os
                # modules (`import os as _os`) must not slip the fence
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in ("fork", "forkpty")
                    and isinstance(f.value, ast.Name)
                ):
                    bad_line = node.lineno
                    what = f"{f.value.id}.{f.attr}()"
            if bad_line is not None and not _allowed(
                lines, bad_line, "proc-spawn"
            ):
                out.append(
                    Violation(
                        rel_path,
                        bad_line,
                        "proc-spawn",
                        f"{what} outside the serve worker fence "
                        "(serve/wire.py, serve/worker.py, "
                        "serve/procfleet.py) — forked JAX runtimes "
                        "deadlock; route process use through the "
                        "process fleet (or annotate "
                        "'# lint: allow-proc-spawn' for an explicit "
                        "spawn/forkserver context)",
                    )
                )

    # ---- gate: literal KEYSTONE_* env reads vs the planner registry
    if gate_env is not None:

        def _is_environ(expr: ast.AST) -> bool:
            return (
                isinstance(expr, ast.Attribute) and expr.attr == "environ"
            ) or (isinstance(expr, ast.Name) and expr.id == "environ")

        def _keystone_name(expr: ast.AST) -> Optional[Tuple[str, int]]:
            if isinstance(expr, ast.Constant) and isinstance(
                expr.value, str
            ) and expr.value.startswith("KEYSTONE_"):
                return expr.value, expr.lineno
            return None

        def _check_gate(name_line: Optional[Tuple[str, int]]) -> None:
            if name_line is None:
                return
            name, lineno = name_line
            if name in gate_env or _allowed(lines, lineno, "gate"):
                return
            out.append(
                Violation(
                    rel_path,
                    lineno,
                    "gate",
                    f"env {name!r} is not registered in the planner gate "
                    "registry (planner/registry.py GATES env / "
                    "OPERATIONAL_ENV) — an unregistered KEYSTONE_* read "
                    "is a scattered gate the physical plan can never "
                    "own; register it (or annotate '# lint: allow-gate' "
                    "for a deliberate off-registry variable)",
                )
            )

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in ("get", "setdefault", "pop")
                    and _is_environ(f.value)
                    and node.args
                ):
                    _check_gate(_keystone_name(node.args[0]))
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr == "getenv"
                    and node.args
                ):
                    _check_gate(_keystone_name(node.args[0]))
            elif isinstance(node, ast.Subscript) and _is_environ(
                node.value
            ):
                _check_gate(_keystone_name(node.slice))
            elif isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
            ):
                if any(_is_environ(c) for c in node.comparators):
                    _check_gate(_keystone_name(node.left))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # ---- fault-site: fault_point("site", ...) / SiteSpec("site", ...)
        callee = (
            func.id
            if isinstance(func, ast.Name)
            else func.attr
            if isinstance(func, ast.Attribute)
            else None
        )
        if callee in ("fault_point", "SiteSpec"):
            arg = _str_arg0(node)
            if arg is not None:
                site, lineno = arg
                if site not in sites and not _allowed(
                    lines, lineno, "fault-site"
                ):
                    out.append(
                        Violation(
                            rel_path,
                            lineno,
                            "fault-site",
                            f"site {site!r} is not in the faults.SITES "
                            "registry — it would never fire",
                        )
                    )
        # ---- metric-name / metric-kind
        recv = _receiver_name(func)
        if recv is not None and recv[1] in _METRIC_KINDS:
            arg = _str_arg0(node)
            if (
                arg is None
                and node.args
                and (
                    isinstance(node.args[0], (ast.JoinedStr, ast.BinOp))
                    or (
                        isinstance(node.args[0], ast.Call)
                        and isinstance(node.args[0].func, ast.Attribute)
                        and node.args[0].func.attr == "format"
                    )
                )
                and not _allowed(lines, node.args[0].lineno, "metric-name")
            ):
                # an f-string / concatenated metric name is how an
                # entity index sneaks into the NAME (one series minted
                # per replica/site/...) — fan-out must use labels
                out.append(
                    Violation(
                        rel_path,
                        node.args[0].lineno,
                        "metric-name",
                        "interpolated metric name — per-entity fan-out "
                        "must ride labels "
                        "(metrics.inc('serve.replica_flushes', "
                        "replica=i)), not name interpolation",
                    )
                )
            if arg is not None:
                mname, lineno = arg
                if not METRIC_NAME_RE.match(mname) and not _allowed(
                    lines, lineno, "metric-name"
                ):
                    out.append(
                        Violation(
                            rel_path,
                            lineno,
                            "metric-name",
                            f"metric {mname!r} does not match the "
                            "registry convention "
                            "(lowercase dotted: subsystem.metric_name)",
                        )
                    )
                elif any(
                    METRIC_INDEX_SEGMENT_RE.search(seg)
                    for seg in mname.split(".")
                ) and not _allowed(lines, lineno, "metric-name"):
                    out.append(
                        Violation(
                            rel_path,
                            lineno,
                            "metric-name",
                            f"metric {mname!r} bakes an entity index "
                            "into the name — per-replica/per-entity "
                            "fan-out must ride labels (one name per "
                            "quantity)",
                        )
                    )
                elif (
                    METRIC_TENANT_WORD_RE.search(mname)
                    and recv[1] != "remove_gauge"
                    and not any(kw.arg == "tenant" for kw in node.keywords)
                    and not _allowed(lines, lineno, "metric-name")
                ):
                    out.append(
                        Violation(
                            rel_path,
                            lineno,
                            "metric-name",
                            f"tenant-scoped metric {mname!r} recorded "
                            "without a tenant= label — per-tenant "
                            "fan-out rides {tenant=} labels, never the "
                            "metric name",
                        )
                    )
                elif (
                    METRIC_FLEET_WORD_RE.search(mname)
                    and recv[1] != "remove_gauge"
                    and not any(
                        kw.arg in ("worker", "host")
                        for kw in node.keywords
                    )
                    and not _allowed(lines, lineno, "metric-name")
                ):
                    out.append(
                        Violation(
                            rel_path,
                            lineno,
                            "metric-name",
                            f"fleet-scoped metric {mname!r} recorded "
                            "without a worker=/host= label — worker-"
                            "shipped series carry their fan-out as "
                            "{worker=,host=} labels, never the metric "
                            "name",
                        )
                    )
                kind = _METRIC_KINDS[recv[1]]
                prev = metric_kinds.get(mname)
                if prev is None:
                    metric_kinds[mname] = (kind, rel_path, lineno)
                elif prev[0] != kind and not _allowed(
                    lines, lineno, "metric-kind"
                ):
                    out.append(
                        Violation(
                            rel_path,
                            lineno,
                            "metric-kind",
                            f"metric {mname!r} used as a {kind} here but "
                            f"as a {prev[0]} at {prev[1]}:{prev[2]} — "
                            "instrument kinds are exclusive per name",
                        )
                    )
        # ---- attr: span/event attribute keys from the registered vocab
        if attr_vocab is not None and isinstance(func, ast.Attribute):
            recv = func.value
            is_emit = isinstance(recv, ast.Name) and (
                (func.attr in _LEDGER_EMITS and recv.id in _LEDGER_RECEIVERS)
                or (
                    func.attr in _RECORDER_EMITS
                    and recv.id in _RECORDER_RECEIVERS
                )
            )
            if is_emit:
                recorder_emit = func.attr in _RECORDER_EMITS
                for kw in node.keywords:
                    if kw.arg is None:  # **attrs splat: dynamic, not ours
                        continue
                    if recorder_emit and kw.arg in _RECORDER_CONTROL_KWARGS:
                        continue  # API flag, never lands in the stream
                    if (
                        ATTR_KEY_RE.match(kw.arg)
                        and kw.arg in attr_vocab
                    ) or _allowed(lines, kw.value.lineno, "attr"):
                        continue
                    out.append(
                        Violation(
                            rel_path,
                            kw.value.lineno,
                            "attr",
                            f"span/event attribute key {kw.arg!r} is not a "
                            "snake_case member of the registered vocabulary "
                            "(obs/ledger.ATTR_VOCABULARY) — a typo'd key "
                            "vanishes silently from every trace reader",
                        )
                    )
        # ---- wall-clock: time.time() in supervised modules
        if (
            supervised
            and isinstance(func, ast.Attribute)
            and func.attr == "time"
            and isinstance(func.value, ast.Name)
            and func.value.id == "time"
            and not _allowed(lines, node.lineno, "wall-clock")
        ):
            out.append(
                Violation(
                    rel_path,
                    node.lineno,
                    "wall-clock",
                    "bare time.time() in guard-supervised code; use "
                    "time.monotonic()/perf_counter() (or annotate "
                    "'# lint: allow-wall-clock' for a true timestamp)",
                )
            )

    # ---- host-sync: np.asarray / .tolist() inside solver sweep loops
    if solver_scoped:
        seen_syncs: set = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                sync = None
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "asarray"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy")
                ):
                    sync = "np.asarray(...)"
                elif isinstance(f, ast.Attribute) and f.attr == "tolist":
                    sync = ".tolist()"
                if sync is None or (node.lineno, sync) in seen_syncs:
                    continue  # nested loops revisit the same call
                seen_syncs.add((node.lineno, sync))
                if not _allowed(lines, node.lineno, "host-sync"):
                    out.append(
                        Violation(
                            rel_path,
                            node.lineno,
                            "host-sync",
                            f"{sync} inside a solver sweep/epoch loop "
                            "forces a host sync that stalls the async "
                            "dispatch pipeline (double-buffered feed + "
                            "donated carries); hoist it out of the loop "
                            "or annotate '# lint: allow-host-sync' for "
                            "a deliberate, obs-gated read",
                        )
                    )

    # ---- obs-gating: per function scope
    scopes: List[Tuple[List[ast.stmt], ast.AST]] = [(tree.body, tree)]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append((node.body, node))
    for body, _scope in scopes:
        # variables bound from *.active() in THIS scope's direct body
        for stmt in body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == "active"
            ):
                var = stmt.targets[0].id
                for lineno in _guarded_uses(body, var):
                    if lineno <= stmt.lineno:
                        continue  # a different binding earlier in the suite
                    if not _allowed(lines, lineno, "obs-gating"):
                        out.append(
                            Violation(
                                rel_path,
                                lineno,
                                "obs-gating",
                                f"{var!r} (bound from ledger.active()) is "
                                "dereferenced without an 'is not None' "
                                "guard — obs hooks must stay inert when "
                                "no ledger is attached",
                            )
                        )
    return out


def lint_paths(
    paths: List[str],
    sites: Optional[frozenset] = None,
    attr_vocab: Optional[frozenset] = None,
    gate_env: Optional[frozenset] = None,
) -> List[Violation]:
    if sites is None:
        sites = load_registered_sites()
    if attr_vocab is None:
        attr_vocab = load_attr_vocabulary()
    if gate_env is None:
        gate_env = load_gate_env()
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                files.extend(
                    os.path.join(root, n) for n in names if n.endswith(".py")
                )
        elif p.endswith(".py"):
            files.append(p)
    violations: List[Violation] = []
    metric_kinds: Dict[str, Tuple[str, str, int]] = {}
    for path in sorted(files):
        rel = os.path.relpath(path, REPO_ROOT)
        with open(path) as f:
            source = f.read()
        violations.extend(
            lint_source(
                rel,
                source,
                sites,
                metric_kinds,
                attr_vocab=attr_vocab,
                gate_env=gate_env,
            )
        )
    return violations


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 2
    paths = argv or [DEFAULT_TARGET]
    violations = lint_paths(paths)
    for v in violations:
        print(v)
    if violations:
        print(f"lint: {len(violations)} violation(s)")
        return 1
    print("lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
