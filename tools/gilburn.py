"""The compute-bound (GIL-held) bench transformer, in its own module.

``GilBurnFeature`` must be defined at module level so worker processes
can unpickle it from a staged deploy payload — and importing it pulls
``keystone_tpu.workflow.transformer`` (hence jax), so it lives HERE
rather than in ``tools/serve_bench.py``: serve_bench keeps its
zero-top-level-keystone-imports design (``--help`` stays instant, and
legs configure JAX platforms before any backend initializes), while
the procs A/B imports this module lazily when it actually builds the
workload.
"""

from __future__ import annotations

import numpy as np

from keystone_tpu.workflow.transformer import Transformer


class GilBurnFeature(Transformer):
    """Deterministic GIL-bound per-row featurizer: scales each row by
    ``0.5 + crc_mix(row)/2`` where ``crc_mix`` is ``rounds`` chained
    CRC32 passes over the row's bytes — pure interpreter-loop work, no
    BLAS, no GIL release, like real tokenize/ngram featurization
    stages.  Bit-deterministic (integer CRC math on exact bytes), so
    thread and process fleets must produce identical output bytes.
    Pickles cleanly (worker processes load it from the staged
    payload)."""

    #: pure-Python host compute: the stage-fusion rule must not inline
    #: it into a jitted chain (its apply is untraceable by design)
    fusable = False

    def __init__(self, rounds: int = 300):
        self.rounds = int(rounds)

    def params(self):
        return (self.rounds,)

    def _burn(self, row_bytes: bytes) -> float:
        import zlib

        h = zlib.crc32(row_bytes)
        for _ in range(self.rounds):
            h = zlib.crc32(row_bytes, h)
        return (h % 1000003) / 1000003.0

    def apply_dataset(self, ds):
        import jax.numpy as jnp

        xs = np.asarray(ds.array)
        out = np.empty_like(xs)
        for i in range(xs.shape[0]):
            out[i] = xs[i] * np.float32(0.5 + 0.5 * self._burn(xs[i].tobytes()))
        return ds.with_array(jnp.asarray(out))
