"""Instrumented north-star fit: where do the ~34 s go?

Splits fit wall-clock into optimizer rule batches (CSE / node-choice /
materialize / fusion) and per-node execute times (device-synchronized),
at exactly the bench.py fit-leg config.  Run on the chip:

    python tools/profile_fit.py [n]
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root, BEFORE any
# keystone_tpu/bench import — `python tools/profile_fit.py` has tools/
# as sys.path[0] and keystone_tpu is not an installed package

from keystone_tpu.utils.compile_cache import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

from bench import (  # noqa: E402 — the profiled config IS the bench fit config
    FIT_CLASSES,
    FIT_EPOCHS,
    FIT_GMM_K,
    FIT_N as _BENCH_FIT_N,
    FIT_SOLVER_BLOCK,
    IMAGE_HW,
    PCA_DIMS,
)

_args = [a for a in sys.argv[1:] if not a.startswith("-")]
FIT_N = int(_args[0]) if _args else _BENCH_FIT_N


def main():
    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import Config, ImageNetSiftLcsFV
    from keystone_tpu.workflow.executor import GraphExecutor
    from keystone_tpu.workflow.pipeline import PipelineEnv

    cfg = Config(
        num_classes=FIT_CLASSES,
        synthetic_n=FIT_N,
        image_size=IMAGE_HW,
        gmm_k=FIT_GMM_K,
        pca_dims=PCA_DIMS,
        num_epochs=FIT_EPOCHS,
        solver_block_size=FIT_SOLVER_BLOCK,
    )
    train = ImageNetLoader.synthetic(
        FIT_N, FIT_CLASSES, size=(IMAGE_HW, IMAGE_HW), seed=1
    )

    from keystone_tpu.workflow import graph as G

    repeat = "--repeat" in sys.argv
    t_all0 = time.perf_counter()
    t0 = time.perf_counter()
    pipe = ImageNetSiftLcsFV.build(cfg, train.data, train.labels)
    t_build = time.perf_counter() - t0

    # mimic Pipeline.fit(): optimize then execute estimator nodes, but
    # timed per rule batch / per rule
    opt = PipelineEnv.get_optimizer()
    g = pipe.graph

    batch_times = {}
    for batch in opt.batches:
        tb = time.perf_counter()
        for _ in range(batch.strategy.max_iterations):
            from keystone_tpu.workflow.optimizer import _graph_fingerprint

            before = _graph_fingerprint(g)
            for rule in batch.rules:
                g = rule.apply(g)
            if _graph_fingerprint(g) == before:
                break
        batch_times[batch.name] = time.perf_counter() - tb

    t0 = time.perf_counter()
    ex = GraphExecutor(g, profile=True)
    for n in g.topological_nodes():
        if isinstance(g.operators[n], G.EstimatorOperator):
            ex.execute(n)
    t_exec = time.perf_counter() - t0
    if repeat:  # second walk in the same process: jit caches warm, so
        # node times are dispatch+device, not tracing/compile-cache loads
        t0 = time.perf_counter()
        ex = GraphExecutor(g, profile=True)
        for n in g.topological_nodes():
            if isinstance(g.operators[n], G.EstimatorOperator):
                ex.execute(n)
        t_exec = time.perf_counter() - t0
    t_total = time.perf_counter() - t_all0

    print(f"n={FIT_N}  total={t_total:.2f}s  build={t_build:.3f}s  "
          f"exec={t_exec:.2f}s")
    print("optimizer batches:")
    for k, v in batch_times.items():
        print(f"  {k:<14} {v:8.2f}s")
    print("top execute nodes (device-synced):")
    items = sorted(ex.timings.items(), key=lambda kv: -kv[1])[:20]
    for node, secs in items:
        label = g.operators[node].label() if node in g.operators else str(node)
        print(f"  {secs:8.3f}s  {node.id}:{label[:100]}")


if __name__ == "__main__":
    main()
