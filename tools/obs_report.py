"""Render a run-ledger JSONL file into a human (or JSON) summary.

The read side of ``keystone_tpu.obs``: ``Pipeline.fit`` (with
``KEYSTONE_OBS_DIR`` set), ``tools/chaos.py --ledger``, and bench.py all
write JSONL ledgers; this tool folds one back into the questions an
operator actually asks::

    JAX_PLATFORMS=cpu python tools/obs_report.py /tmp/obs/run_abc.jsonl
    python tools/obs_report.py /tmp/obs            # newest run in a dir
    python tools/obs_report.py run.jsonl --json    # machine-readable

Sections (each only when the run recorded it):

- **stages**: top executor stages by total span seconds, with attempt /
  retry counts and failed-attempt time;
- **retries**: retry totals across executor, durable I/O, blockstore,
  and streams;
- **convergence**: per-solver epoch series (objective / grad norm /
  distortion / log-likelihood — whatever the solver emitted);
- **io**: blockstore bytes read/written, durable corruption/fallback,
  stream batch latency, from the run's last metrics snapshot;
- **memory**: HBM and host-RSS watermarks;
- **ingress**: front-end health — accepts, binary/HTTP connection
  split, frames, per-kind frame errors, parse/admit time, and the
  bytes-copied counter that IS the zero-copy claim;
- **fleet**: worker-shipped telemetry aggregated at the router —
  per-worker/per-host apply and wire-RTT series plus
  retransmit/late-discard counts;
- **faults**: per-site injected counts (chaos runs).

``summarize()`` / ``render()`` are importable — bench.py embeds the
summary dict in its round artifacts.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def resolve_ledger_path(path: str) -> str:
    """A .jsonl file, or a directory → its most recently modified run."""
    if os.path.isdir(path):
        runs = glob.glob(os.path.join(path, "run_*.jsonl"))
        if not runs:
            raise FileNotFoundError(f"no run_*.jsonl ledgers under {path}")
        return max(runs, key=os.path.getmtime)
    return path


def load_events(path: str) -> List[dict]:
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                # a torn final line (killed process) must not hide the
                # rest of the run
                continue
    return events


def _counter_total(snapshot: dict, name: str) -> float:
    return sum(
        v
        for k, v in (snapshot.get("counters") or {}).items()
        if k == name or k.startswith(name + "{")
    )


def _label_totals(snapshot: dict, name: str, label: str) -> Dict[str, float]:
    """``name{...label=x...}`` counters → {x: total} (summed across the
    other labels)."""
    out: Dict[str, float] = {}
    for k, v in (snapshot.get("counters") or {}).items():
        if not k.startswith(name + "{"):
            continue
        labels = k[len(name) + 1 : -1]
        for part in labels.split(","):
            lk, _, lv = part.partition("=")
            if lk == label:
                out[lv] = out.get(lv, 0.0) + v
    return out


def _fault_sites(snapshot: dict, name: str) -> Dict[str, float]:
    """``faults.injected{site=x}`` counters → {site: count}."""
    return _label_totals(snapshot, name, "site")


def summarize(path: str, top_k: int = 10) -> dict:
    """Fold one ledger into a summary dict (see module docstring)."""
    path = resolve_ledger_path(path)
    events = load_events(path)

    # ------------------------------------------------------------ stages
    # the span fold is shared with workflow/viz.ledger_overlay (ONE
    # reader of the executor.stage span schema)
    from keystone_tpu.obs.ledger import fold_stage_spans

    stages = fold_stage_spans(path)
    retry_events = sum(
        1
        for e in events
        if e.get("kind") == "event" and e.get("name") == "executor.retry"
    )
    top = sorted(stages.values(), key=lambda st: -st["seconds"])[:top_k]
    stage_top = [
        {
            "node": st["label"],
            "seconds": st["seconds"],
            "count": st["count"],
            "retries": st["retries"],
            "failed_attempt_seconds": st["failed_attempt_seconds"],
        }
        for st in top
    ]

    # ------------------------------------------------------- convergence
    convergence: Dict[str, List[dict]] = {}
    for e in events:
        if e.get("kind") == "event" and e.get("name") == "solver.epoch":
            attrs = dict(e.get("attrs") or {})
            solver = str(attrs.pop("solver", "?"))
            convergence.setdefault(solver, []).append(attrs)

    # ----------------------------------------------- metrics (last snap)
    snapshot: dict = {}
    for e in events:
        if e.get("kind") == "metrics":
            snapshot = e.get("attrs") or snapshot
    gauges = snapshot.get("gauges") or {}
    hists = snapshot.get("histograms") or {}

    io = {
        "blockstore_read_bytes": _counter_total(snapshot, "blockstore.read_bytes"),
        "blockstore_write_bytes": _counter_total(snapshot, "blockstore.write_bytes"),
        "blockstore_reads": _counter_total(snapshot, "blockstore.reads"),
        "blockstore_writes": _counter_total(snapshot, "blockstore.writes"),
        "blockstore_read_retries": _counter_total(
            snapshot, "blockstore.read_retries"
        ),
        "durable_retries": _counter_total(snapshot, "durable.retries"),
        "durable_corruption": _counter_total(snapshot, "durable.corruption"),
        "durable_fallback": _counter_total(snapshot, "durable.fallback"),
        "durable_quarantined": _counter_total(snapshot, "durable.quarantined"),
        "stream_bad_batches": _counter_total(snapshot, "stream.bad_batches"),
        "stream_retries": _counter_total(snapshot, "stream.retries"),
        "stream_batch_seconds": {
            k: v for k, v in hists.items() if k.startswith("stream.batch_seconds")
        },
    }

    retries = {
        "executor_retry_events": retry_events,
        "executor_stage_retries": _counter_total(
            snapshot, "executor.stage_retries"
        ),
        "executor_failed_attempt_seconds": _counter_total(
            snapshot, "executor.failed_attempt_seconds"
        ),
        "durable_retries": io["durable_retries"],
        "blockstore_read_retries": io["blockstore_read_retries"],
        "stream_retries": io["stream_retries"],
    }

    # -------------------------------------------------------- dataflow
    # the fit-path dataflow accounts (host-side measures): seconds the
    # host spent BLOCKED on device results (obs-gated solver syncs,
    # ledger.device_wait) vs blocked on host→device staging
    # (blockstore.iter_device_blocks).  The busy fraction is the
    # tentpole metric of the async-feed work: a starved device shows a
    # fraction near zero; a fed one approaches the solver's share of
    # wall time.
    def _hist_sum(name: str) -> float:
        return sum(
            float(h.get("sum") or 0.0)
            for k, h in hists.items()
            if k == name or k.startswith(name + "{")
        )

    device_busy = _hist_sum("device.busy_seconds")
    transfer = _hist_sum("blockstore.stage_wait_seconds")

    memory = {
        "hbm_bytes_in_use": gauges.get("hbm.bytes_in_use"),
        "hbm_peak_bytes_in_use": gauges.get("hbm.peak_bytes_in_use"),
        "host_max_rss_bytes": gauges.get("host.max_rss_bytes"),
    }
    # span-boundary samples are watermarks too (a run killed before its
    # snapshot still has per-span samples)
    for e in events:
        if e.get("kind") == "span_end":
            attrs = e.get("attrs") or {}
            for src, dst in (
                ("hbm_bytes_in_use", "hbm_bytes_in_use"),
                ("host_max_rss_bytes", "host_max_rss_bytes"),
            ):
                v = attrs.get(src)
                if v is not None and (
                    memory.get(dst) is None or float(v) > float(memory[dst])
                ):
                    memory[dst] = float(v)

    # ----------------------------------------------------- AOT artifacts
    # the prime fallback ladder's story: how many bucket primes each
    # tier served (serve.prime_seconds{source=...}) and the install
    # counters — a deploy that silently fell through artifact→compile
    # shows up here as fallbacks>0 with compile-sourced primes
    def _hist(name: str) -> dict:
        h = hists.get(name) or {}
        return {
            "count": int(h.get("count") or 0),
            "seconds": float(h.get("sum") or 0.0),
        }

    artifacts = {
        "hits": int(_counter_total(snapshot, "serve.artifact_hits")),
        "misses": int(_counter_total(snapshot, "serve.artifact_misses")),
        "fallbacks": int(
            _counter_total(snapshot, "serve.artifact_fallbacks")
        ),
        "prime": {
            src: _hist(f"serve.prime_seconds{{source={src}}}")
            for src in ("artifact", "cache", "compile")
        },
    }
    if not any(
        (artifacts["hits"], artifacts["misses"], artifacts["fallbacks"])
    ) and not any(p["count"] for p in artifacts["prime"].values()):
        artifacts = None

    # ------------------------------------------------------------ ingress
    # the front-end block (serve/ingress.py + serve/http.py): only when
    # the run actually served connections
    ingress = {
        "accepts": int(_counter_total(snapshot, "ingress.accepts")),
        "bin_conns": int(_counter_total(snapshot, "ingress.bin_conns")),
        "http_conns": int(_counter_total(snapshot, "ingress.http_conns")),
        "frames": int(_counter_total(snapshot, "ingress.frames")),
        "batch_rows": int(_counter_total(snapshot, "ingress.batch_rows")),
        "bytes_copied": _counter_total(snapshot, "ingress.bytes_copied"),
        "frame_errors": {
            k: int(v)
            for k, v in sorted(
                _label_totals(snapshot, "ingress.frame_errors", "kind").items()
            )
        },
        "parse_seconds": _hist("ingress.parse_seconds"),
        "admit_seconds": _hist("ingress.admit_seconds"),
    }
    if not (
        ingress["accepts"]
        or ingress["bin_conns"]
        or ingress["http_conns"]
        or ingress["frames"]
    ):
        ingress = None

    # -------------------------------------------------------------- fleet
    # worker-shipped telemetry aggregated into the router registry
    # (serve/telemetry.py): per-worker/per-host series keyed by their
    # label string, plus the wire-health counters from serve/net.py
    def _hist_series(name: str) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for k, h in hists.items():
            if k != name and not k.startswith(name + "{"):
                continue
            labels = k[len(name) + 1 : -1] if k != name else ""
            out[labels] = {
                "count": int(h.get("count") or 0),
                "seconds": float(h.get("sum") or 0.0),
                "max": h.get("max"),
            }
        return out

    fleet = {
        "apply_seconds": _hist_series("serve.fleet.apply_seconds"),
        "wire_rtt_seconds": _hist_series("serve.fleet.wire_rtt_seconds"),
        "retransmits": {
            k: int(v)
            for k, v in sorted(
                _label_totals(snapshot, "serve.net.retransmits", "worker").items()
            )
        },
        "late_discards": {
            k: int(v)
            for k, v in sorted(
                _label_totals(
                    snapshot, "serve.net.late_discards", "worker"
                ).items()
            )
        },
    }
    if not (fleet["apply_seconds"] or fleet["wire_rtt_seconds"]):
        fleet = None

    # ------------------------------------------------------------ faults
    faults: Dict[str, dict] = {}
    injected = _fault_sites(snapshot, "faults.injected")
    calls = _fault_sites(snapshot, "faults.calls")
    for site in sorted(set(injected) | set(calls)):
        faults[site] = {
            "calls": int(calls.get(site, 0)),
            "injected": int(injected.get(site, 0)),
        }
    # per-restart stats events (fit_with_recovery / chaos.py)
    fault_events = [
        e["attrs"]
        for e in events
        if e.get("kind") == "event" and e.get("name") == "faults.stats"
    ]

    run_ids = {e.get("run_id") for e in events if e.get("run_id")}
    t0 = min((e["ts"] for e in events if "ts" in e), default=None)
    t1 = max((e["ts"] for e in events if "ts" in e), default=None)
    wall = (t1 - t0) if (t0 is not None and t1 is not None) else None
    dataflow = {
        "device_busy_seconds": device_busy,
        "transfer_seconds": transfer,
        "device_busy_fraction": (
            device_busy / wall if wall else None
        ),
    }
    return {
        "path": path,
        "run_id": sorted(run_ids)[0] if run_ids else None,
        "events": len(events),
        "wall_seconds": wall,
        "stage_top": stage_top,
        "retries": retries,
        "convergence": convergence,
        "io": io,
        "memory": memory,
        "dataflow": dataflow,
        "artifacts": artifacts,
        "ingress": ingress,
        "fleet": fleet,
        "faults": faults,
        "fault_restarts": fault_events,
    }


def _fmt_bytes(b: Optional[float]) -> str:
    if b is None:
        return "-"
    b = float(b)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(b) < 1024 or unit == "TB":
            return f"{b:.1f} {unit}" if unit != "B" else f"{int(b)} B"
        b /= 1024
    return f"{b:.1f} TB"


def render(summary: dict) -> str:
    """Human-readable text for one :func:`summarize` dict."""
    out: List[str] = []
    out.append(f"run {summary.get('run_id')}  ({summary.get('path')})")
    ws = summary.get("wall_seconds")
    out.append(
        f"  events: {summary.get('events')}"
        + (f"   wall: {ws:.2f}s" if ws is not None else "")
    )

    if summary.get("stage_top"):
        out.append("\n== top stages by time ==")
        out.append(
            f"  {'seconds':>9}  {'runs':>4}  {'retries':>7}  "
            f"{'failed_s':>8}  stage"
        )
        for st in summary["stage_top"]:
            out.append(
                f"  {st['seconds']:>9.3f}  {st['count']:>4}  "
                f"{st['retries']:>7}  {st['failed_attempt_seconds']:>8.3f}  "
                f"{st['node']}"
            )

    r = summary.get("retries") or {}
    if any(v for v in r.values()):
        out.append("\n== retries ==")
        for k, v in r.items():
            if v:
                out.append(f"  {k}: {v:g}")

    conv = summary.get("convergence") or {}
    if conv:
        out.append("\n== solver convergence ==")
        for solver, series in conv.items():
            out.append(f"  {solver}: {len(series)} points")
            head = series[: 3]
            tail = series[-2:] if len(series) > 5 else []
            for pt in head:
                out.append("    " + json.dumps(pt, sort_keys=True))
            if tail:
                out.append("    ...")
                for pt in tail:
                    out.append("    " + json.dumps(pt, sort_keys=True))

    io = summary.get("io") or {}
    if any(v for k, v in io.items() if isinstance(v, (int, float))):
        out.append("\n== I/O ==")
        out.append(
            "  blockstore: "
            f"read {_fmt_bytes(io.get('blockstore_read_bytes'))} "
            f"({io.get('blockstore_reads', 0):g} reads, "
            f"{io.get('blockstore_read_retries', 0):g} retries), "
            f"wrote {_fmt_bytes(io.get('blockstore_write_bytes'))} "
            f"({io.get('blockstore_writes', 0):g} appends)"
        )
        out.append(
            "  durable: "
            f"retries {io.get('durable_retries', 0):g}, "
            f"corruption {io.get('durable_corruption', 0):g}, "
            f"fallbacks {io.get('durable_fallback', 0):g}, "
            f"quarantined {io.get('durable_quarantined', 0):g}"
        )
        out.append(
            "  stream: "
            f"retries {io.get('stream_retries', 0):g}, "
            f"bad batches {io.get('stream_bad_batches', 0):g}"
        )
        for k, h in (io.get("stream_batch_seconds") or {}).items():
            mean = h["sum"] / h["count"] if h.get("count") else 0.0
            out.append(
                f"  {k}: n={h.get('count')} mean={mean * 1e3:.2f}ms "
                f"max={(h.get('max') or 0) * 1e3:.2f}ms"
            )

    df = summary.get("dataflow") or {}
    if df.get("device_busy_seconds") or df.get("transfer_seconds"):
        out.append("\n== fit dataflow ==")
        out.append(
            f"  device busy (host-blocked): "
            f"{df.get('device_busy_seconds', 0.0):.3f}s"
        )
        out.append(
            f"  transfer (h2d staging):     "
            f"{df.get('transfer_seconds', 0.0):.3f}s"
        )
        frac = df.get("device_busy_fraction")
        if frac is not None:
            out.append(f"  device-busy fraction of wall: {frac:.1%}")

    mem = summary.get("memory") or {}
    if any(v is not None for v in mem.values()):
        out.append("\n== memory watermarks ==")
        if mem.get("hbm_bytes_in_use") is not None:
            out.append(f"  HBM in use: {_fmt_bytes(mem['hbm_bytes_in_use'])}")
        if mem.get("hbm_peak_bytes_in_use") is not None:
            out.append(
                f"  HBM peak:   {_fmt_bytes(mem['hbm_peak_bytes_in_use'])}"
            )
        if mem.get("host_max_rss_bytes") is not None:
            out.append(
                f"  host peak RSS: {_fmt_bytes(mem['host_max_rss_bytes'])}"
            )

    arts = summary.get("artifacts")
    if arts:
        out.append("\n== AOT artifacts ==")
        out.append(
            f"  installed: {arts['hits']}  misses: {arts['misses']}  "
            f"fallbacks: {arts['fallbacks']}"
        )
        for src, p in (arts.get("prime") or {}).items():
            if p["count"]:
                out.append(
                    f"  prime[{src}]: n={p['count']} "
                    f"total={p['seconds']:.3f}s"
                )

    ing = summary.get("ingress")
    if ing:
        out.append("\n== ingress ==")
        out.append(
            f"  accepts: {ing['accepts']}  "
            f"(binary {ing['bin_conns']}, http {ing['http_conns']})"
        )
        out.append(
            f"  frames: {ing['frames']}  rows: {ing['batch_rows']}  "
            f"bytes copied: {_fmt_bytes(ing.get('bytes_copied'))}"
        )
        for name in ("parse_seconds", "admit_seconds"):
            h = ing.get(name) or {}
            if h.get("count"):
                mean = h["seconds"] / h["count"]
                out.append(
                    f"  {name}: n={h['count']} mean={mean * 1e3:.3f}ms"
                )
        if ing.get("frame_errors"):
            errs = ", ".join(
                f"{k}={v}" for k, v in ing["frame_errors"].items()
            )
            out.append(f"  frame errors: {errs}")

    fl = summary.get("fleet")
    if fl:
        out.append("\n== fleet (worker-shipped) ==")
        for name in ("apply_seconds", "wire_rtt_seconds"):
            for labels, h in sorted((fl.get(name) or {}).items()):
                if not h.get("count"):
                    continue
                mean = h["seconds"] / h["count"]
                mx = h.get("max")
                out.append(
                    f"  {name}{{{labels}}}: n={h['count']} "
                    f"mean={mean * 1e3:.2f}ms"
                    + (f" max={mx * 1e3:.2f}ms" if mx is not None else "")
                )
        for name in ("retransmits", "late_discards"):
            series = fl.get(name) or {}
            if any(series.values()):
                out.append(
                    f"  {name}: "
                    + ", ".join(f"{k}={v}" for k, v in series.items())
                )

    faults = summary.get("faults") or {}
    if faults:
        out.append("\n== faults ==")
        out.append(f"  {'site':<20} {'calls':>7} {'injected':>9}")
        for site, c in faults.items():
            out.append(f"  {site:<20} {c['calls']:>7} {c['injected']:>9}")
    if summary.get("fault_restarts"):
        out.append(
            f"  restart stats events: {len(summary['fault_restarts'])}"
        )

    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a keystone_tpu run-ledger JSONL file"
    )
    ap.add_argument(
        "ledger",
        help="path to a run_*.jsonl file, or a KEYSTONE_OBS_DIR directory "
        "(newest run is picked)",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit the summary dict as JSON"
    )
    ap.add_argument(
        "--top", type=int, default=10, help="stages to list (default 10)"
    )
    args = ap.parse_args(argv)
    summary = summarize(args.ledger, top_k=args.top)
    if args.json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
