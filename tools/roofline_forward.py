"""Per-stage roofline of the headline forward (VERDICT r3 item 2c).

Uses the jax.profiler DEVICE TRACE (works on the axon backend; events
carry per-HLO-op ``device_duration_ps``, ``model_flops``,
``bytes_accessed``, and source attribution), so per-op device times are
exact — no noisy wall-clock differencing.  For every compiled op of the
bench.py headline program (batch 128, SIFT bin 4 + smoothing, K=256 FV,
1000-class scoring) it reports:

- measured device microseconds per batch (median across traced runs)
- FLOPs and HBM bytes (XLA's per-op counters; the Pallas FV custom call
  is priced analytically — XLA cannot see inside it)
- the roofline bound  max(flops/peak_mxu, bytes/peak_hbm)  and the
  achieved fraction — ops at ≥~75% of bound are done; ops far below it
  with big byte counts name the fusion lever.

Also prints total device-busy time per iteration vs the program's wall
marginal time (the overlap/dispatch picture).

Run on the chip:  python tools/roofline_forward.py [--json] [--ms]
                  [--precision {f32,default,bf16_apply,sweep}]

``--ms`` profiles the multi-scale vl_phow config instead (bins
(4,6,8,10) + per-scale smoothing, batch 64 — the densest config the
reference ran, and bench.py's second first-class forward metric).

``--precision`` pins the matmul policy for the profiled program:
``f32`` forces full-precision featurize matmuls, ``default`` is the
shipped policy (auto: bf16 featurize on TPU), ``bf16_apply`` activates
the apply-side bf16 path (utils/precision.py).  ``sweep`` runs all
three back-to-back and prints one summary JSON line per mode with the
achieved TF/s and mfu_bf16_eff — the per-mode numbers the r6 tentpole
is judged on.  Every output carries ``precision``/``achieved_tflops``/
``mfu_bf16_eff`` fields either way.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import tempfile
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root, for bench
from bench import (  # noqa: E402
    BATCH,
    GMM_K,
    IMAGE_HW,
    MS_BATCH,
    MS_BIN_SIZES,
    MS_SMOOTHING,
    PCA_DIMS,
    SIFT_STEP,
    build_forward,
    flops_per_image,
)

BIN_SIZE = 4  # the headline single-scale bin (build_forward default)
_MS = "--ms" in sys.argv
_RUN_BATCH = MS_BATCH if _MS else BATCH
_BIN_SIZES = MS_BIN_SIZES if _MS else (BIN_SIZE,)
_SMOOTHING = MS_SMOOTHING if _MS else None  # None → build_forward default
if "--batch" in sys.argv:  # e.g. --batch 256: probe the large-batch decay
    _idx = sys.argv.index("--batch") + 1
    if _idx >= len(sys.argv) or not sys.argv[_idx].isdigit():
        sys.exit("usage: roofline_forward.py [--json] [--ms] [--batch N]")
    _RUN_BATCH = int(sys.argv[_idx])

#: --precision: the matmul policy the profiled program compiles under.
#: CLI names map onto utils/precision modes ("default" == the shipped
#: auto policy); "sweep" loops all three.
_PRECISION_CLI_TO_MODE = {"f32": "f32", "default": "auto", "bf16_apply": "bf16_apply"}
_PRECISION = "default"
if "--precision" in sys.argv:
    _idx = sys.argv.index("--precision") + 1
    if _idx >= len(sys.argv) or sys.argv[_idx] not in (
        *_PRECISION_CLI_TO_MODE,
        "sweep",
    ):
        sys.exit(
            "usage: roofline_forward.py [--precision {f32,default,bf16_apply,sweep}]"
        )
    _PRECISION = sys.argv[_idx]

TRACE_ITERS = 8
#: v5e bf16-grade MXU peak and HBM stream peak — per-op bounds use the
#: bf16 rate for matmul/conv ops (XLA runs default-precision f32 matmuls
#: as bf16-grade passes) and the f32 VPU-ish rate is not modeled: for
#: elementwise ops the bound is always bytes.
_PEAK_MXU = 1.97e14
_PEAK_HBM = 8.1e11


def _build_and_warm(precision_cli: str):
    """Set the matmul policy, build + warm the jitted forward."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.utils import precision as _prec
    from keystone_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    _prec.set_matmul(_PRECISION_CLI_TO_MODE[precision_cli])
    kw = {"bin_sizes": _BIN_SIZES}
    if _SMOOTHING is not None:
        kw["smoothing_magnif"] = _SMOOTHING
    fwd = jax.jit(build_forward(**kw))
    x = jnp.asarray(
        np.random.default_rng(1)
        .uniform(0, 1, (_RUN_BATCH, 128, 128, 3))
        .astype(np.float32)
    )
    for _ in range(3):
        np.asarray(fwd(x)[:1, :8])  # compile + warm
    return fwd, x


def _wall_marginal(fwd, x) -> float:
    """Marginal seconds/batch: slope between a 20- and a 60-iteration
    pipelined run (real device→host read as the sync)."""

    def run(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fwd(x)
        np.asarray(out[:1, :8])
        return time.perf_counter() - t0

    t20, t60 = run(20), run(60)
    return (t60 - t20) / 40.0


def measure_wall(precision_cli: str) -> float:
    """Wall-marginal seconds/batch under one policy (no profiler trace —
    the --precision sweep wants the per-mode TF/s, not per-op tables)."""
    fwd, x = _build_and_warm(precision_cli)
    return _wall_marginal(fwd, x)


def run_and_trace(logdir: str, precision_cli: str = "default"):
    import jax

    fwd, x = _build_and_warm(precision_cli)
    wall_marginal = _wall_marginal(fwd, x)
    with jax.profiler.trace(logdir):
        out = None
        for _ in range(TRACE_ITERS):
            out = fwd(x)
        np.asarray(out[:1, :8])
    return wall_marginal


def parse_trace(logdir: str):
    paths = sorted(
        glob.glob(os.path.join(logdir, "plugins/profile/*/*.trace.json.gz"))
    )
    if not paths:
        raise RuntimeError(f"no trace.json.gz under {logdir}")
    with gzip.open(paths[-1]) as f:
        t = json.load(f)
    evs = t.get("traceEvents", t) if isinstance(t, dict) else t
    # device-op events: ph=X with an hlo_category arg (host pid events
    # have none); tid varies (queue), pid is the device process
    ops = [
        e
        for e in evs
        if e.get("ph") == "X"
        and isinstance(e.get("args"), dict)
        and "hlo_category" in e["args"]
    ]
    return ops


def aggregate(ops):
    """op label → dict(us_per_run, flops, bytes, category, n)."""
    by_name = defaultdict(list)
    for e in ops:
        a = e["args"]
        by_name[e["name"]].append(
            (
                int(a.get("device_duration_ps", 0)) / 1e6,  # ps → µs
                float(a.get("model_flops", 0) or 0),
                float(a.get("raw_bytes_accessed", a.get("bytes_accessed", 0)) or 0),
                a.get("hlo_category", "?"),
                a.get("tf_op", "") or a.get("source", ""),
            )
        )
    rows = {}
    for name, vals in by_name.items():
        d = [v[0] for v in vals]
        # each run emits the op once; occurrences = ceil(n/TRACE_ITERS)
        per_run = float(np.sum(d)) / TRACE_ITERS
        rows[name] = {
            "us_per_run": per_run,
            "flops": vals[0][1] * len(vals) / TRACE_ITERS,
            "bytes": vals[0][2] * len(vals) / TRACE_ITERS,
            "category": vals[0][3],
            "attr": vals[0][4][:70],
            "n": len(vals),
        }
    return rows


def main():
    if _PRECISION == "sweep":
        # per-mode TF/s + mfu_bf16_eff: one JSON line per policy, same
        # program, traced-free wall marginal (bench.py's slope idea)
        for cli in ("f32", "default", "bf16_apply"):
            wall = measure_wall(cli)
            ips = _RUN_BATCH / wall
            tf = ips * flops_per_image(_BIN_SIZES) / 1e12
            print(
                json.dumps(
                    {
                        "precision": cli,
                        "batch": _RUN_BATCH,
                        "wall_marginal_us": round(wall * 1e6, 1),
                        "images_per_sec": round(ips, 1),
                        "achieved_tflops": round(tf, 2),
                        "mfu_bf16_eff": round(tf * 1e12 / _PEAK_MXU, 3),
                    }
                )
            )
        return

    logdir = tempfile.mkdtemp(prefix="ks-roofline-")
    wall = run_and_trace(logdir, _PRECISION)
    rows = aggregate(parse_trace(logdir))

    # price the Pallas FV custom call analytically (model_flops = 0 for
    # custom calls XLA can't see inside).  Match by NAME — a category
    # match would hand the FV count to any other zero-flop custom call
    # in a future trace.  T derives from the bench geometry, not a
    # hardcoded 784.
    from keystone_tpu.ops.sift import sift_output_count

    t_desc = sift_output_count(IMAGE_HW, IMAGE_HW, SIFT_STEP, _BIN_SIZES)
    for name, r in rows.items():
        if "fisher" in name.lower() and r["flops"] == 0:
            r["flops"] = 4 * 2 * t_desc * PCA_DIMS * GMM_K * _RUN_BATCH
            r["analytic_flops"] = True

    total_dev = sum(r["us_per_run"] for r in rows.values())
    out_rows = []
    for name, r in sorted(rows.items(), key=lambda kv: -kv[1]["us_per_run"]):
        bound_us = max(r["flops"] / _PEAK_MXU, r["bytes"] / _PEAK_HBM) * 1e6 / 1.0
        # flops/bytes are per-run totals; us_per_run the same
        pct = bound_us / r["us_per_run"] if r["us_per_run"] > 0.5 else None
        binding = (
            "flops" if r["flops"] / _PEAK_MXU >= r["bytes"] / _PEAK_HBM else "bytes"
        )
        out_rows.append(
            {
                "op": name,
                "category": r["category"],
                "us_per_batch": round(r["us_per_run"], 1),
                "share_of_device": round(r["us_per_run"] / total_dev, 3),
                "gflops": round(r["flops"] / 1e9, 2),
                "mbytes": round(r["bytes"] / 1e6, 1),
                "bound_us": round(bound_us, 1),
                "binding": binding,
                "pct_of_bound": pct and round(pct, 2),
                "attr": r["attr"],
            }
        )
    achieved_tf = _RUN_BATCH / wall * flops_per_image(_BIN_SIZES) / 1e12
    result = {
        "batch": _RUN_BATCH,
        "precision": _PRECISION,
        "wall_marginal_us": round(wall * 1e6, 1),
        "device_busy_us": round(total_dev, 1),
        "overlap_or_gap_us": round(wall * 1e6 - total_dev, 1),
        "images_per_sec": round(_RUN_BATCH / wall, 1),
        "achieved_tflops": round(achieved_tf, 2),
        "mfu_bf16_eff": round(achieved_tf * 1e12 / _PEAK_MXU, 3),
        "analytic_flops_per_image": flops_per_image(_BIN_SIZES),
        "ops": out_rows,
    }
    if "--json" in sys.argv:
        print(json.dumps(result))
        return
    print(
        f"batch={_RUN_BATCH}  wall={wall*1e6:.0f}us/batch  device-busy="
        f"{total_dev:.0f}us  ({_RUN_BATCH/wall:,.0f} images/s)"
    )
    print(
        f"{'op':<28}{'us':>7}{'%dev':>6}{'GF':>7}{'MB':>8}{'bound':>7}"
        f"{'bind':>7}{'x-off':>7}  attr"
    )
    for r in out_rows:
        if r["us_per_batch"] < 0.5:
            continue
        pct = f"{r['pct_of_bound']:.2f}" if r["pct_of_bound"] else "—"
        print(
            f"{r['op'][:27]:<28}{r['us_per_batch']:>7.1f}"
            f"{100*r['share_of_device']:>5.0f}%{r['gflops']:>7.2f}"
            f"{r['mbytes']:>8.1f}{r['bound_us']:>7.1f}{r['binding']:>7}"
            f"{pct:>7}  {r['attr'][:40]}"
        )


if __name__ == "__main__":
    main()
