"""Host text stage throughput: Python per-doc chain vs the native fused
path (ops/nlp_native), on the r4 synthetic corpus shape (120 tokens/doc,
5k-word vocab; BASELINE.md "Host text stage").

    python tools/bench_text.py [n_docs] [--python-docs M]

Measures the streaming 2-pass fit (df sweep + featurize sweep) docs/s
for both paths through the SAME StreamDataset DAG chain.
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from keystone_tpu.ops import nlp_native
from keystone_tpu.ops.nlp import (
    CommonSparseFeatures,
    LowerCase,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trimmer,
    log_tf,
)
from keystone_tpu.workflow.dataset import StreamDataset

VOCAB = 5000
TOKENS_PER_DOC = 120
BATCH = 2048
NUM_FEATURES = 1 << 15


def corpus(n: int):
    rng = np.random.default_rng(0)
    words = np.array([f"w{i}" for i in range(VOCAB)])
    probs = 1.0 / np.arange(1, VOCAB + 1) ** 1.1
    probs /= probs.sum()
    docs = []
    for _ in range(n):
        docs.append(" ".join(words[rng.choice(VOCAB, TOKENS_PER_DOC, p=probs)]))
    return docs


def run_two_pass(docs, use_native: bool) -> float:
    def src():
        for i in range(0, len(docs), BATCH):
            yield docs[i : i + BATCH]

    ds = StreamDataset(src, n=len(docs), host=True)
    out = ds
    for t in (Trimmer(), LowerCase(), Tokenizer(), NGramsFeaturizer((1, 2)),
              TermFrequency(log_tf)):
        out = t.apply_dataset(out)
    if not use_native:
        out._host_chain = None  # force the Python path
    t0 = time.perf_counter()
    model = CommonSparseFeatures(NUM_FEATURES, sparse_output=True).fit_dataset(out)
    t_df = time.perf_counter() - t0
    feat = model.apply_dataset(out)
    nrows = 0
    t1 = time.perf_counter()
    for b in feat.batches():
        nrows += len(b)
    t_feat = time.perf_counter() - t1
    assert nrows == len(docs)
    total = t_df + t_feat
    print(
        f"  {'native' if use_native else 'python'}: df {len(docs)/t_df:8.0f} docs/s"
        f"   featurize {len(docs)/t_feat:8.0f} docs/s   2-pass {len(docs)/total:8.0f} docs/s"
    )
    return len(docs) / total


def run_hash_pass(docs, use_native: bool) -> float:
    from keystone_tpu.ops.nlp import HashingTF

    def src():
        for i in range(0, len(docs), BATCH):
            yield docs[i : i + BATCH]

    ds = StreamDataset(src, n=len(docs), host=True)
    out = ds
    for t in (Trimmer(), LowerCase(), Tokenizer(), NGramsFeaturizer((1, 2)),
              TermFrequency(log_tf)):
        out = t.apply_dataset(out)
    if not use_native:
        out._host_chain = None
    feat = HashingTF(NUM_FEATURES, sparse_output=True).apply_dataset(out)
    t0 = time.perf_counter()
    nrows = sum(len(b) for b in feat.batches())
    dt = time.perf_counter() - t0
    assert nrows == len(docs)
    print(f"  {'native' if use_native else 'python'} hashtf: "
          f"{len(docs)/dt:8.0f} docs/s")
    return len(docs) / dt


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    n = int(args[0]) if args else 100_000
    pydocs = n
    if "--python-docs" in sys.argv:
        pydocs = int(sys.argv[sys.argv.index("--python-docs") + 1])
    print(f"corpus: {n} docs x {TOKENS_PER_DOC} tokens, vocab {VOCAB}")
    docs = corpus(n)
    native = run_two_pass(docs, use_native=True)
    py = run_two_pass(docs[:pydocs], use_native=False)
    print(f"speedup (2-pass docs/s): {native/py:.2f}x")
    hn = run_hash_pass(docs, use_native=True)
    hp = run_hash_pass(docs[:pydocs], use_native=False)
    print(f"speedup (hashtf docs/s): {hn/hp:.2f}x")


if __name__ == "__main__":
    main()
